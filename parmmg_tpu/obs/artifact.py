"""Canonical schema-versioned artifact + cross-artifact differ.

Before this module the four artifact families (BENCH/SCALE/SERVE/
MULTIHOST) each had an ad-hoc shape; the only machine-checked field was
``extra.compile_ledger``.  The canonical schema (v1) keeps every legacy
top-level key (``metric``/``value``/``unit``/``vs_baseline``/``extra``
— the round driver and ``regressions_vs_latest_artifact`` still parse
old and new artifacts alike) and adds:

- ``schema_version`` + ``kind`` — self-identifying artifacts;
- ``env`` — backend/device/jax/python + the ``PARMMG_*`` knob set that
  shaped the run (the reproducibility block);
- ``metrics`` — the obs registry snapshot (counters/gauges/histograms);
- ``trace`` — the tracer digest (event counts, sink, top span totals).

The compile ledger STAYS at ``extra.compile_ledger`` (the established
location every existing differ reads).

:func:`upgrade_artifact` adapts any legacy artifact (including the
round wrapper ``{"parsed": {...}}`` and the bare multihost result
dict) to the canonical shape so :func:`validate_artifact` and
:func:`artifact_diff` treat ten rounds of history and tomorrow's run
uniformly — that is what lets ``scripts/ledger_check.py --diff``
generalize into the one cross-artifact regression gate (compile
families + throughput + quality + scheduler savings + metrics block).
"""
from __future__ import annotations

import os

__all__ = ["SCHEMA_VERSION", "KINDS", "artifact_diff", "env_block",
           "make_artifact", "upgrade_artifact", "validate_artifact"]

SCHEMA_VERSION = 1
KINDS = ("BENCH", "SCALE", "SERVE", "MULTIHOST", "SOAK")


def env_block() -> dict:
    """Backend/runtime provenance.  Never imports jax — reads it only
    when the emitting process already did."""
    import platform
    import sys
    out = {"python": platform.python_version(),
           "platform": platform.platform()}
    jax = sys.modules.get("jax")
    if jax is None:
        out["backend"] = "unimported"
    else:
        try:
            out["backend"] = jax.default_backend()
            out["device_count"] = jax.device_count()
            out["jax"] = jax.__version__
        except Exception:
            out["backend"] = "?"
    out["knobs"] = {k: v for k, v in sorted(os.environ.items())
                    if k.startswith("PARMMG_")}
    return out


def make_artifact(kind: str, metric: str, value: float, unit: str,
                  extra: dict | None = None,
                  vs_baseline: float | None = None,
                  registry=None, tracer=None) -> dict:
    """Build a canonical artifact document (JSON-serializable)."""
    from .metrics import REGISTRY
    from .trace import TRACER
    if kind not in KINDS:
        raise ValueError(f"unknown artifact kind {kind!r}")
    extra = dict(extra or {})
    if "compile_ledger" not in extra:
        from ..utils.compilecache import ledger_snapshot
        extra["compile_ledger"] = ledger_snapshot()
    doc = {"schema_version": SCHEMA_VERSION, "kind": kind,
           "metric": metric, "value": value, "unit": unit,
           "env": env_block(),
           "metrics": (registry if registry is not None
                       else REGISTRY).snapshot(),
           "trace": (tracer if tracer is not None
                     else TRACER).summary(),
           "extra": extra}
    if vs_baseline is not None:
        doc["vs_baseline"] = vs_baseline
    return doc


def upgrade_artifact(doc: dict) -> dict:
    """Adapt any artifact shape we have ever emitted to canonical v1:
    the round wrapper (``{"parsed": {...}}``), the bench/scale/serve
    one-liners, the bare multihost result dict — already-canonical
    documents pass through untouched."""
    if not isinstance(doc, dict):
        raise ValueError("artifact is not a JSON object")
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if doc.get("schema_version") == SCHEMA_VERSION:
        return doc
    metric = str(doc.get("metric", ""))
    if "serve" in metric:
        kind = "SERVE"
    elif "scale" in metric:
        kind = "SCALE"
    elif metric:
        kind = "BENCH"
    else:
        # the bare multihost result dict has no metric/value keys
        kind = "MULTIHOST"
    extra = dict(doc.get("extra") or {})
    if kind == "MULTIHOST" and not extra:
        extra = {k: v for k, v in doc.items()
                 if k not in ("metric", "value", "unit", "vs_baseline")}
    extra.setdefault("compile_ledger", {})
    up = {"schema_version": SCHEMA_VERSION, "kind": kind,
          "metric": metric or "multihost_adapt",
          "value": float(doc.get("value", doc.get("seconds", 0.0))
                         or 0.0),
          "unit": str(doc.get("unit", "s" if "seconds" in doc else "")),
          "env": {"backend": str(extra.get("device",
                                           doc.get("device", "?"))),
                  "upgraded_from_legacy": True},
          "metrics": {"counters": {}, "gauges": {}, "histograms": {}},
          "trace": {"events": 0, "ring": 0, "dropped": 0, "sink": "",
                    "top_spans_s": {}},
          "extra": extra}
    if "vs_baseline" in doc:
        up["vs_baseline"] = doc["vs_baseline"]
    return up


def validate_artifact(doc: dict) -> list[str]:
    """Canonical-schema check.  Returns the list of problems (empty ==
    valid); legacy artifacts validate through
    ``validate_artifact(upgrade_artifact(doc))``."""
    if not isinstance(doc, dict):
        return ["artifact is not a JSON object"]
    probs = []
    for k, typ in (("schema_version", int), ("kind", str),
                   ("metric", str), ("unit", str), ("env", dict),
                   ("metrics", dict), ("trace", dict), ("extra", dict)):
        if k not in doc:
            probs.append(f"missing key {k!r}")
        elif not isinstance(doc[k], typ):
            probs.append(f"{k} is not a {typ.__name__}")
    if not isinstance(doc.get("value"), (int, float)) \
            or isinstance(doc.get("value"), bool):
        probs.append("value missing or not numeric")
    if isinstance(doc.get("schema_version"), int) \
            and doc["schema_version"] != SCHEMA_VERSION:
        probs.append(f"schema_version {doc['schema_version']} != "
                     f"{SCHEMA_VERSION}")
    if isinstance(doc.get("kind"), str) and doc["kind"] not in KINDS:
        probs.append(f"unknown kind {doc['kind']!r}")
    if isinstance(doc.get("env"), dict) and "backend" not in doc["env"]:
        probs.append("env.backend missing")
    if isinstance(doc.get("metrics"), dict):
        for sub in ("counters", "gauges", "histograms"):
            if not isinstance(doc["metrics"].get(sub), dict):
                probs.append(f"metrics.{sub} missing or not an object")
    if isinstance(doc.get("extra"), dict) \
            and not isinstance(doc["extra"].get("compile_ledger", {}),
                               dict):
        probs.append("extra.compile_ledger is not an object")
    return probs


def artifact_diff(old: dict, new: dict, tol: float = 0.10) -> dict:
    """Cross-artifact regression differ (both sides upgraded first).

    Returns {"ledger": [...], "value": [...], "quality": [...],
    "notes": [...]}: ``ledger`` = compiled-variant growth on shared
    entry points (the historical --diff gate, still the hard-fail
    class); ``value`` = the headline metric dropping > ``tol`` on a
    same-kind/same-metric pair; ``quality`` = qmin/qmean dropping >
    ``tol``; ``notes`` = soft signals (scheduler savings shrinking,
    metric counters disappearing)."""
    from ..utils.compilecache import extract_artifact_ledger, ledger_diff
    o, n = upgrade_artifact(old), upgrade_artifact(new)
    out = {"ledger": [], "value": [], "quality": [], "notes": []}
    # ledger extraction runs on the ORIGINAL docs: extract_artifact_
    # ledger also accepts plain ledger snapshots (its fallback), which
    # the canonical upgrade would bury under extra
    out["ledger"] = ledger_diff(extract_artifact_ledger(old),
                                extract_artifact_ledger(new))
    comparable = (o.get("kind") == n.get("kind")
                  and o.get("metric") == n.get("metric"))
    if comparable:
        vo = float(o.get("value") or 0.0)
        vn = float(n.get("value") or 0.0)
        # direction from the unit: a seconds-valued headline (MULTIHOST
        # wall time) regresses UP; every throughput-style unit
        # regresses DOWN
        unit = str(n.get("unit", o.get("unit", ""))).strip().lower()
        lower_is_better = unit == "s" or unit.startswith("second") \
            or unit.endswith("seconds")
        if vo > 0 and (vn > vo * (1 + tol) if lower_is_better
                       else vn < vo * (1 - tol)):
            pct = (vn / vo - 1) * 100
            out["value"].append(
                f"{o['metric']}: {vo} -> {vn} ({pct:+.1f}%)")
        for q in ("qmin", "qmean"):
            a = o["extra"].get(q)
            b = n["extra"].get(q)
            if isinstance(a, (int, float)) and a > 0 \
                    and isinstance(b, (int, float)) \
                    and b < a * (1 - tol):
                out["quality"].append(f"{q}: {a} -> {b}")
        sa = o["extra"].get("saved_dispatches")
        sb = n["extra"].get("saved_dispatches")
        if isinstance(sa, (int, float)) and sa > 0 \
                and isinstance(sb, (int, float)) \
                and sb < sa * (1 - tol):
            out["notes"].append(
                f"saved_dispatches: {sa} -> {sb} (scheduler win shrank)")
    mo = (o.get("metrics") or {}).get("counters") or {}
    mn = (n.get("metrics") or {}).get("counters") or {}
    for k in sorted(set(mo) - set(mn)):
        out["notes"].append(f"metric counter disappeared: {k}")
    return out
