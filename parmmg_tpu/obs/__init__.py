"""Unified telemetry spine (tracing, metrics, artifacts).

The reference's only observability is the ``PMMG_ctim[TIMEMAX]`` timer
slots plus ``imprim``-gated prints (parmmg.c:35,91; libparmmg1.c:636-948).
This reproduction outgrew that: wall-clock ``utils.timers.Timers``, the
``jax.monitoring`` compile ledger, ``AdaptStats`` counters, scheduler
trajectories and four ad-hoc artifact schemas each told a partial,
incompatible story.  ``obs`` is the one spine they all emit into:

- :mod:`~parmmg_tpu.obs.trace` — structured span/event/log emitter with
  a run context (run id, backend, pass/block/chunk, tenant), a JSONL
  sink (``PARMMG_TRACE=path``) over an always-on ring buffer, plus the
  ``jax.profiler`` capture-window arming (``PARMMG_PROFILE_DIR``) and
  device-timeline annotation wrappers;
- :mod:`~parmmg_tpu.obs.metrics` — typed counter/gauge/histogram
  registry (fixed log buckets, pure host) with Prometheus-style text
  exposition and a JSON snapshot; tenant-tagged series stay namespaced
  exactly like ``AdaptStats`` (``tenant:<id>/``);
- :mod:`~parmmg_tpu.obs.artifact` — the canonical schema-versioned
  artifact every bench/scale/serve/multihost script emits, and the
  cross-artifact regression differ behind
  ``scripts/ledger_check.py --diff``.

Everything here is host-side bookkeeping: no jax import at module
scope, no effect on compiled programs (gated by
``scripts/run_tests.sh --obs``: trace-on adds zero compile families).
"""
from . import artifact, metrics, trace                     # noqa: F401
from .metrics import REGISTRY                              # noqa: F401
from .trace import TRACER, log, set_verbosity              # noqa: F401
