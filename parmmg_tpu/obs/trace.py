"""Structured trace emitter + run context + profiler arming.

One record per completed span (not begin/end pairs): replay is a plain
per-name sum, the file stays half the size, and a crashed run loses at
most the spans still open.  Records are dicts; the run context
(:func:`set_context` for process-wide keys like the run id and backend,
:func:`context` for scoped overlays like pass/block/chunk/tenant) is
folded into every record at emit time, so a trace line is
self-describing without a join.

Sinks: an always-on ring buffer (``PARMMG_TRACE_RING`` records, default
4096 — the ``PMMG_ctim`` slots' bounded-memory role) and, when
``PARMMG_TRACE=path`` is set (or :meth:`Tracer.configure` is called), a
JSONL file appended line-by-line.  ``utils.timers.Timers`` feeds spans
directly — every existing ``with tim(...)`` scope is a trace span for
free, carrying the instance's ``tim`` id so :func:`replay_totals` can
reconstruct exactly one registry's ``report()`` from the stream.

Device timelines: :func:`annotate` wraps
``jax.profiler.TraceAnnotation`` (host events on the profiler timeline)
and :func:`scope` wraps ``jax.named_scope`` (XLA op metadata), so a
profiler capture carries the same phase names as the host trace.
``PARMMG_PROFILE_DIR`` arms ``jax.profiler.start_trace`` over a
requested outer-pass window (``PARMMG_PROFILE_PASS=start[:stop]``,
default pass 0) via :func:`profile_pass_begin` / :func:`profile_pass_end`
— called by the grouped and distributed outer loops and driven
standalone by ``scripts/profile_adapt.py``.

:func:`log` is the one verbosity-gated print path (the reference's
``imprim`` levels, core.constants.PMMG_VERB_*): gated output AND an
always-emitted trace record, so ``-v`` output and the trace stream
cannot drift.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager, nullcontext

__all__ = [
    "TRACER", "Tracer", "annotate", "context", "current_context",
    "emit_span", "event", "log", "new_run", "profile_pass_begin",
    "profile_pass_end", "profiling_active", "replay_totals", "scope",
    "set_context", "set_verbosity", "span", "verbosity",
]


# ---------------------------------------------------------------------------
# run context
# ---------------------------------------------------------------------------
_BASE: dict = {}
_TLS = threading.local()


def set_context(**kv) -> None:
    """Merge process-wide context keys (run id, backend, tenant...).
    ``None`` deletes a key."""
    for k, v in kv.items():
        if v is None:
            _BASE.pop(k, None)
        else:
            _BASE[k] = v


def new_run(backend: str | None = None) -> str:
    """Start a fresh run context: new run id, optional backend tag
    (defaulted from an already-imported jax — never imports it)."""
    import sys
    import uuid
    if backend is None:
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                backend = jax.default_backend()
            except Exception:
                backend = None
    _BASE.clear()
    rid = uuid.uuid4().hex[:12]
    set_context(run=rid, backend=backend)
    return rid


@contextmanager
def context(**kv):
    """Thread-local scoped context overlay (pass/cycle/block/chunk/
    tenant...) folded into every record emitted inside the scope."""
    stk = getattr(_TLS, "stack", None)
    if stk is None:
        stk = _TLS.stack = []
    stk.append({k: v for k, v in kv.items() if v is not None})
    try:
        yield
    finally:
        stk.pop()


def current_context() -> dict:
    out = dict(_BASE)
    for d in getattr(_TLS, "stack", ()) or ():
        out.update(d)
    return out


# ---------------------------------------------------------------------------
# the tracer
# ---------------------------------------------------------------------------
class Tracer:
    """Ring buffer + optional JSONL sink.  Thread-safe; the env sink
    (``PARMMG_TRACE``) is resolved lazily on first emit so importing
    this module never opens files."""

    def __init__(self, ring: int | None = None, path: str | None = None):
        if ring is None:
            ring = int(os.environ.get("PARMMG_TRACE_RING", "4096")
                       or 4096)
        self.ring: deque = deque(maxlen=max(1, ring))
        self._lock = threading.Lock()
        self._emitted = 0
        self._path = path
        self._fh = None
        self._env_checked = path is not None

    def _sink(self):
        if not self._env_checked:
            self._env_checked = True
            p = os.environ.get("PARMMG_TRACE", "")
            if p:
                self._path = p
        if self._path and self._fh is None:
            try:
                self._fh = open(self._path, "a", buffering=1)
            except OSError:
                self._path = None
        return self._fh

    def configure(self, path: str | None = None,
                  ring: int | None = None) -> None:
        """Re-point the JSONL sink (None = ring only); resets the env
        resolution so tests and the obs gate control the sink
        explicitly."""
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
            self._path = path
            self._env_checked = True
            if ring is not None:
                self.ring = deque(maxlen=max(1, ring))

    def reset(self) -> None:
        with self._lock:
            self.ring.clear()
            self._emitted = 0

    def emit(self, rec: dict) -> None:
        rec.setdefault("ts", round(time.time(), 6))
        for k, v in current_context().items():
            rec.setdefault(k, v)
        with self._lock:
            self._emitted += 1
            self.ring.append(rec)
            fh = self._sink()
            if fh is not None:
                try:
                    fh.write(json.dumps(rec) + "\n")
                except (OSError, TypeError, ValueError):
                    pass

    def summary(self, top: int = 8) -> dict:
        """Compact trace digest for artifacts: emit/drop counts, sink,
        and the top span totals seen in the ring."""
        with self._lock:
            recs = list(self.ring)
            emitted = self._emitted
        tot: dict[str, float] = {}
        for r in recs:
            if r.get("kind") == "span":
                tot[r["name"]] = tot.get(r["name"], 0.0) \
                    + float(r.get("dur", 0.0))
        tops = sorted(tot.items(), key=lambda kv: -kv[1])[:top]
        return {"events": emitted, "ring": len(recs),
                "dropped": max(0, emitted - len(recs)),
                "sink": self._path or "",
                "top_spans_s": {k: round(v, 4) for k, v in tops}}


TRACER = Tracer()


def emit_span(name: str, dur: float, count: int = 1,
              tim: int | None = None, ext: bool = False) -> None:
    """One completed span.  ``tim``: emitting Timers instance id (the
    replay filter); ``ext``: segment absorbed from another component's
    measurement (Timers.add outside any scope)."""
    rec = {"kind": "span", "name": name, "dur": round(float(dur), 9),
           "count": int(count)}
    if tim is not None:
        rec["tim"] = tim
    if ext:
        rec["ext"] = True
    TRACER.emit(rec)


@contextmanager
def span(name: str, **fields):
    """Measure-and-emit convenience for code without a Timers."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        rec = {"kind": "span", "name": name,
               "dur": round(time.perf_counter() - t0, 9), "count": 1}
        rec.update(fields)
        TRACER.emit(rec)


def event(name: str, **fields) -> None:
    rec = {"kind": "event", "name": name}
    rec.update(fields)
    TRACER.emit(rec)


def replay_totals(source, tim: int | None = None
                  ) -> tuple[dict, dict]:
    """Reconstruct per-phase (total seconds, counts) from a trace — a
    JSONL path or an iterable of records.  ``tim`` filters to one
    Timers instance so the result is comparable to that instance's
    ``acc``/``count`` (the ``--obs`` gate's replay check).  Unparseable
    lines are skipped (a crashed writer may truncate the last one)."""
    if isinstance(source, (str, os.PathLike)):
        recs = []
        with open(source) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    recs.append(json.loads(line))
                except ValueError:
                    continue
    else:
        recs = list(source)
    tot: dict[str, float] = {}
    cnt: dict[str, int] = {}
    for r in recs:
        if r.get("kind") != "span":
            continue
        if tim is not None and r.get("tim") != tim:
            continue
        n = r["name"]
        tot[n] = tot.get(n, 0.0) + float(r.get("dur", 0.0))
        cnt[n] = cnt.get(n, 0) + int(r.get("count", 1))
    return tot, cnt


# ---------------------------------------------------------------------------
# verbosity-gated logging (imprim analogue)
# ---------------------------------------------------------------------------
_VERBOSITY = [int(os.environ.get("PARMMG_VERBOSE", "1") or 1)]


def set_verbosity(v: int) -> None:
    """Set the process verbosity (the reference's ``imprim``; the
    driver calls this from ``info.imprim`` at run start)."""
    _VERBOSITY[0] = int(v)


def verbosity() -> int:
    return _VERBOSITY[0]


def log(level: int, msg: str, verbose: int | None = None,
        err: bool = False) -> bool:
    """Verbosity-gated print + unconditional trace record.

    ``level``: the imprim threshold (core.constants.PMMG_VERB_*).
    ``verbose``: optional local verbosity (the dist/groups drivers
    carry one on the same scale) — overrides the process value.  The
    record is emitted whether or not the line printed (``shown``
    flags it), so the trace stream and the -v output cannot drift.
    Returns whether the line printed."""
    gate = _VERBOSITY[0] if verbose is None else int(verbose)
    shown = gate >= level
    TRACER.emit({"kind": "log", "lvl": int(level), "msg": str(msg),
                 "shown": bool(shown)})
    if shown:
        import sys
        print(msg, file=sys.stderr if err else sys.stdout)
    return shown


# ---------------------------------------------------------------------------
# jax profiler integration (capture windows + timeline annotations)
# ---------------------------------------------------------------------------
_PROFILE = {"active": False, "dir": "", "window": (0, 0)}


def _profile_conf():
    d = os.environ.get("PARMMG_PROFILE_DIR", "")
    if not d:
        return None
    w = os.environ.get("PARMMG_PROFILE_PASS", "0")
    if ":" in w:
        a, b = w.split(":", 1)
        win = (int(a or 0), int(b or a or 0))
    else:
        win = (int(w or 0), int(w or 0))
    return d, win


def profile_pass_begin(it: int) -> bool:
    """Arm a ``jax.profiler`` capture when outer pass ``it`` enters the
    requested window (``PARMMG_PROFILE_DIR`` + ``PARMMG_PROFILE_PASS``).
    No-op (False) when unarmed, already capturing, or out of window."""
    conf = _profile_conf()
    if conf is None or _PROFILE["active"]:
        return False
    d, (a, b) = conf
    if not (a <= it <= b):
        return False
    try:
        import jax
        os.makedirs(d, exist_ok=True)
        jax.profiler.start_trace(d)
    except Exception as e:
        log(0, f"obs: profiler capture failed to arm ({e!r})", err=True)
        return False
    _PROFILE.update(active=True, dir=d, window=(a, b))
    event("profile_start", dir=d)
    return True


def profile_pass_end(it: int) -> bool:
    """Close the capture once the window's last pass completed."""
    if not _PROFILE["active"]:
        return False
    _a, b = _PROFILE["window"]
    if it < b:
        return False
    try:
        import jax
        jax.profiler.stop_trace()
    except Exception:
        pass
    _PROFILE["active"] = False
    event("profile_stop", dir=_PROFILE["dir"])
    # stderr: stdout is the artifact channel of every emitting script
    log(1, f"obs: profiler trace written to {_PROFILE['dir']}",
        err=True)
    return True


def profile_abort() -> bool:
    """Unconditionally close an active capture — the exception-unwind
    path of the pass loops (a capture left open would both leak and
    make every later :func:`profile_pass_begin` refuse to arm)."""
    if not _PROFILE["active"]:
        return False
    try:
        import jax
        jax.profiler.stop_trace()
    except Exception:
        pass
    _PROFILE["active"] = False
    event("profile_abort", dir=_PROFILE["dir"])
    return True


def profiling_active() -> bool:
    return _PROFILE["active"]


def profile_guard(clear_pass: bool = False):
    """Decorator for outer pass loops that arm capture windows: an
    exception unwinding the loop (capacity MemoryError, device OOM,
    ShardOverflowError degrade) must not leave a capture open (an open
    capture makes every later arm attempt a silent no-op) — only a
    capture the wrapped call itself armed is aborted.  ``clear_pass``
    also drops a process-global ``pass`` context tag the loop set (the
    scoped :func:`context` form unwinds by itself and needs nothing)."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            profiling_before = profiling_active()
            try:
                return fn(*args, **kwargs)
            finally:
                if clear_pass:
                    set_context(**{"pass": None})
                if not profiling_before:
                    profile_abort()
        return wrapper
    return deco


def annotate(name: str):
    """Host-side device-timeline annotation
    (``jax.profiler.TraceAnnotation``) — active only while a capture
    runs, a free nullcontext otherwise (hot dispatch loops wrap every
    chunk in this)."""
    if not _PROFILE["active"]:
        return nullcontext()
    try:
        import jax
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return nullcontext()


def scope(name: str):
    """``jax.named_scope`` wrapper for traced code: XLA ops inside
    carry ``name`` on the device timeline.  Nullcontext when jax is not
    imported (host-only contexts must stay jax-free)."""
    import sys
    jax = sys.modules.get("jax")
    if jax is None:
        return nullcontext()
    try:
        return jax.named_scope(name)
    except Exception:
        return nullcontext()
