"""Typed metrics registry: counters, gauges, log-bucket histograms.

Pure host bookkeeping (no jax): the layers publish into the process
registry (:data:`REGISTRY`) — ``AdaptStats`` via :func:`publish_stats`,
the quiet-group scheduler and halo layout decisions via plain counters,
the serve pool/driver via queue/occupancy gauges and the latency
histogram — and the artifact layer snapshots it
(:func:`MetricsRegistry.snapshot`) into every BENCH/SCALE/SERVE/
MULTIHOST artifact.  :func:`MetricsRegistry.to_prometheus` is the
text exposition for scraping-style consumers;
:func:`parse_prometheus` closes the round-trip (tested).

Tenant namespacing mirrors ``AdaptStats``: a series created with
``tenant="a"`` snapshots under ``tenant:a/<name>`` and exposes with a
``{tenant="a"}`` label — and the cross-tenant isolation contract stays
where it has always lived: ``AdaptStats.__iadd__`` refuses cross-tenant
merges BEFORE anything reaches the registry.

Histograms use fixed log buckets (default powers of two from ~61 us to
256 s) so bucket edges never depend on the data seen — two runs are
always bucket-comparable.
"""
from __future__ import annotations

import bisect
import re
import threading

__all__ = [
    "DEFAULT_BUCKETS", "Counter", "Gauge", "Histogram",
    "MetricsRegistry", "REGISTRY", "parse_prometheus", "publish_stats",
]

# fixed log ladder: 2^-14 s (~61 us) .. 2^8 s (256 s); +Inf implicit
DEFAULT_BUCKETS = tuple(2.0 ** e for e in range(-14, 9))


class Counter:
    """Monotone accumulator (float increments allowed — segment
    seconds accumulate here too)."""
    kind = "counter"
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        self.value += n


class Gauge:
    kind = "gauge"
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Fixed-bound histogram; ``le`` bounds are INCLUSIVE upper edges
    (the Prometheus convention), with an implicit +Inf bucket."""
    kind = "histogram"
    __slots__ = ("bounds", "counts", "sum", "n")

    def __init__(self, bounds=DEFAULT_BUCKETS):
        self.bounds = tuple(float(b) for b in bounds)
        if any(b2 <= b1 for b1, b2 in zip(self.bounds, self.bounds[1:])):
            raise ValueError("histogram bounds must be increasing")
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.n = 0

    def observe(self, v: float) -> None:
        v = float(v)
        self.sum += v
        self.n += 1
        # first bound >= v -> v lands in that (inclusive-upper) bucket
        self.counts[bisect.bisect_left(self.bounds, v)] += 1

    def cumulative(self) -> list[tuple[float, int]]:
        """[(le, cumulative count)] including the +Inf bucket."""
        out = []
        run = 0
        for b, c in zip(self.bounds, self.counts):
            run += c
            out.append((b, run))
        out.append((float("inf"), run + self.counts[-1]))
        return out


class MetricsRegistry:
    """(name, tenant) -> metric.  Names are dotted (``serve.latency_s``);
    the tenant tag is optional and keeps per-tenant series separate."""

    def __init__(self):
        self._m: dict[tuple[str, str | None], object] = {}
        self._lock = threading.Lock()

    def _get(self, kind, name: str, tenant, factory):
        key = (str(name), None if tenant is None else str(tenant))
        with self._lock:
            m = self._m.get(key)
            if m is None:
                m = self._m[key] = factory()
            elif m.kind != kind:
                raise TypeError(
                    f"metric {name!r} (tenant={tenant!r}) already "
                    f"registered as {m.kind}, requested {kind}")
            return m

    def counter(self, name: str, tenant: str | None = None) -> Counter:
        return self._get("counter", name, tenant, Counter)

    def gauge(self, name: str, tenant: str | None = None) -> Gauge:
        return self._get("gauge", name, tenant, Gauge)

    def histogram(self, name: str, tenant: str | None = None,
                  bounds=None) -> Histogram:
        return self._get("histogram", name, tenant,
                         lambda: Histogram(bounds or DEFAULT_BUCKETS))

    def reset(self) -> None:
        with self._lock:
            self._m.clear()

    # ---- reporting --------------------------------------------------------
    @staticmethod
    def _series_key(name: str, tenant: str | None) -> str:
        # the AdaptStats sched_extra namespacing convention
        return name if tenant is None else f"tenant:{tenant}/{name}"

    def snapshot(self) -> dict:
        """JSON-serializable {"counters": {...}, "gauges": {...},
        "histograms": {...}} keyed by the (tenant-namespaced) series
        name — the artifact's ``metrics`` block."""
        with self._lock:
            items = sorted(self._m.items(),
                           key=lambda kv: (kv[0][0], kv[0][1] or ""))
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for (name, tenant), m in items:
            k = self._series_key(name, tenant)
            if m.kind == "counter":
                out["counters"][k] = m.value
            elif m.kind == "gauge":
                out["gauges"][k] = m.value
            else:
                out["histograms"][k] = {
                    "buckets": {repr(le): c
                                for le, c in m.cumulative()},
                    "sum": m.sum, "count": m.n}
        return out

    def to_prometheus(self, prefix: str = "parmmg") -> str:
        """Prometheus text exposition (one HELP-less block per metric;
        tenant as a label; counters suffixed ``_total``)."""
        with self._lock:
            items = sorted(self._m.items(),
                           key=lambda kv: (kv[0][0], kv[0][1] or ""))
        lines = []
        typed: set[str] = set()
        for (name, tenant), m in items:
            base = _prom_name(name, prefix)
            suffix = "_total" if m.kind == "counter" else ""
            full = base + suffix
            if full not in typed:
                typed.add(full)
                lines.append(f"# TYPE {full} {m.kind}")
            lbl = "" if tenant is None else \
                '{tenant="' + _prom_escape(tenant) + '"}'
            if m.kind in ("counter", "gauge"):
                lines.append(f"{full}{lbl} {_prom_num(m.value)}")
            else:
                for le, c in m.cumulative():
                    ll = f'le="{_prom_num(le)}"'
                    if tenant is not None:
                        ll = f'tenant="{_prom_escape(tenant)}",' + ll
                    lines.append(f"{full}_bucket{{{ll}}} {c}")
                lines.append(f"{full}_sum{lbl} {_prom_num(m.sum)}")
                lines.append(f"{full}_count{lbl} {m.n}")
        return "\n".join(lines) + ("\n" if lines else "")


def _prom_name(name: str, prefix: str) -> str:
    return prefix + "_" + re.sub(r"[^a-zA-Z0-9_]", "_", name)


def _prom_escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"')


def _prom_num(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


_LINE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?\s+(?P<val>\S+)$')
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> dict:
    """Exposition text -> {(series name, frozenset(label items)):
    value} — the round-trip half the exposition test closes."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable exposition line: {line!r}")
        labels = frozenset(
            (k, v.replace('\\"', '"').replace("\\\\", "\\"))
            for k, v in _LABEL_RE.findall(m.group("labels") or ""))
        v = m.group("val")
        out[(m.group("name"), labels)] = \
            float("inf") if v == "+Inf" else float(v)
    return out


REGISTRY = MetricsRegistry()


def publish_stats(stats, registry: MetricsRegistry | None = None) -> None:
    """AdaptStats -> metrics bridge.  Series are tenant-tagged from
    ``stats.tenant``; the cross-tenant isolation contract lives in
    ``AdaptStats.__iadd__`` (still raises), so by the time stats reach
    here they are either single-tenant or a legitimately namespaced
    aggregate."""
    reg = registry if registry is not None else REGISTRY
    t = getattr(stats, "tenant", None)
    for name, v in (("adapt.nsplit", stats.nsplit),
                    ("adapt.ncollapse", stats.ncollapse),
                    ("adapt.nswap", stats.nswap),
                    ("adapt.nmoved", stats.nmoved),
                    ("adapt.cycles", stats.cycles),
                    ("adapt.regrows", stats.regrows),
                    ("sched.group_dispatches", stats.group_dispatches),
                    ("sched.group_dispatches_saved",
                     stats.group_dispatches_saved),
                    ("sched.groups_skipped", stats.groups_skipped)):
        if v:
            reg.counter(name, tenant=t).inc(v)
    reg.gauge("adapt.status", tenant=t).set(float(stats.status))
    for k, v in stats.sched_extra.items():
        # already-tenant-namespaced keys (an aggregate's absorbed
        # per-tenant trajectories) keep their AdaptStats spelling
        if k.startswith("tenant:") or not k.endswith("_s") \
                or not isinstance(v, (int, float)):
            continue
        reg.counter(f"sched.{k}", tenant=t).inc(float(v))
