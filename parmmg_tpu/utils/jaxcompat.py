"""jax version compatibility shims (pinned-image survival kit).

The container pins jax 0.4.37 while the code targets the current API
surface; the differences are bridged HERE, in one module, instead of
scattering try/except imports through every caller:

- ``shard_map``: top-level ``jax.shard_map`` only exists on newer jax;
  0.4.x ships it as ``jax.experimental.shard_map.shard_map``.  The
  replication-check kwarg was also renamed (``check_rep`` ->
  ``check_vma``); the shim accepts either spelling and forwards
  whichever the installed jax understands.
- ``axis_size``: ``jax.lax.axis_size`` is newer-jax; on 0.4.x the
  static size of a named axis is recovered via ``lax.psum(1, name)``
  (special-cased to a concrete int for unit literals).

Callers: ``from ..utils.jaxcompat import shard_map, axis_size`` and
use them exactly as on current jax.
"""
from __future__ import annotations

import inspect


def _resolve_shard_map():
    try:
        from jax import shard_map as sm          # jax >= 0.6 spelling
        # jax.shard_map may be a module in some versions — only accept
        # a callable here
        if callable(sm):
            return sm
    except ImportError:
        pass
    from jax.experimental.shard_map import shard_map as sm
    return sm


_SM = _resolve_shard_map()
_SM_PARAMS = inspect.signature(_SM).parameters


def shard_map(f, mesh, in_specs, out_specs, check_vma=None,
              check_rep=None, **kw):
    """Version-portable ``shard_map``: forwards the replication-check
    flag under whichever name (check_vma / check_rep) the installed jax
    accepts; either spelling may be passed."""
    flag = check_vma if check_vma is not None else check_rep
    if flag is not None:
        if "check_vma" in _SM_PARAMS:
            kw["check_vma"] = flag
        elif "check_rep" in _SM_PARAMS:
            kw["check_rep"] = flag
    return _SM(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               **kw)


def axis_size(axis_name):
    """Static size of a named mapped axis, portable across jax versions
    (``jax.lax.axis_size`` vs the psum(1) idiom on 0.4.x)."""
    import jax
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def _jax_version() -> tuple:
    import jax
    try:
        return tuple(int(p) for p in jax.__version__.split(".")[:3])
    except ValueError:                      # pragma: no cover - dev builds
        return (0, 0, 0)


def platform_dependent(*args, default=None, **platform_branches):
    """``jax.lax.platform_dependent`` that survives jax 0.4.x.

    On 0.4.x the underlying cond LOWERS EVERY branch for the target
    platform, so an un-lowerable branch (a Pallas kernel with
    interpret=False on the CPU backend) crashes the whole computation
    even when that branch is unreachable — newer jax prunes branches at
    lowering.  There, fall back to picking the branch for the process
    default backend at TRACE time.  The known cost: a process whose
    default is a TPU plugin but which lowers this computation for CPU
    devices picks the TPU branch wrongly — every CPU-lowering entry
    point in this repo pins JAX_PLATFORMS=cpu (tests/conftest.py,
    scripts/scale_big.py orchestrator, multihost dry runs), so the
    heuristic holds on the pinned image."""
    import jax
    if _jax_version() >= (0, 5, 0) and \
            hasattr(jax.lax, "platform_dependent"):
        return jax.lax.platform_dependent(
            *args, default=default, **platform_branches)
    fn = platform_branches.get(jax.default_backend(), default)
    return fn(*args)


def multiprocess_cache_key_shim() -> bool:
    """Make persistent-compile-cache keys PROCESS-INVARIANT on the
    pinned jax (0.4.37) so pod workers share one warmed cache
    (parallel/multihost.init_multihost).

    Two per-process key poisons on this jax, both empirically verified
    to make worker N+1 MISS every entry worker 0 wrote:

    - the XLA-side autotune-cache mode rides the hashed debug options
      and is UPDATE on process 0 but READ everywhere else
      (jax._src.compiler.get_compile_options) — disabled outright via
      ``jax_persistent_cache_enable_xla_caches="none"`` (those caches
      are GPU-oriented; the pod dev backend is CPU);
    - ``cache_key._hash_accelerator_config`` hashes the SERIALIZED
      PjRt topology, which embeds per-process structure — replaced by
      the module's own documented fallback (device kinds + platform),
      which is process-invariant.  Topology differences that matter
      for compilation still key correctly through the device
      assignment inside the hashed compile options.

    Returns True when the shim applied.  Idempotent."""
    import jax
    try:
        jax.config.update("jax_persistent_cache_enable_xla_caches",
                          "none")
    except Exception:
        pass                        # newer jax: key already invariant
    try:
        from jax._src import cache_key as _ck
        if getattr(_ck, "_parmmg_invariant_accel", False):
            return True

        def _invariant_accel(hash_obj, accelerators, backend):
            _ck._hash_devices(hash_obj, accelerators)
            _ck._hash_platform(hash_obj, backend)

        _ck._hash_accelerator_config = _invariant_accel
        _ck._parmmg_invariant_accel = True
        return True
    except Exception:               # pragma: no cover - future jax
        return False
