"""Debug dumps — debug_pmmg.c parity.

The reference dumps per-group meshes, quality lists, tag tables and
communicator contents to text/.mesh files (debug_pmmg.c:62-773) for
post-mortem inspection.  Equivalents here, driven from any core Mesh or
stacked shard pytree.
"""
from __future__ import annotations

from pathlib import Path

import numpy as np

from ..core.mesh import Mesh, mesh_to_host
from ..core import constants as C


def dump_mesh(mesh: Mesh, path: str | Path, met=None) -> Path:
    """Write the (compacted) mesh as Medit .mesh (+ .sol for the metric)
    — PMMG_grplst_meshes_to_saveMesh flavor."""
    from ..io.medit import MeditMesh, write_mesh, write_sol, SOL_SCALAR, \
        SOL_TENSOR
    from ..core.mesh import tet_face_vertices

    path = Path(path)
    vert, tet, vref, tref, vtag = mesh_to_host(mesh)
    m = MeditMesh()
    m.vert, m.vref = vert, vref
    m.tetra, m.tref = tet, tref
    # boundary faces
    vm = np.asarray(mesh.vmask)
    new_id = np.cumsum(vm) - 1
    fv = np.asarray(tet_face_vertices(mesh.tet))
    ftag = np.asarray(mesh.ftag)
    sel = ((ftag & C.MG_BDY) != 0) & np.asarray(mesh.tmask)[:, None]
    m.tria = new_id[fv[sel]].astype(np.int32)
    m.triaref = np.asarray(mesh.fref)[sel]
    write_mesh(path, m)
    if met is not None:
        mh = np.asarray(met)[vm]
        write_sol(path.with_suffix(".sol"), mh.reshape(len(vert), -1),
                  [SOL_TENSOR if mh.ndim == 2 and mh.shape[1] == 6
                   else SOL_SCALAR])
    return path


def dump_tags(mesh: Mesh, path: str | Path) -> Path:
    """Per-vertex tag table (PMMG_print_* flavor)."""
    path = Path(path)
    vert, tet, vref, tref, vtag = mesh_to_host(mesh)
    names = [("BDY", C.MG_BDY), ("REQ", C.MG_REQ), ("CRN", C.MG_CRN),
             ("GEO", C.MG_GEO), ("REF", C.MG_REF), ("NOM", C.MG_NOM),
             ("PARBDY", C.MG_PARBDY), ("PARBDYBDY", C.MG_PARBDYBDY)]
    with open(path, "w") as f:
        for i, t in enumerate(vtag):
            tags = "|".join(n for n, b in names if t & b) or "-"
            f.write(f"{i} {vert[i][0]:.6g} {vert[i][1]:.6g} "
                    f"{vert[i][2]:.6g} {tags}\n")
    return path


def dump_comms(comms, path: str | Path) -> Path:
    """Communicator tables printer (PMMG_print_ext_comm flavor)."""
    path = Path(path)
    with open(path, "w") as f:
        S, K, _ = comms.node_idx.shape
        for s in range(S):
            for k in range(K):
                b = int(comms.nbr[s, k])
                if b < 0:
                    continue
                n = int(comms.node_cnt[s, k])
                nf = int(comms.face_cnt[s, k])
                f.write(f"shard {s} <-> {b}: {n} nodes, {nf} faces\n")
                f.write("  nodes: " + " ".join(
                    map(str, comms.node_idx[s, k, :n])) + "\n")
    return path


def check_mesh_consistency(mesh: Mesh) -> dict:
    """Aggregate self-check: adjacency symmetry, positive volumes, mask
    consistency (the debug-build assertion battery of the reference)."""
    from ..ops.adjacency import build_adjacency, check_adjacency
    from ..core.mesh import tet_volumes
    import jax.numpy as jnp

    m = build_adjacency(mesh)
    out = dict(check_adjacency(m))
    vols = np.asarray(tet_volumes(m))[np.asarray(m.tmask)]
    out["nonpositive_vols"] = int((vols <= 0).sum())
    tet = np.asarray(m.tet)[np.asarray(m.tmask)]
    vm = np.asarray(m.vmask)
    out["dangling_vertex_refs"] = int((~vm[tet]).sum())
    return out
