"""Synthetic mesh generators for tests and benchmarks.

The reference test suite pulls Cube/Sphere/Torus meshes from a separate data
repo (cmake/testing/pmmg_tests.cmake:12-23); we generate equivalents
procedurally so the test matrix is self-contained.
"""
from __future__ import annotations

import numpy as np

# Each unit cube cell is split into 6 tets (Kuhn/Freudenthal triangulation:
# all tets share the main diagonal (0,0,0)-(1,1,1); produces a conforming
# mesh across cells without parity flips).
_KUHN_TETS = np.array([
    [0, 1, 3, 7],
    [0, 1, 5, 7],
    [0, 2, 3, 7],
    [0, 2, 6, 7],
    [0, 4, 5, 7],
    [0, 4, 6, 7],
], dtype=np.int64)
# corner i of the cell has offsets (i&1, (i>>1)&1, (i>>2)&1)


def cube_mesh(n: int = 4):
    """Structured [0,1]^3 cube: (n+1)^3 vertices, 6*n^3 tets.

    Returns (vert [np,3] float64, tet [ne,4] int32), positively oriented.
    """
    k = n + 1
    g = np.arange(k) / n
    X, Y, Z = np.meshgrid(g, g, g, indexing="ij")
    vert = np.stack([X.ravel(), Y.ravel(), Z.ravel()], axis=1)

    def vid(i, j, l):
        return (i * k + j) * k + l

    ii, jj, ll = np.meshgrid(np.arange(n), np.arange(n), np.arange(n),
                             indexing="ij")
    base = np.stack([ii.ravel(), jj.ravel(), ll.ravel()], 1)  # [n^3,3]
    corners = np.empty((base.shape[0], 8), np.int64)
    for c in range(8):
        off = np.array([c & 1, (c >> 1) & 1, (c >> 2) & 1])
        q = base + off
        corners[:, c] = vid(q[:, 0], q[:, 1], q[:, 2])
    tet = corners[:, _KUHN_TETS].reshape(-1, 4)
    tet = _orient_positive(vert, tet)
    return vert, tet.astype(np.int32)


def sphere_mesh(n: int = 8):
    """Unit ball: cube mesh mapped radially onto the ball (graded)."""
    vert, tet = cube_mesh(n)
    c = vert * 2.0 - 1.0                       # [-1,1]^3
    linf = np.max(np.abs(c), axis=1)
    l2 = np.linalg.norm(c, axis=1)
    scale = np.where(l2 > 1e-12, linf / np.maximum(l2, 1e-12), 1.0)
    vert = c * scale[:, None]
    tet = _orient_positive(vert, tet)
    return vert, tet.astype(np.int32)


def torus_mesh(nu: int = 12, nc: int = 4, R: float = 1.0, r: float = 0.4):
    """Solid torus: centerline radius R, tube radius r.

    Square-to-disk mapped cross-section (nc cells across), extruded around
    nu stations with periodic Kuhn cells — conforming across the wrap by
    translation invariance of the Freudenthal split.  The genus-1 boundary
    (Euler characteristic 0) is the fixture the reference CI matrix pulls
    from its mesh repo (cmake/testing/pmmg_tests.cmake:25-38).
    """
    kc = nc + 1
    g = np.arange(kc) / nc * 2.0 - 1.0
    A, B = np.meshgrid(g, g, indexing="ij")
    ab = np.stack([A.ravel(), B.ravel()], axis=1)
    linf = np.max(np.abs(ab), axis=1)
    l2 = np.linalg.norm(ab, axis=1)
    scale = np.where(l2 > 1e-12, linf / np.maximum(l2, 1e-12), 1.0)
    disk = ab * scale[:, None] * r                 # [(nc+1)^2, 2]
    vert = []
    for u in np.arange(nu) / nu * 2.0 * np.pi:
        x = (R + disk[:, 0]) * np.cos(u)
        y = (R + disk[:, 0]) * np.sin(u)
        vert.append(np.stack([x, y, disk[:, 1]], axis=1))
    vert = np.concatenate(vert)

    def vid(i, j, l):
        return (i % nu) * (kc * kc) + j * kc + l

    ii, jj, ll = np.meshgrid(np.arange(nu), np.arange(nc), np.arange(nc),
                             indexing="ij")
    base = np.stack([ii.ravel(), jj.ravel(), ll.ravel()], 1)
    corners = np.empty((base.shape[0], 8), np.int64)
    for c in range(8):
        off = np.array([c & 1, (c >> 1) & 1, (c >> 2) & 1])
        q = base + off
        corners[:, c] = vid(q[:, 0], q[:, 1], q[:, 2])
    tet = corners[:, _KUHN_TETS].reshape(-1, 4)
    tet = _orient_positive(vert, tet)
    return vert, tet.astype(np.int32)


def _orient_positive(vert, tet):
    p = vert[tet]
    det = np.einsum("ti,ti->t", p[:, 1] - p[:, 0],
                    np.cross(p[:, 2] - p[:, 0], p[:, 3] - p[:, 0]))
    flip = det < 0
    tet = tet.copy()
    tet[flip, 0], tet[flip, 1] = tet[flip, 1], tet[flip, 0].copy()
    return tet


def analytic_iso_metric(vert: np.ndarray, kind: str = "uniform",
                        h: float = 0.1):
    """Test metrics: uniform h, or a planar 'shock' refinement band."""
    if kind == "uniform":
        return np.full(vert.shape[0], h)
    if kind == "shock":
        # small size near the plane x=0.5, large away (aniso-torus analogue
        # of the reference CI matrix)
        d = np.abs(vert[:, 0] - 0.5)
        return h * (0.2 + 4.0 * d)
    raise ValueError(kind)


def analytic_ani_metric(vert: np.ndarray, kind: str = "shock",
                        h: float = 0.1, h_tan: float = 0.45):
    """Packed anisotropic test metrics [n, 6] (Mmg packing
    m11,m12,m13,m22,m23,m33): ``shock`` = planar-shock tensor — tight
    spacing ACROSS the plane x=0.5 (h scaled by distance, like the iso
    shock), loose ``h_tan`` along the tangential directions.  The
    aniso-torus analogue of the reference CI matrix
    (cmake/testing/pmmg_tests.cmake:31-38)."""
    n = vert.shape[0]
    if kind == "shock":
        d = np.abs(vert[:, 0] - 0.5)
        hx = h * (0.2 + 4.0 * d)
        m = np.zeros((n, 6))
        m[:, 0] = 1.0 / hx ** 2
        m[:, 3] = 1.0 / h_tan ** 2
        m[:, 5] = 1.0 / h_tan ** 2
        return m
    raise ValueError(kind)


def cylinder_mesh(n: int = 6, r: float = 0.5):
    """Solid cylinder (radius r, height 1, axis z): cube mesh with the
    (x, y) square cross-section mapped onto the disk.  The cap rims are
    CURVED ridge lines (90-degree dihedral along a circle) — the
    feature-line fixture class (torus-equator/cylinder-cap) the
    reference CI exercises for ridge geometry."""
    vert, tet = cube_mesh(n)
    c = vert[:, :2] * 2.0 - 1.0
    linf = np.max(np.abs(c), axis=1)
    l2 = np.linalg.norm(c, axis=1)
    scale = np.where(l2 > 1e-12, linf / np.maximum(l2, 1e-12), 1.0)
    vert = np.concatenate([c * scale[:, None] * r, vert[:, 2:]], axis=1)
    tet = _orient_positive(vert, tet)
    return vert, tet.astype(np.int32)


def steady_state_migration_scenario(niter: int = 4, cycles: int = 2,
                                    n_shards: int = 2,
                                    n_devices: int | None = None,
                                    return_all: bool = False):
    """The compile-governor CI scenario, shared by the --ledger budget
    gate (scripts/ledger_check.py) and the tier-1 regression test
    (tests/test_compile_ledger.py) so the two gates cannot drift apart:
    ``niter`` migration iterations over a small cube whose interface
    sizes drift every iteration — the steady-state loop whose retag /
    extend-ids / flood / interface-check entry points must stay on a
    bounded set of compiled variants.  ``n_devices`` < ``n_shards``
    runs the grouped (G>1) composition, exercising the grouped
    analysis/halo entry points on the same bucketed shapes.

    Returns the adapted merged mesh, or (mesh, met, part) with
    ``return_all`` — the shared fixture the burned-down migration tests
    assert conformity/labels on, so tier-1 pays ONE compile for the
    whole scenario family instead of one per test."""
    import jax.numpy as jnp
    from ..core.mesh import make_mesh
    from ..ops.analysis import analyze_mesh
    from ..parallel import dist

    vert, tet = cube_mesh(2)
    m = make_mesh(vert, tet, capP=6 * len(vert), capT=6 * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, 0.4, m.vert.dtype)
    out, met_m, part = dist.distributed_adapt_multi(
        m, met, n_shards, niter=niter, cycles=cycles,
        n_devices=n_devices)
    return (out, met_m, part) if return_all else out
