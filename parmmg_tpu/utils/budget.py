"""Memory budgeting: capacity planning under a device-memory cap.

The reference detects available RAM per node, splits it across ranks and
repartitions the budget over the point/xpoint/tetra/xtetra arrays
(``PMMG_parsar -m``, zaldy_pmmg.c:53-254).  On TPU the analogue is HBM:
given a budget in MB, derive the maximum safe array *capacities* (points
and tets) for the adapt kernels, whose footprint is a known multiple of
capP/capT (the wave kernels materialize ~6*capT edge slots of int32 plus
the mesh arrays).
"""
from __future__ import annotations

# bytes per capacity slot (fp32 mesh): measured from the Mesh layout +
# wave-kernel temporaries (edge table + sort buffers dominate)
BYTES_PER_POINT = 3 * 4 + 4 + 4 + 1 + 4          # vert,vref,vtag,vmask,met
BYTES_PER_TET = (4 + 1 + 4 + 4 + 4 + 6) * 4 \
    + 6 * 3 * 4 * 4                               # arrays + edge-table tmp


def plan_capacities(n_p: int, n_t: int, budget_mb: int = -1,
                    headroom: float = 3.0,
                    device_hbm_mb: int = 16_000) -> tuple[int, int]:
    """(capP, capT) under the budget; default = 3x growth headroom
    clamped so the adapt kernels fit in the budget (or HBM)."""
    budget = (budget_mb if budget_mb > 0 else int(0.6 * device_hbm_mb)) \
        * 1_000_000
    capP = int(headroom * n_p)
    capT = int(headroom * n_t)
    need = capP * BYTES_PER_POINT + capT * BYTES_PER_TET
    if need > budget:
        scale = budget / need
        capP = max(n_p, int(capP * scale))
        capT = max(n_t, int(capT * scale))
    return max(64, capP), max(64, capT)
