"""Compile governor: shape bucketing + a process-wide compile ledger.

The steady-state loop of this system re-runs the SAME programs every
migration iteration and every adapt wave (the libparmmg1.c remesh/
repartition cycle), but jitted entry points whose static shapes track
exact per-iteration sizes recompile forever: the retag KF2/KN widths,
the interface comm-table pads, group capacities and narrow-row budgets
all drift by a few entries between iterations, and each drift is a
fresh multi-second XLA compile (ADVICE round 3; a late big compile is
also what kills tunneled TPU workers at the >=1M-tet scale).  A serving
stack bounds and observes its compile count; this module is that layer:

- :func:`bucket` — the ONE shape-rounding policy every dynamic
  static-shape site routes through (next-pow2 with a floor, or a
  geometric 1.5x scheme for wide tables where pow2 doubling wastes
  memory), so repeat iterations land on a small fixed set of shapes;
- :func:`governed` — an explicit registry decorator for jitted entry
  points.  Paired with a ``jax.monitoring`` duration listener on the
  backend-compile event, it maintains a process-wide **compile
  ledger**: per entry point, the distinct static-shape variants that
  actually compiled, the compile count, cumulative compile seconds and
  the last static shapes — printed by bench.py / scripts/scale_big.py
  so churn regressions are visible in every BENCH artifact, and
  enforced by ``scripts/run_tests.sh --ledger`` via per-entry variant
  budgets;
- :func:`set_cache_env` / :func:`enable_persistent_cache` — the
  persistent-cache wiring (JAX_COMPILATION_CACHE_DIR) shared by the
  CLI, bench and scale drivers so cross-process workers
  (parallel/_polish_worker.py, fresh-client pass subprocesses) reuse
  compiled executables instead of starting cold.
"""
from __future__ import annotations

import dataclasses
import functools
import os
import threading

# the jax.monitoring event recorded around every XLA backend compile
# (jax._src.dispatch.BACKEND_COMPILE_EVENT; stable across 0.4.x)
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


# ---------------------------------------------------------------------------
# shape bucketing
# ---------------------------------------------------------------------------
def bucket(n: int, floor: int = 256, scheme: str = "pow2",
           cap: int | None = None) -> int:
    """Round ``n`` up to a bucketed static size.

    ``scheme="pow2"``: next power-of-two multiple of ``floor`` — the
    default for index tables and compaction budgets (at most 2x
    overshoot, very few distinct shapes).
    ``scheme="geo"``: geometric 1.5x ladder from ``floor`` — for WIDE
    tables (comm item axes, group capacities) where a pow2 jump can
    waste a large absolute amount of memory; overshoot <= 1.5x while
    still collapsing drifting sizes onto O(log n) shapes.

    ``cap`` clamps the result (capacity ceilings like capT); a capped
    bucket may be smaller than ``n`` — callers that cannot truncate
    must check, exactly as they would for any static budget.
    """
    n = max(int(n), 1)
    b = max(int(floor), 1)
    if scheme == "pow2":
        while b < n:
            b *= 2
    elif scheme == "geo":
        while b < n:
            b = b * 3 // 2 + 1
    else:
        raise ValueError(f"unknown bucket scheme {scheme!r}")
    if cap is not None:
        b = min(b, int(cap))
    return b


# ---------------------------------------------------------------------------
# the compile ledger
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class EntryStats:
    """Per-entry-point compile accounting (mutated under the ledger lock)."""
    budget: int | None = None      # max allowed compiled variants (None = untracked)
    calls: int = 0
    compiles: int = 0              # backend-compile events attributed here
    compile_secs: float = 0.0
    keys_seen: set = dataclasses.field(default_factory=set)
    keys_compiled: set = dataclasses.field(default_factory=set)
    last_key: tuple = ()

    @property
    def variants(self) -> int:
        """Distinct static-shape keys that triggered >= 1 compile."""
        return len(self.keys_compiled)


class CompileLedger:
    """Process-wide registry: entry point -> EntryStats.

    Attribution: :meth:`track` pushes the entry name on a thread-local
    stack; the ``jax.monitoring`` listener credits every backend-compile
    event to the innermost governed entry on the calling thread (XLA
    compiles synchronously inside the dispatching call).  Events firing
    outside any governed scope land in the ``(ungoverned)`` aggregate,
    so total compile time stays visible even for unregistered programs.
    """

    UNGOVERNED = "(ungoverned)"

    def __init__(self):
        self._entries: dict[str, EntryStats] = {}
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._listener_installed = False

    # -- registration / listener -------------------------------------------
    def register(self, name: str, budget: int | None = None) -> None:
        with self._lock:
            e = self._entries.setdefault(name, EntryStats())
            if budget is not None:
                e.budget = budget
        self.install_listener()

    def install_listener(self) -> None:
        if self._listener_installed:
            return
        try:
            from jax import monitoring
        except Exception:       # pragma: no cover - jax always present
            return
        monitoring.register_event_duration_secs_listener(self._on_event)
        self._listener_installed = True

    def _on_event(self, event: str, duration: float) -> None:
        if event != BACKEND_COMPILE_EVENT:
            return
        stack = getattr(self._tls, "stack", None)
        name = stack[-1][0] if stack else self.UNGOVERNED
        with self._lock:
            e = self._entries.setdefault(name, EntryStats())
            e.compiles += 1
            e.compile_secs += float(duration)
            if stack:
                e.keys_compiled.add(stack[-1][1])

    # -- call tracking ------------------------------------------------------
    def track(self, name: str, key: tuple) -> "_TrackScope":
        return _TrackScope(self, name, key)

    # -- reporting ----------------------------------------------------------
    def snapshot(self) -> dict:
        """{entry: {calls, variants, shapes_seen, compiles, compile_s,
        last_shapes, budget}} — JSON-serializable."""
        with self._lock:
            out = {}
            for name, e in sorted(self._entries.items()):
                out[name] = {
                    "calls": e.calls,
                    "variants": e.variants,
                    "shapes_seen": len(e.keys_seen),
                    "compiles": e.compiles,
                    "compile_s": round(e.compile_secs, 3),
                    "last_shapes": repr(e.last_key) if e.last_key else "",
                    "budget": e.budget,
                }
            return out

    def violations(self) -> list[str]:
        """Entries whose compiled-variant count exceeds their budget."""
        bad = []
        with self._lock:
            for name, e in sorted(self._entries.items()):
                if e.budget is not None and e.variants > e.budget:
                    bad.append(f"{name}: {e.variants} compiled variants "
                               f"> budget {e.budget}")
        return bad

    def format(self, min_compiles: int = 0) -> str:
        rows = [f"{'entry point':36s} {'calls':>6s} {'vars':>5s} "
                f"{'compiles':>8s} {'secs':>8s}"]
        for name, rec in self.snapshot().items():
            # hide rows that were only registered (import-time @governed)
            # but never called or compiled; min_compiles raises the bar
            if rec["calls"] == 0 and rec["compiles"] < max(min_compiles, 1):
                continue
            rows.append(f"{name:36s} {rec['calls']:6d} "
                        f"{rec['variants']:5d} {rec['compiles']:8d} "
                        f"{rec['compile_s']:8.2f}")
        return "\n".join(rows)

    def reset(self) -> None:
        with self._lock:
            for e in self._entries.values():
                e.calls = 0
                e.compiles = 0
                e.compile_secs = 0.0
                e.keys_seen.clear()
                e.keys_compiled.clear()
                e.last_key = ()


class _TrackScope:
    """Context manager crediting backend compiles inside the scope to a
    governed entry (one instance per call — the steady-state loop calls
    governed entries every iteration, so no per-call class creation)."""

    __slots__ = ("_ledger", "_name", "_key")

    def __init__(self, ledger: CompileLedger, name: str, key: tuple):
        self._ledger = ledger
        self._name = name
        self._key = key

    def __enter__(self):
        led = self._ledger
        if not hasattr(led._tls, "stack"):
            led._tls.stack = []
        led._tls.stack.append((self._name, self._key))
        with led._lock:
            e = led._entries.setdefault(self._name, EntryStats())
            e.calls += 1
            e.keys_seen.add(self._key)
            e.last_key = self._key
        return self

    def __exit__(self, *exc):
        self._ledger._tls.stack.pop()
        return False


LEDGER = CompileLedger()


def _static_key(args, kwargs) -> tuple:
    """Hashable static-shape key of a call: array leaves contribute
    (shape, dtype); hashable non-array leaves contribute their value
    (jit static args); everything else its type name."""
    import jax
    leaves = jax.tree_util.tree_leaves((args, kwargs))
    parts = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            parts.append((tuple(leaf.shape), str(leaf.dtype)))
        else:
            try:
                hash(leaf)
                parts.append(leaf)
            except TypeError:
                parts.append(type(leaf).__name__)
    return tuple(parts)


def governed(name: str, budget: int | None = None, key_fn=None):
    """Register a (usually jitted) entry point with the compile ledger.

    Every call records its static-shape key; backend compiles occurring
    inside the call are attributed to ``name``.  ``budget`` declares
    the allowed number of compiled variants (enforced by
    ``scripts/run_tests.sh --ledger`` and checkable in tests via
    :func:`ledger_violations`); ``key_fn(*args, **kwargs)`` overrides
    the default shapes-and-statics key.
    """
    def deco(fn):
        LEDGER.register(name, budget)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            key = key_fn(*args, **kwargs) if key_fn is not None \
                else _static_key(args, kwargs)
            with LEDGER.track(name, key):
                return fn(*args, **kwargs)

        wrapper.__wrapped__ = fn
        return wrapper
    return deco


def ledger_diff(old: dict, new: dict) -> list[str]:
    """Compile-ledger regression check between two snapshots: entry
    points present in BOTH whose compiled-variant count grew.

    ``old``/``new`` accept either a flat snapshot ({entry: {variants,
    ...}}) or the nested per-worker shape scale_big emits ({"pass0":
    {entry: ...}, "host": ...}) — nested levels are flattened with a
    "<worker>/" prefix and compared per worker.  Entries only in
    ``new`` are NOT regressions (fresh programs carry their own
    budgets); a grown variant count on a shared entry is the churn
    signature bench.py and scripts/scale_big.py flag against the
    previous BENCH/SCALE artifact."""
    def flatten(d: dict, prefix: str = "") -> dict:
        out = {}
        for k, v in (d or {}).items():
            if isinstance(v, dict) and "variants" not in v:
                out.update(flatten(v, prefix + str(k) + "/"))
            elif isinstance(v, dict):
                out[prefix + str(k)] = v
        return out

    fo, fn_ = flatten(old), flatten(new)
    bad = []
    for name in sorted(set(fo) & set(fn_)):
        vo = int(fo[name].get("variants", 0))
        vn = int(fn_[name].get("variants", 0))
        if vn > vo:
            bad.append(f"{name}: {vo} -> {vn} compiled variants")
    return bad


def extract_artifact_ledger(doc) -> dict:
    """Pull the compile-ledger dict out of any artifact shape we emit:
    a plain snapshot, bench JSON ({extra: {compile_ledger}}), or the
    round wrapper ({parsed: {extra: {compile_ledger}}})."""
    if not isinstance(doc, dict):
        return {}
    for path in (("parsed", "extra", "compile_ledger"),
                 ("extra", "compile_ledger"),
                 ("compile_ledger",)):
        d = doc
        for k in path:
            d = d.get(k) if isinstance(d, dict) else None
            if d is None:
                break
        if isinstance(d, dict):
            return d
    return doc


def regressions_vs_latest_artifact(root: str, pattern: str,
                                   ledger: dict) -> list[str]:
    """Diff ``ledger`` against the NEWEST round artifact matching
    ``pattern`` (e.g. "BENCH_r*.json") under ``root`` — the shared
    bench-side regression check of bench.py / scripts/scale_big.py.
    Artifacts without a ledger compare clean (the first governed round
    seeds the baseline)."""
    import glob
    import json
    import re

    def rnum(p: str) -> int:
        m = re.search(r"r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    def has_rows(d: dict) -> bool:
        return any(isinstance(v, dict) and
                   ("variants" in v or has_rows(v)) for v in d.values())

    for path in sorted(glob.glob(os.path.join(root, pattern)),
                       key=rnum, reverse=True):
        try:
            with open(path) as f:
                doc = json.load(f)
        except Exception:
            continue
        prev = extract_artifact_ledger(doc)
        if prev and has_rows(prev):
            return ledger_diff(prev, ledger)
    return []


# module-level conveniences (re-exported by utils.timers)
def ledger_snapshot() -> dict:
    return LEDGER.snapshot()


def variants_by_prefix(prefix: str) -> dict:
    """{entry: compiled-variant count} for ledger entries under a name
    prefix — the compile-family comparison unit of the zero-new-family
    gates (ledger_check grouped_sched_gate / serving_gate) and of
    scripts/serve_bench.py's batch-vs-serve diff: snapshot before,
    snapshot after, equality == no new compiled shape families."""
    return {k: r["variants"] for k, r in LEDGER.snapshot().items()
            if k.startswith(prefix)}


def format_ledger(min_compiles: int = 0) -> str:
    return LEDGER.format(min_compiles)


def reset_ledger() -> None:
    LEDGER.reset()


def ledger_violations() -> list[str]:
    return LEDGER.violations()


# ---------------------------------------------------------------------------
# persistent-cache wiring
# ---------------------------------------------------------------------------
def default_cache_dir() -> str:
    """Repo-local cache directory (the same .jax_cache bench.py and
    scripts/profile_adapt.py historically defaulted to)."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, ".jax_cache")


def set_cache_env(cache_dir: str | None = None) -> str:
    """Default the persistent-compile-cache env vars WITHOUT importing
    jax — safe to call before backend selection, and inherited by
    subprocess workers (_polish_worker, scale_big pass workers).  An
    existing JAX_COMPILATION_CACHE_DIR always wins.

    Skipped (returns "") on the forced-CPU backend (JAX_PLATFORMS=cpu):
    the XLA:CPU AOT cache is unreliable on this image (its serializer
    intermittently aborts — tests/conftest.py rationale).  An explicit
    ``cache_dir`` argument or a pre-set JAX_COMPILATION_CACHE_DIR env
    var opts in regardless."""
    if ("JAX_COMPILATION_CACHE_DIR" not in os.environ
            and cache_dir is None
            and os.environ.get("JAX_PLATFORMS", "") == "cpu"):
        return ""
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          cache_dir or default_cache_dir())
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    return os.environ["JAX_COMPILATION_CACHE_DIR"]


def enable_persistent_cache(cache_dir: str | None = None) -> str:
    """set_cache_env + push the values into an already-imported jax
    config (covers callers that imported jax before the env was set).
    No-op (returns "") on a CPU backend — checked against the RESOLVED
    backend, not just the JAX_PLATFORMS env var.  The cache_dir /
    pre-set-env-var opt-ins only apply on the PINNED CPU backend
    (JAX_PLATFORMS=cpu); a silent CPU fallback (accelerator
    absent/unreachable without the pin) always stays uncached, and any
    cache dir jax already picked up from an inherited env var is
    actively cleared — there is no legitimate opt-in story for the
    degraded path."""
    import jax
    if jax.default_backend() == "cpu":
        pinned = os.environ.get("JAX_PLATFORMS", "") == "cpu"
        opted_in = (cache_dir is not None
                    or "JAX_COMPILATION_CACHE_DIR" in os.environ)
        if not (pinned and opted_in):
            os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
            jax.config.update("jax_compilation_cache_dir", None)
            return ""
    path = set_cache_env(cache_dir)
    if not path:
        return ""
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))
    return path


def drop_cache_on_cpu_fallback() -> bool:
    """Post-backend-resolution guard for processes that export the
    cache env BEFORE jax import (CLI, scale_big pass workers): when the
    backend silently resolved to XLA:CPU without the explicit
    JAX_PLATFORMS=cpu pin (accelerator absent/unreachable), drop the
    persistent cache again — the XLA:CPU AOT cache is unreliable on
    this image (tests/conftest.py rationale), and the env var is popped
    too so subprocesses cannot inherit the bad combination.  Returns
    True when dropped.  Resolving the backend here costs nothing extra:
    every caller runs jax programs right after."""
    import jax
    if (os.environ.get("JAX_PLATFORMS", "") != "cpu"
            and os.environ.get("JAX_COMPILATION_CACHE_DIR")
            and jax.default_backend() == "cpu"):
        os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
        jax.config.update("jax_compilation_cache_dir", None)
        return True
    return False
