"""Hierarchical wall-clock timers (mytime/chrono/printim analogue).

The reference tracks per-phase times in ``PMMG_ctim[TIMEMAX]`` slots with
verbosity-gated prints (parmmg.c:35,91; libparmmg1.c:636-948).  Here a
small nestable timer registry with the same reporting role.

Every completed scope ALSO emits a structured trace span
(obs/trace.py) carrying this instance's ``trace_id``, so the JSONL
trace replays to exactly this registry's totals
(``obs.trace.replay_totals(path, tim=timers.trace_id)`` — the
``run_tests.sh --obs`` gate's check).  Emission is a ring-buffer append
when no sink is armed: safe in the chunk-pipeline hot loop.

The compile ledger (utils/compilecache.py) is re-exported here so the
drivers' reporting layer has ONE import surface for both wall-clock and
compile accounting: ``Timers.report`` for phases,
``format_ledger``/``ledger_snapshot`` for XLA compile churn.
"""
from __future__ import annotations

import itertools
import time
from contextlib import contextmanager

from .compilecache import (                                    # noqa: F401
    LEDGER, format_ledger, ledger_snapshot, ledger_violations,
    reset_ledger)

_EMIT = None        # lazily-bound obs.trace.emit_span (False = unavailable)


def _emit_span(path, dur, count=1, tim=None, ext=False) -> None:
    global _EMIT
    if _EMIT is None:
        try:
            from ..obs.trace import emit_span
            _EMIT = emit_span
        except Exception:       # pragma: no cover - obs is always present
            _EMIT = False
    if _EMIT:
        _EMIT(path, dur, count=count, tim=tim, ext=ext)


class Timers:
    _IDS = itertools.count(1)

    def __init__(self):
        self.acc: dict[str, float] = {}
        self.count: dict[str, int] = {}
        self._stack: list[tuple[str, float]] = []
        # paths absorbed via add() OUTSIDE any active scope: externally
        # measured segments, rendered distinctly by report()
        self.external: set[str] = set()
        # stable id stamped on every emitted span (the replay filter)
        self.trace_id: int = next(Timers._IDS)

    @contextmanager
    def __call__(self, name: str):
        path = "/".join([p for p, _ in self._stack] + [name])
        t0 = time.perf_counter()
        self._stack.append((name, t0))
        try:
            yield
        finally:
            self._stack.pop()
            # round to the ns the trace span carries (emit_span rounds
            # its record to 9 decimals): accumulator and replayed
            # stream then agree bit-for-bit even on kernels whose
            # perf_counter returns sub-ns fractions (the --obs gate's
            # replay==report contract)
            dt = round(time.perf_counter() - t0, 9)
            self.acc[path] = self.acc.get(path, 0.0) + dt
            self.count[path] = self.count.get(path, 0) + 1
            _emit_span(path, dt, tim=self.trace_id)

    def add(self, name: str, seconds: float, count: int = 1) -> None:
        """Fold an externally-measured duration into the registry at
        the current nesting path.  The grouped chunk pipeline
        (parallel/groups._pipeline_chunks) measures its
        upload/compute/download/writeback segments on a local Timers
        and absorbs them into the driver's reporting instance here.

        Called OUTSIDE any active ``with tim(...)`` scope, the segment
        is tagged *external* (it was measured by another component, not
        timed here): ``report()`` renders it with an ``[absorbed]``
        marker instead of passing it off as a phase of this registry,
        and the emitted span carries ``ext=True``."""
        ext = not self._stack
        path = "/".join([p for p, _ in self._stack] + [name])
        if ext:
            self.external.add(path)
        # same ns rounding as the scope exit: acc == replayed spans
        seconds = round(float(seconds), 9)
        self.acc[path] = self.acc.get(path, 0.0) + seconds
        self.count[path] = self.count.get(path, 0) + int(count)
        _emit_span(path, seconds, count=int(count),
                   tim=self.trace_id, ext=ext)

    def report(self, min_s: float = 0.0) -> str:
        lines = []
        for k in sorted(self.acc):
            if self.acc[k] < min_s:
                continue
            depth = k.count("/")
            mark = "  [absorbed]" if k in self.external else ""
            lines.append(f"{'  ' * depth}{k.split('/')[-1]:28s} "
                         f"{self.acc[k]:9.3f}s  x{self.count[k]}{mark}")
        return "\n".join(lines)
