"""Hierarchical wall-clock timers (mytime/chrono/printim analogue).

The reference tracks per-phase times in ``PMMG_ctim[TIMEMAX]`` slots with
verbosity-gated prints (parmmg.c:35,91; libparmmg1.c:636-948).  Here a
small nestable timer registry with the same reporting role.

The compile ledger (utils/compilecache.py) is re-exported here so the
drivers' reporting layer has ONE import surface for both wall-clock and
compile accounting: ``Timers.report`` for phases,
``format_ledger``/``ledger_snapshot`` for XLA compile churn.
"""
from __future__ import annotations

import time
from contextlib import contextmanager

from .compilecache import (                                    # noqa: F401
    LEDGER, format_ledger, ledger_snapshot, ledger_violations,
    reset_ledger)


class Timers:
    def __init__(self):
        self.acc: dict[str, float] = {}
        self.count: dict[str, int] = {}
        self._stack: list[tuple[str, float]] = []

    @contextmanager
    def __call__(self, name: str):
        path = "/".join([p for p, _ in self._stack] + [name])
        t0 = time.perf_counter()
        self._stack.append((name, t0))
        try:
            yield
        finally:
            self._stack.pop()
            dt = time.perf_counter() - t0
            self.acc[path] = self.acc.get(path, 0.0) + dt
            self.count[path] = self.count.get(path, 0) + 1

    def add(self, name: str, seconds: float, count: int = 1) -> None:
        """Fold an externally-measured duration into the registry at
        the current nesting path.  The grouped chunk pipeline
        (parallel/groups._pipeline_chunks) measures its
        upload/compute/download/writeback segments on a local Timers
        and absorbs them into the driver's reporting instance here."""
        path = "/".join([p for p, _ in self._stack] + [name])
        self.acc[path] = self.acc.get(path, 0.0) + float(seconds)
        self.count[path] = self.count.get(path, 0) + int(count)

    def report(self, min_s: float = 0.0) -> str:
        lines = []
        for k in sorted(self.acc):
            if self.acc[k] < min_s:
                continue
            depth = k.count("/")
            lines.append(f"{'  ' * depth}{k.split('/')[-1]:28s} "
                         f"{self.acc[k]:9.3f}s  x{self.count[k]}")
        return "\n".join(lines)
