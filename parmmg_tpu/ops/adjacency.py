"""Tet-tet adjacency and boundary detection, sort-based (jittable).

Replaces the reference's hash-table face matching (``MMG3D_hashTetra``, used
at e.g. /root/reference/src/libparmmg1.c:733, and the parallel edge hashes of
hash_pmmg.c:147-234) with the TPU idiom: materialize all 4*capT faces as
sorted vertex triples, sort them, and match equal neighbors in sorted order.
Sorting is XLA-friendly (static shapes, no data-dependent control flow); a
hash table with chaining is not.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.mesh import Mesh, tet_face_vertices
from ..core.constants import MG_BDY
from . import pallas_kernels as pk


def _face_keys(mesh: Mesh):
    """Sorted-triple face keys as 3 int32 columns, invalid tets last.

    Pure int32 (no int64 emulation on TPU): multi-column keys are matched
    with ``jnp.lexsort`` + column-wise equality instead of one packed key.
    Returns (cols [F,3], tetid [F], faceid [F]).
    """
    capT = mesh.capT
    fv = tet_face_vertices(mesh.tet).reshape(capT * 4, 3)       # [F,3]
    fv = jnp.sort(fv, axis=1)
    invalid = ~jnp.repeat(mesh.tmask, 4)
    big = jnp.iinfo(jnp.int32).max
    fv = jnp.where(invalid[:, None], big, fv)
    tetid = jnp.repeat(jnp.arange(capT, dtype=jnp.int32), 4)
    faceid = jnp.tile(jnp.arange(4, dtype=jnp.int32), capT)
    return fv, tetid, faceid


def face_sort(mesh: Mesh):
    """THE face-sort pass, shared by ``build_adjacency`` and the direct
    swap23 pairing (``ops.swap.swap23_wave(..., facesort=True)``).

    Returns sorted-order face records ``(t, f, partner, matched,
    valid_s)``: per sorted slot the tet id, local face id, the sorted-slot
    index of the twin slot (self if unmatched), whether a twin exists, and
    whether the slot belongs to a live tet.  Matched twins are adjacent in
    sorted order, so ``(t[i], f[i]) <-> (t[partner[i]], f[partner[i]])``
    IS the face-pair table — consumers that only need the pairing (swap23
    candidate selection) read it here without materializing the [capT,4]
    ``adja`` matrix.
    """
    from .edges import PACK_LIMIT
    capT = mesh.capT
    big = jnp.iinfo(jnp.int32).max
    cols, tetid, faceid = _face_keys(mesh)
    if mesh.capP <= PACK_LIMIT:
        # pack the two minor columns into one int32 (ids < capP <=
        # sqrt(2^31)): the 3-pass lexsort becomes 2 passes — face
        # matching is one of the measured per-wave hot spots
        invalid = cols[:, 0] == big
        w = jnp.where(invalid, big, cols[:, 1] * mesh.capP + cols[:, 2])
        # major column holds vertex ids < capP <= 46340 < 2^16, so the
        # radix engine runs 2 digit passes on it instead of 4
        order = pk.sort_perm((cols[:, 0], w),
                             ref=lambda ws: jnp.lexsort((ws[1], ws[0])),
                             nbits=(16, 32))
        return face_records_from_sorted(mesh, order, cols[order, 0],
                                        w[order])
    order = pk.sort_perm(
        (cols[:, 0], cols[:, 1], cols[:, 2]),
        ref=lambda ws: jnp.lexsort((ws[2], ws[1], ws[0])))
    k = cols[order]
    t = tetid[order]
    f = faceid[order]
    return _pair_records(capT, k, t, f, big)


def face_records_from_sorted(mesh: Mesh, order: jax.Array,
                             k0: jax.Array, kw: jax.Array):
    """``face_sort``'s record tuple from a precomputed PACKED face sort:
    ``order`` is the stable sort permutation over the 4*capT face slots,
    ``k0``/``kw`` the ascending (major vertex, packed minor pair) key
    columns — exactly what the packed lexsort produces.  Factored so the
    incremental path (ops/topo_incr) feeds its band-merged sort through
    the SAME twin-pairing epilogue.  ``t = order // 4`` / ``f = order %
    4`` reproduce the tetid/faceid gathers bit-for-bit (slot layout:
    tet-major).  Requires ``capP <= PACK_LIMIT``."""
    big = jnp.iinfo(jnp.int32).max
    k = jnp.stack([k0, kw], axis=1)
    order = order.astype(jnp.int32)
    t = order // 4
    f = order % 4
    return _pair_records(mesh.capT, k, t, f, big)


def _pair_records(capT: int, k, t, f, big):
    """Twin pairing over sorted face keys (shared epilogue): matched
    twins are adjacent in sorted order."""
    first = pk.segment_first(tuple(k[:, j] for j in range(k.shape[1])))
    eq_next = ~first[1:] & (k[:-1, 0] != big)
    same_next = jnp.concatenate([eq_next, jnp.array([False])])
    same_prev = jnp.concatenate([jnp.array([False]), eq_next])
    # partner index in sorted order (self if unmatched)
    idx = jnp.arange(capT * 4)
    partner = jnp.where(same_next, idx + 1, jnp.where(same_prev, idx - 1, idx))
    matched = same_next | same_prev
    valid_s = k[:, 0] != big
    return t, f, partner, matched, valid_s


def bdy_tags_from_sort(mesh: Mesh, t, f, matched, valid_s):
    """The MG_BDY face tagging of ``build_adjacency`` computed straight
    off the face-sort records: a live unmatched slot IS a boundary face
    (``adja < 0 & tmask`` of the adja path, by construction — adja is -1
    exactly on unmatched live slots and dead rows).  One permutation
    scatter replaces the adja materialization + compare."""
    unb = valid_s & ~matched
    hit = jnp.zeros((mesh.capT, 4), bool).at[t, f].set(
        unb, unique_indices=True)
    ftag = jnp.where(hit, mesh.ftag | MG_BDY, mesh.ftag)
    return dataclasses_replace(mesh, ftag=ftag)


def build_adjacency(mesh: Mesh, set_bdy_tags: bool = True) -> Mesh:
    """Compute ``adja`` and mark unmatched faces as boundary (MG_BDY).

    In a conforming mesh every interior face appears exactly twice. After
    sorting face keys, twins are neighbors in sorted order; the pairing is
    scattered back as ``adja[t,f] = 4*t' + f'``.

    ``set_bdy_tags=False`` computes adja only: on an active SUB-mesh
    (ops/active.py) faces whose twin lies outside the sub-mesh are
    unmatched without being boundary — tagging them MG_BDY would corrupt
    the surface, while adja=-1 correctly excludes them from swap23.
    """
    t, f, partner, matched, _ = face_sort(mesh)
    return adjacency_from_records(mesh, t, f, partner, matched,
                                  set_bdy_tags=set_bdy_tags)


def adjacency_from_records(mesh: Mesh, t, f, partner, matched,
                           set_bdy_tags: bool = True) -> Mesh:
    """``build_adjacency``'s scatter epilogue from face-sort records —
    shared with the incremental path (ops/topo_incr), which feeds it
    band-merged records."""
    capT = mesh.capT
    adj_val = jnp.where(matched, 4 * t[partner] + f[partner], -1)

    adja = jnp.full((capT, 4), -1, jnp.int32)
    # (t, f) is a permutation of all slots: unique_indices lets the TPU
    # scatter run fully parallel (duplicate-tolerant scatter measured ~2x
    # slower at these shapes, scripts/tpu_microbench.py)
    adja = adja.at[t, f].set(adj_val.astype(jnp.int32),
                             unique_indices=True)
    adja = jnp.where(mesh.tmask[:, None], adja, -1)

    if not set_bdy_tags:
        return dataclasses_replace(mesh, adja=adja)
    # boundary faces: valid tet, face has no twin
    is_bdy = (adja < 0) & mesh.tmask[:, None]
    ftag = jnp.where(is_bdy, mesh.ftag | MG_BDY, mesh.ftag)
    return dataclasses_replace(mesh, adja=adja, ftag=ftag)


def dataclasses_replace(mesh: Mesh, **kw) -> Mesh:
    import dataclasses
    return dataclasses.replace(mesh, **kw)


def check_adjacency(mesh: Mesh) -> dict:
    """Invariant oracle (debug): symmetric adja, shared vertices agree.

    The analogue of the reference's communicator/adjacency assertions
    (chkcomm_pmmg.c): run off the hot path, returns violation counts.
    """
    adja = mesh.adja
    nb = adja >> 2
    nf = adja & 3
    valid = adja >= 0
    # symmetry: adja[nb, nf] must point back
    back = jnp.where(valid, adja[jnp.clip(nb, 0, mesh.capT - 1), nf], -1)
    tid = jnp.arange(mesh.capT, dtype=jnp.int32)[:, None]
    fid = jnp.arange(4, dtype=jnp.int32)[None, :]
    sym_bad = jnp.sum(jnp.where(valid, back != 4 * tid + fid, False))
    # shared face must consist of the same 3 vertices
    fv = jnp.sort(tet_face_vertices(mesh.tet), axis=2)           # [T,4,3]
    nbv = fv[jnp.clip(nb, 0, mesh.capT - 1), nf]
    face_bad = jnp.sum(
        jnp.where(valid[..., None], fv != nbv, False))
    return {"asymmetric": int(sym_bad), "face_mismatch": int(face_bad)}


def boundary_edge_tags(mesh: Mesh) -> Mesh:
    """Propagate MG_BDY from boundary faces to their edges and vertices."""
    from ..core.constants import FACE_EDGES
    fe = jnp.asarray(FACE_EDGES)                     # [4,3]
    is_bdy_face = (mesh.ftag & MG_BDY) != 0          # [T,4]
    # edges of boundary faces get MG_BDY
    etag = mesh.etag
    edge_hit = jnp.zeros((mesh.capT, 6), bool)
    for f in range(4):
        for j in range(3):
            e = int(FACE_EDGES[f, j])
            edge_hit = edge_hit.at[:, e].set(edge_hit[:, e] | is_bdy_face[:, f])
    etag = jnp.where(edge_hit, etag | MG_BDY, etag)
    # vertices of boundary faces get MG_BDY — ONE concatenated scatter
    # over all 4 faces (per-op overhead dominates scatter cost on this
    # device; 4 narrow scatters cost ~4x one long one)
    from ..core.constants import IDIR
    vtag = mesh.vtag
    capP = mesh.capP
    vids_all = jnp.concatenate(
        [mesh.tet[:, jnp.asarray(IDIR[f])].reshape(-1) for f in range(4)])
    m_all = jnp.concatenate(
        [jnp.repeat(is_bdy_face[:, f] & mesh.tmask, 3) for f in range(4)])
    hit = jnp.zeros(capP + 1, bool).at[
        jnp.where(m_all, vids_all, capP)].max(m_all, mode="drop")
    vtag = jnp.where(hit[:capP], vtag | MG_BDY, vtag)
    return dataclasses_replace(mesh, etag=etag, vtag=vtag)
