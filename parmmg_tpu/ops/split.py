"""Batched edge split — data-parallel replacement for Mmg's split cascade.

Reference behavior being reproduced: inside ``MMG5_mmg3d1_delone`` (called by
the group loop at /root/reference/src/libparmmg1.c:737-739) long edges
(metric length > sqrt(2)) are split by inserting a point, and every tet of
the edge's shell is cut in two; entities tagged ``MG_REQ`` (in particular the
frozen parallel interface, tag_pmmg.c:39-124) must not be touched.

TPU design: instead of a sequential cascade, each *wave* selects a maximal
independent set of splittable edges (no two in the same tet) and applies all
of them at once:

1.  every tet nominates its longest splittable edge;
2.  an edge wins iff **all** tets of its shell nominated it (so the whole
    shell splits coherently and each tet is modified by at most one split);
3.  winning edges allocate midpoints (prefix-sum slot assignment) and each
    shell tet is cut in two, tags inherited per the local topology tables.

Determinism: priorities are unique int32 ranks, so the independent set — and
hence the output mesh — is a pure function of the input.
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.mesh import Mesh
from ..core.constants import (
    IARE, EDGE_FACES, FACE_EDGES, IDIR, LLONG, MG_BDY, MG_GEO, MG_REQ,
    MG_PARBDY, MG_REF)
from .edges import (EdgeTable, unique_edges, edge_lengths, claim_channels,
                    NEG_INF, PRI_MIN)

_IARE_J = jnp.asarray(IARE)


class SplitResult(NamedTuple):
    mesh: Mesh
    met: jax.Array
    nsplit: jax.Array      # scalar int32: number of edges split
    overflow: jax.Array    # scalar bool: capacity exhausted, wave truncated
    modified: jax.Array = None  # [capT] bool: tets rewritten/created this
    #                 wave (consumed by collapse_wave's staleness veto
    #                 when both ops share one pre-split edge table)
    deferred: jax.Array = None  # scalar bool: viable winners were dropped
    #                 by the top-K / shell budgets (NOT by gates or
    #                 capacity) — the active-scoped narrow path must see
    #                 a False here before trusting its dirty-region
    #                 worklist (ops/active.py)


def _interp_met_mid(met, va, vb):
    """Metric at an edge midpoint (linear interpolation of the metric
    coefficients; MMG5_intmet semantics simplified to P1)."""
    return 0.5 * (met[va] + met[vb])


def split_wave(mesh: Mesh, met: jax.Array, lmax: float = LLONG,
               frozen_vtag: int = MG_REQ | MG_PARBDY,
               hausd: float | None = None,
               budget_div: int = 8,
               fem_only: bool = False,
               et: EdgeTable | None = None,
               lens: jax.Array | None = None,
               vtan: jax.Array | None = None,
               vact: jax.Array | None = None,
               prescreen: bool = True) -> SplitResult:
    """One independent-set split wave. Jittable; static shapes throughout.

    ``hausd`` enables the PLACEMENT half of surface-approximation
    control (Mmg -hausd): refinement pressure itself comes from the
    metric (driver.build_metric folds sqrt(8*hausd/kappa) into boundary
    sizes via ops.metric.hausd_metric_bound — the defsiz route), while
    here regular boundary midpoints are LIFTED onto the cubic Bezier
    curve
    through the endpoints+normals (MMG5_BezierRegular flavor) — the
    deviation estimate is |t_a - t_b|/8 with t_* the edge vector
    projected on each endpoint's tangent plane; the midpoint correction
    is (t_a - t_b)/8, exact to O(h^4) on a sphere.  Ridge/corner/required
    endpoints are excluded (their normals are multivalued — the flat
    cube workloads are bit-for-bit unchanged).

    ``fem_only``: instead of long edges, target INTERIOR edges whose two
    endpoints both lie on the boundary — the FEM-incompatible
    configuration (an element can end up with all four vertices, or two
    faces, on the boundary).  Splitting such an edge inserts an interior
    point, which is exactly Mmg's fem-mode topology fix; the reference
    forwards ``info.fem`` (default on, API_functions_pmmg.c:413,652) to
    Mmg per group.

    ``budget_div`` widens/narrows the per-wave winner budget (the shared
    ops/edges.wave_budget formula; winners past it are deferred to the
    next wave, NOT flagged as overflow); the convergence-verification
    wide cycle passes 2.

    ``et``/``lens``: a caller-precomputed edge table + metric lengths of
    THIS mesh (adapt_cycle_impl builds one table serving both split and
    collapse — the tables are a measured hot spot of every wave).

    ``vact``: optional [capP] bool active-vertex mask (the narrow path,
    ops/active.py): only edges with BOTH endpoints active are candidates
    — on a sub-mesh holding exactly the tets that touch active vertices,
    such edges have their complete shell present, so shell counts and
    the whole-shell nomination rule stay exact.
    """
    capT, capP = mesh.capT, mesh.capP
    if et is None:
        et = unique_edges(mesh)
    if lens is None:
        lens = edge_lengths(mesh, et, met)

    # --- candidate edges -------------------------------------------------
    va = jnp.clip(et.ev[:, 0], 0, capP - 1)
    vb = jnp.clip(et.ev[:, 1], 0, capP - 1)
    frozen_edge = (et.etag & (MG_REQ | MG_PARBDY)) != 0
    if fem_only:
        both_bdy = ((mesh.vtag[va] & MG_BDY) != 0) & \
            ((mesh.vtag[vb] & MG_BDY) != 0)
        cand = et.emask & ((et.etag & MG_BDY) == 0) & both_bdy & \
            ~frozen_edge
    else:
        cand = et.emask & (lens > lmax) & ~frozen_edge
    if vact is not None:
        cand = cand & vact[va] & vact[vb]
    # NOTE splits are deliberately NOT window-restricted (unlike
    # collapse/swap/smooth, ops/active.py): their steady-state count is
    # ~zero (no footprint problem) while windowing them measurably slows
    # the refinement phase
    lift_corr = None
    if hausd is not None:
        from .analysis import boundary_vertex_normals, \
            ridge_vertex_tangents
        from ..core.constants import MG_CRN, MG_NOM
        vn = boundary_vertex_normals(mesh)
        sing = MG_GEO | MG_CRN | MG_REQ | MG_PARBDY | MG_NOM | MG_REF
        regular = ((et.etag & MG_BDY) != 0) & \
            ((et.etag & (MG_GEO | MG_REQ | MG_PARBDY | MG_REF)) == 0) & \
            ((mesh.vtag[va] & sing) == 0) & ((mesh.vtag[vb] & sing) == 0)
        d = mesh.vert[vb] - mesh.vert[va]
        na, nb = vn[va], vn[vb]
        t_a = d - na * jnp.sum(na * d, -1, keepdims=True)
        t_b = d - nb * jnp.sum(nb * d, -1, keepdims=True)
        corr = 0.125 * (t_a - t_b)                     # Bezier mid offset
        # refinement pressure comes from the METRIC (hausd_metric_bound
        # folds sqrt(8*hausd/kappa) into boundary sizes, the Mmg defsiz
        # route); here hausd only drives point PLACEMENT
        lift_corr = jnp.where(regular[:, None], corr, 0.0)
        # curved FEATURE LINES (ridge/ref edges between two plain
        # ridge/ref points): lift the midpoint along the tangent circle
        # of the feature curve — the Hermite analogue of the surface
        # lift with the edge vector projected on each endpoint's LINE
        # tangent (the reference keeps per-point tangents in the xPoint
        # and maintains them across ranks, analys_pmmg.c:199-1171).
        # Without this, curved ridges (torus equator class) stay
        # piecewise-linear no matter how fine the metric.
        tan = vtan if vtan is not None \
            else ridge_vertex_tangents(mesh, et=et)
        hard = MG_CRN | MG_REQ | MG_PARBDY | MG_NOM
        on_line = ((et.etag & (MG_GEO | MG_REF)) != 0) & \
            ((et.etag & (MG_REQ | MG_PARBDY)) == 0) & \
            ((mesh.vtag[va] & hard) == 0) & \
            ((mesh.vtag[vb] & hard) == 0)
        ta_l = tan[va] * jnp.sum(tan[va] * d, -1, keepdims=True)
        tb_l = tan[vb] * jnp.sum(tan[vb] * d, -1, keepdims=True)
        corr_l = 0.125 * (ta_l - tb_l)
        lift_corr = jnp.where(on_line[:, None], corr_l, lift_corr)
    # Everything below (nomination, degeneracy veto, winner
    # selection, apply) is lax.cond-skipped when NO candidate edge
    # exists — at convergence the wave then costs only the table +
    # candidacy masks.
    def _idle(_):
        return SplitResult(mesh, met, jnp.zeros((), jnp.int32),
                           jnp.zeros((), bool),
                           jnp.zeros(capT, bool), jnp.zeros((), bool))

    def _act(_):
        from .quality import quality_from_points
        from ..core.constants import QUAL_FLOOR
        from .edges import topk_prep, wave_budget
        capE = et.ev.shape[0]
        ar0 = jnp.arange(capT)
        s, t = claim_channels(lens, cand)                 # sort-free priority

        # --- nomination: each tet picks its (s,t)-max candidate edge ---------
        # both channels ride ONE [capT,6,2] gather (t bitcast to f32 lanes)
        st = jnp.stack([s, jax.lax.bitcast_convert_type(t, jnp.float32)],
                       axis=1)                            # [capE,2]
        st_te = st[et.edge_id]                            # [capT,6,2]
        tes = jnp.where(mesh.tmask[:, None], st_te[..., 0], NEG_INF)
        t_te = jax.lax.bitcast_convert_type(st_te[..., 1], jnp.int32)
        best_s = jnp.max(tes, axis=1)                     # [capT]
        at_best = (tes == best_s[:, None]) & jnp.isfinite(best_s)[:, None]
        tet_t = jnp.where(at_best, t_te, PRI_MIN)
        best_t = jnp.max(tet_t, axis=1)
        # exactly one slot per tet (t is unique): the whole-shell win test
        # below stays exact under simultaneous application
        nominate = at_best & (tet_t == best_t[:, None])
        # nomination-time degeneracy prescreen: split children inherit
        # >= half the parent quality (the midpoint halves the volume
        # exactly and no child edge exceeds a parent edge), so only
        # near-degenerate parents can produce sub-floor children.  Veto
        # their nominations HERE so such shells never pin top-K budget
        # slots wave after wave (starvation); the exact [KH] veto below
        # stays as the precise guard (incl. hausd-lifted midpoints,
        # where the half-quality bound is only approximate — the bound
        # is NOT exact for the quality measure, so near-floor parents
        # can be over-vetoed).  The 2x margin (was 4x, ADVICE r3: the
        # wide margin permanently blocked near-floor shells whose
        # children pass the exact veto, stalling refinement in
        # low-quality regions) keeps the starvation guard while halving
        # the over-veto band; the wide convergence-verification cycle
        # AND the drivers' polish cycles pass prescreen=False so any
        # still-blocked shell gets an exact re-evaluation.
        if prescreen:
            q_par = quality_from_points(mesh.vert[mesh.tet])
            nominate = nominate & (q_par > 2.0 * QUAL_FLOOR)[:, None]
        has_nom = jnp.any(nominate, axis=1)
        loc_n = jnp.argmax(nominate, axis=1)              # [capT]
        e_n = jnp.clip(et.edge_id[ar0, loc_n], 0, capE - 1)

        # --- an edge wins iff nominated by its whole shell -------------------
        # each tet nominates at most ONE edge, so the count scatters at
        # [capT] width (not [6*capT] — scatter cost is linear in index
        # count, scripts/tpu_microbench.py)
        nom_count = jnp.zeros(capE, jnp.int32).at[
            jnp.where(has_nom, e_n, capE)].add(1, mode="drop")
        win0 = cand & (nom_count == et.nshell) & (et.nshell > 0)

        # --- budget: top-K winners by priority (longest edges first) ---------
        # replaces a full-width argsort + 6 full-width cumsums with ONE
        # top_k and [KW]-width prefix sums (scripts/split_stage_time.py:
        # the budget/offset stage was ~30 ms of the wave)
        KW = min(wave_budget(capT, budget_div), capE)
        KH = min(2 * wave_budget(capT, budget_div), capT)
        # fused scoring prep (ops/edges.topk_prep wants smallest-first,
        # so pass -lens: -(-lens) is a sign-bit round-trip, bit-exact)
        neg, nwin = topk_prep(win0, -lens)
        vals, wc = jax.lax.top_k(neg, KW)
        wv = vals > NEG_INF                               # real winners
        wcc = jnp.clip(wc, 0, capE - 1)
        # the KH shell-tet budget must bound the winner set BEFORE the
        # row compaction below — rows past the static compaction size
        # would be silently dropped, splitting only part of a shell
        sh0 = jnp.where(wv, et.nshell[wcc], 0)
        toff0 = jnp.cumsum(sh0) - sh0
        shell_fit = (toff0 + sh0) <= KH
        # budget deferral (top-K or shell-budget cut of VIABLE winners —
        # gate/capacity drops are flagged elsewhere): the narrow path's
        # worklist invariant needs to see this
        defer = (nwin > KW) | jnp.any(wv & ~shell_fit)
        wv = wv & shell_fit

        # --- degeneracy veto (MMG5_split1b cavity-quality check) -------------
        # evaluated on the [KH]-compacted shells of the budget winners
        # instead of all capT tets: a shell tet whose child would be
        # degenerate vetoes the whole edge (the wave simply skips it; the
        # old nomination-time veto had the same final effect)
        keep0 = jnp.zeros(capE, bool).at[jnp.where(wv, wc, capE)].set(
            True, mode="drop", unique_indices=True)
        has0 = has_nom & keep0[e_n]
        hidx = jnp.nonzero(has0, size=KH, fill_value=capT)[0]
        hv0 = hidx < capT
        hc = jnp.clip(hidx, 0, capT - 1)
        arK = jnp.arange(KH)
        loc0 = loc_n[hc]
        e0 = jnp.clip(e_n[hc], 0, capE - 1)
        il = _IARE_J[loc0, 0]                             # [KH]
        jl = _IARE_J[loc0, 1]
        rows0 = mesh.tet[hc]                              # [KH,4]
        mid_row = 0.5 * (mesh.vert[va[e0]] + mesh.vert[vb[e0]])
        if lift_corr is not None:
            mid_row = mid_row + lift_corr[e0]
        pts0 = mesh.vert[rows0]                           # [KH,4,3]
        q1 = quality_from_points(pts0.at[arK, jl].set(mid_row))
        q2 = quality_from_points(pts0.at[arK, il].set(mid_row))
        rowbad = hv0 & ~((q1 > QUAL_FLOOR) & (q2 > QUAL_FLOOR))
        veto_e = jnp.zeros(capE + 1, bool).at[
            jnp.where(rowbad, e0, capE)].max(rowbad, mode="drop")[:capE]

        # --- final winner set + offsets, all at [KW] width -------------------
        # allocation pools: reuse rows freed by earlier collapses (not a
        # watermark cursor — see edges.free_rows)
        from .edges import free_rows
        okv = wv & ~veto_e[wcc]
        win_i = okv.astype(jnp.int32)
        new_off = jnp.cumsum(win_i) - win_i
        frow_p, nfree_p = free_rows(mesh.vmask, KW)
        fits_p = new_off < jnp.minimum(nfree_p, KW)
        sh = jnp.where(okv & fits_p, et.nshell[wcc], 0)
        toff = jnp.cumsum(sh) - sh
        frow_t, nfree_t = free_rows(mesh.tmask, KH)
        fits_cap = fits_p & ((toff + sh) <= jnp.minimum(nfree_t, KH))
        ok = okv & fits_cap
        # overflow = CAPACITY-dropped winners only (triggers a host
        # regrow); budget- or veto-dropped winners just defer
        overflow = jnp.any(okv & ~fits_cap)
        nwin = jnp.sum(ok.astype(jnp.int32))

        # midpoint coordinates / refs / tags on the [KW] winner rows
        va_w, vb_w = va[wcc], vb[wcc]
        pa, pb = mesh.vert[va_w], mesh.vert[vb_w]
        mid = 0.5 * (pa + pb)
        if lift_corr is not None:
            mid = mid + lift_corr[wcc]            # onto the Bezier surface
        mid_id_w = frow_p[jnp.clip(new_off, 0, KW - 1)]
        tgt_w = jnp.where(ok, mid_id_w, capP)
        vert = mesh.vert.at[tgt_w].set(mid, mode="drop", unique_indices=True)
        vmask = mesh.vmask.at[tgt_w].set(True, mode="drop",
                                         unique_indices=True)
        # the new point inherits the edge's tags (a point on a ridge edge is a
        # ridge point, on a boundary edge a boundary point, ...)
        vtag = mesh.vtag.at[tgt_w].set(et.etag[wcc], mode="drop",
                                       unique_indices=True)
        vref = mesh.vref.at[tgt_w].set(
            jnp.minimum(mesh.vref[va_w], mesh.vref[vb_w]), mode="drop",
            unique_indices=True)
        met_new = met.at[tgt_w].set(_interp_met_mid(met, va_w, vb_w),
                                    mode="drop", unique_indices=True)

        # --- allocation tables: midpoint vid + free-pool base per edge -------
        # ONE packed [KW] scatter; -1 marks non-winning edges.  Column 1
        # is the edge's base OFFSET into the frow_t free pool (its shell
        # tets take consecutive pool entries, not consecutive slots)
        alloc = jnp.full((capE, 2), -1, jnp.int32).at[
            jnp.where(ok, wc, capE)].set(
            jnp.stack([mid_id_w, toff.astype(jnp.int32)], axis=1),
            mode="drop", unique_indices=True)

        # --- split shell tets on the same [KH] compaction --------------------
        # shell tets of a winning edge are exactly the tets that nominated
        # it (whole-shell rule), so the pre-veto compaction rows are reused
        # with an updated validity mask — no second nonzero pass
        al_row = alloc[e0]                                # [KH,2]
        hv = hv0 & (al_row[:, 0] >= 0)
        mh = jnp.clip(al_row[:, 0], 0, capP - 1)
        # rank of this tet within its shell -> new tet slot from the
        # free pool (the shell rank precomputed by unique_edges:
        # sorted-segment rank)
        new_tid_r = frow_t[jnp.clip(al_row[:, 1] + et.shell_rank[hc, loc0],
                                    0, KH - 1)]
        tgt1 = jnp.where(hv, hc, capT)
        tgt2 = jnp.where(hv, jnp.clip(new_tid_r, 0, capT - 1), capT)
        # tet1 (in place): vertex j -> m ; tet2 (new slot): vertex i -> m
        tet1_rows = rows0.at[arK, jl].set(mh, unique_indices=True)
        tet2_rows = rows0.at[arK, il].set(mh, unique_indices=True)
        tet_out = mesh.tet.at[tgt1].set(tet1_rows, mode="drop",
                                        unique_indices=True)
        tet_out = tet_out.at[tgt2].set(tet2_rows, mode="drop",
                                       unique_indices=True)
        tmask = mesh.tmask.at[tgt2].set(True, mode="drop",
                                        unique_indices=True)
        tref = mesh.tref.at[tgt2].set(mesh.tref[hc], mode="drop",
                                      unique_indices=True)

        # --- tag inheritance (on the compacted rows) --------------------------
        # tet1 keeps its ftag/etag except: the cut face (opposite i) becomes
        # interior (tag 0); the half edges adjacent to the cut inherit; new
        # edges (m,c) inside an old face f inherit that face's boundary bit.
        ftag1r, fref1r, etag1r, ftag2r, fref2r, etag2r = _split_tags_rows(
            mesh, hc, il, jl)
        ftag = mesh.ftag.at[tgt1].set(ftag1r, mode="drop",
                                      unique_indices=True)
        ftag = ftag.at[tgt2].set(ftag2r, mode="drop", unique_indices=True)
        frf = mesh.fref.at[tgt1].set(fref1r, mode="drop",
                                     unique_indices=True)
        frf = frf.at[tgt2].set(fref2r, mode="drop", unique_indices=True)
        etag_out = mesh.etag.at[tgt1].set(etag1r, mode="drop",
                                          unique_indices=True)
        etag_out = etag_out.at[tgt2].set(etag2r, mode="drop",
                                         unique_indices=True)

        # watermarks stay monotone upper bounds over used rows (pool
        # rows may lie below the old watermark — reuse tightens nothing)
        npoin = jnp.maximum(mesh.npoin,
                            jnp.max(jnp.where(ok, mid_id_w + 1, 0)))
        nelem = jnp.maximum(
            mesh.nelem, jnp.max(jnp.where(hv, new_tid_r + 1, 0)))
        out = dataclasses.replace(
            mesh, vert=vert, vmask=vmask, vtag=vtag, vref=vref,
            tet=tet_out, tmask=tmask, tref=tref,
            ftag=ftag, fref=frf, etag=etag_out,
            npoin=npoin.astype(jnp.int32), nelem=nelem.astype(jnp.int32))
        # tets rewritten in place (tgt1) or created (tgt2) this wave — the
        # staleness footprint for a collapse sharing our edge table
        modified = jnp.zeros(capT, bool).at[tgt1].set(
            True, mode="drop", unique_indices=True).at[tgt2].set(
            True, mode="drop", unique_indices=True)
        return SplitResult(out, met_new, nwin, overflow, modified, defer)

    return jax.lax.cond(jnp.any(cand), _act, _idle, None)


def _split_tags_rows(mesh: Mesh, hc, il, jl):
    """Tag inheritance for the two halves of each split tet, computed on
    the COMPACTED affected rows [KH] (hc = affected tet ids).

    For split edge at local (i,j) with midpoint m:
      tet1 = tet with v_j := m, tet2 = tet with v_i := m.
      - face opposite the replaced vertex is the *outer* original face
        (unchanged): inherits.
      - faces k not in {i,j} are cut in half: inherit original face k tags.
      - the cut face (opposite the kept edge endpoint) is interior: tag 0.
      - edges: the split edge's halves inherit its tag; new edges m-c lie
        inside original faces: they get MG_BDY/MG_REF iff that face has it;
        other edges inherit.
    """
    KH = hc.shape[0]
    arK = jnp.arange(KH)
    ftag0 = mesh.ftag[hc]                                  # [KH,4]
    fref0 = mesh.fref[hc]
    etag0 = mesh.etag[hc]                                  # [KH,6]

    def one_half(repl):  # repl [KH] = local vertex replaced by m
        kept = jnp.where(repl == il, jl, il)
        # cut face = face opposite `kept` -> interior
        ftag = ftag0.at[arK, kept].set(0, unique_indices=True)
        fref = fref0.at[arK, kept].set(0, unique_indices=True)
        # edges: for each local edge, decide inheritance.  New edges
        # incident to `repl` (other endpoint c not in {i,j}) lie inside
        # the original face containing {i, j, c} = the face opposite the
        # remaining vertex; they inherit that face's MG_BDY/MG_REF.
        out = etag0
        for el in range(6):
            a, b = int(IARE[el][0]), int(IARE[el][1])
            av = jnp.int32(a)
            bv = jnp.int32(b)
            touches_repl = (av == repl) | (bv == repl)
            other = jnp.where(av == repl, bv, av)
            is_split_edge = ((av == il) & (bv == jl)) | \
                            ((av == jl) & (bv == il))
            # remaining vertex = the one not in {i, j, other}; 0+1+2+3=6
            rem = (jnp.int32(6) - (il + jl + other)).astype(jnp.int32)
            in_old_face = touches_repl & ~is_split_edge & \
                (other != il) & (other != jl)
            face_t = ftag0[arK, jnp.clip(rem, 0, 3)]
            new_t = (face_t & (MG_BDY | MG_REF)).astype(jnp.uint32)
            val = jnp.where(in_old_face, new_t, out[:, el])
            out = out.at[:, el].set(val)
        return ftag, fref, out

    ftag1, fref1, etag1 = one_half(jl)
    ftag2, fref2, etag2 = one_half(il)
    return ftag1, fref1, etag1, ftag2, fref2, etag2
