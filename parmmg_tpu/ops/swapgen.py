"""Generalized edge swaps: shell degree 4-6 ring re-triangulation.

Reference behavior: Mmg's swap pass (``MMG5_swpmsh``/``MMG3D_swpgen``,
invoked from the remesher the reference calls per group at
/root/reference/src/libparmmg1.c:737-739) removes an interior edge whose
shell has n tets by re-triangulating the ring polygon p0..p_{n-1} into
n-2 triangles; each triangle T yields the two tets (T, a), (T, b).  Mmg
enumerates triangulation configurations from precomputed tables and
applies the one whose worst new quality beats the old shell by the swap
gain.  n=3 is the classic 3-2 swap (ops/swap.py); THIS kernel handles
n = 4..6 — the degree classes whose absence capped the final min
quality (the worst surviving tets are exactly the ones only a
higher-degree re-triangulation can fix).

TPU design: one batched wave.  Candidates (interior untagged edges with
a 4-6 tet shell) are top-K compacted by worst shell quality; the ring
is chained from the shell tets with a fixed-trip unrolled walk; all n
FAN triangulations are evaluated in one stacked quality call (for n=4,5
fans enumerate ALL triangulations — Catalan(2)=2, Catalan(3)=5; for n=6
a 6-of-14 subset); the best valid fan is applied under the same
exclusive shell-claim machinery as the other swap kernels.
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.mesh import Mesh
from ..core.constants import EPSD, QUAL_FLOOR, EDGE_FACES
from .edges import unique_edges, claim_shells, wave_budget
from .quality import quality_from_points
from .swap import SWAP_GAIN, _EDGE_OF

RING_MAX = 6            # max shell degree handled
NTRI = RING_MAX - 2     # fan triangles (padded)
NT_NEW = 2 * NTRI       # new tets per fan (padded)


class SwapGenResult(NamedTuple):
    mesh: Mesh
    nswap: jax.Array


def swapgen_wave(mesh: Mesh, met: jax.Array,
                 budget_div: int = 8,
                 lmax: float | None = None) -> SwapGenResult:
    from ..core.constants import LLONG
    if lmax is None:
        lmax = LLONG
    capT, capP = mesh.capT, mesh.capP
    et = unique_edges(mesh, shell_slots=RING_MAX)
    m6 = None if met.ndim == 1 else met
    Efull = et.ev.shape[0]
    eof = jnp.asarray(_EDGE_OF)
    efaces = jnp.asarray(EDGE_FACES)

    # ---- full-width candidacy + worst-shell priority --------------------
    q_tet = quality_from_points(
        mesh.vert[mesh.tet], None if m6 is None else m6[mesh.tet])
    sh_f = et.shell3                                     # [E, 6]
    shc_f = jnp.clip(sh_f, 0, capT - 1)
    slot_valid_f = sh_f >= 0
    qs = jnp.where(slot_valid_f, q_tet[shc_f], jnp.inf)
    q_shell_f = jnp.min(qs, axis=1)
    tref0 = mesh.tref[shc_f[:, 0]]
    same_ref = jnp.all(
        ~slot_valid_f | (mesh.tref[shc_f] == tref0[:, None]), axis=1)
    pre = et.emask & (et.etag == 0) & (et.nshell >= 4) & \
        (et.nshell <= RING_MAX) & same_ref
    # NOTE the remaining static gates (vanishing-face tags, ring
    # closure) are applied post-compaction: they need per-slot corner
    # positions, too heavy at [E,6] width.  Statically-doomed candidates
    # can therefore pin budget slots; this kernel runs in the
    # wide-budget polish phase where K covers the population.
    K = min(Efull, wave_budget(capT, budget_div))
    _, selx = jax.lax.top_k(jnp.where(pre, -q_shell_f, -jnp.inf), K)

    ar = jnp.arange(K)
    cand = pre[selx]
    n = et.nshell[selx]                                  # [K]
    sh = sh_f[selx]                                      # [K, 6] slots
    shc = jnp.clip(sh, 0, capT - 1)
    slot_valid = (sh >= 0) & (jnp.arange(RING_MAX)[None, :] < n[:, None])
    a = jnp.clip(et.ev[selx, 0], 0, capP - 1)
    b = jnp.clip(et.ev[selx, 1], 0, capP - 1)
    q_old = q_shell_f[selx]

    tvs = mesh.tet[shc]                                  # [K,6,4]
    is_a = tvs == a[:, None, None]
    is_b = tvs == b[:, None, None]
    is_ab = is_a | is_b
    # every (valid) shell tet must contain both endpoints
    cand = cand & jnp.all(
        ~slot_valid | (jnp.sum(is_ab.astype(jnp.int32), 2) == 2), axis=1)
    pos_a = jnp.argmax(is_a, axis=2).astype(jnp.int32)   # [K,6]
    pos_b = jnp.argmax(is_b, axis=2).astype(jnp.int32)
    # the two ring corners of each shell tet (stable argsort: non-ab first)
    ordr = jnp.argsort(is_ab.astype(jnp.int32), axis=2, stable=True)
    x = jnp.take_along_axis(tvs, ordr[:, :, 0:1], 2)[:, :, 0]   # [K,6]
    y = jnp.take_along_axis(tvs, ordr[:, :, 1:2], 2)[:, :, 0]

    # ---- vanishing-face gate: the n faces containing (a,b) die ----------
    lae = eof[pos_a, pos_b]                              # [K,6]
    ftags_sh = mesh.ftag[shc]                            # [K,6,4]
    fc = jnp.take_along_axis(ftags_sh, efaces[lae][..., 0:1], 2)[..., 0]
    fc2 = jnp.take_along_axis(ftags_sh, efaces[lae][..., 1:2], 2)[..., 0]
    cand = cand & jnp.all(~slot_valid | ((fc == 0) & (fc2 == 0)), axis=1)

    # ---- ring chain ------------------------------------------------------
    # walk the shell: pair slot 0 covers (ring0, ring1); each step finds
    # the unused shell tet containing the chain head; the final unused
    # tet must close the cycle.  A ring vertex belongs to exactly 2
    # shell tets in a valid ring, so the chain is deterministic.
    ring = jnp.zeros((K, RING_MAX), jnp.int32)
    tet_of_pair = jnp.zeros((K, RING_MAX), jnp.int32)    # shell SLOT idx
    ring = ring.at[:, 0].set(x[:, 0])
    ring = ring.at[:, 1].set(y[:, 0])
    used = jnp.zeros((K, RING_MAX), bool).at[:, 0].set(True)
    used = used | ~slot_valid                            # pad slots "used"
    cur = y[:, 0]
    for step in range(2, RING_MAX):
        active = step < n
        has = (~used) & ((x == cur[:, None]) | (y == cur[:, None]))
        j = jnp.argmax(has, axis=1)
        found = jnp.any(has, axis=1)
        xj = x[ar, j]
        yj = y[ar, j]
        other = jnp.where(xj == cur, yj, xj)
        ring = ring.at[:, step].set(jnp.where(active, other, ring[:, 0]))
        tet_of_pair = tet_of_pair.at[:, step - 1].set(
            jnp.where(active, j, tet_of_pair[:, step - 1]))
        used = used.at[ar, j].set(used[ar, j] | (active & found))
        cand = cand & (~active | found)
        cur = jnp.where(active, other, cur)
    # closing pair (ring[n-1], ring[0]) must be the one unused slot
    r0 = ring[:, 0]
    has_close = (~used) & \
        (((x == cur[:, None]) & (y == r0[:, None])) |
         ((y == cur[:, None]) & (x == r0[:, None])))
    jc = jnp.argmax(has_close, axis=1)
    cand = cand & jnp.any(has_close, axis=1)
    nm1 = jnp.clip(n - 1, 0, RING_MAX - 1)
    tet_of_pair = tet_of_pair.at[ar, nm1].set(jc)

    # ---- per-ring-position tag sources ----------------------------------
    # pair r covers ring edge (ring[r], ring[(r+1)%n]) inside old shell
    # tet t = sh[tet_of_pair[r]].
    rp1 = jnp.where(jnp.arange(RING_MAX)[None, :] + 1 < n[:, None],
                    jnp.arange(RING_MAX)[None, :] + 1, 0)
    ring_next = jnp.take_along_axis(ring, rp1, 1)        # [K,6]
    tp = jnp.take_along_axis(shc, tet_of_pair, 1)        # [K,6] tet ids
    tvp = mesh.tet[tp]                                   # [K,6,4]
    pa_p = jnp.argmax(tvp == a[:, None, None], 2).astype(jnp.int32)
    pb_p = jnp.argmax(tvp == b[:, None, None], 2).astype(jnp.int32)
    pr_p = jnp.argmax(tvp == ring[:, :, None], 2).astype(jnp.int32)
    pn_p = jnp.argmax(tvp == ring_next[:, :, None], 2).astype(jnp.int32)
    etag_p = mesh.etag[tp]                               # [K,6,6]
    ftag_p = mesh.ftag[tp]
    fref_p = mesh.fref[tp]

    def _take(rows, idx):
        return jnp.take_along_axis(rows, idx[..., None], 2)[..., 0]

    ring_etag = _take(etag_p, eof[pr_p, pn_p])           # ring edge (r,r+1)
    spoke_a = _take(etag_p, eof[pr_p, pa_p])             # edge (ring_r, a)
    spoke_b = _take(etag_p, eof[pr_p, pb_p])
    face_a = _take(ftag_p, pb_p)         # face (ring_r, ring_{r+1}, a)
    face_b = _take(ftag_p, pa_p)
    fref_a = _take(fref_p, pb_p)
    fref_b = _take(fref_p, pa_p)

    # ---- fan enumeration -------------------------------------------------
    # fan center c: triangles (c, c+k+1, c+k+2) mod n, k = 0..n-3.
    # tets: (pi, pj, pk, a) and (pj, pi, pk, b).
    pav = mesh.vert[a]
    pbv = mesh.vert[b]
    ringp = mesh.vert[jnp.clip(ring, 0, capP - 1)]       # [K,6,3]

    def ring_at(idx):
        """Gather ring vertex ids/[K] positions at (idx % n)."""
        m = jnp.where(idx < n, idx, idx - n)
        m = jnp.where(m < n, m, 0)
        return m

    fan_q = []
    fan_ok = []
    fan_tets = []        # per fan: [K, NT_NEW, 4] vertex ids
    fan_flip = []
    from .quality import edge_length_iso, edge_length_ani

    def _elen(gu, gv):
        pu, pv = mesh.vert[gu], mesh.vert[gv]
        if m6 is None:
            return edge_length_iso(pu, pv, met[gu], met[gv])
        return edge_length_ani(pu, pv, m6[gu], m6[gv])

    for c in range(RING_MAX):
        active_fan = (c < n) & cand
        vols_a = []
        vols_b = []
        tris = []
        diag_long = jnp.zeros((K,), bool)
        for k in range(NTRI):
            i_i = ring_at(jnp.full((K,), c, jnp.int32))
            i_j = ring_at(c + k + 1 + jnp.zeros((K,), jnp.int32))
            i_k = ring_at(c + k + 2 + jnp.zeros((K,), jnp.int32))
            pi = ringp[ar, i_i]
            pj = ringp[ar, i_j]
            pk = ringp[ar, i_k]
            nrm = jnp.cross(pj - pi, pk - pi)
            vols_a.append(jnp.sum(nrm * (pav - pi), -1))
            vols_b.append(-jnp.sum(nrm * (pbv - pi), -1))
            tris.append((i_i, i_j, i_k))
            # new DIAGONAL edges must not exceed the split threshold —
            # nothing re-splits after the polish phase this kernel runs
            # in, so an overlong diagonal would survive to the output
            kv = k < (n - 2)
            if k > 0:               # (pi,pj) is a diagonal unless k==0
                diag_long = diag_long | (
                    kv & (_elen(ring[ar, i_i], ring[ar, i_j]) > lmax))
            diag_long = diag_long | (
                kv & (k < n - 3) &  # (pi,pk) diagonal unless k==n-3
                (_elen(ring[ar, i_i], ring[ar, i_k]) > lmax))
        va_s = jnp.stack(vols_a, 1)                      # [K, NTRI]
        vb_s = jnp.stack(vols_b, 1)
        kvalid = jnp.arange(NTRI)[None, :] < (n - 2)[:, None]
        tot_a = jnp.sum(jnp.where(kvalid, va_s, 0.0), axis=1)
        sgn = jnp.where(tot_a >= 0, 1.0, -1.0)           # ring orientation
        ok = jnp.all(~kvalid | ((va_s * sgn[:, None] > EPSD) &
                                (vb_s * sgn[:, None] > EPSD)), axis=1) \
            & ~diag_long
        # tets with orientation fix: flip (pi, pj) when sgn < 0
        flip = sgn < 0
        tet_rows = []
        for k, (i_i, i_j, i_k) in enumerate(tris):
            gi = ring[ar, i_i]
            gj = ring[ar, i_j]
            gk = ring[ar, i_k]
            w0a = jnp.where(flip, gj, gi)
            w1a = jnp.where(flip, gi, gj)
            tet_rows.append(jnp.stack([w0a, w1a, gk, a], 1))
            # b-apex tet: base orientation (pj, pi, pk, b), flip undoes
            w0b = jnp.where(flip, gi, gj)
            w1b = jnp.where(flip, gj, gi)
            tet_rows.append(jnp.stack([w0b, w1b, gk, b], 1))
        rows = jnp.stack(tet_rows, 1)                    # [K, NT_NEW, 4]
        qf = quality_from_points(
            mesh.vert[rows.reshape(K * NT_NEW, 4)],
            None if m6 is None else m6[rows.reshape(K * NT_NEW, 4)])
        qf = qf.reshape(K, NT_NEW)
        mvalid = jnp.repeat(kvalid, 2, axis=1)           # [K, NT_NEW]
        fan_q.append(jnp.min(jnp.where(mvalid, qf, jnp.inf), axis=1))
        fan_ok.append(active_fan & ok)
        fan_tets.append(rows)
        fan_flip.append(flip)

    fq = jnp.stack(fan_q, 1)                             # [K, 6]
    fok = jnp.stack(fan_ok, 1)
    fq_m = jnp.where(fok, fq, -jnp.inf)
    best_c = jnp.argmax(fq_m, axis=1)                    # [K]
    q_new = fq_m[ar, best_c]
    cand = cand & jnp.any(fok, axis=1) & \
        (q_new > jnp.maximum(SWAP_GAIN * q_old, QUAL_FLOOR))

    # ---- claims ----------------------------------------------------------
    sh_eff = tuple(
        jnp.where(slot_valid[:, k], shc[:, k], shc[:, 0])
        for k in range(RING_MAX))
    win = claim_shells(q_new - q_old, cand, sh_eff, capT)

    # ---- allocation of the extra (n-4) slots -----------------------------
    # slot-reusing pool (edges.free_rows): each winner takes up to
    # RING_MAX-4 consecutive POOL entries, not consecutive slots
    from .edges import free_rows
    LF = 2 * K
    frow_t, nfree_t = free_rows(mesh.tmask, LF)
    extra = jnp.where(win, n - 4, 0)
    off = jnp.cumsum(extra) - extra
    fits = (off + extra) <= jnp.minimum(nfree_t, LF)
    win = win & fits
    extra = jnp.where(win, n - 4, 0)
    off = jnp.cumsum(extra) - extra

    # ---- gather the winning fan's rows + route tags ----------------------
    tets_best = jnp.stack(fan_tets, 1)[ar, best_c]       # [K, NT_NEW, 4]
    flip_best = jnp.stack(fan_flip, 1)[ar, best_c]       # [K]

    def route(c_arr, k, apex_is_a):
        """Face/edge tags of new tet (tri k of fan c, given apex).

        Base corner order (pi, pj, pk, apex); a corner-(0,1) swap
        permutes face cols (0,1) and edge cols (0,3,4,1,2,5) — the
        ops/swap.py routing convention.  The a-tet is built flipped when
        flip_best; the b-tet starts from (pj, pi, pk, b), so its
        effective routing flip is the NEGATION of flip_best.
        """
        eff_flip = flip_best if apex_is_a else ~flip_best
        i_j = ring_at(c_arr + k + 1)
        i_k = ring_at(c_arr + k + 2)
        pair_j = i_j                 # ring pair (c+k+1, c+k+2): always
        f_src = face_a if apex_is_a else face_b
        fr_src = fref_a if apex_is_a else fref_b
        sp_src = spoke_a if apex_is_a else spoke_b
        zero_u = jnp.zeros(K, jnp.uint32)
        zero_i = jnp.zeros(K, jnp.int32)
        is_first = k == 0                                # (pi,pj) ring pair
        nlast = (k == (n - 3))                           # (pi,pk) ring pair
        pair_c = ring_at(c_arr)                          # pair index c
        pair_last = ring_at(c_arr + k + 2)               # pair (c+k+2)=c-1
        # face cols: 0 opp pi = (pj,pk,ap) <- pair_j; 1 opp pj =
        # (pi,pk,ap) <- pair (c-1) iff k==n-3; 2 opp pk = (pi,pj,ap) <-
        # pair c iff k==0; 3 opp apex = triangle, interior
        f0 = f_src[ar, pair_j]
        f1 = jnp.where(nlast, f_src[ar, pair_last], zero_u)
        f2 = (f_src[ar, pair_c] if is_first
              else zero_u)
        fr0 = fr_src[ar, pair_j]
        fr1 = jnp.where(nlast, fr_src[ar, pair_last], zero_i)
        fr2 = (fr_src[ar, pair_c] if is_first else zero_i)
        ftag_n = jnp.stack([
            jnp.where(eff_flip, f1, f0),
            jnp.where(eff_flip, f0, f1),
            f2, zero_u], 1)
        fref_n = jnp.stack([
            jnp.where(eff_flip, fr1, fr0),
            jnp.where(eff_flip, fr0, fr1),
            fr2, zero_i], 1)
        # edges (pi-pj, pi-pk, pi-ap, pj-pk, pj-ap, pk-ap)
        e0 = (ring_etag[ar, pair_c] if is_first else zero_u)
        e1 = jnp.where(nlast, ring_etag[ar, pair_last], zero_u)
        e2 = sp_src[ar, ring_at(c_arr)]
        e3 = ring_etag[ar, pair_j]
        e4 = sp_src[ar, i_j]
        e5 = sp_src[ar, i_k]
        cols = [e0, e1, e2, e3, e4, e5]
        flipped = [cols[0], cols[3], cols[4], cols[1], cols[2], cols[5]]
        etag_n = jnp.stack(
            [jnp.where(eff_flip, fv, nv)
             for nv, fv in zip(cols, flipped)], 1)
        return ftag_n, fref_n, etag_n

    c_arr = best_c.astype(jnp.int32)
    ftag_rows, fref_rows, etag_rows = [], [], []
    for k in range(NTRI):
        for apex_is_a in (True, False):
            fa, fr, ea = route(c_arr, k, apex_is_a)
            ftag_rows.append(fa)
            fref_rows.append(fr)
            etag_rows.append(ea)
    # m-slot order must match tet_rows construction: (k, a), (k, b)
    ftag_new = jnp.stack(ftag_rows, 1)                   # [K, NT_NEW, 4]
    fref_new = jnp.stack(fref_rows, 1)
    etag_new = jnp.stack(etag_rows, 1)                   # [K, NT_NEW, 6]

    # ---- write: m < n reuses shell slots, m >= n allocates ---------------
    nsw = jnp.sum(win.astype(jnp.int32))

    def _apply(_):
        tet_o = mesh.tet
        ftag_o = mesh.ftag
        fref_o = mesh.fref
        etag_o = mesh.etag
        tmask_o = mesh.tmask
        tref_o = mesh.tref
        idx_all = []
        for m in range(NT_NEW):
            valid_m = win & (m < 2 * (n - 2))
            tgt = jnp.where(
                m < n, shc[:, min(m, RING_MAX - 1)],
                frow_t[jnp.clip(off + jnp.maximum(m - n, 0), 0, LF - 1)])
            idx_all.append(jnp.where(valid_m, tgt, capT))
        idx_cat = jnp.concatenate(idx_all)
        tet_o = tet_o.at[idx_cat].set(
            tets_best.transpose(1, 0, 2).reshape(NT_NEW * K, 4),
            mode="drop")
        ftag_o = ftag_o.at[idx_cat].set(
            ftag_new.transpose(1, 0, 2).reshape(NT_NEW * K, 4),
            mode="drop")
        fref_o = fref_o.at[idx_cat].set(
            fref_new.transpose(1, 0, 2).reshape(NT_NEW * K, 4),
            mode="drop")
        etag_o = etag_o.at[idx_cat].set(
            etag_new.transpose(1, 0, 2).reshape(NT_NEW * K, 6),
            mode="drop")
        tmask_o = tmask_o.at[idx_cat].set(True, mode="drop")
        tref_o = tref_o.at[idx_cat].set(
            jnp.tile(tref0[selx], NT_NEW), mode="drop")
        return tet_o, ftag_o, fref_o, etag_o, tmask_o, tref_o

    def _skip(_):
        return (mesh.tet, mesh.ftag, mesh.fref, mesh.etag, mesh.tmask,
                mesh.tref)

    tet_o, ftag_o, fref_o, etag_o, tmask_o, tref_o = jax.lax.cond(
        nsw > 0, _apply, _skip, None)
    used_hi = jnp.where(extra > 0,
                        frow_t[jnp.clip(off + extra - 1, 0, LF - 1)] + 1, 0)
    nelem = jnp.maximum(mesh.nelem, jnp.max(used_hi))
    out = dataclasses.replace(
        mesh, tet=tet_o, tmask=tmask_o, tref=tref_o, ftag=ftag_o,
        fref=fref_o, etag=etag_o, nelem=nelem.astype(jnp.int32))
    return SwapGenResult(out, nsw)


# eager entry point: ONE module-level jitted object + compile-ledger
# registration (the ROADMAP governor follow-on for the swapgen/repair
# tails).  The production hot path calls swapgen_wave inline from the
# already-jitted sliver_polish_impl and is unaffected; this is the
# governed front door for callers OUTSIDE an enclosing jit (tests,
# diagnostics, future eager tails) so they neither retrace the wave
# op-by-op nor mint a fresh jax.jit object per call
def _make_swapgen_jit():
    from functools import partial as _partial
    from ..utils.compilecache import governed
    return governed("ops.swapgen_wave", budget=4)(
        _partial(jax.jit, static_argnames=("budget_div", "lmax"))(
            swapgen_wave))


swapgen_wave_j = _make_swapgen_jit()
