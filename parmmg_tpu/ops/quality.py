"""Tet quality and metric edge lengths — vmapped kernels.

Reference semantics: ``PMMG_tetraQual`` / ``PMMG_qualhisto`` / ``PMMG_prilen``
(/root/reference/src/quality_pmmg.c:33-733) wrap Mmg's per-tet quality
(``MMG5_caltet_iso``/``_ani``) and edge-length formulas and reduce histograms
across ranks with a custom MPI op.  Here the per-entity math is a dense
vectorized kernel over the whole tet array, and the distributed reduction is a
``psum`` in the sharded path (see parallel/).

Quality is normalized so the equilateral tet scores 1:
    Q = ALPHA_TET * V_M / (sum_e l_M(e)^2)^{3/2}
with V_M and l_M measured in the metric when one is given.

Metric conventions: iso metric = desired edge size h per vertex ([capP]);
aniso metric = symmetric 3x3 tensor per vertex, packed [capP,6] as
(m11,m12,m13,m22,m23,m33) (Mmg packing), with l_M(e) = sqrt(e^T M e).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.constants import ALPHA_TET, EPSD, IARE
from ..core.mesh import Mesh, tet_edge_vertices, tet_volumes

_IARE_J = jnp.asarray(IARE)


def unpack_sym(m6: jax.Array) -> jax.Array:
    """[...,6] packed symmetric -> [...,3,3] full tensor."""
    m11, m12, m13, m22, m23, m33 = jnp.moveaxis(m6, -1, 0)
    row0 = jnp.stack([m11, m12, m13], -1)
    row1 = jnp.stack([m12, m22, m23], -1)
    row2 = jnp.stack([m13, m23, m33], -1)
    return jnp.stack([row0, row1, row2], -2)


def iso_to_tensor(h: jax.Array) -> jax.Array:
    """Iso size h -> packed tensor diag(1/h^2)."""
    w = 1.0 / jnp.maximum(h, EPSD) ** 2
    z = jnp.zeros_like(w)
    return jnp.stack([w, z, z, w, z, w], -1)


# ---------------------------------------------------------------------------
# Edge lengths
# ---------------------------------------------------------------------------
def edge_length_iso(p0, p1, h0, h1):
    """Metric length of segment p0p1 with linearly varying iso size.

    Exact integral of 1/h(t) along the edge (log-mean), guarded to the
    arithmetic mean of reciprocals when h0 ~ h1 (Mmg MMG5_lenedgCoor_iso
    semantics).
    """
    d = jnp.sqrt(jnp.maximum(jnp.sum((p1 - p0) ** 2, -1), 0.0))
    r0 = 1.0 / jnp.maximum(h0, EPSD)
    r1 = 1.0 / jnp.maximum(h1, EPSD)
    close = jnp.abs(r0 - r1) < 1e-6 * jnp.maximum(r0, r1)
    ratio = jnp.where(close, 1.0, h0 / jnp.maximum(h1, EPSD))
    logr = jnp.log(jnp.maximum(ratio, EPSD))
    lm = jnp.where(close, 0.5 * (r0 + r1),
                   (r1 - r0) / jnp.where(close, 1.0, logr))
    return d * lm


def edge_length_ani(p0, p1, m0, m1):
    """Aniso metric length: simpson-like average of endpoint-metric lengths.

    l_i = sqrt(e^T M_i e); combined l = 2/3 * (l0^2 + l0 l1 + l1^2)/(l0+l1)
    (exact for linearly varying sqrt-form, Mmg MMG5_lenedgCoor_ani flavor).
    """
    e = p1 - p0
    M0 = unpack_sym(m0)
    M1 = unpack_sym(m1)
    q0 = jnp.einsum("...i,...ij,...j->...", e, M0, e)
    q1 = jnp.einsum("...i,...ij,...j->...", e, M1, e)
    l0 = jnp.sqrt(jnp.maximum(q0, 0.0))
    l1 = jnp.sqrt(jnp.maximum(q1, 0.0))
    s = jnp.maximum(l0 + l1, EPSD)
    return (2.0 / 3.0) * (l0 * l0 + l0 * l1 + l1 * l1) / s


def tet_edge_lengths(mesh: Mesh, met: jax.Array) -> jax.Array:
    """[capT, 6] metric length of every tet edge (garbage on invalid slots)."""
    ev = tet_edge_vertices(mesh.tet)               # [T,6,2]
    p0 = mesh.vert[ev[..., 0]]
    p1 = mesh.vert[ev[..., 1]]
    if met.ndim == 1:
        return edge_length_iso(p0, p1, met[ev[..., 0]], met[ev[..., 1]])
    return edge_length_ani(p0, p1, met[ev[..., 0]], met[ev[..., 1]])


# ---------------------------------------------------------------------------
# Quality
# ---------------------------------------------------------------------------
_EDGE_I = jnp.asarray(IARE[:, 0])
_EDGE_J = jnp.asarray(IARE[:, 1])


def quality_from_points(p: jax.Array, m6: jax.Array | None = None):
    """Quality of tets given their corner coordinates.

    ``p``: [..., 4, 3]; ``m6``: optional per-corner packed metric
    [..., 4, 6].  Equilateral = 1; <= 0 when inverted/degenerate.  This is
    the kernel shared by smoothing/swap candidate evaluation (Mmg evaluates
    ``MMG5_caltet`` on hypothetical configurations the same way).
    """
    d1 = p[..., 1, :] - p[..., 0, :]
    d2 = p[..., 2, :] - p[..., 0, :]
    d3 = p[..., 3, :] - p[..., 0, :]
    vol = jnp.sum(d1 * jnp.cross(d2, d3), -1) / 6.0
    e = p[..., _EDGE_J, :] - p[..., _EDGE_I, :]        # [...,6,3]
    if m6 is None:
        l2 = jnp.sum(e * e, -1)
        num = ALPHA_TET * vol
    else:
        Mbar = unpack_sym(jnp.mean(m6, axis=-2))       # [...,3,3]
        l2 = jnp.einsum("...ei,...ij,...ej->...e", e, Mbar, e)
        det = jnp.linalg.det(Mbar)
        num = ALPHA_TET * vol * jnp.sqrt(jnp.maximum(det, 0.0))
    rap = jnp.sum(l2, -1)
    q = num / jnp.maximum(rap, EPSD) ** 1.5
    return jnp.where(vol > 0, jnp.minimum(q, 1.0), jnp.minimum(q, 0.0))


def _quality_m6bar(p: jax.Array, m6bar: jax.Array) -> jax.Array:
    """jnp fallback for the aniso Pallas quality kernel: the tet-average
    metric is already formed, so reuse quality_from_points with a
    singleton corner axis (its internal mean is then the identity)."""
    return quality_from_points(p, m6bar[..., None, :])


def tet_quality(mesh: Mesh, met: jax.Array | None = None) -> jax.Array:
    """[capT] quality in [0,1], equilateral=1; <=0 for inverted/degenerate.

    Iso path ignores sizes (quality is scale-invariant for a constant
    metric, matching MMG5_caltet_iso); aniso path measures volume and edge
    lengths in the average tet metric (MMG5_caltet_ani semantics).
    """
    from functools import partial
    from .pallas_kernels import use_pallas, pallas_forced, quality_pallas
    if use_pallas():
        p = mesh.vert[mesh.tet]                         # [T,4,3]
        # off-TPU branch chosen at lowering time: jnp formula normally,
        # interpreted Pallas kernel when PARMMG_TPU_PALLAS=1 forces the
        # production kernel numerics everywhere
        from ..utils.jaxcompat import platform_dependent
        if met is None or met.ndim == 1:
            off_tpu = (partial(quality_pallas, m6bar=None, interpret=True)
                       if pallas_forced()
                       else lambda pp: quality_from_points(pp, None))
            q = platform_dependent(
                p,
                tpu=partial(quality_pallas, m6bar=None, interpret=False),
                default=off_tpu)
        else:
            m6bar = jnp.mean(met[mesh.tet], axis=1)
            off_tpu = (partial(quality_pallas, interpret=True)
                       if pallas_forced() else _quality_m6bar)
            q = platform_dependent(
                p, m6bar,
                tpu=partial(quality_pallas, interpret=False),
                default=off_tpu)
        return jnp.where(mesh.tmask, q, 0.0)
    vol = tet_volumes(mesh)
    ev = tet_edge_vertices(mesh.tet)
    e = mesh.vert[ev[..., 1]] - mesh.vert[ev[..., 0]]   # [T,6,3]
    if met is None or met.ndim == 1:
        l2 = jnp.sum(e * e, -1)                         # [T,6]
        num = ALPHA_TET * vol
    else:
        Mv = unpack_sym(met[mesh.tet])                  # [T,4,3,3]
        Mbar = jnp.mean(Mv, axis=1)                     # [T,3,3]
        l2 = jnp.einsum("tei,tij,tej->te", e, Mbar, e)
        det = jnp.linalg.det(Mbar)
        num = ALPHA_TET * vol * jnp.sqrt(jnp.maximum(det, 0.0))
    rap = jnp.sum(l2, -1)
    q = num / jnp.maximum(rap, EPSD) ** 1.5
    return jnp.where(mesh.tmask, q, 0.0)


def quality_histogram(q: jax.Array, tmask: jax.Array, nbins: int = 5):
    """(counts[nbins], qmin, qmean, n_bad) over valid tets.

    Bins follow Mmg's display histogram (powers-of-... we use uniform [0,1]
    bins like PMMG_qualhisto's 5-class table, quality_pmmg.c:156).
    """
    n = jnp.maximum(jnp.sum(tmask), 1)
    qv = jnp.where(tmask, q, jnp.inf)
    qmin = jnp.min(qv)
    qmean = jnp.sum(jnp.where(tmask, q, 0.0)) / n
    edges = jnp.linspace(0.0, 1.0, nbins + 1)
    idx = jnp.clip(jnp.searchsorted(edges, jnp.clip(q, 0.0, 1.0 - 1e-9),
                                    side="right") - 1, 0, nbins - 1)
    counts = jnp.zeros(nbins, jnp.int32).at[idx].add(
        tmask.astype(jnp.int32))
    n_bad = jnp.sum((q <= 0.0) & tmask)
    return counts, qmin, qmean, n_bad


def length_histogram(mesh: Mesh, met: jax.Array, nbins: int = 9):
    """Edge-length statistics over *unique* edges.

    The reference dedups interface entities across ranks
    (PMMG_count_nodes_par, quality_pmmg.c:33); locally we dedup each edge
    shared by several tets by unique-key weighting: an edge's contribution is
    divided by its multiplicity.  Returns (counts, lmin, lmax, lmean) with the
    reference's 9-bin layout (bounds from Mmg: 0..0.3,0.6,0.7071,0.9,1.3,
    1.4142,2,5,inf).
    """
    ev = tet_edge_vertices(mesh.tet).reshape(-1, 2)     # [T*6,2]
    a = jnp.minimum(ev[:, 0], ev[:, 1])
    b = jnp.maximum(ev[:, 0], ev[:, 1])
    lens = tet_edge_lengths(mesh, met).reshape(-1)
    valid = jnp.repeat(mesh.tmask, 6)
    big = jnp.iinfo(jnp.int32).max
    a = jnp.where(valid, a, big)
    b = jnp.where(valid, b, big)
    # multiplicity via 2-column lexsort (int32-only, TPU-friendly)
    order = jnp.lexsort((b, a))
    ka, kb = a[order], b[order]
    first = jnp.concatenate([jnp.array([True]),
                             (ka[1:] != ka[:-1]) | (kb[1:] != kb[:-1])])
    uniq = first & valid[order]
    l = lens[order]
    n = jnp.maximum(jnp.sum(uniq), 1)
    lmin = jnp.min(jnp.where(uniq, l, jnp.inf))
    lmax = jnp.max(jnp.where(uniq, l, -jnp.inf))
    lmean = jnp.sum(jnp.where(uniq, l, 0.0)) / n
    bounds = jnp.array([0.0, 0.3, 0.6, 0.7071, 0.9, 1.3, 1.4142, 2.0, 5.0,
                        jnp.inf])
    idx = jnp.clip(jnp.searchsorted(bounds, l, side="right") - 1, 0, nbins - 1)
    counts = jnp.zeros(nbins, jnp.int32).at[idx].add(uniq.astype(jnp.int32))
    return counts, lmin, lmax, lmean
