"""Batched vertex smoothing — data-parallel replacement for Mmg's movtet.

Reference behavior: ``MMG5_movtet`` relocates free vertices to improve local
quality (volume barycenter moves for interior points, tangential moves for
surface points), never degrading the worst quality of the ball; required /
corner / parallel-interface points are frozen (the ParMmg contract,
tag_pmmg.c:39-124).

Wave scheme: every movable vertex proposes the quality-weighted centroid of
its ball; validity (ball min-quality must not decrease) is checked
tet-centrically; a hash-rotated independent set (vertex claims all its ball
tets) moves per wave so the precheck remains exact under simultaneous moves.
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.mesh import Mesh
from ..core.constants import (
    MG_BDY, MG_CRN, MG_GEO, MG_REQ, MG_PARBDY, QUAL_FLOOR)
from .quality import quality_from_points
from .edges import PRI_MIN


class SmoothResult(NamedTuple):
    mesh: Mesh
    nmoved: jax.Array


def smooth_wave(mesh: Mesh, met: jax.Array, wave: int = 0,
                relax: float = 1.0) -> SmoothResult:
    capT, capP = mesh.capT, mesh.capP
    movable = mesh.vmask & ((mesh.vtag &
                             (MG_BDY | MG_REQ | MG_CRN | MG_PARBDY)) == 0)

    tv = mesh.tet
    vpos = mesh.vert[tv]                                   # [T,4,3]
    centroid = jnp.mean(vpos, axis=1)                      # [T,3]
    # proposal: mean of ball-tet centroids (volume-barycenter flavor of
    # MMG5_movintpt)
    acc = jnp.zeros((capP + 1, 3), mesh.vert.dtype)
    cnt = jnp.zeros((capP + 1,), mesh.vert.dtype)
    for k in range(4):
        idx = jnp.where(mesh.tmask, tv[:, k], capP)
        acc = acc.at[idx].add(centroid, mode="drop")
        cnt = cnt.at[idx].add(1.0, mode="drop")
    prop = acc[:capP] / jnp.maximum(cnt[:capP, None], 1.0)

    # --- validity: per-ball min quality must not decrease ----------------
    # Try a cascade of relaxation factors (Mmg's movtet retries with damped
    # steps); each vertex takes the largest step whose ball min-quality
    # strictly improves.
    # iso: Euclidean quality (MMG5_caltet_iso — local scaling cancels);
    # aniso: per-corner packed tensors.  Skipping the [T,4,6] gather and
    # the tensor math in the 12 quality evaluations below is a large TPU
    # win per wave.
    mq = None if met.ndim == 1 else met[tv]                # [T,4,6] | None
    q_old = quality_from_points(vpos, mq)                  # [T]
    minq_old = jnp.full(capP + 1, jnp.inf, mesh.vert.dtype)
    for k in range(4):
        idx = jnp.where(mesh.tmask, tv[:, k], capP)
        minq_old = minq_old.at[idx].min(
            jnp.where(mesh.tmask, q_old, jnp.inf), mode="drop")
    minq_old = minq_old[:capP]

    newpos = mesh.vert
    best_gain = jnp.zeros(capP, mesh.vert.dtype)
    for step in (relax, 0.5 * relax, 0.25 * relax):
        cand_pos = mesh.vert + step * (prop - mesh.vert)
        cand_pos = jnp.where(movable[:, None], cand_pos, mesh.vert)
        minq_new = jnp.full(capP + 1, jnp.inf, mesh.vert.dtype)
        for k in range(4):
            idx = jnp.where(mesh.tmask, tv[:, k], capP)
            p_k = vpos.at[:, k].set(cand_pos[tv[:, k]])
            q_new = quality_from_points(p_k, mq)
            minq_new = minq_new.at[idx].min(
                jnp.where(mesh.tmask, q_new, jnp.inf), mode="drop")
        gain = minq_new[:capP] - minq_old
        ok = (minq_new[:capP] > jnp.maximum(minq_old, QUAL_FLOOR)) & movable
        take = ok & (gain > best_gain)
        newpos = jnp.where(take[:, None], cand_pos, newpos)
        best_gain = jnp.where(take, gain, best_gain)
    improves = best_gain > 0

    # --- independent set: vertex claims its ball tets --------------------
    # wave-rotated hash: a full-avalanche BIJECTIVE mix (odd multiplies +
    # xor-shifts, invertible mod 2^32), so per-wave priorities are unique
    # by construction and usable directly as the claim order — no sort
    wv = jnp.asarray(wave, jnp.uint32)
    h = jnp.arange(capP, dtype=jnp.uint32) * jnp.uint32(2654435761)
    h = h + wv * jnp.uint32(2246822519)
    h = h ^ (h >> 15)
    h = h * jnp.uint32(2654435761)
    h = h ^ (h >> 13)
    vpri = jnp.where(improves, h.astype(jnp.int32), PRI_MIN)
    tclaim = jnp.max(jnp.where(mesh.tmask[:, None], vpri[tv], PRI_MIN),
                     axis=1)
    lost = jnp.zeros(capP + 1, bool)
    for k in range(4):
        idx = jnp.where(mesh.tmask, tv[:, k], capP)
        mism = improves[tv[:, k]] & (tclaim != vpri[tv[:, k]])
        lost = lost.at[idx].max(mism, mode="drop")
    win = improves & ~lost[:capP]

    vert = jnp.where(win[:, None], newpos, mesh.vert)
    return SmoothResult(dataclasses.replace(mesh, vert=vert),
                        jnp.sum(win.astype(jnp.int32)))
