"""Batched vertex smoothing — data-parallel replacement for Mmg's movtet.

Reference behavior: ``MMG5_movtet`` relocates free vertices to improve local
quality (volume barycenter moves for interior points — ``MMG5_movintpt``;
tangential moves for regular surface points — ``MMG5_movbdyregpt``), never
degrading the worst quality of the ball; required / corner / ridge /
parallel-interface points are frozen (the ParMmg contract,
tag_pmmg.c:39-124).

Wave scheme: every movable vertex proposes a new position (ball-centroid
for interior points; tangent-plane-projected surface-centroid for regular
boundary points on locally-flat patches); validity (ball min-quality must
not decrease) is checked tet-centrically; a hash-rotated independent set
(vertex claims all its ball tets) moves per wave so the precheck remains
exact under simultaneous moves.
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.mesh import Mesh
from ..core.constants import (
    IDIR, MG_BDY, MG_CRN, MG_GEO, MG_NOM, MG_REF, MG_REQ, MG_PARBDY,
    EPSD, QUAL_FLOOR)
from .quality import quality_from_points
from .edges import PRI_MIN

# a regular surface point only slides in its tangent plane when its
# incident boundary faces are mutually near-parallel — the move is then
# surface-exact; curved patches wait for hausd-driven reprojection (Mmg
# reprojects onto the surface ball instead).  Gate: |sum of unit
# normals| / count >= FLAT_RATIO, i.e. a single outlier face in a
# 12-face ball may tilt ~4 deg (the old per-face min-dot gate allowed
# 2.6 deg but cost a second full-width gather+scatter pass per wave)
FLAT_RATIO = 0.9998


class SmoothResult(NamedTuple):
    mesh: Mesh
    nmoved: jax.Array


def morton_window_mask(vert: jax.Array, vmask: jax.Array, wave,
                       nwin: int) -> jax.Array:
    """[capP] bool: vertices of the ``wave % nwin``-th contiguous
    morton-curve segment.  Smoothing any independent SUBSET per wave is
    valid (the claim scheme already rotates); choosing spatially
    COHERENT subsets keeps each cycle's footprint a compact blob, which
    is what lets the active-scoped narrow path (ops/active.py) hold the
    worklist small — scattered moves have ~100-tet 2-hop stencils each,
    a window's moves share theirs.

    Windows are equal-POPULATION segments of the curve, not equal
    code-space: an adapted mesh concentrates vertices where the metric
    is fine (the shock slab holds most of the mesh), so code-space
    windows made per-cycle footprints oscillate severalfold and
    overflow the narrow row budget (measured 8k-21k active tets at
    nwin=24; each overflow costs a discarded narrow attempt plus a
    full-width fallback cycle).  The live-vertex histogram CDF over
    1024 curve bins equalizes the windows to bin granularity for the
    cost of one [capP] scatter-add.  Window boundaries therefore DRIFT
    as the population changes; the bounded-staleness guarantee of the
    worklist does not rest on stable boundaries but on the periodic
    full-width refresh cycle (ops/active.py module docstring)."""
    from .edges import morton_codes
    code = morton_codes(vert, vmask, bits=5)   # 15-bit morton
    b = code >> 5                              # 1024 curve bins
    hist = jnp.zeros(1024, jnp.int32).at[b].add(
        vmask.astype(jnp.int32), mode="drop")
    cdf = jnp.cumsum(hist)
    n_live = jnp.maximum(cdf[-1], 1)
    rank0 = (cdf - hist)[b]                    # live rank at bin start
    win = (rank0 * nwin) // n_live             # <= capP * 64 < int31
    return win == jnp.mod(jnp.asarray(wave, jnp.int32), nwin)


def smooth_wave(mesh: Mesh, met: jax.Array, wave: int = 0,
                relax: float = 1.0,
                opt_q: float | None = None,
                vact: jax.Array | None = None) -> SmoothResult:
    """One smoothing wave; see module docstring.

    ``opt_q``: optimal-position mode for sliver balls — interior
    vertices whose ball min quality is below ``opt_q`` propose a move
    along the HEIGHT direction of their worst incident tet (direct
    ascent on that tet's quality) instead of the ball centroid; the
    centroid is blind to the worst member and plateaus exactly where
    the min needs lifting (Mmg's bad-element relocation in MMG3D_opttyp
    serves this role).  The relaxation cascade and the exact ball
    min-quality gate are unchanged.

    Fixed-point invariant (the smoothing-cadence contract,
    ops/adapt.adapt_cycle_impl ``smooth_idle``): on the full-width path
    (``vact is None``) ``nmoved == 0`` iff NO vertex has an accepted
    improving move — the globally best improving vertex can never lose
    a claim, so an empty accepted set means the improving set itself is
    empty, and that emptiness is invariant under the ``wave`` rotation
    (proposals are wave-independent; ``wave`` only rotates claim
    tie-breaks among winners).  A zero-move wave is therefore an exact
    identity on the mesh, and skipping the NEXT wave after a fully
    quiet cycle (no topology changes either) is bit-exact, not an
    approximation.
    """
    capT, capP = mesh.capT, mesh.capP
    movable_int = mesh.vmask & ((mesh.vtag &
                                 (MG_BDY | MG_REQ | MG_CRN | MG_PARBDY))
                                == 0)
    reg_bdy = mesh.vmask & ((mesh.vtag & MG_BDY) != 0) & \
        ((mesh.vtag & (MG_REQ | MG_CRN | MG_PARBDY | MG_GEO | MG_NOM |
                       MG_REF)) == 0)
    if vact is not None:
        # narrow-path restriction (ops/active.py): only active vertices
        # may move — their full ball is in the sub-mesh, so proposal and
        # gate stay exact
        movable_int = movable_int & vact
        reg_bdy = reg_bdy & vact

    tv = mesh.tet
    vpos = mesh.vert[tv]                                   # [T,4,3]
    centroid = jnp.mean(vpos, axis=1)                      # [T,3]
    # proposal: mean of ball-tet centroids (volume-barycenter flavor of
    # MMG5_movintpt).  All 4 corners accumulate in ONE concatenated wide
    # scatter — per-op overhead dominates scatter cost on this device
    # (scripts/tpu_microbench.py: cost is flat in payload width).
    idx4 = jnp.concatenate(
        [jnp.where(mesh.tmask, tv[:, k], capP) for k in range(4)])
    pay = jnp.concatenate([jnp.concatenate(
        [centroid, jnp.ones((centroid.shape[0], 1), mesh.vert.dtype)],
        axis=1)] * 4)                                      # [4T, 4]
    acc4 = jnp.zeros((capP + 1, 4), mesh.vert.dtype).at[idx4].add(
        pay, mode="drop")
    prop = acc4[:capP, :3] / jnp.maximum(acc4[:capP, 3:], 1.0)

    # --- surface proposals (movbdyregpt): tangential move on flat patch --
    idir = jnp.asarray(IDIR)
    isb = ((mesh.ftag & MG_BDY) != 0) & mesh.tmask[:, None]   # [T,4]
    fv = tv[:, idir]                                       # [T,4,3] vids
    fp = mesh.vert[fv]                                     # [T,4,3,3]
    fn = jnp.cross(fp[:, :, 1] - fp[:, :, 0],
                   fp[:, :, 2] - fp[:, :, 0])              # [T,4,3] outward
    fc = jnp.mean(fp, axis=2)                              # [T,4,3]
    farea = 0.5 * jnp.sqrt(jnp.sum(fn * fn, -1))           # [T,4]
    # all 12 (face, corner) contributions in ONE wide scatter:
    # payload = (area-weighted normal[3], area*centroid[3], area[1],
    #            unit normal[3], count[1]) — the unit-normal sum feeds
    # the locally-flat gate below with no second full-width pass
    idx12 = jnp.concatenate(
        [jnp.where(isb[:, f], fv[:, f, k], capP)
         for f in range(4) for k in range(3)])
    w4 = jnp.where(isb, farea, 0.0)                        # [T,4]
    fn_unit = fn / (jnp.linalg.norm(fn, axis=-1, keepdims=True) + EPSD)
    pay_f = jnp.concatenate(
        [fn, w4[..., None] * fc, w4[..., None], fn_unit,
         jnp.ones_like(w4)[..., None]], axis=-1)           # [T,4,11]
    pay12 = jnp.concatenate(
        [pay_f[:, f] for f in range(4) for _ in range(3)])
    sacc = jnp.zeros((capP + 1, 11), mesh.vert.dtype).at[idx12].add(
        pay12, mode="drop")
    nacc, cacc, aacc = sacc[:, :3], sacc[:, 3:6], sacc[:, 6]
    uacc, ucnt = sacc[:, 7:10], sacc[:, 10]
    navg = nacc[:capP] / (jnp.linalg.norm(nacc[:capP], axis=-1,
                                          keepdims=True) + EPSD)
    # locally-flat gate: |sum of unit normals| close to the face count
    # means every incident boundary face is near the common plane
    ratio = jnp.linalg.norm(uacc[:capP], axis=-1) / \
        jnp.maximum(ucnt[:capP], 1.0)
    flat = (ratio >= FLAT_RATIO) & (aacc[:capP] > 0)
    bdy_ok = reg_bdy & flat
    cbar = cacc[:capP] / jnp.maximum(aacc[:capP, None], EPSD)
    dvec = cbar - mesh.vert
    dvec = dvec - jnp.sum(dvec * navg, -1, keepdims=True) * navg
    prop = jnp.where(bdy_ok[:, None], mesh.vert + dvec, prop)
    movable = movable_int | bdy_ok

    # --- validity: per-ball min quality must not decrease ----------------
    # Try a cascade of relaxation factors (Mmg's movtet retries with damped
    # steps); each vertex takes the largest step whose ball min-quality
    # strictly improves.
    # iso: Euclidean quality (MMG5_caltet_iso — local scaling cancels);
    # aniso: per-corner packed tensors.  Skipping the [T,4,6] gather and
    # the tensor math in the 12 quality evaluations below is a large TPU
    # win per wave.
    mq = None if met.ndim == 1 else met[tv]                # [T,4,6] | None
    q_old = quality_from_points(vpos, mq)                  # [T]
    minq_old = jnp.full(capP + 1, jnp.inf, mesh.vert.dtype).at[idx4].min(
        jnp.tile(jnp.where(mesh.tmask, q_old, jnp.inf), 4), mode="drop")
    minq_old = minq_old[:capP]

    if opt_q is not None:
        # worst-incident-tet height ascent: for each (tet, corner) whose
        # tet attains the vertex's ball minimum, the perpendicular from
        # the opposite face plane to the corner is the quality gradient
        # direction (moving +d doubles that tet's height); ties average.
        sworst = jnp.where(mesh.tmask, -q_old, -jnp.inf)
        vworst = jnp.full(capP + 1, -jnp.inf, mesh.vert.dtype).at[
            idx4].max(jnp.tile(sworst, 4), mode="drop")[:capP]
        dacc = jnp.zeros((capP + 1, 4), mesh.vert.dtype)
        for k in range(4):
            fidx = idir[k]                                 # face opp k
            p0 = vpos[:, fidx[0]]
            nrm = jnp.cross(vpos[:, fidx[1]] - p0, vpos[:, fidx[2]] - p0)
            n2 = jnp.maximum(jnp.sum(nrm * nrm, -1, keepdims=True), EPSD)
            d = nrm * (jnp.sum((vpos[:, k] - p0) * nrm, -1,
                               keepdims=True) / n2)        # [T,3]
            is_w = mesh.tmask & (sworst >= vworst[tv[:, k]])
            pay = jnp.concatenate(
                [jnp.where(is_w[:, None], d, 0.0),
                 is_w[:, None].astype(mesh.vert.dtype)], axis=1)
            dacc = dacc.at[jnp.where(is_w, tv[:, k], capP)].add(
                pay, mode="drop")
        cnt = jnp.maximum(dacc[:capP, 3:], 1.0)
        prop_opt = mesh.vert + dacc[:capP, :3] / cnt
        use_opt = movable_int & (minq_old < opt_q) & \
            (dacc[:capP, 3] > 0)
        prop = jnp.where(use_opt[:, None], prop_opt, prop)

    # the 4 per-corner displacement variants are evaluated as ONE stacked
    # quality call per relaxation step (4x batch ~ free, 4 calls are not)
    mq4 = None if mq is None else jnp.tile(mq, (4, 1, 1))
    newpos = mesh.vert
    best_gain = jnp.zeros(capP, mesh.vert.dtype)
    # NOTE a two-step cascade (dropping 0.25) was tried for the ~20 ms
    # saving and reverted: the small step is load-bearing for final edge-
    # length conformity (test_adapt_target_lengths regressed without it)
    for step in (relax, 0.5 * relax, 0.25 * relax):
        cand_pos = mesh.vert + step * (prop - mesh.vert)
        cand_pos = jnp.where(movable[:, None], cand_pos, mesh.vert)
        newp = cand_pos[tv]                                # [T,4,3]
        variants = jnp.concatenate(
            [vpos.at[:, k].set(newp[:, k]) for k in range(4)])  # [4T,4,3]
        qv = quality_from_points(variants, mq4)            # [4T]
        minq_new = jnp.full(capP + 1, jnp.inf, mesh.vert.dtype).at[
            idx4].min(jnp.where(jnp.tile(mesh.tmask, 4), qv, jnp.inf),
                      mode="drop")
        gain = minq_new[:capP] - minq_old
        ok = (minq_new[:capP] > jnp.maximum(minq_old, QUAL_FLOOR)) & movable
        take = ok & (gain > best_gain)
        newpos = jnp.where(take[:, None], cand_pos, newpos)
        best_gain = jnp.where(take, gain, best_gain)
    # minimum-gain gate (Mmg's movers demand a real improvement too):
    # balls already above the sliver threshold only move for a >=2%
    # relative lift of their min quality — without this, centroid
    # micro-moves churn forever at steady state (each move re-creates
    # short edges for the collapse pass), so a converged mesh never
    # reaches the cheap idle cycles; bad balls keep the any-gain rule
    gain_tol = jnp.where(minq_old < 0.2, 0.0, 0.02 * minq_old)
    improves = best_gain > gain_tol

    # --- independent set: vertex claims its ball tets --------------------
    # wave-rotated hash: a full-avalanche BIJECTIVE mix (odd multiplies +
    # xor-shifts, invertible mod 2^32), so per-wave priorities are unique
    # by construction and usable directly as the claim order — no sort
    wv = jnp.asarray(wave, jnp.uint32)
    h = jnp.arange(capP, dtype=jnp.uint32) * jnp.uint32(2654435761)
    h = h + wv * jnp.uint32(2246822519)
    h = h ^ (h >> 15)
    h = h * jnp.uint32(2654435761)
    h = h ^ (h >> 13)
    vpri = jnp.where(improves, h.astype(jnp.int32), PRI_MIN)
    tclaim = jnp.max(jnp.where(mesh.tmask[:, None], vpri[tv], PRI_MIN),
                     axis=1)
    vpri_c = vpri[tv]                                      # [T,4]
    mism4 = jnp.concatenate(
        [improves[tv[:, k]] & (tclaim != vpri_c[:, k]) for k in range(4)])
    lost = jnp.zeros(capP + 1, bool).at[idx4].max(mism4, mode="drop")
    win = improves & ~lost[:capP]

    vert = jnp.where(win[:, None], newpos, mesh.vert)
    return SmoothResult(dataclasses.replace(mesh, vert=vert),
                        jnp.sum(win.astype(jnp.int32)))
