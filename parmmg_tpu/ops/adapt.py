"""Whole-mesh adaptation driver — the remesh operator.

This is the TPU-native replacement for the sequential remesher call
``MMG5_mmg3d1_delone`` that the reference invokes per group
(/root/reference/src/libparmmg1.c:737-739).  Where Mmg runs a sequential
cascade of local cavity operations, we run *batched waves*: each jitted
cycle applies one independent set of splits, collapses, swaps and smoothing
moves across the whole mesh, with adjacency rebuilt in between.  The host
loop only reads back scalar counters to decide convergence and to manage
capacity (the static-shape analogue of Mmg's realloc dance and of
``PMMG_parmesh_SetMemGloMax`` budgeting, zaldy_pmmg.c:53-254).

Frozen entities (MG_REQ / MG_PARBDY — the ParMmg interface contract,
tag_pmmg.c:39-124) are respected by every wave, so this same operator
serves both the single-chip whole-mesh path and the per-shard path with
frozen interfaces.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core.mesh import Mesh, with_capacity, compact
from ..core.constants import LLONG, LSHRT
from ..obs import trace as otrace
from .adjacency import build_adjacency
from .split import split_wave
from .collapse import collapse_wave
from .swap import swap_edges_wave, swap23_wave
from .smooth import smooth_wave


@dataclass
class AdaptStats:
    nsplit: int = 0
    ncollapse: int = 0
    nswap: int = 0
    nmoved: int = 0
    cycles: int = 0
    regrows: int = 0
    # PMMG_SUCCESS unless the run degraded (failed_handling contract:
    # PMMG_LOWFAILURE = something failed but a conforming mesh is saved)
    status: int = 0
    # quiet-group scheduler instrumentation (parallel/sched.py via the
    # grouped paths): chunked group-block dispatches executed / skipped
    # by the scheduler, group-block slots skipped, and the free-form
    # extra dict (active-group trajectories + pipeline segment seconds)
    # that bench.py / scripts/scale_big.py surface in their artifacts
    group_dispatches: int = 0
    group_dispatches_saved: int = 0
    groups_skipped: int = 0
    sched_extra: dict = field(default_factory=dict)
    # serving-mode tenant isolation (serve/): stats carrying DIFFERENT
    # tenant ids refuse to merge (a per-tenant SLO must never silently
    # aggregate across tenants), and merging a tenant-tagged stats into
    # an untagged aggregate namespaces its sched_extra/timer keys under
    # "tenant:<id>/" so trajectories and segment seconds stay separable
    tenant: str | None = None

    def __iadd__(self, other):
        if (self.tenant is not None and other.tenant is not None
                and self.tenant != other.tenant):
            raise ValueError(
                f"refusing to merge AdaptStats across tenants "
                f"({self.tenant!r} += {other.tenant!r}); aggregate into "
                "an untagged AdaptStats instead")
        self.nsplit += other.nsplit
        self.ncollapse += other.ncollapse
        self.nswap += other.nswap
        self.nmoved += other.nmoved
        self.cycles += other.cycles
        self.regrows += other.regrows
        self.status = max(self.status, other.status)
        self.group_dispatches += other.group_dispatches
        self.group_dispatches_saved += other.group_dispatches_saved
        self.groups_skipped += other.groups_skipped
        pre = f"tenant:{other.tenant}/" \
            if self.tenant is None and other.tenant is not None else ""
        for k, v in other.sched_extra.items():
            kk = k if k.startswith("tenant:") else pre + k
            if isinstance(v, list):
                self.sched_extra.setdefault(kk, []).extend(v)
            else:
                self.sched_extra[kk] = self.sched_extra.get(kk, 0.0) + v
        return self

    def publish(self, registry=None) -> None:
        """Publish the counters into the obs metrics registry
        (obs/metrics.py): tenant-tagged stats land as tenant-namespaced
        series, the same ``tenant:<id>/`` convention as sched_extra.
        The cross-tenant isolation contract stays in ``__iadd__``."""
        from ..obs.metrics import publish_stats
        publish_stats(self, registry)


def adapt_cycle_impl(mesh: Mesh, met: jax.Array, wave: jax.Array,
                     do_swap: bool = True, do_smooth: bool = True,
                     smooth_waves: int = 1, do_insert: bool = True,
                     final_rebuild: bool = True,
                     hausd: float | None = None,
                     budget_div: int = 8,
                     et0=None, vact=None, submesh: bool = False,
                     wide: bool = False, wwin=None,
                     prescreen: bool = True, active=None,
                     smooth_idle=None, topo=None, incr=None):
    """One adaptation cycle: split -> collapse -> [swap] -> [smooth].

    Pure jittable function (jitted wrapper below) — also the compile-check
    entry point exposed by ``__graft_entry__.entry``.

    Adjacency is rebuilt only where a consumer needs it (it is the most
    expensive primitive of the cycle, ~42 ms at bench shapes): swap23
    (face pairing) is the ONLY adja reader — split/collapse/edge-swaps/
    smooth run off the edge table or tets alone (collapse transfers dying
    tets' face tags with a keyed face join instead of the old adja
    lookup).  ``final_rebuild`` restores the every-returned-mesh-has-
    valid-adja contract for external callers; fused blocks skip it
    between cycles.

    ``vact``/``submesh``: active-scoped narrow mode (ops/active.py) —
    candidates are restricted to active vertices and the adjacency
    rebuilds skip boundary tagging (a sub-mesh's unmatched faces are
    cut faces, not surface).

    Returns (mesh, met, counts) with ``counts`` = int32
    [nsplit, ncollapse, nswap, nmoved, overflow, live_tets, deferred,
    narrow_abort] stacked in ONE device array: the host reads all
    per-cycle counters with a single transfer (each separate scalar pull
    costs a full round trip on a remote-device transport, and an *eager*
    count op on the host would fight the donated input buffers).
    ``deferred`` = top-K budget cuts of viable candidates, encoded as
    2 bits: bit 0 = an INSERTION wave (split/collapse) deferred —
    sizing-critical, the narrow path escalates to full-width on it;
    bit 1 = a SWAP wave deferred — swap nomination pools routinely
    exceed the sub top-K and their backlog is covered by the periodic
    full refresh + polish, so narrow does not escalate on it
    (ops/active.py).  ``narrow_abort`` is always 0 on this full-width
    path.

    ``active``: optional traced scalar bool — the device-resident
    quiet-mask hook of the grouped paths (parallel/sched.py).  When
    given, the WHOLE cycle is wrapped in ``lax.cond``: an inactive
    group slot returns its state unchanged with zero op counts (live
    count still reported), so a ``lax.map`` group body skips the
    split/collapse/swap/smooth wave math for slots the scheduler
    already proved quiet — exact by the frozen-seam + deterministic-
    wave fixed-point argument (re-running any weaker-or-equal block on
    a zero-op state is byte-identity, so returning the input IS the
    recompute).  ``active=None`` compiles the unconditional body — the
    whole-mesh path is untouched.

    ``smooth_idle``: optional traced scalar bool — the smoothing-cadence
    carry (PARMMG_SMOOTH_CADENCE, parallel/sched.cadence_enabled): True
    means the PREVIOUS cycle was a full no-op (zero topo ops AND zero
    smoothing moves).  When also THIS cycle's topo counts are zero, the
    smoothing wave is ``lax.cond``-skipped — provably an identity:
    smooth_wave's proposals are wave-independent and its claim
    resolution cannot rob the globally best improving vertex, so
    nmoved == 0 ⟺ no vertex improves ⟺ the wave is the identity map,
    and the emptiness of the improving set is wave-rotation-invariant
    (ops/smooth.py) — re-running it on the byte-identical mesh of a
    topo-quiet successor cycle would again move nothing.  The skipped
    wave truthfully reports nmoved = 0, so the carry chain stays exact.
    Like ``active``, it is a TRACED argument: toggling the cadence
    never mints a new compile family.  Only used on the full-width path
    (callers pass None alongside vact/wwin restrictions).

    ``topo``/``incr``: the incremental topology engine (ops/topo_incr).
    ``topo`` is a TopoState carrying the retained edge/face sorts and
    dirty masks across cycles; ``incr`` the traced PARMMG_INCR_TOPO
    scalar.  When threaded, the cycle derives its edge table and
    adjacency through the band-merge path (bit-identical to the legacy
    rebuilds — off position, overflow and cold state all take the exact
    full sort), marks the tets each wave touched (unconditionally, so
    both knob arms report identical counts), the counts row widens to 9
    (``counts[8]`` = dirty tets at cycle start), and the return becomes
    a 4-tuple ``(mesh, met, counts, topo)``.  ``topo=None`` is the
    untouched legacy path (8-wide counts, 3-tuple).
    """
    from .adjacency import boundary_edge_tags
    if topo is not None:
        from .topo_incr import (incr_unique_edges, incr_build_adjacency,
                                mark_dirty)
        if incr is None:
            incr = jnp.zeros((), bool)
    if active is not None:
        def _run(ops):
            m, k, tp = ops
            out = adapt_cycle_impl(
                m, k, wave, do_swap=do_swap, do_smooth=do_smooth,
                smooth_waves=smooth_waves, do_insert=do_insert,
                final_rebuild=final_rebuild, hausd=hausd,
                budget_div=budget_div, et0=et0, vact=vact,
                submesh=submesh, wide=wide, wwin=wwin,
                prescreen=prescreen, smooth_idle=smooth_idle,
                topo=tp, incr=incr)
            return out if tp is not None else out + (tp,)

        def _skip(ops):
            m, k, tp = ops
            nc = 8 if tp is None else 9
            counts = jnp.zeros(nc, jnp.int32).at[5].set(
                jnp.sum(m.tmask, dtype=jnp.int32))
            if tp is not None:
                # an idle slot's retained tables stay valid; report its
                # pending dirty count for the occupancy trajectory
                counts = counts.at[8].set(
                    jnp.sum(tp.edirty, dtype=jnp.int32))
            return m, k, counts, tp
        m, k, counts, tp = jax.lax.cond(active, _run, _skip,
                                        (mesh, met, topo))
        return (m, k, counts) if topo is None else (m, k, counts, tp)
    defer = jnp.zeros((), bool)
    defer_sw = jnp.zeros((), bool)
    nd0 = (jnp.zeros((), jnp.int32) if topo is None
           else jnp.sum(topo.edirty, dtype=jnp.int32))
    if do_insert:
        # ONE edge table + metric lengths serve both split and collapse
        # (the tables are a measured wave hot spot); the collapse defers
        # candidates whose table rows the split made stale
        from .edges import unique_edges, edge_lengths
        # slim table: split/collapse never read shell3 (only the swap
        # kernels, which build their own) — skips a [6*capT] scatter.
        # ``et0``: a caller-provided table of THIS mesh (the fused block
        # reuses the previous cycle's table after a topology-quiet
        # cycle — smoothing only moves vertices, so the table is
        # provably identical; metric lengths ALWAYS recompute).
        if et0 is None:
            if topo is not None:
                et0, topo = incr_unique_edges(mesh, topo, incr,
                                              shell_slots=0)
            else:
                et0 = unique_edges(mesh, shell_slots=0)
        lens0 = edge_lengths(mesh, et0, met)
        # ridge tangents once per cycle too (same sharing rationale;
        # collapse only consults non-stale candidates, whose tangent
        # fields are identical pre/post split)
        vtan0 = None
        if hausd is not None:
            from .analysis import ridge_vertex_tangents
            vtan0 = ridge_vertex_tangents(mesh, et=et0)
        # wide convergence-verification cycles (and the drivers' polish
        # cycles, via ``prescreen=False``) disable the approximate
        # nomination prescreen so shells it over-vetoed get one exact
        # re-evaluation before convergence is accepted (split.py)
        res = split_wave(mesh, met, hausd=hausd, budget_div=budget_div,
                         et=et0, lens=lens0, vtan=vtan0, vact=vact,
                         prescreen=prescreen and not wide)
        if topo is not None:
            topo = mark_dirty(topo, mesh.tet, mesh.tmask, res.mesh)
        mesh, met = res.mesh, res.met
        nsplit, overflow = res.nsplit, res.overflow
        defer = defer | res.deferred

        col = collapse_wave(mesh, met, hausd=hausd,
                            budget_div=budget_div,
                            et=et0, lens=lens0,
                            stale_tets=res.modified, vtan=vtan0,
                            vact=vact, wwin=wwin)
        if topo is not None:
            # boundary_edge_tags below touches only tags, which the
            # retained sorts never carry — marking against col.mesh is
            # exact (ops/topo_incr module docstring)
            topo = mark_dirty(topo, mesh.tet, mesh.tmask, col.mesh)
        defer = defer | col.deferred
        # collapse rewires the surface (dying tets' face tags transfer to
        # the surviving neighbors); re-propagate MG_BDY from faces to
        # their edges and vertices so later splits/smooth treat the new
        # surface entities as boundary — without this, untagged surface
        # midpoints become "movable" and smoothing dents the surface.
        # Skipped when no dying tet donated tags (interior collapses):
        # the propagation pass costs a [12*capT]-index scatter
        mesh = jax.lax.cond(col.surface_changed, boundary_edge_tags,
                            lambda m: m, col.mesh)
        ncol = col.ncollapse
    else:
        # -noinsert: no point insertion or deletion (Mmg contract)
        nsplit = jnp.zeros((), jnp.int32)
        ncol = jnp.zeros((), jnp.int32)
        overflow = jnp.zeros((), bool)

    nswap = jnp.zeros((), jnp.int32)
    if do_swap:
        from .swap import swap_facesort_enabled
        sew = swap_edges_wave(mesh, met, hausd=hausd,
                              budget_div=budget_div,
                              vact=vact, wwin=wwin)  # 3-2 + 2-2
        if topo is not None:
            topo = mark_dirty(topo, mesh.tet, mesh.tmask, sew.mesh)
        if swap_facesort_enabled():
            # swap23 pairs directly off the face sort (bit-identical to
            # the adja path — ops/swap._pair_fields_facesort); the
            # [capT,4] adja materialization + compare leaves the cycle
            # interior, final_rebuild restores the adja contract.
            # (This mid-cycle face sort is NOT band-maintained — scope
            # cut: the facesort swap23 derives its pairing internally.)
            s23 = swap23_wave(sew.mesh, met, budget_div=budget_div,
                              wwin=wwin, facesort=True,
                              set_bdy_tags=not submesh)
            pre = sew.mesh
        else:
            # consumed by swap23 (adja-only on a sub-mesh: cut faces are
            # unmatched without being surface)
            if topo is not None:
                mesh, topo = incr_build_adjacency(
                    sew.mesh, topo, incr, set_bdy_tags=not submesh)
            else:
                mesh = build_adjacency(sew.mesh, set_bdy_tags=not submesh)
            s23 = swap23_wave(mesh, met, budget_div=budget_div, wwin=wwin)
            pre = mesh
        if topo is not None:
            topo = mark_dirty(topo, pre.tet, pre.tmask, s23.mesh)
        mesh = s23.mesh
        nswap = sew.nswap + s23.nswap
        defer_sw = defer_sw | sew.deferred | s23.deferred

    nmoved = jnp.zeros((), jnp.int32)
    if do_smooth:
        # in windowed mode (wwin, the ops/active.py rotation) smoothing
        # restricts to the window; in narrow mode vact (the worklist
        # closure, itself window-derived) is the restriction
        sv = vact if vact is not None else wwin

        def _smooth(m):
            nm = jnp.zeros((), jnp.int32)
            for w in range(smooth_waves):
                sm = smooth_wave(m, met, wave=wave * smooth_waves + w,
                                 vact=sv)
                m = sm.mesh
                nm = nm + sm.nmoved
            return m, nm

        if smooth_idle is not None and sv is None:
            # smoothing cadence (see docstring): skip is exact only on
            # the full-width path — a window rotation changes the
            # candidate set between cycles, so sv disables the gate
            skip = smooth_idle & ((nsplit + ncol + nswap) == 0)
            mesh, nmoved = jax.lax.cond(
                skip, lambda m: (m, jnp.zeros((), jnp.int32)),
                _smooth, mesh)
        else:
            mesh, nmoved = _smooth(mesh)

    if final_rebuild:
        if topo is not None:
            mesh, topo = incr_build_adjacency(mesh, topo, incr,
                                              set_bdy_tags=not submesh)
        else:
            mesh = build_adjacency(mesh, set_bdy_tags=not submesh)

    row = [nsplit, ncol, nswap, nmoved,
           overflow.astype(jnp.int32),
           jnp.sum(mesh.tmask, dtype=jnp.int32),
           defer.astype(jnp.int32) + 2 * defer_sw.astype(jnp.int32),
           jnp.zeros((), jnp.int32)]
    if topo is None:
        return mesh, met, jnp.stack(row)
    # counts[8]: dirty tets pending at cycle START — the dirty-band
    # occupancy trajectory the grouped drivers surface in sched_extra
    return mesh, met, jnp.stack(row + [nd0]), topo


from ..utils.compilecache import governed as _governed  # noqa: E402

adapt_cycle = _governed("adapt.cycle")(
    partial(jax.jit, static_argnames=(
        "do_swap", "do_smooth", "smooth_waves", "do_insert", "final_rebuild",
        "hausd", "budget_div", "submesh", "wide", "prescreen"),
        donate_argnums=(0, 1))(adapt_cycle_impl))


def fem_pass_impl(mesh: Mesh, met: jax.Array):
    """One FEM-conformity wave: split interior edges whose endpoints are
    both boundary points (the configuration that lets an element touch
    the boundary with two faces or all four vertices).  This is the
    Mmg fem-mode topology fix the reference forwards per group
    (API_functions_pmmg.c:652-658, default ``info.fem`` ON :413); run
    after the sizing/polish loop until no candidate remains.

    Returns (mesh, met, counts[2] = [nsplit, overflow])."""
    from .adjacency import boundary_edge_tags
    res = split_wave(mesh, met, fem_only=True, budget_div=2)
    mesh = boundary_edge_tags(res.mesh)
    mesh = build_adjacency(mesh)
    return mesh, res.met, jnp.stack(
        [res.nsplit, res.overflow.astype(jnp.int32)])


fem_pass = partial(jax.jit, donate_argnums=(0, 1))(fem_pass_impl)


def adapt_cycles_fused_impl(mesh: Mesh, met: jax.Array, wave0: jax.Array,
                            n_cycles: int = 3, swap_every: int = 3,
                            swap_offset: int = 0,
                            hausd: float | None = None,
                            swap_flags: tuple | None = None,
                            do_smooth: bool = True,
                            do_insert: bool = True,
                            budget_div: int = 8,
                            cadence=None, topo=None, incr=None):
    """``n_cycles`` adaptation cycles in ONE jitted program.

    On a remote-attached TPU every dispatch pays a transport round trip
    (and the per-cycle counter pull is a host sync); fusing a block of
    cycles amortizes both and gives XLA one big program to schedule.  The
    swap cadence is compiled in (cycle c swaps iff c % swap_every ==
    swap_every-1, matching the host driver — or pass ``swap_flags``, an
    explicit per-cycle tuple overriding the cadence, which also sets
    n_cycles); counters come back stacked [n_cycles, 6] and are read
    with a single transfer.

    Overflow safety: a capacity overflow inside the block only truncates
    that cycle's winner set (split_wave drops the lowest-priority winners
    that don't fit); the flag is reported per cycle so the host can regrow
    and rerun as usual.

    ``cadence``: optional traced scalar bool (PARMMG_SMOOTH_CADENCE) —
    threads the smoothing-cadence carry across the block's cycles: after
    a full no-op cycle (zero topo ops, zero moves), the next topo-quiet
    cycle's smoothing wave is skipped as a proven identity (see
    adapt_cycle_impl's ``smooth_idle``).  The carry is derived on-device
    from each cycle's counts, so the cadence costs no extra transfer.

    ``topo``/``incr``: thread the incremental topology engine through
    the block (see adapt_cycle_impl) — the retained table + band state
    is the carry, superseding the all-or-nothing et cache below (the
    engine's nd==0 branch reuses the retained sort wholesale, covering
    the same topo-quiet case AND extending it to adjacency).  Returns a
    4-tuple ``(mesh, met, counts [n,9], topo)`` when threaded.
    """
    if swap_flags is None:
        swap_flags = tuple(
            (c + swap_offset) % swap_every == swap_every - 1
            for c in range(n_cycles))
    counts_all = []
    # edge-table cache across the block: after a cycle with zero
    # topological changes (splits/collapses/swaps), the next cycle's
    # table rebuild is lax.cond-skipped — at steady state (smoothing
    # churn only) this removes the largest remaining per-cycle item
    from .edges import unique_edges
    prev_et = None
    prev_ok = None
    sm_idle = None if cadence is None else jnp.zeros((), bool)
    for c, dosw in enumerate(swap_flags):
        et_c = None
        if do_insert and topo is None:
            if prev_et is None:
                et_c = unique_edges(mesh, shell_slots=0)
            else:
                pe = prev_et

                def _reuse(_, pe=pe):
                    return pe

                def _rebuild(_, m=mesh):
                    return unique_edges(m, shell_slots=0)
                et_c = jax.lax.cond(prev_ok, _reuse, _rebuild, None)
        out = adapt_cycle_impl(
            mesh, met, wave0 + c, do_swap=dosw,
            do_smooth=do_smooth, do_insert=do_insert,
            final_rebuild=(c == len(swap_flags) - 1), hausd=hausd,
            budget_div=budget_div, et0=et_c,
            smooth_idle=None if sm_idle is None else (cadence & sm_idle),
            topo=topo, incr=incr)
        if topo is None:
            mesh, met, counts = out
        else:
            mesh, met, counts, topo = out
        counts_all.append(counts)
        if sm_idle is not None:
            sm_idle = ((counts[0] + counts[1] + counts[2]) == 0) & \
                (counts[3] == 0)
        if do_insert and topo is None:
            prev_et = et_c
            prev_ok = (counts[0] + counts[1] + counts[2]) == 0
    if topo is None:
        return mesh, met, jnp.stack(counts_all)
    return mesh, met, jnp.stack(counts_all), topo


adapt_cycles_fused = _governed("adapt.cycles_fused")(
    partial(jax.jit, static_argnames=(
        "n_cycles", "swap_every", "swap_offset", "hausd", "swap_flags",
        "do_smooth", "do_insert", "budget_div"),
        donate_argnums=(0, 1))(adapt_cycles_fused_impl))


def default_cycle_block(x=None) -> int:
    """Fused cycles per dispatch for the production drivers: 9 on TPU
    (each dispatch pays a ~70-110 ms tunnel round trip; measured 0.222
    -> 0.236 Mtets/s going 3 -> 9 on the bench workload), 1 elsewhere
    (a local backend gains nothing and the CPU test matrix would pay
    the multiplied compile time).  Convergence overshoot inside a block
    is bounded by the zero-candidate lax.cond skips.  Override with
    PARMMG_CYCLE_BLOCK."""
    import os
    v = os.environ.get("PARMMG_CYCLE_BLOCK", "")
    if v:
        return max(1, int(v))
    plat = None
    try:
        if x is not None and hasattr(x, "devices"):
            plat = next(iter(x.devices())).platform
    except Exception:
        plat = None
    if plat is None:
        plat = jax.default_backend()
    return 9 if plat == "tpu" else 1


def sliver_polish_impl(mesh: Mesh, met: jax.Array, wave: jax.Array,
                       sliver_q: float = 0.2, do_collapse: bool = True,
                       do_swap: bool = True, do_smooth: bool = True,
                       hausd: float | None = None, active=None):
    """Bad-element optimization pass (MMG3D_opttyp analogue): quality-
    targeted collapses on tets below ``sliver_q``, then swaps and a
    smoothing wave.  Run after the sizing loop converges — length-driven
    waves leave near-degenerate tets whose edges are all 'nice' lengths.
    The do_* switches mirror -noinsert/-noswap/-nomove.

    ``active``: optional traced scalar bool — same device-resident
    quiet-mask hook as :func:`adapt_cycle_impl`: an inactive group slot
    (a retired group of the wave-major grouped polish, or a padded tail
    row of a compacted chunk plan) returns its state unchanged with
    zero counts instead of running the collapse/swap/smooth math.

    Returns (mesh, counts[4] = [ncollapse, nswap, nmoved, live_tets]).
    """
    from .adjacency import boundary_edge_tags
    if active is not None:
        def _run(m):
            return sliver_polish_impl(
                m, met, wave, sliver_q=sliver_q,
                do_collapse=do_collapse, do_swap=do_swap,
                do_smooth=do_smooth, hausd=hausd)

        def _skip(m):
            counts = jnp.zeros(4, jnp.int32).at[3].set(
                jnp.sum(m.tmask, dtype=jnp.int32))
            return m, counts
        return jax.lax.cond(active, _run, _skip, mesh)
    ncol = jnp.zeros((), jnp.int32)
    nswap = jnp.zeros((), jnp.int32)
    nmoved = jnp.zeros((), jnp.int32)
    if do_collapse:
        # polish is off the timed sizing path: widen the compaction
        # budget (budget_div=2) so the quality pass covers the full
        # sliver population instead of the worst K only
        col = collapse_wave(mesh, met, sliver_q=sliver_q, hausd=hausd,
                            budget_div=2)
        mesh = jax.lax.cond(col.surface_changed, boundary_edge_tags,
                            lambda m: m, col.mesh)
        ncol = col.ncollapse
    if do_swap:
        from .swapgen import swapgen_wave
        from .swap import swap_facesort_enabled
        sew = swap_edges_wave(mesh, met, hausd=hausd,
                              budget_div=2)  # 3-2 + 2-2
        # generalized degree 4-6 ring swaps: the worst surviving tets
        # are typically gate-limited for every lower-degree op — this
        # is the class that lifts the min past the 3-2/2-3 plateau
        sgn = swapgen_wave(sew.mesh, met, budget_div=2)
        if swap_facesort_enabled():
            s23 = swap23_wave(sgn.mesh, met, budget_div=2, facesort=True)
        else:
            mesh = build_adjacency(sgn.mesh)    # consumed by swap23
            s23 = swap23_wave(mesh, met, budget_div=2)
        mesh = s23.mesh
        nswap = sew.nswap + sgn.nswap + s23.nswap
    if do_smooth:
        # optimal-position mode: sliver-ball vertices ascend the height
        # of their worst incident tet instead of chasing the centroid
        sm = smooth_wave(mesh, met, wave=wave, opt_q=sliver_q)
        mesh = sm.mesh
        nmoved = sm.nmoved
    mesh = build_adjacency(mesh)                # exit contract
    counts = jnp.stack([ncol, nswap, nmoved,
                        jnp.sum(mesh.tmask, dtype=jnp.int32)])
    return mesh, counts


sliver_polish = _governed("adapt.sliver_polish")(
    partial(jax.jit, static_argnames=(
        "sliver_q", "do_collapse", "do_swap", "do_smooth", "hausd"),
        donate_argnums=(0,))(sliver_polish_impl))


def grow_mesh_met(mesh: Mesh, met, newP: int, newT: int):
    """Grow capacities, carrying the metric through compact()'s permutation."""
    vperm = np.argsort(~np.asarray(mesh.vmask), kind="stable")
    meth = np.zeros((newP,) + met.shape[1:], np.asarray(met).dtype)
    meth[: mesh.capP] = np.asarray(met)[vperm]
    mesh = with_capacity(mesh, newP, newT)
    return mesh, jnp.asarray(meth)


def adapt_mesh(mesh: Mesh, met: jax.Array, max_cycles: int = 50,
               verbose: int = 0, headroom: float = 0.85,
               swap_every: int = 3, noinsert: bool = False,
               noswap: bool = False, nomove: bool = False,
               angedg: float | None = None,
               hausd: float | None = None,
               cycle_block: int | None = None) -> tuple:
    """Host driver: run cycles until no topological change, manage capacity.

    Swap waves cost about as much as split+collapse+smooth combined (they
    re-derive the edge table and adjacency twice), so they run every
    ``swap_every``-th cycle — like Mmg, which interleaves swap/move passes
    between sizing passes rather than swapping continuously — and always
    once the mesh is near convergence.

    Cycles are dispatched in fused blocks of ``cycle_block`` (default:
    9 on TPU, 1 elsewhere — see default_cycle_block): on the tunneled
    chip every dispatch pays a transport round trip and a counter pull,
    so the production driver pays one per BLOCK, exactly like bench.py.

    Returns (mesh, met, AdaptStats).
    """
    stats = AdaptStats()
    from .analysis import analyze_mesh
    from ..core.constants import ANGEDG
    # honor the caller's ridge-detection threshold (-ar / -nr): a default
    # re-analysis here would re-introduce MG_GEO tags the user disabled
    mesh = analyze_mesh(mesh, ANGEDG if angedg is None else angedg).mesh
    if cycle_block is None:
        cycle_block = default_cycle_block(mesh.vert)
    quiet = 0
    wide_check = False
    converged = False
    cycle = 0
    # worklist state threaded through auto blocks (ops/active.py):
    # zeros/False = no worklist yet, first cycles run full-width
    dirty = None                 # [capP] bool device array
    okflag = False
    while cycle < max_cycles and not converged:
        # capacity management before the wave block (each block can add
        # up to block * 2*capT/8 tets; the overflow flag + regrow below
        # catches a mid-block shortfall, winners are only deferred)
        n_p, n_t = mesh.np_counts()
        if n_p > headroom * mesh.capP or n_t > headroom * mesh.capT:
            mesh, met = grow_mesh_met(mesh, met,
                                      max(mesh.capP, int(2 * n_p)),
                                      max(mesh.capT, int(2 * n_t)))
            stats.regrows += 1
            dirty = None        # regrow permuted slots; footprint stale
            okflag = False

        was_wide = wide_check
        # single-cycle dispatch when quiet: the quiet>0-forces-swap rule
        # (convergence confirmation) is per-cycle state the compiled
        # block cadence cannot see
        if wide_check or cycle_block == 1 or quiet > 0:
            do_swap = ((cycle % swap_every == swap_every - 1)
                       or quiet > 0) and not noswap
            mesh, met, counts = adapt_cycle(
                mesh, met, jnp.asarray(cycle, jnp.int32), do_swap=do_swap,
                do_smooth=not nomove, do_insert=not noinsert, hausd=hausd,
                budget_div=2 if wide_check else 8, wide=wide_check)
            rows = [(do_swap, np.asarray(counts))]
            dirty = None        # full wide pass: worklist invalid
            okflag = False
        else:
            # self-width-selecting fused block (ops/active.py): each
            # cycle runs active-scoped when its worklist is valid and
            # fits, full-width otherwise — one dispatch either way
            from .active import adapt_cycles_auto
            nblk = min(cycle_block, max_cycles - cycle)
            flags = tuple(
                (((cycle + c) % swap_every == swap_every - 1)
                 and not noswap) for c in range(nblk))
            if dirty is None:
                dirty = jnp.zeros(mesh.capP, bool)
                okflag = False
            mesh, met, dirty, okflag, counts_all = adapt_cycles_auto(
                mesh, met, dirty, jnp.asarray(bool(okflag)),
                jnp.asarray(cycle, jnp.int32),
                swap_flags=flags, hausd=hausd,
                do_smooth=not nomove, do_insert=not noinsert)
            ca = np.asarray(counts_all)
            rows = [(flags[c], ca[c]) for c in range(nblk)]

        ovf_any = False
        for do_swap, cnt in rows:
            ns, nc, nw, nm, ovf = (int(v) for v in cnt[:5])
            stats.nsplit += ns
            stats.ncollapse += nc
            stats.nswap += nw
            stats.nmoved += nm
            stats.cycles += 1
            otrace.log(3, f"  cycle {cycle:3d}: split {ns:6d} "
                          f"collapse {nc:6d} swap {nw:6d} move {nm:6d}",
                       verbose=verbose)
            cycle += 1
            if ovf:
                # a capacity-truncated cycle cannot witness convergence
                # (its winner set was cut, not exhausted) — reset the
                # quiet state and force the regrow below
                ovf_any = True
                quiet = 0
                wide_check = False
                converged = False
                continue
            if converged:
                continue        # later block rows: stats only
            if ns == 0 and nc == 0 and (noswap or (nw == 0 and do_swap)):
                quiet += 1
                if quiet >= 2 or nm == 0 or nomove:
                    if was_wide or (noinsert and noswap):
                        # (with insertions AND swaps disabled no budget-
                        # governed op runs — a wide cycle cannot differ)
                        converged = True
                        continue
                    # Verify convergence at a wider candidate budget
                    # before accepting it: with top-K compaction,
                    # candidates that permanently fail the
                    # post-compaction geometric gates (worst shell
                    # quality = always selected) can pin every budget
                    # slot while viable candidates ranked past K are
                    # never attempted — counts==0 would then be
                    # starvation, not convergence.
                    wide_check = True
                    quiet = 1
            elif ns == 0 and nc == 0 and not do_swap and not noswap:
                quiet = max(quiet, 1)    # trigger a swap-inclusive cycle
            else:
                quiet = 0
                wide_check = False
        if ovf_any:
            mesh, met = grow_mesh_met(mesh, met, 2 * mesh.capP,
                                      2 * mesh.capT)
            stats.regrows += 1
            okflag = False
            dirty = None

    # bad-element optimization: the sizing loop leaves slivers whose edge
    # lengths are all in-range; polish until no sliver op applies
    if noinsert and noswap and nomove:
        return mesh, met, stats
    for w in range(4):
        mesh, counts = sliver_polish(mesh, met,
                                     jnp.asarray(1000 + w, jnp.int32),
                                     do_collapse=not noinsert,
                                     do_swap=not noswap,
                                     do_smooth=not nomove, hausd=hausd)
        nc, nw, nm, _ = (int(v) for v in np.asarray(counts))
        stats.ncollapse += nc
        stats.nswap += nw
        stats.nmoved += nm
        otrace.log(3, f"  polish {w}: collapse {nc:5d} swap {nw:5d} "
                      f"move {nm:5d}", verbose=verbose)
        if nc == 0 and nw == 0:
            break
    return mesh, met, stats
