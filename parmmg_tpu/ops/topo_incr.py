"""Incremental topology maintenance: dirty-band edge-table / adjacency.

The sort-based topology primitives (ops/edges.unique_edges,
ops/adjacency.build_adjacency) re-sort ALL 6*capT / 4*capT slot keys
every cycle even when a wave commits ~30 winners — the decay regime every
long-running adaptation ends in (BENCH_r05: ~590 ms of a ~1.2 s cycle).
The reference never does this: Mmg maintains its edge/tetra hash tables
incrementally across operator applications (MMG3D_hashTetra,
hash_pmmg.c).  This module is the sort-idiom analogue:

* each wave's *dirty tet set* (rows it created, killed or re-verticed) is
  accumulated as a [capT] bool mask — exact by construction, computed as
  an elementwise diff of (tet, tmask) across the wave, the ONLY inputs
  the slot keys depend on;
* at the next table derivation the dirty tets' slots are re-keyed into a
  fixed-width band (``incr_band_width`` — one ``compilecache.bucket``
  geo-ladder rung per capT, so band handling mints zero compile
  families) and merged into the RETAINED sorted key table:
  survivors compact by rank (prefix sum), band entries binary-search
  their insertion position (lexicographic lower bound over the dense
  survivor table), and ONE packed scatter materializes the merged order
  — O(T log B) instead of the O(12T log 12T) full sort;
* overflow (more dirty tets than the band) ``lax.cond``-falls back to
  the full rebuild, so exactness is by construction, never sampled.

Exactness argument (the bit-parity proof the tests pin):
``jnp.argsort``/``jnp.lexsort`` are STABLE, so the full sort's order is
exactly "sort by (key..., slot index)".  Slot keys are pure functions of
the owning tet's (tet row, tmask) — dead and padded slots key to
INT32_MAX — so a slot's key can only change when its tet is dirty.  The
merge partitions slots into survivors (clean, keys unchanged, relative
order retained) and the band (dirty, re-keyed from the current mesh),
and merges them under the SAME (key..., slot) lexicographic order; slot
indices are unique, so the merged permutation is the unique sorted
order, i.e. bit-identical to a fresh stable sort.  Tag payloads (etag)
are NOT retained — the shared epilogue re-gathers them from the current
mesh, so mid-cycle tag updates (boundary_edge_tags) need no dirty marks.

The per-slot state (``TopoState``) rides the grouped paths' group axis
and the serve pool's slot axis; the knob (``PARMMG_INCR_TOPO``) is a
TRACED scalar everywhere, so toggling it mints zero new compile
families (the hotloop_knob_gate contract).  The prefix-sum backbone of
the merge lowers to a Pallas kernel on TPU
(ops/pallas_kernels.merge_prefix_pallas, 8x128-tiled, SMEM carry); the
CPU reference is ``jnp.cumsum`` — integer adds, bit-identical.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.mesh import Mesh, tet_edge_vertices, tet_face_vertices
from . import pallas_kernels as pk

_INT32_MAX = 2147483647


def incr_topo_enabled() -> bool:
    """PARMMG_INCR_TOPO=1 enables the incremental maintenance path
    (default off: the exact legacy full-rebuild path).  Read per pass
    and threaded as a traced scalar — same compiled programs either
    way."""
    import os
    return os.environ.get("PARMMG_INCR_TOPO", "0") == "1"


def incr_band_width(capT: int) -> int:
    """Dirty-band width in TETS for a given capacity: one
    ``compilecache.bucket`` geo-ladder rung of capT//16 (floor 1024,
    capped at capT), so every capT maps to ONE static band shape — band
    sizing can never mint a new compile family.  PARMMG_INCR_BAND
    overrides (tests / tuning)."""
    import os
    v = os.environ.get("PARMMG_INCR_BAND", "")
    if v:
        return max(1, min(int(v), capT))
    from ..utils.compilecache import bucket
    return bucket(max(1, capT // 16), floor=1024, scheme="geo", cap=capT)


class TopoState(NamedTuple):
    """Retained sorted-table + dirty-band state of one mesh (group slot).

    ``ekey``/``eslot`` are the packed edge sort (sorted keys + the sort
    permutation = original slot ids) retained from the last edge-table
    derivation; ``fk0``/``fkw``/``fslot`` the same for the 2-column face
    sort.  ``eok``/``fok`` gate reuse (False = no retained table — full
    rebuild regardless of the knob).  ``edirty``/``fdirty`` accumulate
    the tets touched since the LAST derivation of each table (the edge
    and face tables are consumed at different points of a cycle, so the
    masks reset independently)."""
    ekey: jax.Array     # [6*capT] int32 sorted packed edge keys
    eslot: jax.Array    # [6*capT] int32 edge sort permutation
    eok: jax.Array      # [] bool
    edirty: jax.Array   # [capT] bool
    fk0: jax.Array      # [4*capT] int32 sorted face key major column
    fkw: jax.Array      # [4*capT] int32 sorted face key packed minors
    fslot: jax.Array    # [4*capT] int32 face sort permutation
    fok: jax.Array      # [] bool
    fdirty: jax.Array   # [capT] bool


def topo_init(capT: int, stack: int | None = None) -> TopoState:
    """All-zeros state (ok=False: first derivation is a full rebuild).
    ``stack`` prepends a group axis (the lax.map layout)."""
    def z(shape, dt):
        s = shape if stack is None else (stack,) + shape
        return jnp.zeros(s, dt)
    return TopoState(
        ekey=z((6 * capT,), jnp.int32), eslot=z((6 * capT,), jnp.int32),
        eok=z((), bool), edirty=z((capT,), bool),
        fk0=z((4 * capT,), jnp.int32), fkw=z((4 * capT,), jnp.int32),
        fslot=z((4 * capT,), jnp.int32), fok=z((), bool),
        fdirty=z((capT,), bool))


def topo_init_np(nslots: int, capT: int) -> TopoState:
    """Host-numpy stacked state [nslots, ...] for the chunked grouped
    path and the serve pool (mutated in place by drain writebacks —
    the idempotent-writeback contract covers it: rows only change when
    a chunk's drain commits, so a faulted dispatch replays from the
    retained table bit-for-bit)."""
    import numpy as np

    def z(shape, dt):
        return np.zeros((nslots,) + shape, dt)
    return TopoState(
        ekey=z((6 * capT,), np.int32), eslot=z((6 * capT,), np.int32),
        eok=z((), bool), edirty=z((capT,), bool),
        fk0=z((4 * capT,), np.int32), fkw=z((4 * capT,), np.int32),
        fslot=z((4 * capT,), np.int32), fok=z((), bool),
        fdirty=z((capT,), bool))


def mark_dirty(topo: TopoState, tet0: jax.Array, tmask0: jax.Array,
               mesh: Mesh) -> TopoState:
    """Accumulate the dirty tet set across one wave: a tet is dirty iff
    its vertex row or liveness changed — exactly the inputs the edge and
    face slot keys depend on, so the mask is exact (never sampled).
    One elementwise diff; over-marking would still be exact (a re-keyed
    clean slot merges to its old position), under-marking cannot
    happen."""
    d = jnp.any(mesh.tet != tet0, axis=1) | (mesh.tmask != tmask0)
    return topo._replace(edirty=topo.edirty | d, fdirty=topo.fdirty | d)


# ---------------------------------------------------------------------------
# the sorted-band merge
# ---------------------------------------------------------------------------

def _prefix_i32(x: jax.Array) -> jax.Array:
    """Inclusive int32 prefix sum — the merge's scan backbone (survivor
    rank compaction + insertion-shift histogram).  TPU lowers to the
    Pallas kernel; every other platform the jnp reference (integer adds:
    bit-identical, parity pinned in tests)."""
    from .pallas_kernels import (use_pallas, pallas_forced,
                                 merge_prefix_pallas)

    def ref(v):
        return jnp.cumsum(v, dtype=jnp.int32)

    if use_pallas():
        from ..utils.jaxcompat import platform_dependent
        off_tpu = (partial(merge_prefix_pallas, interpret=True)
                   if pallas_forced() else ref)
        return platform_dependent(
            x, tpu=partial(merge_prefix_pallas, interpret=False),
            default=off_tpu)
    return ref(x)


def _lower_bound(qkeys, qslot, keys, slot):
    """Lexicographic lower bound of each (qkeys..., qslot) query in the
    dense ascending (keys..., slot) table: the first index whose entry
    compares >= the query.  Static ``bit_length`` iteration count —
    O(log n) gathers per query, no data-dependent control flow."""
    n = slot.shape[0]
    lo = jnp.zeros(qslot.shape, jnp.int32)
    hi = jnp.full(qslot.shape, n, jnp.int32)
    for _ in range(max(1, int(n).bit_length())):
        mid = (lo + hi) >> 1
        mc = jnp.clip(mid, 0, n - 1)
        less = jnp.zeros(qslot.shape, bool)
        eq = jnp.ones(qslot.shape, bool)
        for qk, k in zip(qkeys, keys):
            kv = k[mc]
            less = less | (eq & (kv < qk))
            eq = eq & (kv == qk)
        kv = slot[mc]
        less = less | (eq & (kv < qslot))
        lo = jnp.where(less, mid + 1, lo)
        hi = jnp.where(less, hi, mid)
    return lo


def band_order(bkeys, bslot):
    """Stable band sort permutation, ascending by (bkeys..., bslot) —
    the slot rides as an EXPLICIT trailing radix word because band
    record order differs from slot order (lexsort((slot, keys...)) in
    jnp terms).  Dispatched to the Pallas radix engine on TPU
    (PARMMG_PALLAS_SORT)."""
    words = tuple(bkeys) + (bslot,)
    return pk.sort_perm(words, ref=lambda ws: jnp.lexsort(ws[::-1]))


def merge_sorted_band(keys, slot, sd, bkeys, bslot):
    """Merge a re-keyed dirty band into a retained stable sort.

    ``keys`` (tuple of [n] int32 columns) + ``slot`` [n] are the
    retained sorted table (ascending by (keys..., slot) — what a stable
    sort produces); ``sd`` [n] marks the sorted positions owned by dirty
    tets (tombstones: their keys are stale).  ``bkeys``/``bslot`` [m]
    are the band's fresh records — every slot of every dirty tet, dead
    slots keyed INT32_MAX with their REAL slot id, pad entries keyed
    INT32_MAX with slot INT32_MAX.

    Survivors (~sd) compact by prefix-sum rank into a dense table padded
    with (+inf, +inf) sentinels; the band sorts locally (m << n) and
    each entry lower-bounds its insertion position; the merge-path
    identity (band j lands at pos_j + j, survivor i shifts by the
    inclusive histogram prefix of insertions at <= i) places every live
    record exactly once, and sentinel/pad rows provably land at index
    >= n, where ``mode="drop"`` discards them.  Returns the merged
    (keys tuple, slot) — bit-identical to a fresh stable sort of the
    current keys (module docstring proof)."""
    n = slot.shape[0]
    m = bslot.shape[0]
    nk = len(keys)
    keep = ~sd
    # survivor ranks: dense position = (# keepers at <= i) - 1
    r = _prefix_i32(keep.astype(jnp.int32)) - 1
    tgt = jnp.where(keep, r, n)
    pay = jnp.stack(list(keys) + [slot], axis=1)              # [n, nk+1]
    sur = jnp.full(pay.shape, _INT32_MAX, jnp.int32).at[tgt].set(
        pay, mode="drop", unique_indices=True)
    skeys = [sur[:, j] for j in range(nk)]
    sslot = sur[:, nk]
    # band sort: (keys..., slot) ascending — pads (all INT32_MAX) last
    border = band_order(bkeys, bslot)
    bks = [bk[border] for bk in bkeys]
    bs = bslot[border]
    pos = _lower_bound(bks, bs, skeys, sslot)                 # [m]
    # survivor shift = inclusive prefix of the insertion histogram
    # (pad entries are parked at bin n and excluded from the prefix)
    real = bs != _INT32_MAX
    hist = jnp.zeros(n + 1, jnp.int32).at[
        jnp.where(real, pos, n)].add(1)
    shift = _prefix_i32(hist[:n])
    sur_final = jnp.arange(n, dtype=jnp.int32) + shift
    band_final = pos + jnp.arange(m, dtype=jnp.int32)
    idx = jnp.concatenate([sur_final, band_final])
    pay_all = jnp.concatenate([sur, jnp.stack(bks + [bs], axis=1)])
    merged = jnp.zeros_like(sur).at[idx].set(
        pay_all, mode="drop", unique_indices=True)
    return [merged[:, j] for j in range(nk)], merged[:, nk]


# ---------------------------------------------------------------------------
# band record extraction (profiled as ``band_extract``)
# ---------------------------------------------------------------------------

def edge_band_records(mesh: Mesh, dt: jax.Array):
    """Fresh packed edge keys + slot ids for the 6 edge slots of each
    band tet ``dt`` ([B] int32, capT-padded).  Dead tets key INT32_MAX
    with their REAL slot ids (tombstones); pads (dt == capT) get slot
    INT32_MAX and are dropped by the merge."""
    capT = mesh.capT
    dtc = jnp.clip(dt, 0, capT - 1)
    ev = tet_edge_vertices(mesh.tet[dtc])                    # [B, 6, 2]
    a = jnp.minimum(ev[..., 0], ev[..., 1])
    b = jnp.maximum(ev[..., 0], ev[..., 1])
    live = mesh.tmask[dtc] & (dt < capT)
    key = jnp.where(live[:, None], a * mesh.capP + b, _INT32_MAX)
    slot = jnp.where(
        (dt < capT)[:, None],
        dt[:, None] * 6 + jnp.arange(6, dtype=jnp.int32)[None, :],
        _INT32_MAX)
    return key.reshape(-1), slot.reshape(-1)


def face_band_records(mesh: Mesh, dt: jax.Array):
    """Fresh (major, packed-minor) face keys + slot ids for the 4 face
    slots of each band tet (same conventions as edge_band_records;
    matches ops/adjacency._face_keys' packed branch bit-for-bit)."""
    capT = mesh.capT
    dtc = jnp.clip(dt, 0, capT - 1)
    fv = jnp.sort(tet_face_vertices(mesh.tet[dtc]), axis=-1)  # [B, 4, 3]
    live = mesh.tmask[dtc] & (dt < capT)
    k0 = jnp.where(live[:, None], fv[..., 0], _INT32_MAX)
    kw = jnp.where(live[:, None], fv[..., 1] * mesh.capP + fv[..., 2],
                   _INT32_MAX)
    slot = jnp.where(
        (dt < capT)[:, None],
        dt[:, None] * 4 + jnp.arange(4, dtype=jnp.int32)[None, :],
        _INT32_MAX)
    return k0.reshape(-1), kw.reshape(-1), slot.reshape(-1)


# ---------------------------------------------------------------------------
# table derivations (band-merged or full, one lax.cond each)
# ---------------------------------------------------------------------------

def incr_unique_edges(mesh: Mesh, topo: TopoState, incr,
                      shell_slots: int = 0):
    """EdgeTable via the retained sort: band-merge when the knob is on,
    the state is valid and the dirty set fits the band; otherwise the
    full packed sort (bit-identical to ops/edges.unique_edges either
    way — both feed the SAME shared epilogue).  Consumes ``edirty``.
    Returns (EdgeTable, new TopoState)."""
    from .edges import PACK_LIMIT, unique_edges, unique_edges_from_sorted
    capT = mesh.capT
    n6 = capT * 6
    if mesh.capP > PACK_LIMIT:
        # the merge needs single-int32 packed keys; oversized id spaces
        # keep the exact legacy path (never reached at group shapes)
        et = unique_edges(mesh, shell_slots=shell_slots)
        return et, topo._replace(eok=jnp.zeros((), bool),
                                 edirty=jnp.zeros(capT, bool))
    B = incr_band_width(capT)
    nd = jnp.sum(topo.edirty, dtype=jnp.int32)
    use_band = jnp.asarray(incr) & topo.eok & (nd <= B)

    def _full(_):
        ev = tet_edge_vertices(mesh.tet).reshape(n6, 2)
        a = jnp.minimum(ev[:, 0], ev[:, 1])
        b = jnp.maximum(ev[:, 0], ev[:, 1])
        valid = jnp.repeat(mesh.tmask, 6)
        key = jnp.where(valid, a * mesh.capP + b, _INT32_MAX)
        order = pk.sort_perm(
            (key,), ref=lambda ws: jnp.argsort(ws[0])).astype(jnp.int32)
        return key[order], order

    def _band(_):
        def _reuse(_):
            # zero dirty tets since the last derivation: the retained
            # sort IS the fresh sort (keys depend only on tet/tmask) —
            # the decay-regime steady state, and the generalization of
            # the old all-or-nothing et-cache to adjacency too
            return topo.ekey, topo.eslot

        def _merge(_):
            sd = topo.edirty[topo.eslot // 6]
            dt = jnp.nonzero(topo.edirty, size=B,
                             fill_value=capT)[0].astype(jnp.int32)
            bkey, bslot = edge_band_records(mesh, dt)
            (ks,), order = merge_sorted_band(
                (topo.ekey,), topo.eslot, sd, (bkey,), bslot)
            return ks, order
        return jax.lax.cond(nd == 0, _reuse, _merge, None)

    ks, order = jax.lax.cond(use_band, _band, _full, None)
    et = unique_edges_from_sorted(mesh, order, ks,
                                  shell_slots=shell_slots)
    topo = topo._replace(ekey=ks, eslot=order,
                         eok=jnp.ones((), bool),
                         edirty=jnp.zeros(capT, bool))
    return et, topo


def incr_build_adjacency(mesh: Mesh, topo: TopoState, incr,
                         set_bdy_tags: bool = True):
    """Adjacency (and boundary tags) via the retained face sort — the
    incremental form of ops/adjacency.build_adjacency, re-deriving
    twins only where the band touched (merged face records feed the
    SAME pairing epilogue).  Consumes ``fdirty``.  Returns
    (mesh with adja/ftag, new TopoState)."""
    from .edges import PACK_LIMIT
    from .adjacency import (_face_keys, adjacency_from_records,
                            build_adjacency, face_records_from_sorted)
    capT = mesh.capT
    if mesh.capP > PACK_LIMIT:
        return (build_adjacency(mesh, set_bdy_tags=set_bdy_tags),
                topo._replace(fok=jnp.zeros((), bool),
                              fdirty=jnp.zeros(capT, bool)))
    B = incr_band_width(capT)
    nd = jnp.sum(topo.fdirty, dtype=jnp.int32)
    use_band = jnp.asarray(incr) & topo.fok & (nd <= B)

    def _full(_):
        cols, _, _ = _face_keys(mesh)
        invalid = cols[:, 0] == _INT32_MAX
        w = jnp.where(invalid, _INT32_MAX,
                      cols[:, 1] * mesh.capP + cols[:, 2])
        order = pk.sort_perm(
            (cols[:, 0], w), ref=lambda ws: jnp.lexsort((ws[1], ws[0])),
            nbits=(16, 32)).astype(jnp.int32)
        return cols[order, 0], w[order], order

    def _band(_):
        def _reuse(_):
            return topo.fk0, topo.fkw, topo.fslot

        def _merge(_):
            sd = topo.fdirty[topo.fslot // 4]
            dt = jnp.nonzero(topo.fdirty, size=B,
                             fill_value=capT)[0].astype(jnp.int32)
            bk0, bkw, bslot = face_band_records(mesh, dt)
            (k0, kw), order = merge_sorted_band(
                (topo.fk0, topo.fkw), topo.fslot, sd, (bk0, bkw), bslot)
            return k0, kw, order
        return jax.lax.cond(nd == 0, _reuse, _merge, None)

    k0, kw, order = jax.lax.cond(use_band, _band, _full, None)
    t, f, partner, matched, valid_s = face_records_from_sorted(
        mesh, order, k0, kw)
    mesh = adjacency_from_records(mesh, t, f, partner, matched,
                                  set_bdy_tags=set_bdy_tags)
    topo = topo._replace(fk0=k0, fkw=kw, fslot=order,
                         fok=jnp.ones((), bool),
                         fdirty=jnp.zeros(capT, bool))
    return mesh, topo
