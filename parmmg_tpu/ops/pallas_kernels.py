"""Fused Pallas TPU kernels for the per-entity hot math.

The adaptation waves evaluate metric edge lengths and tet qualities for
every entity every cycle (the vectorized analogue of Mmg's ``MMG5_lenedg``
/ ``MMG5_caltet`` calls inside ``MMG5_mmg3d1_delone``, which the reference
invokes per group at /root/reference/src/libparmmg1.c:737-739).  In pure
XLA each formula materializes a chain of [capE]/[capT] intermediates in
HBM; these kernels fuse the whole formula into one VMEM pass per block —
one HBM read per operand, one write per result, all math on the VPU.

Layout: 1-D entity arrays are padded and viewed as [R, 128] (lane dim =
128), blocked (8, 128) per grid step — the float32 min tile.  Gathers
(vertex coords by index) stay outside in XLA, which already batches them;
the kernels are pure elementwise fusion, so they are exact drop-ins.

On non-TPU backends the same kernels run with ``interpret=True`` in tests
(parity is asserted against the jnp reference in tests/test_pallas.py);
production dispatch (ops/quality.py, ops/edges.py) uses them only on TPU.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..core.constants import ALPHA_TET, EPSD

try:  # pallas is part of jax, but guard exotic builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

_LANE = 128
_SUB = 8
_BLOCK = _SUB * _LANE


def use_pallas() -> bool:
    """Are the Pallas kernels ALLOWED (pallas importable, not disabled)?

    The actual TPU-vs-other choice is made at LOWERING time by
    ``jax.lax.platform_dependent`` at the call sites — deciding from
    ``jax.default_backend()`` here was wrong whenever a TPU plugin is
    registered as the process default while a computation lowers for CPU
    devices (e.g. the multichip dry run on the virtual CPU mesh), which
    crashed with 'Only interpret mode is supported on CPU backend'.
    """
    env = os.environ.get("PARMMG_TPU_PALLAS", "")
    if env == "0":
        return False
    return HAVE_PALLAS


def pallas_forced() -> bool:
    """PARMMG_TPU_PALLAS=1: call the Pallas kernels UNCONDITIONALLY
    (interpret mode off-TPU) — lets CPU verification runs exercise the
    production kernel numerics instead of the jnp formulas."""
    return HAVE_PALLAS and os.environ.get("PARMMG_TPU_PALLAS", "") == "1"


def pallas_score_enabled() -> bool:
    """PARMMG_PALLAS_SCORE gate for the candidate-scoring kernels
    (score_count_pallas / score3_count_pallas): default on — the
    production dispatch in ops/edges.topk_prep is TPU-only either way,
    so CPU runs are unaffected; =0 falls back to the jnp reference on
    every backend."""
    return os.environ.get("PARMMG_PALLAS_SCORE", "") != "0"


def pallas_sort_enabled() -> bool:
    """PARMMG_PALLAS_SORT gate for the radix-sort/segment engine
    (radix_sort_pallas / segment_flags_pallas, dispatched through
    sort_perm / sort_perm_f32 / segment_first below).  Platform-aware
    default like PARMMG_SWAP_FACESORT: unset = on iff the process
    default backend is a TPU (off-TPU the stable jnp argsort/lexsort
    reference is the right program); 1/0 force either way."""
    v = os.environ.get("PARMMG_PALLAS_SORT", "")
    if v == "":
        return jax.default_backend() == "tpu"
    return v != "0"


def _pad_rows(n: int) -> int:
    """Rows of a [R,128] view holding n elements, R a multiple of 8."""
    r = -(-n // _LANE)
    return -(-r // _SUB) * _SUB


def _to_blocks(a: jax.Array, rows: int) -> jax.Array:
    """[n] -> [rows,128] zero-padded float32 view."""
    n = a.shape[0]
    flat = jnp.zeros(rows * _LANE, jnp.float32).at[:n].set(
        a.astype(jnp.float32))
    return flat.reshape(rows, _LANE)


def _from_blocks(b: jax.Array, n: int, dtype) -> jax.Array:
    return b.reshape(-1)[:n].astype(dtype)


# ---------------------------------------------------------------------------
# Edge length (iso): exact log-mean integral of 1/h along the edge
# (numerics identical to ops/quality.py:edge_length_iso)
# ---------------------------------------------------------------------------
def _len_iso_kernel(x0, y0, z0, x1, y1, z1, h0, h1, out):
    dx = x1[:] - x0[:]
    dy = y1[:] - y0[:]
    dz = z1[:] - z0[:]
    d = jnp.sqrt(jnp.maximum(dx * dx + dy * dy + dz * dz, 0.0))
    ha = jnp.maximum(h0[:], EPSD)
    hb = jnp.maximum(h1[:], EPSD)
    r0 = 1.0 / ha
    r1 = 1.0 / hb
    close = jnp.abs(r0 - r1) < 1e-6 * jnp.maximum(r0, r1)
    ratio = jnp.where(close, 1.0, ha / hb)
    logr = jnp.log(jnp.maximum(ratio, EPSD))
    lm = jnp.where(close, 0.5 * (r0 + r1),
                   (r1 - r0) / jnp.where(close, 1.0, logr))
    out[:] = d * lm


def _auto_interpret(interpret: bool | None) -> bool:
    """interpret=None -> run compiled on TPU, interpreted elsewhere."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def edge_length_iso_pallas(p0: jax.Array, p1: jax.Array,
                           h0: jax.Array, h1: jax.Array,
                           interpret: bool | None = None) -> jax.Array:
    """Fused iso edge length. p0,p1: [N,3]; h0,h1: [N] -> [N]."""
    n = p0.shape[0]
    rows = _pad_rows(n)
    args = [_to_blocks(p0[:, 0], rows), _to_blocks(p0[:, 1], rows),
            _to_blocks(p0[:, 2], rows), _to_blocks(p1[:, 0], rows),
            _to_blocks(p1[:, 1], rows), _to_blocks(p1[:, 2], rows),
            _to_blocks(h0, rows), _to_blocks(h1, rows)]
    spec = pl.BlockSpec((_SUB, _LANE), lambda i: (i, 0))
    out = pl.pallas_call(
        _len_iso_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
        grid=(rows // _SUB,),
        in_specs=[spec] * 8,
        out_specs=spec,
        interpret=_auto_interpret(interpret),
    )(*args)
    return _from_blocks(out, n, p0.dtype)


# ---------------------------------------------------------------------------
# Edge length (aniso): endpoint quadratic forms + simpson-like average
# (numerics identical to ops/quality.py:edge_length_ani)
# ---------------------------------------------------------------------------
def _len_ani_kernel(ex, ey, ez, a11, a12, a13, a22, a23, a33,
                    b11, b12, b13, b22, b23, b33, out):
    x, y, z = ex[:], ey[:], ez[:]

    def quad(m11, m12, m13, m22, m23, m33):
        return (m11[:] * x * x + m22[:] * y * y + m33[:] * z * z
                + 2.0 * (m12[:] * x * y + m13[:] * x * z + m23[:] * y * z))

    q0 = quad(a11, a12, a13, a22, a23, a33)
    q1 = quad(b11, b12, b13, b22, b23, b33)
    l0 = jnp.sqrt(jnp.maximum(q0, 0.0))
    l1 = jnp.sqrt(jnp.maximum(q1, 0.0))
    s = jnp.maximum(l0 + l1, EPSD)
    out[:] = (2.0 / 3.0) * (l0 * l0 + l0 * l1 + l1 * l1) / s


def edge_length_ani_pallas(p0: jax.Array, p1: jax.Array,
                           m0: jax.Array, m1: jax.Array,
                           interpret: bool | None = None) -> jax.Array:
    """Fused aniso edge length. p0,p1: [N,3]; m0,m1: [N,6] -> [N]."""
    n = p0.shape[0]
    rows = _pad_rows(n)
    e = p1 - p0
    args = [_to_blocks(e[:, k], rows) for k in range(3)]
    args += [_to_blocks(m0[:, k], rows) for k in range(6)]
    args += [_to_blocks(m1[:, k], rows) for k in range(6)]
    spec = pl.BlockSpec((_SUB, _LANE), lambda i: (i, 0))
    out = pl.pallas_call(
        _len_ani_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
        grid=(rows // _SUB,),
        in_specs=[spec] * 15,
        out_specs=spec,
        interpret=_auto_interpret(interpret),
    )(*args)
    return _from_blocks(out, n, p0.dtype)


# ---------------------------------------------------------------------------
# Candidate scoring + top-k budget prep: the wave selection preamble
# (numerics identical to the jnp reference in ops/edges.py:topk_prep).
# First non-elementwise kernels in this file: the candidate COUNT (the
# defer/budget scalar every wave computes before lax.top_k) is reduced
# across the sequential TPU grid into a (1,1) int32 ref — one pass
# produces both the masked-negated score vector and the reduction.
# ---------------------------------------------------------------------------
def _score_kernel(m, v, out, cnt):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        cnt[0, 0] = 0

    sel = m[:] > 0.0
    out[:] = jnp.where(sel, -v[:], -jnp.inf)
    cnt[0, 0] += jnp.sum(sel.astype(jnp.int32))


def _score_min3_kernel(m, v0, v1, v2, out, cnt):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        cnt[0, 0] = 0

    sel = m[:] > 0.0
    v = jnp.minimum(v0[:], jnp.minimum(v1[:], v2[:]))
    out[:] = jnp.where(sel, -v, -jnp.inf)
    cnt[0, 0] += jnp.sum(sel.astype(jnp.int32))


def score_count_pallas(mask: jax.Array, val: jax.Array,
                       interpret: bool | None = None):
    """Fused top-k prep: (where(mask, -val, -inf) [N], sum(mask) int32)."""
    n = mask.shape[0]
    rows = _pad_rows(n)
    args = [_to_blocks(mask, rows), _to_blocks(val, rows)]
    spec = pl.BlockSpec((_SUB, _LANE), lambda i: (i, 0))
    # every grid step maps the count output to the SAME (1,1) block: the
    # TPU grid is sequential, so += across steps is a legal reduction
    cspec = pl.BlockSpec((1, 1), lambda i: (0, 0))
    out, cnt = pl.pallas_call(
        _score_kernel,
        out_shape=(jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
                   jax.ShapeDtypeStruct((1, 1), jnp.int32)),
        grid=(rows // _SUB,),
        in_specs=[spec] * 2,
        out_specs=(spec, cspec),
        interpret=_auto_interpret(interpret),
    )(*args)
    return _from_blocks(out, n, val.dtype), cnt[0, 0]


def score3_count_pallas(mask: jax.Array, v0: jax.Array, v1: jax.Array,
                        v2: jax.Array, interpret: bool | None = None):
    """Fused shell-score top-k prep: min3 + mask + negate + count.

    (where(mask, -min(v0,min(v1,v2)), -inf) [N], sum(mask) int32) — the
    exact minimum chain order of the swap_edges_wave reference, so f32
    results are bit-identical."""
    n = mask.shape[0]
    rows = _pad_rows(n)
    args = [_to_blocks(mask, rows), _to_blocks(v0, rows),
            _to_blocks(v1, rows), _to_blocks(v2, rows)]
    spec = pl.BlockSpec((_SUB, _LANE), lambda i: (i, 0))
    cspec = pl.BlockSpec((1, 1), lambda i: (0, 0))
    out, cnt = pl.pallas_call(
        _score_min3_kernel,
        out_shape=(jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
                   jax.ShapeDtypeStruct((1, 1), jnp.int32)),
        grid=(rows // _SUB,),
        in_specs=[spec] * 4,
        out_specs=(spec, cspec),
        interpret=_auto_interpret(interpret),
    )(*args)
    return _from_blocks(out, n, v0.dtype), cnt[0, 0]


# ---------------------------------------------------------------------------
# Tet quality: volume + 6 edge lengths + normalization in one pass
# (numerics identical to ops/quality.py:quality_from_points)
# ---------------------------------------------------------------------------
def _qual_kernel(x0, y0, z0, x1, y1, z1, x2, y2, z2, x3, y3, z3,
                 m11, m12, m13, m22, m23, m33, out, *, aniso: bool):
    d1x = x1[:] - x0[:]
    d1y = y1[:] - y0[:]
    d1z = z1[:] - z0[:]
    d2x = x2[:] - x0[:]
    d2y = y2[:] - y0[:]
    d2z = z2[:] - z0[:]
    d3x = x3[:] - x0[:]
    d3y = y3[:] - y0[:]
    d3z = z3[:] - z0[:]
    cx = d2y * d3z - d2z * d3y
    cy = d2z * d3x - d2x * d3z
    cz = d2x * d3y - d2y * d3x
    vol = (d1x * cx + d1y * cy + d1z * cz) / 6.0

    xs = (x0[:], x1[:], x2[:], x3[:])
    ys = (y0[:], y1[:], y2[:], y3[:])
    zs = (z0[:], z1[:], z2[:], z3[:])
    if aniso:
        M11, M12, M13 = m11[:], m12[:], m13[:]
        M22, M23, M33 = m22[:], m23[:], m33[:]
    rap = jnp.zeros_like(vol)
    # IARE order: (0,1)(0,2)(0,3)(1,2)(1,3)(2,3)
    for (i, j) in ((0, 1), (0, 2), (0, 3), (1, 2), (1, 3), (2, 3)):
        ex = xs[j] - xs[i]
        ey = ys[j] - ys[i]
        ez = zs[j] - zs[i]
        if aniso:
            rap = rap + (M11 * ex * ex + M22 * ey * ey + M33 * ez * ez
                         + 2.0 * (M12 * ex * ey + M13 * ex * ez
                                  + M23 * ey * ez))
        else:
            rap = rap + ex * ex + ey * ey + ez * ez
    if aniso:
        det = (M11 * (M22 * M33 - M23 * M23)
               - M12 * (M12 * M33 - M23 * M13)
               + M13 * (M12 * M23 - M22 * M13))
        num = ALPHA_TET * vol * jnp.sqrt(jnp.maximum(det, 0.0))
    else:
        num = ALPHA_TET * vol
    q = num / jnp.maximum(rap, EPSD) ** 1.5
    out[:] = jnp.where(vol > 0, jnp.minimum(q, 1.0), jnp.minimum(q, 0.0))


# ---------------------------------------------------------------------------
# Inclusive int32 prefix sum: the scan backbone of the incremental
# topology merge (ops/topo_incr.merge_sorted_band) — survivor ranks and
# band insertion shifts are both prefix sums over [6*capT]/[4*capT] flag
# vectors.  Within a block, cumsum along lanes then across sublanes; the
# running block total is carried across the sequential grid in SMEM.
# Integer adds are associative, so this is bit-identical to jnp.cumsum.
# ---------------------------------------------------------------------------
def _prefix_kernel(x_ref, o_ref, carry):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        carry[0] = 0

    x = x_ref[:]
    c1 = jnp.cumsum(x, axis=1)                      # within-row inclusive
    rt = c1[:, _LANE - 1:_LANE]                     # [8,1] row totals
    roff = jnp.cumsum(rt, axis=0) - rt              # exclusive row offsets
    o_ref[:] = c1 + roff + carry[0]
    carry[0] = carry[0] + jnp.sum(x)


def _to_blocks_i32(a: jax.Array, rows: int) -> jax.Array:
    """[n] -> [rows,128] zero-padded int32 view."""
    n = a.shape[0]
    flat = jnp.zeros(rows * _LANE, jnp.int32).at[:n].set(
        a.astype(jnp.int32))
    return flat.reshape(rows, _LANE)


def merge_prefix_pallas(x: jax.Array,
                        interpret: bool | None = None) -> jax.Array:
    """Inclusive prefix sum of an int32 vector: [n] -> [n].

    Zero padding at the tail only feeds positions >= n, which are
    discarded, so the result equals ``jnp.cumsum(x)`` exactly."""
    n = x.shape[0]
    rows = _pad_rows(n)
    spec = pl.BlockSpec((_SUB, _LANE), lambda i: (i, 0))
    out = pl.pallas_call(
        _prefix_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), jnp.int32),
        grid=(rows // _SUB,),
        in_specs=[spec],
        out_specs=spec,
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
        interpret=_auto_interpret(interpret),
    )(_to_blocks_i32(x, rows))
    return out.reshape(-1)[:n]


def quality_pallas(p: jax.Array, m6bar: jax.Array | None = None,
                   interpret: bool | None = None) -> jax.Array:
    """Fused tet quality. p: [N,4,3]; m6bar: optional [N,6] mean metric."""
    n = p.shape[0]
    rows = _pad_rows(n)
    args = []
    for c in range(4):
        for k in range(3):
            args.append(_to_blocks(p[:, c, k], rows))
    aniso = m6bar is not None
    if aniso:
        for k in range(6):
            args.append(_to_blocks(m6bar[:, k], rows))
    else:
        zero = jnp.zeros((rows, _LANE), jnp.float32)
        args += [zero] * 6
    spec = pl.BlockSpec((_SUB, _LANE), lambda i: (i, 0))
    out = pl.pallas_call(
        functools.partial(_qual_kernel, aniso=aniso),
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
        grid=(rows // _SUB,),
        in_specs=[spec] * 18,
        out_specs=spec,
        interpret=_auto_interpret(interpret),
    )(*args)
    return _from_blocks(out, n, p.dtype)


# ---------------------------------------------------------------------------
# Radix sort / segment engine (ISSUE 20).  A stable tiled LSD radix sort
# over logical multi-word keys: each word is sorted least-significant
# first in 8-bit digit passes.  One Pallas kernel per pass computes, over
# a sequential grid of (8,128) blocks, the stable within-digit rank of
# every element plus the per-block digit histogram; the scatter offsets
# come from merge_prefix_pallas over the digit-major/block-minor
# flattened histogram (the PR 18 prefix leg, reused).  Stability makes
# the permutation bit-identical to jnp.argsort / jnp.lexsort: LSD radix
# ties resolve by position, exactly like jax's stable comparator sort.
# Gathers/scatters between passes stay in XLA.
# ---------------------------------------------------------------------------
_RADIX = 256
_I32_MAX = 2147483647


def _radix_pass_kernel(d_ref, rank_ref, hist_ref):
    d = d_ref[:]
    oh = (d[:, :, None] == jax.lax.broadcasted_iota(
        jnp.int32, (_SUB, _LANE, _RADIX), 2)).astype(jnp.int32)
    c1 = jnp.cumsum(oh, axis=1)                     # within-row, per digit
    rt = c1[:, _LANE - 1:_LANE, :]                  # [8,1,256] row totals
    roff = jnp.cumsum(rt, axis=0) - rt              # exclusive row offsets
    rank_ref[:] = jnp.sum((c1 + roff) * oh, axis=2) - 1
    hist_ref[:] = jnp.sum(oh, axis=(0, 1))[None, :]


def radix_sort_pallas(words, nbits=None, interpret=None):
    """Stable multi-word sort permutation: argsort of the logical key
    whose major word is words[0].  Each word holds non-negative int32
    values (uint32 digit order == int32 order for those).  ``nbits[j]``
    bounds word j's valid values below 2**nbits[j]; words with
    nbits < 31 get their INT32_MAX tombstones remapped to the in-range
    maximum (order-preserving: every valid value is strictly smaller),
    cutting digit passes.  Tail padding uses 0xFFFFFFFF, which sorts
    after every key; ties against real 0xFFFFFFFF keys keep real rows
    first by stability, so the returned ``order[:n]`` is exact."""
    n = words[0].shape[0]
    rows = _pad_rows(n)
    npad = rows * _LANE
    nblocks = rows // _SUB
    interp = _auto_interpret(interpret)
    if nbits is None:
        nbits = (32,) * len(words)
    order = jnp.arange(npad, dtype=jnp.int32)
    pos_blk = jnp.arange(npad, dtype=jnp.int32) // _BLOCK
    spec = pl.BlockSpec((_SUB, _LANE), lambda i: (i, 0))
    hspec = pl.BlockSpec((1, _RADIX), lambda i: (i, 0))
    for w, bits in list(zip(words, nbits))[::-1]:   # LSD: minor word first
        wu = w.astype(jnp.uint32)
        if bits < 31:
            wu = jnp.where(wu == jnp.uint32(_I32_MAX),
                           jnp.uint32((1 << bits) - 1), wu)
        wp = jnp.full(npad, jnp.uint32(0xFFFFFFFF)).at[:n].set(wu)
        for shift in range(0, bits, 8):
            g = wp[order]
            d = ((g >> jnp.uint32(shift)) & jnp.uint32(0xFF)).astype(jnp.int32)
            rank, hist = pl.pallas_call(
                _radix_pass_kernel,
                out_shape=(jax.ShapeDtypeStruct((rows, _LANE), jnp.int32),
                           jax.ShapeDtypeStruct((nblocks, _RADIX), jnp.int32)),
                grid=(nblocks,),
                in_specs=[spec],
                out_specs=(spec, hspec),
                interpret=interp,
            )(d.reshape(rows, _LANE))
            flat = hist.T.reshape(-1)               # digit-major, block-minor
            excl = merge_prefix_pallas(flat, interpret=interpret) - flat
            dest = excl[d * nblocks + pos_blk] + rank.reshape(-1)
            order = jnp.zeros(npad, jnp.int32).at[dest].set(
                order, unique_indices=True)
    return order[:n]


def f32_sort_u32(x: jax.Array) -> jax.Array:
    """Map float32 to uint32 so radix digit order mirrors jax's stable
    sort comparator exactly: -0.0 == +0.0 (ties by position), all NaNs
    equal and after +inf.  NaN maps to 0xFFFFFFFF, colliding with tail
    padding — stability keeps real rows ahead of pads, so order[:n] is
    still exact."""
    x = jnp.where(x == 0.0, 0.0, x)
    b = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    u = jnp.where(b >> 31 != 0, ~b, b | jnp.uint32(0x80000000))
    return jnp.where(jnp.isnan(x), jnp.uint32(0xFFFFFFFF), u)


def _seg_kernel(*refs, nw):
    word_refs = refs[:nw]
    o_ref = refs[nw]
    carry = refs[nw + 1]
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        for j in range(nw):
            carry[j] = 0

    r_io = jax.lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 0)
    l_io = jax.lax.broadcasted_iota(jnp.int32, (_SUB, _LANE), 1)
    neq = jnp.zeros((_SUB, _LANE), jnp.int32)
    for j in range(nw):
        x = word_refs[j][:]
        rowlast = x[:, _LANE - 1:_LANE]
        shifted = jnp.concatenate(
            [jnp.full((1, 1), carry[j], jnp.int32), rowlast[:-1]], axis=0)
        prev = jnp.concatenate([shifted, x[:, :-1]], axis=1)
        neq = neq | (x != prev).astype(jnp.int32)
        carry[j] = jnp.sum(
            jnp.where((r_io == _SUB - 1) & (l_io == _LANE - 1), x, 0))
    first0 = ((i == 0) & (r_io == 0) & (l_io == 0)).astype(jnp.int32)
    o_ref[:] = neq | first0


def segment_flags_pallas(words, interpret=None):
    """Boolean segment-start flags over sorted columns: first[i] is True
    iff i == 0 or any words[j][i] != words[j][i-1].  Cross-block
    previous elements ride an SMEM carry.  Zero tail padding only feeds
    positions >= n, which are discarded."""
    n = words[0].shape[0]
    rows = _pad_rows(n)
    nw = len(words)
    spec = pl.BlockSpec((_SUB, _LANE), lambda i: (i, 0))
    out = pl.pallas_call(
        functools.partial(_seg_kernel, nw=nw),
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), jnp.int32),
        grid=(rows // _SUB,),
        in_specs=[spec] * nw,
        out_specs=spec,
        scratch_shapes=[pltpu.SMEM((nw,), jnp.int32)],
        interpret=_auto_interpret(interpret),
    )(*[_to_blocks_i32(w, rows) for w in words])
    return out.reshape(-1)[:n].astype(bool)


# -- dispatch helpers --------------------------------------------------------
def _sort_dispatch_on() -> bool:
    return HAVE_PALLAS and use_pallas() and pallas_sort_enabled()


def sort_perm(words, ref, nbits=None):
    """Sort-permutation dispatcher.  ``words`` is a tuple of int32
    columns, major first; ``ref`` is the stable jnp reference taking the
    same tuple.  TPU gets the radix engine; everywhere else lowers only
    the reference (identical HLO knob-on/off), except under forced
    Pallas where the interpreter runs for parity tests."""
    from ..utils.jaxcompat import platform_dependent
    words = tuple(words)
    if not _sort_dispatch_on():
        return ref(words)
    krn = functools.partial(radix_sort_pallas, nbits=nbits, interpret=False)
    if pallas_forced():
        default = functools.partial(radix_sort_pallas, nbits=nbits,
                                    interpret=True)
    else:
        default = ref
    return platform_dependent(words, tpu=krn, default=default)


def sort_perm_f32(x, ref):
    """Float argsort dispatcher: the Pallas branch radix-sorts the
    order-preserving uint32 image of x (f32_sort_u32); the reference
    branch runs the stable jnp argsort on x itself."""
    from ..utils.jaxcompat import platform_dependent
    if not _sort_dispatch_on():
        return ref(x)

    def krn(v, interpret):
        u = f32_sort_u32(v).astype(jnp.int32)
        return radix_sort_pallas((u,), interpret=interpret)

    if pallas_forced():
        default = functools.partial(krn, interpret=True)
    else:
        default = ref
    return platform_dependent(x, tpu=functools.partial(krn, interpret=False),
                              default=default)


def segment_first(words):
    """Segment-start dispatcher over sorted columns; the reference is the
    canonical concat-of-neighbour-compares the call sites used inline."""
    from ..utils.jaxcompat import platform_dependent
    words = tuple(words)

    def ref(ws):
        neq = ws[0][1:] != ws[0][:-1]
        for w in ws[1:]:
            neq = neq | (w[1:] != w[:-1])
        return jnp.concatenate([jnp.array([True]), neq])

    if not _sort_dispatch_on():
        return ref(words)
    krn = functools.partial(segment_flags_pallas, interpret=False)
    if pallas_forced():
        default = functools.partial(segment_flags_pallas, interpret=True)
    else:
        default = ref
    return platform_dependent(words, tpu=krn, default=default)


def pallas_sort_sites():
    """Static site list the sort engine would dispatch on this backend —
    empty unless the knob is on and the backend is TPU (or Pallas is
    forced into the interpreter).  Feeds the bench artifact."""
    if not _sort_dispatch_on():
        return []
    if jax.default_backend() != "tpu" and not pallas_forced():
        return []
    return ["unique_edges_sort", "unique_edges_segment", "priority_sort",
            "face_sort", "band_sort"]
