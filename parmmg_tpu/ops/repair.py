"""Sequential last-resort repair of pathological sliver clusters (host).

The batched independent-set waves (ops/adapt.py) fix 99.9+% of bad
elements, but tangled clusters — stacks of near-flat tets where every
single parallel move inverts a neighbor — deadlock them: each candidate
is vetoed GIVEN the others' stationarity, while a sequential pass
resolves the chain one op at a time.  The reference remesher is fully
sequential (MMG3D_opttyp cascades collapse/swap/move per element,
mmg3d/opttyp.c via libparmmg1.c), so this pass reproduces exactly that
freedom for the tail: host numpy, worst-first, ball-local, a few dozen
tets at most.

Scope guard: only cavities with no face/edge tags are touched (tag
routing stays the batched kernels' job); frozen vertices are respected.
"""
from __future__ import annotations

import collections

import numpy as np

from ..core.constants import (
    IARE, IDIR, MG_BDY, MG_CRN, MG_GEO, MG_NOM, MG_PARBDY, MG_REF, MG_REQ)

_FROZEN_V = MG_REQ | MG_CRN | MG_PARBDY | MG_NOM


def _qual(p):
    """Euclidean tet quality (vol / sum |e|^2 ^1.5, ALPHA-normalized) for
    a [*,4,3] array — matches ops.quality.quality_from_points(iso)."""
    d1 = p[..., 1, :] - p[..., 0, :]
    d2 = p[..., 2, :] - p[..., 0, :]
    d3 = p[..., 3, :] - p[..., 0, :]
    vol = np.einsum("...i,...i->...", d1, np.cross(d2, d3)) / 6.0
    ee = 0.0
    for a in range(4):
        for b in range(a + 1, 4):
            e = p[..., b, :] - p[..., a, :]
            ee = ee + np.einsum("...i,...i->...", e, e)
    den = np.maximum(ee, 1e-30) ** 1.5
    return 8.48528137423857 * 6.0 * vol / den          # ALPHA_TET * 6V


def sequential_repair(vert, tet, tmask, vtag, vmask, tref, ftag, etag,
                      fref, q_floor: float = 1e-3, max_rounds: int = 4,
                      allow_collapse: bool = True, allow_swap: bool = True,
                      allow_move: bool = True):
    """Repair tets with quality < q_floor by sequential local ops.

    Operates on numpy copies; returns
    (vert, tet, tmask, vmask, tref, ftag, etag, fref, nfixed).
    Ops per bad tet, in order of preference: collapse an edge (both
    directions), 2-3/3-2 swap, relocate a free vertex (damped centroid
    line search) — each validated on the CURRENT state: no inversion
    anywhere in the touched ball and strict improvement of the cavity
    minimum.  Every touched cavity must be fully untagged (tag routing
    stays the batched kernels' job), so rewritten/resurrected slots carry
    all-zero face/edge tags by construction.
    """
    vert = np.array(vert, copy=True)
    tet = np.array(tet, copy=True)
    tmask = np.array(tmask, copy=True)
    vmask = np.array(vmask, copy=True)
    tref = np.array(tref, copy=True)
    ftag = np.array(ftag, copy=True)
    etag = np.array(etag, copy=True)
    fref = np.array(fref, copy=True)
    inc = collections.defaultdict(set)
    for t_i in np.where(tmask)[0]:
        for v in tet[t_i]:
            inc[int(v)].add(int(t_i))

    def ball(v):
        return [t for t in inc[v] if tmask[t]]

    def ball_q(ts):
        if not ts:
            return np.inf
        return float(_qual(vert[tet[np.asarray(ts)]]).min())

    _HARD_TAGS = MG_REQ | MG_PARBDY | MG_NOM

    def _edge_slot(t, a, b):
        tv = tet[t]
        for e, (i, j) in enumerate(IARE):
            u, v = int(tv[i]), int(tv[j])
            if (u == a and v == b) or (u == b and v == a):
                return e
        return -1

    def try_collapse(rm, kp):
        """Contract rm -> kp.  Interior vertices need a fully-untagged
        cavity (as before); a plain MG_BDY vertex may now slide along a
        boundary edge onto another boundary vertex (Mmg chkcol_bdy rule)
        with SEQUENTIAL tag routing: dying tets' tagged faces/edges are
        re-keyed (rm->kp) and OR-ed onto the surviving slots — the
        one-at-a-time version of collapse_wave's keyed joins.  This is
        the boundary-cap fix: the flattest surviving clusters sit ON the
        surface where the old all-untagged guard made them untouchable.
        """
        if vtag[rm] & (_FROZEN_V | MG_GEO | MG_REF):
            return False
        on_bdy = bool(vtag[rm] & MG_BDY)
        brm = ball(rm)
        if not brm:
            return False
        if on_bdy:
            if not (vtag[kp] & MG_BDY):
                return False
            # the contraction edge must itself be a boundary edge
            e_bdy = False
            for t in brm:
                e = _edge_slot(t, rm, kp)
                if e >= 0 and (etag[t][e] & MG_BDY):
                    e_bdy = True
                    break
            if not e_bdy:
                return False
            # restriction applies to entities INCIDENT TO rm (the Mmg
            # chkcol_bdy scope): hard-frozen faces/edges at rm, or a
            # feature line (GEO/REF edge) through rm, refuse; peripheral
            # tags elsewhere in the cavity are fine — dying tets' tags
            # are routed by the keyed join below
            for t in brm:
                tv_t = tet[t]
                for f in range(4):
                    if int(tv_t[f]) != rm and \
                            (ftag[t][f] & _HARD_TAGS):
                        return False     # face containing rm hard-frozen
                for e, (i, j) in enumerate(IARE):
                    if rm in (int(tv_t[i]), int(tv_t[j])) and \
                            (etag[t][e] & (_HARD_TAGS | MG_GEO | MG_REF)):
                        return False
        else:
            if not all(_untagged(t) for t in brm):
                return False
        dying = [t for t in brm if kp in tet[t]]
        moved = [t for t in brm if kp not in tet[t]]
        old_min = ball_q(brm)
        rows = []
        for t in moved:
            row = np.where(tet[t] == rm, kp, tet[t])
            rows.append(row)
        if rows:
            q_new = _qual(vert[np.asarray(rows)])
            if (q_new <= 0).any() or q_new.min() <= old_min:
                return False
        if on_bdy:
            # surface fold-over guard: boundary faces that contain rm
            # must keep their orientation after the move
            for t, row in zip(moved, rows):
                for f in range(4):
                    if not (ftag[t][f] & MG_BDY):
                        continue
                    tri = [int(tet[t][i]) for i in IDIR[f]]
                    if rm not in tri:
                        continue
                    tri_new = [kp if v == rm else v for v in tri]
                    n_old = np.cross(vert[tri[1]] - vert[tri[0]],
                                     vert[tri[2]] - vert[tri[0]])
                    n_new = np.cross(vert[tri_new[1]] - vert[tri_new[0]],
                                     vert[tri_new[2]] - vert[tri_new[0]])
                    if np.dot(n_old, n_new) <= 0:
                        return False
        # ---- tag routing from dying tets (sequential keyed join) ----
        def holders(v):
            """Tets that will contain v AFTER the remap rm->kp."""
            s = set(inc[v])
            if v == kp:
                s |= inc[rm]
            return s

        for t in dying:
            for f in range(4):
                if not (ftag[t][f] or fref[t][f]):
                    continue
                tri = [int(tet[t][i]) for i in IDIR[f]]
                key = frozenset(kp if v == rm else v for v in tri)
                if len(key) < 3:
                    continue             # face degenerates with the tet
                ks = list(key)
                cands = (holders(ks[0]) & holders(ks[1]) & holders(ks[2]))
                for t2 in cands:
                    if not tmask[t2] or t2 in dying:
                        continue
                    tv2 = [kp if int(v) == rm else int(v)
                           for v in tet[t2]]
                    for f2 in range(4):
                        if frozenset(tv2[i] for i in IDIR[f2]) == key:
                            ftag[t2][f2] |= ftag[t][f]
                            if fref[t2][f2] == 0:
                                fref[t2][f2] = fref[t][f]
            for e, (i, j) in enumerate(IARE):
                if not etag[t][e]:
                    continue
                a2 = kp if int(tet[t][i]) == rm else int(tet[t][i])
                b2 = kp if int(tet[t][j]) == rm else int(tet[t][j])
                if a2 == b2:
                    continue             # the contracted edge itself
                for t2 in (holders(a2) & holders(b2)):
                    if not tmask[t2] or t2 in dying:
                        continue
                    tv2 = [kp if int(v) == rm else int(v)
                           for v in tet[t2]]
                    for e2, (i2, j2) in enumerate(IARE):
                        u, v = tv2[i2], tv2[j2]
                        if (u == a2 and v == b2) or (u == b2 and v == a2):
                            etag[t2][e2] |= etag[t][e]
        for t in dying:
            tmask[t] = False
        for t, row in zip(moved, rows):
            tet[t] = row
            inc[int(kp)].add(t)
        vmask[rm] = False           # no orphan live vertices
        return True

    def _untagged(t):
        return not (ftag[t].any() or etag[t].any())

    def try_swap23(t):
        """2-3 swap on any interior untagged face of t."""
        if not _untagged(t):
            return False
        tv = tet[t]
        for f in range(4):
            tri = [int(tv[i]) for i in IDIR[f]]
            commons = (inc[tri[0]] & inc[tri[1]] & inc[tri[2]])
            commons = [c for c in commons if tmask[c] and c != t]
            if len(commons) != 1:
                continue
            t2 = commons[0]
            if not _untagged(t2):
                continue
            a = int(tv[f])
            b = int(next(v for v in tet[t2] if v not in tri))
            p, q, r = tri
            cav = [t, t2]
            old_min = ball_q(cav)
            rows = np.array([[p, q, a, b], [q, r, a, b], [r, p, a, b]])
            qn = _qual(vert[rows])
            if (qn <= 0).any():                  # try the mirrored fan
                rows = rows[:, [0, 1, 3, 2]]
                qn = _qual(vert[rows])
            if (qn <= 0).any() or qn.min() <= old_min * 1.02:
                continue
            dead = np.where(~tmask)[0]
            if not len(dead):
                continue
            free = int(dead[0])
            tet[t] = rows[0]
            tet[t2] = rows[1]
            tet[free] = rows[2]
            tmask[free] = True
            # the resurrected slot must not inherit a prior tenant's tags
            ftag[free] = 0
            etag[free] = 0
            fref[free] = 0
            tref[free] = tref[t]
            for row, ti in ((rows[0], t), (rows[1], t2), (rows[2], free)):
                for v in row:
                    inc[int(v)].add(int(ti))
            return True
        return False

    def try_swap32(t):
        """3-2 swap on any interior untagged 3-shell edge of t."""
        if not _untagged(t):
            return False
        tv = tet[t]
        for i, j in IARE:
            a, b = int(tv[i]), int(tv[j])
            shell = [c for c in (inc[a] & inc[b]) if tmask[c]]
            if len(shell) != 3:
                continue
            if not all(_untagged(c) for c in shell):
                continue
            ring = []
            for c in shell:
                ring += [int(v) for v in tet[c] if v != a and v != b]
            ring = list(dict.fromkeys(ring))
            if len(ring) != 3:
                continue
            p, q, r = ring
            old_min = ball_q(shell)
            for newa, newb in (([p, q, r, a], [q, p, r, b]),
                               ([q, p, r, a], [p, q, r, b])):
                rows = np.array([newa, newb])
                qn = _qual(vert[rows])
                if (qn > 0).all() and qn.min() > old_min * 1.02:
                    t1, t2, t3 = shell
                    tet[t1] = rows[0]
                    tet[t2] = rows[1]
                    tmask[t3] = False
                    for row, ti in ((rows[0], t1), (rows[1], t2)):
                        for v in row:
                            inc[int(v)].add(int(ti))
                    return True
        return False

    def try_relocate(v):
        if vtag[v] & (_FROZEN_V | MG_BDY | MG_GEO | MG_REF):
            return False
        bv = ball(v)
        if not bv:
            return False
        rows = tet[np.asarray(bv)]
        old_min = float(_qual(vert[rows]).min())
        cent = vert[rows].mean(axis=(0, 1))
        p0 = vert[v].copy()
        for step in (1.0, 0.5, 0.25, 0.1):
            vert[v] = p0 + step * (cent - p0)
            q = _qual(vert[rows])
            if (q > 0).all() and q.min() > old_min * 1.02:
                return True
            vert[v] = p0
        return False

    nfixed = 0
    if not (allow_collapse or allow_swap or allow_move):
        max_rounds = 0
    for _ in range(max_rounds):
        live = np.where(tmask)[0]
        if not len(live):
            break
        q = _qual(vert[tet[live]])
        bad = live[q < q_floor]
        if not len(bad):
            break
        order = bad[np.argsort(q[q < q_floor])]
        progressed = False
        for t in order:
            if not tmask[t]:
                continue
            if _qual(vert[tet[t]][None])[0] >= q_floor:
                continue
            done = False
            if allow_collapse:
                # edges sorted by length: shortest first (the cap)
                pts = vert[tet[t]]
                el = [(np.linalg.norm(pts[j] - pts[i]), i, j)
                      for i, j in IARE]
                for _d, i, j in sorted(el):
                    a, b = int(tet[t][i]), int(tet[t][j])
                    if try_collapse(a, b) or try_collapse(b, a):
                        done = True
                        break
            if not done and allow_swap:
                done = try_swap23(t) or try_swap32(t)
            if not done and allow_move:
                for k in range(4):
                    if try_relocate(int(tet[t][k])):
                        done = True
                        break
            if done:
                nfixed += 1
                progressed = True
        if not progressed:
            break
    return vert, tet, tmask, vmask, tref, ftag, etag, fref, nfixed


# repair-tail quality probe: ONE module-level jitted object + ledger
# registration (compile governor).  The eager quality_from_points call
# this replaces re-dispatched a dozen kernels per repair_mesh call —
# the tail runs once per pass in the driver and scale workers, so the
# probe is a steady-state entry point like the other governed tails.
# No variant budget: the probe's static shape tracks whatever mesh caps
# the caller holds (merged meshes regrow), which is caller-driven churn
# the ledger should SHOW, not gate.
_QPROBE = []


def _quality_probe():
    if not _QPROBE:
        import jax
        from ..utils.compilecache import governed
        from .quality import quality_from_points

        @governed("repair.quality_probe")
        @jax.jit
        def probe(vert, tet):
            return quality_from_points(vert[tet])

        _QPROBE.append(probe)
    return _QPROBE[0]


def repair_mesh(mesh, met, q_floor: float = 1e-3,
                allow_collapse: bool = True, allow_swap: bool = True,
                allow_move: bool = True):
    """Wrapper: run sequential_repair on a device Mesh, rebuild tags via
    adjacency.  Cheap no-op when nothing is below the floor."""
    import dataclasses
    import jax.numpy as jnp
    from .adjacency import build_adjacency, boundary_edge_tags

    q = np.asarray(_quality_probe()(mesh.vert, mesh.tet))
    tm = np.asarray(mesh.tmask)
    if not (tm & (q < q_floor)).any():
        return mesh, 0
    (vert, tet, tmask, vmask, tref, ftag, etag, fref,
     nfixed) = sequential_repair(
        np.asarray(mesh.vert), np.asarray(mesh.tet), tm,
        np.asarray(mesh.vtag), np.asarray(mesh.vmask),
        np.asarray(mesh.tref), np.asarray(mesh.ftag),
        np.asarray(mesh.etag), np.asarray(mesh.fref), q_floor=q_floor,
        allow_collapse=allow_collapse, allow_swap=allow_swap,
        allow_move=allow_move)
    if nfixed == 0:
        return mesh, 0
    live = np.where(tmask)[0]
    nelem = int(live.max()) + 1 if len(live) else 0
    out = dataclasses.replace(
        mesh, vert=jnp.asarray(vert), tet=jnp.asarray(tet),
        tmask=jnp.asarray(tmask), vmask=jnp.asarray(vmask),
        tref=jnp.asarray(tref), ftag=jnp.asarray(ftag),
        etag=jnp.asarray(etag), fref=jnp.asarray(fref),
        nelem=jnp.asarray(max(nelem, int(mesh.nelem)), jnp.int32))
    out = boundary_edge_tags(build_adjacency(out))
    return out, nfixed
