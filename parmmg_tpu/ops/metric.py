"""Metric synthesis, clamping and gradation.

Reference semantics: Mmg computes a size map for ``-optim`` (local mean edge
length) / ``-hsiz`` (constant), clamps to [hmin, hmax], and enforces size
gradation ``-hgrad`` (bounded relative growth along edges).  ParMmg forwards
these per group (API_functions_pmmg.c:531-830) and rejects some combos in
``PMMG_check_inputData`` (libparmmg.c:55-101).  Here each is a vectorized
kernel over the whole vertex array; gradation is an iterated scatter-min
relaxation (a parallel fixpoint instead of Mmg's sequential edge sweeps).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.mesh import Mesh, tet_edge_vertices
from ..core.constants import EPSD, HGRAD_DEFAULT


def metric_hsiz(mesh: Mesh, hsiz: float) -> jax.Array:
    """Constant target size (Mmg -hsiz)."""
    return jnp.full(mesh.capP, hsiz, mesh.vert.dtype)


def metric_optim(mesh: Mesh) -> jax.Array:
    """Local mean incident-edge length per vertex (Mmg -optim).

    Preserves the existing sizing of the mesh: adaptation then only
    improves quality without refining/coarsening on average.
    """
    ev = tet_edge_vertices(mesh.tet).reshape(-1, 2)
    p0 = mesh.vert[ev[:, 0]]
    p1 = mesh.vert[ev[:, 1]]
    l = jnp.sqrt(jnp.maximum(jnp.sum((p1 - p0) ** 2, -1), 0.0))
    w = jnp.repeat(mesh.tmask, 6).astype(mesh.vert.dtype)
    acc = jnp.zeros(mesh.capP + 1, mesh.vert.dtype)
    cnt = jnp.zeros(mesh.capP + 1, mesh.vert.dtype)
    for side in range(2):
        idx = jnp.where(jnp.repeat(mesh.tmask, 6), ev[:, side], mesh.capP)
        acc = acc.at[idx].add(l * w, mode="drop")
        cnt = cnt.at[idx].add(w, mode="drop")
    h = acc[:-1] / jnp.maximum(cnt[:-1], 1.0)
    return jnp.where(mesh.vmask, h, 1.0)


def hausd_metric_bound(mesh: Mesh, met, hausd: float, hmin: float):
    """Bound boundary sizes by the surface approximation tolerance.

    The Mmg ``defsiz`` route for -hausd: a chord of length h on a surface
    of curvature kappa deviates by ~ h^2 * kappa / 8, so keeping the
    deviation under hausd requires h <= sqrt(8 * hausd / kappa).  Vertex
    curvature is estimated from the spread of boundary-vertex normals
    over incident regular boundary edges (ridge/corner endpoints are
    excluded — their normals are multivalued and ridges are preserved by
    tags, not size).  Iso metric only; host-side, once per run.
    """
    import numpy as np
    from ..core.constants import (
        IDIR, MG_BDY, MG_CRN, MG_GEO, MG_NOM, MG_PARBDY, MG_REQ)
    from .analysis import boundary_vertex_normals
    if met.ndim != 1:
        return met                           # aniso: not yet bounded
    vn = np.asarray(boundary_vertex_normals(mesh))
    tm = np.asarray(mesh.tmask)
    tet = np.asarray(mesh.tet)[tm]
    ftag = np.asarray(mesh.ftag)[tm]
    vtag = np.asarray(mesh.vtag)
    capP = mesh.capP
    tris = []
    for f in range(4):
        sel = (ftag[:, f] & MG_BDY) != 0
        if sel.any():
            tris.append(tet[sel][:, IDIR[f]])
    if not tris:
        return met
    tris = np.concatenate(tris)
    ed = np.concatenate([tris[:, [0, 1]], tris[:, [1, 2]],
                         tris[:, [0, 2]]])
    sing = MG_GEO | MG_CRN | MG_REQ | MG_PARBDY | MG_NOM
    ok = ((vtag[ed[:, 0]] & sing) == 0) & ((vtag[ed[:, 1]] & sing) == 0)
    ed = ed[ok]
    if not len(ed):
        return met
    vh = np.asarray(mesh.vert)
    dn = np.linalg.norm(vn[ed[:, 0]] - vn[ed[:, 1]], axis=1)
    dl = np.linalg.norm(vh[ed[:, 0]] - vh[ed[:, 1]], axis=1)
    kappa = dn / np.maximum(dl, 1e-30)
    kv = np.zeros(capP)
    np.maximum.at(kv, ed[:, 0], kappa)
    np.maximum.at(kv, ed[:, 1], kappa)
    with np.errstate(divide="ignore"):
        h_geom = np.sqrt(8.0 * hausd / np.maximum(kv, 1e-30))
    h_geom = np.maximum(np.where(kv > 1e-12, h_geom, np.inf), hmin)
    return jnp.minimum(met, jnp.asarray(h_geom, met.dtype))


def clamp_metric(met: jax.Array, hmin: float, hmax: float) -> jax.Array:
    if met.ndim == 1:
        return jnp.clip(met, hmin, hmax)
    # aniso: clamp eigenvalues of each tensor to [1/hmax^2, 1/hmin^2]
    from .quality import unpack_sym
    M = unpack_sym(met)
    w, V = jnp.linalg.eigh(M)
    w = jnp.clip(w, 1.0 / hmax**2, 1.0 / hmin**2)
    Mc = jnp.einsum("...ij,...j,...kj->...ik", V, w, V)
    return jnp.stack([Mc[..., 0, 0], Mc[..., 0, 1], Mc[..., 0, 2],
                      Mc[..., 1, 1], Mc[..., 1, 2], Mc[..., 2, 2]], -1)


def gradation(mesh: Mesh, met: jax.Array, hgrad: float = HGRAD_DEFAULT,
              max_sweeps: int = 20) -> jax.Array:
    """Bound relative size growth along edges (Mmg -hgrad, iso only).

    Rule (Mmg MMG5_grad2met flavor): along an edge of euclidean length d,
    h_b may not exceed h_a + (hgrad - 1) * d.  Enforced by Jacobi
    scatter-min sweeps until stationary (bounded by max_sweeps); each sweep
    is one fused gather/scatter — the parallel form of Mmg's sequential
    edge relaxation.
    """
    if met.ndim != 1:
        return met  # aniso gradation is a later milestone
    ev = tet_edge_vertices(mesh.tet).reshape(-1, 2)
    valid = jnp.repeat(mesh.tmask, 6)
    p0 = mesh.vert[ev[:, 0]]
    p1 = mesh.vert[ev[:, 1]]
    d = jnp.sqrt(jnp.maximum(jnp.sum((p1 - p0) ** 2, -1), 0.0))
    slope = hgrad - 1.0

    def sweep(met, _):
        h0 = met[ev[:, 0]]
        h1 = met[ev[:, 1]]
        cap0 = h1 + slope * d                 # bound on h at endpoint 0
        cap1 = h0 + slope * d
        out = met
        big = jnp.inf
        lim = jnp.full(met.shape[0] + 1, big, met.dtype)
        lim = lim.at[jnp.where(valid, ev[:, 0], met.shape[0])].min(
            cap0, mode="drop")
        lim = lim.at[jnp.where(valid, ev[:, 1], met.shape[0])].min(
            cap1, mode="drop")
        return jnp.minimum(met, lim[:-1]), None

    met, _ = jax.lax.scan(sweep, met, None, length=max_sweeps)
    return met
