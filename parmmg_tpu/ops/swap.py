"""Batched topology swaps (3-2 edge swap, 2-3 face swap, 2-2 boundary swap).

Reference behavior: Mmg's ``MMG5_swpmsh``/``MMG3D_swpmshcpy`` remove bad
configurations by re-triangulating small cavities around an edge or face
when the worst quality strictly improves; boundary edges are swapped by
``MMG5_swpbdy`` after ``MMG5_chkswpbdy`` validates the surface retiling;
the frozen-interface contract (tag_pmmg.c:39-124) keeps parallel entities
untouched.  Improvement gate: new worst quality > SWAP_GAIN * old worst
(Mmg uses 1.053).
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.mesh import Mesh
from ..core.constants import (
    EPSD, QUAL_FLOOR, MG_BDY, MG_GEO, MG_NOM, MG_OPNBDY, MG_PARBDY,
    MG_REF, MG_REQ)
from .edges import (unique_edges, claim_channels, claim_shells, NEG_INF,
                    PRI_MIN)
from .quality import quality_from_points

SWAP_GAIN = 1.053

# local edge index for a corner pair (i, j) — inverse of IARE
_EDGE_OF = np.zeros((4, 4), np.int32)
for _e, (_i, _j) in enumerate([[0, 1], [0, 2], [0, 3],
                               [1, 2], [1, 3], [2, 3]]):
    _EDGE_OF[_i, _j] = _EDGE_OF[_j, _i] = _e


class SwapResult(NamedTuple):
    mesh: Mesh
    nswap: jax.Array
    deferred: jax.Array = None  # scalar bool: candidates exceeded the
    #                 top-K budget (see ops/active.py worklist invariant)


def _met6(met):
    """Aniso: packed tensors; iso: None — quality is evaluated in
    Euclidean space exactly like Mmg's ``MMG5_caltet_iso`` (the constant
    local scaling cancels in Q), which skips the [*,4,6] metric gathers
    that dominate swap cost on TPU."""
    return None if met.ndim == 1 else met


def swap_facesort_enabled() -> bool:
    """PARMMG_SWAP_FACESORT (default on): pair swap23 directly off the
    face-sort records instead of materializing ``adja`` with a full
    ``build_adjacency`` between the edge-swap and 2-3 waves — swap23 is
    the only cycle-interior adja reader, and the facesort pairing is
    bit-identical (see _pair_fields_facesort).  TRACE-TIME read: both
    paths produce the same bits, so a stale jit cache entry is only a
    perf choice, never a correctness one.

    Platform-aware default (like the Pallas scoring dispatch): unset
    means on for TPU, off elsewhere — the CPU backend's sort is slow
    enough that the face re-sort costs more than the adja rebuild it
    replaces (measured ~+7% s/cycle on the grouped bench), while on
    TPU the sort amortizes and the rebuild's gather/compare does not.
    ``1``/``0`` force the path on any backend (the parity tests and
    the ledger gate force both arms on CPU)."""
    import os
    v = os.environ.get("PARMMG_SWAP_FACESORT", "")
    if v == "":
        import jax
        return jax.default_backend() == "tpu"
    return v != "0"


def swap_edges_wave(mesh: Mesh, met: jax.Array, enable32: bool = True,
                    enable22: bool = True,
                    flat_tol: float = 1e-5,
                    hausd: float | None = None,
                    budget_div: int = 8,
                    vact: jax.Array | None = None,
                    wwin: jax.Array | None = None) -> SwapResult:
    """Combined edge-swap wave: 3-2 interior + 2-2 boundary, ONE pass.

    Both swaps share the same cavity shape — edge (a,b) is replaced by two
    tets A=(x0,x1,x2,a), B=(x0,x1,x2,b) overwriting the first two shell
    slots — so they share one edge table, one batched position lookup, one
    stacked quality call and one claim resolution (each distinct XLA op
    carries a multi-ms fixed cost on this device, scripts/tpu_microbench.py).

    3-2 (Mmg ``MMG5_swap``): interior untagged edge with a 3-tet shell
    ring (p,q,r); (x0,x1,x2)=(p,q,r); the third shell slot dies.  The
    cavity MAY touch the boundary elsewhere: every exterior face/edge
    survives in A/B and its tags are routed through.

    2-2 (Mmg ``MMG5_swpbdy``/``chkswpbdy``): regular boundary edge whose
    2-tet shell covers a planar boundary quad (a,x0,b,x1) with shared
    interior vertex x2=c; the surface diagonal flips to (x0,x1) —
    surface-exact within ``flat_tol`` of the local scale (the hausd
    analogue for piecewise-flat geometry); both gates carry float32 noise
    floors (cross products of coordinate differences err with
    eps32*|coords|, which swamps a purely relative tolerance on exactly
    the thin quads this swap targets).

    Top-K compaction (the wave's cost lever, scripts/wave_time.py): the
    cheap candidacy masks are computed at full [6*capT] width, then only
    the K = capT/``budget_div`` candidates with the WORST current shell
    quality go through the heavy role-derivation / gate / routing /
    scatter machinery.  Claims resolve against the global tet pool, so
    exactness under simultaneous application is unchanged; candidates
    past the budget are simply deferred to the next wave (waves repeat
    until quiet, and swaps exist to fix the worst elements first — the
    same prioritization Mmg's quality-driven sweeps apply).
    """
    capT, capP = mesh.capT, mesh.capP
    et = unique_edges(mesh)
    m6 = _met6(met)
    Efull = et.ev.shape[0]
    eof = jnp.asarray(_EDGE_OF)

    # ---- cheap full-width candidacy + worst-shell priority ---------------
    ft0_, ft1_, ft2_ = et.shell3[:, 0], et.shell3[:, 1], et.shell3[:, 2]
    q_tet = quality_from_points(
        mesh.vert[mesh.tet], None if m6 is None else m6[mesh.tet])
    s0f = jnp.clip(ft0_, 0, capT - 1)
    s1f = jnp.clip(ft1_, 0, capT - 1)
    s2f = jnp.clip(ft2_, 0, capT - 1)
    qs0 = jnp.where(ft0_ >= 0, q_tet[s0f], jnp.inf)
    qs1 = jnp.where(ft1_ >= 0, q_tet[s1f], jnp.inf)
    qs2 = jnp.where(ft2_ >= 0, q_tet[s2f], jnp.inf)
    # STATIC gates go into the pre-mask at full width: a candidate that
    # can never pass (wrong tref pairing, missing shell slots) must not
    # pin a top-K slot wave after wave (it would never be deferred — the
    # mesh doesn't change under it).  Only genuinely geometric gates
    # (planarity, quality) stay post-compaction.
    pair_ok_f = (ft0_ >= 0) & (ft1_ >= 0) & \
        (mesh.tref[s0f] == mesh.tref[s1f])
    if enable32:
        pre32 = et.emask & (et.nshell == 3) & (et.etag == 0) & \
            pair_ok_f & (ft2_ >= 0) & (mesh.tref[s0f] == mesh.tref[s2f])
    else:
        pre32 = jnp.zeros(Efull, bool)
    if enable22:
        frozen22 = (et.etag & (MG_GEO | MG_REQ | MG_PARBDY | MG_NOM |
                               MG_REF | MG_OPNBDY)) != 0
        pre22 = et.emask & (et.nshell == 2) & \
            ((et.etag & MG_BDY) != 0) & ~frozen22 & pair_ok_f
    else:
        pre22 = jnp.zeros(Efull, bool)
    if vact is not None:
        # narrow-path restriction (ops/active.py): both endpoints active
        # keeps the cavity fully inside the sub-mesh
        vok = vact[jnp.clip(et.ev[:, 0], 0, capP - 1)] & \
            vact[jnp.clip(et.ev[:, 1], 0, capP - 1)]
        pre32 = pre32 & vok
        pre22 = pre22 & vok
    if wwin is not None:
        # spatial-window rotation (ops/active.py): see collapse_wave
        wok = wwin[jnp.clip(et.ev[:, 0], 0, capP - 1)] & \
            wwin[jnp.clip(et.ev[:, 1], 0, capP - 1)]
        pre32 = pre32 & wok
        pre22 = pre22 & wok
    pre = pre32 | pre22
    from .edges import wave_budget, topk_prep3
    K = min(Efull, wave_budget(capT, budget_div))
    # fused scoring prep (exact q_shell = min(qs0, min(qs1, qs2)) chain)
    neg, npre = topk_prep3(pre, qs0, qs1, qs2)
    defer = npre > K
    # top-K worst shells without a full-width argsort
    _, sel = jax.lax.top_k(neg, K)

    # ---- compacted columns ----------------------------------------------
    ev_c = et.ev[sel]
    shell3_c = et.shell3[sel]
    E = K
    ar = jnp.arange(E)
    false_e = jnp.zeros(E, bool)

    t0, t1, t2 = shell3_c[:, 0], shell3_c[:, 1], shell3_c[:, 2]
    s0 = jnp.clip(t0, 0, capT - 1)
    s1 = jnp.clip(t1, 0, capT - 1)
    s2 = jnp.clip(t2, 0, capT - 1)
    a = jnp.clip(ev_c[:, 0], 0, capP - 1)
    b = jnp.clip(ev_c[:, 1], 0, capP - 1)
    tv0 = mesh.tet[s0]
    tv1 = mesh.tet[s1]

    # pair/tref gates already folded into the pre-masks (full width)
    base32 = pre32[sel] if enable32 else false_e
    base22 = pre22[sel] if enable22 else false_e

    # ---- role derivation -------------------------------------------------
    # s0's two non-(a,b) corners y1, y2
    is_ab0 = (tv0 == a[:, None]) | (tv0 == b[:, None])
    ordr = jnp.argsort(is_ab0.astype(jnp.int32), axis=1, stable=True)
    y1 = tv0[ar, ordr[:, 0]]
    y2 = tv0[ar, ordr[:, 1]]
    # 2-2 roles: c = the one shared with T2, p = the other, q = T2's 4th
    y1_in1 = jnp.any(tv1 == y1[:, None], axis=1)
    y2_in1 = jnp.any(tv1 == y2[:, None], axis=1)
    c22 = jnp.where(y1_in1, y1, y2)
    p22 = jnp.where(y1_in1, y2, y1)
    is_abc1 = (tv1 == a[:, None]) | (tv1 == b[:, None]) | \
        (tv1 == c22[:, None])
    q22 = tv1[ar, jnp.argmax(~is_abc1, axis=1)]
    # degenerate shells (edge shared without a shared face) rejected
    base22 = base22 & (y1_in1 ^ y2_in1) & \
        (jnp.sum(is_abc1.astype(jnp.int32), axis=1) == 3)
    # 3-2 roles: ring (p,q) from s0, r from s1; relabel (s1,s2) as
    # (t_pr, t_qr) by which one contains p
    p32, q32 = y1, y2
    is_pq1 = (tv1 == p32[:, None]) | (tv1 == q32[:, None])
    r32 = tv1[ar, jnp.argmax(~(is_abc1 | is_pq1), axis=1)]
    s1_has_p = jnp.any(tv1 == p32[:, None], axis=1)
    t_pr = jnp.where(s1_has_p, s1, s2)
    t_qr = jnp.where(s1_has_p, s2, s1)

    # unified roles: new tets A=(x0,x1,x2,a), B=(x0,x1,x2,b); tag sources
    # u1 (holds x0,x2 faces/edges) and u2 (holds x1,x2)
    x0 = jnp.where(base32, p32, p22)
    x1 = jnp.where(base32, q32, q22)
    x2 = jnp.where(base32, r32, c22)
    u1 = jnp.where(base32, t_pr, s0)
    u2 = jnp.where(base32, t_qr, s1)
    tu1 = mesh.tet[u1]
    tu2 = mesh.tet[u2]

    # ---- batched positions of (a, b, x0, x1, x2) in s0/u1/u2 -------------
    tgt = jnp.stack([a, b, x0, x1, x2], axis=1)            # [E,5]

    def pos5(tv):
        eqm = tv[:, None, :] == tgt[:, :, None]            # [E,5,4]
        return (jnp.argmax(eqm, axis=2).astype(jnp.int32),
                jnp.any(eqm, axis=2))

    P0, in0 = pos5(tv0)
    P1, in1 = pos5(tu1)
    P2, in2 = pos5(tu2)
    # 3-2 ring sanity: u1 must hold {x0,x2}, u2 {x1,x2}
    ring_ok = in1[:, 2] & in1[:, 4] & in2[:, 3] & in2[:, 4]
    base32 = base32 & ring_ok
    base22 = base22 & ring_ok          # holds by construction; belt+braces

    # ---- gathered tag/ref rows (all routing reads go through these) ------
    et0, et1r, et2r = mesh.etag[s0], mesh.etag[u1], mesh.etag[u2]
    ft0, ft1r, ft2r = mesh.ftag[s0], mesh.ftag[u1], mesh.ftag[u2]
    fr0, fr1r, fr2r = mesh.fref[s0], mesh.fref[u1], mesh.fref[u2]

    def ecol(rows, pi, pj):
        return jnp.take_along_axis(rows, eof[pi, pj][:, None], axis=1)[:, 0]

    def fcol(rows, pi):
        return jnp.take_along_axis(rows, pi[:, None], axis=1)[:, 0]

    # ---- 2-2 gates: boundary faces, planarity, area, duplicate edge ------
    if enable22:
        ft_bdy1 = fcol(ft0, P0[:, 4])          # T1 face opposite c
        ft_bdy2 = fcol(ft2r, P2[:, 4])         # T2 face opposite c
        fr_bdy1 = fcol(fr0, P0[:, 4])
        fr_bdy2 = fcol(fr2r, P2[:, 4])
        bad_face_bits = MG_REQ | MG_PARBDY | MG_NOM | MG_OPNBDY
        base22 = base22 & ((ft_bdy1 & MG_BDY) != 0) & \
            ((ft_bdy2 & MG_BDY) != 0) & \
            (((ft_bdy1 | ft_bdy2) & bad_face_bits) == 0) & \
            (ft_bdy1 == ft_bdy2) & (fr_bdy1 == fr_bdy2) & \
            (fcol(ft0, P0[:, 2]) == 0) & (fcol(ft2r, P2[:, 3]) == 0)
        newf = ft_bdy1
        newfr = fr_bdy1
        newe22 = jnp.uint32(MG_BDY) | (newf & MG_REF)

        pa_, pb_ = mesh.vert[a], mesh.vert[b]
        pp_, pq_ = mesh.vert[x0], mesh.vert[x1]
        pc_ = mesh.vert[x2]
        n_abp = jnp.cross(pb_ - pa_, pp_ - pa_)
        n_abq = jnp.cross(pq_ - pa_, pb_ - pa_)
        nn = jnp.sqrt(jnp.sum(n_abp * n_abp, -1)) + EPSD
        hloc = jnp.sqrt(jnp.maximum(jnp.maximum(
            jnp.sum((pb_ - pa_) ** 2, -1), jnp.sum((pp_ - pa_) ** 2, -1)),
            jnp.sum((pq_ - pa_) ** 2, -1)))
        eps_c = jnp.finfo(mesh.vert.dtype).eps
        cmax = jnp.max(jnp.stack([jnp.max(jnp.abs(pt_), -1) for pt_ in
                                  (pa_, pb_, pc_, pp_, pq_)]), axis=0)
        off_plane = jnp.abs(jnp.sum(n_abp * (pq_ - pa_), -1)) / nn
        noise_op = 32.0 * eps_c * cmax * hloc * hloc / nn
        # hausd relaxes the surface-exactness requirement to the Mmg
        # approximation tolerance: the flip changes the surface by at
        # most the quad's out-of-plane deviation
        tol_op = flat_tol * hloc + noise_op
        if hausd is not None:
            tol_op = jnp.maximum(tol_op, hausd)
        base22 = base22 & (off_plane <= tol_op)
        area = lambda nv: 0.5 * jnp.sqrt(jnp.sum(nv * nv, -1))
        a_old = area(n_abp) + area(n_abq)
        a_new = area(jnp.cross(pq_ - pp_, pa_ - pp_)) + \
            area(jnp.cross(pq_ - pp_, pb_ - pp_))
        noise_ar = 32.0 * eps_c * cmax * hloc
        tol_ar = 1e-5 * (a_old + EPSD) + noise_ar
        if hausd is not None:
            # area may legitimately change by ~ hausd * perimeter when
            # the quad is curved within tolerance
            tol_ar = jnp.maximum(tol_ar, hausd * hloc)
        base22 = base22 & (jnp.abs(a_old - a_new) <= tol_ar)
        # the flipped diagonal must not already exist (duplicate edge =>
        # non-manifold surface).  Packed int32 binary search when ids fit
        # (edges.PACK_LIMIT); sort-join fallback otherwise (no x64).
        from .edges import PACK_LIMIT, sort_pairs, segmented_or
        kmin = jnp.minimum(x0, x1)
        kmax = jnp.maximum(x0, x1)
        if capP <= PACK_LIMIT:
            i32max = jnp.iinfo(jnp.int32).max
            # the table's internal sort already produced ascending packed
            # keys (duplicates included — harmless for the existence
            # probe); reuse them instead of re-sorting [6*capT] keys
            if et.skey.shape[0] == Efull:
                ekey = et.skey
            else:
                ekey = jnp.sort(jnp.where(
                    et.emask, et.ev[:, 0] * capP + et.ev[:, 1], i32max))
            pkey = kmin * capP + kmax
            loc = jnp.searchsorted(ekey, pkey)
            exists = ekey[jnp.clip(loc, 0, Efull - 1)] == pkey
        else:
            # sort-join over full table + the K compacted candidates
            aa = jnp.concatenate([jnp.where(et.emask, et.ev[:, 0], 0),
                                  kmin])
            bb = jnp.concatenate([jnp.where(et.emask, et.ev[:, 1], 0),
                                  kmax])
            vv = jnp.concatenate([et.emask, base22])
            n_all = Efull + E
            order, _, _, first = sort_pairs(aa, bb, vv, capP)
            is_edge = (order < Efull) & vv[order]
            has_edge = segmented_or(first, is_edge.astype(jnp.uint32))
            is_last = jnp.concatenate([first[1:], jnp.array([True])])
            seg = jax.lax.associative_scan(
                jnp.maximum, jnp.where(first, jnp.arange(n_all), 0))
            total = jnp.zeros(n_all, jnp.uint32).at[
                jnp.where(is_last, seg, n_all)].set(
                has_edge, mode="drop", unique_indices=True)
            exists = jnp.zeros(E, bool).at[
                jnp.where(order >= Efull, order - Efull, E)].set(
                total[seg] > 0, mode="drop")
        base22 = base22 & ~exists
    else:
        newf = jnp.zeros(E, jnp.uint32)
        newfr = jnp.zeros(E, jnp.int32)
        newe22 = jnp.zeros(E, jnp.uint32)

    # ---- 3-2 gate: the vanishing interior faces must be untagged ---------
    if enable32:
        from ..core.constants import EDGE_FACES
        cfaces = jnp.asarray(EDGE_FACES)     # faces containing IARE edge
        face_clean = jnp.ones(E, bool)
        for rows, Pm in ((ft0, P0), (ft1r, P1), (ft2r, P2)):
            lae = eof[Pm[:, 0], Pm[:, 1]]
            for k in range(2):
                face_clean = face_clean & \
                    (fcol(rows, cfaces[lae, k]) == 0)
        base32 = base32 & face_clean

    cand = base32 | base22

    # ---- geometric validity: a, b astride the new interior plane ---------
    def signed_vol(v0, v1, v2, v3):
        q0, q1, q2, q3 = (mesh.vert[v0], mesh.vert[v1], mesh.vert[v2],
                          mesh.vert[v3])
        return jnp.sum((q1 - q0) * jnp.cross(q2 - q0, q3 - q0), -1)

    sv_a = signed_vol(x0, x1, x2, a)
    sv_b = signed_vol(x0, x1, x2, b)
    cand = cand & (sv_a * sv_b < 0) & (jnp.abs(sv_a) > EPSD) & \
        (jnp.abs(sv_b) > EPSD)
    flip_a = sv_a < 0
    flip_b = sv_b < 0

    def orient(v0, v1, v2, v3, flip):
        w0 = jnp.where(flip, v1, v0)
        w1 = jnp.where(flip, v0, v1)
        return jnp.stack([w0, w1, v2, v3], axis=1)

    new_a = orient(x0, x1, x2, a, flip_a)
    new_b = orient(x0, x1, x2, b, flip_b)

    # ---- quality gate: one stacked call for both new tets ----------------
    # (q_tet computed once above, at the priority step)
    q_old = jnp.minimum(q_tet[s0], q_tet[s1])
    q_old = jnp.minimum(q_old, jnp.where(base32, q_tet[s2], jnp.inf))
    new_ab = jnp.concatenate([new_a, new_b])
    q_ab = quality_from_points(
        mesh.vert[new_ab], None if m6 is None else m6[new_ab])
    q_new = jnp.minimum(q_ab[:E], q_ab[E:])
    cand = cand & (q_new > jnp.maximum(SWAP_GAIN * q_old, QUAL_FLOOR))

    # ---- tag routing (base corner order (x0,x1,x2,y)) --------------------
    # faces: col0 (opp x0) <- u2 opposite the vanished vertex; col1 <- u1;
    # col2 <- s0 for 3-2 / the NEW boundary face for 2-2; col3 interior.
    # edges (IARE): (x0x1, x0x2, x0y, x1x2, x1y, x2y).  A flip of
    # (x0,x1) permutes face cols (0,1) and edge cols (0,3,4,1,2,5).
    zero_u = jnp.zeros(E, jnp.uint32)
    zero_i = jnp.zeros(E, jnp.int32)

    def route_f(col0, col1, col2, zero, flip):
        w0 = jnp.where(flip, col1, col0)
        w1 = jnp.where(flip, col0, col1)
        return jnp.stack([w0, w1, col2, zero], axis=1)

    def route_e(cols, flip):
        flipped = [cols[0], cols[3], cols[4], cols[1], cols[2], cols[5]]
        return jnp.stack([jnp.where(flip, f, n)
                          for n, f in zip(cols, flipped)], axis=1)

    def routed(y_idx):
        """Face/edge/ref routing for new tet (x0,x1,x2,y); y_idx: 0=a 1=b.

        Inherited faces are the old faces OPPOSITE the vanished endpoint
        (tet A keeps the faces that b vanished from), so face columns use
        the other endpoint's positions; edges incident to y use y's own.
        """
        py0, py1, py2 = P0[:, y_idx], P1[:, y_idx], P2[:, y_idx]
        po0, po1, po2 = (P0[:, 1 - y_idx], P1[:, 1 - y_idx],
                         P2[:, 1 - y_idx])
        ftag_n = route_f(
            fcol(ft2r, po2), fcol(ft1r, po1),
            jnp.where(base32, fcol(ft0, po0), newf), zero_u,
            flip_a if y_idx == 0 else flip_b)
        fref_n = route_f(
            fcol(fr2r, po2), fcol(fr1r, po1),
            jnp.where(base32, fcol(fr0, po0), newfr), zero_i,
            flip_a if y_idx == 0 else flip_b)
        e0 = jnp.where(base32, ecol(et0, P0[:, 2], P0[:, 3]), newe22)
        e1 = ecol(et1r, P1[:, 2], P1[:, 4])
        e2 = ecol(et0, P0[:, 2], py0)
        e3 = ecol(et2r, P2[:, 3], P2[:, 4])
        e4 = jnp.where(base32, ecol(et0, P0[:, 3], py0),
                       ecol(et2r, P2[:, 3], py2))
        e5 = ecol(et2r, P2[:, 4], py2) | \
            jnp.where(base22, ecol(et0, P0[:, 4], py0), 0)
        etag_n = route_e([e0, e1, e2, e3, e4, e5],
                         flip_a if y_idx == 0 else flip_b)
        return ftag_n, fref_n, etag_n

    ftag_a, fref_a, etag_a = routed(0)
    ftag_b, fref_b, etag_b = routed(1)

    # ---- claims: s0, s1 (+ s2 for 3-2), exclusively ----------------------
    s2eff = jnp.where(base32, s2, s0)        # duplicate claim is harmless
    win = claim_shells(q_new - q_old, cand, (s0, s1, s2eff), capT)

    if enable22:
        # same-wave duplicate-diagonal veto: two 2-2 winners flipping to
        # the SAME new edge (x0,x1) — disjoint shells, so claims allow it
        # — would give that edge four boundary faces (non-manifold).  The
        # pre-wave existence check cannot see same-wave creations; keep
        # only the first winner per key (sort is ~free on this device).
        from .edges import sort_pairs as _sp
        win22 = win & base22
        order_d, _, _, first_d = _sp(jnp.minimum(x0, x1),
                                     jnp.maximum(x0, x1), win22, capP)
        dup_sorted = win22[order_d] & ~first_d
        dup = jnp.zeros(E, bool).at[order_d].set(dup_sorted,
                                                 unique_indices=True)
        win = win & ~dup

    # ---- apply: one concatenated scatter per array -----------------------
    w0i = jnp.where(win, s0, capT)
    w1i = jnp.where(win, s1, capT)
    idx2 = jnp.concatenate([w0i, w1i])
    tet = mesh.tet.at[idx2].set(
        jnp.concatenate([new_a, new_b]), mode="drop")
    ftag = mesh.ftag.at[idx2].set(
        jnp.concatenate([ftag_a, ftag_b]), mode="drop")
    fref = mesh.fref.at[idx2].set(
        jnp.concatenate([fref_a, fref_b]), mode="drop")
    etag = mesh.etag.at[idx2].set(
        jnp.concatenate([etag_a, etag_b]), mode="drop")
    tmask = mesh.tmask.at[jnp.where(win & base32, s2, capT)].set(
        False, mode="drop")
    nsw = jnp.sum(win.astype(jnp.int32))
    out = dataclasses.replace(mesh, tet=tet, tmask=tmask, ftag=ftag,
                              fref=fref, etag=etag, nelem=mesh.nelem)
    return SwapResult(out, nsw, defer)


def swap32_wave(mesh: Mesh, met: jax.Array) -> SwapResult:
    """3-2 interior edge swap only (see swap_edges_wave)."""
    return swap_edges_wave(mesh, met, enable32=True, enable22=False)


def swap22_wave(mesh: Mesh, met: jax.Array, flat_tol: float = 1e-5,
                hausd: float | None = None) -> SwapResult:
    """2-2 boundary edge swap only (see swap_edges_wave)."""
    return swap_edges_wave(mesh, met, enable32=False, enable22=True,
                           flat_tol=flat_tol, hausd=hausd)


def _pair_fields_adja(mesh: Mesh, q_tet, capT):
    """Legacy swap23 pairing off the materialized ``adja`` matrix:
    per-tet candidate fields (fstar, t2_full, f2_full, cand_full)."""
    adja = mesh.adja
    nb = adja >> 2
    nf = adja & 3
    valid = (adja >= 0) & mesh.tmask[:, None]
    nb_s = jnp.clip(nb, 0, capT - 1)
    # candidate faces, owned by the lower tet id; the swapped face itself
    # must be untagged (strictly interior) — exterior faces/edges of the
    # cavity may be tagged, their tags are routed to the new fan below
    tid = jnp.arange(capT, dtype=jnp.int32)[:, None]
    own = valid & (tid < nb) & mesh.tmask[nb_s]
    nf_s = jnp.clip(nf, 0, 3)
    own = own & (mesh.ftag == 0) & \
        (mesh.ftag[nb_s, nf_s] == 0)
    q_nb = jnp.where(own, q_tet[nb_s], jnp.inf)          # [T,4]
    fstar = jnp.argmin(q_nb, axis=1).astype(jnp.int32)   # [T]
    arT = jnp.arange(capT)
    t2_full = nb_s[arT, fstar]
    f2_full = nf_s[arT, fstar]
    cand_full = own[arT, fstar]
    return fstar, t2_full, f2_full, cand_full


def _pair_fields_facesort(mesh: Mesh, q_tet, capT, set_bdy_tags):
    """Swap23 pairing DIRECTLY off the face-sort records — no [capT,4]
    ``adja`` materialization, no per-tet [T,4] argmin machinery.

    Bit-parity with :func:`_pair_fields_adja` on every row the wave can
    consume:

    * a sorted slot is ``own`` iff its legacy (t, f) entry is: matched
      twins are exactly the ``adja >= 0`` entries (dead tets carry the
      INT32_MAX key and never match, so both twins are live — the
      ``valid``/``tmask[nb]`` conjuncts of the legacy mask hold by
      construction), and the ownership/ftag gates are evaluated on the
      same values;
    * the per-tet winner face reproduces ``argmin(q_nb, axis=1)``'s
      first-index tie-break exactly: the two-channel scatter-max with
      channels (-q_twin, -f) picks the minimum twin quality and, among
      float-equal minima, the smallest local face id (``scatter_argmax2``
      is exact — the tie channel is unique per (tet, face));
    * non-candidate rows default to 0 instead of the legacy clipped
      garbage; every downstream read of those rows is masked by
      ``cand_full`` (claims, scatters and the duplicate-edge veto all
      route masked rows to the drop sentinel), so the applied mesh is
      bit-identical — asserted by tests/test_hotloop.py.

    When ``set_bdy_tags`` the MG_BDY face tagging of the legacy
    ``build_adjacency`` call is applied from the same sort records, so
    the ftag this function reads AND returns matches the legacy
    sequence's exactly.  Returns (mesh', fstar, t2_full, f2_full,
    cand_full)."""
    from .adjacency import face_sort, bdy_tags_from_sort
    from .edges import scatter_argmax2
    t, f, partner, matched, valid_s = face_sort(mesh)
    if set_bdy_tags:
        mesh = bdy_tags_from_sort(mesh, t, f, matched, valid_s)
    tp = t[partner]
    fp = f[partner]
    own_s = matched & (t < tp) & (mesh.ftag[t, f] == 0) & \
        (mesh.ftag[tp, fp] == 0)
    q2 = q_tet[tp]
    is_star, _, _ = scatter_argmax2(t, -q2, -f, own_s, capT)
    site_star = jnp.where(is_star, t, capT)
    # ONE packed 3-column scatter for the winner fields (per-op overhead
    # dominates scatter cost on this device)
    pay = jnp.stack([f, tp, fp], axis=1)
    tbl = jnp.zeros((capT, 3), jnp.int32).at[site_star].set(
        pay, mode="drop", unique_indices=True)
    fstar, t2_full, f2_full = tbl[:, 0], tbl[:, 1], tbl[:, 2]
    cand_full = jnp.zeros(capT + 1, bool).at[
        jnp.where(own_s, t, capT)].max(own_s, mode="drop")[:capT]
    return mesh, fstar, t2_full, f2_full, cand_full


def swap23_wave(mesh: Mesh, met: jax.Array,
                budget_div: int = 8,
                wwin: jax.Array | None = None,
                facesort: bool = False,
                set_bdy_tags: bool = True) -> SwapResult:
    """2-to-3 swap: interior faces whose two tets improve as an edge fan.

    Tets T1, T2 share interior face (p,q,r) with apexes a (in T1) and b (in
    T2); replaced by (a,b,p,q), (a,b,q,r), (a,b,r,p) — two slots reused,
    one allocated.

    ``facesort=True`` (PARMMG_SWAP_FACESORT): derive the face-pair table
    directly from the face-sort records (ops/adjacency.face_sort) instead
    of requiring a ``build_adjacency`` call between swap_edges_wave and
    this wave — the caller passes the post-edge-swap mesh as-is and
    ``set_bdy_tags`` replays the legacy rebuild's MG_BDY tagging from the
    same sort.  Bit-for-bit identical to the legacy sequence (see
    _pair_fields_facesort); ``adja`` is left stale, which is sound
    because this pairing is its only cycle-interior reader (the cycle
    exit contract rebuilds it).
    """
    capT, capP = mesh.capT, mesh.capP
    m6 = _met6(met)
    # per-tet quality once; ONE candidate face per tet — the face toward
    # the worst neighbor.  Then top-K compaction: only the K candidate
    # pairs with the WORST current quality go through the fan
    # construction / quality / routing / scatters (the same cost lever
    # as swap_edges_wave; claims resolve against the global tet pool so
    # exactness is unchanged, deferred candidates wait one wave)
    q_tet = quality_from_points(
        mesh.vert[mesh.tet], None if m6 is None else m6[mesh.tet])
    if facesort:
        mesh, fstar, t2_full, f2_full, cand_full = _pair_fields_facesort(
            mesh, q_tet, capT, set_bdy_tags)
    else:
        fstar, t2_full, f2_full, cand_full = _pair_fields_adja(
            mesh, q_tet, capT)
    if wwin is not None:
        # spatial-window rotation (ops/active.py): see collapse_wave
        cand_full = cand_full & jnp.all(
            wwin[jnp.clip(mesh.tet, 0, capP - 1)], axis=1)
    q_pair = jnp.minimum(q_tet, jnp.where(cand_full, q_tet[t2_full],
                                          jnp.inf))
    from .edges import wave_budget, topk_prep
    F = min(capT, wave_budget(capT, budget_div))
    neg, ncand = topk_prep(cand_full, q_pair)
    defer = ncand > F
    _, sel = jax.lax.top_k(neg, F)
    ar = jnp.arange(F)
    t1 = sel.astype(jnp.int32)
    f1 = fstar[sel]
    t2 = t2_full[sel]
    f2 = f2_full[sel]
    cand = cand_full[sel]

    from ..core.constants import IDIR
    idir = jnp.asarray(IDIR)
    tv1 = mesh.tet[t1]                                   # [F,4]
    tv2 = mesh.tet[t2]
    pqr = tv1[ar[:, None], idir[f1]]                     # [F,3]
    a = tv1[ar, f1]                                      # apex in T1
    b = tv2[ar, f2]                                      # apex in T2

    p, q, r = pqr[:, 0], pqr[:, 1], pqr[:, 2]

    def mk(v0, v1, v2, v3):
        return jnp.stack([v0, v1, v2, v3], axis=1)

    # Face (p,q,r) = IDIR[f1] is oriented outward from T1 (away from a),
    # so for a visible pair the ring tets (x, y, a, b) over ring edges
    # (p,q), (q,r), (r,p) are all positively oriented; requiring all three
    # volumes strictly positive IS the convexity (visibility) test — no
    # sign fixing, which would mask invalid concave configurations.
    def signed_vol(tets):
        pts = mesh.vert[tets]
        d1 = pts[:, 1] - pts[:, 0]
        d2 = pts[:, 2] - pts[:, 0]
        d3 = pts[:, 3] - pts[:, 0]
        return jnp.sum(d1 * jnp.cross(d2, d3), -1)

    n1 = mk(p, q, a, b)
    n2 = mk(q, r, a, b)
    n3 = mk(r, p, a, b)
    pos = (signed_vol(n1) > EPSD) & (signed_vol(n2) > EPSD) & \
          (signed_vol(n3) > EPSD)
    # same region on both tets
    cand = cand & (mesh.tref[t1] == mesh.tref[t2])

    def qual(tets):
        pts = mesh.vert[tets]
        return quality_from_points(pts, None if m6 is None else m6[tets])

    # the 3 fan tets in ONE stacked call (per-op overhead dominates)
    q_old = jnp.minimum(q_tet[t1], q_tet[t2])
    q_fan = qual(jnp.concatenate([n1, n2, n3]))
    q_new = jnp.minimum(jnp.minimum(q_fan[:F], q_fan[F:2 * F]),
                        q_fan[2 * F:])
    cand = cand & pos & (q_new > jnp.maximum(SWAP_GAIN * q_old, QUAL_FLOOR))

    # --- claims on both tets (two-channel sort-free) ---------------------
    win = claim_shells(q_new - q_old, cand, (t1, t2), capT)
    # same-wave duplicate-edge veto: two winners whose fans both create
    # edge (a,b) (a "lens" of two face-pairs between the same apexes)
    # would put four tets on each (x,a,b) face; keep the first per key
    from .edges import sort_pairs as _sp23
    order_d, _, _, first_d = _sp23(jnp.minimum(a, b), jnp.maximum(a, b),
                                   win, capP)
    dup_sorted = win[order_d] & ~first_d
    win = win & ~jnp.zeros(F, bool).at[order_d].set(
        dup_sorted, unique_indices=True)
    # slot-reusing allocation from the free pool (edges.free_rows):
    # rows freed by collapses are reclaimed instead of bumping the
    # watermark cursor
    from .edges import free_rows
    frow_t, nfree_t = free_rows(mesh.tmask, F)
    w_i = win.astype(jnp.int32)
    off = jnp.cumsum(w_i) - w_i
    fits = off < jnp.minimum(nfree_t, F)
    win = win & fits
    w_i = win.astype(jnp.int32)
    off = jnp.cumsum(w_i) - w_i
    t3 = frow_t[jnp.clip(off, 0, F - 1)]

    # --- tag routing: the fan tet over ring edge (x,y) inherits the two
    # exterior faces (x,y,a) [old T1, opposite the third ring vertex] and
    # (x,y,b) [old T2]; ring and spoke edges keep their old tags; the new
    # interior edge (a,b) and the two fan-internal faces are untagged.
    eof = jnp.asarray(_EDGE_OF)
    pos_p1 = idir[f1][:, 0]
    pos_q1 = idir[f1][:, 1]
    pos_r1 = idir[f1][:, 2]
    # batched position lookup of (p,q,r) in T2: one comparison + argmax
    eqm2 = tv2[:, None, :] == pqr[:, :, None]            # [F,3,4]
    P2x = jnp.argmax(eqm2, axis=2).astype(jnp.int32)     # [F,3]
    pos_p2, pos_q2, pos_r2 = P2x[:, 0], P2x[:, 1], P2x[:, 2]
    zero_u = jnp.zeros(F, jnp.uint32)
    zero_i = jnp.zeros(F, jnp.int32)

    def route_f(arr, pos_opp1, pos_opp2, zero):
        # new tet (x,y,a,b): col2 = (x,y,b) from T2, col3 = (x,y,a) from T1
        return jnp.stack([zero, zero,
                          arr[t2, pos_opp2], arr[t1, pos_opp1]], axis=1)

    def route_e(pos_x1, pos_y1, pos_x2, pos_y2):
        # (x,y,a,b) IARE edges: (xy, xa, xb, ya, yb, ab)
        return jnp.stack([
            mesh.etag[t1, eof[pos_x1, pos_y1]],
            mesh.etag[t1, eof[pos_x1, f1]],
            mesh.etag[t2, eof[pos_x2, f2]],
            mesh.etag[t1, eof[pos_y1, f1]],
            mesh.etag[t2, eof[pos_y2, f2]],
            zero_u], axis=1)

    ftag_n = [route_f(mesh.ftag, pos_r1, pos_r2, zero_u),
              route_f(mesh.ftag, pos_p1, pos_p2, zero_u),
              route_f(mesh.ftag, pos_q1, pos_q2, zero_u)]
    fref_n = [route_f(mesh.fref, pos_r1, pos_r2, zero_i),
              route_f(mesh.fref, pos_p1, pos_p2, zero_i),
              route_f(mesh.fref, pos_q1, pos_q2, zero_i)]
    etag_n = [route_e(pos_p1, pos_q1, pos_p2, pos_q2),
              route_e(pos_q1, pos_r1, pos_q2, pos_r2),
              route_e(pos_r1, pos_p1, pos_r2, pos_p2)]

    # one concatenated scatter per array (per-op overhead dominates)
    idx3 = jnp.concatenate([jnp.where(win, tt, capT) for tt in (t1, t2, t3)])
    tet = mesh.tet.at[idx3].set(
        jnp.concatenate([n1, n2, n3]), mode="drop")
    tmask = mesh.tmask.at[jnp.where(win, t3, capT)].set(True, mode="drop")
    tref3 = mesh.tref[t1]
    tref = mesh.tref.at[jnp.where(win, t3, capT)].set(tref3, mode="drop")
    ftag = mesh.ftag.at[idx3].set(jnp.concatenate(ftag_n), mode="drop")
    etag = mesh.etag.at[idx3].set(jnp.concatenate(etag_n), mode="drop")
    fref = mesh.fref.at[idx3].set(jnp.concatenate(fref_n), mode="drop")
    nsw = jnp.sum(w_i)
    nelem = jnp.maximum(mesh.nelem,
                        jnp.max(jnp.where(win, t3 + 1, 0)))
    out = dataclasses.replace(mesh, tet=tet, tmask=tmask, tref=tref,
                              ftag=ftag, etag=etag, fref=fref,
                              nelem=nelem.astype(jnp.int32))
    return SwapResult(out, nsw, defer)


