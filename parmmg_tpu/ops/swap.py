"""Batched topology swaps (3-2 edge swap, 2-3 face swap).

Reference behavior: Mmg's ``MMG5_swpmsh``/``MMG3D_swpmshcpy`` remove bad
configurations by re-triangulating small cavities around an edge or face
when the worst quality strictly improves; the frozen-interface contract
(tag_pmmg.c:39-124) keeps parallel entities untouched.

v1 scope: swaps run only on *fully interior, untagged* cavities (no shell
tet carries face/edge tags), which sidesteps tag re-routing; boundary-aware
swaps are a later milestone.  Improvement gate: new worst quality >
SWAP_GAIN * old worst (Mmg uses 1.053).
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.mesh import Mesh
from ..core.constants import EPSD, QUAL_FLOOR
from .edges import unique_edges, claim_channels, NEG_INF, PRI_MIN
from .quality import quality_from_points

SWAP_GAIN = 1.053


class SwapResult(NamedTuple):
    mesh: Mesh
    nswap: jax.Array


def _met6(met):
    """Aniso: packed tensors; iso: None — quality is evaluated in
    Euclidean space exactly like Mmg's ``MMG5_caltet_iso`` (the constant
    local scaling cancels in Q), which skips the [*,4,6] metric gathers
    that dominate swap cost on TPU."""
    return None if met.ndim == 1 else met


def swap32_wave(mesh: Mesh, met: jax.Array) -> SwapResult:
    """3-to-2 swap: interior edges with exactly 3 shell tets.

    Shell T1=(a,b,p,q), T2, T3 around edge (a,b) with ring (p,q,r) is
    replaced by tets (p,q,r,a') and (p,q,r,b') — two slots reused, one
    freed.
    """
    capT, capP = mesh.capT, mesh.capP
    et = unique_edges(mesh)
    m6 = _met6(met)

    t0, t1, t2 = et.shell3[:, 0], et.shell3[:, 1], et.shell3[:, 2]
    s0, s1, s2 = (jnp.clip(t0, 0, capT - 1), jnp.clip(t1, 0, capT - 1),
                  jnp.clip(t2, 0, capT - 1))
    cand = et.emask & (et.nshell == 3) & (et.etag == 0) & \
        (t0 >= 0) & (t1 >= 0) & (t2 >= 0)
    # untagged cavity only
    for s in (s0, s1, s2):
        cand = cand & (jnp.sum(mesh.ftag[s], axis=1) == 0) & \
            (jnp.sum(mesh.etag[s], axis=1) == 0)

    a = jnp.clip(et.ev[:, 0], 0, capP - 1)
    b = jnp.clip(et.ev[:, 1], 0, capP - 1)

    def opp_pair(ts):
        """the 2 vertices of tet ts not equal to a or b."""
        tv = mesh.tet[ts]                               # [E,4]
        is_ab = (tv == a[:, None]) | (tv == b[:, None])
        # gather the two non-ab corners (positions via argsort of is_ab)
        ordr = jnp.argsort(is_ab.astype(jnp.int32), axis=1, stable=True)
        return tv[jnp.arange(tv.shape[0])[:, None], ordr[:, :2]]

    pq = opp_pair(s0)                                   # [E,2] = (p,q)
    rs = opp_pair(s1)
    # r = vertex of T2 not in {p,q}
    r = jnp.where((rs[:, 0] != pq[:, 0]) & (rs[:, 0] != pq[:, 1]),
                  rs[:, 0], rs[:, 1])
    p, q = pq[:, 0], pq[:, 1]

    def signed_vol(v0, v1, v2, v3):
        p0, p1, p2, p3 = (mesh.vert[v0], mesh.vert[v1], mesh.vert[v2],
                          mesh.vert[v3])
        return jnp.sum((p1 - p0) * jnp.cross(p2 - p0, p3 - p0), -1)

    # validity: a and b strictly on opposite sides of plane (p,q,r) — the
    # swapped pair tiles the shell union only then
    vol_a = signed_vol(p, q, r, a)
    vol_b = signed_vol(p, q, r, b)
    cand = cand & (vol_a * vol_b < 0) & (jnp.abs(vol_a) > EPSD) & \
        (jnp.abs(vol_b) > EPSD)
    # same region on all shell tets
    cand = cand & (mesh.tref[s0] == mesh.tref[s1]) & \
        (mesh.tref[s0] == mesh.tref[s2])

    def orient_from_sign(v0, v1, v2, v3, vol):
        neg = vol < 0
        w0 = jnp.where(neg, v1, v0)
        w1 = jnp.where(neg, v0, v1)
        return jnp.stack([w0, w1, v2, v3], axis=1)      # [E,4]

    new_a = orient_from_sign(p, q, r, a, vol_a)
    new_b = orient_from_sign(p, q, r, b, vol_b)

    def qual(tets):
        pts = mesh.vert[tets]
        return quality_from_points(pts, None if m6 is None else m6[tets])

    # q_old via a per-tet quality table computed once (one [capT,4] gather)
    # then three cheap 1-D gathers — not three full row-gather passes
    q_tet = qual(mesh.tet)
    q_old = jnp.minimum(jnp.minimum(q_tet[s0], q_tet[s1]), q_tet[s2])
    q_new = jnp.minimum(qual(new_a), qual(new_b))
    cand = cand & (q_new > jnp.maximum(SWAP_GAIN * q_old, QUAL_FLOOR))

    # --- claims: the 3 shell tets, exclusively (two-channel sort-free) ---
    ps, pt = claim_channels(q_new - q_old, cand)
    cl_s = jnp.full(capT + 1, NEG_INF)
    for sh in (s0, s1, s2):
        cl_s = cl_s.at[jnp.where(cand, sh, capT)].max(ps, mode="drop")
    eq = cand
    for sh in (s0, s1, s2):
        eq = eq & (ps == cl_s[sh])
    cl_t = jnp.full(capT + 1, PRI_MIN)
    for sh in (s0, s1, s2):
        cl_t = cl_t.at[jnp.where(eq, sh, capT)].max(pt, mode="drop")
    # winners are pairwise shell-disjoint: two winners sharing a tet would
    # both be that tet's pooled (s,t)-max — impossible, t is unique
    win = eq
    for sh in (s0, s1, s2):
        win = win & (pt == cl_t[sh])

    # --- apply: overwrite slots t0,t1; kill t2 ---------------------------
    tet = mesh.tet
    tet = tet.at[jnp.where(win, s0, capT)].set(new_a, mode="drop")
    tet = tet.at[jnp.where(win, s1, capT)].set(new_b, mode="drop")
    tmask = mesh.tmask.at[jnp.where(win, s2, capT)].set(False, mode="drop")
    # cavity was untagged: clear tags on rewritten slots
    zero4 = jnp.zeros((et.ev.shape[0], 4), jnp.uint32)
    zero6 = jnp.zeros((et.ev.shape[0], 6), jnp.uint32)
    ftag = mesh.ftag
    etag = mesh.etag
    for s in (s0, s1):
        ftag = ftag.at[jnp.where(win, s, capT)].set(zero4, mode="drop")
        etag = etag.at[jnp.where(win, s, capT)].set(zero6, mode="drop")
    nsw = jnp.sum(win.astype(jnp.int32))
    out = dataclasses.replace(mesh, tet=tet, tmask=tmask, ftag=ftag,
                              etag=etag,
                              nelem=mesh.nelem)  # count unchanged (masked)
    return SwapResult(out, nsw)


def swap23_wave(mesh: Mesh, met: jax.Array) -> SwapResult:
    """2-to-3 swap: interior faces whose two tets improve as an edge fan.

    Tets T1, T2 share interior face (p,q,r) with apexes a (in T1) and b (in
    T2); replaced by (a,b,p,q), (a,b,q,r), (a,b,r,p) — two slots reused,
    one allocated.
    """
    capT, capP = mesh.capT, mesh.capP
    m6 = _met6(met)
    adja = mesh.adja
    nb = adja >> 2
    nf = adja & 3
    valid = (adja >= 0) & mesh.tmask[:, None]
    nb_s = jnp.clip(nb, 0, capT - 1)
    # one candidate per interior face, owned by the lower tet id
    tid = jnp.arange(capT, dtype=jnp.int32)[:, None]
    own = valid & (tid < nb) & mesh.tmask[nb_s]
    # untagged cavity
    clean = (jnp.sum(mesh.ftag, axis=1) == 0) & \
            (jnp.sum(mesh.etag, axis=1) == 0)
    own = own & clean[:, None] & clean[nb_s]

    flat = lambda x: x.reshape(-1)
    F = capT * 4
    t1 = jnp.repeat(jnp.arange(capT, dtype=jnp.int32), 4)
    f1 = jnp.tile(jnp.arange(4, dtype=jnp.int32), capT)
    t2 = flat(nb_s)
    f2 = flat(nf)
    cand = flat(own)

    from ..core.constants import IDIR
    idir = jnp.asarray(IDIR)
    tv1 = mesh.tet[t1]                                   # [F,4]
    tv2 = mesh.tet[t2]
    pqr = tv1[jnp.arange(F)[:, None], idir[f1]]          # [F,3]
    a = tv1[jnp.arange(F), f1]                           # apex in T1
    b = tv2[jnp.arange(F), f2]                           # apex in T2

    p, q, r = pqr[:, 0], pqr[:, 1], pqr[:, 2]

    def mk(v0, v1, v2, v3):
        return jnp.stack([v0, v1, v2, v3], axis=1)

    # Face (p,q,r) = IDIR[f1] is oriented outward from T1 (away from a),
    # so for a visible pair the ring tets (x, y, a, b) over ring edges
    # (p,q), (q,r), (r,p) are all positively oriented; requiring all three
    # volumes strictly positive IS the convexity (visibility) test — no
    # sign fixing, which would mask invalid concave configurations.
    def signed_vol(tets):
        pts = mesh.vert[tets]
        d1 = pts[:, 1] - pts[:, 0]
        d2 = pts[:, 2] - pts[:, 0]
        d3 = pts[:, 3] - pts[:, 0]
        return jnp.sum(d1 * jnp.cross(d2, d3), -1)

    n1 = mk(p, q, a, b)
    n2 = mk(q, r, a, b)
    n3 = mk(r, p, a, b)
    pos = (signed_vol(n1) > EPSD) & (signed_vol(n2) > EPSD) & \
          (signed_vol(n3) > EPSD)
    # same region on both tets
    cand = cand & (mesh.tref[t1] == mesh.tref[t2])

    def qual(tets):
        pts = mesh.vert[tets]
        return quality_from_points(pts, None if m6 is None else m6[tets])

    # per-tet quality computed once on [capT], then flat 1-D lookups
    q_tet = qual(mesh.tet)
    q_old = jnp.minimum(q_tet[t1], q_tet[t2])
    q_new = jnp.minimum(jnp.minimum(qual(n1), qual(n2)), qual(n3))
    cand = cand & pos & (q_new > jnp.maximum(SWAP_GAIN * q_old, QUAL_FLOOR))

    # --- claims on both tets (two-channel sort-free) ---------------------
    ps, pt = claim_channels(q_new - q_old, cand)
    cl_s = jnp.full(capT + 1, NEG_INF)
    cl_s = cl_s.at[jnp.where(cand, t1, capT)].max(ps, mode="drop")
    cl_s = cl_s.at[jnp.where(cand, t2, capT)].max(ps, mode="drop")
    eq = cand & (ps == cl_s[t1]) & (ps == cl_s[t2])
    cl_t = jnp.full(capT + 1, PRI_MIN)
    cl_t = cl_t.at[jnp.where(eq, t1, capT)].max(pt, mode="drop")
    cl_t = cl_t.at[jnp.where(eq, t2, capT)].max(pt, mode="drop")
    win = eq & (pt == cl_t[t1]) & (pt == cl_t[t2])
    w_i = win.astype(jnp.int32)
    off = jnp.cumsum(w_i) - w_i
    fits = off < (capT - mesh.nelem)
    win = win & fits
    w_i = win.astype(jnp.int32)
    off = jnp.cumsum(w_i) - w_i
    t3 = (mesh.nelem + off).astype(jnp.int32)

    tet = mesh.tet
    tet = tet.at[jnp.where(win, t1, capT)].set(n1, mode="drop")
    tet = tet.at[jnp.where(win, t2, capT)].set(n2, mode="drop")
    tet = tet.at[jnp.where(win, t3, capT)].set(n3, mode="drop")
    tmask = mesh.tmask.at[jnp.where(win, t3, capT)].set(True, mode="drop")
    tref3 = mesh.tref[t1]
    tref = mesh.tref.at[jnp.where(win, t3, capT)].set(tref3, mode="drop")
    zero4 = jnp.zeros((F, 4), jnp.uint32)
    zero6 = jnp.zeros((F, 6), jnp.uint32)
    ftag, etag, fref = mesh.ftag, mesh.etag, mesh.fref
    for tt in (t1, t2, t3):
        ftag = ftag.at[jnp.where(win, tt, capT)].set(zero4, mode="drop")
        etag = etag.at[jnp.where(win, tt, capT)].set(zero6, mode="drop")
        fref = fref.at[jnp.where(win, tt, capT)].set(
            zero4.astype(jnp.int32), mode="drop")
    nsw = jnp.sum(w_i)
    nelem = mesh.nelem + nsw
    out = dataclasses.replace(mesh, tet=tet, tmask=tmask, tref=tref,
                              ftag=ftag, etag=etag, fref=fref,
                              nelem=nelem.astype(jnp.int32))
    return SwapResult(out, nsw)
