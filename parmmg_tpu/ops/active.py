"""Active-scoped narrow adaptation — the TPU analogue of Mmg's worklist.

The reference's sequential kernel (``MMG5_mmg3d1_delone``, called per group
at /root/reference/src/libparmmg1.c:737) is *worklist-driven*: each pass
walks a cascade of entities affected by earlier operations, so a nearly
converged mesh costs almost nothing.  Our batched waves historically paid
full [capT]-width table builds and gather/scatter passes per cycle even
when only a handful of candidates remained — the measured throughput
ceiling of rounds 1-3.

This module restores the worklist economics under XLA's static shapes:

- ``dirty`` [capP] bool marks vertices whose neighborhood changed in the
  previous cycle (computed by diffing the mesh arrays — generic, no
  per-wave bookkeeping).
- One cheap full-width pass computes the 1-ring closure ``dirty2`` and the
  ACTIVE tet set (tets holding a dirty2 vertex).  For any entity whose
  candidacy could have changed, its whole gate stencil (edge shell, ball
  of the removed/moved vertex, swap cavity) lies inside the active set —
  see the invariant below.
- The active tets are compacted into an [A]-row SUB-mesh (tet-axis arrays
  only; vertex-axis arrays are shared at full width).  The SAME wave
  kernels run on it with ``vact=dirty2`` restricting candidates; results
  scatter back.  A = capT//NARROW_DIV, so sorts and heavy passes shrink
  by the same factor.

Worklist invariant (why narrow cycles are exact): an edge/vertex whose
gate inputs did NOT change since it last failed keeps failing, so only
entities touching the previous cycle's footprint need re-evaluation.
Losers become revisitable exactly when their blocker applies (its
footprint makes them dirty).  The ONE exception is a candidate dropped
by a top-K *budget* (it failed for scheduling, not geometric, reasons):
at steady state thousands of permanently-gate-failing short edges can
pin the budget, so a strict "no deferral" entry condition would never
open (measured on the bench workload).  The full path itself never
attempts that backlog either — it re-examines the same top-K every
cycle — so narrow mode instead guarantees BOUNDED staleness: a
full-width refresh cycle runs periodically (``full_every``, default
once per block), attempting the same global top-K the full path would,
and the convergence decision in the host driver (wide check,
budget_div=2) and the polish/repair tail remain full-width — final
results keep full-path exactness.

Shell-count exactness on the sub-mesh: every shell tet of a candidate
edge contains one of its endpoints; endpoints are dirty2, so all shell
tets are active and in the sub-mesh — counts, nominations and claims are
exact.  Sub-mesh adjacency is built WITHOUT boundary tagging
(cut faces are unmatched but not surface, adjacency.build_adjacency
``set_bdy_tags=False``); swap23 skips unmatched faces, which is correct
because a pair whose twin is inactive cannot have changed status.
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from ..core.mesh import Mesh
# top-level imports (NOT lazy): a module first imported inside a jit
# trace would create its module-level jnp constants as tracers, which
# then leak into every later trace (UnexpectedTracerError)
from .adapt import adapt_cycle_impl
from .adjacency import build_adjacency

# A = max(NARROW_MIN, capT // NARROW_DIV).  8 measured best on the bench
# workload (2026-08-02): equal-population morton windows hold the active
# set at ~11-16k tets, comfortably under the A=capT/8 budget at bench
# shapes, and every narrow pass (sorts, scatters, adjacency) is half the
# width of the old capT/4 sub-mesh — +30% steady-state block throughput.
NARROW_DIV = 8
NARROW_MIN = 8192


def _narrow_div() -> int:
    """Narrow-row divisor, env-overridable (PARMMG_NARROW_DIV): a larger
    divisor shrinks every narrow-cycle pass proportionally, at the cost
    of more frequent active-set overflows (which fall back to full-width
    cycles, correct but slow) — tune against the workload's steady-state
    footprint."""
    import os
    v = os.environ.get("PARMMG_NARROW_DIV", "")
    return max(2, int(v)) if v else NARROW_DIV
# fraction of A reserved for rows ALLOCATED by splits/swaps inside the
# narrow cycle; the active set itself may only fill A - A//4
NARROW_HEADROOM_DIV = 4


def narrow_rows(capT: int) -> int:
    """Narrow sub-mesh row budget, BUCKETED (compile governor): the raw
    capT//div drifts with every capacity choice and A keys the compile
    of every narrow-cycle program — bucketing from the NARROW_MIN floor
    collapses those onto a handful of variants.  The geo (1.5x) ladder,
    not pow2: a pow2 round-up can widen the tuned capT//8 narrow width
    by almost 2x, silently giving back the measured capT/4 -> capT/8
    throughput win (comment above NARROW_DIV)."""
    from ..utils.compilecache import bucket
    return bucket(max(NARROW_MIN, capT // _narrow_div()),
                  floor=NARROW_MIN, scheme="geo", cap=capT)


def dirty_from_diff(pre: Mesh, post: Mesh, pre_met=None, post_met=None):
    """[capP] bool: vertices whose neighborhood changed between two mesh
    states.  Generic footprint: vertices of any tet row whose vertex
    list / liveness / face or edge tags / face refs changed, plus moved
    vertices and vertices whose own tag/liveness changed.  Every wave's
    effect is visible in one of these arrays, so no per-wave bookkeeping
    is needed (elementwise compares are HBM-cheap)."""
    capP = pre.capP
    row = jnp.any(pre.tet != post.tet, axis=1)
    row = row | (pre.tmask != post.tmask)
    row = row | jnp.any(pre.ftag != post.ftag, axis=1)
    row = row | jnp.any(pre.fref != post.fref, axis=1)
    row = row | jnp.any(pre.etag != post.etag, axis=1)
    # vertices of changed rows (pre AND post vertex lists: a remapped
    # row must dirty both the old and the new endpoints)
    idx = jnp.where(row[:, None], pre.tet, capP)
    idx2 = jnp.where(row[:, None], post.tet, capP)
    dirty = jnp.zeros(capP + 1, bool)
    dirty = dirty.at[idx.reshape(-1)].set(True, mode="drop")
    dirty = dirty.at[idx2.reshape(-1)].set(True, mode="drop")
    dirty = dirty[:capP]
    dirty = dirty | jnp.any(pre.vert != post.vert, axis=1)
    dirty = dirty | (pre.vtag != post.vtag) | (pre.vmask != post.vmask)
    if pre_met is not None:
        dm = pre_met != post_met
        dirty = dirty | (dm if dm.ndim == 1 else jnp.any(dm, axis=1))
    return dirty


def closure_active(mesh: Mesh, dirty: jax.Array):
    """(dirty2, active): 1-ring vertex closure of ``dirty`` and the tets
    containing any dirty2 vertex.  Two [4T]-index passes — the only
    full-width work a narrow cycle pays besides the final compaction."""
    capP = mesh.capP
    touched = jnp.any(dirty[mesh.tet], axis=1) & mesh.tmask     # [T]
    idx = jnp.where(touched[:, None], mesh.tet, capP).reshape(-1)
    d2 = jnp.zeros(capP + 1, bool).at[idx].set(True, mode="drop")[:capP]
    d2 = d2 | dirty
    active = jnp.any(d2[mesh.tet], axis=1) & mesh.tmask
    return d2, active


def extract_active(mesh: Mesh, active: jax.Array, A: int):
    """Compact the active tets into an [A]-row sub-mesh.

    Returns (sub, back, n_act, ovf): ``back[r]`` is the full-mesh slot a
    sub-mesh row writes back to — active rows keep their slot, rows past
    ``n_act`` map to the full mesh's FREE rows in pool order (so in-sub
    allocations land in genuinely dead full slots, matching the
    slot-reusing allocators — edges.free_rows).  Tail rows past the full
    free count map to capT (write-back drops them; a LIVE such row is
    the alloc-overflow signal checked in auto_cycle).
    ``ovf`` = the active set does not fit the budgeted rows (caller must
    abort the narrow cycle WITHOUT applying anything)."""
    from .edges import free_rows
    capT = mesh.capT
    n_act = jnp.sum(active, dtype=jnp.int32)
    ovf = n_act > (A - A // NARROW_HEADROOM_DIV)
    ids = jnp.nonzero(active, size=A, fill_value=capT)[0].astype(jnp.int32)
    ffree, _nfree = free_rows(mesh.tmask, A)
    r = jnp.arange(A, dtype=jnp.int32)
    back = jnp.where(r < n_act, ids,
                     ffree[jnp.clip(r - n_act, 0, A - 1)])
    src = jnp.clip(ids, 0, capT - 1)
    pad = r >= n_act
    sub = dataclasses.replace(
        mesh,
        tet=jnp.where(pad[:, None], 0, mesh.tet[src]),
        tmask=jnp.where(pad, False, mesh.tmask[src]),
        tref=jnp.where(pad, 0, mesh.tref[src]),
        ftag=jnp.where(pad[:, None], 0, mesh.ftag[src]),
        fref=jnp.where(pad[:, None], 0, mesh.fref[src]),
        etag=jnp.where(pad[:, None], jnp.uint32(0), mesh.etag[src]),
        adja=jnp.full((A, 4), -1, jnp.int32),
        nelem=n_act)
    return sub, back, n_act, ovf


def writeback_active(mesh: Mesh, sub: Mesh, back: jax.Array,
                     n_act: jax.Array):
    """Scatter the sub-mesh's tet-axis rows back into the full mesh and
    adopt its (shared) vertex-axis arrays.  Rows whose target exceeds
    capT drop (they are dead pad rows past the free region)."""
    capT = mesh.capT
    tgt = jnp.where(back < capT, back, capT)
    tmask2 = mesh.tmask.at[tgt].set(sub.tmask, mode="drop",
                                    unique_indices=True)
    # exact watermark from the final liveness (free-pool targets may lie
    # below the old watermark, and pad writes may tighten nothing)
    rowsT = jnp.arange(capT, dtype=jnp.int32)
    nelem2 = jnp.max(jnp.where(tmask2, rowsT + 1, 0))
    out = dataclasses.replace(
        mesh,
        tet=mesh.tet.at[tgt].set(sub.tet, mode="drop",
                                 unique_indices=True),
        tmask=tmask2,
        tref=mesh.tref.at[tgt].set(sub.tref, mode="drop",
                                   unique_indices=True),
        ftag=mesh.ftag.at[tgt].set(sub.ftag, mode="drop",
                                   unique_indices=True),
        fref=mesh.fref.at[tgt].set(sub.fref, mode="drop",
                                   unique_indices=True),
        etag=mesh.etag.at[tgt].set(sub.etag, mode="drop",
                                   unique_indices=True),
        vert=sub.vert, vmask=sub.vmask, vtag=sub.vtag, vref=sub.vref,
        npoin=sub.npoin,
        nelem=nelem2)
    return out


def auto_cycle(mesh: Mesh, met, pending, okflag, wave, A: int,
               do_swap: bool, do_smooth: bool, do_insert: bool,
               hausd, budget_div: int = 8,
               narrow_budget_div: int = 2,
               window: int = 0):
    """One adaptation cycle that picks its own width (jit-inline).

    ``pending`` [capP] bool is the WORKLIST: vertices whose neighborhood
    changed since they were last examined.  With ``window`` > 0 each
    cycle examines only the pending vertices of the current contiguous
    morton-curve segment (``wave % window``) — and the topology waves
    restrict their candidate pools to that window too
    (split/collapse/swap ``wwin``), so each cycle's footprint is a
    compact blob.  Pending work outside the window is carried and
    re-examined when its window rotates in: staleness is bounded by
    ``window`` cycles, and the rotation attempts EVERY candidate —
    strictly better coverage than the full path's permanently-pinned
    global top-K.

    A cheap full-width closure pass sizes the active set; when
    ``okflag`` holds and the active tets fit the narrow row budget, the
    cycle runs on the compacted sub-mesh, else full-width (same
    windowed candidate masks).  Both branches live in ONE compiled
    program.

    Returns (mesh, met, pending_next, ok_next, counts[8]); counts
    column 7 is a diagnostic 1 when the narrow branch ran."""
    capP = mesh.capP
    # effective window count scales with the mesh (capT is static, so
    # this is a compile-time choice): region(~capT/nwin) + its 2-hop
    # halo must fit A - A//4 — measured on the bench workload the
    # closure covers ~the whole window region, so size regions at about
    # a THIRD of the narrow rows.  A mesh that fits the narrow rows
    # whole (A >= capT) needs no windowing at all.
    if A >= mesh.capT:
        nwin = 1
    else:
        nwin = min(window, max(2, (3 * mesh.capT) // max(1, A)))
    if window > 0 and nwin > 1:
        from .smooth import morton_window_mask
        wmask = morton_window_mask(mesh.vert, mesh.vmask, wave, nwin)
        dirty_proc = pending & wmask
    else:
        wmask = None
        dirty_proc = pending
    d2, active = closure_active(mesh, dirty_proc)
    n_act = jnp.sum(active, dtype=jnp.int32)
    fits_rows = n_act <= (A - A // NARROW_HEADROOM_DIV)
    can_narrow = okflag & fits_rows

    def _pending_next(dn):
        if wmask is None:
            return dn
        return (pending & ~wmask) | dn

    def _nar(_):
        sub0, back, n_act2, _ovf = extract_active(mesh, active, A)
        sub, met2, counts = adapt_cycle_impl(
            sub0, met, wave, do_swap=do_swap, do_smooth=do_smooth,
            do_insert=do_insert, final_rebuild=False, hausd=hausd,
            budget_div=narrow_budget_div, vact=d2, submesh=True)
        # the sub's allocated rows land in full-mesh FREE rows via the
        # back pool; a live sub row whose back target is the capT
        # sentinel means the pool ran out and the writeback would
        # silently drop a tet (half-applied ops) — detect post-hoc and
        # discard the whole cycle instead (exact; never trips at steady
        # state where allocations are small)
        alloc_bad = jnp.any(sub.tmask & (back >= mesh.capT))

        def _apply(_):
            dn = dirty_from_diff(sub0, sub)
            mesh2 = writeback_active(mesh, sub, back, n_act2)
            # a sub CAPACITY overflow (col 4) truncated winners inside
            # the sub-mesh, or an INSERTION wave deferred at its top-K
            # (col 6 bit 0 — sizing-critical backlog): escalate to the
            # full path next cycle.  A SWAP-wave deferral (col 6 bit 1)
            # does NOT escalate: swap nomination pools routinely exceed
            # the sub top-K, escalating on them forced a ~500 ms
            # full-width cycle after most swap waves for no measured
            # quality gain, and their backlog is covered by the
            # periodic full refresh + the polish tail (the
            # bounded-staleness contract, module docstring).
            bad = (counts[4] > 0) | (counts[6] % 2 > 0)
            counts2 = counts.at[4].set(0).at[5].set(
                jnp.sum(mesh2.tmask, dtype=jnp.int32)).at[6].set(
                bad.astype(jnp.int32)).at[7].set(1)
            counts2 = jnp.concatenate(
                [counts2, n_act[None], okflag.astype(jnp.int32)[None]])
            return mesh2, met2, _pending_next(dn), ~bad, counts2

        def _discard(_):
            counts2 = jnp.zeros(8, jnp.int32).at[5].set(
                jnp.sum(mesh.tmask, dtype=jnp.int32)).at[6].set(
                1).at[7].set(1)
            counts2 = jnp.concatenate(
                [counts2, n_act[None], okflag.astype(jnp.int32)[None]])
            return mesh, met, pending, jnp.zeros((), bool), counts2

        return jax.lax.cond(~alloc_bad, _apply, _discard, None)

    def _full(_):
        mesh2, met2, counts = adapt_cycle_impl(
            mesh, met, wave, do_swap=do_swap, do_smooth=do_smooth,
            do_insert=do_insert, final_rebuild=False, hausd=hausd,
            budget_div=budget_div, wwin=wmask)
        dn = dirty_from_diff(mesh, mesh2)
        # a full cycle (re)seeds the worklist when (a) capacity did not
        # overflow (the host regrows and restarts the worklist anyway)
        # and (b) the mesh is in the STEADY-STATE regime: during
        # refinement thousands of split candidates exist far from any
        # footprint, and a narrow cycle would advance only the worklist
        # region while the global frontier waits — measured as a
        # mid-protocol refinement backlog burst.  Top-K deferral does
        # NOT block narrow — see the bounded-staleness contract in the
        # module docstring.
        topo = counts[0] + counts[1] + counts[2]
        ok = (counts[4] == 0) & (topo < 512)
        counts = jnp.concatenate(
            [counts, n_act[None], okflag.astype(jnp.int32)[None]])
        return mesh2, met2, _pending_next(dn), ok, counts

    return jax.lax.cond(can_narrow, _nar, _full, None)


def adapt_cycles_auto_impl(mesh: Mesh, met, pending, okflag, wave0,
                           swap_flags: tuple,
                           full_flags: tuple | None = None,
                           hausd=None, do_smooth: bool = True,
                           do_insert: bool = True,
                           budget_div: int = 8,
                           final_rebuild: bool = True,
                           window: int = 24):
    """Fused block of self-width-selecting cycles (one dispatch).

    Thread ``pending`` [capP] bool (the worklist) and ``okflag`` scalar
    bool across blocks (start a session with zeros/False: the first
    cycles run full-width and seed the worklist).  ``full_flags``
    forces the marked positions to run full-width — the
    bounded-staleness refresh (module docstring); default: the LAST
    cycle of the block, whose morton window rotates across blocks so
    every window's backlog is refreshed periodically.  The final cycle
    restores the full-mesh adjacency/boundary-tag exit contract."""
    A = narrow_rows(mesh.capT)
    if full_flags is None:
        full_flags = tuple(c == len(swap_flags) - 1
                           for c in range(len(swap_flags)))
    counts_all = []
    for c, dosw in enumerate(swap_flags):
        okc = jnp.logical_and(okflag, not full_flags[c])
        mesh, met, pending, okflag, counts = auto_cycle(
            mesh, met, pending, okc, wave0 + c, A, dosw,
            do_smooth, do_insert, hausd, budget_div=budget_div,
            window=window)
        counts_all.append(counts)
    if final_rebuild:
        mesh = build_adjacency(mesh)
    return mesh, met, pending, okflag, jnp.stack(counts_all)


from ..utils.compilecache import governed as _governed  # noqa: E402

adapt_cycles_auto = _governed("active.adapt_cycles_auto")(
    partial(jax.jit, static_argnames=(
        "swap_flags", "full_flags", "hausd", "do_smooth", "do_insert",
        "budget_div", "final_rebuild", "window"),
        donate_argnums=(0, 1, 2))(adapt_cycles_auto_impl))
