"""Batched edge collapse — data-parallel replacement for Mmg's colver.

Reference behavior: short edges (metric length < 1/sqrt(2)) are removed by
merging one endpoint into the other; the shell tets die, the rest of the
removed vertex's ball is rewritten.  Constraints reproduced from Mmg's
``MMG5_colver`` checks + the ParMmg freeze contract (tag_pmmg.c:39-124):
required/corner/parallel vertices never move; boundary points only collapse
along boundary edges onto boundary points; ridge points only along ridges.

Independent-set scheduling (one wave):
  1. candidates = short, un-frozen edges; pick a *removed* endpoint per edge;
  2. per-vertex "top remover" priorities; geometric validity (positive
     volumes, no boundary fold-over, no overlong new edges) is evaluated for
     top removers only, tet-centrically;
  3. claims: a winner must be argmax at both endpoints and on every tet of
     the removed vertex's ball — so winner balls are disjoint and the
     per-candidate precheck stays exact under simultaneous application;
  4. apply via a vertex remap gather; shell tets (containing both endpoints)
     are invalidated; face tags of dying tets transfer to the surviving
     neighbor across (that face was interior, it becomes boundary iff it was
     tagged).
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.mesh import Mesh
from ..core.constants import (
    IDIR, LSHRT, LLONG, EPSD, MG_BDY, MG_CRN, MG_GEO, MG_NOM, MG_REF,
    MG_REQ, MG_PARBDY, QUAL_FLOOR)
from .edges import (unique_edges, edge_lengths, claim_channels,
                    scatter_argmax2, NEG_INF, PRI_MIN)

_IDIR_J = jnp.asarray(IDIR)


class CollapseResult(NamedTuple):
    mesh: Mesh
    ncollapse: jax.Array
    # did any dying tet donate face/edge tags (surface rewired)?  False
    # lets the caller skip the boundary re-propagation pass entirely
    surface_changed: jax.Array = None
    deferred: jax.Array = None  # scalar bool: candidates exceeded the
    #                 top-K budget (see ops/active.py worklist invariant)


def _removable(vtag, other_vtag, edge_tag):
    """May vertex v (tags vtag) be deleted by collapsing along this edge?"""
    free = (vtag & (MG_REQ | MG_CRN | MG_PARBDY | MG_NOM)) == 0
    on_bdy = (vtag & MG_BDY) != 0
    bdy_ok = ~on_bdy | (((edge_tag & MG_BDY) != 0) &
                        ((other_vtag & MG_BDY) != 0))
    on_geo = (vtag & MG_GEO) != 0
    # a ridge point may slide along its ridge onto another ridge point or
    # onto the corner terminating the ridge (Mmg chkcol_bdy semantics)
    geo_ok = ~on_geo | (((edge_tag & MG_GEO) != 0) &
                        ((other_vtag & (MG_GEO | MG_CRN)) != 0))
    # likewise a reference-edge point stays on its reference line
    on_ref = (vtag & MG_REF) != 0
    ref_ok = ~on_ref | (((edge_tag & MG_REF) != 0) &
                        ((other_vtag & (MG_REF | MG_CRN)) != 0))
    return free & bdy_ok & geo_ok & ref_ok


def collapse_wave(mesh: Mesh, met: jax.Array, lmin: float = LSHRT,
                  lmax: float = LLONG,
                  sliver_q: float | None = None,
                  hausd: float | None = None,
                  budget_div: int = 8,
                  et=None, lens=None,
                  stale_tets: jax.Array | None = None,
                  vtan: jax.Array | None = None,
                  vact: jax.Array | None = None,
                  wwin: jax.Array | None = None) -> CollapseResult:
    """One independent-set collapse wave.

    Normal mode: contract edges shorter than ``lmin`` (Mmg's colver over
    the short-edge cascade).  Sliver mode (``sliver_q`` set): target the
    edges of tets whose quality is below ``sliver_q`` regardless of
    length, and additionally require that the simulated collapse STRICTLY
    improves the min quality over the removed vertex's ball — the batched
    analogue of Mmg's bad-element optimization pass (``MMG3D_opttyp``
    collapses on ``MMG3D_BADKAL`` elements).

    ``et``/``lens``/``stale_tets``: shared-table mode.  adapt_cycle_impl
    builds ONE edge table + lengths before the split wave and passes
    them to both ops; ``stale_tets`` is the split's modification
    footprint, and any candidate edge touching a vertex of a modified
    tet is deferred to the next wave (its table row describes pre-split
    geometry).  Validity/quality below run against the CURRENT (post-
    split) mesh arrays, which are identical on every unmodified slot.
    """
    capT, capP = mesh.capT, mesh.capP
    if et is None:
        et = unique_edges(mesh)
    if lens is None:
        lens = edge_lengths(mesh, et, met)
    Efull = et.ev.shape[0]
    va_f = jnp.clip(et.ev[:, 0], 0, capP - 1)
    vb_f = jnp.clip(et.ev[:, 1], 0, capP - 1)

    frozen_edge = (et.etag & (MG_REQ | MG_PARBDY)) != 0
    if sliver_q is None:
        short = et.emask & (lens < lmin) & ~frozen_edge
        if stale_tets is not None:
            # staleness veto: vertices of any tet the split modified
            stale_v = jnp.zeros(capP + 1, bool).at[
                jnp.where(stale_tets[:, None], mesh.tet, capP)
                .reshape(-1)].max(
                jnp.repeat(stale_tets, 4), mode="drop")[:capP]
            short = short & ~stale_v[va_f] & ~stale_v[vb_f]
    else:
        from .quality import quality_from_points
        q_tet = quality_from_points(
            mesh.vert[mesh.tet],
            None if met.ndim == 1 else met[mesh.tet])
        bad_tet = mesh.tmask & (q_tet < sliver_q)
        bad_edge = jnp.zeros(et.ev.shape[0], bool).at[
            et.edge_id.reshape(-1)].max(
            jnp.repeat(bad_tet, 6), mode="drop")
        # don't lengthen already-long edges by contracting into them
        short = et.emask & bad_edge & ~frozen_edge & (lens < lmax)

    if vact is not None:
        # narrow-path restriction (ops/active.py): both endpoints active
        # — the removed endpoint's whole ball is then in the sub-mesh,
        # keeping the ball-quality gate below exact
        short = short & vact[va_f] & vact[vb_f]
    if wwin is not None:
        # spatial-window rotation (ops/active.py): collapse candidates
        # restrict to the current morton window UNCONDITIONALLY — the
        # steady-state candidate pool exceeds the top-K budget anyway
        # (the global pass never attempts the backlog), while the
        # window's share fits the budget, so rotation ATTEMPTS EVERY
        # candidate within nwin cycles — strictly better coverage, and
        # the winners' footprints stay spatially compact
        short = short & wwin[va_f] & wwin[vb_f]
    ta_f, tb_f = mesh.vtag[va_f], mesh.vtag[vb_f]
    rem_b_f = _removable(tb_f, ta_f, et.etag)   # can delete b (keep a)
    rem_a_f = _removable(ta_f, tb_f, et.etag)
    pre = short & (rem_a_f | rem_b_f)

    if hausd is not None:
        # surface-approximation veto (Mmg -hausd) at FULL width, BEFORE
        # the top-K cut: a post-cut veto would let permanently-vetoed
        # boundary edges pin budget slots every wave, starving legal
        # candidates ranked past K
        from .analysis import boundary_vertex_normals, \
            ridge_vertex_tangents
        vn = boundary_vertex_normals(mesh)
        on_bdy_f = (et.etag & MG_BDY) != 0
        d_f = mesh.vert[vb_f] - mesh.vert[va_f]
        na_f, nb_f = vn[va_f], vn[vb_f]
        t_a = d_f - na_f * jnp.sum(na_f * d_f, -1, keepdims=True)
        t_b = d_f - nb_f * jnp.sum(nb_f * d_f, -1, keepdims=True)
        dev = jnp.linalg.norm(0.125 * (t_a - t_b), axis=-1)
        # feature-line edges: curvature deviation along the LINE
        # tangent, not the (multivalued) surface normal — matches the
        # tangent-circle lift in split_wave
        tanv = vtan if vtan is not None \
            else ridge_vertex_tangents(mesh, et=et)
        on_line_f = (et.etag & (MG_GEO | MG_REF)) != 0
        ta_l = tanv[va_f] * jnp.sum(tanv[va_f] * d_f, -1, keepdims=True)
        tb_l = tanv[vb_f] * jnp.sum(tanv[vb_f] * d_f, -1, keepdims=True)
        dev_l = jnp.linalg.norm(0.125 * (ta_l - tb_l), axis=-1)
        dev = jnp.where(on_line_f, dev_l, dev)
        pre = pre & ~(on_bdy_f & (dev > hausd))

    # Everything below (top-K sort, role derivation, tet-centric
    # validity, claims, apply) is lax.cond-skipped when NO candidate
    # exists — at convergence the wave then costs only the table +
    # candidacy masks.
    def _idle(_):
        return CollapseResult(mesh, jnp.zeros((), jnp.int32),
                              jnp.zeros((), bool), jnp.zeros((), bool))

    def _act(_):
        # top-K compaction (scripts/wave_time.py cost lever): the K highest-
        # priority candidates go through the heavy machinery; claims stay
        # exact (they resolve against global vertex/tet pools) and deferred
        # candidates are picked up by the next wave.  Priority: shortest
        # edges in sizing mode; WORST incident tet in sliver mode (the pass
        # exists to raise the min — edge length would misrank the targets)
        from .edges import wave_budget, topk_prep
        K = min(Efull, wave_budget(capT, budget_div))
        if sliver_q is None:
            prio = lens
        else:
            eq_min = jnp.full(Efull, jnp.inf).at[
                et.edge_id.reshape(-1)].min(
                jnp.repeat(jnp.where(bad_tet, q_tet, jnp.inf), 6),
                mode="drop")
            prio = eq_min
        # fused scoring prep + top-K by priority (smallest first) without
        # a full-width argsort
        neg, npre = topk_prep(pre, prio)
        defer = npre > K
        _, sel = jax.lax.top_k(neg, K)
        lens_c = lens[sel]
        va = va_f[sel]
        vb = vb_f[sel]
        cand = pre[sel]
        del_b = rem_b_f[sel]
        rm = jnp.where(del_b, vb, va)
        kp = jnp.where(del_b, va, vb)

        # sort-free claim priority: (s, t) = (-length, unique hash); shorter
        # edge = higher score, ties broken without spatial bias
        s, t = claim_channels(-lens_c, cand)
        # per-vertex top remover and its kept endpoint; v_s/v_t are the
        # per-vertex channel maxima (the sortless 'rmpri')
        is_top, v_s, v_t = scatter_argmax2(rm, s, t, cand, capP)
        kept_of = jnp.zeros(capP, jnp.int32).at[
            jnp.where(is_top, rm, capP)].set(kp, mode="drop",
                                             unique_indices=True)

        # --- claims + validity, claimed-corner only --------------------------
        # tet claim = (s,t)-max removal target over the 4 corners.  A
        # remover contested at ANY ball tet (some corner holds a target
        # that is not that tet's claim max) can never win, so geometric
        # validity and the simulated ball quality only need evaluating at
        # each tet's single CLAIMED corner — [T]-width instead of the old
        # [4T] stacked variants, with the contested/invalid cases folded
        # into the same ball-quality scatter as -inf rows
        # (scripts/split_stage_time.py: validity+ballq was ~28 ms).
        tv = mesh.tet                                          # [T,4]
        vpos = mesh.vert[tv]                                   # [T,4,3]
        vs_c = v_s[tv]                                         # [T,4] score max
        vt_c = v_t[tv]                                         # [T,4] tie max
        has_c = jnp.isfinite(vs_c)        # corner is a top-removal target
        tmax_s = jnp.max(jnp.where(mesh.tmask[:, None], vs_c, NEG_INF), axis=1)
        selc = (vs_c == tmax_s[:, None]) & jnp.isfinite(tmax_s)[:, None]
        tsel = jnp.where(selc, vt_c, PRI_MIN)
        tmax_t = jnp.max(tsel, axis=1)
        corner_max = selc & (tsel == tmax_t[:, None])
        claimed = corner_max & has_c                           # [T,4]
        has_cl = jnp.any(claimed, axis=1) & mesh.tmask
        kc = jnp.argmax(claimed, axis=1)                       # [T]
        ar0 = jnp.arange(capT)
        rm_v = tv[ar0, kc]                                     # claimed target
        kept_v = kept_of[jnp.clip(rm_v, 0, capP - 1)]          # its kept vtx
        kept_p = mesh.vert[jnp.clip(kept_v, 0, capP - 1)]      # [T,3]
        # does this tet also contain the kept vertex? then it dies with the
        # collapse — it drops out of the surviving ball, no checks needed
        contains_kept = jnp.zeros(capT, bool)
        for j in range(4):
            contains_kept = contains_kept | \
                ((tv[:, j] == kept_v) & (j != kc))
        active_cl = has_cl & ~contains_kept

        # single simulated variant per tet: claimed corner -> kept position
        oh = jnp.arange(4)[None, :] == kc[:, None]             # [T,4]
        p = jnp.where(oh[..., None], kept_p[:, None, :], vpos)
        d1 = p[:, 1] - p[:, 0]
        d2 = p[:, 2] - p[:, 0]
        d3 = p[:, 3] - p[:, 0]
        vol = jnp.einsum("ti,ti->t", d1, jnp.cross(d2, d3)) / 6.0
        bad = vol <= EPSD
        # fold-over: boundary faces containing the claimed corner must
        # keep their orientation
        for f in range(4):
            idx = IDIR[f]
            n_old = jnp.cross(vpos[:, idx[1]] - vpos[:, idx[0]],
                              vpos[:, idx[2]] - vpos[:, idx[0]])
            n_new = jnp.cross(p[:, idx[1]] - p[:, idx[0]],
                              p[:, idx[2]] - p[:, idx[0]])
            isb = (mesh.ftag[:, f] & MG_BDY) != 0
            flip = jnp.sum(n_old * n_new, -1) <= 0
            bad = bad | (isb & flip & (kc != f))
        # overlong new edges from the kept vertex to the other corners
        if met.ndim == 1:
            from .quality import edge_length_iso
            for j in range(4):
                lnew = edge_length_iso(kept_p, p[:, j],
                                       met[jnp.clip(kept_v, 0, capP - 1)],
                                       met[tv[:, j]])
                bad = bad | ((lnew > lmax) & (kc != j))

        # --- ball-quality gate ----------------------------------------------
        # Normal mode: the collapse must not degrade the ball min quality
        # below 30% of its old value nor below the degeneracy floor
        # (MMG5_colver's calnew/calold check).  Sliver mode: STRICT
        # improvement.  Invalid geometry and contested balls force -inf.
        from .quality import quality_from_points
        mq = None if met.ndim == 1 else met[tv]
        # q_tet is a closure variable in sliver mode — don't shadow it
        q_ball = quality_from_points(vpos, mq) if sliver_q is None \
            else q_tet
        idx4c = jnp.concatenate(
            [jnp.where(mesh.tmask, tv[:, k], capP) for k in range(4)])
        ballq_old = jnp.full(capP + 1, jnp.inf).at[idx4c].min(
            jnp.tile(jnp.where(mesh.tmask, q_ball, jnp.inf), 4),
            mode="drop")
        mq_cl = None if mq is None else jnp.where(
            oh[..., None], met[jnp.clip(kept_v, 0, capP - 1)][:, None, :],
            mq)
        qv = quality_from_points(p, mq_cl)                     # [T]
        row_val = jnp.where(bad, -jnp.inf, qv)
        # contested rows: a corner holding a target that is NOT the tet's
        # claim max kills that target via a -inf contribution
        mism4 = jnp.concatenate(
            [has_c[:, k] & ~corner_max[:, k] & mesh.tmask for k in range(4)])
        idx_cat = jnp.concatenate(
            [jnp.where(active_cl, rm_v, capP),
             jnp.where(mism4, jnp.concatenate([tv[:, k] for k in range(4)]),
                       capP)])
        val_cat = jnp.concatenate(
            [jnp.where(active_cl, row_val, jnp.inf),
             jnp.where(mism4, -jnp.inf, jnp.inf)])
        ballq_new = jnp.full(capP + 1, jnp.inf).at[idx_cat].min(
            val_cat, mode="drop")
        if sliver_q is None:
            ok = (ballq_new[:capP] >= 0.3 * ballq_old[:capP]) & \
                 (ballq_new[:capP] > QUAL_FLOOR)
            geombad = ~ok
        else:
            improves = ballq_new[:capP] > ballq_old[:capP]
            geombad = ~improves

        # vertex claims: a winner must be the (s,t)-max among all candidate
        # edges touching either of its endpoints (both roles) — one
        # concatenated scatter per channel
        idx_rk = jnp.concatenate([jnp.where(cand, rm, capP),
                                  jnp.where(cand, kp, capP)])
        cl_s = jnp.full(capP + 1, NEG_INF).at[idx_rk].max(
            jnp.tile(s, 2), mode="drop")
        eq_rm = cand & (s == cl_s[rm])
        eq_kp = cand & (s == cl_s[kp])
        idx_rk2 = jnp.concatenate([jnp.where(eq_rm, rm, capP),
                                   jnp.where(eq_kp, kp, capP)])
        cl_t = jnp.full(capP + 1, PRI_MIN).at[idx_rk2].max(
            jnp.tile(t, 2), mode="drop")
        claim_ok = eq_rm & (t == cl_t[rm]) & eq_kp & (t == cl_t[kp])

        # contested balls are already folded into geombad via -inf rows
        win = cand & is_top & ~geombad[rm] & claim_ok
        ncol = jnp.sum(win.astype(jnp.int32))

        # --- apply: vertex remap + dead shell tets ---------------------------
        # the whole apply phase (remap gather, dup detection, keyed tag
        # joins — 3 full-width sorts) is lax.cond-skipped when the wave has
        # no winner: near convergence most waves are empty and the apply
        # cost would dominate the cycle for nothing
        def _apply_collapse(_):
            return _collapse_apply(mesh, met, win, rm, kp, capT, capP)

        def _skip_collapse(_):
            return (mesh.tet, mesh.tmask, mesh.vmask, mesh.ftag, mesh.fref,
                    mesh.etag, jnp.zeros((), bool))

        new_tet, tmask, vmask, ftag, fref, etag, schg = jax.lax.cond(
            ncol > 0, _apply_collapse, _skip_collapse, None)

        out = dataclasses.replace(
            mesh, tet=new_tet, tmask=tmask, vmask=vmask, ftag=ftag,
            fref=fref, etag=etag)
        return CollapseResult(out, ncol, schg, defer)

    return jax.lax.cond(jnp.any(pre), _act, _idle, None)


def _collapse_apply(mesh: Mesh, met, win, rm, kp, capT, capP):
    """Apply phase of collapse_wave (see there): vertex remap, dead-tet
    detection, and the donor tag/ref keyed joins."""
    remap = jnp.arange(capP, dtype=jnp.int32)
    remap = remap.at[jnp.where(win, rm, capP)].set(
        kp, mode="drop", unique_indices=True)   # winners exclusive at rm
    new_tet = remap[mesh.tet]
    # dead = any duplicated vertex pair (tet contained rm and kp)
    dup = jnp.zeros(capT, bool)
    for i in range(4):
        for j in range(i + 1, 4):
            dup = dup | (new_tet[:, i] == new_tet[:, j])
    dead = dup & mesh.tmask
    tmask = mesh.tmask & ~dead
    vmask = mesh.vmask.at[jnp.where(win, rm, capP)].set(False, mode="drop")

    # Donor joins are themselves cond-skipped when no dying tet carries
    # any face/edge tag or face ref — interior collapses (the bulk of a
    # sizing run) then skip all 3 join sorts.
    has_donor_info = jnp.any(
        dead[:, None] & ((mesh.ftag != 0) | (mesh.fref != 0))) | \
        jnp.any(jnp.repeat(dead, 6) & (mesh.etag.reshape(-1) != 0))

    def _joins(_):
        return _collapse_tag_joins(mesh, new_tet, dead, tmask, capT, capP)

    def _no_joins(_):
        return mesh.ftag, mesh.fref, mesh.etag

    ftag, fref, etag = jax.lax.cond(has_donor_info, _joins, _no_joins,
                                    None)
    return new_tet, tmask, vmask, ftag, fref, etag, has_donor_info


def _tag_joins_core(new_tet, ftag, fref, etag, donor, recv, capP):
    """Width-generic body of the donor tag/ref keyed joins.

    Runs over n = new_tet.shape[0] tet rows (the FULL capT width or a
    compacted donor band — see ``_collapse_tag_joins``) and returns the
    ADD arrays only: ``(add_tag [n,4] uint32, add_ref [n,4] int32,
    add_e [n,6] uint32)``.  Rows with neither donor nor recv set are
    keyed with the int32-max sentinel and contribute/receive nothing.
    Segment aggregation is OR/max — commutative and associative — so the
    adds per row are independent of the sort width n: a band containing
    every donor and every key-matching receiver produces bit-identical
    adds to the full-width join.
    """
    n = new_tet.shape[0]
    # --- transfer face tags/refs from dying tets: keyed face join --------
    # Every face of the REMAPPED mesh is keyed by its sorted vertex
    # triple; dying tets donate their old tags/refs, alive slots with the
    # same key OR/max them in.  This covers BOTH transfer cases: the
    # shared-slot case (dying tet's interior face survives on the
    # neighbor — the old adja-based transfer) and the remapped-boundary
    # case (dying tet's tagged surface face (rm,u,w) becomes (kp,u,w),
    # owned by a tet that never shared a slot with the donor — the old
    # code recovered only the MG_BDY bit via the next build_adjacency and
    # silently dropped fref/REQ/REF bits).
    from ..core.mesh import tet_face_vertices
    from .edges import PACK_LIMIT, segmented_or, segmented_max
    F4 = n * 4
    fvn = jnp.sort(tet_face_vertices(new_tet).reshape(F4, 3), axis=1)
    donor_f = jnp.repeat(donor, 4)
    recv_f = jnp.repeat(recv, 4)
    rel_f = donor_f | recv_f
    i32max = jnp.iinfo(jnp.int32).max
    if capP <= PACK_LIMIT:
        w_f = jnp.where(rel_f, fvn[:, 1] * capP + fvn[:, 2], i32max)
        k0_f = jnp.where(rel_f, fvn[:, 0], i32max)
        order_f = jnp.lexsort((w_f, k0_f))
        k0s, k1s = k0_f[order_f], w_f[order_f]
        first_f = jnp.concatenate(
            [jnp.array([True]), (k0s[1:] != k0s[:-1]) | (k1s[1:] != k1s[:-1])])
    else:
        c0 = jnp.where(rel_f, fvn[:, 0], i32max)
        c1 = jnp.where(rel_f, fvn[:, 1], i32max)
        c2 = jnp.where(rel_f, fvn[:, 2], i32max)
        order_f = jnp.lexsort((c2, c1, c0))
        k0s, k1s, k2s = c0[order_f], c1[order_f], c2[order_f]
        first_f = jnp.concatenate(
            [jnp.array([True]), (k0s[1:] != k0s[:-1]) |
             (k1s[1:] != k1s[:-1]) | (k2s[1:] != k2s[:-1])])
    seg_f = jax.lax.associative_scan(
        jnp.maximum, jnp.where(first_f, jnp.arange(F4), 0))
    is_last_f = jnp.concatenate([first_f[1:], jnp.array([True])])
    dtag_f = jnp.where(donor_f[order_f], ftag.reshape(F4)[order_f], 0)
    or_f = segmented_or(first_f, dtag_f)
    tot_tag = jnp.zeros(F4, jnp.uint32).at[
        jnp.where(is_last_f, seg_f, F4)].set(
        or_f, mode="drop", unique_indices=True)
    add_tag_s = tot_tag[seg_f]
    add_tag = jnp.zeros(F4, jnp.uint32).at[order_f].set(
        add_tag_s, unique_indices=True).reshape(n, 4)
    dref_f = jnp.where(donor_f[order_f], fref.reshape(F4)[order_f], 0)
    mx_f = segmented_max(first_f, dref_f)
    tot_ref = jnp.zeros(F4, jnp.int32).at[
        jnp.where(is_last_f, seg_f, F4)].set(
        mx_f, mode="drop", unique_indices=True)
    add_ref = jnp.zeros(F4, jnp.int32).at[order_f].set(
        tot_ref[seg_f], unique_indices=True).reshape(n, 4)

    # --- transfer edge tags from dying tets to surviving slots -----------
    # The collapse merges edge (u,rm) into (u,kp).  Mmg's colver unites
    # the tags of the merged edges; without this, a ridge edge loses its
    # MG_GEO when every tet carrying the tagged slot dies (all its shell
    # tets contain rm AND kp) — the untagged ridge then erodes (volume
    # loss).  Batched equivalent: a keyed OR-join — sort ALL remapped
    # slot keys (surviving slots as receivers, dying tets' slots as
    # donors of their OLD tag) and OR each key group's donor tags into
    # its receivers.
    from ..core.mesh import tet_edge_vertices
    from .edges import sort_pairs
    ev_new = tet_edge_vertices(new_tet).reshape(n * 6, 2)
    ka = jnp.minimum(ev_new[:, 0], ev_new[:, 1])
    kb = jnp.maximum(ev_new[:, 0], ev_new[:, 1])
    alive_s = jnp.repeat(recv, 6)
    donor_s = jnp.repeat(donor, 6)
    rel = alive_s | donor_s
    order, _, _, first = sort_pairs(ka, kb, rel, capP)
    seg = jax.lax.associative_scan(
        jnp.maximum, jnp.where(first, jnp.arange(n * 6), 0))
    dtag = jnp.where(donor_s[order], etag.reshape(n * 6)[order], 0)
    # segment OR of donor tags, then broadcast the segment total back to
    # every member (the OR-scan total sits at the LAST member)
    or_fwd = segmented_or(first, dtag)
    is_last = jnp.concatenate([first[1:], jnp.array([True])])
    # per-segment total, scattered to the head slot then gathered by seg
    # id; buffer sized n6 exactly so the masked-out sentinel index n6 is
    # genuinely out of bounds (dropped) — required for unique_indices
    total_at_head = jnp.zeros(n * 6, jnp.uint32).at[
        jnp.where(is_last, seg, n * 6)].set(
        or_fwd, mode="drop", unique_indices=True)
    add_sorted = total_at_head[seg]                       # [capE] per slot
    add_e = jnp.zeros(n * 6, jnp.uint32).at[order].set(
        add_sorted, unique_indices=True).reshape(n, 6)
    return add_tag, add_ref, add_e


def collapse_band_width(capT: int) -> int:
    """Static donor-band width for ``_collapse_tag_joins``: geo-bucketed
    (utils/compilecache.bucket — the existing shape ladder, so no new
    shape families) from capT//4, never exceeding capT."""
    from ..utils.compilecache import bucket
    return bucket(max(1, capT // 4), floor=256, scheme="geo", cap=capT)


def _collapse_tag_joins(mesh: Mesh, new_tet, dead, tmask, capT, capP):
    """Keyed face/edge tag-transfer joins (see collapse_wave docstring).

    PARMMG_COLLAPSE_BAND (default on): a steady-state wave kills ~30
    tets, yet the joins sort 4*capT face keys and 6*capT edge keys.  The
    banded path compacts the join to the DONOR BAND — the dead tets plus
    every live tet containing a "relevant vertex" (a vertex of a
    remapped dead tet) — and scatters the adds back.

    Coverage proof (band result ≡ full result, bit for bit): every donor
    key (face/edge of a remapped dead tet) has all its endpoints among
    the relevant vertices, so any LIVE row matching a donor key contains
    ≥2 relevant vertices and is in the band by construction; every
    non-band row therefore lands in a segment with no donor and gets
    add = 0 in the full-width join — exactly the zeros the band scatter
    leaves behind.  Degenerate donor keys (the collapsed (kp,kp,·)
    faces/edges of a dead tet) can never match a live row, whose
    remapped vertices stay distinct.  Aggregation is OR/max, so segment
    results are independent of the sort width (see _tag_joins_core).
    The band width is static (collapse_band_width); when the band
    overflows it — a mass-collapse wave — a lax.cond falls back to the
    full-width join, which computes the identical result, so the switch
    itself is parity-safe.
    """
    import os

    def _merge(add_tag, add_ref, add_e):
        ftag = jnp.where(tmask[:, None], mesh.ftag | add_tag, mesh.ftag)
        fref = jnp.where(tmask[:, None] & (mesh.fref == 0) & (add_ref != 0),
                         add_ref, mesh.fref)
        etag = jnp.where(tmask[:, None], mesh.etag | add_e, mesh.etag)
        return ftag, fref, etag

    B = collapse_band_width(capT) \
        if os.environ.get("PARMMG_COLLAPSE_BAND", "") != "0" else capT
    if B >= capT:  # tiny meshes: the band ladder reaches capT anyway
        return _merge(*_tag_joins_core(
            new_tet, mesh.ftag, mesh.fref, mesh.etag, dead, tmask, capP))

    # relevant vertices: every vertex of a remapped dead tet
    rv = jnp.zeros(capP + 1, bool).at[
        jnp.where(dead[:, None], new_tet, capP).reshape(-1)].max(
        jnp.repeat(dead, 4), mode="drop")[:capP]
    band = dead | (tmask & jnp.any(rv[new_tet], axis=1))
    nband = jnp.sum(band.astype(jnp.int32))

    def _banded(_):
        rows = jnp.nonzero(band, size=B, fill_value=capT)[0]
        vrow = rows < capT
        rc = jnp.clip(rows, 0, capT - 1)
        bt, br, be = _tag_joins_core(
            new_tet[rc], mesh.ftag[rc], mesh.fref[rc], mesh.etag[rc],
            dead[rc] & vrow, tmask[rc] & vrow, capP)
        add_tag = jnp.zeros((capT, 4), jnp.uint32).at[rows].set(
            bt, mode="drop", unique_indices=True)
        add_ref = jnp.zeros((capT, 4), jnp.int32).at[rows].set(
            br, mode="drop", unique_indices=True)
        add_e = jnp.zeros((capT, 6), jnp.uint32).at[rows].set(
            be, mode="drop", unique_indices=True)
        return add_tag, add_ref, add_e

    def _full(_):
        return _tag_joins_core(new_tet, mesh.ftag, mesh.fref, mesh.etag,
                               dead, tmask, capP)

    return _merge(*jax.lax.cond(nband <= B, _banded, _full, None))
