"""Batched edge collapse — data-parallel replacement for Mmg's colver.

Reference behavior: short edges (metric length < 1/sqrt(2)) are removed by
merging one endpoint into the other; the shell tets die, the rest of the
removed vertex's ball is rewritten.  Constraints reproduced from Mmg's
``MMG5_colver`` checks + the ParMmg freeze contract (tag_pmmg.c:39-124):
required/corner/parallel vertices never move; boundary points only collapse
along boundary edges onto boundary points; ridge points only along ridges.

Independent-set scheduling (one wave):
  1. candidates = short, un-frozen edges; pick a *removed* endpoint per edge;
  2. per-vertex "top remover" priorities; geometric validity (positive
     volumes, no boundary fold-over, no overlong new edges) is evaluated for
     top removers only, tet-centrically;
  3. claims: a winner must be argmax at both endpoints and on every tet of
     the removed vertex's ball — so winner balls are disjoint and the
     per-candidate precheck stays exact under simultaneous application;
  4. apply via a vertex remap gather; shell tets (containing both endpoints)
     are invalidated; face tags of dying tets transfer to the surviving
     neighbor across (that face was interior, it becomes boundary iff it was
     tagged).
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.mesh import Mesh
from ..core.constants import (
    IDIR, LSHRT, LLONG, EPSD, MG_BDY, MG_CRN, MG_GEO, MG_NOM, MG_REF,
    MG_REQ, MG_PARBDY, QUAL_FLOOR)
from .edges import unique_edges, edge_lengths, unique_priority

_IDIR_J = jnp.asarray(IDIR)


class CollapseResult(NamedTuple):
    mesh: Mesh
    ncollapse: jax.Array


def _removable(vtag, other_vtag, edge_tag):
    """May vertex v (tags vtag) be deleted by collapsing along this edge?"""
    free = (vtag & (MG_REQ | MG_CRN | MG_PARBDY | MG_NOM)) == 0
    on_bdy = (vtag & MG_BDY) != 0
    bdy_ok = ~on_bdy | (((edge_tag & MG_BDY) != 0) &
                        ((other_vtag & MG_BDY) != 0))
    on_geo = (vtag & MG_GEO) != 0
    # a ridge point may slide along its ridge onto another ridge point or
    # onto the corner terminating the ridge (Mmg chkcol_bdy semantics)
    geo_ok = ~on_geo | (((edge_tag & MG_GEO) != 0) &
                        ((other_vtag & (MG_GEO | MG_CRN)) != 0))
    # likewise a reference-edge point stays on its reference line
    on_ref = (vtag & MG_REF) != 0
    ref_ok = ~on_ref | (((edge_tag & MG_REF) != 0) &
                        ((other_vtag & (MG_REF | MG_CRN)) != 0))
    return free & bdy_ok & geo_ok & ref_ok


def collapse_wave(mesh: Mesh, met: jax.Array, lmin: float = LSHRT,
                  lmax: float = LLONG) -> CollapseResult:
    capT, capP = mesh.capT, mesh.capP
    et = unique_edges(mesh)
    lens = edge_lengths(mesh, et, met)
    va = jnp.clip(et.ev[:, 0], 0, capP - 1)
    vb = jnp.clip(et.ev[:, 1], 0, capP - 1)

    frozen_edge = (et.etag & (MG_REQ | MG_PARBDY)) != 0
    short = et.emask & (lens < lmin) & ~frozen_edge

    ta, tb = mesh.vtag[va], mesh.vtag[vb]
    rem_b = _removable(tb, ta, et.etag)      # can delete b (keep a)
    rem_a = _removable(ta, tb, et.etag)
    # prefer deleting the topologically freer endpoint; deterministic choice
    del_b = rem_b
    rm = jnp.where(del_b, vb, va)
    kp = jnp.where(del_b, va, vb)
    cand = short & (rem_a | rem_b)

    pri = unique_priority(-lens, cand)                     # short = high
    # per-vertex top remover and its kept endpoint
    rmpri = jnp.zeros(capP, jnp.int32).at[rm].max(jnp.where(cand, pri, 0))
    is_top = cand & (pri == rmpri[rm]) & (pri > 0)
    kept_of = jnp.zeros(capP, jnp.int32).at[
        jnp.where(is_top, rm, capP)].set(kp, mode="drop")

    # --- geometric validity of top removers, tet-centric -----------------
    # for each (tet, corner k): v = tet[k]; if v is a top-removal target,
    # simulate v -> kept_of[v] and test volumes / fold-over / new lengths.
    tv = mesh.tet                                          # [T,4]
    vpos = mesh.vert[tv]                                   # [T,4,3]
    vt = rmpri[tv]                                         # [T,4] pri or 0
    kept = kept_of[tv]                                     # [T,4]
    kept_pos = mesh.vert[kept]                             # [T,4,3]
    # does this tet also contain the kept vertex? then it dies, skip checks
    contains_kept = jnp.zeros((capT, 4), bool)
    for k in range(4):
        hit = jnp.zeros((capT,), bool)
        for j in range(4):
            hit = hit | ((tv[:, j] == kept[:, k]) & (j != k))
        contains_kept = contains_kept.at[:, k].set(hit)

    geombad = jnp.zeros(capP + 1, bool)
    newlong = jnp.zeros(capP + 1, bool)
    for k in range(4):
        active = (vt[:, k] > 0) & mesh.tmask & ~contains_kept[:, k]
        p = vpos.at[:, k].set(kept_pos[:, k])              # moved corner
        d1 = p[:, 1] - p[:, 0]
        d2 = p[:, 2] - p[:, 0]
        d3 = p[:, 3] - p[:, 0]
        vol = jnp.einsum("ti,ti->t", d1, jnp.cross(d2, d3)) / 6.0
        bad = vol <= EPSD
        # fold-over: boundary faces containing corner k keep orientation
        for f in range(4):
            if k == f:
                continue  # face opposite k does not contain k
            idx = IDIR[f]
            n_old = jnp.cross(vpos[:, idx[1]] - vpos[:, idx[0]],
                              vpos[:, idx[2]] - vpos[:, idx[0]])
            n_new = jnp.cross(p[:, idx[1]] - p[:, idx[0]],
                              p[:, idx[2]] - p[:, idx[0]])
            isb = (mesh.ftag[:, f] & MG_BDY) != 0
            flip = jnp.sum(n_old * n_new, -1) <= 0
            bad = bad | (isb & flip)
        # overlong new edges from the kept vertex to the other corners
        if met.ndim == 1:
            from .quality import edge_length_iso
            for j in range(4):
                if j == k:
                    continue
                lnew = edge_length_iso(
                    kept_pos[:, k], p[:, j],
                    met[kept[:, k]], met[tv[:, j]])
                bad_l = lnew > lmax
                newlong = newlong.at[jnp.where(active, tv[:, k], capP)].max(
                    bad_l, mode="drop")
        geombad = geombad.at[jnp.where(active, tv[:, k], capP)].max(
            bad, mode="drop")
    geombad = geombad[:capP] | newlong[:capP]

    # --- claims ----------------------------------------------------------
    vclaim = jnp.zeros(capP, jnp.int32)
    vclaim = vclaim.at[rm].max(jnp.where(cand, pri, 0))
    vclaim = vclaim.at[kp].max(jnp.where(cand, pri, 0))
    # tet claim = max removal-pri over its 4 corners
    tclaim = jnp.max(vt, axis=1)
    # bad claim: some tet of rm's ball is contested by a higher claim
    contested = jnp.zeros(capP + 1, bool)
    for k in range(4):
        mism = (vt[:, k] > 0) & (tclaim != vt[:, k]) & mesh.tmask
        contested = contested.at[
            jnp.where(mesh.tmask, tv[:, k], capP)].max(mism, mode="drop")
    contested = contested[:capP]

    win = (cand & (pri == rmpri[rm]) & ~geombad[rm] & ~contested[rm]
           & (pri == vclaim[rm]) & (pri == vclaim[kp]))

    # --- apply: vertex remap + dead shell tets ---------------------------
    remap = jnp.arange(capP, dtype=jnp.int32)
    remap = remap.at[jnp.where(win, rm, capP)].set(kp, mode="drop")
    new_tet = remap[mesh.tet]
    # dead = any duplicated vertex pair (tet contained rm and kp)
    dup = jnp.zeros(capT, bool)
    for i in range(4):
        for j in range(i + 1, 4):
            dup = dup | (new_tet[:, i] == new_tet[:, j])
    dead = dup & mesh.tmask
    tmask = mesh.tmask & ~dead
    vmask = mesh.vmask.at[jnp.where(win, rm, capP)].set(False, mode="drop")

    # --- transfer face tags from dying tets to surviving neighbors -------
    # the shared face sits at (nb, nf) on the other side; it survives there
    nb = mesh.adja >> 2
    nf = mesh.adja & 3
    has_nb = mesh.adja >= 0
    nb_safe = jnp.clip(nb, 0, capT - 1)
    nb_dead = dead[nb_safe] & has_nb
    # receiving side: tet alive, neighbor dying, neighbor's face tagged
    recv = (~dead)[:, None] & nb_dead & mesh.tmask[:, None]
    nbr_ftag = mesh.ftag[nb_safe, nf]
    nbr_fref = mesh.fref[nb_safe, nf]
    ftag = jnp.where(recv, mesh.ftag | nbr_ftag, mesh.ftag)
    fref = jnp.where(recv & (nbr_fref != 0), nbr_fref, mesh.fref)

    ncol = jnp.sum(win.astype(jnp.int32))
    out = dataclasses.replace(
        mesh, tet=new_tet, tmask=tmask, vmask=vmask, ftag=ftag, fref=fref)
    return CollapseResult(out, ncol)
