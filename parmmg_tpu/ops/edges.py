"""Unique mesh edges and edge->tet incidence, sort-based (jittable).

Replaces Mmg's edge hash tables (``MMG5_hashEdge`` family; the reference's
parallel variants live in hash_pmmg.c:38-234) with the sort/segment idiom:
all 6*capT tet edges are materialized, lexsorted by (min vid, max vid), and
the first occurrence of each key becomes the representative unique edge.
Every (tet, local-edge) slot learns its unique-edge id — that gather table is
what the split/collapse/swap kernels use to look up per-edge decisions.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.mesh import Mesh, tet_edge_vertices
from ..core.constants import IARE
from . import pallas_kernels as pk

_INT32_MAX = 2147483647


PACK_LIMIT = 46340     # floor(sqrt(2^31)): a*capP+b stays in int32


def wave_budget(capT: int, div: int = 8) -> int:
    """Per-wave top-K compaction budget shared by every wave kernel: the
    K = max(2048, capT//div) highest-priority candidates go through the
    heavy geometry/routing/scatter machinery (cost is linear in index
    count — scripts/wave_time.py); the rest are deferred to the next
    wave.  The untimed polish passes div=2 for full coverage."""
    return max(2048, capT // div)


def free_rows(mask: jax.Array, K: int):
    """First ``K`` dead rows (``mask`` False) — the slot-reusing
    allocation pool shared by the allocating wave kernels (split,
    swap23, swapgen).

    Allocating from the watermark cursor alone (the rounds-1..3 scheme)
    never reclaims interior rows freed by collapses; once the watermark
    reaches capacity every split is capacity-dropped FOREVER even when
    most of the array is dead — observed as a permanently-overflowing
    bench at ~92% live fill (the reference instead reuses freed slots
    through its linked free lists, MMG3D_newElt/MMG3D_delElt).  One
    [cap]-width compaction per allocating wave buys exact slot reuse;
    watermarks remain monotone upper bounds (used-prefix hints only —
    mesh.py documents masks as authoritative).

    Returns (rows [K] int32, cap-padded; nfree scalar int32)."""
    cap = mask.shape[0]
    rows = jnp.nonzero(~mask, size=K, fill_value=cap)[0].astype(jnp.int32)
    return rows, jnp.sum(~mask, dtype=jnp.int32)


def sort_pairs(a: jax.Array, b: jax.Array, valid: jax.Array, capP: int):
    """Sort (a, b) id pairs ascending, invalid slots last.

    Returns (order, ka, kb, first): the sort permutation, the sorted key
    columns (INT32_MAX on invalid slots), and the unique-segment heads.
    When ids fit (capP <= PACK_LIMIT — always true for ParMmg-sized
    shards, the reference targets ~30k-element groups) both keys pack
    into ONE int32 so the TPU runs a single O(n log^2 n) sort instead of
    two stable lexsort passes — the sorts are the measured hot spot of
    every wave.
    """
    if capP <= PACK_LIMIT:
        key = jnp.where(valid, a * capP + b, _INT32_MAX)
        order = pk.sort_perm((key,), ref=lambda ws: jnp.argsort(ws[0]))
        ks = key[order]
        first = pk.segment_first((ks,))
        inv = ks == _INT32_MAX
        ka = jnp.where(inv, _INT32_MAX, ks // capP)
        kb = jnp.where(inv, _INT32_MAX, ks % capP)
        return order, ka, kb, first
    aa = jnp.where(valid, a, _INT32_MAX)
    bb = jnp.where(valid, b, _INT32_MAX)
    order = pk.sort_perm((aa, bb), ref=lambda ws: jnp.lexsort((ws[1], ws[0])))
    ka, kb = aa[order], bb[order]
    first = pk.segment_first((ka, kb))
    return order, ka, kb, first


def segmented_or(first: jax.Array, values: jax.Array) -> jax.Array:
    """Inclusive segmented bitwise-OR scan over sorted segments.

    ``first`` marks segment heads; returns the running OR within each
    segment (the LAST element of a segment holds the full segment OR).
    Shared by unique_edges and the collapse edge/face tag-transfer joins.
    """
    def seg_or(pair_a, pair_b):
        fa, va = pair_a
        fb, vb = pair_b
        return fa | fb, jnp.where(fb, vb, va | vb)
    _, out = jax.lax.associative_scan(seg_or, (first, values))
    return out


def segmented_max(first: jax.Array, values: jax.Array) -> jax.Array:
    """Inclusive segmented max scan (same contract as segmented_or)."""
    def seg_max(pair_a, pair_b):
        fa, va = pair_a
        fb, vb = pair_b
        return fa | fb, jnp.where(fb, vb, jnp.maximum(va, vb))
    _, out = jax.lax.associative_scan(seg_max, (first, values))
    return out


class EdgeTable(NamedTuple):
    """Unique edges of the mesh.  capE = 6*capT slots, masked.

    ``edge_id[t, e]`` maps each tet-edge slot to its unique edge id
    (garbage on invalid tets).  ``ev`` are the (min, max) vertex ids of the
    unique edge; ``emask`` marks live unique-edge slots; ``etag`` is the OR
    of the per-tet edge tags over all incident tets (tags must agree, the
    OR makes the table robust to partially-propagated tags); ``nshell`` is
    the number of incident tets (the shell size).
    """
    ev: jax.Array       # [capE, 2] int32
    emask: jax.Array    # [capE] bool
    etag: jax.Array     # [capE] uint32
    nshell: jax.Array   # [capE] int32
    edge_id: jax.Array  # [capT, 6] int32
    shell3: jax.Array   # [capE, S] int32 first S shell tet ids (-1 unused;
    #                     S = 3 by default, wider for the generalized swaps
    #                     — see unique_edges(shell_slots=...))
    shell_rank: jax.Array  # [capT, 6] int32 rank of this tet in the edge's
    #                     shell (ascending tet id) — free by-product of the
    #                     sort; lets split_wave skip its own ranking sort
    skey: jax.Array = None  # [capE] ascending packed keys a*capP+b of the
    #                     internal sort (duplicates included, INT32_MAX on
    #                     invalid slots); empty [0] when capP > PACK_LIMIT.
    #                     Lets swap22's duplicate-diagonal existence probe
    #                     binary-search without re-sorting the table


def unique_edges(mesh: Mesh, shell_slots: int = 3) -> EdgeTable:
    """``shell_slots=0`` skips the shell-tet-id scatter entirely (returns
    ``shell3`` with zero columns) — split/collapse never read it, only the
    swap kernels do, and every scatter at [6*capT] width is a measured
    multi-ms item on this device (scripts/tpu_microbench.py,
    scripts/split_stage_time.py)."""
    capT = mesh.capT
    n6 = capT * 6
    ev = tet_edge_vertices(mesh.tet).reshape(n6, 2)
    a = jnp.minimum(ev[:, 0], ev[:, 1])
    b = jnp.maximum(ev[:, 0], ev[:, 1])
    valid = jnp.repeat(mesh.tmask, 6)
    order, ka, kb, first = sort_pairs(a, b, valid, mesh.capP)
    return _edges_epilogue(mesh, order, ka, kb, first, shell_slots)


def unique_edges_from_sorted(mesh: Mesh, order: jax.Array, ks: jax.Array,
                             shell_slots: int = 0) -> EdgeTable:
    """EdgeTable from a precomputed PACKED edge sort: ``order`` is the
    stable sort permutation over the 6*capT slot keys and ``ks`` the
    ascending packed keys (a*capP+b, INT32_MAX on invalid slots) —
    exactly what ``sort_pairs``' packed branch produces.  This is the
    epilogue of :func:`unique_edges` factored out so the incremental
    path (ops/topo_incr) can feed a band-merged sort through the SAME
    code: tag payloads are re-gathered from the CURRENT mesh here, so
    the retained state never carries tags.  Requires
    ``capP <= PACK_LIMIT``."""
    first = pk.segment_first((ks,))
    inv = ks == _INT32_MAX
    ka = jnp.where(inv, _INT32_MAX, ks // mesh.capP)
    kb = jnp.where(inv, _INT32_MAX, ks % mesh.capP)
    return _edges_epilogue(mesh, order, ka, kb, first, shell_slots)


def _edges_epilogue(mesh: Mesh, order, ka, kb, first,
                    shell_slots: int) -> EdgeTable:
    """Shared unique_edges epilogue: segment scan + scatters from the
    sorted key columns (bit-neutral factoring of the original body)."""
    capT = mesh.capT
    n6 = capT * 6
    valid_s = ka != _INT32_MAX          # sorted-order validity, no gather
    # unique-edge id of each sorted slot = index of its segment head.
    # ONE tuple-carry scan produces the segment head AND the running
    # etag-OR together (two separate scans were a measured cost).
    pos = jnp.arange(n6)
    tags = jnp.where(valid_s, mesh.etag.reshape(n6)[order], 0)

    def seg_comb2(pa, pb):
        fa, ha, va = pa
        fb, hb, vb = pb
        return (fa | fb, jnp.where(fb, hb, jnp.maximum(ha, hb)),
                jnp.where(fb, vb, va | vb))

    _, seg_head, or_scan = jax.lax.associative_scan(
        seg_comb2, (first, jnp.where(first, pos, 0), tags))
    eid_sorted = seg_head
    rank = pos - seg_head
    is_last = jnp.concatenate([first[1:], jnp.array([True])])

    emask = first & valid_s
    ev_u = jnp.stack([ka, kb], axis=1)
    # per-unique-edge values (full OR of tags; shell count = last rank+1)
    # land at the head slot with ONE packed 2-column scatter
    head_pay = jnp.stack([or_scan.astype(jnp.int32),
                          (rank + 1).astype(jnp.int32)], axis=1)
    head_tbl = jnp.zeros((n6, 2), jnp.int32).at[
        jnp.where(is_last, eid_sorted, n6)].set(
        head_pay, mode="drop", unique_indices=True)
    etag = head_tbl[:, 0].astype(jnp.uint32)
    nshell = head_tbl[:, 1]
    # per (tet, local edge) slot: unique edge id + rank within the shell
    # (stable lexsort keeps equal keys in slot order = ascending tet id),
    # scattered back through the permutation in ONE packed scatter
    back_pay = jnp.stack([eid_sorted.astype(jnp.int32),
                          rank.astype(jnp.int32)], axis=1)
    back = jnp.zeros((n6, 2), jnp.int32).at[order].set(
        back_pay, unique_indices=True)
    edge_id = back[:, 0].reshape(capT, 6)
    shell_rank = back[:, 1].reshape(capT, 6)
    # first-S shell tet ids per edge (3 for the 3-2 swap; 6-7 for the
    # generalized ring swaps): rank within segment
    if shell_slots > 0:
        tet_of_slot = (order // 6).astype(jnp.int32)
        shell3 = jnp.full((n6, shell_slots), -1, jnp.int32)
        tgt_e = jnp.where(valid_s & (rank < shell_slots), eid_sorted, n6)
        shell3 = shell3.at[tgt_e, jnp.clip(rank, 0, shell_slots - 1)].set(
            tet_of_slot, mode="drop", unique_indices=True)
    else:
        shell3 = jnp.zeros((n6, 0), jnp.int32)
    if shell_slots > 0 and mesh.capP <= PACK_LIMIT:
        # only the swap kernels consume skey; the slim split/collapse
        # tables (shell_slots=0) skip materializing it
        skey = jnp.where(valid_s, ka * mesh.capP + kb, _INT32_MAX)
    else:
        skey = jnp.zeros((0,), jnp.int32)
    return EdgeTable(ev=ev_u, emask=emask, etag=etag, nshell=nshell,
                     edge_id=edge_id, shell3=shell3, shell_rank=shell_rank,
                     skey=skey)


def edge_lengths(mesh: Mesh, et: EdgeTable, met: jax.Array) -> jax.Array:
    """[capE] metric length of each unique edge (garbage on dead slots).

    TPU lowering uses the fused Pallas kernels; every other platform the
    jnp formula — selected per lowering platform (NOT per process
    default backend, which may be a TPU plugin while this computation
    lowers for CPU devices)."""
    from functools import partial
    from .quality import edge_length_iso, edge_length_ani
    from .pallas_kernels import (use_pallas, pallas_forced,
                                 edge_length_iso_pallas,
                                 edge_length_ani_pallas)
    i0 = jnp.clip(et.ev[:, 0], 0, mesh.capP - 1)
    i1 = jnp.clip(et.ev[:, 1], 0, mesh.capP - 1)
    if met.ndim == 1:
        # pack (x, y, z, h) so each endpoint costs ONE row gather
        # (gather cost is linear in index count on this device)
        vm = jnp.concatenate([mesh.vert, met[:, None]], axis=1)
        r0, r1 = vm[i0], vm[i1]
        p0, p1 = r0[:, :3], r1[:, :3]
        m0, m1 = r0[:, 3], r1[:, 3]
    else:
        p0, p1 = mesh.vert[i0], mesh.vert[i1]
        m0, m1 = met[i0], met[i1]
    pal = (edge_length_iso_pallas if met.ndim == 1
           else edge_length_ani_pallas)
    ref = edge_length_iso if met.ndim == 1 else edge_length_ani
    if use_pallas():
        # the off-TPU branch is chosen at LOWERING time (the process
        # default may be a TPU plugin while this computation lowers for
        # CPU devices): jnp formula normally, interpreted Pallas kernel
        # when PARMMG_TPU_PALLAS=1 forces kernel numerics everywhere
        # (jaxcompat shim: 0.4.x lowers every branch — see jaxcompat)
        from ..utils.jaxcompat import platform_dependent
        off_tpu = partial(pal, interpret=True) if pallas_forced() else ref
        return platform_dependent(
            p0, p1, m0, m1,
            tpu=partial(pal, interpret=False), default=off_tpu)
    return ref(p0, p1, m0, m1)


def topk_prep(cand: jax.Array, val: jax.Array):
    """Top-k budget prep for a wave's candidate cut.

    Returns ``(where(cand, -val, -inf), sum(cand))`` — the score vector
    handed to ``lax.top_k`` and the int32 candidate count behind every
    ``defer`` flag.  These are exactly the two expressions each wave
    wrote inline, so wiring this in is bit-neutral; the TPU lowering
    fuses them into one VMEM pass + cross-block reduction
    (pallas_kernels.score_count_pallas, gated by PARMMG_PALLAS_SCORE),
    every other platform keeps the jnp reference.
    """
    from functools import partial
    from .pallas_kernels import (use_pallas, pallas_forced,
                                 pallas_score_enabled, score_count_pallas)

    def ref(c, v):
        return jnp.where(c, -v, -jnp.inf), jnp.sum(c.astype(jnp.int32))

    if use_pallas() and pallas_score_enabled():
        from ..utils.jaxcompat import platform_dependent
        off_tpu = (partial(score_count_pallas, interpret=True)
                   if pallas_forced() else ref)
        return platform_dependent(
            cand, val,
            tpu=partial(score_count_pallas, interpret=False),
            default=off_tpu)
    return ref(cand, val)


def topk_prep3(cand: jax.Array, v0: jax.Array, v1: jax.Array,
               v2: jax.Array):
    """``topk_prep`` fused with the 3-way shell-quality minimum of
    swap_edges_wave: ``val = min(v0, min(v1, v2))`` in that exact
    association order (f32 minimum is exact, so the fused kernel is
    bit-identical to the reference chain)."""
    from functools import partial
    from .pallas_kernels import (use_pallas, pallas_forced,
                                 pallas_score_enabled, score3_count_pallas)

    def ref(c, a, b, d):
        v = jnp.minimum(a, jnp.minimum(b, d))
        return jnp.where(c, -v, -jnp.inf), jnp.sum(c.astype(jnp.int32))

    if use_pallas() and pallas_score_enabled():
        from ..utils.jaxcompat import platform_dependent
        off_tpu = (partial(score3_count_pallas, interpret=True)
                   if pallas_forced() else ref)
        return platform_dependent(
            cand, v0, v1, v2,
            tpu=partial(score3_count_pallas, interpret=False),
            default=off_tpu)
    return ref(cand, v0, v1, v2)


def claim_shells(score, cand, shells, capT):
    """Exclusive multi-slot claims: winner must be the two-channel
    (score, tie-hash) max at EVERY shell slot it touches.  Winners are
    pairwise shell-disjoint: two winners sharing a slot would both be
    that slot's pooled (s,t)-max — impossible, t is unique.  Shared by
    the swap kernels (each candidate claims its 2-3 cavity tets).

    All shells are claimed in ONE concatenated scatter per channel and
    checked with one stacked gather — per-op overhead dominates
    scatter/gather cost on this device (scripts/tpu_microbench.py)."""
    ps, pt = claim_channels(score, cand)
    k = len(shells)
    shs = jnp.stack(shells)                               # [k, E]
    idx = jnp.where(cand[None, :], shs, capT).reshape(-1)
    cl_s = jnp.full(capT + 1, NEG_INF).at[idx].max(
        jnp.tile(ps, k), mode="drop")
    eq = cand & jnp.all(ps[None, :] == cl_s[shs], axis=0)
    idx2 = jnp.where(eq[None, :], shs, capT).reshape(-1)
    cl_t = jnp.full(capT + 1, PRI_MIN).at[idx2].max(
        jnp.tile(pt, k), mode="drop")
    win = eq & jnp.all(pt[None, :] == cl_t[shs], axis=0)
    return win


def unique_priority(score: jax.Array, mask: jax.Array) -> jax.Array:
    """Turn a float score into a unique int32 priority (higher = better).

    Ties are broken by argsort rank; masked slots get priority 0.  Used by
    the independent-set claim resolution in the remesh kernels (the
    parallel analogue of Mmg's sequential everything-in-order
    application).  NOTE a sortless quantized variant (score top-bits +
    slot-index tie-break) was tried and reverted: index-ordered tie-breaks
    spatially bias the winner sets and measurably degrade final min
    quality.

    Retained for reference/tests; the production waves use the sort-free
    two-channel scheme below (full-precision f32 score + bijective-hash
    tie-break), which has the same total order without the O(n log^2 n)
    TPU sort and without the spatial bias of index tie-breaks.
    """
    n = score.shape[0]
    neg = jnp.where(mask, -score, jnp.inf)
    order = priority_order(neg)       # best (highest score) first
    rank = jnp.zeros(n, jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))
    pri = n - rank                    # in [1, n], unique
    return jnp.where(mask, pri, 0).astype(jnp.int32)


def priority_order(neg: jax.Array) -> jax.Array:
    """Stable ascending argsort of the negated-score vector — the
    priority rank's sort leg, dispatched to the Pallas radix engine on
    TPU (PARMMG_PALLAS_SORT).  The radix image of f32 preserves jax's
    stable comparator order exactly (pallas_kernels.f32_sort_u32), and
    LSD stability reproduces the documented argsort-rank tie-break (the
    lane index is the implicit minor word)."""
    return pk.sort_perm_f32(neg, ref=jnp.argsort)


# ---------------------------------------------------------------------------
# Sort-free claim priorities.
#
# The waves need a deterministic TOTAL order over candidate entities to
# resolve claim conflicts.  A rank (sort) gives one, but TPU sorts are
# O(n log^2 n) bitonic passes.  Instead compare candidates by the pair
#   (score: float32, tie: int32)
# lexicographically: the score keeps its FULL f32 precision (no
# quantization), and the tie channel is a *bijective* integer mix of the
# slot index — unique by construction, pseudo-random in order, so equal
# scores (ubiquitous in structured meshes) break without spatial bias.
# Claim resolution then needs only elementwise max / scatter-max passes:
# first on the score channel, then on the tie channel restricted to
# score-maximal slots.
# ---------------------------------------------------------------------------
PRI_MIN = jnp.int32(-2147483648)     # tie-channel sentinel (< every hash)
NEG_INF = jnp.float32(-jnp.inf)      # score-channel sentinel


def tie_hash(n: int, salt: int = 0) -> jax.Array:
    """Unique pseudo-random int32 per slot: a bijective avalanche mix of
    the index (odd multiplications and xor-shifts are invertible mod
    2^32), so distinct slots NEVER collide — the total order is exact."""
    x = jnp.arange(n, dtype=jnp.uint32) + jnp.uint32(salt) * jnp.uint32(
        2246822519)
    x = x * jnp.uint32(2654435761)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(2246822519)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(3266489917)
    x = x ^ (x >> 16)
    return x.astype(jnp.int32)


def claim_channels(score: jax.Array, mask: jax.Array, salt: int = 0):
    """(s, t) channels for the two-channel claim scheme: masked slots get
    (-inf, PRI_MIN) and lose every comparison."""
    s = jnp.where(mask, score.astype(jnp.float32), NEG_INF)
    t = jnp.where(mask, tie_hash(score.shape[0], salt), PRI_MIN)
    return s, t


def scatter_argmax2(site: jax.Array, s: jax.Array, t: jax.Array,
                    mask: jax.Array, nsites: int):
    """Is each slot the unique (s,t)-max among slots scattered to its site?

    Returns (is_max [slots] bool, c_s [nsites+1], c_t [nsites+1]):
    ``is_max`` is True iff ``mask`` and no other slot with the same
    ``site`` has a lexicographically larger (s, t); c_s/c_t are the
    per-site channel maxima (sentinels where no slot landed).  Two
    scatter-max passes; exact because t is unique.
    """
    sited = jnp.clip(site, 0, nsites - 1)
    safe = jnp.where(mask, site, nsites)
    c_s = jnp.full(nsites + 1, NEG_INF).at[safe].max(
        jnp.where(mask, s, NEG_INF), mode="drop")
    at_max = mask & (s == c_s[sited])
    safe2 = jnp.where(at_max, site, nsites)
    c_t = jnp.full(nsites + 1, PRI_MIN).at[safe2].max(
        jnp.where(at_max, t, PRI_MIN), mode="drop")
    return at_max & (t == c_t[sited]), c_s, c_t


def morton_codes(pts: jax.Array, valid: jax.Array, bits: int = 10):
    """[n] int32 morton (Z-order) codes of 3D points, normalized over
    the bounding box of the ``valid`` rows; ``3*bits <= 30`` so the code
    stays in int32.  Shared by the smoothing/worklist window rotation
    (ops/smooth.morton_window_mask) and the device cluster assignment of
    the graph-balancing probe (parallel/migrate_dev.graph_probe) — one
    curve definition, one set of bit masks."""
    lo = jnp.min(jnp.where(valid[:, None], pts, jnp.inf), axis=0)
    hi = jnp.max(jnp.where(valid[:, None], pts, -jnp.inf), axis=0)
    u = jnp.clip((pts - lo) / jnp.maximum(hi - lo, 1e-30),
                 0.0, 1.0 - 1e-7)
    q = (u * float(1 << bits)).astype(jnp.uint32)

    def spread(x):          # interleave up to 10 bits -> every 3rd bit
        x = (x | (x << 16)) & jnp.uint32(0x030000FF)
        x = (x | (x << 8)) & jnp.uint32(0x0300F00F)
        x = (x | (x << 4)) & jnp.uint32(0x030C30C3)
        x = (x | (x << 2)) & jnp.uint32(0x09249249)
        return x

    code = spread(q[:, 0]) | (spread(q[:, 1]) << 1) | \
        (spread(q[:, 2]) << 2)
    return code.astype(jnp.int32)
