"""Unique mesh edges and edge->tet incidence, sort-based (jittable).

Replaces Mmg's edge hash tables (``MMG5_hashEdge`` family; the reference's
parallel variants live in hash_pmmg.c:38-234) with the sort/segment idiom:
all 6*capT tet edges are materialized, lexsorted by (min vid, max vid), and
the first occurrence of each key becomes the representative unique edge.
Every (tet, local-edge) slot learns its unique-edge id — that gather table is
what the split/collapse/swap kernels use to look up per-edge decisions.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.mesh import Mesh, tet_edge_vertices
from ..core.constants import IARE

_INT32_MAX = 2147483647


class EdgeTable(NamedTuple):
    """Unique edges of the mesh.  capE = 6*capT slots, masked.

    ``edge_id[t, e]`` maps each tet-edge slot to its unique edge id
    (garbage on invalid tets).  ``ev`` are the (min, max) vertex ids of the
    unique edge; ``emask`` marks live unique-edge slots; ``etag`` is the OR
    of the per-tet edge tags over all incident tets (tags must agree, the
    OR makes the table robust to partially-propagated tags); ``nshell`` is
    the number of incident tets (the shell size).
    """
    ev: jax.Array       # [capE, 2] int32
    emask: jax.Array    # [capE] bool
    etag: jax.Array     # [capE] uint32
    nshell: jax.Array   # [capE] int32
    edge_id: jax.Array  # [capT, 6] int32
    shell3: jax.Array   # [capE, 3] int32 first 3 shell tet ids (-1 unused)


def unique_edges(mesh: Mesh) -> EdgeTable:
    capT = mesh.capT
    ev = tet_edge_vertices(mesh.tet).reshape(capT * 6, 2)
    a = jnp.minimum(ev[:, 0], ev[:, 1])
    b = jnp.maximum(ev[:, 0], ev[:, 1])
    valid = jnp.repeat(mesh.tmask, 6)
    a = jnp.where(valid, a, _INT32_MAX)
    b = jnp.where(valid, b, _INT32_MAX)
    order = jnp.lexsort((b, a))
    ka, kb = a[order], b[order]
    first = jnp.concatenate([jnp.array([True]),
                             (ka[1:] != ka[:-1]) | (kb[1:] != kb[:-1])])
    # unique-edge id of each sorted slot = index of its segment head
    seg_head = jnp.where(first, jnp.arange(capT * 6), 0)
    seg_head = jax.lax.associative_scan(jnp.maximum, seg_head)
    # representative id = position of the segment head in SORTED order; we
    # use the sorted position itself as the unique edge id (stable, dense
    # enough). Scatter back to (tet, local edge) slots.
    eid_sorted = seg_head
    eid = jnp.zeros(capT * 6, jnp.int32).at[order].set(
        eid_sorted.astype(jnp.int32))
    edge_id = eid.reshape(capT, 6)

    emask = first & (ka != _INT32_MAX)
    ev_u = jnp.stack([ka, kb], axis=1)
    # shell size + tag OR per unique edge (segment sums via scatter-add)
    ones = (valid[order]).astype(jnp.int32)
    nshell = jnp.zeros(capT * 6, jnp.int32).at[eid_sorted].add(ones)
    tags = mesh.etag.reshape(capT * 6)[order]
    tags = jnp.where(valid[order], tags, 0)
    # true bitwise-OR over each segment (a scatter-max would let a slot
    # with a numerically larger tag shadow e.g. the MG_REQ bit of another
    # slot of the same edge): segmented inclusive OR scan, then the last
    # element of each segment holds the full OR and is scattered to the
    # segment head (= the unique-edge id)
    def seg_or(pair_a, pair_b):
        fa, va = pair_a
        fb, vb = pair_b
        return fa | fb, jnp.where(fb, vb, va | vb)
    _, or_scan = jax.lax.associative_scan(seg_or, (first, tags))
    n6 = capT * 6
    is_last = jnp.concatenate([first[1:], jnp.array([True])])
    etag = jnp.zeros(n6, jnp.uint32).at[
        jnp.where(is_last, eid_sorted, n6)].set(or_scan, mode="drop")
    # first-3 shell tet ids per edge (for 3-2 swaps): rank within segment
    pos = jnp.arange(capT * 6)
    rank = pos - seg_head
    tet_of_slot = (order // 6).astype(jnp.int32)
    shell3 = jnp.full((capT * 6, 3), -1, jnp.int32)
    tgt_e = jnp.where(valid[order] & (rank < 3), eid_sorted, capT * 6)
    shell3 = shell3.at[tgt_e, jnp.clip(rank, 0, 2)].set(
        tet_of_slot, mode="drop")
    return EdgeTable(ev=ev_u, emask=emask, etag=etag, nshell=nshell,
                     edge_id=edge_id, shell3=shell3)


def edge_lengths(mesh: Mesh, et: EdgeTable, met: jax.Array) -> jax.Array:
    """[capE] metric length of each unique edge (garbage on dead slots)."""
    from .quality import edge_length_iso, edge_length_ani
    from .pallas_kernels import (use_pallas, edge_length_iso_pallas,
                                 edge_length_ani_pallas)
    p0 = mesh.vert[jnp.clip(et.ev[:, 0], 0, mesh.capP - 1)]
    p1 = mesh.vert[jnp.clip(et.ev[:, 1], 0, mesh.capP - 1)]
    i0 = jnp.clip(et.ev[:, 0], 0, mesh.capP - 1)
    i1 = jnp.clip(et.ev[:, 1], 0, mesh.capP - 1)
    if met.ndim == 1:
        if use_pallas():
            return edge_length_iso_pallas(p0, p1, met[i0], met[i1])
        return edge_length_iso(p0, p1, met[i0], met[i1])
    if use_pallas():
        return edge_length_ani_pallas(p0, p1, met[i0], met[i1])
    return edge_length_ani(p0, p1, met[i0], met[i1])


def unique_priority(score: jax.Array, mask: jax.Array) -> jax.Array:
    """Turn a float score into a unique int32 priority (higher = better).

    Ties are broken by argsort rank; masked slots get priority 0.  Used by
    the independent-set claim resolution in the remesh kernels (the
    parallel analogue of Mmg's sequential everything-in-order
    application).  NOTE a sortless quantized variant (score top-bits +
    slot-index tie-break) was tried and reverted: index-ordered tie-breaks
    spatially bias the winner sets and measurably degrade final min
    quality.
    """
    n = score.shape[0]
    neg = jnp.where(mask, -score, jnp.inf)
    order = jnp.argsort(neg)          # best (highest score) first
    rank = jnp.zeros(n, jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))
    pri = n - rank                    # in [1, n], unique
    return jnp.where(mask, pri, 0).astype(jnp.int32)
