"""Background-mesh localization and P1 interpolation.

Reference semantics (/root/reference/src/locate_pmmg.c,
interpmesh_pmmg.c, barycoord_pmmg.c): after each remesh iteration the
metric and user solution fields are transferred from the *background* copy
of the pre-remesh mesh onto the new vertices: each new vertex is located in
the background tetrahedrization by an adjacency walk with barycentric sign
tests (exhaustive + closest-element fallbacks), then P1-interpolated
(``PMMG_interp4bar_iso``; for anisotropic metrics the *inverse* tensors are
combined barycentrically and inverted back, interpmesh_pmmg.c:240-271).

TPU design: the walk is a ``lax.while_loop`` vmapped over all query points
(every point walks independently, all lanes advance in lockstep until the
slowest converges); the exhaustive fallback is a masked argmax over all
background tets, batched only over the failed points via a second pass.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.mesh import Mesh
from ..core.constants import EPSD


class LocateResult(NamedTuple):
    tet: jax.Array     # [M] int32 containing (or closest) background tet
    bary: jax.Array    # [M,4] barycentric coordinates in that tet
    failed: jax.Array  # [M] bool walk failed (fallback used)
    steps: jax.Array   # [M] int32 walk steps (locateStats analogue)


def _barycentric(bg_vert, bg_tet, tid, pt):
    """Barycentric coords of pt in background tet tid (normalized)."""
    tv = bg_tet[tid]
    p = bg_vert[tv]                      # [4,3]
    d1 = p[1] - p[0]
    d2 = p[2] - p[0]
    d3 = p[3] - p[0]
    vol = jnp.sum(d1 * jnp.cross(d2, d3))
    # face-opposite volumes
    def sub(i):
        q = p.at[i].set(pt)
        e1 = q[1] - q[0]
        e2 = q[2] - q[0]
        e3 = q[3] - q[0]
        return jnp.sum(e1 * jnp.cross(e2, e3))
    vols = jnp.stack([sub(0), sub(1), sub(2), sub(3)])
    return vols / jnp.where(jnp.abs(vol) > EPSD, vol, 1.0)


def locate_points(bg: Mesh, points: jax.Array, start: jax.Array,
                  max_steps: int = 256, tol: float = -1e-4) -> LocateResult:
    """Walk-locate each point in the background mesh.

    ``start``: [M] initial tet hints (the reference warm-starts from
    ``point->src`` under USE_POINTMAP, locate_pmmg.c:931; callers pass the
    creation-time parent tet or 0).
    """
    capT = bg.capT

    def walk_one(pt, t0):
        def cond(state):
            t, done, steps, prev = state
            return (~done) & (steps < max_steps)

        def body(state):
            t, done, steps, prev = state
            bar = _barycentric(bg.vert, bg.tet, t, pt)
            inside = jnp.min(bar) >= tol
            worst = jnp.argmin(bar)
            nxt_enc = bg.adja[t, worst]
            nxt = nxt_enc >> 2
            blocked = nxt_enc < 0
            new_t = jnp.where(inside | blocked, t, nxt)
            # dead end at boundary counts as done-but-failed; flag via prev
            return (new_t.astype(jnp.int32), inside | blocked,
                    steps + 1, jnp.where(blocked & ~inside, 1, prev))

        t, done, steps, failflag = jax.lax.while_loop(
            cond, body, (t0.astype(jnp.int32), False, 0, 0))
        bar = _barycentric(bg.vert, bg.tet, t, pt)
        ok = jnp.min(bar) >= tol
        return t, bar, ~ok | (failflag == 1) & ~ok, steps

    tids, bary, failed, steps = jax.vmap(walk_one)(points, start)

    # --- exhaustive fallback for failed walks (argmax of min-barycoord) --
    def exhaustive(pt):
        tv = bg.tet
        p = bg.vert[tv]                                   # [T,4,3]
        d1 = p[:, 1] - p[:, 0]
        d2 = p[:, 2] - p[:, 0]
        d3 = p[:, 3] - p[:, 0]
        vol = jnp.sum(d1 * jnp.cross(d2, d3), -1)
        bars = []
        for i in range(4):
            q = p.at[:, i].set(pt)
            e1 = q[:, 1] - q[:, 0]
            e2 = q[:, 2] - q[:, 0]
            e3 = q[:, 3] - q[:, 0]
            bars.append(jnp.sum(e1 * jnp.cross(e2, e3), -1))
        bar = jnp.stack(bars, 1) / jnp.where(
            jnp.abs(vol)[:, None] > EPSD, vol[:, None], 1.0)
        score = jnp.where(bg.tmask, jnp.min(bar, 1), -jnp.inf)
        best = jnp.argmax(score)
        return best.astype(jnp.int32), bar[best]

    # Exhaustive fallback only for the points whose walk FAILED, and in
    # chunks bounding the [chunk, capT] intermediates (the reference
    # runs its exhaustive pass per failed walk too, locate_pmmg.c:737).
    # An all-points batched pass materializes [M, capT] under vmap —
    # tens of GB at the 1M-10M-tet target.  Host-level subsetting is
    # fine: every caller is a host driver function.
    import numpy as np
    fidx = np.where(np.asarray(failed))[0]
    if len(fidx):
        fb_t, fb_b = _chunked_vmap(exhaustive, points[fidx],
                                   _fallback_chunk(capT))
        tids = tids.at[fidx].set(fb_t)
        bary = bary.at[fidx].set(fb_b)
    return LocateResult(tids, bary, failed, steps)


def _fallback_chunk(nf: int) -> int:
    """Points per exhaustive-fallback chunk: bounds the [chunk, nf]
    vmap intermediates to ~2^24 elements (<=200 MB of f32 temporaries)."""
    return max(1, (1 << 24) // max(nf, 1))


def _chunked_vmap(fn, pts, chunk: int):
    """vmap ``fn`` over points in host-side chunks (memory-bounded)."""
    outs = [jax.vmap(fn)(pts[i: i + chunk])
            for i in range(0, pts.shape[0], chunk)]
    return tuple(jnp.concatenate(parts) for parts in zip(*outs))


# ---------------------------------------------------------------------------
# P1 interpolation
# ---------------------------------------------------------------------------
def interp_p1(values: jax.Array, bg_tet: jax.Array, loc: LocateResult):
    """P1-interpolate per-vertex values at located points.

    values: [capP_bg, ...] -> returns [M, ...].
    Barycentric coords are clipped to the simplex (closest-point semantics
    of PMMG_barycoord*_getClosest for points that fell outside).
    """
    w = jnp.clip(loc.bary, 0.0, 1.0)
    w = w / jnp.maximum(jnp.sum(w, -1, keepdims=True), EPSD)
    tv = bg_tet[loc.tet]                                  # [M,4]
    vals = values[tv]                                     # [M,4,...]
    wexp = w.reshape(w.shape + (1,) * (vals.ndim - 2))
    return jnp.sum(vals * wexp, axis=1)


def interp_metric_ani(met6: jax.Array, bg_tet: jax.Array, loc: LocateResult):
    """Aniso metric interpolation via inverse-tensor combination.

    Exactly the reference scheme (interpmesh_pmmg.c:240-271): invert each
    corner tensor, combine with barycentric weights, invert back.
    """
    from .quality import unpack_sym
    w = jnp.clip(loc.bary, 0.0, 1.0)
    w = w / jnp.maximum(jnp.sum(w, -1, keepdims=True), EPSD)
    tv = bg_tet[loc.tet]
    M = unpack_sym(met6[tv])                              # [M,4,3,3]
    Minv = jnp.linalg.inv(M + jnp.eye(3) * EPSD)
    comb = jnp.einsum("mk,mkij->mij", w, Minv)
    out = jnp.linalg.inv(comb + jnp.eye(3) * EPSD)
    return jnp.stack([out[:, 0, 0], out[:, 0, 1], out[:, 0, 2],
                      out[:, 1, 1], out[:, 1, 2], out[:, 2, 2]], -1)


# ---------------------------------------------------------------------------
# surface localization (PMMG_locatePointBdy analogue, locate_pmmg.c:587)
# ---------------------------------------------------------------------------
class SurfLocateResult(NamedTuple):
    tri: jax.Array     # [M] int32 surface slot (4*tet+face) in the bg mesh
    bary: jax.Array    # [M,3] triangle barycentric coordinates (clipped)
    dist: jax.Array    # [M] distance to the triangle plane (signed)
    failed: jax.Array  # [M] bool walk failed (closest-tria fallback used)


def surface_triangulation(bg: Mesh):
    """Background boundary surface as a static-shape triangle soup.

    Returns (tri [4T,3] vertex ids, fmask [4T], tadj [4T,3]): one slot per
    (tet, face); ``tadj[t, i]`` is the neighbor surface slot across the
    edge opposite local vertex i (or -1).  The sort-based edge pairing is
    the surface analogue of build_adjacency — replaces the reference's
    ``PMMG_precompute_nodeTrias`` + hash walk prep (locate_pmmg.c:68-206).
    Non-manifold edges (> 2 incident boundary faces) pair arbitrarily;
    the exhaustive fallback covers walks that cross them wrongly.
    """
    from ..core.constants import IDIR, MG_BDY
    from .edges import PACK_LIMIT
    capT = bg.capT
    F = capT * 4
    fmask = ((bg.ftag & MG_BDY) != 0) & bg.tmask[:, None]
    tri = bg.tet[:, jnp.asarray(IDIR)].reshape(F, 3)
    fm = fmask.reshape(F)
    big = jnp.iinfo(jnp.int32).max
    # the 3 edges of each tri, edge i opposite local vertex i
    e_pairs = [(1, 2), (0, 2), (0, 1)]
    tadj = jnp.full((F, 3), -1, jnp.int32)
    slot = jnp.arange(F, dtype=jnp.int32)
    kas, kbs, slots, eloc = [], [], [], []
    for i, (a, b) in enumerate(e_pairs):
        kas.append(jnp.where(fm, jnp.minimum(tri[:, a], tri[:, b]), big))
        kbs.append(jnp.where(fm, jnp.maximum(tri[:, a], tri[:, b]), big))
        slots.append(slot)
        eloc.append(jnp.full(F, i, jnp.int32))
    ka = jnp.concatenate(kas)
    kb = jnp.concatenate(kbs)
    sl = jnp.concatenate(slots)
    el = jnp.concatenate(eloc)
    if bg.capP <= PACK_LIMIT:
        # both ids fit one int32 key (the edges.py packing convention)
        k = jnp.where(ka == big, big, ka * bg.capP + kb)
        order = jnp.argsort(k)
        ks = k[order]
        invalid = ks == big
        eq_next = (ks[1:] == ks[:-1]) & ~invalid[:-1]
    else:
        # no x64 on TPU: two-column lexsort instead of packing (the same
        # fallback ops/edges.py:sort_pairs uses)
        order = jnp.lexsort((kb, ka))
        ka_s, kb_s = ka[order], kb[order]
        invalid = ka_s == big
        eq_next = (ka_s[1:] == ka_s[:-1]) & (kb_s[1:] == kb_s[:-1]) \
            & ~invalid[:-1]
    sls, els = sl[order], el[order]
    same_next = jnp.concatenate([eq_next, jnp.array([False])])
    same_prev = jnp.concatenate([jnp.array([False]), eq_next])
    idx = jnp.arange(3 * F)
    partner = jnp.where(same_next, idx + 1,
                        jnp.where(same_prev, idx - 1, idx))
    matched = same_next | same_prev
    nb_slot = jnp.where(matched, sls[partner], -1)
    tadj = tadj.at[sls, els].set(nb_slot, unique_indices=True)
    return tri, fm, tadj


def _bar_tri(p, a, b, c):
    n = jnp.cross(b - a, c - a)
    n2 = jnp.maximum(jnp.sum(n * n), EPSD)
    w0 = jnp.sum(jnp.cross(b - p, c - p) * n)
    w1 = jnp.sum(jnp.cross(c - p, a - p) * n)
    w2 = jnp.sum(jnp.cross(a - p, b - p) * n)
    bar = jnp.stack([w0, w1, w2]) / n2
    dist = jnp.sum((p - a) * n) / jnp.sqrt(n2)
    return bar, dist


def locate_points_bdy(bg: Mesh, points: jax.Array,
                      start: jax.Array | None = None,
                      max_steps: int = 256,
                      tol: float = -1e-4) -> SurfLocateResult:
    """Surface walk-localization of boundary points on the background
    boundary triangulation (PMMG_locatePointBdy, locate_pmmg.c:587).

    The walk moves across the edge with the most negative projected
    barycentric; vertex/edge hits (the reference's cone/wedge tests,
    locate_pmmg.c:209,286) are realized by CLIPPED barycentrics — a point
    past a vertex/edge interpolates from that vertex/edge exactly, the
    ``PMMG_barycoord2d_getClosest`` semantics (barycoord_pmmg.c:324).
    """
    tri, fm, tadj = surface_triangulation(bg)
    first = jnp.argmax(fm).astype(jnp.int32)   # some surface slot
    if start is None:
        start = jnp.full(points.shape[0], first, jnp.int32)
    else:
        start = jnp.where(fm[jnp.clip(start, 0, tri.shape[0] - 1)],
                          start, first).astype(jnp.int32)

    def walk_one(pt, t0):
        def cond(state):
            t, done, steps = state
            return (~done) & (steps < max_steps)

        def body(state):
            t, done, steps = state
            v = bg.vert[tri[t]]
            bar, _ = _bar_tri(pt, v[0], v[1], v[2])
            inside = jnp.min(bar) >= tol
            worst = jnp.argmin(bar)
            nxt = tadj[t, worst]
            blocked = nxt < 0
            new_t = jnp.where(inside | blocked, t, nxt)
            return new_t.astype(jnp.int32), inside | blocked, steps + 1

        t, done, _ = jax.lax.while_loop(
            cond, body, (t0, False, 0))
        v = bg.vert[tri[t]]
        bar, dist = _bar_tri(pt, v[0], v[1], v[2])
        ok = jnp.min(bar) >= tol
        # distance of the CLIPPED point: the projected-inside test alone
        # is wrong on closed surfaces (a point on one side of the body
        # projects inside far triangles on the other side); the true
        # closest triangle is arbitrated below
        cb = jnp.clip(bar, 0.0, 1.0)
        cb = cb / jnp.maximum(jnp.sum(cb), EPSD)
        dclip = jnp.linalg.norm(pt - cb @ v)
        # landing-triangle diameter: the wrong-side detector (a correct
        # landing has dclip ~ hausd << diam; a wrong-side landing is at
        # body-thickness distance)
        diam = jnp.sqrt(jnp.maximum(jnp.maximum(
            jnp.sum((v[1] - v[0]) ** 2), jnp.sum((v[2] - v[0]) ** 2)),
            jnp.sum((v[2] - v[1]) ** 2)))
        return t, bar, dist, ~ok, dclip, diam

    tids, bary, dist, failed, dwalk, diam = jax.vmap(walk_one)(points,
                                                              start)

    # exhaustive closest-triangle fallback (locate_pmmg.c:737 flavor):
    # clip barycentrics to the simplex, evaluate the clipped point, take
    # the nearest masked triangle
    def exhaustive(pt):
        v = bg.vert[tri]                                  # [F,3,3]
        n = jnp.cross(v[:, 1] - v[:, 0], v[:, 2] - v[:, 0])
        n2 = jnp.maximum(jnp.sum(n * n, -1), EPSD)
        w0 = jnp.sum(jnp.cross(v[:, 1] - pt, v[:, 2] - pt) * n, -1)
        w1 = jnp.sum(jnp.cross(v[:, 2] - pt, v[:, 0] - pt) * n, -1)
        w2 = jnp.sum(jnp.cross(v[:, 0] - pt, v[:, 1] - pt) * n, -1)
        bar = jnp.stack([w0, w1, w2], -1) / n2[:, None]
        cb = jnp.clip(bar, 0.0, 1.0)
        cb = cb / jnp.maximum(jnp.sum(cb, -1, keepdims=True), EPSD)
        q = jnp.einsum("fk,fkd->fd", cb, v)
        d = jnp.sum((pt - q) ** 2, -1)
        d = jnp.where(fm, d, jnp.inf)
        best = jnp.argmin(d)
        return best.astype(jnp.int32), cb[best], jnp.sqrt(d[best])

    # The closest triangle is authoritative whenever it is meaningfully
    # closer than the walk's landing spot (wrong-side landings on closed
    # surfaces); the walk is the accelerator, not the arbiter — the
    # role split of PMMG_locatePointBdy + closest-tria fallback.
    # The exhaustive pass runs ONLY on suspect points — failed walks and
    # landings farther from the surface than a fraction of the landing
    # triangle's diameter (a correct landing sits within ~hausd of its
    # triangle; a wrong-side landing is at body-thickness distance) —
    # and in chunks bounding the [chunk, F] vmap intermediates.  An
    # all-points batched pass is tens-to-hundreds of GB at the 1M-tet
    # target.  Host subsetting is fine: every caller is a host driver.
    # Threshold tradeoff: a wrong-side landing closer than 5% of the
    # landing triangle's diameter escapes arbitration — that needs wall
    # thickness < 0.05x the local surface triangle size, i.e. a surface
    # mesh that does not resolve the wall it bounds (the volume walk is
    # equally ambiguous there).  Correct landings sit within ~hausd
    # (<< 1e-2 diam) of their triangle.
    import numpy as np
    suspect = failed | (dwalk > 0.05 * diam + 1e-12)
    sidx = np.where(np.asarray(suspect))[0]
    use_fb = jnp.zeros(points.shape[0], bool)
    if len(sidx):
        fb_t, fb_b, fb_d = _chunked_vmap(
            exhaustive, points[sidx], _fallback_chunk(tri.shape[0]))
        use_s = failed[sidx] | (dwalk[sidx] > fb_d * (1.0 + 1e-3)
                                + 1e-12)
        tids = tids.at[sidx].set(jnp.where(use_s, fb_t, tids[sidx]))
        bary = bary.at[sidx].set(
            jnp.where(use_s[:, None], fb_b, bary[sidx]))
        dist = dist.at[sidx].set(jnp.where(use_s, fb_d, dist[sidx]))
        use_fb = use_fb.at[sidx].set(use_s)
    return SurfLocateResult(tids, bary, dist, use_fb)


def interp_p1_tri(values: jax.Array, bg: Mesh, loc: SurfLocateResult):
    """P1 interpolation over the located surface triangle
    (PMMG_interp3bar_iso semantics, interpmesh_pmmg.c:50-120)."""
    from ..core.constants import IDIR
    tri = bg.tet[:, jnp.asarray(IDIR)].reshape(bg.capT * 4, 3)
    w = jnp.clip(loc.bary, 0.0, 1.0)
    w = w / jnp.maximum(jnp.sum(w, -1, keepdims=True), EPSD)
    tv = tri[loc.tri]                                     # [M,3]
    vals = values[tv]                                     # [M,3,...]
    wexp = w.reshape(w.shape + (1,) * (vals.ndim - 2))
    return jnp.sum(vals * wexp, axis=1)


def interp_metric_ani_tri(met6: jax.Array, bg: Mesh,
                          loc: SurfLocateResult):
    """Aniso inverse-tensor interpolation over the surface triangle
    (PMMG_interp3bar_ani, interpmesh_pmmg.c:240-271)."""
    from ..core.constants import IDIR
    from .quality import unpack_sym
    tri = bg.tet[:, jnp.asarray(IDIR)].reshape(bg.capT * 4, 3)
    w = jnp.clip(loc.bary, 0.0, 1.0)
    w = w / jnp.maximum(jnp.sum(w, -1, keepdims=True), EPSD)
    tv = tri[loc.tri]
    M = unpack_sym(met6[tv])                              # [M,3,3,3]
    Minv = jnp.linalg.inv(M + jnp.eye(3) * EPSD)
    comb = jnp.einsum("mk,mkij->mij", w, Minv)
    out = jnp.linalg.inv(comb + jnp.eye(3) * EPSD)
    return jnp.stack([out[:, 0, 0], out[:, 0, 1], out[:, 0, 2],
                      out[:, 1, 1], out[:, 1, 2], out[:, 2, 2]], -1)


def interpolate_from_background(bg: Mesh, bg_met: jax.Array,
                                mesh: Mesh, met: jax.Array,
                                bg_fields: jax.Array | None = None,
                                only_new: jax.Array | None = None,
                                start: jax.Array | None = None):
    """Transfer metric (and fields) from a background mesh onto mesh's
    vertices — the driver-level analogue of PMMG_interpMetricsAndFields
    (interpmesh_pmmg.c:663).

    ``only_new``: bool [capP] — vertices to overwrite (default: all valid);
    others keep their current values (the reference copies unmoved/required
    points directly, interpmesh_pmmg.c:432).

    Boundary vertices are localized on the background SURFACE (triangle
    walk, locate_points_bdy) and interpolated from the located triangle —
    the reference's split between PMMG_locatePointBdy and
    PMMG_locatePointVol (interpmesh_pmmg.c:535-620): a volume walk puts a
    curved-surface point inside some tet whose P1 field is wrong for a
    point that geometrically lives on the surface.

    Returns (met', fields' or None, LocateResult).
    """
    import numpy as np
    from ..core.constants import MG_BDY
    sel = mesh.vmask if only_new is None else (only_new & mesh.vmask)
    pts = mesh.vert
    if start is None:
        start = jnp.zeros(mesh.capP, jnp.int32)
    loc = locate_points(bg, pts, start)
    on_bdy = (mesh.vtag & MG_BDY) != 0
    # the surface pass runs on the boundary-selected SUBSET only (this
    # is a host driver function): feeding all capP rows — dead slots
    # and interior points included — would send them through the
    # surface walk + closest-triangle machinery for nothing
    bidx = np.where(np.asarray(on_bdy & sel))[0]
    sloc = locate_points_bdy(bg, pts[bidx]) if len(bidx) else None
    if bg_met.ndim == 1:
        met_i = interp_p1(bg_met, bg.tet, loc)
        met_b = interp_p1_tri(bg_met, bg, sloc) \
            if sloc is not None else None
    else:
        met_i = interp_metric_ani(bg_met, bg.tet, loc)
        met_b = interp_metric_ani_tri(bg_met, bg, sloc) \
            if sloc is not None else None
    if sloc is not None:
        met_i = met_i.at[bidx].set(met_b.astype(met_i.dtype))
    met_out = jnp.where(sel.reshape(sel.shape + (1,) * (met.ndim - 1)),
                        met_i.astype(met.dtype), met)
    fields_out = None
    if bg_fields is not None:
        f_i = interp_p1(bg_fields, bg.tet, loc)
        if sloc is not None:
            f_b = interp_p1_tri(bg_fields, bg, sloc)
            f_i = f_i.at[bidx].set(f_b.astype(f_i.dtype))
        fields_out = f_i
    return met_out, fields_out, loc
