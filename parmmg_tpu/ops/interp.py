"""Background-mesh localization and P1 interpolation.

Reference semantics (/root/reference/src/locate_pmmg.c,
interpmesh_pmmg.c, barycoord_pmmg.c): after each remesh iteration the
metric and user solution fields are transferred from the *background* copy
of the pre-remesh mesh onto the new vertices: each new vertex is located in
the background tetrahedrization by an adjacency walk with barycentric sign
tests (exhaustive + closest-element fallbacks), then P1-interpolated
(``PMMG_interp4bar_iso``; for anisotropic metrics the *inverse* tensors are
combined barycentrically and inverted back, interpmesh_pmmg.c:240-271).

TPU design: the walk is a ``lax.while_loop`` vmapped over all query points
(every point walks independently, all lanes advance in lockstep until the
slowest converges); the exhaustive fallback is a masked argmax over all
background tets, batched only over the failed points via a second pass.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.mesh import Mesh
from ..core.constants import EPSD


class LocateResult(NamedTuple):
    tet: jax.Array     # [M] int32 containing (or closest) background tet
    bary: jax.Array    # [M,4] barycentric coordinates in that tet
    failed: jax.Array  # [M] bool walk failed (fallback used)
    steps: jax.Array   # [M] int32 walk steps (locateStats analogue)


def _barycentric(bg_vert, bg_tet, tid, pt):
    """Barycentric coords of pt in background tet tid (normalized)."""
    tv = bg_tet[tid]
    p = bg_vert[tv]                      # [4,3]
    d1 = p[1] - p[0]
    d2 = p[2] - p[0]
    d3 = p[3] - p[0]
    vol = jnp.sum(d1 * jnp.cross(d2, d3))
    # face-opposite volumes
    def sub(i):
        q = p.at[i].set(pt)
        e1 = q[1] - q[0]
        e2 = q[2] - q[0]
        e3 = q[3] - q[0]
        return jnp.sum(e1 * jnp.cross(e2, e3))
    vols = jnp.stack([sub(0), sub(1), sub(2), sub(3)])
    return vols / jnp.where(jnp.abs(vol) > EPSD, vol, 1.0)


def locate_points(bg: Mesh, points: jax.Array, start: jax.Array,
                  max_steps: int = 256, tol: float = -1e-4) -> LocateResult:
    """Walk-locate each point in the background mesh.

    ``start``: [M] initial tet hints (the reference warm-starts from
    ``point->src`` under USE_POINTMAP, locate_pmmg.c:931; callers pass the
    creation-time parent tet or 0).
    """
    capT = bg.capT

    def walk_one(pt, t0):
        def cond(state):
            t, done, steps, prev = state
            return (~done) & (steps < max_steps)

        def body(state):
            t, done, steps, prev = state
            bar = _barycentric(bg.vert, bg.tet, t, pt)
            inside = jnp.min(bar) >= tol
            worst = jnp.argmin(bar)
            nxt_enc = bg.adja[t, worst]
            nxt = nxt_enc >> 2
            blocked = nxt_enc < 0
            new_t = jnp.where(inside | blocked, t, nxt)
            # dead end at boundary counts as done-but-failed; flag via prev
            return (new_t.astype(jnp.int32), inside | blocked,
                    steps + 1, jnp.where(blocked & ~inside, 1, prev))

        t, done, steps, failflag = jax.lax.while_loop(
            cond, body, (t0.astype(jnp.int32), False, 0, 0))
        bar = _barycentric(bg.vert, bg.tet, t, pt)
        ok = jnp.min(bar) >= tol
        return t, bar, ~ok | (failflag == 1) & ~ok, steps

    tids, bary, failed, steps = jax.vmap(walk_one)(points, start)

    # --- exhaustive fallback for failed walks (argmax of min-barycoord) --
    def exhaustive(pt):
        tv = bg.tet
        p = bg.vert[tv]                                   # [T,4,3]
        d1 = p[:, 1] - p[:, 0]
        d2 = p[:, 2] - p[:, 0]
        d3 = p[:, 3] - p[:, 0]
        vol = jnp.sum(d1 * jnp.cross(d2, d3), -1)
        bars = []
        for i in range(4):
            q = p.at[:, i].set(pt)
            e1 = q[:, 1] - q[:, 0]
            e2 = q[:, 2] - q[:, 0]
            e3 = q[:, 3] - q[:, 0]
            bars.append(jnp.sum(e1 * jnp.cross(e2, e3), -1))
        bar = jnp.stack(bars, 1) / jnp.where(
            jnp.abs(vol)[:, None] > EPSD, vol[:, None], 1.0)
        score = jnp.where(bg.tmask, jnp.min(bar, 1), -jnp.inf)
        best = jnp.argmax(score)
        return best.astype(jnp.int32), bar[best]

    # run fallback for every point but only *use* it where failed (keeps
    # shapes static; cost bounded by doing it in one batched pass)
    fb_t, fb_b = jax.vmap(exhaustive)(points)
    tids = jnp.where(failed, fb_t, tids)
    bary = jnp.where(failed[:, None], fb_b, bary)
    return LocateResult(tids, bary, failed, steps)


# ---------------------------------------------------------------------------
# P1 interpolation
# ---------------------------------------------------------------------------
def interp_p1(values: jax.Array, bg_tet: jax.Array, loc: LocateResult):
    """P1-interpolate per-vertex values at located points.

    values: [capP_bg, ...] -> returns [M, ...].
    Barycentric coords are clipped to the simplex (closest-point semantics
    of PMMG_barycoord*_getClosest for points that fell outside).
    """
    w = jnp.clip(loc.bary, 0.0, 1.0)
    w = w / jnp.maximum(jnp.sum(w, -1, keepdims=True), EPSD)
    tv = bg_tet[loc.tet]                                  # [M,4]
    vals = values[tv]                                     # [M,4,...]
    wexp = w.reshape(w.shape + (1,) * (vals.ndim - 2))
    return jnp.sum(vals * wexp, axis=1)


def interp_metric_ani(met6: jax.Array, bg_tet: jax.Array, loc: LocateResult):
    """Aniso metric interpolation via inverse-tensor combination.

    Exactly the reference scheme (interpmesh_pmmg.c:240-271): invert each
    corner tensor, combine with barycentric weights, invert back.
    """
    from .quality import unpack_sym
    w = jnp.clip(loc.bary, 0.0, 1.0)
    w = w / jnp.maximum(jnp.sum(w, -1, keepdims=True), EPSD)
    tv = bg_tet[loc.tet]
    M = unpack_sym(met6[tv])                              # [M,4,3,3]
    Minv = jnp.linalg.inv(M + jnp.eye(3) * EPSD)
    comb = jnp.einsum("mk,mkij->mij", w, Minv)
    out = jnp.linalg.inv(comb + jnp.eye(3) * EPSD)
    return jnp.stack([out[:, 0, 0], out[:, 0, 1], out[:, 0, 2],
                      out[:, 1, 1], out[:, 1, 2], out[:, 2, 2]], -1)


def interpolate_from_background(bg: Mesh, bg_met: jax.Array,
                                mesh: Mesh, met: jax.Array,
                                bg_fields: jax.Array | None = None,
                                only_new: jax.Array | None = None,
                                start: jax.Array | None = None):
    """Transfer metric (and fields) from a background mesh onto mesh's
    vertices — the driver-level analogue of PMMG_interpMetricsAndFields
    (interpmesh_pmmg.c:663).

    ``only_new``: bool [capP] — vertices to overwrite (default: all valid);
    others keep their current values (the reference copies unmoved/required
    points directly, interpmesh_pmmg.c:432).
    Returns (met', fields' or None, LocateResult).
    """
    sel = mesh.vmask if only_new is None else (only_new & mesh.vmask)
    pts = mesh.vert
    if start is None:
        start = jnp.zeros(mesh.capP, jnp.int32)
    loc = locate_points(bg, pts, start)
    if bg_met.ndim == 1:
        met_i = interp_p1(bg_met, bg.tet, loc)
    else:
        met_i = interp_metric_ani(bg_met, bg.tet, loc)
    met_out = jnp.where(sel.reshape(sel.shape + (1,) * (met.ndim - 1)),
                        met_i.astype(met.dtype), met)
    fields_out = None
    if bg_fields is not None:
        f_i = interp_p1(bg_fields, bg.tet, loc)
        fields_out = f_i
    return met_out, fields_out, loc
