"""Surface analysis: boundary extraction, ridges, corners, normals.

TPU-native equivalent of the sequential analysis the reference delegates to
Mmg (``MMG3D_analys``: ``setadj``/``setdhd``/``singul``/``norver``; invoked
at /root/reference/src/libparmmg.c:128-204 before adaptation) and whose
parallel supplement lives in analys_pmmg.c.  The semantics reproduced here:

- boundary faces are tet faces without a neighbor (``build_adjacency``);
- an edge shared by two boundary faces whose normals make a dihedral angle
  sharper than ``angedg`` (default 45 deg) is a *ridge* (``MG_GEO``) —
  Mmg's ``setdhd``;
- an edge whose two boundary faces carry different surface references is a
  *reference edge* (``MG_REF``);
- an edge with a number of incident boundary faces other than 2 is
  *non-manifold* (``MG_NOM``, e.g. open boundaries);
- a boundary vertex with exactly 2 incident ridge edges is a ridge point
  (``MG_GEO``); with 1 or >2 it is a *corner* (``MG_CRN``) — Mmg's
  ``singul`` rules;
- vertex normals are area-weighted averages of incident boundary-face
  normals (Mmg's ``norver``; the two-normal ridge bookkeeping is carried by
  the per-face normals, recomputed on demand).

Everything is sort/segment based (no hash tables): boundary face-edge
records are matched through the unique-edge table of ``ops.edges``.
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.mesh import Mesh, tet_face_vertices
from ..core.constants import (
    ANGEDG, FACE_EDGES, IDIR, MG_BDY, MG_CRN, MG_GEO, MG_NOM, MG_REF)
from .adjacency import build_adjacency
from .edges import unique_edges

_IDIR_J = jnp.asarray(IDIR)
_FACE_EDGES_J = jnp.asarray(FACE_EDGES)


class AnalysisResult(NamedTuple):
    mesh: Mesh
    vnormal: jax.Array    # [capP, 3] unit vertex normals (0 off-surface)


def boundary_vertex_normals(mesh: Mesh) -> jax.Array:
    """[capP,3] unit outward vertex normals from true-boundary faces.

    Area-weighted average over incident MG_BDY (non-PARBDY) faces via ONE
    concatenated scatter — cheap enough to run inside the waves (the
    hausd-driven surface approximation needs endpoint normals per split/
    collapse candidate; Mmg instead stores xPoint normals, norver).
    Zeros off-surface.
    """
    import jax.numpy as jnp
    from ..core.constants import IDIR, MG_BDY, MG_PARBDY, EPSD
    capP = mesh.capP
    idir = jnp.asarray(IDIR)
    isb = ((mesh.ftag & MG_BDY) != 0) & ((mesh.ftag & MG_PARBDY) == 0) & \
        mesh.tmask[:, None]
    fv = mesh.tet[:, idir]                                 # [T,4,3]
    fp = mesh.vert[fv]                                     # [T,4,3,3]
    fn = jnp.cross(fp[:, :, 1] - fp[:, :, 0], fp[:, :, 2] - fp[:, :, 0])
    idx12 = jnp.concatenate(
        [jnp.where(isb[:, f], fv[:, f, k], capP)
         for f in range(4) for k in range(3)])
    pay12 = jnp.concatenate([fn[:, f] for f in range(4) for _ in range(3)])
    nacc = jnp.zeros((capP + 1, 3), mesh.vert.dtype).at[idx12].add(
        pay12, mode="drop")[:capP]
    return nacc / (jnp.linalg.norm(nacc, axis=-1, keepdims=True) + EPSD)


def ridge_vertex_normals(mesh: Mesh):
    """Per-side normals (n1, n2) at ridge/reference-line vertices.

    The reference stores TWO normals per ridge point (xPoint n1/n2,
    routed by the hashNorver face coloring, analys_pmmg.c:199-1171 —
    faces connected without crossing the ridge share a slot).  Batched
    equivalent: per ridge vertex, the incident boundary faces are
    2-clustered by normal direction — side 1 is seeded by the largest
    incident face (two-channel scatter-max), side 2 is everything
    deviating from that seed by more than ~half the ridge angle.  Exact
    for the ubiquitous two-smooth-patch ridge; a connectivity coloring
    (the reference's) differs only on pathological multi-patch points,
    which classify MG_NOM/corner and are excluded anyway.

    Returns (n1 [capP,3], n2 [capP,3]) unit normals; zeros off-ridge.
    """
    import jax.numpy as jnp
    from ..core.constants import (IDIR, MG_BDY, MG_PARBDY, MG_GEO,
                                  MG_REF, MG_CRN, MG_NOM, EPSD)
    from .edges import PRI_MIN, tie_hash
    capP = mesh.capP
    idir = jnp.asarray(IDIR)
    is_ridge_v = mesh.vmask & ((mesh.vtag & (MG_GEO | MG_REF)) != 0) & \
        ((mesh.vtag & (MG_CRN | MG_NOM)) == 0)
    isb = ((mesh.ftag & MG_BDY) != 0) & ((mesh.ftag & MG_PARBDY) == 0) & \
        mesh.tmask[:, None]
    fv = mesh.tet[:, idir]                                  # [T,4,3]
    fp = mesh.vert[fv]
    fn = jnp.cross(fp[:, :, 1] - fp[:, :, 0], fp[:, :, 2] - fp[:, :, 0])
    area2 = jnp.linalg.norm(fn, axis=-1)                    # [T,4]
    fn_u = fn / (area2[..., None] + EPSD)
    # seed: the largest incident boundary face per ridge vertex
    rec_v = jnp.concatenate(
        [jnp.where(isb[:, f] & is_ridge_v[fv[:, f, k]], fv[:, f, k],
                   capP) for f in range(4) for k in range(3)])
    rec_s = jnp.concatenate([area2[:, f] for f in range(4)
                             for _ in range(3)])
    rec_n = jnp.concatenate([fn_u[:, f] for f in range(4)
                             for _ in range(3)])
    smax = jnp.full(capP + 1, -jnp.inf, mesh.vert.dtype).at[rec_v].max(
        rec_s, mode="drop")
    at_max = (rec_v < capP) & (rec_s >= smax[jnp.clip(rec_v, 0, capP)])
    t_ch = jnp.where(at_max, tie_hash(rec_v.shape[0]), PRI_MIN)
    tmax = jnp.full(capP + 1, PRI_MIN, jnp.int32).at[
        jnp.where(at_max, rec_v, capP)].max(t_ch, mode="drop")
    seed_sel = at_max & (t_ch == tmax[jnp.clip(rec_v, 0, capP)])
    seed = jnp.zeros((capP + 1, 3), mesh.vert.dtype).at[
        jnp.where(seed_sel, rec_v, capP)].set(
        jnp.where(seed_sel[:, None], rec_n, 0.0), mode="drop",
        unique_indices=True)[:capP]
    # side split: within ~22.5 deg of the seed = side 1, else side 2
    # (patches meeting at a ridge differ by > ANGEDG = 45 deg)
    dots = jnp.sum(rec_n * seed[jnp.clip(rec_v, 0, capP - 1)], axis=-1)
    side1 = dots >= jnp.cos(jnp.pi / 8)
    pay = jnp.concatenate(
        [jnp.where(side1[:, None], rec_n, 0.0),
         jnp.where(side1[:, None], 0.0, rec_n)], axis=1)    # [R,6]
    acc = jnp.zeros((capP + 1, 6), mesh.vert.dtype).at[rec_v].add(
        pay, mode="drop")[:capP]
    n1 = acc[:, :3] / (jnp.linalg.norm(acc[:, :3], axis=-1,
                                       keepdims=True) + EPSD)
    n2 = acc[:, 3:] / (jnp.linalg.norm(acc[:, 3:], axis=-1,
                                       keepdims=True) + EPSD)
    n1 = jnp.where(is_ridge_v[:, None], n1, 0.0)
    n2 = jnp.where(is_ridge_v[:, None], n2, 0.0)
    return n1, n2


def ridge_vertex_tangents(mesh: Mesh, et=None) -> jax.Array:
    """[capP, 3] unit tangent of the feature (ridge/ref) line at each
    MG_GEO/MG_REF vertex; zeros elsewhere.

    The reference stores the tangent in the xPoint alongside the two
    per-side normals (Mmg norver; maintained across ranks by
    PMMG_hashNorver, analys_pmmg.c:199-1171).  Batched equivalent: the
    direction sign along a curve is arbitrary, so accumulate the OUTER
    PRODUCT of the incident special-edge directions per vertex (sign-
    free) and take the principal eigenvector by a few power iterations —
    exact for <=2 incident feature edges (the ridge-point case).
    """
    from ..core.constants import MG_GEO, MG_REF
    capP = mesh.capP
    if et is None:      # callers on the hot path pass their shared table
        et = unique_edges(mesh)
    special = et.emask & ((et.etag & (MG_GEO | MG_REF)) != 0)
    va = jnp.clip(et.ev[:, 0], 0, capP - 1)
    vb = jnp.clip(et.ev[:, 1], 0, capP - 1)
    d = mesh.vert[vb] - mesh.vert[va]
    d = d / jnp.maximum(jnp.linalg.norm(d, axis=-1, keepdims=True),
                        1e-30)
    outer = d[:, :, None] * d[:, None, :]                 # [E,3,3]
    pay = jnp.where(special[:, None, None], outer, 0.0).reshape(-1, 9)
    idx2 = jnp.concatenate([jnp.where(special, va, capP),
                            jnp.where(special, vb, capP)])
    M = jnp.zeros((capP + 1, 9), mesh.vert.dtype).at[idx2].add(
        jnp.concatenate([pay, pay]), mode="drop")[:capP].reshape(
        capP, 3, 3)
    has = jnp.trace(M, axis1=1, axis2=2) > 1e-12
    # principal eigenvector by power iteration (M is PSD; 4 steps are
    # plenty for the 2-edge spectrum).  Init with the column under the
    # largest diagonal entry — never orthogonal to the principal
    # direction (a fixed init like (1,1,1) is exactly orthogonal to
    # common lattice directions such as (1,-1,0)).
    diag = M[:, jnp.arange(3), jnp.arange(3)]
    jcol = jnp.argmax(diag, axis=1)
    v = jnp.take_along_axis(M, jcol[:, None, None].repeat(3, 1),
                            axis=2)[:, :, 0]
    v = jnp.where(jnp.linalg.norm(v, axis=-1, keepdims=True) > 1e-30,
                  v, 1.0)
    for _ in range(4):
        v = jnp.einsum("pij,pj->pi", M, v)
        v = v / jnp.maximum(jnp.linalg.norm(v, axis=-1, keepdims=True),
                            1e-30)
    return jnp.where(has[:, None], v, 0.0)


def face_normals(mesh: Mesh) -> jax.Array:
    """[capT, 4, 3] outward (non-unit) normals of each tet face.

    With the IDIR convention and positively oriented tets, the cross
    product of the two face edge vectors points outward.
    """
    fv = tet_face_vertices(mesh.tet)               # [T,4,3] vertex ids
    p = mesh.vert[fv]                              # [T,4,3,3]
    return jnp.cross(p[:, :, 1] - p[:, :, 0], p[:, :, 2] - p[:, :, 0])


def analyze_mesh_impl(mesh: Mesh, angedg: float = ANGEDG) -> AnalysisResult:
    """Run the full sequential surface analysis; jittable.

    Expects/It (re)builds adjacency, then derives all geometric entity tags
    from scratch (existing REQ/PARBDY bits are preserved).
    """
    mesh = build_adjacency(mesh)
    capT, capP = mesh.capT, mesh.capP
    et = unique_edges(mesh)
    capE = et.ev.shape[0]

    # open-boundary faces (-opnbdy ingestion, MG_OPNBDY): an interior
    # face pair carries the tag on BOTH slots; analysis must see the
    # sheet ONE-sided (else every sheet edge counts 4 records and the
    # whole sheet turns non-manifold) — the lower-tet-id slot represents
    # the geometric face
    from ..core.constants import MG_OPNBDY
    opn = (mesh.ftag & MG_OPNBDY) != 0
    own_side = (mesh.adja < 0) | \
        (jnp.arange(capT)[:, None] < (mesh.adja >> 2))
    is_bdy_face = ((mesh.ftag & MG_BDY) != 0) & mesh.tmask[:, None] & \
        (~opn | own_side)                                             # [T,4]
    nrm = face_normals(mesh)                                          # [T,4,3]
    nrm_unit = nrm / jnp.maximum(
        jnp.linalg.norm(nrm, axis=-1, keepdims=True), 1e-30)

    # --- boundary face-edge records (12 per tet) -------------------------
    # record r = (tet t, face f, edge j of face): eid via the edge table
    le = _FACE_EDGES_J[None, :, :]                       # [1,4,3] local edge
    le = jnp.broadcast_to(le, (capT, 4, 3))
    eid = jnp.take_along_axis(
        et.edge_id[:, None, :].repeat(4, axis=1), le, axis=2)   # [T,4,3]
    rec_valid = is_bdy_face[:, :, None] & jnp.ones((1, 1, 3), bool)
    R = capT * 12
    eid_f = eid.reshape(R)
    val_f = rec_valid.reshape(R)
    nrm_f = jnp.broadcast_to(nrm_unit[:, :, None, :],
                             (capT, 4, 3, 3)).reshape(R, 3)
    fref_f = jnp.broadcast_to(mesh.fref[:, :, None],
                              (capT, 4, 3)).reshape(R)
    opn_f = jnp.broadcast_to(opn[:, :, None], (capT, 4, 3)).reshape(R)

    # --- sort records by eid, match neighbors in segments ----------------
    key = jnp.where(val_f, eid_f, capE)
    order = jnp.argsort(key)
    ks = key[order]
    n_s = nrm_f[order]
    r_s = fref_f[order]
    v_s = val_f[order]
    eq_next = (ks[1:] == ks[:-1]) & (ks[:-1] < capE)
    same_next = jnp.concatenate([eq_next, jnp.array([False])])
    same_prev = jnp.concatenate([jnp.array([False]), eq_next])
    idx = jnp.arange(R)
    partner = jnp.where(same_next, idx + 1,
                        jnp.where(same_prev, idx - 1, idx))
    # per-record pair tests (meaningful only when the segment has size 2;
    # larger segments are non-manifold and flagged by the count below).
    # Open-boundary sheets are unoriented (the representative slot's
    # normal sign is arbitrary): their dihedral test uses |dot|.
    o_s = opn_f[order]
    dot = jnp.sum(n_s * n_s[partner], axis=-1)
    dot = jnp.where(o_s | o_s[partner], jnp.abs(dot), dot)
    ridge_r = v_s & (same_next | same_prev) & (dot < angedg)
    refed_r = v_s & (same_next | same_prev) & (r_s != r_s[partner])

    # segment sizes per eid (number of incident boundary faces)
    cnt = jnp.zeros(capE + 1, jnp.int32).at[
        jnp.where(val_f, eid_f, capE)].add(1, mode="drop")[:capE]
    has_bdy = cnt > 0
    nom_e = has_bdy & (cnt != 2)

    # scatter pair flags to unique edges
    ridge_e = jnp.zeros(capE + 1, bool).at[
        jnp.where(v_s, ks, capE)].max(ridge_r, mode="drop")[:capE]
    refed_e = jnp.zeros(capE + 1, bool).at[
        jnp.where(v_s, ks, capE)].max(refed_r, mode="drop")[:capE]
    ridge_e = ridge_e & ~nom_e      # non-manifold handled separately
    bdy_e = has_bdy

    # --- write edge tags back onto every tet-edge slot -------------------
    add = (jnp.where(ridge_e, MG_GEO, 0) | jnp.where(refed_e, MG_REF, 0)
           | jnp.where(nom_e, MG_NOM, 0)
           | jnp.where(bdy_e, MG_BDY, 0)).astype(jnp.uint32)
    etag = mesh.etag | jnp.where(mesh.tmask[:, None], add[et.edge_id],
                                 jnp.uint32(0))

    # --- vertex classification (singul) ----------------------------------
    sing_e = ridge_e | refed_e | nom_e       # edges that make points special
    nsing = jnp.zeros(capP + 1, jnp.int32)
    vbdy = jnp.zeros(capP + 1, bool)
    vnom = jnp.zeros(capP + 1, bool)
    vref = jnp.zeros(capP + 1, bool)
    for side in range(2):
        tgt = jnp.where(et.emask, et.ev[:, side], capP)
        nsing = nsing.at[tgt].add(sing_e.astype(jnp.int32), mode="drop")
        vbdy = vbdy.at[tgt].max(bdy_e, mode="drop")
        vnom = vnom.at[tgt].max(nom_e, mode="drop")
        vref = vref.at[tgt].max(refed_e, mode="drop")
    nsing, vbdy = nsing[:capP], vbdy[:capP]
    vnom, vref = vnom[:capP], vref[:capP]

    on_ridge = nsing == 2
    corner = (nsing == 1) | (nsing > 2)
    vadd = (jnp.where(vbdy, MG_BDY, 0)
            | jnp.where(on_ridge, MG_GEO, 0)
            | jnp.where(corner, MG_CRN, 0)
            | jnp.where(vnom, MG_NOM, 0)
            | jnp.where(vref, MG_REF, 0)).astype(jnp.uint32)
    vtag = jnp.where(mesh.vmask, mesh.vtag | vadd, mesh.vtag)

    # --- vertex normals (norver) -----------------------------------------
    fv = tet_face_vertices(mesh.tet)                       # [T,4,3]
    acc = jnp.zeros((capP + 1, 3), mesh.vert.dtype)
    nrm_flat = nrm.reshape(capT * 4, 3)       # area-weighted (non-unit)
    for c in range(3):
        tgt = jnp.where(is_bdy_face, fv[:, :, c], capP).reshape(-1)
        acc = acc.at[tgt].add(nrm_flat, mode="drop")
    vn = acc[:capP]
    vn = vn / jnp.maximum(jnp.linalg.norm(vn, axis=-1, keepdims=True), 1e-30)
    vn = jnp.where(vbdy[:, None], vn, 0.0)

    out = dataclasses.replace(mesh, etag=etag, vtag=vtag)
    return AnalysisResult(out, vn)


# Always jitted: eager dispatch of the ~200-op analysis graph is
# catastrophic over a remote-device transport (one RPC per op); under jit
# it is one compiled executable (cached persistently).  jit-of-jit at the
# call sites inside other jitted code simply inlines.
analyze_mesh = jax.jit(analyze_mesh_impl)
