"""SPMD distributed adaptation over a jax.sharding.Mesh.

The TPU-native replacement for ParMmg's MPI layer: where the reference runs
one MPI rank per subdomain with Sendrecv exchanges and
``MPI_Allreduce(MIN, ier)`` phase agreement (the status-agreement idiom,
/root/reference/src/libparmmg1.c:812,876,912), we run one *shard* per
device under ``shard_map``: every device executes the identical jitted
adapt program on its shard; cross-shard agreement (op counters, error
status, quality histograms) is a ``psum`` over the 'shard' axis — the
collective rides ICI instead of MPI.

During shard-local adaptation the interfaces are frozen (MG_PARBDY tags set
by distribute.py), so no halo exchange is needed *inside* the hot loop —
exactly the reference's design (interfaces remeshed only after migration).
Repartitioning/migration between outer iterations is host-side DCN
orchestration (SURVEY §5: dynamic-topology group migration stays off the
static-shape device path).
"""
from __future__ import annotations

import dataclasses
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh as DeviceMesh, PartitionSpec as P, NamedSharding
from jax import shard_map

from ..core.mesh import Mesh
from ..ops.quality import tet_quality, quality_histogram


def _unstack(pytree):
    return jax.tree.map(lambda x: x[0], pytree)


def _restack(pytree):
    return jax.tree.map(lambda x: x[None], pytree)


def make_device_mesh(n_devices: int | None = None) -> DeviceMesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return DeviceMesh(np.array(devs), ("shard",))


def shard_stacked(stacked, dmesh: DeviceMesh):
    """Place a [D, ...]-stacked pytree with leading axis over 'shard'."""
    sh = NamedSharding(dmesh, P("shard"))
    return jax.tree.map(lambda x: jax.device_put(x, sh), stacked)


def dist_adapt_cycle(dmesh: DeviceMesh, do_swap: bool = True,
                     do_smooth: bool = True, do_insert: bool = True):
    """Build the jitted SPMD adapt step for a given device mesh.

    The per-shard body is the same ``adapt_cycle_impl`` as the single-chip
    path (frozen MG_PARBDY interfaces make it correct under SPMD); the
    counters are globally ``psum``-reduced — the analogue of the
    reference's Allreduce(ier/counters) phase-agreement idiom
    (libparmmg1.c:812).

    Returns fn(stacked_mesh, stacked_met, wave) ->
      (stacked_mesh, stacked_met, global_counts[4], any_overflow).
    """
    from ..ops.adapt import adapt_cycle_impl
    spec = P("shard")

    def local_cycle(mesh_s: Mesh, met_s, wave):
        mesh = _unstack(mesh_s)
        met = met_s[0]
        mesh, met, counts = adapt_cycle_impl(
            mesh, met, wave, do_swap=do_swap, do_smooth=do_smooth,
            do_insert=do_insert, smooth_waves=2)
        ovf = jax.lax.pmax(counts[4], "shard")
        counts = jax.lax.psum(counts[:4], "shard")
        return _restack(mesh), met[None], counts, ovf

    fn = shard_map(local_cycle, mesh=dmesh,
                   in_specs=(spec, spec, P()),
                   out_specs=(spec, spec, P(), P()),
                   check_vma=False)
    return jax.jit(fn)


def dist_quality(dmesh: DeviceMesh):
    """Global quality histogram across shards (PMMG_qualhisto analogue,
    quality_pmmg.c:156 — the custom MPI_Op reduction becomes psum/pmin)."""
    spec = P("shard")

    def local(mesh_s: Mesh, met_s):
        mesh = _unstack(mesh_s)
        met = met_s[0]
        q = tet_quality(mesh, met)
        counts, qmin, qmean, nbad = quality_histogram(q, mesh.tmask)
        n = jnp.sum(mesh.tmask.astype(jnp.int32))
        counts = jax.lax.psum(counts, "shard")
        qmin = jax.lax.pmin(qmin, "shard")
        qsum = jax.lax.psum(qmean * n, "shard")
        ntot = jax.lax.psum(n, "shard")
        nbad = jax.lax.psum(nbad, "shard")
        return counts, qmin, qsum / jnp.maximum(ntot, 1), nbad, ntot

    fn = shard_map(local, mesh=dmesh, in_specs=(spec, spec),
                   out_specs=(P(), P(), P(), P(), P()), check_vma=False)
    return jax.jit(fn)


def distributed_adapt(mesh: Mesh, met, n_shards: int,
                      cycles: int = 10, dmesh: DeviceMesh | None = None,
                      partitioner: str = "morton", verbose: int = 0,
                      part: np.ndarray | None = None, stats=None,
                      noinsert: bool = False, noswap: bool = False,
                      nomove: bool = False):
    """One outer remesh pass on n_shards devices (host driver).

    partition (or take the caller's displaced ``part``) -> freeze
    interfaces -> SPMD adapt cycles -> merge.  Returns
    (merged mesh, met, part_of_merged): the partition labels of the NEW
    tets (= source shard), which the caller displaces with
    ``move_interfaces`` before the next outer iteration — the
    remesh-and-repartition scheme of PMMG_parmmglib1/loadbalancing.
    """
    from ..core.mesh import tet_volumes, mesh_to_host
    from .partition import morton_partition, greedy_partition, fix_contiguity
    from .distribute import split_to_shards, merge_shards

    if dmesh is None:
        dmesh = make_device_mesh(n_shards)

    vert, tet, vref, tref, vtag = mesh_to_host(mesh)
    if part is None:
        cent = vert[tet].mean(axis=1)
        if partitioner == "morton":
            part = morton_partition(cent, n_shards)
        else:
            part = greedy_partition(tet, cent, n_shards)
        part = fix_contiguity(tet, part)

    cap_mult = 3.0
    step_full = dist_adapt_cycle(dmesh, do_swap=not noswap,
                                 do_smooth=not nomove,
                                 do_insert=not noinsert)
    # with -noswap both flavors are the same program: don't compile the
    # multi-minute SPMD graph twice
    step_light = step_full if noswap else dist_adapt_cycle(
        dmesh, do_swap=False, do_smooth=not nomove,
        do_insert=not noinsert)
    stacked = met_s = None
    c = 0
    regrows = 0
    while c < cycles:
        if stacked is None:
            s, ms = split_to_shards(mesh, met, part, n_shards,
                                    cap_mult=cap_mult)
            stacked = shard_stacked(s, dmesh)
            met_s = shard_stacked(ms, dmesh)
        # swaps every 3rd cycle (see ops.adapt.adapt_mesh) and on the
        # final two (quality polish before the merge)
        step = step_full if (c % 3 == 2 or c >= cycles - 2) else step_light
        stacked, met_s, counts, ovf = step(stacked, met_s,
                                           jnp.asarray(c, jnp.int32))
        cs = np.asarray(counts)
        if stats is not None:          # psum'd global counters -> AdaptStats
            stats.nsplit += int(cs[0])
            stats.ncollapse += int(cs[1])
            stats.nswap += int(cs[2])
            stats.nmoved += int(cs[3])
            stats.cycles += 1
        if verbose >= 3:
            print(f"  dist cycle {c}: split {cs[0]} collapse {cs[1]} "
                  f"swap {cs[2]} move {cs[3]}")
        if int(ovf) != 0:
            # shard capacity exhausted: merge, double headroom, re-split
            # with the same partition and continue (the static-shape
            # analogue of the reference's realloc/memory repartition,
            # zaldy_pmmg.c:140-254)
            if regrows >= 6:
                raise MemoryError("shard capacity overflow")
            mesh, met, part = merge_shards(stacked, met_s,
                                           return_part=True)
            cap_mult *= 2.0
            regrows += 1
            stacked = None
            continue
        c += 1
        if step is step_full and cs[0] == 0 and cs[1] == 0 and cs[2] == 0:
            break
    merged, met_m, part_new = merge_shards(stacked, met_s,
                                           return_part=True)
    return merged, met_m, part_new
