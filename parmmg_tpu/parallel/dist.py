"""SPMD distributed adaptation over a jax.sharding.Mesh.

The TPU-native replacement for ParMmg's MPI layer: where the reference runs
one MPI rank per subdomain with Sendrecv exchanges and
``MPI_Allreduce(MIN, ier)`` phase agreement (the status-agreement idiom,
/root/reference/src/libparmmg1.c:812,876,912), we run one *shard* per
device under ``shard_map``: every device executes the identical jitted
adapt program on its shard; cross-shard agreement (op counters, error
status, quality histograms) is a ``psum`` over the 'shard' axis — the
collective rides ICI instead of MPI.

During shard-local adaptation the interfaces are frozen (MG_PARBDY tags set
by distribute.py), so no halo exchange is needed *inside* the hot loop —
exactly the reference's design (interfaces remeshed only after migration).
Repartitioning/migration between outer iterations is host-side DCN
orchestration (SURVEY §5: dynamic-topology group migration stays off the
static-shape device path).
"""
from __future__ import annotations

import dataclasses
import time
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh as DeviceMesh, PartitionSpec as P, NamedSharding

from ..utils.jaxcompat import shard_map

from ..core.mesh import Mesh
from ..obs import trace as otrace
from ..ops.quality import tet_quality, quality_histogram
from ..utils.compilecache import bucket, governed


MAX_SHARD_REGROWS = 6


class ShardOverflowError(RuntimeError):
    """Shard capacity exhausted after MAX_SHARD_REGROWS doublings.

    Carries the last CONFORMING merged state so the caller can degrade
    to PMMG_LOWFAILURE and still save a valid mesh — the reference's
    failed_handling contract (libparmmg1.c:974-1011)."""

    def __init__(self, mesh, met, part):
        super().__init__("shard capacity overflow")
        self.mesh = mesh
        self.met = met
        self.part = part


def _unstack(pytree):
    return jax.tree.map(lambda x: x[0], pytree)


def _restack(pytree):
    return jax.tree.map(lambda x: x[None], pytree)


def make_device_mesh(n_devices: int | None = None) -> DeviceMesh:
    """Device mesh over the 'shard' axis.  Under an initialized
    ``jax.distributed`` runtime (parallel/multihost.py), ``jax.devices()``
    is the GLOBAL list across hosts and the same mesh spans processes —
    the MPI-communicator analogue (mpi_pmmg.h role)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return DeviceMesh(np.array(devs), ("shard",))


def shard_stacked(stacked, dmesh: DeviceMesh):
    """Place a [D, ...]-stacked pytree with leading axis over 'shard'.
    Multi-process meshes route through shard_stacked_global (each host
    uploads its addressable slices)."""
    if jax.process_count() > 1:
        from .multihost import shard_stacked_global
        return shard_stacked_global(stacked, dmesh)
    sh = NamedSharding(dmesh, P("shard"))
    return jax.tree.map(lambda x: jax.device_put(x, sh), stacked)


def dist_adapt_block(dmesh: DeviceMesh, swap_flags: tuple,
                     do_smooth: bool = True, do_insert: bool = True,
                     hausd: float | None = None, G: int = 1,
                     pre_flags: tuple | None = None,
                     swap_inclusive: bool | None = None):
    """SPMD fused cycle block: ``len(swap_flags)`` adapt cycles in ONE
    jitted shard_map program — the production analogue of
    ops.adapt.adapt_cycles_fused.  One dispatch + one psum'd counter
    pull per block instead of per cycle: on the tunneled chip each
    dispatch pays a ~70-110 ms transport round trip.

    ``G`` > 1 is the groups x shards composition (the reference's
    rank-level x group-level two-level loop, grpsplit_pmmg.c:1551-1614,
    libparmmg1.c:597-636): the stacked leading axis holds S*G LOGICAL
    shards, G consecutive rows per device; inside the shard_map body a
    ``lax.map`` serializes the device's G groups through ONE compiled
    group-shaped cycle program, so peak HBM per chip is the G resident
    group states plus a single group's wave working set — the bound
    that makes meshes far beyond one group's HBM feasible per chip.

    Returns fn(stacked_mesh, stacked_met, wave0, quiet_lvl[S*G]) ->
      (stacked_mesh, stacked_met, global_counts[n,4],
       active_groups[n], any_overflow, quiet_lvl'[S*G]).

    ``active_groups[i]`` = number of LOGICAL shards that posted a
    nonzero split+collapse+swap in cycle i (psum'd like the counters):
    the per-group convergence signal is kept instead of being summed
    away, so :func:`run_adapt_cycles` can drive its early-exit and its
    verbose "active g/G" trajectory from per-group data — the SPMD
    mirror of the quiet-group scheduler on the single-device grouped
    path (parallel/sched.py).

    ``quiet_lvl`` is that scheduler's quiet state made DEVICE-RESIDENT
    (int8 per logical shard, the sched.LEVEL_* ladder): a shard at or
    above this block's skip level has its ``lax.map`` body wrapped in
    ``lax.cond`` identity — the split/collapse/swap/smooth wave math is
    never executed for it — and a swap-inclusive block posting zero
    split+collapse+swap+move+overflow for a shard raises its level ON
    DEVICE (the same frozen-seam + deterministic-wave fixed-point
    proof, the same two prescreen levels; sched module docstring).
    Zero host syncs are added: the level array never leaves the device.
    ``swap_inclusive`` must be passed as ``any(flags) or noswap`` by
    callers that honor -noswap (a noswap run's blocks are trivially
    swap-inclusive); it defaults to ``any(swap_flags)``.  The caller
    opts out of skipping by discarding the returned level and passing
    zeros each block (run_adapt_cycles under PARMMG_DEVICE_MASK=0 /
    PARMMG_GROUP_SCHED=0) — same compiled program either way.
    """
    from ..ops.adapt import adapt_cycle_impl
    spec = P("shard")
    if pre_flags is None:
        pre_flags = (True,) * len(swap_flags)
    if swap_inclusive is None:
        swap_inclusive = any(swap_flags)
    # the level this block skips at == the level it can prove
    # (sched.LEVEL_PRE under an all-prescreen-ON block, LEVEL_FULL once
    # a prescreen-OFF cycle ran — numerically 1 and 2)
    skip_lvl = 1 if all(pre_flags) else 2

    def one_shard(mesh: Mesh, met, wave0, act):
        counts_all = []
        for c, dosw in enumerate(swap_flags):
            mesh, met, counts = adapt_cycle_impl(
                mesh, met, wave0 + c, do_swap=dosw, do_smooth=do_smooth,
                do_insert=do_insert, smooth_waves=2, hausd=hausd,
                final_rebuild=(c == len(swap_flags) - 1),
                prescreen=pre_flags[c], active=act)
            counts_all.append(counts)
        return mesh, met, jnp.stack(counts_all)            # [n, 8]

    def local_block(mesh_s: Mesh, met_s, wave0, lvl_s):
        act_in = lvl_s < skip_lvl                          # [G] bool
        if G == 1:
            mesh, met, cs = one_shard(_unstack(mesh_s), met_s[0],
                                      wave0, act_in[0])
            mesh_s, met_s = _restack(mesh), met[None]
            cs_g = cs[None]                                # [1, n, 8]
            act = (jnp.sum(cs[:, :3], axis=1) > 0).astype(jnp.int32)
        else:
            def body(args):
                m, k, a = args
                return one_shard(m, k, wave0, a)
            mesh_s, met_s, cs_g = jax.lax.map(
                body, (mesh_s, met_s, act_in))
            act = jnp.sum((jnp.sum(cs_g[:, :, :3], axis=2) > 0
                           ).astype(jnp.int32), axis=0)    # [n]
        if swap_inclusive:
            # quiet marking on device — sched.quiet_rows' rule: the
            # WHOLE block a no-op (zero split+collapse+swap+move AND
            # zero overflow; a truncated winner set witnesses nothing)
            nG = cs_g.shape[0]
            blk_zero = jnp.sum(cs_g[:, :, :5].reshape(nG, -1),
                               axis=1) == 0
            lvl_s = jnp.maximum(
                lvl_s, jnp.where(blk_zero, jnp.int8(skip_lvl),
                                 jnp.int8(0)))
        ovf = jax.lax.pmax(jnp.max(cs_g[:, :, 4]), "shard")
        counts = jax.lax.psum(jnp.sum(cs_g[:, :, :4], axis=0), "shard")
        nact = jax.lax.psum(act, "shard")
        return mesh_s, met_s, counts, nact, ovf, lvl_s

    fn = shard_map(local_block, mesh=dmesh,
                   in_specs=(spec, spec, P(), spec),
                   out_specs=(spec, spec, P(), P(), P(), spec),
                   check_vma=False)
    return governed("dist.adapt_block")(jax.jit(fn))


class DistSteps:
    """Per-driver-invocation cache of compiled SPMD block programs keyed
    by the (swap, prescreen) flag tuples.  jax.jit caches by function
    identity, so a fresh shard_map per outer iteration would recompile
    the multi-minute SPMD graph every time; the multi-iteration drivers
    build ONE of these and reuse it."""

    def __init__(self, dmesh: DeviceMesh, do_smooth: bool = True,
                 do_insert: bool = True, hausd: float | None = None,
                 G: int = 1):
        self.dmesh = dmesh
        self.kw = dict(do_smooth=do_smooth, do_insert=do_insert,
                       hausd=hausd, G=G)
        self._cache: dict = {}

    def get(self, flags: tuple, pre_flags: tuple | None = None,
            swap_inclusive: bool | None = None):
        flags = tuple(bool(f) for f in flags)
        if pre_flags is None:
            pre_flags = (True,) * len(flags)
        pre_flags = tuple(bool(f) for f in pre_flags)
        if swap_inclusive is None:
            swap_inclusive = any(flags)
        key = (flags, pre_flags, bool(swap_inclusive))
        if key not in self._cache:
            self._cache[key] = dist_adapt_block(
                self.dmesh, flags, pre_flags=pre_flags,
                swap_inclusive=swap_inclusive, **self.kw)
        return self._cache[key]


def dist_interface_check(dmesh: DeviceMesh, G: int = 1,
                         packed_M: int | None = None):
    """On-device interface echo (PMMG_check_extNodeComm on the jittable
    exchange): every shard sends its interface vertices' coordinates +
    metric through :func:`halo_exchange` and compares against the mirror
    side; the psum'd mismatch count must be zero.  Production guard for
    the ordering contract of the comm tables — runs once per outer
    iteration in distributed_adapt.

    ``G`` > 1: groups x shards composition — the stacked leading axis is
    S*G logical shards and the exchange routes (dest_device, dest_slot)
    through :func:`comms.halo_exchange_grouped`, or the per-device-pair
    packed layout (:func:`comms.halo_exchange_grouped_packed`) when
    ``packed_M`` is set (the measured-occupancy decision of
    :func:`comms.packed_halo_rows`).

    Returns fn(stacked_mesh, stacked_met, node_idx[S,K,I], nbr[S,K],
    tol) -> global mismatch count.
    """
    from .comms import (halo_exchange, halo_exchange_grouped,
                        halo_exchange_grouped_packed)
    spec = P("shard")

    def local(mesh_s: Mesh, met_s, node_idx_s, nbr_s, tol):
        met_g = met_s[..., None] if met_s.ndim == 2 else met_s
        vals_g = jnp.concatenate(
            [mesh_s.vert, met_g.astype(mesh_s.vert.dtype)],
            axis=-1)                                     # [G, capP, 3+m]
        if G == 1:
            recv = halo_exchange(vals_g[0], node_idx_s[0],
                                 nbr_s[0])[None]          # [1,K,I,3+m]
        elif packed_M is not None:
            recv = halo_exchange_grouped_packed(
                vals_g, node_idx_s, nbr_s, G, packed_M)
        else:
            recv = halo_exchange_grouped(vals_g, node_idx_s, nbr_s, G)
        capP = mesh_s.vert.shape[1]
        g_ar = jnp.arange(G)[:, None, None]
        mine = vals_g[jnp.broadcast_to(g_ar, node_idx_s.shape),
                      jnp.clip(node_idx_s, 0, capP - 1)]
        valid = (node_idx_s >= 0)[..., None]
        bad = valid & (jnp.abs(recv - jnp.where(valid, mine, 0)) > tol)
        n_bad = jnp.sum(bad.astype(jnp.int32))
        return jax.lax.psum(n_bad, "shard")

    # lint: ok(R1) — builder: the sole caller (check_interface_echo)
    # caches in _IFC_CHECK_CACHE and wraps the product in
    # governed("dist.interface_check", budget=2)
    fn = shard_map(local, mesh=dmesh,
                   in_specs=(spec, spec, spec, spec, P()),
                   out_specs=P(), check_vma=False)
    # lint: ok(R1) — same builder contract as above
    return jax.jit(fn)


def refresh_shard_analysis_device(stacked: Mesh, comms, n_shards: int,
                                  angedg: float, glo, dmesh,
                                  cache: dict | None = None,
                                  pack_state: dict | None = None):
    """Device-resident analysis refresh (parallel/analysis_dev.py): the
    sort/segment reductions of the host path run jitted under shard_map,
    keyed by the persistent global numbering — no O(mesh) host pull.

    ``n_shards`` > device count dispatches the GROUPED program
    (analysis_dev.dist_analysis_grouped): G = n_shards // n_devices
    logical shards per device, per-group lax.map reductions + the
    grouped (packed when sparse) halo exchange — the G>1 loop pays the
    same zero-host-pull bill as G=1.

    Returns the updated stacked mesh, or None when the shared-record
    budget overflowed (caller falls back to the host path) — never a
    silent truncation."""
    import os
    if os.environ.get("PARMMG_HOST_ANALYSIS", "") == "1":
        return None
    # injectable KS-overflow (resilience/faults.py): the real failure
    # here is a flag, not an exception — firing takes the exact branch
    # a shared-record budget overflow takes (None -> host fallback)
    from ..resilience.faults import fault_trigger, faultpoint
    if fault_trigger("analysis.ks_overflow"):
        return None
    from .analysis_dev import dist_analysis, dist_analysis_grouped
    from .comms import packed_halo_rows
    # lint: ok(R2) — glo is the HOST-resident persistent numbering
    # (list of np arrays grown on host, distributed_adapt_multi);
    # stacking it syncs nothing — audited PR 10, no device pull here
    glo_np = np.stack([np.asarray(g) for g in glo])
    if glo_np.max() >= np.iinfo(np.int32).max:
        return None                      # int32 id budget exhausted
    capT = stacked.tet.shape[1]
    # lint: ok(R2) — device-id METADATA (dmesh.devices is a host numpy
    # object array), no device sync
    n_dev = int(np.asarray(dmesh.devices).size)
    G = max(1, n_shards // max(n_dev, 1))
    # bucketed shared-record budget (compile governor): the comm tables
    # drift between migrations and an exact KS would key a fresh
    # dist_analysis compile each outer iteration
    KS = bucket(max(1024, 4 * comms.node_idx[0].size),
                floor=1024, cap=12 * capT)
    # pack_state: sticky dense/packed layout across comm-table rebuilds
    # (hysteresis; the multi-iteration driver threads one dict through)
    Mp = packed_halo_rows(comms.nbr, G, state=pack_state) \
        if G > 1 else None
    key = (angedg, KS, n_shards, G, Mp)
    if cache is not None and key in cache:
        fn = cache[key]
    else:
        if G > 1:
            fn = governed("dist.analysis_grouped", budget=2)(
                dist_analysis_grouped(dmesh, angedg, KS, G, packed_M=Mp))
        else:
            fn = governed("dist.analysis", budget=2)(
                dist_analysis(dmesh, angedg, KS))
        if cache is not None:
            cache[key] = fn
    args = (stacked,
            shard_stacked(jnp.asarray(glo_np.astype(np.int32)), dmesh),
            shard_stacked(jnp.asarray(comms.node_idx), dmesh),
            shard_stacked(jnp.asarray(comms.nbr), dmesh))
    try:
        if Mp is not None:
            faultpoint("halo.exchange")
        vt, et, ovf = fn(*args)
        # sync INSIDE the guard: device dispatch is async, so a real
        # crash of the packed program surfaces at this first host pull,
        # not at the fn() call — outside the try it would bypass the
        # dense fallback entirely
        ovf_host = int(ovf)
    except Exception as e:
        if Mp is None:
            raise
        # packed halo program failed (injectable via
        # PARMMG_FAULT=halo.exchange): retry once on the DENSE layout —
        # ladder step "halo_dense".  Same governed program family
        # (dist.analysis_grouped), dense variant; the hysteresis state
        # is left alone so a healthy next iteration can re-pick packed.
        from ..resilience.recover import ladder_step
        ladder_step("halo_dense", site="halo.exchange", detail=repr(e))
        dkey = (angedg, KS, n_shards, G, None)
        if cache is not None and dkey in cache:
            fn = cache[dkey]
        else:
            fn = governed("dist.analysis_grouped", budget=2)(
                dist_analysis_grouped(dmesh, angedg, KS, G,
                                      packed_M=None))
            if cache is not None:
                cache[dkey] = fn
        vt, et, ovf = fn(*args)
        ovf_host = int(ovf)
    if ovf_host != 0:
        return None
    return dataclasses.replace(stacked, vtag=vt, etag=et)


def refresh_shard_analysis(stacked: Mesh, comms, n_shards: int,
                           angedg: float, glo=None, views=None):
    """Cross-shard surface analysis refresh on ADAPTED shards — the
    production PMMG_update_analys analogue (analys_pmmg.c:1571): ridge /
    corner / reference classification is recomputed with cross-interface
    dihedrals (a shard cannot see the other side's face normals), then
    written back into the stacked shard tags before the merge.

    Interface slots are stable under adaptation (frozen entities are
    never collapsed and slots are not compacted in-cycle), so the
    split-time comm tables remain valid — the reference relies on the
    same invariant between migrations.
    """
    import dataclasses
    from ..core.constants import (
        MG_BDY, MG_CRN, MG_GEO, MG_NOM, MG_PARBDY, MG_REF)
    from .analysis_par import analyze_shards, extend_numbering

    capP = stacked.vert.shape[1]
    verts, tets, ftags, frefs, tms = [], [], [], [], []
    for s in range(n_shards):
        if views is not None:
            tm = views.tmask[s]
            verts.append(views.vert[s])
            tets.append(views.tet[s][tm].astype(np.int64))
            ftags.append(views.ftag[s][tm])
            frefs.append(views.fref[s][tm])
        else:
            tm = np.asarray(stacked.tmask[s])
            verts.append(np.asarray(stacked.vert[s]))
            tets.append(np.asarray(stacked.tet[s])[tm].astype(np.int64))
            ftags.append(np.asarray(stacked.ftag[s])[tm])
            frefs.append(np.asarray(stacked.fref[s])[tm])
        tms.append(tm)
    if glo is None:
        glo = extend_numbering(comms, [capP] * n_shards)
    vtag_add, special_edges, _ = analyze_shards(
        verts, tets, ftags, frefs, comms, angedg, glo=glo)

    CLS = np.uint32(MG_GEO | MG_CRN | MG_REF | MG_NOM)
    new_vtag = []
    new_etag = []
    for s in range(n_shards):
        vt = (views.vtag[s] if views is not None
              else np.asarray(stacked.vtag[s])).copy()
        add = vtag_add[s].astype(np.uint32)
        # re-derive the classification bits; never drop freeze/user bits
        vt = (vt & ~CLS) | (add & CLS) | (add & MG_BDY)
        new_vtag.append(vt)
        # edges: clear stale classification on plain boundary edges, then
        # re-apply the global special-edge set (vectorized keyed lookup)
        from ..core.constants import IARE
        et = (views.etag[s] if views is not None
              else np.asarray(stacked.etag[s])).copy()
        tm = tms[s]
        tth = (views.tet[s] if views is not None
               else np.asarray(stacked.tet[s])).astype(np.int64)
        evl = np.sort(tth[:, IARE], axis=2)[tm]            # [nt,6,2]
        live_rows = np.where(tm)[0]
        plain_bdy = ((et[tm] & MG_BDY) != 0) & ((et[tm] & MG_PARBDY) == 0)
        cleared = et[tm] & ~np.where(plain_bdy, CLS, np.uint32(0))
        rows = special_edges[s]
        if len(rows):
            ka = np.minimum(rows[:, 0], rows[:, 1]).astype(np.int64)
            kb = np.maximum(rows[:, 0], rows[:, 1]).astype(np.int64)
            skey = ka * capP + kb
            o = np.argsort(skey, kind="stable")
            sk, sb = skey[o], rows[:, 2][o].astype(np.uint32)
            heads = np.concatenate([[True], sk[1:] != sk[:-1]])
            uk = sk[heads]
            ub = np.bitwise_or.reduceat(sb, np.where(heads)[0]) \
                if len(sk) else sb
            ekey = evl[..., 0] * capP + evl[..., 1]        # [nt,6]
            loc = np.clip(np.searchsorted(uk, ekey), 0, len(uk) - 1)
            hit = uk[loc] == ekey
            cleared |= np.where(hit, ub[loc], 0).astype(np.uint32)
        et[live_rows] = cleared
        new_etag.append(et)
    if views is not None:
        # keep the host mirrors in sync (migration reads them next)
        for s in range(n_shards):
            views.vtag[s] = new_vtag[s]
            views.etag[s] = new_etag[s]
    return dataclasses.replace(
        stacked,
        vtag=jnp.asarray(np.stack(new_vtag)),
        etag=jnp.asarray(np.stack(new_etag)))


# compiled quality-histogram programs keyed by device ids (compile
# governor, same rationale as _IFC_CHECK_CACHE below): dist_quality used
# to hand back a FRESH jax.jit object per call, so periodic quality
# reports recompiled the shard_map reduction every time — the last
# per-call jit builder the ROADMAP governor item names
_QUALITY_CACHE: dict = {}


def dist_quality(dmesh: DeviceMesh):
    """Global quality histogram across shards (PMMG_qualhisto analogue,
    quality_pmmg.c:156 — the custom MPI_Op reduction becomes psum/pmin).
    Cached per device mesh + registered in the compile ledger."""
    spec = P("shard")
    key = tuple(d.id for d in np.asarray(dmesh.devices).flat)
    cached = _QUALITY_CACHE.get(key)
    if cached is not None:
        return cached

    def local(mesh_s: Mesh, met_s):
        mesh = _unstack(mesh_s)
        met = met_s[0]
        q = tet_quality(mesh, met)
        counts, qmin, qmean, nbad = quality_histogram(q, mesh.tmask)
        n = jnp.sum(mesh.tmask.astype(jnp.int32))
        counts = jax.lax.psum(counts, "shard")
        qmin = jax.lax.pmin(qmin, "shard")
        qsum = jax.lax.psum(qmean * n, "shard")
        ntot = jax.lax.psum(n, "shard")
        nbad = jax.lax.psum(nbad, "shard")
        return counts, qmin, qsum / jnp.maximum(ntot, 1), nbad, ntot

    fn = shard_map(local, mesh=dmesh, in_specs=(spec, spec),
                   out_specs=(P(), P(), P(), P(), P()), check_vma=False)
    fn = governed("dist.quality", budget=2)(jax.jit(fn))
    _QUALITY_CACHE[key] = fn
    return fn


# compiled interface-echo programs keyed by (device ids, G): the echo
# runs once per outer iteration and after every migration, and a fresh
# jax.jit object per call would recompile the shard_map program every
# time even at identical shapes — the cache plus the bucketed comm-table
# pads (comms.pad_comm_tables) bound it to a handful of variants
_IFC_CHECK_CACHE: dict = {}


def check_interface_echo(stacked, met_s, comms, dmesh, vert_h, G: int = 1,
                         pack_state: dict | None = None):
    """On-device interface coordinate+metric echo (the production chkcomm
    guard, chkcomm_pmmg.c:815 role); raises on an ordering-contract
    violation.  G > 1 routes the exchange through the packed grouped
    layout when the measured occupancy says it beats the dense tile
    (comms.packed_halo_rows; ``pack_state`` makes the layout decision
    sticky across comm-table rebuilds — hysteresis)."""
    from .comms import packed_halo_rows
    Mp = packed_halo_rows(comms.nbr, G, state=pack_state) \
        if G > 1 else None
    key = (tuple(d.id for d in np.asarray(dmesh.devices).flat), G, Mp)
    chk = _IFC_CHECK_CACHE.get(key)
    if chk is None:
        chk = governed("dist.interface_check", budget=2)(
            dist_interface_check(dmesh, G=G, packed_M=Mp))
        _IFC_CHECK_CACHE[key] = chk
    diag = float(np.linalg.norm(vert_h.max(0) - vert_h.min(0))) \
        if len(vert_h) else 1.0
    nbad = int(chk(
        stacked, met_s,
        shard_stacked(jnp.asarray(comms.node_idx), dmesh),
        shard_stacked(jnp.asarray(comms.nbr), dmesh),
        jnp.asarray(1e-6 * diag, stacked.vert.dtype)))
    if nbad:
        raise RuntimeError(
            f"interface comm echo mismatch: {nbad} items "
            "(ordering contract violated)")


def run_adapt_cycles(stacked, met_s, steps: DistSteps, cycles,
                     dmesh, stats=None, verbose=0, on_grow=None,
                     regrow_state=None, label="dist", noswap=False,
                     block=None):
    """Shared SPMD cycle loop: swap cadence (every 3rd cycle + the final
    two), psum'd counter accounting, and the in-place overflow regrow
    (zaldy_pmmg.c:140-254 analogue — slot ids preserved so comm tables
    stay valid).  Past MAX_SHARD_REGROWS doublings, degrades to a
    ShardOverflowError carrying the conforming merged state
    (failed_handling, libparmmg1.c:974-1011).

    Cycles dispatch in fused blocks (default_cycle_block: 9 on TPU, 1
    elsewhere) — one transport round trip + one counter pull per block,
    the same amortization bench.py measures.

    ``on_grow(old_capP)`` lets the caller grow its side tables (global
    numbering) in lockstep; ``regrow_state`` is a 1-element mutable list
    carried across calls so repeated passes share the regrow budget.
    """
    from .distribute import merge_shards, grow_shards
    from .sched import device_mask_enabled, sched_enabled
    from ..ops.adapt import default_cycle_block
    if regrow_state is None:
        regrow_state = [0]
    if block is None:
        block = default_cycle_block(stacked.vert)
    # device-resident quiet levels (the sched.py proof pushed into the
    # compiled block — dist_adapt_block docstring): int8 per logical
    # shard, never pulled to host.  With masking disabled the SAME
    # program runs with an all-zeros level every block (no skipping, no
    # new compile family).
    n_logical = stacked.tmask.shape[0]
    mask_on = sched_enabled() and device_mask_enabled()
    lvl = shard_stacked(jnp.zeros(n_logical, jnp.int8), dmesh)
    c = 0
    while c < cycles:
        nblk = min(block, cycles - c)
        # swaps every 3rd cycle (see ops.adapt.adapt_mesh) and on the
        # final two (quality polish before the merge/migration); those
        # polish cycles also bypass the approximate split prescreen so
        # near-floor shells it over-vetoed get one exact re-evaluation
        # (ops/split.py, ADVICE r3)
        flags = tuple((cc % 3 == 2 or cc >= cycles - 2) and not noswap
                      for cc in range(c, c + nblk))
        pres = tuple(cc < cycles - 2 for cc in range(c, c + nblk))
        step = steps.get(flags, pres,
                         swap_inclusive=any(flags) or noswap)
        stacked, met_s, counts, nact, ovf, lvl2 = step(
            stacked, met_s, jnp.asarray(c, jnp.int32), lvl)
        if mask_on:
            lvl = lvl2
        # ONE host pull per array per block (the blessed .tolist()
        # idiom): the per-field int() casts each forced their own
        # device sync
        ca = counts.tolist()                     # [nblk][4]
        na = nact.tolist()                       # [nblk] active groups
        n_logical = stacked.tmask.shape[0]
        for i in range(nblk):
            cs = ca[i]
            if stats is not None:        # psum'd global counters
                stats.nsplit += cs[0]
                stats.ncollapse += cs[1]
                stats.nswap += cs[2]
                stats.nmoved += cs[3]
                stats.cycles += 1
                # per-group convergence trajectory (the SPMD mirror of
                # the grouped path's active_groups_per_block)
                stats.sched_extra.setdefault(
                    "active_shards_per_cycle", []).append(na[i])
            otrace.log(3, f"  {label} cycle {c + i}: split {cs[0]} "
                          f"collapse {cs[1]} swap {cs[2]} move {cs[3]} "
                          f"active {na[i]}/{n_logical} grp",
                       verbose=verbose)
        if ovf.tolist() != 0:
            if regrow_state[0] >= MAX_SHARD_REGROWS:
                m_, k_, p_ = merge_shards(stacked, met_s,
                                          return_part=True)
                raise ShardOverflowError(m_, k_, p_)
            capP = stacked.vert.shape[1]
            capT = stacked.tet.shape[1]
            stacked, met_s = grow_shards(stacked, met_s,
                                         2 * capP, 2 * capT)
            stacked = shard_stacked(stacked, dmesh)
            met_s = shard_stacked(met_s, dmesh)
            if on_grow is not None:
                on_grow(capP)
            regrow_state[0] += 1
            # every quiet proof is stale at the new capacity (the top-K
            # wave budgets scale with capT) — sched.on_regrow's rule
            lvl = shard_stacked(jnp.zeros(n_logical, jnp.int8), dmesh)
            continue        # re-run the block: truncated winners rerun
        c += nblk
        # convergence: a swap-inclusive (or noswap) cycle on which
        # EVERY logical group posted zero topological ops ends the pass
        # (active_groups == 0 is exactly the summed-zero rule, read
        # from the per-group counts instead of the psum'd total)
        if any((flags[i] or noswap) and na[i] == 0
               for i in range(nblk)):
            break
    return stacked, met_s


def distributed_adapt(mesh: Mesh, met, n_shards: int,
                      cycles: int = 10, dmesh: DeviceMesh | None = None,
                      partitioner: str = "morton", verbose: int = 0,
                      part: np.ndarray | None = None, stats=None,
                      noinsert: bool = False, noswap: bool = False,
                      nomove: bool = False, angedg: float | None = None,
                      hausd: float | None = None):
    """One outer remesh pass on n_shards devices (host driver).

    partition (metric-weighted, boundary-refined; or take the caller's
    displaced ``part``) -> freeze interfaces -> on-device interface echo
    check -> SPMD adapt cycles -> cross-shard surface analysis refresh ->
    merge.  Returns (merged mesh, met, part_of_merged): the partition
    labels of the NEW tets (= source shard), which the caller displaces
    with ``move_interfaces`` before the next outer iteration — the
    remesh-and-repartition scheme of PMMG_parmmglib1/loadbalancing.
    """
    from ..core.mesh import tet_volumes, mesh_to_host
    from .partition import (morton_partition, greedy_partition,
                            fix_contiguity, metric_edge_weights,
                            refine_partition)
    from .distribute import split_to_shards, merge_shards
    from .multihost import require_single_process

    # host-side split/merge orchestration is single-controller today
    require_single_process("distributed_adapt host orchestration")
    if dmesh is None:
        dmesh = make_device_mesh(n_shards)

    vert, tet, vref, tref, vtag = mesh_to_host(mesh)
    if part is None:
        cent = vert[tet].mean(axis=1)
        if partitioner == "morton":
            part = morton_partition(cent, n_shards)
        else:
            part = greedy_partition(tet, cent, n_shards)
        part = fix_contiguity(tet, part)
        # metric-aware cut refinement (PMMG_computeWgt role,
        # metis_pmmg.c:280): keep the interface out of regions whose
        # edges are far from unit metric length
        methost = np.asarray(met)[np.asarray(mesh.vmask)]
        wd = metric_edge_weights(tet, vert, methost)
        part = fix_contiguity(tet, refine_partition(
            part, n_shards, wd["pairs"], wd["w"]))

    steps = DistSteps(dmesh, do_smooth=not nomove,
                      do_insert=not noinsert, hausd=hausd)
    vert_h, tet_h = vert, tet
    s, ms, l2g = split_to_shards(mesh, met, part, n_shards,
                                 cap_mult=3.0, return_l2g=True)
    stacked = shard_stacked(s, dmesh)
    met_s = shard_stacked(ms, dmesh)
    # comm tables (communicators_pmmg.c role) + the on-device interface
    # echo: exchange interface coordinates+metric over halo_exchange and
    # require exact mirror agreement — the production chkcomm guard for
    # the ordering contract
    from .comms import build_interface_comms
    g2l = []
    for s_ in range(n_shards):
        mmap = np.full(len(vert_h), -1, np.int64)
        mmap[l2g[s_]] = np.arange(len(l2g[s_]))
        g2l.append(mmap)
    comms = build_interface_comms(tet_h, part, n_shards, l2g, g2l)
    check_interface_echo(stacked, met_s, comms, dmesh, vert_h)
    stacked, met_s = run_adapt_cycles(
        stacked, met_s, steps, cycles, dmesh,
        stats=stats, verbose=verbose, noswap=noswap)
    # cross-shard surface analysis refresh (PMMG_update_analys analogue)
    # BEFORE the merge: ridge/corner/ref classification with
    # cross-interface dihedrals, written into the shard tags so the
    # merged mesh needs no whole-mesh re-analysis.  Device-resident path
    # first (analysis_dev.py); host fallback on budget overflow.
    from ..core.constants import ANGEDG
    from .analysis_par import extend_numbering
    ang_ = ANGEDG if angedg is None else angedg
    capP_ = stacked.vert.shape[1]
    glo_ = extend_numbering(comms, [capP_] * n_shards)
    st2 = refresh_shard_analysis_device(stacked, comms, n_shards, ang_,
                                        glo_, dmesh)
    if st2 is not None:
        stacked = st2
    else:
        from ..resilience.recover import ladder_step
        ladder_step("host_analysis", site="analysis.ks_overflow")
        stacked = refresh_shard_analysis(stacked, comms, n_shards, ang_,
                                         glo=glo_)
    merged, met_m, part_new = merge_shards(stacked, met_s,
                                           return_part=True)
    return merged, met_m, part_new


@otrace.profile_guard(clear_pass=True)
def distributed_adapt_multi(mesh: Mesh, met, n_shards: int,
                            niter: int = 3, cycles: int = 10,
                            dmesh: DeviceMesh | None = None,
                            partitioner: str = "morton", verbose: int = 0,
                            stats=None, noinsert: bool = False,
                            noswap: bool = False, nomove: bool = False,
                            angedg: float | None = None,
                            hausd: float | None = None,
                            ifc_layers: int = 2,
                            nobalancing: bool = False,
                            part: np.ndarray | None = None,
                            mode: str = "ifc",
                            n_devices: int | None = None,
                            ckpt_tag: str | None = None,
                            resume: bool = False):
    """Shard-resident multi-iteration adaptation (host driver).

    ``n_devices``: groups x shards composition (default = ``n_shards``,
    i.e. one logical shard per device).  With ``n_devices`` <
    ``n_shards``, G = n_shards // n_devices logical shards live on each
    device (leading-axis sharding, G consecutive rows per device); the
    adapt block serializes them with ``lax.map`` so peak HBM per chip is
    bounded by one group's wave working set — the reference's rank-level
    x group-level two-level decomposition (grpsplit_pmmg.c:1551-1614).
    The band-migration and flood programs already operate on the logical
    leading axis (plain jit over sharded arrays) and compose unchanged;
    the analysis refresh dispatches the grouped device program
    (analysis_dev.dist_analysis_grouped) for G > 1, host path on
    KS-budget overflow only.

    ``mode``: between-iteration label source — "ifc" = advancing-front
    interface displacement (device flood, the default repartitioning of
    the reference, libparmmgtypes.h:194); "graph" = group-graph
    repartitioning (morton clusters as the reference's redistribution
    groups, weighted KL/FM on the cluster graph —
    migrate.graph_repartition_labels, metis_pmmg.c:845-1550 role).
    Both realize the moves with the SAME band-migration machinery, so
    neither merges the world between iterations.

    The reference's outer loop re-balances by migrating only moving
    groups over the wire (loadbalancing_pmmg.c:44-161 +
    distributegrps_pmmg.c:1631-1841); the round-1 TPU path instead merged
    the WORLD through host memory every outer iteration.  This driver is
    the incremental redesign: ONE split, then per iteration

        SPMD adapt cycles (device)  ->  cross-shard analysis refresh  ->
        advancing-front labels (device flood)  ->  band migration
        (O(band) host, sparse device scatters)  ->  comm echo check

    and ONE merge at final output.  No full-mesh merge_shards happens
    between iterations — the VERDICT r1 #5 contract.

    Returns (merged mesh, met, part_of_merged).
    """
    from ..core.mesh import mesh_to_host
    from ..core.constants import ANGEDG
    from .partition import (morton_partition, greedy_partition,
                            fix_contiguity, metric_edge_weights,
                            refine_partition)
    from .distribute import split_to_shards, merge_shards
    from .comms import build_interface_comms
    from . import pod
    from .migrate import (pull_views, extend_global_ids_from_vmask,
                          flood_labels, enforce_ne_min, migrate_shards,
                          rebuild_shards, weld_shard_bands,
                          graph_repartition_labels, apply_fresh_ids,
                          kill_glo_rows)
    from .multihost import (require_single_process, pull_host as _pull,
                            is_multiprocess, hot_path, cold_io,
                            mh_uniform)

    # Multi-process contract (round 4, the mpi_pmmg.h role): every
    # process runs THIS SAME driver on the SAME input mesh (identical
    # split + comm tables — the deterministic-host-stage SPMD idiom);
    # device arrays are global ('shard'-sharded across processes via
    # shard_stacked_global), band-table host pulls replicate through
    # pull_host (DCN allgather of band-sized data), and every process
    # computes identical host decisions — the reference's
    # every-rank-agrees design (MPI_Allreduce on ier/counters).  The
    # full-view fallback paths are NOT distributed: they raise below
    # rather than silently pulling a partial world view.
    multi = is_multiprocess()
    if n_devices is None:
        n_devices = n_shards
    if n_shards % n_devices:
        raise ValueError(
            f"n_shards={n_shards} must be a multiple of "
            f"n_devices={n_devices} (G logical shards per device)")
    G = n_shards // n_devices
    if dmesh is None:
        dmesh = make_device_mesh(n_devices)
    ang = ANGEDG if angedg is None else angedg

    vert_h, tet_h, vref_h, tref_h, vtag_h = mesh_to_host(mesh)
    if part is None:
        cent = vert_h[tet_h].mean(axis=1)
        if partitioner == "morton":
            part = morton_partition(cent, n_shards)
        else:
            part = greedy_partition(tet_h, cent, n_shards)
        part = fix_contiguity(tet_h, part)
        methost = np.asarray(met)[np.asarray(mesh.vmask)]
        wd = metric_edge_weights(tet_h, vert_h, methost)
        part = fix_contiguity(tet_h, refine_partition(
            part, n_shards, wd["pairs"], wd["w"]))

    s0, ms0, l2g = split_to_shards(mesh, met, part, n_shards,
                                   cap_mult=3.0, return_l2g=True)
    stacked = shard_stacked(s0, dmesh)
    met_s = shard_stacked(ms0, dmesh)
    capP0 = stacked.vert.shape[1]
    g2l = []
    for s_ in range(n_shards):
        mmap = np.full(len(vert_h), -1, np.int64)
        mmap[l2g[s_]] = np.arange(len(l2g[s_]))
        g2l.append(mmap)
    comms = build_interface_comms(tet_h, part, n_shards, l2g, g2l)
    # persistent global vertex numbering: split-time ids, extended with
    # fresh ids for adapt-created vertices each pass (the
    # PMMG_Compute_verticesGloNum role, libparmmg.c:923)
    glo = [np.full(capP0, -1, np.int64) for _ in range(n_shards)]
    for s_ in range(n_shards):
        glo[s_][: len(l2g[s_])] = l2g[s_]
    top = len(vert_h)

    # ---- per-pass checkpoint/resume (the pod restart unit) -------------
    # worker crash/stall is the EXPECTED failure mode at pod scale
    # (parallel/pod.py): the run re-launches with resume=True and
    # re-enters the loop at the pass after the newest checkpoint —
    # bit-identical to the uninterrupted run (passes are deterministic
    # functions of their input state)
    it0 = 0
    regrow0 = 0
    ckpt_fp = None
    resumed_shared = None
    if ckpt_tag is not None:
        from ..resilience.checkpoint import run_fingerprint
        ckpt_fp = run_fingerprint(
            mesh, met, "dist", n_shards, n_devices, niter, cycles,
            mode, ifc_layers, bool(noswap), bool(noinsert),
            bool(nomove), bool(nobalancing))
    if resume and ckpt_tag is not None:
        from ..obs.metrics import REGISTRY as _REG
        from ..resilience.checkpoint import (latest_dist_checkpoint,
                                             load_dist_checkpoint)
        found = latest_dist_checkpoint(ckpt_tag, ckpt_fp)
        if multi:
            # the resume point is read from each process's LOCAL
            # filesystem: ranks silently re-entering at different
            # passes would execute different collective sequences (the
            # worst failure shape — a hang or a wrong mesh, not an
            # error).  Agree loudly up front: every rank announces its
            # newest pass and they must all match, which also documents
            # the shared-storage requirement of PARMMG_CKPT_DIR.
            from jax.experimental import multihost_utils
            mine = -1 if found is None else found[1]
            # lint: ok(R7) — pre-loop resume agreement on 4 bytes per
            # rank, outside the hot path by construction
            seen = np.asarray(multihost_utils.process_allgather(
                np.asarray([mine], np.int32))).reshape(-1)
            if int(seen.min()) != int(seen.max()):
                raise RuntimeError(
                    f"dist resume diverges across processes (newest "
                    f"checkpointed pass per rank: {seen.tolist()}) — "
                    "PARMMG_CKPT_DIR must be shared storage visible "
                    "to every worker")
        if found is not None:
            payload = load_dist_checkpoint(found[0])
            stacked = shard_stacked(Mesh(
                **{k: jnp.asarray(v)
                   for k, v in payload["stacked"].items()}), dmesh)
            met_s = shard_stacked(jnp.asarray(payload["met"]), dmesh)
            glo = payload["glo"]
            top = payload["top"]
            comms = payload["comms"]
            resumed_shared = payload["shared_prev"]
            regrow0 = payload["regrow"]
            it0 = payload["it"] + 1
            _REG.counter("resilience.resumes").inc()
            otrace.log(1, f"  resuming dist loop at pass {it0} "
                          f"(checkpoint {found[0]})", verbose=verbose)
            # crash-loop breaker: a pass that deterministically kills
            # its worker must not be resumed forever.  The attempt
            # count lives next to the checkpoints (shared storage at
            # pod scale) — only rank 0 writes it, and the escalate
            # decision is agreed across ranks so every worker skips
            # the same passes.
            from ..resilience.checkpoint import crash_loop
            _, esc = crash_loop(
                ckpt_tag, ckpt_fp, it0,
                write=mh_uniform(
                    (not multi) or jax.process_index() == 0,
                    "rank-0-writes: the attempt file lives on shared "
                    "storage, so only process 0 appends; the escalate "
                    "decision itself is re-agreed right below via "
                    "process_allgather(max), every rank skips the "
                    "same passes"))
            if multi:
                from jax.experimental import multihost_utils
                # lint: ok(R7) — pre-loop resume agreement on 4 bytes
                # per rank, outside the hot path by construction
                esc_all = np.asarray(multihost_utils.process_allgather(
                    np.asarray([1 if esc else 0], np.int32))).reshape(-1)
                esc = bool(esc_all.max())
            if esc:
                from ..resilience.recover import ladder_step
                ladder_step(
                    "lowfailure", site="ckpt.resume",
                    detail=f"dist crash loop at pass {it0}: returning "
                           "last conforming checkpoint state")
                # skip the adapt loop entirely: the restored state IS
                # the last conforming answer; the post-loop merge hands
                # it back (graded-failure contract, PMMG_LOWFAILURE)
                it0 = max(1, niter)

    # sticky dense/packed halo-layout decision across comm-table
    # rebuilds (comms.packed_halo_rows hysteresis): ONE state dict
    # threaded through every packed-layout decision of this run
    pack_state: dict = {}
    check_interface_echo(stacked, met_s, comms, dmesh, vert_h, G=G,
                         pack_state=pack_state)

    steps = DistSteps(dmesh, do_smooth=not nomove,
                      do_insert=not noinsert, hausd=hausd, G=G)

    def grow_glo(old_capP):
        # keep the global-numbering tables in lockstep with a device
        # regrow (slot-stable pad)
        for s_ in range(n_shards):
            glo[s_] = np.concatenate(
                [glo[s_], np.full(old_capP, -1, np.int64)])

    # ---- O(band) device path state --------------------------------------
    # the band path keeps the numbering ON DEVICE (int32 lockstep copy)
    # and replaces the full views pull + host interface rescan with
    # device-compacted band/interface tables (parallel/migrate_dev.py);
    # any budget overflow falls back to the full-view oracle path below
    import os as _os
    # both repartitioning modes ride the band path (graph mode since
    # round 4: cluster graph from device-compacted tables,
    # migrate_dev.graph_repartition_labels_band)
    use_band = _os.environ.get("PARMMG_BAND_PATH", "1") != "0"
    if multi and not use_band:
        raise NotImplementedError(
            "multi-process runs require the band path (the full-view "
            "loop is single-controller)")
    glo_d = None
    shared_prev = None
    if use_band:
        from .migrate_dev import (extend_ids_device, band_migrate_iteration,
                                  band_weld, session_ids_fit,
                                  dead_glo_rows)
        glo_d = jnp.asarray(np.stack(glo).astype(np.int32))
        # initially-shared gids: interface vertices of the initial comms
        # (a resumed run restores the exact set its checkpoint carried)
        shared_prev = resumed_shared if resumed_shared is not None \
            else _shared_gids(comms, glo, n_shards)

    regrow_state = [regrow0]
    ana_cache: dict = {}
    # ---- the pod hot path -------------------------------------------
    # every iteration body runs inside multihost.hot_path(): a stray
    # process_allgather in there is metered on mh.hot_allgather_bytes
    # (run_tests.sh --multihost asserts ZERO) and raises under
    # PARMMG_MH_STRICT; pod.activate feeds pod.gather_band the device
    # topology its cached exchange programs key on
    with pod.activate(dmesh, n_shards), hot_path():
        for it in range(it0, max(1, niter)):
            # profiler capture window + pass tag on every trace record
            # emitted inside this outer iteration (obs/trace.py)
            otrace.profile_pass_begin(it)
            otrace.set_context(**{"pass": it})
            capP_before = stacked.vert.shape[1]
            _t_seg = time.perf_counter()
            stacked, met_s = run_adapt_cycles(
                stacked, met_s, steps, cycles, dmesh,
                stats=stats, verbose=verbose, on_grow=grow_glo,
                regrow_state=regrow_state, label=f"dist it {it}",
                noswap=noswap)
            otrace.emit_span("dist.adapt", time.perf_counter() - _t_seg)
            _t_seg = time.perf_counter()
            if use_band and stacked.vert.shape[1] != capP_before:
                glo_d = None          # regrown: rebuild the device copy
            # extend the session numbering (device on the band path, with a
            # band-sized fresh-id pull; vmask-pull host path otherwise),
            # then the DEVICE analysis refresh
            if use_band:
                if glo_d is None:
                    glo_d = jnp.asarray(np.stack(glo).astype(np.int32))
                KN = max(256, stacked.vert.shape[1] // 2)
                # int32 numbering on device (documented migrate_dev limit):
                # the monotone session counter must not wrap — if this
                # iteration could hand out ids past int31, take the host
                # path (which re-derives a compact numbering) instead of
                # silently aliasing device ids
                ids_fit = session_ids_fit(top, n_shards, KN)
                oke = False
                if ids_fit:
                    # newly-dead delta FIRST: the pre-extend numbering
                    # still carries the dying rows' ids, so (glo >= 0 &
                    # ~vmask) is exactly the band-sized kill list the host
                    # mirror needs — the O(mesh) vmask allgather of the
                    # pre-pod path is gone (migrate_dev.dead_glo_rows)
                    d_rows, d_cnt, d_ok = dead_glo_rows(
                        glo_d, stacked.vmask, KD=KN)
                    glo_d2, top_d, f_rows, f_gids, oke = extend_ids_device(
                        glo_d, stacked.vmask, jnp.asarray(top, jnp.int32),
                        KN=KN)
                    oke = bool(oke) and bool(d_ok)
                if ids_fit and oke:
                    glo_d = glo_d2
                    top = int(top_d)
                    # ONE packed band exchange replicates the compacted
                    # fresh-id + dead-delta tables to every process
                    f_rows, f_gids, d_rows, d_cnt = pod.gather_band(
                        f_rows, f_gids, d_rows, d_cnt, what="extend")
                    apply_fresh_ids(glo, f_rows, f_gids)
                    kill_glo_rows(glo, d_rows, d_cnt)
                else:               # fresh-id/dead budget blown: host extend
                    # lint: ok(R7) — documented escape hatch (budget
                    # overflow): the O(mesh) mask pull is metered by
                    # pull_host and visible on mh.allgather_bytes
                    vmask_h = _pull(stacked.vmask, what="host_extend")
                    top = extend_global_ids_from_vmask(glo, vmask_h, top)
                    if top >= 2 ** 31:
                        # the int32 device numbering can no longer represent
                        # the session ids: permanently leave the band path
                        # (the host path carries int64 ids) instead of
                        # wrapping them on the next device cast
                        use_band = False
                        glo_d = None
                    else:
                        glo_d = jnp.asarray(np.stack(glo).astype(np.int32))
            else:
                # lint: ok(R7) — legacy full-view path (PARMMG_BAND_PATH=0,
                # single-controller only); metered by pull_host
                vmask_h = _pull(stacked.vmask, what="legacy_extend")
                top = extend_global_ids_from_vmask(glo, vmask_h, top)
            # device analysis refresh: per-device shard_map for G=1, the
            # grouped lax.map program for G>1 (analysis_dev) — the host
            # path below is the KS-budget-overflow fallback ONLY, so the
            # steady-state G>1 loop performs zero O(mesh) host pulls
            st2 = refresh_shard_analysis_device(
                stacked, comms, n_shards, ang, glo, dmesh, cache=ana_cache,
                pack_state=pack_state)
            views = None
            if st2 is not None:
                stacked = st2
            else:
                if multi:
                    # no ladder event here: the fallback is NOT taken on
                    # the multi-process path — recording host_analysis and
                    # then dying would log a recovery that never happened
                    raise NotImplementedError(
                        "analysis host fallback needs a full-view pull — "
                        "not distributed; raise the KS budget or run "
                        "single-process")
                # host fallback (shared-record budget overflow) — the
                # "host_analysis" escalation-ladder rung
                from ..resilience.recover import ladder_step
                ladder_step("host_analysis", site="analysis.ks_overflow")
                views = pull_views(stacked, met_s)
                stacked = refresh_shard_analysis(
                    stacked, comms, n_shards, ang, glo=glo, views=views)
            otrace.emit_span("dist.refresh", time.perf_counter() - _t_seg)
            _t_seg = time.perf_counter()
            if it + 1 < max(1, niter) and not nobalancing:
                nmoved = 0
                band_done = False
                if use_band:
                    from .migrate_dev import (repair_flood_labels,
                                              graph_repartition_labels_band)
                    if mode == "graph":
                        # cluster-graph rebalance from device tables (the
                        # metis_pmmg.c:845-1550 gather-only-the-graph role);
                        # depth 0 everywhere — the donor floor still bounds
                        # per-shard departures, order within a shard is
                        # immaterial for cluster moves
                        labels_d = graph_repartition_labels_band(
                            stacked, comms, n_shards, verbose=verbose)
                        depth_d = jnp.zeros(stacked.tmask.shape, jnp.int32)
                        if labels_d is None:
                            labels_d = jnp.broadcast_to(
                                jnp.arange(n_shards, dtype=jnp.int32)[:, None],
                                stacked.tmask.shape)
                    else:
                        sizes = jnp.sum(stacked.tmask, axis=1,
                                        dtype=jnp.int32)
                        labels_d, depth_d = flood_labels(
                            stacked, jnp.asarray(comms.node_idx),
                            jnp.asarray(comms.nbr), sizes, n_shards,
                            nlayers=ifc_layers)
                        # contiguity/reachability repair on the displaced
                        # partition (moveinterfaces_pmmg.c:475-720 role)
                        labels_d, _nfix = repair_flood_labels(
                            stacked, labels_d, depth_d, n_shards,
                            verbose=verbose)
                    res = band_migrate_iteration(
                        stacked, met_s, glo_d, glo, labels_d, depth_d,
                        shared_prev, n_shards, verbose=verbose)
                    # capacity/budget overflow: slot-stable grow (the full
                    # path's migrate_shards grow loop analogue) raises both
                    # the free slots AND the capacity-scaled band budgets;
                    # bounded retries before the full-view fallback
                    for _retry in range(3):
                        if res is not None:
                            break
                        from .distribute import grow_shards
                        capP_o = stacked.vert.shape[1]
                        capT_o = stacked.tet.shape[1]
                        stacked, met_s = grow_shards(
                            stacked, met_s, 2 * capP_o, 2 * capT_o)
                        views = None    # any pre-grow pull is shape-stale
                        grow_glo(capP_o)
                        glo_d = jnp.asarray(np.stack(glo).astype(np.int32))
                        me_col = jnp.arange(n_shards,
                                            dtype=labels_d.dtype)[:, None]
                        labels_d = jnp.concatenate(
                            [labels_d, jnp.broadcast_to(
                                me_col, (n_shards, capT_o))], axis=1)
                        depth_d = jnp.concatenate(
                            [depth_d, jnp.zeros((n_shards, capT_o),
                                                depth_d.dtype)], axis=1)
                        res = band_migrate_iteration(
                            stacked, met_s, glo_d, glo, labels_d, depth_d,
                            shared_prev, n_shards, verbose=verbose)
                    if res is not None:
                        (stacked, met_s, glo_d, comms2, shared_prev,
                         nmoved, arr_slots) = res
                        band_done = True
                        if nmoved:
                            comms = comms2
                            # weld the arrival neighborhoods (region-scoped)
                            stacked, glo_d, nweld = band_weld(
                                stacked, met_s, glo_d, glo, arr_slots,
                                n_shards, verbose=verbose)
                            if nweld < 0:     # region budget blown: full weld
                                if multi:
                                    # fail loudly (the designed
                                    # contract) instead of the opaque
                                    # non-addressable fetch error
                                    # pull_views would raise
                                    raise NotImplementedError(
                                        "full-region weld fallback is "
                                        "single-controller; band_weld'"
                                        "s escalating probe must hold "
                                        "on a multi-process run")
                                views_w = pull_views(stacked, met_s)
                                stacked, _ = weld_shard_bands(
                                    stacked, views_w, glo, n_shards,
                                    verbose=verbose)
                                # the full weld freed host-glo rows; the
                                # device copy must drop them too (stale
                                # gids resurrect — see band_weld)
                                glo_d = jnp.asarray(
                                    np.stack(glo).astype(np.int32))
                            stacked = rebuild_shards(stacked)
                            check_interface_echo(stacked, met_s, comms,
                                                 dmesh, vert_h, G=G,
                                                 pack_state=pack_state)
                    else:
                        otrace.log(1, f"  it {it}: band budgets exceeded — "
                                      "falling back to the full-view path",
                                   verbose=verbose)
                if not band_done:
                    if multi:
                        raise NotImplementedError(
                            "full-view migration fallback is "
                            "single-controller; band budgets must hold on "
                            "a multi-process run")
                    if views is None:
                        views = pull_views(stacked, met_s)
                    if mode == "graph":
                        labels = graph_repartition_labels(views, glo,
                                                          n_shards)
                        labels = enforce_ne_min(labels, views.tmask,
                                                n_shards)
                    else:
                        from .migrate_dev import repair_flood_labels
                        sizes = jnp.asarray(
                            views.tmask.sum(axis=1).astype(np.int32))
                        labels_d, depth_d = flood_labels(
                            stacked, jnp.asarray(comms.node_idx),
                            jnp.asarray(comms.nbr), sizes, n_shards,
                            nlayers=ifc_layers)
                        labels_d, _nfix = repair_flood_labels(
                            stacked, labels_d, depth_d, n_shards,
                            verbose=verbose)
                        labels = np.asarray(labels_d)
                        labels = enforce_ne_min(labels, views.tmask,
                                                n_shards,
                                                depth=np.asarray(depth_d))
                    touched = sorted({int(r) for s_ in range(n_shards)
                                      for r in np.unique(
                                          labels[s_][views.tmask[s_]])
                                      if int(r) != s_})
                    stacked, met_s, comms2, nmoved = migrate_shards(
                        stacked, met_s, views, glo, labels, n_shards,
                        verbose=verbose)
                    if nmoved:
                        comms = comms2
                        stacked, _ = weld_shard_bands(
                            stacked, views, glo, n_shards,
                            touched=touched, verbose=verbose)
                        stacked = rebuild_shards(stacked)
                        check_interface_echo(stacked, met_s, comms, dmesh,
                                             vert_h, G=G,
                                             pack_state=pack_state)
                    if use_band:    # resync the device numbering copy
                        glo_d = jnp.asarray(np.stack(glo).astype(np.int32))
                        shared_prev = _shared_gids(comms, glo, n_shards)
                if nmoved:
                    otrace.log(2, f"  it {it}: migrated {nmoved} "
                                  "interface-band tets", verbose=verbose)
                # host-to-host group handoff (pod runtime, opt-in knob
                # PARMMG_MH_HANDOFF): when device loads skew past the
                # imbalance threshold, whole logical shards move to other
                # devices — and thereby other processes — as one compiled
                # permutation; comm tables + numbering mirrors remap in
                # lockstep (parallel/pod.py).  gids are unchanged under a
                # permutation, so shared_prev needs no update.
                if pod.handoff_enabled() and use_band and glo_d is not None:
                    (stacked, met_s, glo_d, glo, comms,
                     nmv_h) = pod.maybe_handoff(stacked, met_s, glo_d, glo,
                                                comms, verbose=verbose)
                    if nmv_h:
                        check_interface_echo(stacked, met_s, comms, dmesh,
                                             vert_h, G=G,
                                             pack_state=pack_state)
            otrace.emit_span("dist.migrate", time.perf_counter() - _t_seg)
            if ckpt_tag is not None:
                from ..core.mesh import MESH_FIELDS
                from ..resilience.checkpoint import (ckpt_due,
                                                     save_dist_checkpoint)
                if ckpt_due(it):
                    # durable-output replication is the designed cost of
                    # the checkpoint path, not a stray hot-loop allgather:
                    # every process participates in the collective pull
                    # (cold_io exempts it from the hot meter), process 0
                    # writes the file
                    with cold_io():
                        # lint: ok(R7) — checkpoint IO replication under
                        # cold_io (module-documented escape hatch)
                        sh_host = {f: _pull(getattr(stacked, f))
                                   for f in MESH_FIELDS}
                        # lint: ok(R7) — same checkpoint IO section
                        met_host = _pull(met_s)
                        save_dist_checkpoint(
                            ckpt_tag, it, sh_host, met_host, glo, top,
                            comms,
                            shared_prev if shared_prev is not None
                            else np.zeros(0, np.int64),
                            regrow_state[0], fingerprint=ckpt_fp,
                            write=mh_uniform(
                                (not multi)
                                or jax.process_index() == 0,
                                "rank-0-writes: every rank computed "
                                "the identical checkpoint payload "
                                "(the cold_io collective pull above "
                                "replicated it); process 0 durably "
                                "writes, the others only needed the "
                                "agreement"))
            otrace.profile_pass_end(it)
    otrace.set_context(**{"pass": None})
    _t_seg = time.perf_counter()
    if multi:
        # final output: replicate the (end-state) shards to every
        # process and merge identically everywhere — the
        # centralized-output analogue of PMMG_parmmglib_centralized's
        # gather (the distributed-output entry, io.distributed, writes
        # per-process rank files instead and never pays this gather).
        # OUTSIDE the hot path: this is the one designed O(mesh)
        # replication of a centralized run, visible on
        # mh.allgather_bytes but never on the hot counter.
        # lint: ok(R7) — the documented final-output gather
        stacked = jax.tree.map(_pull, stacked)
        # lint: ok(R7) — same final-output gather
        met_s = _pull(met_s)
    merged, met_m, part_new = merge_shards(stacked, met_s,
                                           return_part=True)
    otrace.emit_span("dist.merge", time.perf_counter() - _t_seg)
    return merged, met_m, part_new


def _shared_gids(comms, glo, n_shards: int) -> np.ndarray:
    """Interface-vertex gids from the comm tables (the band path's
    shared-vertex candidate seed)."""
    sh0 = []
    for s_ in range(n_shards):
        rows = np.unique(comms.node_idx[s_][comms.node_idx[s_] >= 0])
        sh0.append(glo[s_][rows])
    return np.unique(np.concatenate(sh0)) if sh0 else \
        np.zeros(0, np.int64)
