"""Device-resident band migration — the O(band + interface) host path.

The round-2/3 incremental migration (parallel/migrate.py) made the
DEVICE traffic O(band), but the host still pulled every shard's full
arrays each outer iteration (``pull_views``), re-scanned every live
tet's faces (``recompute_interface``) and re-derived tag membership at
full width (``_retag_interfaces``) — the host-side scaling ceiling the
reference never has: ParMmg's loop touches only moving groups and
OLDPARBDY entities (/root/reference/src/distributegrps_pmmg.c:1631-1841,
analys_pmmg.c:1571).

This module moves the whole between-iteration pipeline onto the device:

  - ``device_migrate``: donor floor (deepest-flood-layer-first, the
    moveinterfaces_pmmg.c:1343 front-order semantics), band compaction,
    cross-shard package transfer (XLA inserts the all-to-all over the
    sharded axis), arrival resolution by global id including slot
    resurrection, vertex-slot allocation, liveness, and the session
    numbering extension — ONE jitted program, all static shapes.
  - ``exposed_face_probe``: per-shard exposed-face tables (global-id
    triples), compacted to an interface-sized budget on device.

The host sees only compacted, band/interface-sized tables: arrival
(row, gid) pairs, fresh-id assignments, exposed-face keys, and tag
values at (old ∪ new) interface slots.  Budget overflows set ``ok=False``
and the caller falls back to the full-view path (parallel/migrate.py),
which remains the correctness oracle (tests/test_band_path.py asserts
end-state parity between the two paths).

Global ids ride int32 on device: the session counter is monotonic and
stays far below 2^31 for any mesh this single-controller path hosts
(10M tets x a few ids/tet/iteration); the host mirror stays int64.
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .multihost import pull_host as _pull
from ..core.mesh import Mesh
from ..obs import trace as otrace
from ..core.constants import IDIR
from ..utils.compilecache import bucket, governed

_I32MAX = 2147483647


# ---------------------------------------------------------------------------
# the migration program
# ---------------------------------------------------------------------------
# NO donation: on a budget overflow (ok=False) the caller falls back to
# the full-view path with the ORIGINAL arrays — donating them here would
# hand back deleted buffers exactly on that path
@governed("migrate_dev.device_migrate", budget=4)
@partial(jax.jit, static_argnames=("KB", "KV"))
def device_migrate(stacked: Mesh, met_s, glo_d, labels, depth,
                   KB: int, KV: int):
    """Apply the displaced partition on device.

    ``glo_d``: [S, capP] int32 global vertex ids (-1 dead).
    ``labels``/``depth``: flood output [S, capT].
    ``KB``: max moved tets per shard (and max arrivals per shard);
    ``KV``: max new vertex rows per shard.

    Returns (stacked', met', glo_d', info) with info = dict of
      ok          scalar bool — every budget respected; when False the
                  outputs are UNDEFINED and the caller must fall back
      nmoved      scalar int32 total moved tets
      arr_rows/arr_gids [S, KV] newly-allocated vertex rows (-1 pad)
      dep_slots   [S, KB] departed tet slots (capT pad)
      arr_slots   [S, KB] arrival tet slots (capT pad)
    """
    S, capT = stacked.tet.shape[:2]
    capP = stacked.vert.shape[1]
    me = jnp.arange(S, dtype=jnp.int32)[:, None]
    live = stacked.tmask
    nlive = jnp.sum(live, axis=1)

    # ---- donor floor: revert deepest flood layers first -----------------
    floor = jnp.minimum(6, nlive // 2 + 1)
    moved0 = live & (labels != me)
    nmove0 = jnp.sum(moved0, axis=1)
    excess = jnp.maximum(0, nmove0 - (nlive - floor))
    ordd = jnp.argsort(jnp.where(moved0, -depth, _I32MAX), axis=1,
                       stable=True)
    rank = jnp.zeros((S, capT), jnp.int32).at[
        jnp.arange(S)[:, None], ordd].set(
        jnp.broadcast_to(jnp.arange(capT, dtype=jnp.int32), (S, capT)))
    revert = moved0 & (rank < excess[:, None])
    labels = jnp.where(revert, me, labels)
    moved = moved0 & ~revert
    nmove = jnp.sum(moved, axis=1)
    nmoved = jnp.sum(nmove)
    ok = jnp.all(nmove <= KB)

    # ---- band compaction + cross-shard pool -----------------------------
    midx = jax.vmap(lambda m: jnp.nonzero(m, size=KB,
                                          fill_value=capT)[0])(moved)
    mvalid = midx < capT
    mslot = jnp.clip(midx, 0, capT - 1)
    src2 = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[:, None],
                            (S, KB))
    mdst = jnp.where(mvalid,
                     labels[src2, mslot].astype(jnp.int32), S)
    P = S * KB
    p_src = src2.reshape(P)
    p_slot = mslot.reshape(P)
    p_dst = mdst.reshape(P)
    # payload gathers (cross-shard reads; the sharded axis makes this the
    # band all-to-all)
    p_tet = stacked.tet[p_src, p_slot]                     # [P,4] local
    p_gt = glo_d[p_src[:, None], jnp.clip(p_tet, 0, capP - 1)]
    p_tref = stacked.tref[p_src, p_slot]
    p_ftag = stacked.ftag[p_src, p_slot]
    p_fref = stacked.fref[p_src, p_slot]
    p_etag = stacked.etag[p_src, p_slot]
    p_vert = stacked.vert[p_src[:, None], jnp.clip(p_tet, 0, capP - 1)]
    p_vtag = stacked.vtag[p_src[:, None], jnp.clip(p_tet, 0, capP - 1)]
    p_vref = stacked.vref[p_src[:, None], jnp.clip(p_tet, 0, capP - 1)]
    p_met = met_s[p_src[:, None], jnp.clip(p_tet, 0, capP - 1)]

    # sort the pool by destination -> contiguous per-recipient segments
    ordp = jnp.argsort(p_dst, stable=True)
    sdst = p_dst[ordp]
    seg_start = jnp.searchsorted(sdst, jnp.arange(S, dtype=sdst.dtype))
    seg_cnt = jnp.searchsorted(
        sdst, jnp.arange(S, dtype=sdst.dtype), side="right") - seg_start
    ok = ok & jnp.all(seg_cnt <= KB)

    def take_seg(arr):
        """[P, ...] sorted-pool array -> [S, KB, ...] per recipient.

        The sorted pool is padded by KB rows so a segment starting near
        the end never clamps (a clamped dynamic_slice would shift the
        segment and misalign the validity mask)."""
        s_arr = arr[ordp]
        pad = jnp.zeros((KB,) + arr.shape[1:], arr.dtype)
        s_arr = jnp.concatenate([s_arr, pad], axis=0)

        def one(start):
            return jax.lax.dynamic_slice_in_dim(s_arr, start, KB, axis=0)
        return jax.vmap(one)(seg_start)

    apos = jnp.arange(KB)[None, :]
    avalid = apos < seg_cnt[:, None]                       # [S,KB]
    a_gt = jnp.where(avalid[..., None], take_seg(p_gt), -1)
    a_tref = take_seg(p_tref)
    a_ftag = take_seg(p_ftag)
    a_fref = take_seg(p_fref)
    a_etag = take_seg(p_etag)
    a_vert = take_seg(p_vert)                              # [S,KB,4,3]
    a_vtag = take_seg(p_vtag)
    a_vref = take_seg(p_vref)
    a_met = take_seg(p_met)

    # ---- departures ------------------------------------------------------
    tmask1 = live & ~moved

    # ---- arrival vertex resolution by global id -------------------------
    # recipient's current gid -> row table (dead rows sort last)
    gkey = jnp.where(glo_d >= 0, glo_d, _I32MAX)
    gord = jnp.argsort(gkey, axis=1)                       # [S,capP]
    gsorted = jnp.take_along_axis(gkey, gord, axis=1)
    A4 = KB * 4
    agid = a_gt.reshape(S, A4)
    a4valid = agid >= 0
    pos = jax.vmap(jnp.searchsorted)(gsorted, jnp.where(a4valid, agid, 0))
    posc = jnp.clip(pos, 0, capP - 1)
    found = a4valid & (jnp.take_along_axis(gsorted, posc, 1) == agid)
    found_row = jnp.take_along_axis(gord, posc, 1)         # [S,A4]

    # unique missing gids per shard: sort, head-detect, allocate
    mkey = jnp.where(a4valid & ~found, agid, _I32MAX)
    mord = jnp.argsort(mkey, axis=1)
    msort = jnp.take_along_axis(mkey, mord, axis=1)
    mhead = jnp.concatenate(
        [jnp.ones((S, 1), bool), msort[:, 1:] != msort[:, :-1]], axis=1)
    mhead = mhead & (msort != _I32MAX)
    n_new = jnp.sum(mhead, axis=1)                         # [S]
    # free vertex rows (ascending)
    fidx = jax.vmap(lambda g: jnp.nonzero(g < 0, size=KV,
                                          fill_value=capP)[0])(glo_d)
    nfree = jnp.sum(glo_d < 0, axis=1)
    ok = ok & jnp.all(n_new <= KV) & jnp.all(n_new <= nfree)
    alloc_ord = jnp.cumsum(mhead, axis=1) - 1              # [S,A4]
    new_row_sorted = jnp.where(
        mhead, jnp.take_along_axis(
            fidx, jnp.clip(alloc_ord, 0, KV - 1), 1), capP)
    # broadcast the head's row to its duplicates (same gid, same segment)
    seg_id = jnp.cumsum(mhead, axis=1) - 1
    head_row_of_seg = jnp.full((S, A4), -1, jnp.int32).at[
        jnp.arange(S)[:, None],
        jnp.where(mhead, seg_id, A4)].max(
        new_row_sorted.astype(jnp.int32), mode="drop")
    row_sorted = head_row_of_seg[jnp.arange(S)[:, None],
                                 jnp.clip(seg_id, 0, A4 - 1)]
    # unsort back to arrival-corner order
    row_missing = jnp.zeros((S, A4), jnp.int32).at[
        jnp.arange(S)[:, None], mord].set(row_sorted)
    a_row = jnp.where(found, found_row, row_missing)       # [S,A4]
    a_row = jnp.where(a4valid, a_row, capP)

    # ---- scatter new vertex rows ----------------------------------------
    # payload source: the sorted head corners (first occurrence wins)
    pay_corner = mord                                       # [S,A4] corner
    vsrc = jnp.clip(pay_corner, 0, A4 - 1)
    tgt_new = jnp.where(mhead, new_row_sorted, capP)        # [S,A4]
    sidx = jnp.arange(S)[:, None]
    av_flat = a_vert.reshape(S, A4, 3)
    at_flat = a_vtag.reshape(S, A4)
    ar_flat = a_vref.reshape(S, A4)
    am_flat = a_met.reshape(S, A4, *a_met.shape[3:])
    vert2 = stacked.vert.at[sidx, tgt_new].set(
        jnp.take_along_axis(av_flat, vsrc[..., None], 1), mode="drop")
    vtag2 = stacked.vtag.at[sidx, tgt_new].set(
        jnp.take_along_axis(at_flat, vsrc, 1), mode="drop")
    vref2 = stacked.vref.at[sidx, tgt_new].set(
        jnp.take_along_axis(ar_flat, vsrc, 1), mode="drop")
    if am_flat.ndim == 2:
        met2 = met_s.at[sidx, tgt_new].set(
            jnp.take_along_axis(am_flat, vsrc, 1), mode="drop")
    else:
        met2 = met_s.at[sidx, tgt_new].set(
            jnp.take_along_axis(am_flat, vsrc[..., None], 1), mode="drop")
    glo2 = glo_d.at[sidx, tgt_new].set(
        jnp.where(mhead, msort, 0).astype(jnp.int32), mode="drop")

    # ---- place arrival tets in free slots -------------------------------
    tfree = jax.vmap(lambda m: jnp.nonzero(~m, size=KB,
                                           fill_value=capT)[0])(tmask1)
    nfree_t = jnp.sum(~tmask1, axis=1)
    ok = ok & jnp.all(seg_cnt <= nfree_t)
    arr_slot = jnp.where(avalid, tfree[:, :KB], capT)      # [S,KB]
    lt = a_row.reshape(S, KB, 4).astype(jnp.int32)
    lt = jnp.clip(lt, 0, capP - 1)
    tet2 = stacked.tet.at[sidx, arr_slot].set(lt, mode="drop")
    tref2 = stacked.tref.at[sidx, arr_slot].set(a_tref, mode="drop")
    ftag2 = stacked.ftag.at[sidx, arr_slot].set(a_ftag, mode="drop")
    fref2 = stacked.fref.at[sidx, arr_slot].set(a_fref, mode="drop")
    etag2 = stacked.etag.at[sidx, arr_slot].set(a_etag, mode="drop")
    tmask2 = tmask1.at[sidx, arr_slot].set(True, mode="drop")

    # ---- liveness + watermarks ------------------------------------------
    tid = jnp.where(tmask2[..., None], tet2, capP)
    ref = jnp.zeros((S, capP + 1), bool).at[
        sidx[..., None], tid.reshape(S, -1)].max(
        True, mode="drop")[:, :capP]
    vmask2 = ref
    glo2 = jnp.where(ref, glo2, -1)
    rowsP = jnp.broadcast_to(jnp.arange(capP, dtype=jnp.int32),
                             (S, capP))
    npoin2 = jnp.max(jnp.where(ref, rowsP + 1, 0), axis=1)
    rowsT = jnp.broadcast_to(jnp.arange(capT, dtype=jnp.int32),
                             (S, capT))
    nelem2 = jnp.max(jnp.where(tmask2, rowsT + 1, 0), axis=1)

    out = dataclasses.replace(
        stacked, vert=vert2, vtag=vtag2, vref=vref2, vmask=vmask2,
        tet=tet2, tref=tref2, tmask=tmask2, ftag=ftag2, fref=fref2,
        etag=etag2, npoin=npoin2.astype(jnp.int32),
        nelem=nelem2.astype(jnp.int32))
    # newly-allocated vertex rows, compacted to [S, KV] for the host glo
    # mirror sync
    alloc_tgt = jnp.where(mhead, jnp.clip(alloc_ord, 0, KV - 1), KV)
    arr_rows = jnp.full((S, KV), -1, jnp.int32).at[sidx, alloc_tgt].set(
        new_row_sorted.astype(jnp.int32), mode="drop")
    arr_gids = jnp.full((S, KV), -1, jnp.int32).at[sidx, alloc_tgt].set(
        msort.astype(jnp.int32), mode="drop")
    # newly-DEAD vertex rows (id-carrying before, unreferenced after the
    # departures), compacted: the band-sized liveness DELTA that lets
    # the host glo mirror sync without an O(mesh) vmask allgather
    # (migrate.kill_glo_rows; dying rows are vertices of departed tets,
    # so the KV budget that bounds arrivals bounds them too — overflow
    # joins the ok fallback like every other budget)
    newly_dead = (glo_d >= 0) & ~ref
    n_dead = jnp.sum(newly_dead, axis=1)
    ok = ok & jnp.all(n_dead <= KV)
    dead_rows = jax.vmap(lambda m: jnp.nonzero(m, size=KV,
                                               fill_value=capP)[0])(
        newly_dead).astype(jnp.int32)
    info = dict(ok=ok, nmoved=nmoved, arr_rows=arr_rows,
                arr_gids=arr_gids, dep_slots=midx,
                arr_slots=arr_slot, labels=labels,
                dead_rows=dead_rows, dead_cnt=n_dead.astype(jnp.int32),
                # per-condition diagnostics (which budget blew)
                ok_parts=jnp.stack([
                    jnp.all(nmove <= KB), jnp.all(seg_cnt <= KB),
                    jnp.all(n_new <= KV), jnp.all(n_new <= nfree),
                    jnp.all(seg_cnt <= nfree_t),
                    jnp.all(n_dead <= KV)]))
    return out, met2, glo2, info


# ---------------------------------------------------------------------------
# exposed-face probe
# ---------------------------------------------------------------------------
@governed("migrate_dev.exposed_face_probe", budget=4)
@partial(jax.jit, static_argnames=("KF",))
def exposed_face_probe(stacked: Mesh, glo_d, KF: int):
    """Per-shard exposed faces as global-id triples, device-compacted.

    Returns (keys [S, KF, 3] int32 sorted-gid triples (-1 pad),
             slots [S, KF] int32 4*tet+face (capT*4 pad),
             cnt [S], ok scalar bool).
    """
    S, capT = stacked.tet.shape[:2]
    capP = stacked.vert.shape[1]
    idir = jnp.asarray(IDIR)

    def one(tet, tmask, glo_s):
        gtet = glo_s[jnp.clip(tet, 0, capP - 1)]           # [capT,4]
        tri = jnp.sort(gtet[:, idir], axis=2).reshape(capT * 4, 3)
        valid = jnp.repeat(tmask, 4)
        c0 = jnp.where(valid, tri[:, 0], _I32MAX)
        c1 = jnp.where(valid, tri[:, 1], _I32MAX)
        c2 = jnp.where(valid, tri[:, 2], _I32MAX)
        order = jnp.lexsort((c2, c1, c0))
        k0, k1, k2 = c0[order], c1[order], c2[order]
        eq_next = (k0[1:] == k0[:-1]) & (k1[1:] == k1[:-1]) & \
            (k2[1:] == k2[:-1]) & (k0[:-1] != _I32MAX)
        same_next = jnp.concatenate([eq_next, jnp.array([False])])
        same_prev = jnp.concatenate([jnp.array([False]), eq_next])
        exposed_s = ~(same_next | same_prev) & (k0 != _I32MAX)
        slot4 = order.astype(jnp.int32)      # flat index IS 4*tet+face
        cnt = jnp.sum(exposed_s, dtype=jnp.int32)
        sel = jnp.nonzero(exposed_s, size=KF, fill_value=capT * 4)[0]
        selc = jnp.clip(sel, 0, capT * 4 - 1)
        keys = jnp.where((sel < capT * 4)[:, None],
                         jnp.stack([k0, k1, k2], 1)[selc], -1)
        slots = jnp.where(sel < capT * 4, slot4[selc], capT * 4)
        return keys, slots, cnt

    keys, slots, cnt = jax.vmap(one)(stacked.tet, stacked.tmask, glo_d)
    return keys, slots, cnt, jnp.all(cnt <= KF)


# ---------------------------------------------------------------------------
# freeze / unfreeze retag, fully on device
# ---------------------------------------------------------------------------
def _freeze_bits_j(tags, is_edge_or_vert: bool, true_bdy=None):
    """jnp mirror of migrate._freeze_bits (tag_pmmg.c:39-124 contract)."""
    from ..core.constants import (PARBDY_TAGS, MG_REQ, MG_NOSURF, MG_BDY,
                                  MG_PARBDYBDY)
    user_req = (tags & MG_REQ) != 0
    out = tags | PARBDY_TAGS
    if is_edge_or_vert:
        tb = (tags & MG_BDY) != 0 if true_bdy is None else true_bdy
        out = jnp.where(tb, out | MG_PARBDYBDY, out)
    out = jnp.where(user_req, out & ~jnp.uint32(MG_NOSURF), out)
    return out


def _unfreeze_bits_j(tags, is_edge_or_vert: bool):
    """jnp mirror of migrate._unfreeze_bits (no MG_OLDPARBDY — see the
    rationale in migrate._unfreeze_bits)."""
    from ..core.constants import (PARBDY_TAGS, MG_REQ, MG_NOSURF, MG_BDY,
                                  MG_PARBDY, MG_PARBDYBDY)
    was = (tags & MG_PARBDY) != 0
    user_req = was & ((tags & MG_NOSURF) == 0) & ((tags & MG_REQ) != 0)
    true_bdy = was & ((tags & MG_PARBDYBDY) != 0)
    out = jnp.where(was,
                    tags & ~jnp.uint32(PARBDY_TAGS | MG_PARBDYBDY), tags)
    if is_edge_or_vert:
        out = jnp.where(true_bdy, out | MG_BDY, out)
    out = jnp.where(user_req, out | MG_REQ, out)
    return out


@governed("migrate_dev.retag_device", budget=2)
@partial(jax.jit, donate_argnums=(0,))
def retag_device(stacked: Mesh, glo_d, ifc_slots, ifc_vrows):
    """Reconcile freeze tags with the NEW interface, on device.

    ``ifc_slots`` [S, KF2] int32 4*tet+face slots of the new interface
    (pad capT*4); ``ifc_vrows`` [S, KN] shared-vertex rows (pad capP).
    Faces/vertices: membership by slot/row.  Edges: every local slot of
    a geometric edge of any interface face must (un)freeze — membership
    resolved with a per-shard 2-column sort-join on global edge keys
    (the _retag_interfaces in_new computation, device-resident).
    """
    from ..core.constants import (IARE, FACE_EDGES, MG_PARBDY)
    S, capT = stacked.tet.shape[:2]
    capP = stacked.vert.shape[1]
    sidx = jnp.arange(S)[:, None]
    KF2 = ifc_slots.shape[1]
    iare = jnp.asarray(IARE)
    fedges = jnp.asarray(FACE_EDGES)                       # [4,3]

    # ---- faces ----
    slot_ifc = jnp.zeros((S, capT * 4), bool).at[
        sidx, jnp.where(ifc_slots < capT * 4, ifc_slots, capT * 4)].set(
        True, mode="drop", unique_indices=True).reshape(S, capT, 4)
    tm = stacked.tmask
    cur_f = ((stacked.ftag & MG_PARBDY) != 0) & tm[..., None]
    ftag = jnp.where(slot_ifc & ~cur_f,
                     _freeze_bits_j(stacked.ftag, False), stacked.ftag)
    ftag = jnp.where(cur_f & ~slot_ifc,
                     _unfreeze_bits_j(ftag, False), ftag)

    # ---- edges ----
    def one_shard(tet, tmask, glo_s, slot_ifc_s, etag_s):
        gtet = glo_s[jnp.clip(tet, 0, capP - 1)]           # [capT,4]
        ev = jnp.sort(gtet[:, iare], axis=2)               # [capT,6,2]
        ka = ev[..., 0].reshape(-1)
        kb = ev[..., 1].reshape(-1)
        n6 = capT * 6
        valid = jnp.repeat(tmask, 6)
        # interface-edge markers: the 3 edges of every interface face
        mark = jnp.zeros((capT, 6), bool)
        for f in range(4):
            for j in range(3):
                # lint: ok(R2) — FACE_EDGES is a static host table;
                # constant fold at trace time, no device sync
                e = int(FACE_EDGES[f, j])
                mark = mark.at[:, e].set(
                    mark[:, e] | slot_ifc_s[:, f])
        mark = mark.reshape(-1) & valid
        # 2-col sort join: does my (ka,kb) match ANY marked slot?
        ordj = jnp.lexsort((jnp.where(valid, kb, _I32MAX),
                            jnp.where(valid, ka, _I32MAX)))
        ka_s = jnp.where(valid, ka, _I32MAX)[ordj]
        kb_s = jnp.where(valid, kb, _I32MAX)[ordj]
        first = jnp.concatenate(
            [jnp.array([True]),
             (ka_s[1:] != ka_s[:-1]) | (kb_s[1:] != kb_s[:-1])])
        seg = jax.lax.associative_scan(
            jnp.maximum, jnp.where(first, jnp.arange(n6), 0))
        mk_s = mark[ordj].astype(jnp.int32)
        # segment OR: total at every member via max-scan + head gather
        def seg_or(pa, pb):
            fa, va = pa
            fb, vb = pb
            return fa | fb, jnp.where(fb, vb, va | vb)
        _, or_run = jax.lax.associative_scan(seg_or, (first, mk_s))
        is_last = jnp.concatenate([first[1:], jnp.array([True])])
        tot = jnp.zeros(n6, jnp.int32).at[
            jnp.where(is_last, seg, n6)].set(
            or_run, mode="drop", unique_indices=True)
        in_new_s = tot[seg] > 0
        in_new = jnp.zeros(n6, bool).at[ordj].set(
            in_new_s, unique_indices=True).reshape(capT, 6)
        in_new = in_new & tmask[:, None]
        cur = ((etag_s & MG_PARBDY) != 0) & tmask[:, None]
        out = jnp.where(in_new & ~cur,
                        _freeze_bits_j(etag_s, True), etag_s)
        out = jnp.where(cur & ~in_new, _unfreeze_bits_j(out, True), out)
        return out

    etag = jax.vmap(one_shard)(stacked.tet, stacked.tmask, glo_d,
                               slot_ifc, stacked.etag)

    # ---- vertices ----
    new_v = jnp.zeros((S, capP), bool).at[
        sidx, jnp.where(ifc_vrows < capP, ifc_vrows, capP)].set(
        True, mode="drop", unique_indices=True)
    cur_v = ((stacked.vtag & MG_PARBDY) != 0) & stacked.vmask
    vtag = jnp.where(new_v & ~cur_v,
                     _freeze_bits_j(stacked.vtag, True), stacked.vtag)
    vtag = jnp.where(cur_v & ~new_v, _unfreeze_bits_j(vtag, True), vtag)

    return dataclasses.replace(stacked, ftag=ftag, etag=etag, vtag=vtag)


# ---------------------------------------------------------------------------
# band-scoped weld region probe
# ---------------------------------------------------------------------------
@governed("migrate_dev.band_region_probe", budget=4)
@partial(jax.jit, static_argnames=("KW", "KWp"))
def band_region_probe(stacked: Mesh, glo_d, seed_tets, KW: int, KWp: int):
    """Tets/vertices within one ring of the seed tet rows, compacted.

    ``seed_tets`` [S, KB] local tet slots (pad >= capT) — the migration
    arrival tets (their vertices span the whole band, including the old
    now-interior interface where the duplicate pairs live).  Returns
    (trow [S,KW], vrow [S,KWp], tcnt, vcnt, v_open [S,KWp] bool —
    vertex has an incident tet OUTSIDE the region (must not be welded
    away), ok)."""
    S, capT = stacked.tet.shape[:2]
    capP = stacked.vert.shape[1]
    sidx = jnp.arange(S)[:, None]
    seedc = jnp.clip(seed_tets, 0, capT - 1)
    seed_ok = (seed_tets < capT)[..., None]                # [S,KB,1]
    seed_vids = jnp.where(seed_ok, stacked.tet[sidx, seedc], capP)
    vmark = jnp.zeros((S, capP + 1), bool).at[
        sidx[..., None], seed_vids.reshape(S, -1)].max(
        True, mode="drop")[:, :capP]
    tc = jnp.clip(stacked.tet, 0, capP - 1)

    def ring(vm):
        touch = jnp.any(vm[sidx[..., None], tc], axis=2) & stacked.tmask
        vm2 = jnp.zeros((S, capP + 1), bool).at[
            sidx[..., None],
            jnp.where(touch[..., None], stacked.tet, capP)].max(
            True, mode="drop")[:, :capP]
        return touch, vm | vm2

    _, vm1 = ring(vmark)
    touch2, vm2 = ring(vm1)
    tcnt = jnp.sum(touch2, axis=1)
    vcnt = jnp.sum(vm2 & stacked.vmask, axis=1)
    ok = jnp.all(tcnt <= KW) & jnp.all(vcnt <= KWp)
    trow = jax.vmap(lambda m: jnp.nonzero(m, size=KW,
                                          fill_value=capT)[0])(touch2)
    vrow = jax.vmap(lambda m: jnp.nonzero(m, size=KWp,
                                          fill_value=capP)[0])(
        vm2 & stacked.vmask)
    # vertices with an incident tet outside the region stay frozen for
    # the weld (rewriting them would dangle the outside tets)
    outside = stacked.tmask & ~touch2
    vopen = jnp.zeros((S, capP + 1), bool).at[
        sidx[..., None],
        jnp.where(outside[..., None], stacked.tet, capP)].max(
        True, mode="drop")[:, :capP]
    v_open = vopen[sidx, jnp.clip(vrow, 0, capP - 1)]
    return trow, vrow, tcnt, vcnt, v_open, ok


@governed("migrate_dev.extend_ids_device", budget=2)
@partial(jax.jit, static_argnames=("KN",))
def extend_ids_device(glo_d, vmask, top, KN: int):
    """Assign fresh global ids to adapt-created vertices on device.

    Fresh = live rows with glo<0; ids are a disjoint block per shard
    starting at ``top`` (same assignment the host extend_global_ids
    makes: ascending row order within a shard, shards in order).
    Returns (glo', new_top, fresh_rows [S,KN], fresh_gids [S,KN], ok)."""
    S, capP = glo_d.shape
    fresh = vmask & (glo_d < 0)
    nf = jnp.sum(fresh, axis=1)
    ok = jnp.all(nf <= KN)
    base = top + jnp.concatenate(
        [jnp.zeros(1, nf.dtype), jnp.cumsum(nf)[:-1]])
    rows = jax.vmap(lambda m: jnp.nonzero(m, size=KN,
                                          fill_value=capP)[0])(fresh)
    sidx = jnp.arange(S)[:, None]
    offs = jnp.broadcast_to(jnp.arange(KN), (S, KN))
    gids = (base[:, None] + offs).astype(jnp.int32)
    valid = rows < capP
    glo2 = glo_d.at[sidx, jnp.where(valid, rows, capP)].set(
        jnp.where(valid, gids, 0), mode="drop")
    # dead rows lose their id (mirrors extend_global_ids)
    glo2 = jnp.where(vmask, glo2, -1)
    return (glo2, top + jnp.sum(nf),
            jnp.where(valid, rows, -1).astype(jnp.int32),
            jnp.where(valid, gids, -1), ok)


@governed("migrate_dev.dead_rows", budget=4)
@partial(jax.jit, static_argnames=("KD",))
def dead_glo_rows(glo_d, vmask, KD: int):
    """Compacted newly-dead vertex rows: live-id rows of the numbering
    whose liveness mask has dropped (adapt-cycle collapses since the
    last mirror sync).  The band-sized DELTA replacing the hot-loop
    O(mesh) vmask allgather of the pre-pod multi-host path — the host
    mirror kills exactly these rows (migrate.kill_glo_rows).

    Returns (rows [S, KD] int32 (pad capP), cnt [S], ok); ok False =
    budget overflow, caller takes the metered pull_host escape hatch."""
    S, capP = glo_d.shape
    dead = (glo_d >= 0) & ~vmask
    cnt = jnp.sum(dead, axis=1, dtype=jnp.int32)
    rows = jax.vmap(lambda m: jnp.nonzero(m, size=KD,
                                          fill_value=capP)[0])(
        dead).astype(jnp.int32)
    return rows, cnt, jnp.all(cnt <= KD)


def session_ids_fit(top: int, n_shards: int, KN: int) -> bool:
    """Whether this iteration's fresh-id block provably fits the int32
    device numbering (the module-docstring contract): extend_ids_device
    hands out at most ``n_shards * KN`` ids starting at ``top``, and the
    monotone session counter must never wrap int32 — on a miss the
    caller takes the host ``extend_global_ids_from_vmask`` path, whose
    mirror carries int64 (ADVICE r3: guard, don't assume)."""
    return int(top) + int(n_shards) * int(KN) < 2 ** 31


def has_multiway_face_run(eq: np.ndarray) -> bool:
    """True when the sorted exposed-face keys contain a run of length
    > 2 — a global-id triple exposed by 3+ shards (non-manifold parallel
    face).  ``eq`` is the consecutive-equality mask of the lexsorted
    keys; two adjacent True entries mean three equal keys.  The
    consecutive-pair linking in band_migrate_iteration would double-link
    the middle slot, so the caller must fall back to the full-view path
    for that iteration (ADVICE r3)."""
    return eq.size > 1 and bool(np.any(eq[1:] & eq[:-1]))


# ---------------------------------------------------------------------------
# host orchestration: one O(band + interface) migration iteration
# ---------------------------------------------------------------------------
def band_migrate_iteration(stacked: Mesh, met_s, glo_d,
                           glo: list[np.ndarray],
                           labels_d, depth_d, shared_prev: np.ndarray,
                           n_shards: int, verbose: int = 0):
    """Run device_migrate + interface rebuild with band-sized host work.

    ``glo_d``: [S, capP] int32 device numbering (kept in lockstep with
    the host ``glo`` mirror); ``shared_prev``: gids shared across shards
    before this migration (candidates for the incremental shared-vertex
    update: a gid can only BECOME shared through a band arrival).

    Returns (stacked, met_s, glo_d, comms, shared_now, nmoved) or None
    when any device budget overflowed — the caller falls back to the
    full-view path (parallel/migrate.py), the correctness oracle.
    """
    from .comms import pad_comm_tables
    S = n_shards
    capT = stacked.tet.shape[1]
    capP = stacked.vert.shape[1]
    # a 2-layer advancing front can move a large fraction of a donor and
    # concentrate on one recipient: the band budget scales with capacity
    # (so a grow-retry genuinely raises it), not with a fixed floor
    KB = max(256, capT // 2)
    KV = max(256, capP // 2)
    KF = max(512, capT // 2)

    stacked2, met2, glo_d2, info = device_migrate(
        stacked, met_s, glo_d, labels_d, depth_d, KB=KB, KV=KV)
    ok = bool(info["ok"])
    nmoved = int(info["nmoved"])
    if not ok:
        if verbose >= 1:
            names = ("nmove<=KB", "arrivals<=KB", "new_v<=KV",
                     "new_v<=free_v", "arrivals<=free_t", "dead<=KV")
            # lint: ok(R7) — fallback diagnostic off the steady path
            # (the iteration is being abandoned to the full-view
            # oracle); tiny [6] bool vector
            parts = _pull(info["ok_parts"])
            bad = [n for n, p in zip(names, parts) if not p]
            otrace.log(1, f"  band migrate overflow: {bad}",
                       verbose=verbose)
        return None         # fallback: caller re-runs the full path
    if nmoved == 0:
        return stacked2, met2, glo_d2, None, shared_prev, 0, None

    # ---- exposed-face probe (budget-checked BEFORE any mirror mutation:
    # the okf fallback must hand the caller an untouched numbering) -----
    keys, slots, cnt, okf = exposed_face_probe(stacked2, glo_d2, KF=KF)
    if not bool(okf):
        return None

    # ---- cross-shard face match (band exchange, pod.gather_band) --------
    from .pod import gather_band
    keys, slots, cnt = gather_band(keys, slots, cnt, what="faces")
    ks, sl, sh = [], [], []
    for s in range(S):
        n = int(cnt[s])
        ks.append(keys[s][:n])
        sl.append(slots[s][:n])
        sh.append(np.full(n, s, np.int32))
    K = np.concatenate(ks) if ks else np.zeros((0, 3), np.int32)
    SL = np.concatenate(sl)
    SH = np.concatenate(sh)
    order = np.lexsort((K[:, 2], K[:, 1], K[:, 0]))
    Ks, SLs, SHs = K[order], SL[order], SH[order]
    eq = (Ks[1:] == Ks[:-1]).all(1)
    if has_multiway_face_run(eq):
        # a global-id triple exposed by 3+ shards (non-manifold parallel
        # face): the consecutive-pair linking below would double-link the
        # middle slot — fall back to the full-view path this iteration
        # (the host oracle shares the 2-shard assumption but rebuilds
        # interfaces from whole views, which stays consistent)
        return None

    # ---- host glo mirror sync (arrivals + newly-dead delta) -------------
    # (after the pairing guard: a None return above must leave the host
    # glo mirror untouched for the full-view fallback.)  One band
    # exchange replaces the old O(mesh) vmask allgather: arrivals write
    # their device-assigned rows, the compacted dead delta drops its
    # ids — the mirror invariant (glo >= 0 iff live id-carrying row)
    # makes the delta exact (migrate.kill_glo_rows)
    from .migrate import apply_fresh_ids, kill_glo_rows
    arr_rows, arr_gids, dead_rows, dead_cnt, arr_slots = gather_band(
        info["arr_rows"], info["arr_gids"], info["dead_rows"],
        info["dead_cnt"], info["arr_slots"], what="migrate_glo")
    apply_fresh_ids(glo, arr_rows, arr_gids)
    kill_glo_rows(glo, dead_rows, dead_cnt)

    pair = np.concatenate([eq, [False]])
    iA = np.where(pair)[0]
    iB = iA + 1
    face_lists = [[[] for _ in range(S)] for _ in range(S)]
    ifc_face_slots = [[] for _ in range(S)]
    a_arr, b_arr = SHs[iA], SHs[iB]
    sa_arr, sb_arr = SLs[iA], SLs[iB]
    for a, b, sa, sb in zip(a_arr, b_arr, sa_arr, sb_arr):
        a, b = int(a), int(b)
        face_lists[a][b].append(int(sa))
        face_lists[b][a].append(int(sb))
        ifc_face_slots[a].append(int(sa))
        ifc_face_slots[b].append(int(sb))

    # ---- incremental shared-vertex update -------------------------------
    # candidates: previously shared ∪ band-arrival gids ∪ interface-face
    # endpoint gids (the only routes by which a gid can become shared)
    endp = Ks[iA].reshape(-1).astype(np.int64)
    cands = np.unique(np.concatenate(
        [shared_prev.astype(np.int64),
         arr_gids[arr_gids >= 0].astype(np.int64), endp]))
    rows_per = []
    live_per = []
    for s in range(S):
        o = np.argsort(glo[s], kind="stable")
        gs = glo[s][o]
        lo = np.searchsorted(gs, cands)
        loc = np.clip(lo, 0, len(gs) - 1)
        hit = (gs[loc] == cands) & (cands >= 0)
        row = np.where(hit, o[loc], -1)
        # liveness IS the id hit: the mirror invariant (synced above)
        # guarantees glo >= 0 only at live rows — no mask consult
        live = hit & (row >= 0)
        rows_per.append(np.where(live, row, -1))
        live_per.append(live)
    nliv = np.sum(live_per, axis=0)
    shared = nliv >= 2
    shared_now = cands[shared]
    owner_of = np.full(len(cands), -1, np.int32)
    for s in range(S):
        owner_of[live_per[s]] = s          # ascending: max rank wins
    node_lists = [[[] for _ in range(S)] for _ in range(S)]
    ifc_vert_rows = [[] for _ in range(S)]
    owner = [np.full(capP, s, np.int32) for s in range(S)]
    sh_idx = np.where(shared)[0]           # ascending gid order (A.4)
    for s in range(S):
        rows_s = rows_per[s][sh_idx]
        here = rows_s >= 0
        ifc_vert_rows[s] = [int(r) for r in rows_s[here]]
        owner[s][rows_s[here]] = owner_of[sh_idx][here]
    for ci in sh_idx:
        holders = [s for s in range(S) if live_per[s][ci]]
        for i in range(len(holders)):
            for j in range(i + 1, len(holders)):
                a, b = holders[i], holders[j]
                node_lists[a][b].append(int(rows_per[a][ci]))
                node_lists[b][a].append(int(rows_per[b][ci]))

    comms = pad_comm_tables(node_lists, face_lists, owner, S)

    # ---- retag on device ------------------------------------------------
    # bucket the static shapes (compile governor) so the jitted retag
    # program is reused across iterations instead of recompiling for
    # every distinct interface size
    KF2 = bucket(max(len(x) for x in ifc_face_slots), floor=256)
    KN = bucket(max(len(x) for x in ifc_vert_rows), floor=256)
    slots_d = np.full((S, KF2), capT * 4, np.int32)
    vrows_d = np.full((S, KN), capP, np.int32)
    for s in range(S):
        slots_d[s, :len(ifc_face_slots[s])] = ifc_face_slots[s]
        vrows_d[s, :len(ifc_vert_rows[s])] = ifc_vert_rows[s]
    stacked2 = retag_device(stacked2, glo_d2, jnp.asarray(slots_d),
                            jnp.asarray(vrows_d))
    otrace.log(2, f"  band migration: moved {nmoved} tets, "
                  f"{len(iA)} interface faces, "
                  f"{int(shared.sum())} shared vertices "
                  "(device path)", verbose=verbose)
    return (stacked2, met2, glo_d2, comms, shared_now, nmoved,
            arr_slots)


def band_weld(stacked: Mesh, met_s, glo_d, glo: list[np.ndarray],
              arr_slots: np.ndarray, n_shards: int, verbose: int = 0):
    """Region-scoped near-duplicate weld after a band migration.

    Pulls only the 1-ring neighborhood of the arrival vertices per
    recipient shard and runs the sequential weld there (the
    distribute._weld_close_pairs semantics); vertices with incident
    tets outside the region are poisoned so the weld cannot dangle
    outside references.  Returns (stacked, nweld)."""
    from .distribute import _weld_close_pairs
    S = n_shards
    capT = stacked.tet.shape[1]
    capP = stacked.vert.shape[1]
    KW = max(512, capT // 2)
    KWp = max(512, capP // 2)
    seed = jnp.asarray(arr_slots)
    while True:
        trow, vrow, tcnt, vcnt, v_open, ok = band_region_probe(
            stacked, glo_d, seed, KW=KW, KWp=KWp)
        if bool(ok):
            break
        if KW >= capT and KWp >= capP:
            # cannot happen (the region is at most the live mesh, and
            # the full-width probe holds it) — kept as the caller's
            # documented full-weld fallback signal
            return stacked, glo_d, -1
        # the probe budget is a COMPACTION table, not a capacity: a big
        # arrival neighborhood just needs a wider table.  Double toward
        # the full width (one extra governed variant at most) instead
        # of abandoning the band path — the full-view weld fallback is
        # single-controller and would kill a multi-process run.
        KW = min(capT, KW * 2)
        KWp = min(capP, KWp * 2)
    from .pod import gather_band
    trow, vrow, tcnt, vcnt, v_open = gather_band(
        trow, vrow, tcnt, vcnt, v_open, what="weld_probe")
    # one consolidated region gather (device compaction) + ONE band
    # exchange of the resulting tables
    sidx = jnp.arange(S)[:, None]
    tr_c = jnp.clip(jnp.asarray(trow), 0, capT - 1)
    vr_c = jnp.clip(jnp.asarray(vrow), 0, capP - 1)
    tet_r, tref_r, ftag_r, etag_r, vert_r, vtag_r, met_r = gather_band(
        stacked.tet[sidx, tr_c], stacked.tref[sidx, tr_c],
        stacked.ftag[sidx, tr_c], stacked.etag[sidx, tr_c],
        stacked.vert[sidx, vr_c], stacked.vtag[sidx, vr_c],
        met_s[sidx, vr_c], what="weld_region")
    tet_d = stacked.tet
    tmask_d = stacked.tmask
    vmask_d = stacked.vmask
    glo_d_out = glo_d
    ntot = 0
    for s in range(S):
        nt, nv = int(tcnt[s]), int(vcnt[s])
        if nt == 0 or nv == 0:
            continue
        vr_s = vrow[s][:nv]
        l2r = np.full(capP, -1, np.int64)
        l2r[vr_s] = np.arange(nv)
        tloc = l2r[tet_r[s][:nt]]
        if (tloc < 0).any():        # ring closure failed — skip shard
            continue
        vtag_s = vtag_r[s][:nv].copy()
        vtag_s[v_open[s][:nv]] |= np.uint32(0x80000000)   # poison
        tet2, vkeep, tkeep = _weld_close_pairs(
            vert_r[s][:nv], tloc.astype(np.int32), vtag_s,
            met_r[s][:nv], tref_r[s][:nt], ftag_r[s][:nt],
            etag_r[s][:nt])
        if vkeep.all() and tkeep.all() and np.array_equal(tet2, tloc):
            continue
        ntot += int((~vkeep).sum())
        chg = np.where(np.any(tet2 != tloc, axis=1) | ~tkeep)[0]
        rows_g = trow[s][chg]
        tet_g = vr_s[np.clip(tet2[chg], 0, nv - 1)].astype(np.int32)
        tet_d = tet_d.at[s, jnp.asarray(rows_g)].set(jnp.asarray(tet_g))
        dead_rows = trow[s][np.where(~tkeep)[0]]
        if len(dead_rows):
            tmask_d = tmask_d.at[s, jnp.asarray(dead_rows)].set(False)
        dead_v = vr_s[np.where(~vkeep)[0]]
        if len(dead_v):
            vmask_d = vmask_d.at[s, jnp.asarray(dead_v)].set(False)
            glo[s][dead_v] = -1
            # the DEVICE numbering must drop the welded gids too: the
            # next adapt cycles run before extend_ids_device and can
            # reuse these slots — a stale gid there would resurrect
            # under the old identity and corrupt shared-vertex matching
            glo_d_out = glo_d_out.at[s, jnp.asarray(dead_v)].set(-1)
    if ntot == 0:
        return stacked, glo_d_out, 0
    otrace.log(2, f"  band weld: {ntot} near-duplicate pairs "
                  "contracted", verbose=verbose)
    out = dataclasses.replace(stacked, tet=tet_d, tmask=tmask_d,
                              vmask=vmask_d)
    return out, glo_d_out, ntot


# ---------------------------------------------------------------------------
# flood-label contiguity / reachability repair
# ---------------------------------------------------------------------------
# The advancing-front flood (migrate.flood_labels) propagates colors via
# vertex priorities, so each color region is vertex-connected to its
# seeds BY CONSTRUCTION — but priority ties between competing colors can
# cut a region off its front (an unreachable moving blob), and two
# fronts meeting can enclose an unflooded pocket of retained tets.  The
# reference repairs exactly these on the displaced partition:
# sub-blob merge (/root/reference/src/moveinterfaces_pmmg.c:475-626) and
# destination reachability (:627-720).  Here both checks run on a
# band-sized compacted probe (moving tets + their retained 1-ring), so
# the host never touches O(mesh) state.

def _flood_probe_one(tet, tmask, adja, label, depth, me, KB: int,
                     capP: int):
    capT = tet.shape[0]
    moving = tmask & (label != me)
    nbrc = jnp.clip(adja >> 2, 0, capT - 1)
    has = (adja >= 0) & tmask[:, None]
    nbr_mov = jnp.where(has, moving[nbrc], False)          # [T,4]
    ring = tmask & ~moving & jnp.any(nbr_mov, axis=1)
    band = moving | ring
    cnt = jnp.sum(band, dtype=jnp.int32)
    rows = jnp.nonzero(band, size=KB, fill_value=capT)[0].astype(jnp.int32)
    rv = rows < capT
    rc = jnp.clip(rows, 0, capT - 1)
    # vertices held by a retained tet OUTSIDE the band: a ring component
    # with no such vertex is an enclosed island
    out_ret = tmask & ~band
    vout = jnp.zeros(capP + 1, bool).at[
        jnp.where(out_ret[:, None], tet, capP).reshape(-1)].set(
        True, mode="drop")[:capP]
    row_tet = jnp.where(rv[:, None], tet[rc], 0)
    out_touch = jnp.any(vout[jnp.clip(row_tet, 0, capP - 1)],
                        axis=1) & rv
    return (cnt, rows,
            jnp.where(rv, label[rc], -1),
            jnp.where(rv, depth[rc], 0),
            jnp.where(rv[:, None], row_tet, -1),
            out_touch)


@governed("migrate_dev.flood_band_counts", budget=4)
@partial(jax.jit, static_argnames=("n_shards",))
def flood_band_counts(stacked: Mesh, labels, n_shards: int):
    """[S] int32: band size (moving + retained 1-ring) per shard.
    Ledger-registered: runs every rebalance iteration (G=1 AND the
    grouped layout share the logical-leading-axis program family)."""
    me = jnp.arange(n_shards, dtype=jnp.int32)

    def one(tet, tm, adja, lab, m):
        capT = tet.shape[0]
        moving = tm & (lab != m)
        nbrc = jnp.clip(adja >> 2, 0, capT - 1)
        has = (adja >= 0) & tm[:, None]
        ring = tm & ~moving & jnp.any(
            jnp.where(has, moving[nbrc], False), axis=1)
        return jnp.sum(moving | ring, dtype=jnp.int32)

    return jax.vmap(one)(stacked.tet, stacked.tmask, stacked.adja,
                         labels, me)


@governed("migrate_dev.flood_probe", budget=4)
@partial(jax.jit, static_argnames=("n_shards", "KB"))
def flood_probe(stacked: Mesh, labels, depth, n_shards: int, KB: int):
    me = jnp.arange(n_shards, dtype=jnp.int32)
    capP = stacked.vert.shape[-2]
    return jax.vmap(
        lambda t, tm, a, l, d, m: _flood_probe_one(
            t, tm, a, l, d, m, KB, capP)
    )(stacked.tet, stacked.tmask, stacked.adja, labels, depth, me)


@governed("migrate_dev.apply_label_fixes", budget=4)
@jax.jit
def _apply_label_fixes(labels, rows, newlab):
    def one(lab, r, nl):
        capT = lab.shape[0]
        tgt = jnp.where((r >= 0) & (r < capT) & (nl >= 0), r, capT)
        return lab.at[tgt].set(jnp.where(nl >= 0, nl, 0), mode="drop")
    return jax.vmap(one)(labels, rows, newlab)


def _vertex_components(rtet: np.ndarray, sel: np.ndarray) -> np.ndarray:
    """Connected components (by shared vertex) among the selected rows.

    Returns [n] int component id (-1 on unselected rows).  Vectorized
    min-label propagation over the (row, vertex) incidence — O(band *
    diameter) numpy passes, no per-row Python (the band can reach tens
    of thousands of rows on a big displaced partition)."""
    n = rtet.shape[0]
    rows = np.repeat(np.arange(n), rtet.shape[1])
    verts = rtet.reshape(-1)
    keep = (verts >= 0) & sel[rows]
    rows, verts = rows[keep], verts[keep]
    if not len(rows):
        return np.full(n, -1, np.int64)
    uv, vid = np.unique(verts, return_inverse=True)
    comp = np.where(sel, np.arange(n), n).astype(np.int64)
    for _ in range(64):                    # >> any real blob diameter
        vmin = np.full(len(uv), n, np.int64)
        np.minimum.at(vmin, vid, comp[rows])
        new_c = comp.copy()
        np.minimum.at(new_c, rows, vmin[vid])
        if (new_c == comp).all():
            break
        comp = new_c
    comp[~sel] = -1
    return comp


def repair_flood_labels(stacked: Mesh, labels_d, depth_d, n_shards: int,
                        verbose: int = 0):
    """Contiguity + reachability repair on the flood-displaced labels.

    - an unreachable moving blob (a same-color vertex-connected
      component with no depth-1 member, i.e. cut off its seed front by
      color competition) reverts to its owner;
    - an enclosed retained pocket (a ring component touching no retained
      tet outside the band) joins the surrounding moving color (majority
      among vertex-adjacent moving rows).

    Returns (labels_d, nfixed).  Reference semantics:
    moveinterfaces_pmmg.c:475-626 (fix_contiguity merge into a neighbor
    color) and :627-720 (check_reachability revert)."""
    from .pod import gather_band
    cnts = gather_band(flood_band_counts(stacked, labels_d, n_shards),
                       what="flood_counts")
    if int(cnts.max()) == 0:
        return labels_d, 0
    capT = stacked.tet.shape[1]
    KB = bucket(int(cnts.max()), floor=1024, cap=capT)
    # band exchange, not a per-leaf allgather: the probe outputs are
    # 'shard'-sharded compacted tables and every process computes the
    # identical host repair from the replicated copies
    cnt, rows, lab, dep, rtet, out_touch = gather_band(
        *flood_probe(stacked, labels_d, depth_d, n_shards, KB),
        what="flood_probe")
    new_lab = np.full((n_shards, KB), -1, np.int32)
    nfixed = 0
    for s in range(n_shards):
        n = int(cnt[s])
        if n == 0:
            continue
        lab_s = np.array(lab[s][:n])
        dep_s = dep[s][:n]
        rtet_s = rtet[s][:n]
        touch_s = out_touch[s][:n]
        fixed_s = np.zeros(n, bool)
        # --- moving blobs: same-color components need a depth-1 seed ---
        for c in np.unique(lab_s):
            c = int(c)
            if c == s or c < 0:
                continue
            selc = lab_s == c
            comp = _vertex_components(rtet_s, selc)
            for cid in np.unique(comp[selc]):
                mem = comp == cid
                if not (dep_s[mem] == 1).any():
                    lab_s[mem] = s              # revert: unreachable
                    fixed_s |= mem
        # --- retained pockets: ring components with no outside anchor --
        selr = lab_s == s
        comp = _vertex_components(rtet_s, selr)
        # vectorized vertex -> (component, moving-label) incidence for
        # the anchored test + majority relabel (no per-row Python)
        rows_i = np.repeat(np.arange(n), rtet_s.shape[1])
        verts_i = rtet_s.reshape(-1)
        vok = verts_i >= 0
        rows_i, verts_i = rows_i[vok], verts_i[vok]
        mov_i = (lab_s[rows_i] != s) & (lab_s[rows_i] >= 0)
        for cid in np.unique(comp[selr]):
            mem = comp == cid
            if touch_s[mem].any():
                continue                        # anchored to the interior
            vset = np.unique(verts_i[mem[rows_i]])
            nbr = mov_i & np.isin(verts_i, vset)
            if not nbr.any():
                continue
            vals, freq = np.unique(lab_s[rows_i[nbr]],
                                   return_counts=True)
            lab_s[mem] = int(vals[np.argmax(freq)])
            fixed_s |= mem
        if fixed_s.any():
            new_lab[s, :n][fixed_s] = lab_s[fixed_s]
            nfixed += int(fixed_s.sum())
    if nfixed == 0:
        return labels_d, 0
    otrace.log(2, f"  flood repair: relabeled {nfixed} band tets "
                  "(contiguity/reachability)", verbose=verbose)
    labels_d = _apply_label_fixes(labels_d, jnp.asarray(rows),
                                  jnp.asarray(new_lab))
    return labels_d, nfixed


# ---------------------------------------------------------------------------
# graph-balancing labels from device-compacted tables (zero full pulls)
# ---------------------------------------------------------------------------
# The reference's graph mode gathers ONLY the group graph to rank 0 and
# runs METIS on it (/root/reference/src/metis_pmmg.c:845-1550).  Round 3
# matched the algorithm (morton clusters as redistribution groups +
# weighted KL/FM on the cluster graph) but still pulled full shard views
# to build it.  Here the cluster assignment, cluster weights, the
# intra-shard cluster adjacency (via the maintained adja — no face
# sort), and the interface-slot cluster ids are computed ON DEVICE and
# only O(S*G^2 + interface) tables reach the host.

@governed("migrate_dev.graph_probe", budget=4)
@partial(jax.jit, static_argnames=("n_shards", "G"))
def graph_probe(stacked: Mesh, face_idx, n_shards: int, G: int):
    """Per shard: morton cluster id per live tet [S, capT], live count
    [S], cluster weights [S, G], intra-shard cluster-pair face counts
    [S, G*G], and the cluster id at each comm face slot [S, K, I]."""
    capP = stacked.vert.shape[1]

    def one(tet, tm, adja, vert, fidx):
        from ..ops.edges import morton_codes
        capT = tet.shape[0]
        cent = jnp.mean(vert[jnp.clip(tet, 0, capP - 1)], axis=1)
        code = morton_codes(cent, tm, bits=10)
        key = jnp.where(tm, code, _I32MAX)
        order = jnp.argsort(key)
        rank = jnp.zeros(capT, jnp.int32).at[order].set(
            jnp.arange(capT, dtype=jnp.int32))
        nlive = jnp.sum(tm, dtype=jnp.int32)
        # equal-count chunks along the curve = the redistribution groups
        clus = jnp.clip((rank * G) // jnp.maximum(nlive, 1), 0, G - 1)
        clus = jnp.where(tm, clus, 0).astype(jnp.int32)
        cw = jnp.zeros(G, jnp.int32).at[
            jnp.where(tm, clus, G)].add(1, mode="drop")
        # intra-shard cluster adjacency from adja (cross-shard faces are
        # adja=-1 at the frozen interface and counted via the comms)
        nbrt = jnp.clip(adja >> 2, 0, capT - 1)
        tid = jnp.arange(capT, dtype=jnp.int32)[:, None]
        own = (adja >= 0) & tm[:, None] & (tid < (adja >> 2)) & \
            tm[nbrt]
        ci = jnp.broadcast_to(clus[:, None], (capT, 4))
        cj = clus[nbrt]
        cross = own & (ci != cj)
        pk = jnp.where(cross, jnp.minimum(ci, cj) * G +
                       jnp.maximum(ci, cj), G * G)
        pcnt = jnp.zeros(G * G, jnp.int32).at[pk.reshape(-1)].add(
            1, mode="drop")
        # cluster at each interface face slot (order matches both sides)
        ft = jnp.clip(fidx // 4, 0, capT - 1)
        cif = jnp.where(fidx >= 0, clus[ft], -1)
        return clus, nlive, cw, pcnt, cif

    return jax.vmap(one)(stacked.tet, stacked.tmask, stacked.adja,
                         stacked.vert, face_idx)


@partial(jax.jit, static_argnames=("n_shards",))
def _labels_from_parts(clus, tmask, new_part, n_shards: int):
    me = jnp.arange(n_shards, dtype=jnp.int32)
    G = new_part.shape[0] // n_shards

    def one(c, tm, m):
        lab = new_part[m * G + c]
        return jnp.where(tm, lab, m).astype(jnp.int32)

    return jax.vmap(one)(clus, tmask, me)


def graph_repartition_labels_band(stacked: Mesh, comms, n_shards: int,
                                  clusters_per_shard: int = 8,
                                  verbose: int = 0):
    """Device-resident graph-balancing labels: [S, capT] target shard
    per tet (device array), from O(S*G^2 + interface) host tables only.

    Same algorithm as migrate.graph_repartition_labels (morton clusters
    + weighted KL/FM on the cluster graph, the metis_pmmg.c:845-1550
    gather-only-the-graph role) without the full views pull."""
    from .partition import refine_partition
    S, G = n_shards, clusters_per_shard
    # bucket the comm-table pad shape (compile governor): the tables are
    # rebuilt every rebalance iteration and an exact-shape jit would
    # recompile graph_probe each time (the same recompile class the
    # retag KF2/KN bucketing fixes).  Same ladders as pad_comm_tables
    # (geo/64 items, pow2/2 capped neighbors) so tables it built pass
    # through untouched — bucket() is idempotent on its own ladder —
    # and graph_probe shares the other consumers' compiled-shape
    # family; only older callers' exact tables get re-padded here.
    fi = comms.face_idx
    If = bucket(fi.shape[2], floor=64, scheme="geo")
    Kn = bucket(fi.shape[1], floor=2,
                cap=max(fi.shape[1], n_shards - 1))
    if (Kn, If) != fi.shape[1:]:
        fi2 = np.full((fi.shape[0], Kn, If), -1, fi.dtype)
        fi2[:, :fi.shape[1], :fi.shape[2]] = fi
        fi = fi2
    # band exchange (pod.gather_band): every process receives the same
    # O(S*G^2 + interface) tables through one compiled collective.
    # clus/nlive stay DEVICE-resident: the host graph build never reads
    # them (clus feeds _labels_from_parts on device) — the pre-pod path
    # allgathered the O(mesh) cluster map just to re-upload it
    from .pod import gather_band
    clus, nlive, cw, pcnt, cif = graph_probe(stacked, jnp.asarray(fi),
                                             S, G)
    cw, pcnt, cif = gather_band(cw, pcnt, cif, what="graph")
    nclu = S * G
    pi, pj, w = [], [], []
    for s in range(S):
        mat = pcnt[s]
        nz = np.where(mat > 0)[0]
        if len(nz):
            pi.append(s * G + nz // G)
            pj.append(s * G + nz % G)
            w.append(mat[nz].astype(float))
    # interface edges: the comm tables are ordered identically on both
    # sides of a pair, so zipping the two shards' slot-cluster rows
    # gives the cross-shard cluster pairs directly
    nbr = comms.nbr
    fcnt = comms.face_cnt
    for s in range(S):
        for k in range(nbr.shape[1]):
            b = int(nbr[s, k])
            if b <= s:
                continue
            n_items = int(fcnt[s, k])
            if n_items == 0:
                continue
            kb = int(np.where(nbr[b] == s)[0][0])
            ca = cif[s, k, :n_items]
            cb = cif[b, kb, :n_items]
            okm = (ca >= 0) & (cb >= 0)
            key = (s * G + ca[okm]).astype(np.int64) * nclu + \
                (b * G + cb[okm])
            uk, cnts = np.unique(key, return_counts=True)
            pi.append((uk // nclu).astype(np.int64))
            pj.append((uk % nclu).astype(np.int64))
            w.append(cnts.astype(float))
    if not pi:
        return None
    pi = np.concatenate(pi)
    pj = np.concatenate(pj)
    w = np.concatenate(w)
    init = np.repeat(np.arange(S, dtype=np.int32), G)
    new_part = refine_partition(init, S, (pi, pj), w,
                                elem_w=cw.reshape(-1).astype(float),
                                npasses=5)
    nmv = int((new_part != init).sum())
    otrace.log(2, f"  graph band labels: {nmv}/{nclu} clusters "
                  "reassigned", verbose=verbose)
    return _labels_from_parts(clus, stacked.tmask,
                              jnp.asarray(new_part), S)
