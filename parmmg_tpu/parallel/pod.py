"""Pod runtime: collective-native multi-host band exchange + group
handoff.

The multi-process story before this module (round 4 / MULTIHOST2P_r04)
was correct but allgather-shaped: every host stage of the band path
pulled its compacted device tables through
``multihost.pull_host`` — a ``process_allgather`` per LEAF per call,
each building its own jitted gather, on a run that was already
compile-dominated (656 s for a 384-tet toy).  ParMmg's equivalent
stages ride per-neighbor ``MPI_Sendrecv``/``Alltoall`` of packed
band payloads (distributegrps_pmmg.c:1631-1841); the JAX-native
analogue is ONE compiled ``shard_map`` collective per table family.

This module is that layer:

- :func:`gather_band` — the one exchange every hot-path host stage
  routes through.  Single-process it is a plain host view (the
  degenerate collective); multi-process it runs a CACHED
  ``shard_map`` ``all_gather`` program whose static shapes are the
  callers' band budgets — all of which already ride the compile
  governor's geo/pow2 ladders (``comms.packed_halo_rows`` /
  ``pad_comm_tables`` / the ``KB/KV/KF/KW`` probe budgets), so the
  exchange adds a BOUNDED program family instead of one fresh
  ``process_allgather`` jit per leaf per iteration.  Every call is a
  ``multihost.exchange`` faultpoint riding ``retry_call``; exhaustion
  degrades to the metered ``pull_host`` escape hatch (ladder step
  ``mh_allgather``) — bit-identical output, visibly counted.
- :func:`plan_handoff` / :func:`maybe_handoff` — host-to-host group
  migration (the ``distributegrps`` role at process granularity): a
  logical shard (group) is handed to another device — and thereby
  another process — as one compiled leading-axis permutation
  (``distribute.permute_shards``), with the comm tables and host
  numbering mirrors remapped in lockstep.  Off by default
  (``PARMMG_MH_HANDOFF``): a handoff reorders arrival slots in later
  migrations, so the bit-for-bit 1-vs-N-process parity contract is
  pinned with handoff off.
- :func:`activate` / :func:`current` — the pod context (device mesh +
  logical-shard topology) the distributed driver threads through the
  iteration loop so the exchange sites need no signature churn.

Worker crash/stall is the EXPECTED failure mode at pod scale: a
process that dies mid-collective takes the step down with it, the
survivors' gloo ops time out, and the run restarts from the last
per-pass checkpoint (``PARMMG_CKPT_DIR`` — resilience/checkpoint.py,
wired through ``distributed_adapt_multi(..., resume=True)`` and
``scripts/multihost_run.py --resume``).  In-process transients (the
chaos gate's arm) recover through retry/fallback without a restart.
"""
from __future__ import annotations

import contextlib
import os

import numpy as np

from ..obs import trace as otrace
from ..obs.metrics import REGISTRY
from ..utils.compilecache import governed


# ---------------------------------------------------------------------------
# pod context
# ---------------------------------------------------------------------------
class PodContext:
    """Device mesh + logical-shard topology of one distributed run.

    ``n_shards`` logical shards (groups) over ``n_dev`` devices, G
    consecutive leading-axis rows per device; a row's process is
    ``dmesh`` device ``row // G``'s ``process_index``.  The compiled
    exchange programs live in the module-level ``_GATHER_CACHE`` keyed
    by this context's ``dev_key`` + the leaf shapes."""

    def __init__(self, dmesh, n_shards: int):
        import jax
        self.dmesh = dmesh
        self.n_shards = int(n_shards)
        self.n_dev = int(np.asarray(dmesh.devices).size)
        self.G = max(1, self.n_shards // max(self.n_dev, 1))
        self.nproc = jax.process_count()
        self.pid = jax.process_index()
        # lint: ok(R2) — device-id metadata (cache key), no device sync
        self.dev_key = tuple(
            d.id for d in np.asarray(dmesh.devices).flat)

    def multi(self) -> bool:
        return self.nproc > 1


_ACTIVE: list = []


@contextlib.contextmanager
def activate(dmesh, n_shards: int):
    """Install the pod context for one driver invocation (the band
    exchange sites read it via :func:`current` — no signature churn
    through migrate_dev's call tree)."""
    ctx = PodContext(dmesh, n_shards)
    _ACTIVE.append(ctx)
    try:
        yield ctx
    finally:
        _ACTIVE.pop()


def current() -> PodContext | None:
    return _ACTIVE[-1] if _ACTIVE else None


# ---------------------------------------------------------------------------
# the band exchange
# ---------------------------------------------------------------------------
def exchange_key(arrays) -> tuple:
    """Compile key of one exchange family: the (shape, dtype) tuple of
    its leaves.  Stable across iterations because every band table is
    budget-bucketed upstream (``KB/KV/KF/KW`` probe budgets, the
    ``pad_comm_tables`` geo/pow2 ladders) — the same anti-churn ladders
    that bound the halo-exchange families bound the exchange here."""
    return tuple((tuple(np.shape(a)), str(np.asarray(a).dtype)
                  if isinstance(a, np.ndarray) else str(a.dtype))
                 for a in arrays)


# compiled exchange programs keyed by (device ids, leaf shapes/dtypes)
# — module-level so repeated driver invocations on the same mesh reuse
# the jit objects instead of retracing per run (the DistSteps rationale)
_GATHER_CACHE: dict = {}


def _gather_program(ctx: PodContext, arrays):
    import jax
    from jax.sharding import PartitionSpec as P
    from ..utils.jaxcompat import shard_map

    key = (ctx.dev_key,) + exchange_key(arrays)
    fn = _GATHER_CACHE.get(key)
    if fn is None:
        n = len(arrays)

        def body(*xs):
            return tuple(jax.lax.all_gather(x, "shard", axis=0,
                                            tiled=True) for x in xs)

        fn = shard_map(body, mesh=ctx.dmesh,
                       in_specs=(P("shard"),) * n,
                       out_specs=(P(),) * n, check_vma=False)
        fn = governed("mh.band_exchange", budget=24)(jax.jit(fn))
        _GATHER_CACHE[key] = fn
    return fn


def _exchange(arrays) -> tuple:
    """One packed band exchange: replicate the compacted device tables
    to every process through ONE compiled collective (multi-process) or
    a plain host view (the single-controller degenerate form)."""
    import jax
    ctx = current()
    if ctx is None or not ctx.multi():
        # single-controller degenerate exchange: the tables are fully
        # addressable; np.asarray IS the collective's identity form
        # lint: ok(R2) — band/interface-sized compacted tables only;
        # this IS the designed exchange (pod module docstring), the
        # O(mesh) views stay behind require_single_process
        return tuple(np.asarray(x) for x in arrays)
    fn = _gather_program(ctx, arrays)
    out = fn(*arrays)
    host = tuple(np.asarray(x) for x in out)      # replicated outputs
    REGISTRY.counter("mh.band_exchange_bytes").inc(
        float(sum(h.nbytes for h in host)))
    return host


def gather_band(*arrays, what: str = ""):
    """Replicate band-sized device tables to every process's host.

    The ONE exchange surface of the multi-host hot path (module
    docstring).  ``what`` labels the site for fault keying and trace.
    Returns host numpy arrays (a single array for a single input).

    Failure semantics: each attempt is a ``multihost.exchange``
    faultpoint; ``retry_call`` re-attempts under PARMMG_RETRY_*, and
    exhaustion falls back to the metered ``pull_host`` escape hatch
    (ladder step ``mh_allgather``) — bit-identical values, counted
    bytes, never a silent divergence.  Hang semantics: each call beats
    the pod heartbeat (the supervisor's lease cadence), and on the
    SINGLE-process form a ``PARMMG_DEADLINE_EXCHANGE_S`` watchdog
    bounds each attempt (a wedged exchange raises ``WatchdogTimeout``
    into the same retry ladder).  Cross-process the deadline stays
    OFF by design: a watchdog retry would re-enter the collective out
    of step with ranks still parked inside it — there the heartbeat
    lease + kill-the-pack supervisor IS the hang ladder
    (scripts/multihost_run.py)."""
    from ..resilience.faults import faultpoint
    from ..resilience.recover import (RetryBudgetExhausted, ladder_step,
                                      retry_call)
    from ..resilience.watchdog import (beat, deadline_knob,
                                       run_with_deadline)

    ctx0 = current()
    multi = ctx0 is not None and ctx0.multi()
    xdl = 0.0 if multi else deadline_knob("PARMMG_DEADLINE_EXCHANGE_S")

    def attempt():
        beat()
        faultpoint("multihost.exchange", key=what or None)
        return _exchange(arrays)

    try:
        out = retry_call(
            lambda: run_with_deadline(attempt, xdl,
                                      "multihost.exchange"),
            site="multihost.exchange")
    except RetryBudgetExhausted as e:
        ctx = current()
        if ctx is not None and ctx.multi():
            # cross-process a divergent local fallback would DESYNC the
            # SPMD step (the other ranks are parked inside the
            # collective): let the worker die — crash-and-resume from
            # the per-pass checkpoint IS the ladder at pod scale
            # (module docstring; scripts/multihost_run.py drill)
            raise
        ladder_step("mh_allgather", site="multihost.exchange",
                    detail=f"{what}: {e!r}")
        from .multihost import pull_host
        # lint: ok(R7) — this IS the documented mh_allgather ladder
        # rung: exchange exhausted retries, degrade to the metered
        # escape hatch (bit-identical values, counted bytes)
        out = tuple(pull_host(x) for x in arrays)
    return out[0] if len(out) == 1 else out


# ---------------------------------------------------------------------------
# host-to-host group handoff (distributegrps at process granularity)
# ---------------------------------------------------------------------------
def handoff_enabled() -> bool:
    return os.environ.get("PARMMG_MH_HANDOFF", "") == "1"


def plan_handoff(sizes, n_dev: int,
                 max_imbalance: float | None = None) -> np.ndarray | None:
    """LPT re-assignment of logical shards to devices.

    ``sizes``: [S_l] live-tet count per logical shard.  Returns the
    permutation ``perm`` (new leading-axis position -> old logical row,
    G rows per device preserved, rows within a device in ascending old
    order for determinism) or None when the current placement is
    already within ``max_imbalance`` (knob PARMMG_MH_IMBALANCE,
    default 0.25) of the mean device load — or when the greedy plan
    does not strictly improve the bottleneck."""
    sizes = np.asarray(sizes, np.int64).reshape(-1)
    S_l = len(sizes)
    if n_dev <= 1 or S_l % n_dev:
        return None
    G = S_l // n_dev
    if max_imbalance is None:
        max_imbalance = float(
            os.environ.get("PARMMG_MH_IMBALANCE", "0.25"))
    load = sizes.reshape(n_dev, G).sum(axis=1)
    mean = float(load.mean())
    if mean <= 0 or float(load.max()) - mean <= max_imbalance * mean:
        return None
    order = np.argsort(-sizes, kind="stable")
    dev_rows: list[list[int]] = [[] for _ in range(n_dev)]
    dev_load = np.zeros(n_dev, np.int64)
    for r in order:
        free = [d for d in range(n_dev) if len(dev_rows[d]) < G]
        d = min(free, key=lambda i: (int(dev_load[i]), i))
        dev_rows[d].append(int(r))
        dev_load[d] += sizes[r]
    if int(dev_load.max()) >= int(load.max()):
        return None                      # no bottleneck win: stay put
    perm = np.concatenate(
        [np.sort(np.asarray(rows, np.int64)) for rows in dev_rows])
    if np.array_equal(perm, np.arange(S_l)):
        return None
    return perm


def permute_comms(comms, perm: np.ndarray):
    """Remap the interface comm tables under a logical-shard
    permutation: rows reordered (new row ``i`` = old row ``perm[i]``)
    and every embedded logical id (nbr, owner values) rewritten through
    the inverse map.  Item order within each pair is untouched — the
    A.4 ordering contract survives a handoff by construction."""
    import dataclasses
    S_l = len(perm)
    inv = np.empty(S_l, np.int64)
    inv[perm] = np.arange(S_l)
    nbr = comms.nbr[perm]
    nbr = np.where(nbr >= 0, inv[np.clip(nbr, 0, S_l - 1)],
                   nbr).astype(comms.nbr.dtype)
    owner = []
    for i in range(S_l):
        ow = comms.owner[perm[i]]
        owner.append(inv[np.clip(ow, 0, S_l - 1)].astype(ow.dtype))
    return dataclasses.replace(
        comms, nbr=nbr, node_idx=comms.node_idx[perm],
        node_cnt=comms.node_cnt[perm], face_idx=comms.face_idx[perm],
        face_cnt=comms.face_cnt[perm], owner=owner)


def maybe_handoff(stacked, met_s, glo_d, glo, comms, verbose: int = 0):
    """Rebalance logical shards across devices/processes when the load
    skew warrants it (module docstring).  Returns (stacked, met_s,
    glo_d, glo, comms, n_moved_groups); everything unchanged (and 0)
    when the plan is a no-op or the handoff collective fails after
    retries — the handoff is an optimization, skipping it preserves
    every invariant."""
    import jax.numpy as jnp
    from ..resilience.recover import RetryBudgetExhausted, retry_call
    from .distribute import permute_shards

    ctx = current()
    if ctx is None:
        return stacked, met_s, glo_d, glo, comms, 0
    sizes = gather_band(
        jnp.sum(stacked.tmask, axis=1, dtype=jnp.int32),
        what="handoff_sizes")
    perm = plan_handoff(sizes, ctx.n_dev)
    if perm is None:
        return stacked, met_s, glo_d, glo, comms, 0
    moved = int(np.sum(perm // ctx.G != np.arange(len(perm)) // ctx.G))
    try:
        stacked2, met2, glo_d2 = retry_call(
            lambda: permute_shards(stacked, met_s, glo_d, perm,
                                   ctx.dmesh),
            site="multihost.exchange")
    except RetryBudgetExhausted as e:
        if ctx.multi():
            # same invariant as gather_band's exhaustion path: one
            # rank skipping the permutation while the others apply it
            # desyncs every later collective — die and resume from the
            # per-pass checkpoint instead
            raise
        REGISTRY.counter("mh.handoff_skipped").inc()
        otrace.log(1, f"  ## pod handoff skipped after retries ({e!r})",
                   err=True)
        return stacked, met_s, glo_d, glo, comms, 0
    glo2 = [glo[int(p)] for p in perm]
    comms2 = permute_comms(comms, perm)
    REGISTRY.counter("mh.handoffs").inc(moved)
    otrace.event("mh.handoff", moved=moved, n_dev=ctx.n_dev)
    otrace.log(2, f"  pod handoff: {moved} group(s) changed device "
                  f"(loads {np.asarray(sizes).reshape(ctx.n_dev, -1).sum(1).tolist()})",
               verbose=verbose)
    return stacked2, met2, glo_d2, glo2, comms2, moved
