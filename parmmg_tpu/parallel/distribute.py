"""Distribute a mesh into shards and merge shards back (host orchestration).

Reference analogues: ``PMMG_distribute_mesh`` (distributemesh_pmmg.c:1109)
splits the rank-0 mesh along a partition and sends each piece to its rank;
``PMMG_merge_parmesh`` (mergemesh_pmmg.c:1571) gathers everything back and
dedups interface entities through the node communicators.  Here shards are
slots of a stacked pytree (leading device axis) and interface vertices are
deduplicated at merge time by *exact* coordinate match — sound because
parallel-interface points are frozen (``MG_PARBDY | MG_REQ``; reference
tag contract tag_pmmg.c:39-124) and thus bit-identical on all shards.

The interface tagging applied here IS the freeze contract: interface faces
get MG_PARBDY|MG_BDY|MG_REQ|MG_NOSURF, their edges and vertices likewise
(+ MG_PARBDYBDY on entities that are also true boundary), so the shard-local
adapt operator (ops/adapt.py) leaves the interface untouched.
"""
from __future__ import annotations

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp

from ..core.mesh import Mesh, make_mesh, mesh_to_host
from ..core.constants import (
    IDIR, FACE_EDGES, IARE, MG_BDY, MG_PARBDY, MG_PARBDYBDY, MG_REQ,
    MG_NOSURF, PARBDY_TAGS)
from ..ops.adjacency import build_adjacency, boundary_edge_tags


def split_to_shards(mesh: Mesh, met, part: np.ndarray, nparts: int,
                    cap_mult: float = 3.0):
    """Split a host-resident Mesh into ``nparts`` shard Meshes (stacked).

    Returns (shards: Mesh with leading axis [nparts, ...], met stacked,
    None).  All shards share one capacity (max over shards * cap_mult /
    nparts-balance) so they stack into one pytree for shard_map.
    """
    vert, tet, vref, tref, vtag = mesh_to_host(mesh)
    methost = np.asarray(met)
    vm = np.asarray(mesh.vmask)
    new_id = np.cumsum(vm) - 1
    methost = methost[vm]
    part = np.asarray(part, np.int32)
    assert part.shape[0] == len(tet)

    # interface faces: faces shared by tets of different parts
    n = len(tet)
    faces = np.sort(tet[:, IDIR].reshape(n * 4, 3), axis=1)
    key = (faces[:, 0].astype(np.int64) << 42) | \
          (faces[:, 1].astype(np.int64) << 21) | faces[:, 2].astype(np.int64)
    order = np.argsort(key, kind="stable")
    ks = key[order]
    same = ks[1:] == ks[:-1]
    fA = order[:-1][same]
    fB = order[1:][same]
    cross = part[fA // 4] != part[fB // 4]
    ifc_faces = np.concatenate([fA[cross], fB[cross]])   # global face slots

    # mark interface vertices
    ifc_vert = np.zeros(len(vert), bool)
    ifc_vert[faces[ifc_faces].reshape(-1)] = True

    shards_m = []
    shards_met = []
    maxP = maxT = 0
    locals_ = []
    for p in range(nparts):
        sel = part == p
        ltet_g = tet[sel]
        used = np.zeros(len(vert), bool)
        used[ltet_g.reshape(-1)] = True
        g2l = np.full(len(vert), -1, np.int64)
        gids = np.where(used)[0]
        g2l[gids] = np.arange(len(gids))
        locals_.append((gids, ltet_g, np.where(sel)[0]))
        maxP = max(maxP, len(gids))
        maxT = max(maxT, len(ltet_g))

    capP = max(64, int(cap_mult * maxP))
    capT = max(64, int(cap_mult * maxT))

    face_is_ifc = np.zeros(n * 4, bool)
    face_is_ifc[ifc_faces] = True
    face_is_ifc = face_is_ifc.reshape(n, 4)

    for p in range(nparts):
        gids, ltet_g, tsel = locals_[p]
        g2l = np.full(len(vert), -1, np.int64)
        g2l[gids] = np.arange(len(gids))
        lvert = vert[gids]
        ltet = g2l[ltet_g].astype(np.int32)
        sm = make_mesh(lvert, ltet, vref=vref[gids], tref=tref[tsel],
                       capP=capP, capT=capT, dtype=mesh.dtype)
        # carry original tags
        svtag = np.zeros(capP, np.uint32)
        svtag[: len(gids)] = vtag[gids]
        # freeze interface: vertices
        on_ifc = ifc_vert[gids]
        svtag[: len(gids)][on_ifc] |= PARBDY_TAGS
        # PARBDYBDY: interface vertex that is also true boundary
        true_bdy = (vtag[gids] & MG_BDY) != 0
        svtag[: len(gids)][on_ifc & true_bdy] |= MG_PARBDYBDY
        # faces + edges of interface
        sftag = np.zeros((capT, 4), np.uint32)
        setag = np.zeros((capT, 6), np.uint32)
        lf_ifc = face_is_ifc[tsel]                       # [nt,4]
        sftag[: len(ltet)][lf_ifc] |= PARBDY_TAGS
        for f in range(4):
            for e in FACE_EDGES[f]:
                setag[: len(ltet), e] |= np.where(
                    lf_ifc[:, f], np.uint32(PARBDY_TAGS), np.uint32(0))
        sm = dataclasses.replace(
            sm, vtag=jnp.asarray(svtag),
            ftag=jnp.maximum(sm.ftag, jnp.asarray(sftag)),
            etag=jnp.maximum(sm.etag, jnp.asarray(setag)))
        sm = boundary_edge_tags(build_adjacency(sm))
        shards_m.append(sm)
        lmet = np.zeros((capP,) + methost.shape[1:], methost.dtype)
        lmet[: len(gids)] = methost[gids]
        shards_met.append(jnp.asarray(lmet))

    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *shards_m)
    met_stacked = jnp.stack(shards_met)
    return stacked, met_stacked


def merge_shards(shards: Mesh, mets=None, return_part: bool = False):
    """Merge stacked shard Meshes back into one host Mesh (+ metric).

    Interface vertices are deduplicated by exact coordinate bytes — valid
    because MG_PARBDY points are frozen during shard-local adaptation.
    With ``return_part``, also returns the source-shard label of every
    merged tet (a valid partition of the merged mesh, ready for
    interface displacement).
    """
    nsh = shards.vert.shape[0]
    all_v, all_tag, all_ref, all_met = [], [], [], []
    all_t, all_tref, all_src = [], [], []
    offsets = []
    off = 0
    for s in range(nsh):
        one = jax.tree.map(lambda x: x[s], shards)
        vert, tet, vref, tref, vtag = mesh_to_host(one)
        all_v.append(vert)
        all_tag.append(vtag)
        all_ref.append(vref)
        all_t.append(tet + off)
        all_tref.append(tref)
        all_src.append(np.full(len(tet), s, np.int32))
        if mets is not None:
            mh = np.asarray(mets[s])[np.asarray(one.vmask)]
            all_met.append(mh)
        offsets.append(off)
        off += len(vert)
    vert = np.concatenate(all_v)
    vtag = np.concatenate(all_tag)
    vref = np.concatenate(all_ref)
    tet = np.concatenate(all_t)
    tref = np.concatenate(all_tref)

    # dedup PARBDY vertices by coordinate bytes
    is_ifc = (vtag & MG_PARBDY) != 0
    keys = vert.astype(np.float64).tobytes()
    rows = np.frombuffer(keys, dtype=np.dtype((np.void, 24)))
    uniq, first_idx, inv = np.unique(rows, return_index=True,
                                     return_inverse=True)
    # canonical id: first occurrence; only merge interface copies
    canon = first_idx[inv]
    remap = np.arange(len(vert))
    remap[is_ifc] = canon[is_ifc]
    # drop PARBDY tags after merge (interfaces no longer exist) but keep
    # true-boundary info via MG_PARBDYBDY
    keep = np.zeros(len(vert), bool)
    keep[remap] = True
    new_id = np.cumsum(keep) - 1
    tet = new_id[remap[tet]].astype(np.int32)
    vtag2 = vtag[keep].copy()
    was_truebdy = (vtag2 & MG_PARBDYBDY) != 0
    was_parbdy = (vtag2 & MG_PARBDY) != 0
    vtag2 &= ~np.uint32(PARBDY_TAGS | MG_PARBDYBDY)
    vtag2[was_truebdy] |= MG_BDY
    vtag2[was_parbdy & ~was_truebdy] &= ~np.uint32(MG_BDY)
    m = make_mesh(vert[keep], tet, vref=vref[keep], tref=tref)
    vtag_full = np.zeros(m.capP, np.uint32)
    vtag_full[: len(vtag2)] = vtag2
    m = dataclasses.replace(m, vtag=jnp.asarray(vtag_full))
    m = boundary_edge_tags(build_adjacency(m))
    out_met = None
    if mets is not None:
        met = np.concatenate(all_met)[keep]
        full = np.zeros((m.capP,) + met.shape[1:], met.dtype)
        full[: len(met)] = met
        out_met = jnp.asarray(full)
    if return_part:
        return m, out_met, np.concatenate(all_src)
    return m, out_met
