"""Distribute a mesh into shards and merge shards back (host orchestration).

Reference analogues: ``PMMG_distribute_mesh`` (distributemesh_pmmg.c:1109)
splits the rank-0 mesh along a partition and sends each piece to its rank;
``PMMG_merge_parmesh`` (mergemesh_pmmg.c:1571) gathers everything back and
dedups interface entities through the node communicators.  Here shards are
slots of a stacked pytree (leading device axis) and interface vertices are
deduplicated at merge time by *exact* coordinate match — sound because
parallel-interface points are frozen (``MG_PARBDY | MG_REQ``; reference
tag contract tag_pmmg.c:39-124) and thus bit-identical on all shards.

The interface tagging applied here IS the freeze contract: interface faces
get MG_PARBDY|MG_BDY|MG_REQ|MG_NOSURF, their edges and vertices likewise
(+ MG_PARBDYBDY on entities that are also true boundary), so the shard-local
adapt operator (ops/adapt.py) leaves the interface untouched.
"""
from __future__ import annotations

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp

from ..core.mesh import Mesh, make_mesh, mesh_to_host
from ..core.constants import (
    IDIR, FACE_EDGES, IARE, MG_BDY, MG_PARBDY, MG_PARBDYBDY, MG_REQ,
    MG_NOSURF, PARBDY_TAGS)
from ..ops.adjacency import build_adjacency, boundary_edge_tags


def split_to_shards(mesh: Mesh, met, part: np.ndarray, nparts: int,
                    cap_mult: float = 3.0, return_l2g: bool = False):
    """Split a host-resident Mesh into ``nparts`` shard Meshes (stacked).

    Returns (shards: Mesh with leading axis [nparts, ...], met stacked),
    plus the per-shard local->global vertex maps when ``return_l2g`` (the
    input to build_interface_comms).  All shards share one capacity (max
    over shards * cap_mult / nparts-balance) so they stack into one
    pytree for shard_map.
    """
    vert, tet, vref, tref, vtag = mesh_to_host(mesh)
    methost = np.asarray(met)
    vm = np.asarray(mesh.vmask)
    tm = np.asarray(mesh.tmask)
    new_id = np.cumsum(vm) - 1
    methost = methost[vm]
    # per-tet face/edge tags + refs travel with the tets: ridge (MG_GEO)
    # and reference data must survive the split — the waves rely on edge
    # tags for the freeze contract, and fref is user data (the reference
    # ships whole MMG5_xTetra records in the group pack,
    # mpipack_pmmg.c:~400; dropping them here silently eroded ridges in
    # the distributed path)
    ftag_h = np.asarray(mesh.ftag)[tm]
    fref_h = np.asarray(mesh.fref)[tm]
    etag_h = np.asarray(mesh.etag)[tm]
    part = np.asarray(part, np.int32)
    assert part.shape[0] == len(tet)

    # interface faces: faces shared by tets of different parts
    n = len(tet)
    faces = np.sort(tet[:, IDIR].reshape(n * 4, 3), axis=1)
    key = (faces[:, 0].astype(np.int64) << 42) | \
          (faces[:, 1].astype(np.int64) << 21) | faces[:, 2].astype(np.int64)
    order = np.argsort(key, kind="stable")
    ks = key[order]
    same = ks[1:] == ks[:-1]
    fA = order[:-1][same]
    fB = order[1:][same]
    cross = part[fA // 4] != part[fB // 4]
    ifc_faces = np.concatenate([fA[cross], fB[cross]])   # global face slots

    # mark interface vertices
    ifc_vert = np.zeros(len(vert), bool)
    ifc_vert[faces[ifc_faces].reshape(-1)] = True

    shards_m = []
    shards_met = []
    maxP = maxT = 0
    locals_ = []
    for p in range(nparts):
        sel = part == p
        ltet_g = tet[sel]
        used = np.zeros(len(vert), bool)
        used[ltet_g.reshape(-1)] = True
        g2l = np.full(len(vert), -1, np.int64)
        gids = np.where(used)[0]
        g2l[gids] = np.arange(len(gids))
        locals_.append((gids, ltet_g, np.where(sel)[0]))
        maxP = max(maxP, len(gids))
        maxT = max(maxT, len(ltet_g))

    # BUCKETED shard capacities (compile governor): every per-shard and
    # per-group program (adapt blocks, flood, migration, analysis) keys
    # its compile on (capP, capT), and exact cap_mult*max sizes drift
    # with every re-split — one fresh multi-minute group-program compile
    # per grouped pass in the steady state, and a late big compile is
    # what kills tunneled TPU workers at the >=1M-tet scale.  The
    # geometric 1.5x ladder bounds the overshoot (<= 1.5x the requested
    # cap) while collapsing drifting sizes onto O(log n) shapes.
    from ..utils.compilecache import bucket
    capP = bucket(int(cap_mult * maxP), floor=64, scheme="geo")
    capT = bucket(int(cap_mult * maxT), floor=64, scheme="geo")

    face_is_ifc = np.zeros(n * 4, bool)
    face_is_ifc[ifc_faces] = True
    face_is_ifc = face_is_ifc.reshape(n, 4)

    for p in range(nparts):
        gids, ltet_g, tsel = locals_[p]
        g2l = np.full(len(vert), -1, np.int64)
        g2l[gids] = np.arange(len(gids))
        lvert = vert[gids]
        ltet = g2l[ltet_g].astype(np.int32)
        sm = make_mesh(lvert, ltet, vref=vref[gids], tref=tref[tsel],
                       capP=capP, capT=capT, dtype=mesh.dtype)
        # carry original tags
        svtag = np.zeros(capP, np.uint32)
        svtag[: len(gids)] = vtag[gids]
        # freeze interface: vertices.  MG_NOSURF marks REQ as OURS — a
        # vertex the user already required must NOT carry NOSURF, or the
        # merge would strip the user's REQ along with the freeze
        # (tag_pmmg.c NOSURF semantics: "REQ set by us, can be relaxed")
        on_ifc = ifc_vert[gids]
        user_req_v = (svtag[: len(gids)] & MG_REQ) != 0
        svtag[: len(gids)][on_ifc] |= PARBDY_TAGS
        svtag[: len(gids)][on_ifc & user_req_v] &= ~np.uint32(MG_NOSURF)
        # PARBDYBDY: interface vertex that is also true boundary
        true_bdy = (vtag[gids] & MG_BDY) != 0
        svtag[: len(gids)][on_ifc & true_bdy] |= MG_PARBDYBDY
        # faces + edges: carry the global tags/refs, then freeze interface
        sftag = np.zeros((capT, 4), np.uint32)
        setag = np.zeros((capT, 6), np.uint32)
        sfref = np.zeros((capT, 4), np.int32)
        sftag[: len(ltet)] = ftag_h[tsel]
        setag[: len(ltet)] = etag_h[tsel]
        sfref[: len(ltet)] = fref_h[tsel]
        lf_ifc = face_is_ifc[tsel]                       # [nt,4]
        user_req_f = (sftag[: len(ltet)] & MG_REQ) != 0
        sftag[: len(ltet)][lf_ifc] |= PARBDY_TAGS
        sftag[: len(ltet)][lf_ifc & user_req_f] &= ~np.uint32(MG_NOSURF)
        e_ifc_m = np.zeros((len(ltet), 6), bool)
        for f in range(4):
            for e in FACE_EDGES[f]:
                e_ifc_m[:, e] |= lf_ifc[:, f]
        # an interface edge that was ALSO true boundary keeps that fact
        # through the freeze via MG_PARBDYBDY (tag_pmmg.c PARBDYBDY
        # role); a user-required edge keeps REQ by NOT carrying NOSURF
        pre_bdy_e = (setag[: len(ltet)] & MG_BDY) != 0
        user_req_e = (setag[: len(ltet)] & MG_REQ) != 0
        setag[: len(ltet)][e_ifc_m] |= PARBDY_TAGS
        setag[: len(ltet)][e_ifc_m & pre_bdy_e] |= MG_PARBDYBDY
        setag[: len(ltet)][e_ifc_m & user_req_e] &= ~np.uint32(MG_NOSURF)
        sm = dataclasses.replace(
            sm, vtag=jnp.asarray(svtag),
            ftag=jnp.maximum(sm.ftag, jnp.asarray(sftag)),
            etag=jnp.maximum(sm.etag, jnp.asarray(setag)),
            fref=jnp.asarray(sfref))
        sm = boundary_edge_tags(build_adjacency(sm))
        shards_m.append(sm)
        lmet = np.zeros((capP,) + methost.shape[1:], methost.dtype)
        lmet[: len(gids)] = methost[gids]
        shards_met.append(jnp.asarray(lmet))

    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *shards_m)
    met_stacked = jnp.stack(shards_met)
    if return_l2g:
        return stacked, met_stacked, [loc[0] for loc in locals_]
    return stacked, met_stacked


def _weld_close_pairs(vert, tet, vtag, met, tref, ftag, etag,
                      tol_rel: float = 0.1):
    """Contract near-coincident untagged vertex pairs, sequentially.

    Independent refinement on the two sides of a frozen interface can
    drop interior points a tiny distance apart (each shard splits its own
    near-mirror edges); after the merge these tangled clusters deadlock
    the batched collapse wave (any single contraction inverts a sliver
    spanning the gap, so every direction is vetoed in parallel — while a
    SEQUENTIAL pass resolves the chain pair by pair, trying both
    directions, exactly like the reference's one-op-at-a-time remesher
    would).  Host-side, O(pairs); pairs are vertices closer than
    ``tol_rel`` x their metric size with BOTH tags clear, welded only
    when every rewritten tet stays positive and every dying tet is
    untagged.

    Returns (tet, vkeep, tkeep) — updated connectivity plus vertex/tet
    keep masks.
    """
    n = len(vert)
    if met is None:
        return tet, np.ones(n, bool), np.ones(len(tet), bool)
    if met.ndim == 1:
        href = met
    else:  # aniso: isotropic proxy h ~ 1/sqrt(mean diagonal eigenvalue)
        diag = (met[:, 0] + met[:, 3] + met[:, 5]) / 3.0
        href = 1.0 / np.sqrt(np.maximum(diag, 1e-30))
    free = vtag == 0
    if not free.any():
        return tet, np.ones(n, bool), np.ones(len(tet), bool)
    # vectorized prefilter: any pair within the weld radius collides in
    # at least one of the 8 half-cell-shifted grids at cell = 2*radius —
    # O(n log n) numpy, no Python loops on the (typical) no-pair path
    cell = max(1e-12, 2.0 * float(np.median(tol_rel * href[free])))
    fidx = np.where(free)[0]
    fv = vert[fidx]
    sus = np.zeros(len(fidx), bool)
    for sx in (0.0, 0.5):
        for sy in (0.0, 0.5):
            for sz in (0.0, 0.5):
                k = np.floor(fv / cell +
                             np.array([sx, sy, sz])).astype(np.int64)
                kk = (k[:, 0] << 42) ^ (k[:, 1] << 21) ^ k[:, 2]
                _, inv, cnts = np.unique(kk, return_inverse=True,
                                         return_counts=True)
                sus |= cnts[inv] > 1
    cand_v = fidx[sus]
    if not len(cand_v):
        return tet, np.ones(n, bool), np.ones(len(tet), bool)
    import collections
    import itertools
    key = np.round(vert / cell).astype(np.int64)
    cells = collections.defaultdict(list)
    for i in cand_v:
        cells[tuple(key[i])].append(int(i))
    cand_pairs = []
    for k, lst in cells.items():
        for dx in itertools.product((-1, 0, 1), repeat=3):
            k2 = (k[0] + dx[0], k[1] + dx[1], k[2] + dx[2])
            other = cells.get(k2)
            if not other:
                continue
            for i in lst:
                for j in other:
                    if i < j:
                        d = np.linalg.norm(vert[i] - vert[j])
                        if d < tol_rel * min(href[i], href[j]):
                            cand_pairs.append((d, i, j))
    if not cand_pairs:
        return tet, np.ones(n, bool), np.ones(len(tet), bool)
    cand_pairs.sort()
    # vertex -> tets incidence, restricted to tets touching a candidate
    touch = np.isin(tet, cand_v).any(axis=1)
    inc = collections.defaultdict(set)
    for t_i in np.where(touch)[0]:
        for v in tet[t_i]:
            inc[int(v)].add(int(t_i))
    tet = tet.copy()
    tkeep = np.ones(len(tet), bool)
    vkeep = np.ones(n, bool)

    def try_weld(rm, kp):
        ball = [t_i for t_i in inc[rm] if tkeep[t_i]]
        dying, moved = [], []
        for t_i in ball:
            row = tet[t_i]
            if kp in row:
                # must carry no tags to die silently, and a weld must
                # not bridge different regions
                if ftag[t_i].any() or etag[t_i].any():
                    return False
                dying.append(t_i)
            else:
                moved.append(t_i)
        if len({int(tref[t_i]) for t_i in ball}) > 1:
            return False
        for t_i in moved:
            row = np.where(tet[t_i] == rm, kp, tet[t_i])
            p = vert[row]
            if np.dot(p[1] - p[0], np.cross(p[2] - p[0], p[3] - p[0])) \
                    <= 1e-30:
                return False
        for t_i in dying:
            tkeep[t_i] = False
        for t_i in moved:
            tet[t_i] = np.where(tet[t_i] == rm, kp, tet[t_i])
            inc[kp].add(t_i)
        vkeep[rm] = False
        return True

    nweld = 0
    for _d, i, j in cand_pairs:
        if not (vkeep[i] and vkeep[j]):
            continue
        if try_weld(j, i) or try_weld(i, j):
            nweld += 1
    return tet, vkeep, tkeep


def grow_shards(shards: Mesh, mets, new_capP: int, new_capT: int):
    """Grow every shard's capacity IN PLACE (stacked axis intact).

    The static-shape analogue of the reference's realloc
    (zaldy_pmmg.c:140-254) without the whole-mesh merge->resplit round
    trip the old regrow path used: buffers are zero/False-padded on the
    capacity axis, so vertex/tet SLOT IDS are preserved — the split-time
    comm tables and frozen-interface contract remain valid, and host
    involvement is O(1) metadata instead of O(mesh).
    """
    capP, capT = shards.vert.shape[1], shards.tet.shape[1]
    dP, dT = new_capP - capP, new_capT - capT
    if dP <= 0 and dT <= 0:
        return shards, mets

    def padP(x, fill=0):
        pad = [(0, 0)] * x.ndim
        pad[1] = (0, max(0, dP))
        return jnp.pad(x, pad, constant_values=fill)

    def padT(x, fill=0):
        pad = [(0, 0)] * x.ndim
        pad[1] = (0, max(0, dT))
        return jnp.pad(x, pad, constant_values=fill)

    out = dataclasses.replace(
        shards,
        vert=padP(shards.vert), vref=padP(shards.vref),
        vtag=padP(shards.vtag), vmask=padP(shards.vmask, False),
        tet=padT(shards.tet), tref=padT(shards.tref),
        tmask=padT(shards.tmask, False), adja=padT(shards.adja, -1),
        ftag=padT(shards.ftag), fref=padT(shards.fref),
        etag=padT(shards.etag))
    return out, padP(mets)


# compiled leading-axis permutation programs keyed by (device ids, leaf
# shapes) — the host-to-host group handoff (parallel/pod.py): one
# x[perm] gather per leaf inside a single jit whose out_shardings keep
# the 'shard' leading axis, so XLA realizes the row moves as
# cross-device (and thereby cross-process) transfers of whole groups
_PERMUTE_CACHE: dict = {}


def permute_shards(shards: Mesh, mets, glo_d, perm, dmesh):
    """Reorder the logical-shard leading axis: new row ``i`` = old row
    ``perm[i]`` (a bijection, G rows per device preserved by the
    caller's plan).  Row CONTENTS — slot ids, and thereby the comm
    tables' local indices — are untouched: the handoff moves whole
    groups, the frozen-interface contract survives by construction.
    Returns (shards', mets', glo_d' | None)."""
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..utils.compilecache import governed

    leaves = (shards, mets) if glo_d is None else (shards, mets, glo_d)
    flat = jax.tree.leaves(leaves)
    # lint: ok(R2) — device-id metadata + abstract leaf shapes (cache
    # key construction), no device sync
    key = (tuple(d.id for d in np.asarray(dmesh.devices).flat),
           tuple((tuple(x.shape), str(x.dtype)) for x in flat))
    fn = _PERMUTE_CACHE.get(key)
    if fn is None:
        sh = NamedSharding(dmesh, P("shard"))
        fn = governed("mh.group_handoff", budget=8)(
            jax.jit(lambda xs, p: jax.tree.map(lambda x: x[p], xs),
                    out_shardings=sh))
        _PERMUTE_CACHE[key] = fn
    out = fn(leaves, jnp.asarray(np.asarray(perm), jnp.int32))
    if glo_d is None:
        return out[0], out[1], None
    return out


def merge_shards(shards: Mesh, mets=None, return_part: bool = False):
    """Merge stacked shard Meshes back into one host Mesh (+ metric).

    Interface vertices are deduplicated by exact coordinate bytes — valid
    because MG_PARBDY points are frozen during shard-local adaptation.
    With ``return_part``, also returns the source-shard label of every
    merged tet (a valid partition of the merged mesh, ready for
    interface displacement).
    """
    nsh = shards.vert.shape[0]
    all_v, all_tag, all_ref, all_met = [], [], [], []
    all_t, all_tref, all_src = [], [], []
    all_ft, all_fr, all_et = [], [], []
    offsets = []
    off = 0
    for s in range(nsh):
        one = jax.tree.map(lambda x: x[s], shards)
        vert, tet, vref, tref, vtag = mesh_to_host(one)
        tm = np.asarray(one.tmask)
        all_v.append(vert)
        all_tag.append(vtag)
        all_ref.append(vref)
        all_t.append(tet + off)
        all_tref.append(tref)
        all_ft.append(np.asarray(one.ftag)[tm])
        all_fr.append(np.asarray(one.fref)[tm])
        all_et.append(np.asarray(one.etag)[tm])
        all_src.append(np.full(len(tet), s, np.int32))
        if mets is not None:
            mh = np.asarray(mets[s])[np.asarray(one.vmask)]
            all_met.append(mh)
        offsets.append(off)
        off += len(vert)
    vert = np.concatenate(all_v)
    vtag = np.concatenate(all_tag)
    vref = np.concatenate(all_ref)
    tet = np.concatenate(all_t)
    tref = np.concatenate(all_tref)
    # face/edge tags travel back with the tets; interface faces become
    # interior (drop the freeze + BDY bits); interface edges keep their
    # true-boundary nature via PARBDYBDY and USER-required status via the
    # absence of MG_NOSURF (REQ without NOSURF was set by the caller, not
    # by the freeze — tag_pmmg.c NOSURF semantics)
    ftag_m = np.concatenate(all_ft)
    fref_m = np.concatenate(all_fr)
    etag_m = np.concatenate(all_et)
    f_ifc = (ftag_m & MG_PARBDY) != 0
    f_user = f_ifc & ((ftag_m & MG_NOSURF) == 0) & \
        ((ftag_m & MG_REQ) != 0)
    ftag_m[f_ifc] &= ~np.uint32(PARBDY_TAGS)
    ftag_m[f_user] |= MG_REQ
    e_ifc = (etag_m & MG_PARBDY) != 0
    e_truebdy = (etag_m & MG_PARBDYBDY) != 0
    e_user = e_ifc & ((etag_m & MG_NOSURF) == 0) & \
        ((etag_m & MG_REQ) != 0)
    etag_m[e_ifc] &= ~np.uint32(PARBDY_TAGS | MG_PARBDYBDY)
    etag_m[e_ifc & e_truebdy] |= MG_BDY
    etag_m[e_user] |= MG_REQ

    # dedup PARBDY vertices by coordinate bytes
    is_ifc = (vtag & MG_PARBDY) != 0
    keys = vert.astype(np.float64).tobytes()
    rows = np.frombuffer(keys, dtype=np.dtype((np.void, 24)))
    uniq, first_idx, inv = np.unique(rows, return_index=True,
                                     return_inverse=True)
    # canonical id: first occurrence; only merge interface copies
    canon = first_idx[inv]
    remap = np.arange(len(vert))
    remap[is_ifc] = canon[is_ifc]
    # drop PARBDY tags after merge (interfaces no longer exist) but keep
    # true-boundary info via MG_PARBDYBDY
    keep = np.zeros(len(vert), bool)
    keep[remap] = True
    new_id = np.cumsum(keep) - 1
    tet = new_id[remap[tet]].astype(np.int32)
    vtag2 = vtag[keep].copy()
    was_truebdy = (vtag2 & MG_PARBDYBDY) != 0
    was_parbdy = (vtag2 & MG_PARBDY) != 0
    was_user_req = was_parbdy & ((vtag2 & MG_NOSURF) == 0) & \
        ((vtag2 & MG_REQ) != 0)
    vtag2 &= ~np.uint32(PARBDY_TAGS | MG_PARBDYBDY)
    vtag2[was_truebdy] |= MG_BDY
    vtag2[was_parbdy & ~was_truebdy] &= ~np.uint32(MG_BDY)
    vtag2[was_user_req] |= MG_REQ

    vert_k = vert[keep]
    vref_k = vref[keep]
    met_k = np.concatenate(all_met)[keep] if mets is not None else None
    src_k = np.concatenate(all_src)
    # sequential weld of near-coincident interior pairs left by
    # independent refinement across the frozen interface (see
    # _weld_close_pairs — the batched collapse deadlocks on these)
    tet, vkeep2, tkeep2 = _weld_close_pairs(
        vert_k, tet, vtag2, met_k, tref, ftag_m, etag_m)
    if not (vkeep2.all() and tkeep2.all()):
        nid = np.cumsum(vkeep2) - 1
        tet = nid[tet[tkeep2]].astype(np.int32)
        tref = tref[tkeep2]
        ftag_m = ftag_m[tkeep2]
        fref_m = fref_m[tkeep2]
        etag_m = etag_m[tkeep2]
        src_k = src_k[tkeep2]
        vert_k = vert_k[vkeep2]
        vref_k = vref_k[vkeep2]
        vtag2 = vtag2[vkeep2]
        if met_k is not None:
            met_k = met_k[vkeep2]

    m = make_mesh(vert_k, tet, vref=vref_k, tref=tref)
    vtag_full = np.zeros(m.capP, np.uint32)
    vtag_full[: len(vtag2)] = vtag2
    ftag_full = np.zeros((m.capT, 4), np.uint32)
    ftag_full[: len(ftag_m)] = ftag_m
    fref_full = np.zeros((m.capT, 4), np.int32)
    fref_full[: len(fref_m)] = fref_m
    etag_full = np.zeros((m.capT, 6), np.uint32)
    etag_full[: len(etag_m)] = etag_m
    m = dataclasses.replace(m, vtag=jnp.asarray(vtag_full),
                            ftag=jnp.asarray(ftag_full),
                            fref=jnp.asarray(fref_full),
                            etag=jnp.asarray(etag_full))
    m = boundary_edge_tags(build_adjacency(m))
    out_met = None
    if mets is not None:
        full = np.zeros((m.capP,) + met_k.shape[1:], met_k.dtype)
        full[: len(met_k)] = met_k
        out_met = jnp.asarray(full)
    if return_part:
        return m, out_met, src_k
    return m, out_met
