"""Device-resident cross-shard surface analysis (PMMG_update_analys).

The host-numpy ``analysis_par.analyze_shards`` pulls every shard's full
arrays each outer iteration and re-derives the classification in Python
loops — the reference instead refreshes analysis per migration with
rank-local work + neighbor exchanges (analys_pmmg.c:1571,2001,1679).
This module is the jitted SPMD equivalent, so the between-iteration
refresh stays on device:

- every shard extracts its boundary-face edge records at static width
  [12*capT] (three edges per boundary face), keyed by the persistent
  GLOBAL vertex numbering;
- records whose two endpoints are NOT both interface (MG_PARBDY)
  vertices can only ever meet records of the same shard — they are
  grouped and classified locally (sort/segment: dihedral ridge test on
  2-record edges, ref-mismatch, non-manifold on counts != 2 — the
  PMMG_setdhd / MG_NOM rules);
- potentially-shared records (both endpoints interface) are compacted
  into a fixed [KS] buffer and ``all_gather``-ed over the shard axis
  (the ICI analogue of the reference's edge-comm normal exchange,
  analys_pmmg.c:2001): every shard runs the identical global grouping
  and reads back the verdicts for its own records;
- vertex singularity classification (corner = 1 or >2 incident special
  edges, ridge-point = 2; PMMG_singul:1679) needs GLOBAL incident
  counts: each special edge contributes +1 at its endpoints exactly
  once (the globally-first record's shard owns the contribution), and
  interface vertices sum their partial counts over the node comm tables
  (the int-comm count reduction of the reference);
- edge tags are rewritten in place: stale classification bits are
  cleared on plain-boundary slots elementwise, record slots receive
  their verdicts directly, and a keyed OR-join propagates the special
  bits to every other local slot of the same edge (interior tets
  sharing a ridge edge keep MG_GEO — tag routing reads per-slot tags).

The [KS] shared-record budget is static; if a shard exceeds it the
program reports overflow and the caller falls back to the host path for
that iteration (never silently truncates).

**Groups x shards (G > 1)**: :func:`dist_analysis_grouped` runs the
same pipeline when each device hosts G logical shards (the reference's
rank-level x group-level decomposition, grpsplit_pmmg.c:1551-1614).
The [R]-width sort/segment phases run per group under ``lax.map`` —
the same HBM discipline as the adapt block: peak working set is ONE
group's record table, not G of them — while the cross-shard phases ride
two collectives on interface-sized data: one ``all_gather`` of the
[G, KS] shared-record packs (logical shard l = device*G + slot) and one
grouped node-comm halo exchange (:func:`comms.halo_exchange_grouped`,
or its per-device-pair packed variant when the neighbor table is
sparse).  The per-group record extraction runs ONCE (fused, PR 12):
the pack phase also computes the local verdicts and carries the
per-record bits ([G, 12*capT] uint32 + head bool — 5 bytes/record)
across the map, and the tail re-derives only the cheap endpoint/slot
gathers instead of re-running the normals + global-id extraction (the
``extract2x_s`` decision input that priced this, retired in favor of
the bench's ``extract1x_s`` single-extraction timing).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.mesh import Mesh
from ..core.constants import (
    IDIR, MG_BDY, MG_CRN, MG_GEO, MG_NOM, MG_PARBDY, MG_REF)
from ..ops.edges import segmented_or

CLS = np.uint32(MG_GEO | MG_CRN | MG_REF | MG_NOM)
_EDGE_PAIRS = ((0, 1), (1, 2), (0, 2))
_I32MAX = jnp.iinfo(jnp.int32).max


def _sort2(a, b, valid):
    """Two-column ascending sort of (a, b) id pairs, invalid last.
    Global ids do not fit the packed single-key trick; always lexsort.
    Returns (order, ka, kb, first)."""
    aa = jnp.where(valid, a, _I32MAX)
    bb = jnp.where(valid, b, _I32MAX)
    order = jnp.lexsort((bb, aa))
    ka, kb = aa[order], bb[order]
    first = jnp.concatenate([jnp.array([True]),
                             (ka[1:] != ka[:-1]) | (kb[1:] != kb[:-1])])
    return order, ka, kb, first


def _seg_fields(first, valid_sorted):
    """(seg_id, cnt_of_my_segment, is_head) helpers for a sorted run."""
    n = first.shape[0]
    seg = jax.lax.associative_scan(
        jnp.maximum, jnp.where(first, jnp.arange(n), 0))
    ones = valid_sorted.astype(jnp.int32)
    # inclusive per-segment count at the LAST member, broadcast back
    def seg_add(pa, pb):
        fa, va = pa
        fb, vb = pb
        return fa | fb, jnp.where(fb, vb, va + vb)
    _, run = jax.lax.associative_scan(seg_add, (first, ones))
    is_last = jnp.concatenate([first[1:], jnp.array([True])])
    total_at_head = jnp.zeros(n, jnp.int32).at[
        jnp.where(is_last, seg, n)].set(run, mode="drop",
                                        unique_indices=True)
    return seg, total_at_head[seg], is_last


def _classify_sorted(first, valid_s, nu_s, fref_s, angedg):
    """Per-ROW verdict bits for a sorted record run: the segment verdict
    (ridge/ref/non-manifold) broadcast to every member row."""
    n = first.shape[0]
    seg, cnt, _ = _seg_fields(first, valid_s)
    nxt_same = jnp.concatenate([~first[1:], jnp.array([False])])
    dot = jnp.sum(nu_s * jnp.concatenate(
        [nu_s[1:], nu_s[:1]], axis=0), axis=-1)
    ref_mis = fref_s != jnp.concatenate([fref_s[1:], fref_s[:1]])
    # verdicts are decided at the segment HEAD of 2-record segments
    ridge_h = first & (cnt == 2) & nxt_same & (dot < angedg)
    ref_h = first & (cnt == 2) & nxt_same & ref_mis
    nom_h = first & valid_s & (cnt != 2)
    bits_h = (jnp.where(ridge_h, jnp.uint32(MG_GEO), 0)
              | jnp.where(ref_h, jnp.uint32(MG_REF), 0)
              | jnp.where(nom_h, jnp.uint32(MG_NOM), 0))
    bits_head = jnp.zeros(n, jnp.uint32).at[
        jnp.where(first, seg, n)].set(bits_h, mode="drop",
                                      unique_indices=True)
    bits_row = jnp.where(valid_s, bits_head[seg], 0)
    return bits_row, first & valid_s      # (row verdicts, head-row mask)


class _Records(NamedTuple):
    """Boundary-face edge records of ONE shard at static width
    R = 12*capT (3 edges x 4 faces per tet)."""
    la: jax.Array          # [R] local endpoint a
    lb: jax.Array          # [R] local endpoint b
    valid: jax.Array       # [R] record is a live plain-boundary face edge
    nu: jax.Array          # [R, 3] unit face normal
    frf: jax.Array         # [R] face ref
    trow: jax.Array        # [R] tet row
    le: jax.Array          # [R] local edge slot 0..5
    g_lo: jax.Array        # [R] global endpoint min
    g_hi: jax.Array        # [R] global endpoint max
    loc_rec: jax.Array     # [R] purely-local record
    sh_rec: jax.Array      # [R] potentially-shared record


def _extract_records(mesh: Mesh, glo=None) -> _Records:
    """Extract the [R] record table (the rank-local half of the
    reference's analys exchange).

    ``glo=None`` extracts the LIGHT table: endpoint/slot fields only
    (la/lb/valid/trow/le — cheap index gathers), with the
    normal/ref/global-id/interface fields zeroed.  The fused grouped
    analysis (:func:`shard_analysis_body_grouped`) runs the FULL
    extraction exactly once per group (pack phase) and carries the
    verdict bits across the map; its tail re-derives only this light
    table — the cross products, normalization, global-id and
    interface-classification gathers of the second extraction are the
    work the fusion removed (the retired ``extract2x_s`` cost)."""
    capT, capP = mesh.capT, mesh.capP
    idir = jnp.asarray(IDIR)
    full = glo is not None
    glo_i = glo.astype(jnp.int32) if full else None
    la_l, lb_l, valid_l, nrm_l, fref_l, trow_l, le_l = \
        [], [], [], [], [], [], []
    for f in range(4):
        tri = mesh.tet[:, idir[f]]                        # [T,3]
        if full:
            p = mesh.vert[tri]
            nrm = jnp.cross(p[:, 1] - p[:, 0], p[:, 2] - p[:, 0])
        is_b = mesh.tmask & ((mesh.ftag[:, f] & MG_BDY) != 0) & \
            ((mesh.ftag[:, f] & MG_PARBDY) == 0)
        for (a, b) in _EDGE_PAIRS:
            la_l.append(tri[:, a])
            lb_l.append(tri[:, b])
            valid_l.append(is_b)
            if full:
                nrm_l.append(nrm)
                fref_l.append(mesh.fref[:, f])
            trow_l.append(jnp.arange(capT, dtype=jnp.int32))
            from ..ops.swap import _EDGE_OF
            # lint: ok(R2) — _EDGE_OF is a static host table; the int()
            # folds a Python constant at trace time, no device sync
            eid = int(_EDGE_OF[IDIR[f][a], IDIR[f][b]])
            le_l.append(jnp.full(capT, eid, jnp.int32))
    la = jnp.concatenate(la_l)
    lb = jnp.concatenate(lb_l)
    valid = jnp.concatenate(valid_l)
    trow = jnp.concatenate(trow_l)
    le = jnp.concatenate(le_l)
    R = la.shape[0]
    if not full:
        zi = jnp.zeros(R, jnp.int32)
        return _Records(la, lb, valid, jnp.zeros((R, 3), mesh.vert.dtype),
                        zi, trow, le, zi, zi,
                        jnp.zeros(R, bool), jnp.zeros(R, bool))
    nrm = jnp.concatenate(nrm_l)
    nu = nrm / jnp.maximum(
        jnp.linalg.norm(nrm, axis=-1, keepdims=True), 1e-30)
    frf = jnp.concatenate(fref_l)
    ga = glo_i[jnp.clip(la, 0, capP - 1)]
    gb = glo_i[jnp.clip(lb, 0, capP - 1)]
    g_lo = jnp.minimum(ga, gb)
    g_hi = jnp.maximum(ga, gb)

    both_ifc = ((mesh.vtag[jnp.clip(la, 0, capP - 1)] & MG_PARBDY) != 0) \
        & ((mesh.vtag[jnp.clip(lb, 0, capP - 1)] & MG_PARBDY) != 0)
    return _Records(la, lb, valid, nu, frf, trow, le, g_lo, g_hi,
                    valid & ~both_ifc, valid & both_ifc)


def _local_bits(rec: _Records, angedg: float):
    """Local grouping + verdicts for the purely-local records.
    Returns (bits_rec [R] uint32, head_rec [R] bool)."""
    R = rec.la.shape[0]
    order, _, _, first = _sort2(rec.g_lo, rec.g_hi, rec.loc_rec)
    bits_srt, head_srt = _classify_sorted(
        first, rec.loc_rec[order], rec.nu[order], rec.frf[order], angedg)
    bits_rec = jnp.zeros(R, jnp.uint32).at[order].set(
        bits_srt, unique_indices=True)
    head_rec = jnp.zeros(R, bool).at[order].set(
        head_srt, unique_indices=True)
    return bits_rec, head_rec


def _shared_pack(rec: _Records, KS: int):
    """Compact the potentially-shared records into the fixed [KS]
    exchange buffer.  Returns (pack dict, overflow bool)."""
    R = rec.la.shape[0]
    n_sh = jnp.sum(rec.sh_rec.astype(jnp.int32))
    ovf = n_sh > KS
    widx = jnp.nonzero(rec.sh_rec, size=KS, fill_value=R)[0]
    wv = widx < R
    wc = jnp.clip(widx, 0, R - 1)
    pack = {
        "glo": jnp.where(wv, rec.g_lo[wc], _I32MAX),
        "ghi": jnp.where(wv, rec.g_hi[wc], _I32MAX),
        "nu": jnp.where(wv[:, None], rec.nu[wc], 0.0),
        "fref": jnp.where(wv, rec.frf[wc], 0),
        "row": jnp.where(wv, wc, R).astype(jnp.int32),
        "valid": wv,
    }
    return pack, ovf


def _merge_pack_verdicts(bits_rec, head_rec, pack, sh_bits, sh_head):
    """Scatter the [KS] global-exchange verdicts back onto the record
    rows (pack['row'] already points at R for pad slots)."""
    bits_rec = bits_rec.at[pack["row"]].max(sh_bits, mode="drop")
    head_rec = head_rec.at[pack["row"]].max(sh_head & pack["valid"],
                                            mode="drop")
    return bits_rec, head_rec


def _vertex_payload(mesh: Mesh, rec: _Records, bits_rec, head_rec):
    """Per-vertex partials of the int-comm reduction: [capP, 4] float32
    columns (nsing, has_ref, has_nom, on_bdy)."""
    capP = mesh.capP
    is_spec_rec = bits_rec != 0
    contrib = head_rec & is_spec_rec
    la, lb, valid = rec.la, rec.lb, rec.valid
    idx2 = jnp.concatenate([jnp.where(contrib, la, capP),
                            jnp.where(contrib, lb, capP)])
    nsing = jnp.zeros(capP + 1, jnp.int32).at[idx2].add(1, mode="drop")
    nsing = nsing[:capP]
    # partial per-vertex bit union (BDY from any record; REF/NOM presence)
    idx_all = jnp.concatenate([jnp.where(valid, la, capP),
                               jnp.where(valid, lb, capP)])
    vbits = jnp.zeros(capP + 1, jnp.uint32)
    vbits = vbits.at[idx_all].max(jnp.uint32(MG_BDY), mode="drop")
    has_ref = jnp.zeros(capP + 1, bool).at[jnp.concatenate([
        jnp.where(contrib & ((bits_rec & MG_REF) != 0), la, capP),
        jnp.where(contrib & ((bits_rec & MG_REF) != 0), lb, capP)])].max(
        True, mode="drop")[:capP]
    has_nom = jnp.zeros(capP + 1, bool).at[jnp.concatenate([
        jnp.where(contrib & ((bits_rec & MG_NOM) != 0), la, capP),
        jnp.where(contrib & ((bits_rec & MG_NOM) != 0), lb, capP)])].max(
        True, mode="drop")[:capP]
    on_bdy_local = (vbits[:capP] & MG_BDY) != 0
    return jnp.stack([
        nsing.astype(jnp.float32),
        has_ref.astype(jnp.float32),
        has_nom.astype(jnp.float32),
        on_bdy_local.astype(jnp.float32)], axis=1)       # [capP, 4]


def _vtag_from_payload(vtag, vmask, payload, acc):
    """Final vertex classification from the local payload + the summed
    neighbor contributions (shape-polymorphic over leading axes)."""
    nsing_t = payload[..., 0].astype(jnp.int32) + \
        acc[..., 0].astype(jnp.int32)
    ref_t = (payload[..., 1] > 0) | (acc[..., 1] > 0)
    nom_t = (payload[..., 2] > 0) | (acc[..., 2] > 0)
    bdy_t = (payload[..., 3] > 0) | (acc[..., 3] > 0)
    gtag = jnp.where(bdy_t, jnp.uint32(MG_BDY), 0)
    gtag = gtag | jnp.where(nsing_t == 2, jnp.uint32(MG_GEO), 0)
    gtag = gtag | jnp.where((nsing_t == 1) | (nsing_t > 2),
                            jnp.uint32(MG_CRN), 0)
    gtag = gtag | jnp.where(ref_t, jnp.uint32(MG_REF), 0)
    gtag = gtag | jnp.where(nom_t, jnp.uint32(MG_NOM), 0)
    vtag_new = (vtag & ~jnp.uint32(CLS)) | (gtag & CLS) | \
        (gtag & MG_BDY)
    return jnp.where(vmask, vtag_new, vtag)


def _etag_rewrite(mesh: Mesh, rec: _Records, bits_rec):
    """Edge-tag rewrite: clear stale classification on plain-boundary
    slots, write record verdicts, then OR-join the special bits onto
    every local slot of the same (local vertex pair) edge."""
    capT, capP = mesh.capT, mesh.capP
    R = rec.la.shape[0]
    la, lb, valid = rec.la, rec.lb, rec.valid
    is_spec_rec = bits_rec != 0
    plain = ((mesh.etag & MG_BDY) != 0) & ((mesh.etag & MG_PARBDY) == 0)
    etag_flat = (mesh.etag & ~jnp.where(plain, CLS, jnp.uint32(0))
                 ).reshape(-1)
    # record-slot verdicts: scatter-OR realized as gather|OR|set —
    # colliding writes (two boundary faces of one tet sharing the edge)
    # carry IDENTICAL verdict bits (same global segment), so duplicate
    # set()s are deterministic; a scatter-MAX would drop bits instead
    # of uniting them
    slot_flat = jnp.where(valid, rec.trow * 6 + rec.le, capT * 6)
    slot_c = jnp.clip(slot_flat, 0, capT * 6 - 1)
    merged = etag_flat[slot_c] | jnp.where(valid, bits_rec, 0)
    etag_new = etag_flat.at[slot_flat].set(merged, mode="drop")
    # keyed OR-join: donors = special records (local pair), receivers =
    # all live tet-edge slots
    from ..core.mesh import tet_edge_vertices
    from ..ops.edges import sort_pairs
    ev = tet_edge_vertices(mesh.tet).reshape(capT * 6, 2)
    ka = jnp.minimum(ev[:, 0], ev[:, 1])
    kb = jnp.maximum(ev[:, 0], ev[:, 1])
    alive6 = jnp.repeat(mesh.tmask, 6)
    don_a = jnp.minimum(la, lb)
    don_b = jnp.maximum(la, lb)
    don_v = valid & is_spec_rec
    n_all = capT * 6 + R
    aa = jnp.concatenate([ka, don_a])
    bb = jnp.concatenate([kb, don_b])
    vvv = jnp.concatenate([alive6, don_v])
    order_j, _, _, first_j = sort_pairs(aa, bb, vvv, capP)
    seg_j = jax.lax.associative_scan(
        jnp.maximum, jnp.where(first_j, jnp.arange(n_all), 0))
    dbits = jnp.where((order_j >= capT * 6) & vvv[order_j],
                      bits_rec[jnp.clip(order_j - capT * 6, 0, R - 1)],
                      0)
    or_run = segmented_or(first_j, dbits)
    is_last_j = jnp.concatenate([first_j[1:], jnp.array([True])])
    tot = jnp.zeros(n_all, jnp.uint32).at[
        jnp.where(is_last_j, seg_j, n_all)].set(
        or_run, mode="drop", unique_indices=True)
    add_srt = tot[seg_j]
    recv_rows = (order_j < capT * 6) & vvv[order_j]
    tgt_j = jnp.where(recv_rows, order_j, capT * 6)
    merged_j = etag_new[jnp.clip(tgt_j, 0, capT * 6 - 1)] | add_srt
    # receiver rows are unique (each tet-edge slot appears once)
    etag_new = etag_new.at[tgt_j].set(merged_j, mode="drop",
                                      unique_indices=True)
    return etag_new.reshape(capT, 6)


def shard_analysis_body(mesh: Mesh, glo, node_idx, nbr, angedg: float,
                        KS: int, axis_name: str = "shard"):
    """Per-shard analysis body (call inside shard_map), G = 1 layout.

    Returns (vtag_new [capP], etag_new [capT,6], overflow scalar bool).
    """
    capP = mesh.capP
    R = 12 * mesh.capT

    # ---- extract + local grouping + verdicts ----------------------------
    rec = _extract_records(mesh, glo)
    bits_rec, head_rec = _local_bits(rec, angedg)

    # ---- shared records: compact, all_gather, global grouping -----------
    pack, ovf = _shared_pack(rec, KS)
    me = jax.lax.axis_index(axis_name)
    gath = {k: jax.lax.all_gather(v, axis_name) for k, v in pack.items()}
    S = gath["glo"].shape[0]
    shard_of = jnp.repeat(jnp.arange(S, dtype=jnp.int32), KS)
    gl = gath["glo"].reshape(S * KS)
    gh = gath["ghi"].reshape(S * KS)
    gn = gath["nu"].reshape(S * KS, 3)
    gf = gath["fref"].reshape(S * KS)
    grow = gath["row"].reshape(S * KS)
    gv = gath["valid"].reshape(S * KS)
    order_g, _, _, first_g = _sort2(gl, gh, gv)
    bits_g, head_g = _classify_sorted(
        first_g, gv[order_g], gn[order_g], gf[order_g], angedg)
    # back to MY record rows: rows of the gathered run with shard == me
    mine_g = (shard_of[order_g] == me) & gv[order_g]
    tgt = jnp.where(mine_g, grow[order_g], R)
    bits_rec = bits_rec.at[tgt].max(bits_g, mode="drop")
    head_rec = head_rec.at[tgt].max(head_g & mine_g, mode="drop")
    ovf = jax.lax.pmax(ovf.astype(jnp.int32), axis_name) > 0

    # ---- vertex classification ------------------------------------------
    # +1 per endpoint per special edge, contributed by the globally-first
    # record's shard, then summed across shards at interface vertices
    # (the int-comm reduction)
    from .comms import halo_exchange
    payload = _vertex_payload(mesh, rec, bits_rec, head_rec)
    recv = halo_exchange(payload, node_idx, nbr, axis_name)  # [K,I,4]
    K, I = node_idx.shape
    flat = jnp.where(node_idx >= 0, node_idx, capP).reshape(-1)
    acc = jnp.zeros((capP + 1, 4), jnp.float32).at[flat].add(
        recv.reshape(K * I, 4), mode="drop")[:capP]
    vtag_new = _vtag_from_payload(mesh.vtag, mesh.vmask, payload, acc)

    # ---- edge tags -------------------------------------------------------
    etag_new = _etag_rewrite(mesh, rec, bits_rec)
    return vtag_new, etag_new, ovf


def shard_analysis_body_grouped(mesh_s: Mesh, glo_s, node_idx_s, nbr_s,
                                angedg: float, KS: int, G: int,
                                packed_M: int | None = None,
                                axis_name: str = "shard"):
    """Grouped analysis body (call inside shard_map): the device hosts
    ``G`` logical shards on the leading axis (logical shard l = device
    ``l // G``, slot ``l % G`` — the dist.py grouped layout).

    [R]-width phases run one group at a time under ``lax.map``; the
    cross-shard exchange gathers the [G, KS] shared-record packs in one
    collective and routes the vertex int-comm reduction through the
    grouped halo exchange (dense, or per-device-pair packed when
    ``packed_M`` is set).

    **Fused single extraction** (PR 12, ROADMAP 4a): the [12*capT]
    record extraction runs ONCE per group per refresh.  Phase 1 does
    the full extraction AND the local sort/classification, carrying the
    per-record verdict bits ([G, R] uint32 + the [G, R] head-row bool —
    5 bytes/record, vs the ~50-byte full record row the old design
    refused to persist) across the map; the tail re-derives only the
    cheap endpoint/slot gathers (light ``_extract_records``).  The
    predecessor extracted twice to keep the cross-map intermediate at
    [G, KS]; the ``extract2x_s`` probe priced that redundant second
    extraction at ~G x one extraction per refresh, which bought this
    trade (bench ``extract1x_s`` = the measured per-group saving).

    Returns (vtag_new [G, capP], etag_new [G, capT, 6], overflow bool).
    """
    from .comms import halo_exchange_grouped, halo_exchange_grouped_packed
    capP = mesh_s.vert.shape[1]

    # ---- phase 1 (per group, lax.map): ONE full extraction — local
    # verdicts + shared-record packs + the [G, R] verdict carry ----------
    def pack_one(args):
        mesh_g, glo_g = args
        rec = _extract_records(mesh_g, glo_g)
        bits_rec, head_rec = _local_bits(rec, angedg)
        pack, ovf = _shared_pack(rec, KS)
        return pack, ovf, bits_rec, head_rec

    packs, ovf_g, bits_all, head_all = \
        jax.lax.map(pack_one, (mesh_s, glo_s))              # [G, ...]
    ovf = jnp.any(ovf_g)

    # ---- phase 2: one all_gather + the global grouping ------------------
    # (the "row" field stays local: grouped verdicts return through the
    # pack-slot index, so the record-row mapping never rides the wire)
    me = jax.lax.axis_index(axis_name)
    gath = {k: jax.lax.all_gather(v, axis_name)
            for k, v in packs.items() if k != "row"}
    S = gath["glo"].shape[0]                   # devices on the axis
    L = S * G                                  # logical shards
    logical_of = jnp.repeat(jnp.arange(L, dtype=jnp.int32), KS)
    gl = gath["glo"].reshape(L * KS)
    gh = gath["ghi"].reshape(L * KS)
    gn = gath["nu"].reshape(L * KS, 3)
    gf = gath["fref"].reshape(L * KS)
    gv = gath["valid"].reshape(L * KS)
    order_g, _, _, first_g = _sort2(gl, gh, gv)
    bits_g, head_g = _classify_sorted(
        first_g, gv[order_g], gn[order_g], gf[order_g], angedg)
    # verdicts for MY logical shards, back in [G, KS] pack-slot layout
    lo = logical_of[order_g]
    mine_g = (lo // G == me) & gv[order_g]
    # pack slot j = flat % KS, group g = (flat // KS) % G
    src_flat = order_g                          # original gathered index
    g_tgt = jnp.where(mine_g, (src_flat // KS) % G, G)
    j_tgt = jnp.where(mine_g, src_flat % KS, 0)
    sh_bits = jnp.zeros((G, KS), jnp.uint32).at[g_tgt, j_tgt].max(
        bits_g, mode="drop")
    sh_head = jnp.zeros((G, KS), bool).at[g_tgt, j_tgt].max(
        head_g & mine_g, mode="drop")
    ovf = jax.lax.pmax(ovf.astype(jnp.int32), axis_name) > 0

    # ---- phase 3 (per group, lax.map): verdict merge + local tail -------
    # (light re-extraction only: the verdict bits and the pack-slot
    # mapping were carried from phase 1 — no second full extraction)
    def tail_one(args):
        (mesh_g, bits_rec, head_rec, row_g, pv_g,
         sh_bits_g, sh_head_g) = args
        rec = _extract_records(mesh_g)                     # light
        bits_rec, head_rec = _merge_pack_verdicts(
            bits_rec, head_rec, {"row": row_g, "valid": pv_g},
            sh_bits_g, sh_head_g)
        payload = _vertex_payload(mesh_g, rec, bits_rec, head_rec)
        etag_new = _etag_rewrite(mesh_g, rec, bits_rec)
        return etag_new, payload

    etag_new, payload = jax.lax.map(
        tail_one, (mesh_s, bits_all, head_all, packs["row"],
                   packs["valid"], sh_bits, sh_head))

    # ---- phase 4: grouped int-comm reduction + vertex classification ---
    if packed_M is not None:
        recv = halo_exchange_grouped_packed(
            payload, node_idx_s, nbr_s, G, packed_M, axis_name)
    else:
        recv = halo_exchange_grouped(payload, node_idx_s, nbr_s, G,
                                     axis_name)               # [G,K,I,4]
    K, I = node_idx_s.shape[1:]
    flat = jnp.where(node_idx_s >= 0, node_idx_s, capP)       # [G,K,I]

    def acc_one(fl, rc):
        return jnp.zeros((capP + 1, 4), jnp.float32).at[
            fl.reshape(-1)].add(rc.reshape(-1, 4), mode="drop")[:capP]

    acc = jax.vmap(acc_one)(flat, recv)                       # [G,capP,4]
    vtag_new = _vtag_from_payload(mesh_s.vtag, mesh_s.vmask, payload, acc)
    return vtag_new, etag_new, ovf


_EXTRACT_PROBE = None


def extract_probe_seconds(mesh_g: Mesh, glo_g, repeats: int = 3) -> float:
    """Wall-seconds for ONE [12*capT] record-table extraction, jitted
    standalone (compile excluded; median of ``repeats`` runs).

    PR 5 surfaced this as ``extract2x_s``, the decision input pricing
    :func:`dist_analysis_grouped`'s redundant SECOND extraction (~G x
    this number per refresh).  PR 12 fused the double extraction into
    one pass (ROADMAP 4a) — the probe now prices what the fusion
    REMOVED: before = 2x this per group per refresh, after = 1x plus
    cheap endpoint gathers.  Surfaced as ``extract1x_s`` in the bench
    extra (the measured before/after of the fusion).

    The probe reduces every record field to scalars so the measurement
    covers the full extraction (gathers + cross products + the
    interface classification) without paying an [R]-wide device->host
    pull."""
    import time
    from ..utils.compilecache import governed

    global _EXTRACT_PROBE
    if _EXTRACT_PROBE is None:
        @governed("analysis.extract_probe", budget=2)
        @jax.jit
        def _probe(m, g):
            # scalar sinks only (keeps every extraction field live
            # against DCE without an [R]-wide pull; int32 wrap is fine
            # for a timing sink)
            rec = _extract_records(m, g)
            return (jnp.sum(rec.g_lo) + jnp.sum(rec.g_hi),
                    jnp.sum(rec.nu), jnp.sum(rec.frf),
                    jnp.sum(rec.loc_rec), jnp.sum(rec.sh_rec))
        _EXTRACT_PROBE = _probe
    out = _EXTRACT_PROBE(mesh_g, glo_g)
    jax.block_until_ready(out)                   # compile + warm
    ts = []
    for _ in range(max(1, int(repeats))):
        t0 = time.perf_counter()
        jax.block_until_ready(_EXTRACT_PROBE(mesh_g, glo_g))
        ts.append(time.perf_counter() - t0)
    sec = float(np.median(ts))
    # obs spine: the fused-extraction timing rides the metrics registry
    # too (the per-group per-refresh seconds the PR-12 fusion saves)
    from ..obs.metrics import REGISTRY
    REGISTRY.gauge("analysis.extract1x_s").set(sec)
    return sec


def dist_analysis(dmesh, angedg: float, KS: int):
    """Build the jitted SPMD analysis-refresh program for a device mesh.

    Returns fn(stacked_mesh, glo_s [S,capP] int32, node_idx_s, nbr_s) ->
      (vtag [S,capP], etag [S,capT,6], overflow scalar).
    """
    from jax.sharding import PartitionSpec as P
    from ..utils.jaxcompat import shard_map
    from .dist import _unstack

    spec = P("shard")

    def local(mesh_s, glo_s, node_idx_s, nbr_s):
        mesh = _unstack(mesh_s)
        vt, et, ovf = shard_analysis_body(
            mesh, glo_s[0], node_idx_s[0], nbr_s[0], angedg, KS)
        return vt[None], et[None], ovf.astype(jnp.int32)

    # lint: ok(R1) — builder: the sole caller (dist.refresh_shard_
    # analysis_device) caches by (angedg,KS,S,G,Mp) and wraps the
    # product in governed("dist.analysis", budget=2)
    fn = shard_map(local, mesh=dmesh,
                   in_specs=(spec, spec, spec, spec),
                   out_specs=(spec, spec, P()), check_vma=False)
    # lint: ok(R1) — same builder contract as above
    return jax.jit(fn)


def dist_analysis_grouped(dmesh, angedg: float, KS: int, G: int,
                          packed_M: int | None = None):
    """Grouped (G logical shards per device) SPMD analysis-refresh
    program: same contract as :func:`dist_analysis` with the stacked
    leading axis carrying S*G logical shards.

    Returns fn(stacked_mesh, glo_s [S*G,capP] int32, node_idx_s, nbr_s)
      -> (vtag [S*G,capP], etag [S*G,capT,6], overflow scalar).
    """
    from jax.sharding import PartitionSpec as P
    from ..utils.jaxcompat import shard_map

    spec = P("shard")

    def local(mesh_s, glo_s, node_idx_s, nbr_s):
        vt, et, ovf = shard_analysis_body_grouped(
            mesh_s, glo_s, node_idx_s, nbr_s, angedg, KS, G,
            packed_M=packed_M)
        return vt, et, ovf.astype(jnp.int32)

    # lint: ok(R1) — builder: the sole caller (dist.refresh_shard_
    # analysis_device) caches by (angedg,KS,S,G,Mp) and wraps the
    # product in governed("dist.analysis_grouped", budget=2)
    fn = shard_map(local, mesh=dmesh,
                   in_specs=(spec, spec, spec, spec),
                   out_specs=(spec, spec, P()), check_vma=False)
    # lint: ok(R1) — same builder contract as above
    return jax.jit(fn)
