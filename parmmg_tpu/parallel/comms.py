"""Communicator layer: interface index arrays, halo exchange, numbering.

TPU-native re-design of the reference's communicator abstraction
(/root/reference/src/libparmmgtypes.h:253-280; construction
communicators_pmmg.c; checks chkcomm_pmmg.c; global numbering
libparmmg.c:464-1105):

- the *internal communicator* (flat per-rank interface array with scratch
  ``intvalues``) + *external communicators* (per-neighbor ordered item
  lists) become, per shard, a single padded index table
  ``send_idx[s, k, i]`` = local entity id of item i of neighbor slot k,
  with ``nbr[s, k]`` the neighbor shard — static shapes, so the whole
  exchange jits under ``shard_map``;
- the canonical ParMmg exchange idiom (scatter->Sendrecv->merge with an
  owner rule, e.g. libparmmg.c:743-790) becomes ``halo_exchange``:
  gather item values into per-neighbor send rows -> ``all_to_all`` over
  the shard axis (rides ICI; O(S*I) traffic, each shard ships only its
  own neighbor rows) -> each shard reads the mirrored row it received
  from each neighbor -> merge (min/max/sum).  Matching item order on the
  two sides of a pair is guaranteed by construction: both sides sort
  items by *global* entity key — the ordering contract of the reference
  API (API_functions_pmmg.c:1295-1330, SURVEY A.4);
- owner rule: max shard id touching the entity (libparmmg.c:962-973);
- the chkcomm "coordinate echo" oracle becomes :func:`check_node_comms`:
  exchange actual coordinates and compare within a bbox-scaled epsilon
  (chkcomm_pmmg.c:40-126 scaling idea).
"""
from __future__ import annotations

import dataclasses

import numpy as np

from ..core.constants import IDIR


@dataclasses.dataclass
class InterfaceComms:
    """Padded per-shard communicator tables (host-built, device-ready).

    For S shards, K = max neighbors, I = max items per neighbor pair:
      nbr[s, k]           neighbor shard id or -1
      node_idx[s, k, i]   local vertex row in shard s (or -1 pad)
      face_idx[s, k, i]   local tet-face slot 4*t+f in shard s (or -1)
    Item order along i is identical on the two sides of every pair.
    """
    nbr: np.ndarray
    node_idx: np.ndarray
    node_cnt: np.ndarray     # [S, K]
    face_idx: np.ndarray
    face_cnt: np.ndarray     # [S, K]
    owner: list[np.ndarray]  # per shard: owner shard of each local vertex


def build_interface_comms(tet: np.ndarray, part: np.ndarray,
                          nparts: int,
                          l2g: list[np.ndarray],
                          g2l: list[np.ndarray]) -> InterfaceComms:
    """Build node+face comms from a partition of a global mesh.

    ``l2g[s]``: shard-local vertex row -> global vertex id;
    ``g2l[s]``: global vertex id -> local row (-1 if absent).
    Reproduces PMMG_build_faceCommIndex/_nodeCommFromFaces semantics
    (communicators_pmmg.c:894-1823) including nodes shared by shards with
    no common face (the completeExtNodeComm case :1826): node comms here
    are derived from the full vertex->shards incidence, which covers
    vertex-only adjacency by construction.

    Fully sort/segment based: no [nvert, nparts] dense incidence and no
    per-item Python loops, so construction stays O(interface log) at
    S=64 and beyond.  Item ordering is bit-identical to the reference
    implementation below (faces by global key, nodes by global id — the
    A.4 ordering contract); tests/test_comms.py asserts the equality.
    """
    n = len(tet)
    S = nparts
    # ---- interface faces (matched pairs across parts) -------------------
    faces = np.sort(tet[:, IDIR].reshape(n * 4, 3), axis=1)
    key = (faces[:, 0].astype(np.int64) << 42) | \
          (faces[:, 1].astype(np.int64) << 21) | faces[:, 2].astype(np.int64)
    order = np.argsort(key, kind="stable")
    ks = key[order]
    same = ks[1:] == ks[:-1]
    fA, fB = order[:-1][same], order[1:][same]
    pA, pB = part[fA // 4], part[fB // 4]
    cross = pA != pB
    fA, fB, pA, pB = fA[cross], fB[cross], pA[cross], pB[cross]
    fkey = key[fA]

    # group matched faces by unordered pair, keep fkey order inside each
    lo = np.minimum(pA, pB).astype(np.int64)
    hi = np.maximum(pA, pB).astype(np.int64)
    o2 = np.lexsort((fkey, hi, lo))
    loS, hiS = lo[o2], hi[o2]
    fA_s, fB_s, pA_s = fA[o2], fB[o2], pA[o2]
    head = np.concatenate([[True], (loS[1:] != loS[:-1]) |
                           (hiS[1:] != hiS[:-1])]) \
        if len(loS) else np.zeros(0, bool)
    bounds = np.concatenate([np.where(head)[0], [len(loS)]]) \
        if len(loS) else np.array([0])
    face_lists = [[[] for _ in range(S)] for _ in range(S)]
    for bi in range(len(bounds) - 1):
        sl = slice(bounds[bi], bounds[bi + 1])
        a, b = int(loS[bounds[bi]]), int(hiS[bounds[bi]])
        a_is_A = pA_s[sl] == a
        fa = np.where(a_is_A, fA_s[sl], fB_s[sl])
        fb = np.where(a_is_A, fB_s[sl], fA_s[sl])
        face_lists[a][b] = fa.tolist()
        face_lists[b][a] = fb.tolist()

    # ---- vertex -> parts incidence (sorted pairs, no dense matrix) ------
    allg = np.concatenate([np.asarray(l, np.int64) for l in l2g]) \
        if l2g else np.zeros(0, np.int64)
    allsh = np.concatenate([np.full(len(l), s, np.int64)
                            for s, l in enumerate(l2g)])
    ov = np.lexsort((allsh, allg))
    gs, ss = allg[ov], allsh[ov]
    headv = np.concatenate([[True], gs[1:] != gs[:-1]]) \
        if len(gs) else np.zeros(0, bool)
    segv = np.cumsum(headv) - 1
    nseg = int(segv[-1]) + 1 if len(segv) else 0
    cnt = np.bincount(segv, minlength=nseg)
    # owner per global id = max incident shard (sorted segments: last)
    last = np.concatenate([headv[1:], [True]]) if len(gs) else headv
    owner_of_seg = ss[last] if len(gs) else np.zeros(0, np.int64)
    seg_gid = gs[headv] if len(gs) else np.zeros(0, np.int64)

    # pair expansion: each row pairs with every OTHER row of its segment
    crow = cnt[segv]
    startv = np.zeros(nseg, np.int64)
    if nseg:
        startv[1:] = np.cumsum(cnt)[:-1]
    rankv = np.arange(len(gs)) - startv[segv]
    rep = np.repeat(np.arange(len(gs)), np.maximum(crow - 1, 0))
    m = len(rep)
    if m:
        off_in = np.arange(m) - np.repeat(
            np.concatenate([[0], np.cumsum(np.maximum(crow - 1, 0))[:-1]]),
            np.maximum(crow - 1, 0))
        r_rep = rankv[rep]
        other_rank = off_in + (off_in >= r_rep)
        other = startv[segv[rep]] + other_rank
        a_sh = ss[rep]
        b_sh = ss[other]
        gid_p = gs[rep]
    else:
        a_sh = b_sh = gid_p = np.zeros(0, np.int64)
    # group by (a, b); gid order inside each pair list is preserved by a
    # stable sort (segments were gid-ascending already)
    node_lists = [[[] for _ in range(S)] for _ in range(S)]
    if m:
        op = np.lexsort((gid_p, b_sh, a_sh))
        aS, bS, gS = a_sh[op], b_sh[op], gid_p[op]
        headp = np.concatenate([[True], (aS[1:] != aS[:-1]) |
                                (bS[1:] != bS[:-1])])
        pb = np.concatenate([np.where(headp)[0], [len(aS)]])
        for bi in range(len(pb) - 1):
            sl = slice(pb[bi], pb[bi + 1])
            node_lists[int(aS[pb[bi]])][int(bS[pb[bi]])] = gS[sl].tolist()

    # ---- convert to local indices and pad into tables -------------------
    node_loc = [[(g2l[s][np.asarray(node_lists[s][b], np.int64)].tolist()
                  if node_lists[s][b] else [])
                 for b in range(S)] for s in range(S)]
    face_loc = [[(_global_face_to_local(
                    np.asarray(face_lists[s][b], np.int64), part,
                    s).tolist() if face_lists[s][b] else [])
                 for b in range(S)] for s in range(S)]
    owner = []
    gid2owner = np.full(int(allg.max()) + 1 if len(allg) else 1, -1,
                        np.int64)
    if nseg:
        gid2owner[seg_gid] = owner_of_seg
    for s in range(S):
        ow = gid2owner[np.asarray(l2g[s], np.int64)].astype(np.int32) \
            if len(l2g[s]) else np.zeros(0, np.int32)
        ow[ow < 0] = s
        owner.append(ow)
    return pad_comm_tables(node_loc, face_loc, owner, S)


def build_interface_comms_ref(tet: np.ndarray, part: np.ndarray,
                              nparts: int,
                              l2g: list[np.ndarray],
                              g2l: list[np.ndarray]) -> InterfaceComms:
    """Reference (dense-incidence, per-item Python loop) construction —
    kept as the bit-identity oracle for the sort-based builder above."""
    n = len(tet)
    # ---- interface faces (matched pairs across parts) -------------------
    faces = np.sort(tet[:, IDIR].reshape(n * 4, 3), axis=1)
    key = (faces[:, 0].astype(np.int64) << 42) | \
          (faces[:, 1].astype(np.int64) << 21) | faces[:, 2].astype(np.int64)
    order = np.argsort(key, kind="stable")
    ks = key[order]
    same = ks[1:] == ks[:-1]
    fA, fB = order[:-1][same], order[1:][same]
    pA, pB = part[fA // 4], part[fB // 4]
    cross = pA != pB
    fA, fB, pA, pB = fA[cross], fB[cross], pA[cross], pB[cross]
    fkey = key[fA]                       # global face key (same for both)

    # ---- vertex -> parts incidence --------------------------------------
    nvert = max(int(l.max()) + 1 if len(l) else 0 for l in l2g) \
        if l2g else int(tet.max()) + 1
    incid = np.zeros((nvert, nparts), bool)
    for s in range(nparts):
        incid[l2g[s], s] = True
    shared = incid.sum(axis=1) > 1
    owner_g = np.where(incid.any(axis=1),
                       nparts - 1 - np.argmax(incid[:, ::-1], axis=1), -1)

    # ---- per-pair item lists, ordered by global key ---------------------
    S = nparts
    node_lists = [[[] for _ in range(S)] for _ in range(S)]
    face_lists = [[[] for _ in range(S)] for _ in range(S)]
    # faces: ordered by fkey
    o = np.argsort(fkey, kind="stable")
    for i in o:
        a, b = int(pA[i]), int(pB[i])
        face_lists[a][b].append(int(fA[i]))
        face_lists[b][a].append(int(fB[i]))
    # nodes: every globally-shared vertex, for each pair of its parts,
    # ordered by global id
    shared_ids = np.where(shared)[0]
    for g in shared_ids:
        ps = np.where(incid[g])[0]
        for a in ps:
            for b in ps:
                if a < b:
                    node_lists[a][b].append(int(g))
                    node_lists[b][a].append(int(g))

    # ---- convert to local indices and pad into tables --------------------
    node_loc = [[(g2l[s][np.asarray(node_lists[s][b], np.int64)].tolist()
                  if node_lists[s][b] else [])
                 for b in range(S)] for s in range(S)]
    face_loc = [[(_global_face_to_local(
                    np.asarray(face_lists[s][b], np.int64), part,
                    s).tolist() if face_lists[s][b] else [])
                 for b in range(S)] for s in range(S)]
    owner = []
    for s in range(S):
        ow = owner_g[l2g[s]].astype(np.int32)
        ow[ow < 0] = s
        owner.append(ow)
    return pad_comm_tables(node_loc, face_loc, owner, S)


def pad_comm_tables(node_lists, face_lists, owner,
                    n_shards: int) -> InterfaceComms:
    """Pad per-pair item lists (LOCAL indices, both-sides-identical
    order — the A.4 contract) into the device-ready InterfaceComms
    layout.  Single source of truth for the padding/K>=1 clamps, shared
    by build_interface_comms and the migration rebuild
    (parallel/migrate.py).

    Pad widths are BUCKETED (utils/compilecache.bucket), not exact: the
    tables are rebuilt with drifting interface sizes every migration
    iteration, and every jitted consumer of node_idx/face_idx
    (dist_interface_check, dist_analysis, flood_labels, graph_probe)
    keys its compile on these shapes — exact pads meant one fresh XLA
    compile per iteration in the steady state.  The item axes use the
    geometric 1.5x scheme (wide tables; pow2 doubling wastes up to 2x
    the interface memory), the neighbor axis pow2 from a floor of 2.
    Consumers already tolerate pads by construction (-1 idx, cnt
    arrays)."""
    from ..utils.compilecache import bucket
    S = n_shards
    nbrs = [[b for b in range(S)
             if b != s and (node_lists[s][b] or face_lists[s][b])]
            for s in range(S)]
    K = bucket(max((len(x) for x in nbrs), default=1), floor=2,
               cap=max(1, S - 1))
    In = bucket(max((len(node_lists[s][b]) for s in range(S)
                     for b in range(S)), default=1),
                floor=64, scheme="geo")
    If = bucket(max((len(face_lists[s][b]) for s in range(S)
                     for b in range(S)), default=1),
                floor=64, scheme="geo")
    nbr = np.full((S, K), -1, np.int32)
    node_idx = np.full((S, K, In), -1, np.int32)
    node_cnt = np.zeros((S, K), np.int32)
    face_idx = np.full((S, K, If), -1, np.int32)
    face_cnt = np.zeros((S, K), np.int32)
    for s in range(S):
        for k, b in enumerate(nbrs[s]):
            nbr[s, k] = b
            nl = node_lists[s][b]
            node_idx[s, k, : len(nl)] = nl
            node_cnt[s, k] = len(nl)
            fl = face_lists[s][b]
            face_idx[s, k, : len(fl)] = fl
            face_cnt[s, k] = len(fl)
    return InterfaceComms(nbr, node_idx, node_cnt, face_idx, face_cnt,
                          owner)


def _global_face_to_local(gface: np.ndarray, part: np.ndarray, s: int)\
        -> np.ndarray:
    """global 4*tet+face slot -> local 4*tet+face for shard s (tets of
    shard s are numbered in global order, as split_to_shards does)."""
    sel = np.where(part == s)[0]
    g2l_t = np.full(len(part), -1, np.int64)
    g2l_t[sel] = np.arange(len(sel))
    return (4 * g2l_t[gface // 4] + (gface % 4)).astype(np.int32)


# ---------------------------------------------------------------------------
# jittable halo exchange (inside shard_map)
# ---------------------------------------------------------------------------
def halo_exchange(vals, send_idx, nbr, axis_name: str = "shard",
                  reduce: str = "max"):
    """Exchange per-interface-item values with every neighbor.

    vals:      [P, ...] per-local-entity values (this shard)
    send_idx:  [K, I] local entity ids (−1 pad); item order matches the
               neighbor's table for the same pair (ordering contract)
    nbr:       [K] neighbor shard ids (−1 pad)
    Returns ``recv[K, I, ...]``: the neighbor's values for each item
    (zeros on pads).  The caller merges with its own gather + owner rule —
    the scatter/merge half of the reference idiom.

    Implementation: NEIGHBOR exchange via ``all_to_all`` — each shard
    scatters its per-neighbor buffers into a [S, I] send matrix (row j =
    items for shard j), the collective transposes it across the axis,
    and row j of the result is what shard j sent me.  Traffic is
    O(S * I) per shard instead of the previous all_gather's
    O(S * K * I) broadcast — the difference between S=8 and S=64
    viability (VERDICT r2 comm-layer scaling item).
    """
    import jax
    import jax.numpy as jnp
    from ..utils.jaxcompat import axis_size

    K, I = send_idx.shape
    S = axis_size(axis_name)
    safe = jnp.clip(send_idx, 0, vals.shape[0] - 1)
    send = jnp.where(
        (send_idx >= 0).reshape(K, I + (vals.ndim - 1) * 0, *([1] *
                                (vals.ndim - 1))),
        vals[safe], 0) if vals.ndim > 1 else \
        jnp.where(send_idx >= 0, vals[safe], 0)
    tail = send.shape[2:]
    mat = jnp.zeros((S, I) + tail, send.dtype)
    mat = mat.at[jnp.where(nbr >= 0, nbr, S)].set(send, mode="drop",
                                                  unique_indices=True)
    recv_mat = jax.lax.all_to_all(mat, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True)
    recv = recv_mat[jnp.clip(nbr, 0, S - 1)]            # [K, I, ...]
    valid = (nbr >= 0)
    if vals.ndim > 1:
        valid = valid.reshape(K, *([1] * (recv.ndim - 1)))
    else:
        valid = valid[:, None]
    return jnp.where(valid, recv, 0)


def halo_exchange_grouped(vals, send_idx, nbr, G: int,
                          axis_name: str = "shard"):
    """Grouped halo exchange: G logical shards per device (the
    groups x shards composition, grpsplit_pmmg.c:1551 role).

    Logical shard ``l`` lives on device ``l // G`` at slot ``l % G``;
    ``nbr`` carries LOGICAL shard ids.  Routing is (dest_device,
    dest_slot): each device scatters its per-(group, neighbor) rows into
    a [S, G, G, I] send block — mat[dd, g, ds] = my group g's items for
    slot ds of device dd — and ONE ``all_to_all`` transposes the device
    axis, after which recv_mat[sd, sg, g] is what logical shard
    sd*G+sg sent my group g.  Same-device neighbor pairs ride the
    self-row of the tiled collective.  Traffic carries a G^2 slot
    factor; the exchange runs on interface-sized I between outer
    iterations, where simplicity beats compaction.

    vals [G, P, ...]; send_idx [G, K, I]; nbr [G, K] logical ids.
    Returns recv [G, K, I, ...] (zeros on pads)."""
    import jax
    import jax.numpy as jnp
    from ..utils.jaxcompat import axis_size

    Gk, K, I = send_idx.shape
    assert Gk == G
    S = axis_size(axis_name)
    P_ = vals.shape[1]
    safe = jnp.clip(send_idx, 0, P_ - 1)                 # [G,K,I]
    g_ar = jnp.arange(G)[:, None, None]
    gath = vals[jnp.broadcast_to(g_ar, send_idx.shape), safe]
    vmask = (send_idx >= 0)
    if gath.ndim > 3:
        vmask = vmask.reshape(G, K, I, *([1] * (gath.ndim - 3)))
    send = jnp.where(vmask, gath, 0)                     # [G,K,I,...]
    tail = send.shape[3:]
    dd = jnp.where(nbr >= 0, nbr // G, S)                # [G,K]
    ds = jnp.where(nbr >= 0, nbr % G, 0)
    mat = jnp.zeros((S, G, G, I) + tail, send.dtype)
    mat = mat.at[dd, jnp.broadcast_to(jnp.arange(G)[:, None], (G, K)),
                 ds].set(send, mode="drop", unique_indices=True)
    recv_mat = jax.lax.all_to_all(mat, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True)
    # recv_mat[sd, sg, my_g, I, ...]
    sd = jnp.clip(nbr // G, 0, S - 1)
    sg = jnp.clip(nbr % G, 0, G - 1)
    recv = recv_mat[sd, sg,
                    jnp.broadcast_to(jnp.arange(G)[:, None], (G, K))]
    valid = (nbr >= 0)
    valid = valid.reshape(G, K, *([1] * (recv.ndim - 2)))
    return jnp.where(valid, recv, 0)


def packed_halo_rows(nbr: np.ndarray, G: int,
                     occupancy: float | None = None,
                     state: dict | None = None) -> int | None:
    """Per-device-pair packed row budget for
    :func:`halo_exchange_grouped_packed`, or None when the dense
    [S, G, G, I] block should be kept.

    ``nbr``: [S*G, K] LOGICAL neighbor table (host numpy).  The packed
    layout ships one row per actual (group, neighbor) entry instead of
    a dense G x G tile per device pair, so it wins exactly when the
    group-neighbor structure is sparse.  Decision = measured occupancy:
    take the max over (device, dest device) of the actual entry count;
    if it exceeds ``occupancy * G^2`` (default 0.75, knob
    PARMMG_HALO_PACK_OCC) the dense tile is at least as tight and the
    caller keeps it.  The returned budget is BUCKETED on the geo ladder
    (compile governor: per-pair counts drift every migration; an exact
    M would key a fresh compile per iteration).

    ``state``: optional mutable dict carried by the caller across
    comm-table rebuilds — the layout decision then becomes STICKY with
    a hysteresis margin (knob PARMMG_HALO_PACK_HYST, default 0.05):
    once a layout is chosen it only flips when the occupancy ratio
    crosses the threshold by more than the margin, so a borderline mesh
    cannot flip-flop dense<->packed compiles on every rebuild.  The
    packed row budget M itself still re-buckets freely (the geo ladder
    is the anti-churn layer for the WIDTH; hysteresis is the anti-churn
    layer for the LAYOUT).  Stateless decide-per-call when None.
    """
    import os
    if G <= 1:
        return None
    if occupancy is None:
        occupancy = float(os.environ.get("PARMMG_HALO_PACK_OCC", "0.75"))
    S_l, K = nbr.shape
    S = S_l // G
    # one-pull .tolist() then pure-Python counting: no per-entry
    # device-array int() coercions inside the loop (lint R2)
    counts = [[0] * max(S, 1) for _ in range(S)]
    for l, row in enumerate(nbr.tolist()):
        for b in row:
            if b >= 0:
                counts[l // G][b // G] += 1
    mx = max((c for row in counts for c in row), default=0)
    if mx == 0:
        return None           # no traffic: no evidence, state untouched
    r = mx / float(G * G)
    use_packed = r <= occupancy
    if state is not None:
        hyst = float(os.environ.get("PARMMG_HALO_PACK_HYST", "0.05"))
        prev = state.get("layout")
        if prev == "packed":
            use_packed = r <= occupancy + hyst
        elif prev == "dense":
            use_packed = r <= occupancy - hyst
    M = None
    if use_packed:
        from ..utils.compilecache import bucket
        M = bucket(mx, floor=2, scheme="geo")
        # after rounding, the packed layout must still beat the dense
        # tile (headers ride along; require a strict row win)
        if M >= G * G:
            M = None
    # metrics spine: layout decisions + hysteresis flips are the churn
    # signal the BENCH/SCALE metrics block surfaces (obs/metrics.py)
    from ..obs.metrics import REGISTRY
    layout = "packed" if M is not None else "dense"
    REGISTRY.counter("halo.layout_packed" if M is not None
                     else "halo.layout_dense").inc()
    if state is not None:
        prev = state.get("layout")
        if prev is not None and prev != layout:
            REGISTRY.counter("halo.layout_flips").inc()
        state["layout"] = layout
    return M


def halo_exchange_grouped_packed(vals, send_idx, nbr, G: int, M: int,
                                 axis_name: str = "shard"):
    """Packed grouped halo exchange: identical contract to
    :func:`halo_exchange_grouped` without the G^2 dense slot factor.

    Each device scatters its actual (group, neighbor) rows into a
    [S, M, I] send block (row budget ``M`` from
    :func:`packed_halo_rows`), with a parallel [S, M, 2] header block
    carrying (dest_slot, src_group) so the receiver can unpack without
    reconstructing the sender's packing order.  ONE ``all_to_all`` per
    block transposes the device axis; the receiver routes each incoming
    row to its (group, k) table entry by matching the header against
    its own LOGICAL ``nbr`` table (pair uniqueness makes the scatter
    collision-free).  Same-device neighbor pairs ride the self-row of
    the tiled collective, exactly like the dense path.

    Traffic per device: O(S * M * I) payload + O(S * M) headers versus
    the dense O(S * G^2 * I) — the wire win the G>1 path needs before
    it can default at scale.

    vals [G, P, ...]; send_idx [G, K, I]; nbr [G, K] logical ids.
    Returns recv [G, K, I, ...] (zeros on pads)."""
    import jax
    import jax.numpy as jnp
    from ..utils.jaxcompat import axis_size

    Gk, K, I = send_idx.shape
    assert Gk == G
    S = axis_size(axis_name)
    P_ = vals.shape[1]
    safe = jnp.clip(send_idx, 0, P_ - 1)                 # [G,K,I]
    g_ar = jnp.arange(G)[:, None, None]
    gath = vals[jnp.broadcast_to(g_ar, send_idx.shape), safe]
    vmask = (send_idx >= 0)
    if gath.ndim > 3:
        vmask = vmask.reshape(G, K, I, *([1] * (gath.ndim - 3)))
    send = jnp.where(vmask, gath, 0)                     # [G,K,I,...]
    tail = send.shape[3:]

    valid = (nbr >= 0)                                   # [G,K]
    dd = jnp.where(valid, nbr // G, S).reshape(G * K)    # dest device
    ds = jnp.where(valid, nbr % G, 0).reshape(G * K)     # dest slot
    sg = jnp.broadcast_to(jnp.arange(G, dtype=nbr.dtype)[:, None],
                          (G, K)).reshape(G * K)
    # pack slot = rank of the entry within its destination device, in
    # (group, k) flat order — deterministic, pads sort last (dd = S)
    order = jnp.argsort(dd, stable=True)
    start = jnp.searchsorted(dd[order], jnp.arange(S, dtype=dd.dtype))
    pos = jnp.zeros(G * K, jnp.int32).at[order].set(
        jnp.arange(G * K, dtype=jnp.int32), unique_indices=True)
    slot = pos - start[jnp.clip(dd, 0, S - 1)]
    slot = jnp.where(valid.reshape(G * K), slot, M)      # pads dropped

    pay = jnp.zeros((S, M, I) + tail, send.dtype)
    pay = pay.at[dd, slot].set(
        send.reshape(G * K, I, *tail), mode="drop")
    hdr = jnp.full((S, M, 2), -1, nbr.dtype)
    hdr = hdr.at[dd, slot].set(jnp.stack([ds, sg], axis=-1), mode="drop")

    recv_pay = jax.lax.all_to_all(pay, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True)
    recv_hdr = jax.lax.all_to_all(hdr, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True)

    # unpack: row (sd, m) came from logical shard sd*G + hdr.src_group
    # and targets my group hdr.dest_slot; its k is the unique entry of
    # my nbr row carrying that logical id (K is small and bucketed)
    sd = jnp.arange(S, dtype=nbr.dtype)[:, None]         # [S,1]
    tgt_g = recv_hdr[..., 0]                             # [S,M]
    src_l = sd * G + recv_hdr[..., 1]
    rvalid = tgt_g >= 0
    tgt_gc = jnp.clip(tgt_g, 0, G - 1)
    eq = nbr[tgt_gc] == src_l[..., None]                 # [S,M,K]
    hask = jnp.any(eq, axis=-1) & rvalid
    kk = jnp.argmax(eq, axis=-1).astype(jnp.int32)       # [S,M]
    out = jnp.zeros((G, K, I) + tail, send.dtype)
    out = out.at[jnp.where(hask, tgt_gc, G),
                 jnp.where(hask, kk, 0)].set(recv_pay, mode="drop")
    return out


def merge_owner_max(vals, send_idx, recv):
    """Merge received neighbor values into local entity values with the
    max rule (the reference's max-rank/max-value priority merges)."""
    import jax.numpy as jnp
    K, I = send_idx.shape
    flat_idx = jnp.where(send_idx >= 0, send_idx, vals.shape[0]).reshape(-1)
    upd = recv.reshape(K * I, *recv.shape[2:])
    return vals.at[flat_idx].max(upd, mode="drop")


# ---------------------------------------------------------------------------
# global numbering (PMMG_Compute_verticesGloNum, libparmmg.c:923)
# ---------------------------------------------------------------------------
def global_node_numbering(comms: InterfaceComms,
                          npoin: list[int]) -> list[np.ndarray]:
    """1-based global vertex numbers per shard.  Owner = max incident
    shard; per-shard owned counts -> exclusive scan offsets (the
    MPI_Allgather + prefix of the reference); non-owners receive the
    owner's number through the node comm tables."""
    S = len(npoin)
    owned = [comms.owner[s] == s for s in range(S)]
    counts = np.array([int(o.sum()) for o in owned])
    offs = np.concatenate([[0], np.cumsum(counts)[:-1]])
    glo = []
    for s in range(S):
        g = np.zeros(npoin[s], np.int64)
        g[owned[s]] = offs[s] + 1 + np.arange(counts[s])
        glo.append(g)
    # propagate owner numbers to the other copies via the comm tables:
    # item order matches pairwise, so positional transfer is exact
    for s in range(S):
        for k in range(comms.nbr.shape[1]):
            b = int(comms.nbr[s, k])
            if b < 0:
                continue
            cnt = int(comms.node_cnt[s, k])
            mine = comms.node_idx[s, k, :cnt]
            kp = int(np.where(comms.nbr[b] == s)[0][0])
            theirs = comms.node_idx[b, kp, :cnt]
            take = glo[b][theirs] > 0
            upd = (glo[s][mine] == 0) & take
            g = glo[s]
            g[mine[upd]] = glo[b][theirs][upd]
    return glo


def global_triangle_numbering(comms: InterfaceComms, ntria_owned:
                              list[int]) -> np.ndarray:
    """Offsets for boundary-triangle numbering (two-phase scheme of
    PMMG_Compute_trianglesGloNum, libparmmg.c:464): owned boundary tris
    first, then interface tris numbered by their owner side."""
    counts = np.asarray(ntria_owned)
    return np.concatenate([[0], np.cumsum(counts)[:-1]])


# ---------------------------------------------------------------------------
# the chkcomm oracle
# ---------------------------------------------------------------------------
def check_node_comms(comms: InterfaceComms,
                     verts: list[np.ndarray]) -> dict:
    """Coordinate-echo invariant check (PMMG_check_extNodeComm,
    chkcomm_pmmg.c:815): for every pair, the two ordered item lists must
    reference identical coordinates within a bbox-scaled epsilon."""
    S = comms.nbr.shape[0]
    allv = np.concatenate([v for v in verts if len(v)]) \
        if any(len(v) for v in verts) else np.zeros((1, 3))
    scale = max(1e-30, float(np.abs(allv).max()))
    bad = 0
    checked = 0
    for s in range(S):
        for k in range(comms.nbr.shape[1]):
            b = int(comms.nbr[s, k])
            if b < 0 or b < s:
                continue
            cnt = int(comms.node_cnt[s, k])
            kp_arr = np.where(comms.nbr[b] == s)[0]
            if len(kp_arr) == 0:
                bad += cnt
                continue
            kp = int(kp_arr[0])
            if int(comms.node_cnt[b, kp]) != cnt:
                bad += abs(int(comms.node_cnt[b, kp]) - cnt)
            m = min(cnt, int(comms.node_cnt[b, kp]))
            a_ids = comms.node_idx[s, k, :m]
            b_ids = comms.node_idx[b, kp, :m]
            d = np.abs(verts[s][a_ids] - verts[b][b_ids]).max(axis=1)
            bad += int((d > 1e-9 * scale).sum())
            checked += m
    return {"items_checked": checked, "mismatch": bad}


def check_face_comms(comms: InterfaceComms, tets: list[np.ndarray],
                     verts: list[np.ndarray]) -> dict:
    """Face version of the oracle (PMMG_check_extFaceComm,
    chkcomm_pmmg.c:1027): matched face barycenters must coincide."""
    S = comms.nbr.shape[0]
    bad = checked = 0
    for s in range(S):
        for k in range(comms.nbr.shape[1]):
            b = int(comms.nbr[s, k])
            if b < 0 or b < s:
                continue
            cnt = int(comms.face_cnt[s, k])
            kp = int(np.where(comms.nbr[b] == s)[0][0])
            m = min(cnt, int(comms.face_cnt[b, kp]))

            def bary(shard, slots):
                t, f = slots // 4, slots % 4
                tri = tets[shard][t][np.arange(len(t))[:, None],
                                     IDIR[f]]
                return verts[shard][tri].mean(axis=1)

            ba = bary(s, comms.face_idx[s, k, :m])
            bb = bary(b, comms.face_idx[b, kp, :m])
            d = np.abs(ba - bb).max(axis=1)
            bad += int((d > 1e-9).sum())
            checked += m
    return {"items_checked": checked, "mismatch": bad}
