"""Quiet-group scheduler: convergence-aware active-set compaction.

SCALE_r03 measured the grouped adapt pass at 97.6% of a 1M-tet run, and
its per-cycle op counts collapse across cycles — yet the chunked
dispatch loop of ``grouped_adapt_pass`` re-shipped EVERY group through
EVERY cycle block (host gather + device upload + compute + counter sync
+ download), even for groups that posted zero ops blocks ago.  The
per-group counts were summed away before anyone looked at them.

This module is the host-side bookkeeping that fixes that: per-group
counts mark groups *quiet*, and from then on the active group indices
are compacted into dense chunks — the SAME compiled ``[chunk, ...]``
program runs on gathered slices (zero new shapes, zero new
compile-ledger families), it just runs on fewer of them.

Exactness contract (why skipping is bit-for-bit, not approximate):

- group seams are frozen (MG_PARBDY — the split_to_shards freeze
  contract), so a group that posts zero ops cannot be re-dirtied by its
  neighbors within a pass; the reference's rank-level loop
  (libparmmg1.c:636-948) has the same convergence structure;
- every wave kernel is a deterministic function of (mesh, met) alone —
  the smoothing wave's hash rotation only permutes priorities among
  vertices that already pass the improvement gate, and the gate is
  geometry-only — so a block that posts zero split+collapse+swap+move
  leaves the group state a *fixed point*: re-running any weaker-or-equal
  block on it is byte-identity;
- "weaker-or-equal" is tracked as two quiet levels, because the cycle
  scheduler emits two block classes: prescreen-ON sizing blocks and the
  final prescreen-OFF polish blocks whose exact split veto re-evaluates
  candidates the approximate prescreen over-vetoed (ops/split.py, ADVICE
  r3).  Zero under a swap-inclusive prescreen-on block only proves the
  group inert for further prescreen-on blocks (``LEVEL_PRE``); zero
  under a swap-inclusive block containing a prescreen-off cycle proves
  it inert for everything (``LEVEL_FULL``).  Swap kernels and smoothing
  do not read the prescreen, so the pres-off proof subsumes the pres-on
  one;
- a capacity regrow invalidates every proof: the top-K wave budgets
  scale with capT, so a group whose winners were budget-truncated at the
  old capacity can post fresh ops at the new one — ``on_regrow``
  reactivates the full set (truncated winners must rerun), exactly like
  the always-dispatch path's block rerun;
- dead pad groups (the chunk-alignment padding of grouped_adapt_pass)
  are fixed points by construction (all masks False) and are never
  dispatched.

**Device-resident quiet masks** (PR 12, ROADMAP 1a): host-side
compaction makes quiet groups cost zero *dispatches*; the device mask
makes them cost ~zero *on device* too.  Every grouped block program
(`groups._group_block`, `groups._group_polish_block`, the dist-path
`dist.dist_adapt_block`) takes a per-slot bool mask and wraps its
``lax.map`` group body in ``lax.cond`` (ops/adapt.py ``active=``): an
inactive slot returns its state unchanged with zero counts instead of
running the split/collapse/swap/smooth wave math.  This is exact by the
SAME fixed-point argument as dispatch skipping — a quiet state's
recompute IS the identity — and carries the same two proof levels:
:meth:`QuietGroupScheduler.block_mask` masks ``level >= LEVEL_PRE``
slots only under prescreen-ON blocks and ``level >= LEVEL_FULL`` slots
under any block.  Three mask sources:

- **unchunked dispatches** (``PARMMG_GROUP_CHUNK=0``, where compaction
  cannot change the dispatch shape): ``block_mask`` — the only skip
  mechanism this layout has;
- **padded tail rows** of compacted chunk plans (:func:`pad_mask` via
  ``groups._pipeline_chunks``): the repeat-padded duplicate rows used
  to compute redundantly and be discarded at writeback — now they are
  cond-skipped (serving cohorts included);
- **the SPMD dist path** (``dist.run_adapt_cycles``): a per-logical-
  shard quiet level lives ON DEVICE (int8, threaded through the block
  program, updated by the same swap-inclusive zero-count rule) so the
  G>1 ``lax.map`` body skips converged groups with zero host syncs.

The mask is ALWAYS an argument of the compiled programs (an all-true
mask when disabled), so toggling it mints zero new compile families —
asserted by the ``run_tests.sh --ledger`` grouped_sched_gate.
``PARMMG_DEVICE_MASK=0`` disables the on-device skipping;
``PARMMG_GROUP_SCHED=0`` is the escape hatch back to always-dispatch
(and also forces all-true masks).
"""
from __future__ import annotations

import numpy as np

LEVEL_ACTIVE = 0   # must dispatch
LEVEL_PRE = 1      # proven zero under a swap-inclusive prescreen-ON block
LEVEL_FULL = 2     # proven zero under a swap-inclusive prescreen-OFF block


def sched_enabled() -> bool:
    """PARMMG_GROUP_SCHED knob (default on)."""
    import os
    return os.environ.get("PARMMG_GROUP_SCHED", "1") != "0"


def device_mask_enabled() -> bool:
    """PARMMG_DEVICE_MASK knob (default on): device-resident quiet
    masks — ``lax.cond``-skip the wave math for quiet/pad group slots
    (module docstring).  0 = compute every slot (masks all-true; same
    compiled programs)."""
    import os
    return os.environ.get("PARMMG_DEVICE_MASK", "1") != "0"


def cadence_enabled() -> bool:
    """PARMMG_SMOOTH_CADENCE knob (default on): quality-triggered
    smoothing cadence — adapt_cycle_impl skips ``smooth_wave`` on a
    cycle whose topology counts are all zero AND whose previous cycle's
    smoothing already moved nothing (an exact fixed point: the claim
    resolution in smooth_wave guarantees nmoved==0 iff no vertex can
    improve, and that emptiness is wave-rotation-invariant; see the
    adapt_cycle_impl docstring for the full argument).  The enable is
    threaded as a TRACED device scalar (like the quiet mask), so
    toggling it mints zero new ``groups.*`` compile families —
    asserted by the ``run_tests.sh --ledger`` hotloop_knob_gate."""
    import os
    return os.environ.get("PARMMG_SMOOTH_CADENCE", "") != "0"


def pad_mask(chunk: int, nreal: int) -> np.ndarray:
    """[chunk] bool device-mask for a compacted chunk plan: the first
    ``nreal`` rows are real, the repeat-padded tail rows are masked off
    (their compute was always discarded at writeback — chunk_plans).
    All-true when PARMMG_DEVICE_MASK=0 — or under the
    PARMMG_GROUP_SCHED=0 escape hatch, which forces the full legacy
    behavior (module docstring) — so the disabled path computes
    exactly what it always did."""
    if not (sched_enabled() and device_mask_enabled()):
        return np.ones(chunk, bool)
    m = np.zeros(chunk, bool)
    m[:nreal] = True
    return m


def quiet_rows(counts: np.ndarray) -> np.ndarray:
    """Per-row fixed-point witness from a dispatched block's counts.

    ``counts``: [n, nblk, >=5] — reads ONLY columns 0..4, so the
    9-wide rows of the topo-threaded block (col 8 = dirty-tet count,
    ops/topo_incr) satisfy the contract unchanged.  Row ``i`` is quiet
    when the WHOLE block was a no-op for it — zero
    split+collapse+swap+move AND zero overflow (a truncated winner set
    witnesses nothing).  Shared by
    :meth:`QuietGroupScheduler.record_block` (group granularity) and
    the serving pool (serve/pool.py, tenant granularity): one rule, one
    exactness argument (module docstring)."""
    # host-by-contract: the drain already pulled the block counters to
    # numpy ([n, nblk, >=5]) — no conversion, no possible device sync
    n = counts.shape[0]
    return counts[..., :5].reshape(n, -1).sum(axis=1,
                                              dtype=np.int64) == 0


def chunk_plans(act: np.ndarray, chunk: int) -> list:
    """Compact active group indices (an ndarray) into dense
    [chunk]-sized plans.

    Returns [(idx_exec [chunk], nreal)]: a short tail plan is padded by
    repeating its last real index so every dispatch keeps the compiled
    [chunk, ...] shape; the duplicate rows are masked off on device
    (:func:`pad_mask`) and only the first ``nreal`` rows are written
    back."""
    plans = []
    for i in range(0, len(act), chunk):
        idx = act[i:i + chunk]
        nreal = len(idx)
        if nreal < chunk:
            idx = np.concatenate(
                [idx, np.repeat(idx[-1:], chunk - nreal)])
        plans.append((idx, nreal))
    return plans


class QuietGroupScheduler:
    """Active-set bookkeeping for one grouped adapt pass.

    ``g_exec`` >= ``ngroups``: the pad-aligned executable group count
    (pad groups are born quiet).  ``chunk`` = groups per dispatch
    (0 = one unchunked dispatch; compaction then cannot change the
    dispatch shape and the scheduler only records the trajectory)."""

    def __init__(self, ngroups: int, g_exec: int, chunk: int,
                 enabled: bool | None = None):
        if enabled is None:
            enabled = sched_enabled()
        self.ngroups = int(ngroups)
        self.g_exec = int(g_exec)
        self.chunk = int(chunk)
        # compaction needs per-chunk dispatches to have fewer of them
        self.enabled = bool(enabled) and self.chunk > 0
        # the device mask works at ANY chunking (including unchunked,
        # where it is the only skip mechanism — module docstring)
        self.mask_on = bool(enabled) and device_mask_enabled()
        self.level = np.zeros(self.g_exec, np.int8)
        self.level[self.ngroups:] = LEVEL_FULL     # dead pad groups
        self.dispatches = 0
        self.saved_dispatches = 0
        self.skipped_group_blocks = 0
        # group-slot executions skipped ON DEVICE by the lax.cond mask
        # (unchunked quiet slots + padded tail rows of chunk plans)
        self.cond_skipped = 0
        self.active_per_block: list[int] = []

    # ---- block planning --------------------------------------------------
    def _skip_level(self, pres_all_on: bool) -> int:
        return LEVEL_PRE if pres_all_on else LEVEL_FULL

    def plan_block(self, pres_all_on: bool):
        """Plan one cycle block: returns (act, plans).

        ``act``: group indices to dispatch, in plan order.  ``plans``:
        [(idx_exec, nreal)] chunk plans (empty when every group is
        quiet).  Dispatch/saved counters and the active-group trajectory
        are accounted here; the always-dispatch baseline is
        ceil(g_exec / chunk) dispatches per block."""
        skip = self._skip_level(pres_all_on)
        if self.enabled:
            act = np.where(self.level < skip)[0]
        else:
            act = np.arange(self.g_exec)
        # level is host scheduler state (np.int8): count, then int() a
        # bound host scalar — nothing here can sync a device value
        n_active = np.count_nonzero(self.level[:self.ngroups] < skip)
        self.active_per_block.append(int(n_active))
        if self.chunk:
            base = -(-self.g_exec // self.chunk)
            plans = chunk_plans(act, self.chunk) if len(act) else []
        else:
            base = 1
            plans = [(act, len(act))] if len(act) else []
        self.dispatches += len(plans)
        # saved vs the always-dispatch baseline, which ships the dead
        # pad groups too — skipping those IS a real dispatch saving
        self.saved_dispatches += base - len(plans)
        # ...but the skipped-GROUP counter reports convergence, so it
        # counts REAL groups only (pads are dead at birth, not wins)
        n_real = np.count_nonzero(act < self.ngroups)
        self.skipped_group_blocks += self.ngroups - int(n_real)
        return act, plans

    def block_mask(self, pres_all_on: bool) -> np.ndarray:
        """[g_exec] bool device-mask for an UNCHUNKED dispatch: quiet
        slots at or above this block's skip level are cond-skipped on
        device (the only skip mechanism when compaction cannot change
        the dispatch shape).  All-true when the mask is disabled.
        Accounts the skipped slots in ``cond_skipped``."""
        if not self.mask_on:
            return np.ones(self.g_exec, bool)
        m = self.level < self._skip_level(pres_all_on)
        # lint: ok(R2) — m is the host scheduler state (numpy bool);
        # counting the masked slots syncs nothing
        self.cond_skipped += int(np.sum(~m))
        return m

    def note_plan_pads(self, plans: list) -> None:
        """Account the repeat-padded tail rows of compacted chunk plans
        that the device mask skipped (``pad_mask`` — one entry per
        padded row per dispatch).  No-op whenever ``pad_mask`` returns
        all-true (mask off, or the sched=0 escape hatch)."""
        if not (sched_enabled() and device_mask_enabled()):
            return
        for idx, nreal in plans:
            self.cond_skipped += len(idx) - nreal

    # ---- quiet marking ---------------------------------------------------
    def record_block(self, act: np.ndarray, counts: np.ndarray,
                     swap_inclusive: bool, pres_all_on: bool) -> None:
        """Mark groups quiet from a dispatched block's per-group counts.

        ``counts``: [n_act, nblk, >=5] (split, collapse, swap, moved,
        overflow, ...).  A group is quiet only when the WHOLE block was
        a no-op for it — including moves (the fixed-point requirement)
        and overflow (a truncated winner set witnesses nothing) — and
        the block was swap-inclusive (``swap_inclusive`` = any swap
        cycle, or -noswap, mirroring the global convergence rule).

        The ``deferred`` column (6) is deliberately NOT part of the
        proof: deferred marks top-K budget cuts, and the budgets are
        constant across blocks (budget_div=8; only a capacity regrow
        changes them, which reactivates everything).  Split, collapse
        and swap take no wave input, so on an unchanged state they
        re-select the identical (possibly empty) winner set every
        block — a deferred-but-zero-op state is still a fixed point.
        The only wave-rotated kernel is smoothing, and moved == 0
        proves its geometry-only improvement gate rejects every
        vertex, which no later wave's priority rotation can change."""
        if not swap_inclusive or len(act) == 0:
            return
        zero = quiet_rows(counts)
        lvl = LEVEL_PRE if pres_all_on else LEVEL_FULL
        # act comes from plan_block (np.where/arange): already host
        sel = act[zero]
        self.level[sel] = np.maximum(self.level[sel], lvl)

    def on_regrow(self) -> None:
        """Capacity regrow: every proof is stale (the top-K budgets
        scale with capT — budget-truncated winners must rerun).  Pad
        groups stay quiet (dead at any capacity)."""
        self.level[:self.ngroups] = LEVEL_ACTIVE


# ---------------------------------------------------------------------------
# PARMMG_GROUP_CHUNK auto-tune (ROADMAP item 1b, lightweight host side)
# ---------------------------------------------------------------------------
def calibrate_dispatch_overhead(acc: dict, count: dict,
                                chunk: int) -> float | None:
    """Measured per-dispatch overhead in GROUP-COMPUTE UNITS from the
    ``_pipeline_chunks`` segment timings (the PR-8 Timers spans) — the
    calibration that replaces :func:`recommend_group_chunk`'s hand-set
    ``dispatch_overhead=1.0`` default (ROADMAP 1b validation, host
    side).

    ``acc``/``count`` are the local pipeline registry's accumulators
    (keys upload/compute/download/writeback; one count per dispatch).
    overhead = (upload + download + writeback seconds per dispatch) /
    (compute seconds per GROUP) — i.e. how many groups' worth of
    compute one extra dispatch costs, exactly the unit the cost model
    ``ceil(a/c) * (c + overhead)`` wants.  Under the double-buffered
    pipeline the recorded compute segment is the RESIDUAL stall (the
    overlap hides part of it), which biases the per-group unit low and
    the overhead HIGH — i.e. toward larger chunks, the direction that
    cannot recommend pathological tiny dispatches.  Returns ``None``
    when the segments carry no signal (no dispatches, zero compute) —
    the caller keeps the hand-set default then."""
    disp = count.get("compute", 0)
    if not disp or chunk <= 0:
        return None
    over = (acc.get("upload", 0.0) + acc.get("download", 0.0)
            + acc.get("writeback", 0.0)) / disp
    comp = acc.get("compute", 0.0) / disp / chunk
    if comp <= 0.0 or over <= 0.0:
        return None
    return over / comp


def recommend_group_chunk(traj, g_exec: int,
                          dispatch_overhead: float = 1.0) -> int:
    """Recommend a PARMMG_GROUP_CHUNK from a recorded
    ``extra.active_groups_per_block`` trajectory.

    Cost model per block with ``a`` active groups at chunk ``c``:
    ``ceil(a/c) * (c + dispatch_overhead)`` in group-compute units —
    every dispatch ships a full [c, ...] slice (short tails are padded
    by repeating rows — pad_mask cond-skips their compute, but the
    transfer is still paid), plus a per-dispatch overhead (host gather
    + upload + counter sync; ~one group-block of useful work on the
    tunneled TPU, the hand-set default).  Pass the MEASURED value from
    :func:`calibrate_dispatch_overhead` when a pipeline has run — the
    grouped pass does, recording the calibration in
    ``sched_extra["chunk_overhead_units"]`` and the bench/SCALE
    artifact extras.  Smaller chunks track the decaying active set
    with less padding waste; larger chunks amortize the dispatch
    overhead — exactly the trade named in ROADMAP item 1.

    Candidates are the pow2 ladder 1..g_exec (so the recommendation
    lands on a small set of compiled [chunk, ...] shape families); ties
    prefer the LARGER chunk (fewer dispatches at equal modeled cost).
    Returns 0 (= unchunked) for an empty/degenerate trajectory or when
    the winner covers every group anyway — the group_chunk() "no
    chunking" convention."""
    a = [int(v) for v in (traj or []) if int(v) > 0]
    if not a or g_exec <= 1:
        return 0
    cands = []
    c = 1
    while c < g_exec:
        cands.append(c)
        c *= 2
    cands.append(g_exec)

    def cost(c: int) -> float:
        return sum(-(-ab // c) * (c + dispatch_overhead) for ab in a)

    best = max((c for c in cands
                if cost(c) == min(cost(x) for x in cands)))
    return 0 if best >= g_exec else best


# last recommendation computed by a grouped pass in this process
# (module-level on purpose: the steady-state loop re-enters
# grouped_adapt_pass every outer iteration, and PARMMG_GROUP_CHUNK=auto
# reads the newest trajectory-derived value at the NEXT pass — no
# behavior change unless the operator opts in with "auto").  Only the
# newest value is kept: a long-lived serving process notes one per
# pass forever, and only [-1] is ever read.
_CHUNK_RECOMMENDATION: list[int] = []


def note_chunk_recommendation(chunk: int) -> None:
    _CHUNK_RECOMMENDATION[:] = [int(chunk)]


def auto_chunk_recommendation() -> int | None:
    """Newest recorded recommendation, or None before any grouped pass
    has run (group_chunk then falls back to the backend default)."""
    return _CHUNK_RECOMMENDATION[-1] if _CHUNK_RECOMMENDATION else None
