"""Incremental shard-to-shard interface migration — no whole-mesh merge.

The reference displaces group interfaces between outer iterations by moving
only the affected groups over the wire with third-party communicator repair
(``PMMG_part_moveInterfaces`` moveinterfaces_pmmg.c:1306,
``PMMG_transfer_all_grps`` distributegrps_pmmg.c:1631-1841, wire format
mpipack_pmmg.c:1067).  The round-1 TPU path instead merged ALL shards into a
global host mesh and re-split from scratch every outer iteration — correct,
but O(global mesh) host round trips per iteration.

This module is the TPU-native replacement:

- **labels on device**: the advancing-front flood (bigger shard's color
  invades the smaller across the frozen interface, ``nlayers`` tet-ball
  waves — PMMG_get_ifcDirection/PMMG_mark_boulevolp semantics) runs as a
  jitted, vmapped program over the stacked shard axis.  The only cross-shard
  information it needs — which shards share each interface vertex and their
  sizes — is already static in the comm tables, so the flood needs no
  collective at all: one scatter-max seeds neighbor priorities at interface
  vertices, then each wave is a gather/scatter pair.
- **data movement O(band)**: only the tets/vertices of the displaced
  interface band travel host<->device; shard buffers are updated in place
  by sparse scatters (slot ids are stable, the high-watermark allocator of
  the waves never reuses freed slots).  No global mesh is materialized;
  the per-shard host views used to rebuild the interface are the same pull
  the cross-shard analysis refresh already pays.
- **identity by global id**: vertices are welded across shards by the
  session's persistent global numbering (split-time ids extended with fresh
  ids for adapt-created vertices) — the exact-match analogue of the
  reference's global node numbering (libparmmg.c:923), more robust than
  coordinate matching.
- **freeze/unfreeze in place**: entities that leave the interface drop the
  ``MG_PARBDY|MG_BDY|MG_REQ|MG_NOSURF`` freeze (keeping true-boundary via
  ``MG_PARBDYBDY`` and user-required via ``MG_REQ`` without ``MG_NOSURF`` —
  tag_pmmg.c:126-207 untag semantics); entities that join the
  interface get the freeze (tag_pmmg.c:39-124).

Known deviations from the reference (documented, not hidden): no
contiguity/reachability repair on the displaced partition (the flood
advances a connected front, which keeps parts connected in practice;
the merged-path partitioner still runs ``fix_contiguity``), and the
donor floor ``ne_min`` keeps an arbitrary prefix of moves rather than the
reference's first-come order.
"""
from __future__ import annotations

import dataclasses
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from ..core.mesh import Mesh
from ..obs import trace as otrace
from ..core.constants import (
    IDIR, IARE, FACE_EDGES, MG_BDY, MG_REQ, MG_NOSURF, MG_PARBDY,
    MG_PARBDYBDY, PARBDY_TAGS)
from .comms import InterfaceComms


# ---------------------------------------------------------------------------
# device-side advancing-front labels
# ---------------------------------------------------------------------------
_SIZE_CLAMP = 1 << 22   # priority = min(size, clamp) * S + color stays int32


def _flood_one(tet, tmask, vmask, node_idx, nbr, sizes, me, n_shards: int,
               nlayers: int):
    """Per-shard advancing-front labels (vmapped over the stacked axis).

    Priority of color c = (tet count of shard c, c) lexicographic, packed
    into one int32 (sizes clamped at 2^22 — beyond that 'bigger' is a tie
    and the id breaks it, which matches the reference's intent).  A tet
    flips to the strongest color seen at its corners when that color beats
    its own; flipped tets push their color to their corners — one tet-ball
    layer per wave, exactly ``PMMG_part_moveInterfaces``'s front advance.
    """
    capP = vmask.shape[0]
    S = n_shards

    def pri_of(c):
        return jnp.minimum(sizes[jnp.clip(c, 0, S - 1)], _SIZE_CLAMP) * S + c

    vpri = jnp.where(vmask, pri_of(me), -1)
    # seed: every interface vertex sees the priorities of the OTHER shards
    # that share it — static knowledge from the node comm tables
    idx = node_idx.reshape(-1)
    nb_pri = jnp.repeat(jnp.where(nbr >= 0, pri_of(nbr), -1),
                        node_idx.shape[1])
    safe = jnp.where((idx >= 0) & (nb_pri >= 0), idx, capP)
    vpri = vpri.at[safe].max(nb_pri, mode="drop")

    label = jnp.full(tet.shape[0], me, jnp.int32)
    # front depth: wave index (1-based) at which each tet flipped away
    # from its home shard; 0 = never flipped.  Consumed by
    # enforce_ne_min so the donor floor reverts the DEEPEST layer first
    # and the retained moves stay a connected front
    # (moveinterfaces_pmmg.c:1343 keeps front order the same way).
    depth = jnp.zeros(tet.shape[0], jnp.int32)

    def wave(w, carry):
        vpri, label, depth = carry
        corner = vpri[jnp.clip(tet, 0, capP - 1)]            # [T,4]
        tp = jnp.max(corner, axis=1)
        better = tmask & (tp > pri_of(label))
        label = jnp.where(better, (tp % S).astype(jnp.int32), label)
        depth = jnp.where(better, w + 1, depth)
        # propagate the flipped color to the tet's corners
        lp = jnp.where(tmask, pri_of(label), -1)
        tgt = jnp.where(tmask[:, None], tet, capP).reshape(-1)
        vpri = vpri.at[tgt].max(jnp.repeat(lp, 4), mode="drop")
        return vpri, label, depth

    _, label, depth = jax.lax.fori_loop(0, nlayers, wave,
                                        (vpri, label, depth))
    return label, depth


from ..utils.compilecache import governed as _governed  # noqa: E402


@_governed("migrate.flood_labels", budget=2)
@partial(jax.jit, static_argnames=("n_shards", "nlayers"))
def flood_labels(stacked: Mesh, node_idx, nbr, sizes, n_shards: int,
                 nlayers: int = 2):
    """([S, capT] int32 target-shard label per tet, [S, capT] int32 flood
    depth — wave at which the tet flipped, 0 = kept).  Garbage on dead
    slots."""
    me = jnp.arange(n_shards, dtype=jnp.int32)
    return jax.vmap(
        lambda t, tm, vm, ni, nb, m: _flood_one(
            t, tm, vm, ni, nb, sizes, m, n_shards, nlayers)
    )(stacked.tet, stacked.tmask, stacked.vmask, node_idx, nbr, me)


# ---------------------------------------------------------------------------
# freeze / unfreeze tag semantics (numpy, applied to selected slots)
# ---------------------------------------------------------------------------
def _freeze_bits(tags: np.ndarray, is_edge_or_vert: bool) -> np.ndarray:
    """Interface freeze (split_to_shards contract; tag_pmmg.c:39-124)."""
    out = tags.copy()
    user_req = (out & MG_REQ) != 0
    true_bdy = (out & MG_BDY) != 0
    out |= PARBDY_TAGS
    if is_edge_or_vert:
        out[true_bdy] |= MG_PARBDYBDY
    out[user_req] &= ~np.uint32(MG_NOSURF)
    return out


def _unfreeze_bits(tags: np.ndarray, is_edge_or_vert: bool) -> np.ndarray:
    """Drop the freeze from entities leaving the interface (merge_shards /
    PMMG_updateTag untag contract, tag_pmmg.c:126-207).

    Deliberately does NOT set ``MG_OLDPARBDY`` (the reference's
    resetOldTag marker, tag_pmmg.c:211): the reference consumes it to
    target update_analys and to weight the group graph, but here the
    analysis refresh is global (refresh_shard_analysis re-derives every
    classification from the global numbering) and partition weights come
    from the metric — while a residual bit on formerly-interface
    faces/edges would poison every 'untagged cavity' guard (repair,
    weld, swap candidacy) exactly where the band needs remeshing most.
    """
    out = tags.copy()
    was_ifc = (out & MG_PARBDY) != 0
    user_req = was_ifc & ((out & MG_NOSURF) == 0) & ((out & MG_REQ) != 0)
    true_bdy = was_ifc & ((out & MG_PARBDYBDY) != 0)
    out[was_ifc] &= ~np.uint32(PARBDY_TAGS | MG_PARBDYBDY)
    if is_edge_or_vert:
        out[true_bdy] |= MG_BDY
    out[user_req] |= MG_REQ
    return out


# ---------------------------------------------------------------------------
# host mirrors
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class ShardViews:
    """Per-outer-iteration host views of the stacked shards (one pull)."""
    vert: np.ndarray    # [S, capP, 3]
    vtag: np.ndarray
    vref: np.ndarray
    vmask: np.ndarray
    tet: np.ndarray     # [S, capT, 4]
    tref: np.ndarray
    tmask: np.ndarray
    ftag: np.ndarray    # [S, capT, 4]
    fref: np.ndarray
    etag: np.ndarray    # [S, capT, 6]
    met: np.ndarray     # [S, capP(, 6)]
    npoin: np.ndarray   # [S]
    nelem: np.ndarray   # [S]


def pull_views(stacked: Mesh, met_s) -> ShardViews:
    """One consolidated device->host transfer of the shard state."""
    h, m = jax.device_get((stacked, met_s))
    # np.array (copy) everywhere: device_get may hand back READ-ONLY
    # views of the device buffer, and migration mutates every field
    return ShardViews(
        vert=np.array(h.vert), vtag=np.array(h.vtag),
        vref=np.array(h.vref), vmask=np.array(h.vmask),
        tet=np.array(h.tet), tref=np.array(h.tref),
        tmask=np.array(h.tmask), ftag=np.array(h.ftag),
        fref=np.array(h.fref), etag=np.array(h.etag),
        met=np.array(m), npoin=np.array(h.npoin), nelem=np.array(h.nelem))


def extend_global_ids_from_vmask(glo: list[np.ndarray],
                                 vmask: np.ndarray, top: int):
    """Fresh global ids for adapt-created vertices (shard-private by the
    freeze contract, so a disjoint id block per shard is exact).  Takes
    the [S, capP] validity masks directly so the caller can extend from
    a vmask-only device pull before the big views pull."""
    for s, g in enumerate(glo):
        fresh = vmask[s] & (g < 0)
        n = int(fresh.sum())
        if n:
            g[fresh] = top + np.arange(n, dtype=np.int64)
            top += n
        dead = ~vmask[s]
        g[dead] = -1
    return top


def apply_fresh_ids(glo: list[np.ndarray], rows: np.ndarray,
                    gids: np.ndarray) -> None:
    """Write device-assigned fresh ids into the host numbering mirror:
    ``rows``/``gids`` are [S, K] compacted tables (-1 pads) from
    ``migrate_dev.extend_ids_device`` / ``device_migrate`` arrivals,
    replicated to every process through ``pod.gather_band`` — the
    band-sized mirror sync the old full-vmask allgather performed at
    O(mesh) width."""
    for s, g in enumerate(glo):
        m = rows[s] >= 0
        g[rows[s][m]] = gids[s][m].astype(np.int64)


def kill_glo_rows(glo: list[np.ndarray], rows: np.ndarray,
                  cnt: np.ndarray) -> None:
    """Drop the ids of newly-dead vertex rows from the host mirror:
    ``rows`` [S, K] compacted (pad >= capP or -1), ``cnt`` [S] live
    counts — the per-iteration DELTA of the liveness mask (probe:
    ``migrate_dev.dead_glo_rows`` / ``device_migrate`` info), which is
    band-sized where the mask itself is O(mesh).  Exactness: glo >= 0
    only at live id-carrying rows (the mirror invariant every producer
    maintains — adapt deaths here, migration departures via
    device_migrate's probe, welds explicitly in band_weld), so killing
    the delta keeps the invariant without ever shipping the mask."""
    for s, g in enumerate(glo):
        r = rows[s][: int(cnt[s])]
        r = r[(r >= 0) & (r < len(g))]
        g[r] = -1


def extend_global_ids(glo: list[np.ndarray], views: ShardViews, top: int):
    return extend_global_ids_from_vmask(glo, views.vmask, top)


# ---------------------------------------------------------------------------
# interface recomputation from per-shard views (global-id matching)
# ---------------------------------------------------------------------------
def _shard_face_table(tet_live: np.ndarray, slots_live: np.ndarray,
                      glo_s: np.ndarray):
    """(sorted-global-triple keys, 4*tetslot+face) for every face of the
    shard's live tets; plus an 'exposed' mask (face unmatched in-shard)."""
    nt = len(tet_live)
    if nt == 0:
        return (np.zeros((0, 3), np.int64), np.zeros(0, np.int64),
                np.zeros(0, bool))
    gtet = glo_s[tet_live]                                  # [nt,4] global
    tri = np.sort(gtet[:, IDIR], axis=2).reshape(nt * 4, 3)  # [4nt,3]
    slot4 = (4 * slots_live[:, None] +
             np.arange(4)[None, :]).reshape(-1)
    order = np.lexsort((tri[:, 2], tri[:, 1], tri[:, 0]))
    ts = tri[order]
    same_next = np.concatenate([(ts[1:] == ts[:-1]).all(1), [False]])
    same_prev = np.concatenate([[False], same_next[:-1]])
    exposed = np.empty(nt * 4, bool)
    exposed[order] = ~(same_next | same_prev)
    return tri, slot4, exposed


def recompute_interface(views: ShardViews, glo: list[np.ndarray],
                        n_shards: int):
    """Match exposed faces across shards by global key; derive shared
    vertices from global-id incidence.  Returns
    (face_lists[a][b] local 4*tet_slot+face ordered by key,
     node_lists[a][b] local vertex rows ordered by global id,
     owner[s] [capP] owner shard per local vertex,
     ifc_face_slots[s] / ifc_vert_rows[s] for retagging)."""
    S = n_shards
    keys_all, slots_all, shard_all = [], [], []
    for s in range(S):
        live = np.where(views.tmask[s])[0]
        tri, slot4, exposed = _shard_face_table(
            views.tet[s][live], live, glo[s])
        keys_all.append(tri[exposed])
        slots_all.append(slot4[exposed])
        shard_all.append(np.full(int(exposed.sum()), s, np.int32))
    K = np.concatenate(keys_all) if keys_all else np.zeros((0, 3), np.int64)
    SL = np.concatenate(slots_all)
    SH = np.concatenate(shard_all)
    order = np.lexsort((K[:, 2], K[:, 1], K[:, 0]))
    Ks, SLs, SHs = K[order], SL[order], SH[order]
    pair = np.concatenate([(Ks[1:] == Ks[:-1]).all(1), [False]])
    iA = np.where(pair)[0]
    iB = iA + 1
    # conforming mesh: a face key appears in at most 2 shards
    face_lists = [[[] for _ in range(S)] for _ in range(S)]
    ifc_face_slots = [[] for _ in range(S)]
    for a, b, sa, sb in zip(SHs[iA], SHs[iB], SLs[iA], SLs[iB]):
        a, b = int(a), int(b)
        face_lists[a][b].append(int(sa))
        face_lists[b][a].append(int(sb))
        ifc_face_slots[a].append(int(sa))
        ifc_face_slots[b].append(int(sb))

    # shared vertices by global-id incidence of live vertex sets
    live_g = [glo[s][views.vmask[s]] for s in range(S)]
    live_l = [np.where(views.vmask[s])[0] for s in range(S)]
    allg = np.concatenate(live_g) if live_g else np.zeros(0, np.int64)
    alls = np.concatenate([np.full(len(g), s, np.int32)
                           for s, g in enumerate(live_g)])
    alll = np.concatenate(live_l) if live_l else np.zeros(0, np.int64)
    o = np.argsort(allg, kind="stable")
    gs, ss, ls = allg[o], alls[o], alll[o]
    head = np.concatenate([[True], gs[1:] != gs[:-1]])
    seg = np.cumsum(head) - 1
    cnt = np.bincount(seg)
    shared_seg = cnt > 1
    node_lists = [[[] for _ in range(S)] for _ in range(S)]
    owner = [np.full(views.vmask[s].shape[0], s, np.int32)
             for s in range(S)]
    ifc_vert_rows = [[] for _ in range(S)]
    # group rows of each shared vertex (gs sorted, so contiguous)
    bounds = np.where(head)[0]
    for b0 in np.where(shared_seg)[0]:
        lo = bounds[b0]
        hi = lo + cnt[b0]
        shards_here = ss[lo:hi]
        locals_here = ls[lo:hi]
        own = int(shards_here.max())
        for s_, l_ in zip(shards_here, locals_here):
            owner[int(s_)][int(l_)] = own
            ifc_vert_rows[int(s_)].append(int(l_))
        for i in range(len(shards_here)):
            for j in range(len(shards_here)):
                if shards_here[i] < shards_here[j]:
                    a, b = int(shards_here[i]), int(shards_here[j])
                    node_lists[a][b].append(int(locals_here[i]))
                    node_lists[b][a].append(int(locals_here[j]))
    # node lists are built in ascending-global-id order because the
    # shared-vertex loop walks the sorted segment array — the A.4
    # ordering contract holds by construction
    return face_lists, node_lists, owner, ifc_face_slots, ifc_vert_rows


def comms_from_lists(face_lists, node_lists, owner,
                     n_shards: int) -> InterfaceComms:
    """Pad pair item lists into the device-ready comm tables — delegates
    to the single padding implementation (comms.pad_comm_tables)."""
    from .comms import pad_comm_tables
    return pad_comm_tables(node_lists, face_lists, owner, n_shards)


# ---------------------------------------------------------------------------
# the migration step
# ---------------------------------------------------------------------------
def enforce_ne_min(labels: np.ndarray, tmask: np.ndarray, n_shards: int,
                   ne_min: int | None = None,
                   depth: np.ndarray | None = None) -> np.ndarray:
    """Donor floor: a shard keeps at least ne_min tets
    (moveinterfaces_pmmg.c:1343 semantics, min(6, ne/2+1) scaled).

    Excess moves are reverted DEEPEST flood layer first (``depth`` from
    flood_labels) so the retained prefix stays a connected advancing
    front — a slot-ordered cut could keep band tets disconnected from
    the recipient.  Without ``depth`` falls back to slot order."""
    S = n_shards
    lab = labels.copy()
    for s in range(S):
        live = tmask[s]
        n = int(live.sum())
        floor = ne_min if ne_min is not None else min(6, n // 2 + 1)
        moved = np.where(live & (lab[s] != s))[0]
        excess = len(moved) - (n - floor)
        if excess > 0:
            if depth is not None:
                # stable sort by flood depth: deepest (latest-flipped)
                # layers revert first, ties keep slot order
                moved = moved[np.argsort(depth[s][moved], kind="stable")]
            lab[s][moved[len(moved) - excess:]] = s
    return lab


def migrate_shards(stacked: Mesh, met_s, views: ShardViews,
                   glo: list[np.ndarray], labels: np.ndarray,
                   n_shards: int, verbose: int = 0):
    """Apply the displaced partition: move labeled tet bands between
    shards, weld by global id, refreeze the new interface, rebuild comms.

    Mutates ``views`` and ``glo`` in place (they are this iteration's host
    mirrors); returns (stacked, met_s, comms, nmoved).  Device updates are
    sparse scatters (O(band + interface) transferred), buffer slot ids are
    stable, and no global mesh is ever materialized — the incremental
    replacement for merge->repartition->resplit
    (PMMG_transfer_all_grps role, distributegrps_pmmg.c:1631-1841).

    Phase structure (a shard can be donor AND recipient, so all sender
    data is extracted before any mirror is mutated):
      A. extract band packages (pure reads),
      B. capacity check (+ slot-stable device grow if needed),
      C. removals + arrivals on the mirrors,
      D. interface recomputation + freeze/unfreeze retag,
      E. one sparse push to the device.
    """
    S = n_shards

    # ---------- collect moves per (src -> dst) ---------------------------
    moves = []            # (src, dst, src_tet_slots)
    nmoved = 0
    for s in range(S):
        m = views.tmask[s] & (labels[s] != s)
        if not m.any():
            continue
        for r in np.unique(labels[s][m]):
            slots = np.where(m & (labels[s] == int(r)))[0]
            moves.append((s, int(r), slots))
            nmoved += len(slots)
    if nmoved == 0:
        return stacked, met_s, None, 0

    # ---------- A. extract packages (before any mutation) ----------------
    # per destination r: stacked arrays of arriving tets + a global-id ->
    # (vert, vtag, vref, met) vertex bank from the senders
    pkg = {}
    for s, r, slots in moves:
        p = pkg.setdefault(r, dict(gt=[], tref=[], ftag=[], fref=[],
                                   etag=[], bank={}))
        gt = glo[s][views.tet[s][slots]]                   # [k,4] global
        p["gt"].append(gt)
        p["tref"].append(views.tref[s][slots].copy())
        p["ftag"].append(views.ftag[s][slots].copy())
        p["fref"].append(views.fref[s][slots].copy())
        p["etag"].append(views.etag[s][slots].copy())
        uq, first = np.unique(gt.reshape(-1), return_index=True)
        lrows = views.tet[s][slots].reshape(-1)[first]
        for gid, lrow in zip(uq, lrows):
            gid = int(gid)
            if gid not in p["bank"]:
                p["bank"][gid] = (views.vert[s][lrow].copy(),
                                  np.uint32(views.vtag[s][lrow]),
                                  views.vref[s][lrow],
                                  views.met[s][lrow].copy())

    # ---------- B. capacity check ----------------------------------------
    while True:
        capP = views.vert.shape[1]
        capT = views.tet.shape[1]
        need_grow = False
        for r, p in pkg.items():
            need_g = np.unique(np.concatenate(p["gt"]).reshape(-1))
            known = np.isin(need_g, glo[r][glo[r] >= 0])
            n_new_v = int((~known).sum())
            free_v = int((glo[r] < 0).sum())
            arriving_t = sum(len(g) for g in p["gt"])
            departing_t = int((views.tmask[r] & (labels[r] != r)).sum())
            free_t = capT - int(views.tmask[r].sum()) + departing_t
            if n_new_v > free_v or arriving_t > free_t:
                need_grow = True
                break
        if not need_grow:
            break
        # slot-stable device grow (zaldy_pmmg.c regrow analogue) + mirror
        # and label/glo padding; device buffers untouched otherwise
        from .distribute import grow_shards
        stacked, met_s = grow_shards(stacked, met_s, 2 * capP, 2 * capT)
        views.vert = _padP(views.vert, capP)
        views.vtag = _padP(views.vtag, capP)
        views.vref = _padP(views.vref, capP)
        views.vmask = _padP(views.vmask, capP, False)
        views.met = _padP(views.met, capP)
        views.tet = _padT(views.tet, capT)
        views.tref = _padT(views.tref, capT)
        views.tmask = _padT(views.tmask, capT, False)
        views.ftag = _padT(views.ftag, capT)
        views.fref = _padT(views.fref, capT)
        views.etag = _padT(views.etag, capT)
        labels = np.concatenate(
            [labels, np.zeros((S, capT), labels.dtype)], axis=1)
        for s in range(S):
            glo[s] = np.concatenate([glo[s], np.full(capP, -1, np.int64)])
    capP = views.vert.shape[1]

    # device update accumulators
    upd_v = {s: [] for s in range(S)}    # (rows, vert, vtag, vref, met)
    upd_t = {s: [] for s in range(S)}    # (rows, tet, tref, ftag, fref, etag)
    mask_dirty = set()

    # ---------- C1. removals ---------------------------------------------
    for s, r, slots in moves:
        views.tmask[s][slots] = False
        mask_dirty.add(s)

    # ---------- C2. arrivals ---------------------------------------------
    for r, p in pkg.items():
        gt_all = np.concatenate(p["gt"])
        need_g = np.unique(gt_all.reshape(-1))
        # known rows: any slot still holding that global id (including
        # rows whose tets just left — shared vertices are frozen, so the
        # slot data is still valid and is simply resurrected)
        hold = glo[r] >= 0
        have_g = glo[r][hold]
        have_l = np.where(hold)[0]
        o = np.argsort(have_g, kind="stable")
        have_g, have_l = have_g[o], have_l[o]
        pos = np.searchsorted(have_g, need_g)
        pos_c = np.clip(pos, 0, max(0, len(have_g) - 1))
        known = (have_g[pos_c] == need_g) if len(have_g) \
            else np.zeros(len(need_g), bool)
        new_g = need_g[~known]
        free = np.where(glo[r] < 0)[0]
        tgt_rows = free[: len(new_g)]
        lut_g = np.concatenate([have_g, new_g])
        lut_l = np.concatenate([have_l, tgt_rows])
        o2 = np.argsort(lut_g, kind="stable")
        lut_g, lut_l = lut_g[o2], lut_l[o2]
        if len(new_g):
            vv = np.stack([p["bank"][int(g_)][0] for g_ in new_g])
            vt = np.asarray([p["bank"][int(g_)][1] for g_ in new_g],
                            np.uint32)
            vr = np.asarray([p["bank"][int(g_)][2] for g_ in new_g])
            vm = np.stack([p["bank"][int(g_)][3] for g_ in new_g])
            views.vert[r][tgt_rows] = vv
            views.vtag[r][tgt_rows] = vt
            views.vref[r][tgt_rows] = vr
            views.met[r][tgt_rows] = vm
            glo[r][tgt_rows] = new_g
            upd_v[r].append((tgt_rows, vv, vt, vr, vm))
        # tet rows into free slots
        k = len(gt_all)
        tfree = np.where(~views.tmask[r])[0]
        t_rows = tfree[:k]
        lt = lut_l[np.searchsorted(lut_g, gt_all.reshape(-1))]\
            .reshape(-1, 4).astype(np.int32)
        tr_ = np.concatenate(p["tref"])
        ftg = np.concatenate(p["ftag"])
        frf = np.concatenate(p["fref"])
        etg = np.concatenate(p["etag"])
        views.tet[r][t_rows] = lt
        views.tref[r][t_rows] = tr_
        views.ftag[r][t_rows] = ftg
        views.fref[r][t_rows] = frf
        views.etag[r][t_rows] = etg
        views.tmask[r][t_rows] = True
        mask_dirty.add(r)
        upd_t[r].append((t_rows, lt, tr_, ftg, frf, etg))

    # ---------- C3. final vertex liveness + watermarks -------------------
    for s in range(S):
        live = views.tet[s][views.tmask[s]]
        ref = np.zeros(capP, bool)
        if len(live):
            ref[live.reshape(-1)] = True
        if not np.array_equal(ref, views.vmask[s]):
            mask_dirty.add(s)
        views.vmask[s] = ref
        glo[s][~ref] = -1          # dead rows become allocatable again
        used_v = np.where(ref)[0]
        used_t = np.where(views.tmask[s])[0]
        views.npoin[s] = (used_v.max() + 1) if len(used_v) else 0
        views.nelem[s] = (used_t.max() + 1) if len(used_t) else 0

    # ---------- D. recompute the interface + retag -----------------------
    face_lists, node_lists, owner, ifc_face_slots, ifc_vert_rows = \
        recompute_interface(views, glo, S)
    tag_updates = _retag_interfaces(views, glo, ifc_face_slots,
                                    ifc_vert_rows, S)
    comms = comms_from_lists(face_lists, node_lists, owner, S)

    # ---------- E. one sparse push to the device -------------------------
    stacked, met_s = _push_updates(stacked, met_s, views, upd_v, upd_t,
                                   mask_dirty, tag_updates, S)
    otrace.log(2, f"  migration: moved {nmoved} tets across "
                  f"{len(moves)} shard pairs", verbose=verbose)
    return stacked, met_s, comms, nmoved


def _padP(a, n, fill=0):
    pad = [(0, 0)] * a.ndim
    pad[1] = (0, n)
    return np.pad(a, pad, constant_values=fill)


_padT = _padP


def _retag_interfaces(views: ShardViews, glo, ifc_face_slots,
                      ifc_vert_rows, S):
    """Per shard, reconcile freeze tags with the NEW interface: unfreeze
    entities that left it, freeze entities that joined.  Membership is
    decided at the geometric-entity level (global keys) and applied to
    every local slot of the entity.  Returns per-shard sparse updates
    {(field): (rows..., values)} and mutates the views."""
    out = []
    for s in range(S):
        tm = views.tmask[s]
        live = np.where(tm)[0]
        upd = {}
        # ---- faces ----
        ft = views.ftag[s]
        slot_ifc = np.zeros((views.tet.shape[1], 4), bool)
        if ifc_face_slots[s]:
            sl = np.asarray(ifc_face_slots[s], np.int64)
            slot_ifc[sl // 4, sl % 4] = True
        cur_ifc = (ft & MG_PARBDY) != 0
        cur_ifc[~tm] = False
        to_unfreeze = cur_ifc & ~slot_ifc
        to_freeze = slot_ifc & ~cur_ifc
        # interface faces carried by BOTH member slots of a face pair:
        # freeze/unfreeze applies per slot, each side listed separately
        ftr, ftc = np.where(to_unfreeze | to_freeze)
        if len(ftr):
            vals = ft[ftr, ftc].copy()
            un = to_unfreeze[ftr, ftc]
            vals[un] = _unfreeze_bits(vals[un], False)
            vals[~un] = _freeze_bits(vals[~un], False)
            ft[ftr, ftc] = vals
            upd["ftag"] = (ftr, ftc, vals)
        # ---- edges ----
        # global interface edge keys = edges of the new interface faces
        et = views.etag[s]
        g = glo[s]
        # pack (gid_a, gid_b) with the CURRENT id bound, not a fixed
        # 1<<31: session global ids grow monotonically (extend_global_ids
        # never reuses freed ids), so a fixed base would silently alias
        # distinct edges once any id crosses it.  int64 keys stay exact
        # up to base ~ 3e9.
        base = np.int64(max(int(g.max()) + 1, 1))
        if ifc_face_slots[s]:
            sl = np.asarray(ifc_face_slots[s], np.int64)
            tri = np.sort(g[views.tet[s][sl // 4]][
                np.arange(len(sl))[:, None], IDIR[sl % 4]], axis=1)
            ek = np.concatenate([
                tri[:, [0, 1]], tri[:, [0, 2]], tri[:, [1, 2]]])
            ekey = np.unique(ek[:, 0] * base + ek[:, 1])
        else:
            ekey = np.zeros(0, np.int64)
        gtet = g[views.tet[s]]
        ev = np.sort(gtet[:, IARE], axis=2)             # [T,6,2]
        slot_key = ev[..., 0] * base + ev[..., 1]
        in_new = np.zeros(slot_key.shape, bool)
        if len(ekey):
            p = np.searchsorted(ekey, slot_key)
            pc = np.clip(p, 0, len(ekey) - 1)
            in_new = ekey[pc] == slot_key
        in_new[~tm] = False
        cur = (et & MG_PARBDY) != 0
        cur[~tm] = False
        eu = cur & ~in_new
        ef = in_new & ~cur
        er, ec = np.where(eu | ef)
        if len(er):
            vals = et[er, ec].copy()
            un = eu[er, ec]
            vals[un] = _unfreeze_bits(vals[un], True)
            vals[~un] = _freeze_bits(vals[~un], True)
            et[er, ec] = vals
            upd["etag"] = (er, ec, vals)
        # ---- vertices ----
        vt = views.vtag[s]
        new_ifc_v = np.zeros(len(vt), bool)
        if ifc_vert_rows[s]:
            new_ifc_v[np.asarray(ifc_vert_rows[s], np.int64)] = True
        curv = (vt & MG_PARBDY) != 0
        curv[~views.vmask[s]] = False
        vu = curv & ~new_ifc_v
        vf = new_ifc_v & ~curv
        vr = np.where(vu | vf)[0]
        if len(vr):
            vals = vt[vr].copy()
            un = vu[vr]
            vals[un] = _unfreeze_bits(vals[un], True)
            vals[~un] = _freeze_bits(vals[~un], True)
            vt[vr] = vals
            upd["vtag"] = (vr, vals)
        out.append(upd)
    return out


def _push_updates(stacked: Mesh, met_s, views: ShardViews, upd_v, upd_t,
                  mask_dirty, tag_updates, S):
    """Apply the collected sparse updates to the device-resident stacked
    shards.  Transfers are O(band + interface) for the data arrays (the
    validity masks of touched shards go up whole — they are 1-byte bools,
    negligible next to one tet row); full-array traffic never leaves the
    device."""
    vert_d, vtag_d, vref_d, vmask_d = (stacked.vert, stacked.vtag,
                                       stacked.vref, stacked.vmask)
    tet_d, tref_d, tmask_d = stacked.tet, stacked.tref, stacked.tmask
    ftag_d, fref_d, etag_d = stacked.ftag, stacked.fref, stacked.etag
    met_d = met_s
    for s in range(S):
        for rows, vv, vt, vr_, vm in upd_v[s]:
            r = jnp.asarray(rows)
            vert_d = vert_d.at[s, r].set(jnp.asarray(vv, vert_d.dtype))
            vtag_d = vtag_d.at[s, r].set(jnp.asarray(vt))
            vref_d = vref_d.at[s, r].set(jnp.asarray(vr_))
            met_d = met_d.at[s, r].set(jnp.asarray(vm, met_d.dtype))
        for rows, lt, tr_, ftg, frf, etg in upd_t[s]:
            r = jnp.asarray(rows)
            tet_d = tet_d.at[s, r].set(jnp.asarray(lt))
            tref_d = tref_d.at[s, r].set(jnp.asarray(tr_))
            ftag_d = ftag_d.at[s, r].set(jnp.asarray(ftg))
            fref_d = fref_d.at[s, r].set(jnp.asarray(frf))
            etag_d = etag_d.at[s, r].set(jnp.asarray(etg))
        if s in mask_dirty:
            vmask_d = vmask_d.at[s].set(jnp.asarray(views.vmask[s]))
            tmask_d = tmask_d.at[s].set(jnp.asarray(views.tmask[s]))
        upd = tag_updates[s]
        if "ftag" in upd:
            ftr, ftc, vals = upd["ftag"]
            ftag_d = ftag_d.at[s, jnp.asarray(ftr), jnp.asarray(ftc)].set(
                jnp.asarray(vals))
        if "etag" in upd:
            er, ec, vals = upd["etag"]
            etag_d = etag_d.at[s, jnp.asarray(er), jnp.asarray(ec)].set(
                jnp.asarray(vals))
        if "vtag" in upd:
            vr_, vals = upd["vtag"]
            vtag_d = vtag_d.at[s, jnp.asarray(vr_)].set(jnp.asarray(vals))
    npoin = jnp.asarray(views.npoin.astype(np.int32))
    nelem = jnp.asarray(views.nelem.astype(np.int32))
    out = dataclasses.replace(
        stacked, vert=vert_d, vtag=vtag_d, vref=vref_d, vmask=vmask_d,
        tet=tet_d, tref=tref_d, tmask=tmask_d, ftag=ftag_d, fref=fref_d,
        etag=etag_d, npoin=npoin, nelem=nelem)
    return out, met_d


def weld_shard_bands(stacked: Mesh, views: ShardViews,
                     glo: list[np.ndarray], n_shards: int,
                     touched=None, verbose: int = 0):
    """Sequential near-duplicate weld INSIDE each shard after migration.

    Independent refinement on both sides of a frozen interface leaves
    near-coincident interior point pairs; once the band migrates, both
    copies live in ONE shard and deadlock the batched collapse (every
    parallel contraction inverts a neighbor sliver).  The merged path
    welds them at every inter-iteration merge (distribute.merge_shards);
    the shard-resident loop does the same here, per shard, on the host
    views — only untagged (non-interface) pairs are touched, so the comm
    tables stay valid.  Returns (stacked, nweld).
    """
    from .distribute import _weld_close_pairs

    tet_d = stacked.tet
    tmask_d = stacked.tmask
    vmask_d = stacked.vmask
    ntot = 0
    for s in (range(n_shards) if touched is None else touched):
        tm = views.tmask[s]
        live = np.where(tm)[0]
        if not len(live):
            continue
        tet_live = views.tet[s][live]
        # dead rows must not participate: _weld_close_pairs' candidacy is
        # vtag==0 and its weld-radius median is computed over candidates —
        # grow-padded rows (vert=0, vtag=0, met=0) would both poison the
        # radius and 'weld' against stale dead slots.  Mark dead rows
        # with an all-ones poison tag (never equal to 0).
        vtag_live = views.vtag[s].copy()
        vtag_live[~views.vmask[s]] = np.uint32(0xFFFFFFFF)
        tet2, vkeep, tkeep = _weld_close_pairs(
            views.vert[s], tet_live, vtag_live, views.met[s],
            views.tref[s][live], views.ftag[s][live],
            views.etag[s][live])
        if vkeep.all() and tkeep.all() and \
                np.array_equal(tet2, tet_live):
            continue
        ntot += int((~vkeep).sum())
        # apply to the mirrors (slot-stable)
        views.tet[s][live] = tet2
        views.tmask[s][live[~tkeep]] = False
        ref = np.zeros(views.vmask.shape[1], bool)
        alive = views.tet[s][views.tmask[s]]
        if len(alive):
            ref[alive.reshape(-1)] = True
        views.vmask[s] = ref
        glo[s][~ref] = -1
        # sparse device push: changed tet rows + the two masks
        chg = live[np.any(tet2 != tet_live, axis=1) | ~tkeep]
        if len(chg):
            tet_d = tet_d.at[s, jnp.asarray(chg)].set(
                jnp.asarray(views.tet[s][chg]))
        tmask_d = tmask_d.at[s].set(jnp.asarray(views.tmask[s]))
        vmask_d = vmask_d.at[s].set(jnp.asarray(views.vmask[s]))
    if ntot:
        otrace.log(2, f"  band weld: {ntot} near-duplicate pairs "
                      "contracted", verbose=verbose)
    if ntot == 0:
        return stacked, 0
    return dataclasses.replace(stacked, tet=tet_d, tmask=tmask_d,
                               vmask=vmask_d), ntot


@jax.jit
def rebuild_shards(stacked: Mesh) -> Mesh:
    """Per-shard adjacency + boundary-tag propagation after migration
    (vmapped build_adjacency; the MMG3D_hashTetra re-hash analogue)."""
    from ..ops.adjacency import build_adjacency, boundary_edge_tags
    return jax.vmap(lambda m: boundary_edge_tags(build_adjacency(m)))(
        stacked)


# ---------------------------------------------------------------------------
# group-graph repartitioning labels (graph-balancing mode)
# ---------------------------------------------------------------------------
def graph_repartition_labels(views: ShardViews, glo, n_shards: int,
                             clusters_per_shard: int = 8) -> np.ndarray:
    """Per-tet target-shard labels from a GROUP-graph repartition — the
    graph-balancing mode's replacement for merge->METIS->resplit.

    The reference gathers only the group graph (xadj/adjncy/weights) to
    rank 0 and runs METIS on it (metis_pmmg.c:845-1550) — O(groups)
    gathered, never the mesh.  Here: each shard's live tets are
    clustered along the morton curve (the clusters play the reference's
    'redistribution groups'), the cluster adjacency graph is built from
    ONE global face sort keyed by the persistent global vertex ids
    (intra-shard and interface faces in the same pass), and the
    cluster->shard map is rebalanced with the weighted KL/FM refinement
    (partition.refine_partition, the METIS-kway role).  The realized
    moves then ride the band-migration machinery (migrate_shards), so
    NO whole-mesh merge happens between iterations.

    Returns labels [S, capT] int32 (target shard per live tet).
    """
    from ..core.constants import IDIR
    from .partition import morton_partition, refine_partition
    S = n_shards
    capT = views.tet.shape[1]
    labels = np.tile(np.arange(S, dtype=np.int32)[:, None], (1, capT))
    cl_local = np.full((S, capT), -1, np.int64)
    all_tri, all_cl = [], []
    cweights = []
    offset = 0
    for s in range(S):
        live = np.where(views.tmask[s])[0]
        if not len(live):
            cweights.append(np.zeros(clusters_per_shard))
            offset += clusters_per_shard
            continue
        cent = views.vert[s][views.tet[s][live]].mean(axis=1)
        c = morton_partition(cent, min(clusters_per_shard, len(live)))
        cl_local[s, live] = c + offset
        cw = np.bincount(c, minlength=clusters_per_shard).astype(float)
        cweights.append(cw)
        gtet = glo[s][views.tet[s][live]]
        tri = np.sort(gtet[:, IDIR], axis=2).reshape(-1, 3)
        all_tri.append(tri)
        all_cl.append(np.repeat(c + offset, 4))
        offset += clusters_per_shard
    nclu = offset
    cw = np.concatenate(cweights)
    if not all_tri:
        return labels
    tri = np.concatenate(all_tri)
    cl4 = np.concatenate(all_cl)
    o = np.lexsort((tri[:, 2], tri[:, 1], tri[:, 0]))
    ts, cs = tri[o], cl4[o]
    same = np.concatenate([(ts[1:] == ts[:-1]).all(1), [False]])
    ia = np.where(same)[0]
    ca, cb = cs[ia], cs[ia + 1]
    cross = ca != cb
    pi = np.minimum(ca[cross], cb[cross])
    pj = np.maximum(ca[cross], cb[cross])
    # aggregate multiplicity (face count between cluster pairs)
    key = pi * nclu + pj
    uk, wcnt = np.unique(key, return_counts=True)
    pi_u = (uk // nclu).astype(np.int64)
    pj_u = (uk % nclu).astype(np.int64)
    init = np.repeat(np.arange(S, dtype=np.int32), clusters_per_shard)
    new_part = refine_partition(init, S, (pi_u, pj_u),
                                wcnt.astype(float), elem_w=cw,
                                npasses=5)
    for s in range(S):
        live = cl_local[s] >= 0
        labels[s][live] = new_part[cl_local[s][live]]
    return labels
