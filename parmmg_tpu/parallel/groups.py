"""Two-level decomposition: sub-device remesh groups.

The reference splits each rank's mesh into ``-mesh-size``-element groups
and remeshes them one at a time (``PMMG_splitPart_grps`` / ``howManyGroups``
grpsplit_pmmg.c:47,1551-1614, capped at ``PMMG_REMESHER_NGRPS_MAX``); the
group is the unit that bounds the remesher's working set.  TPU-native
analogue: groups are slots of a stacked pytree traversed with ``lax.map``
— XLA compiles ONE cycle program for the group shape and executes it per
group, so peak HBM scales with the GROUP capacity, not the mesh.  Mesh
size per chip is then bounded by HBM-for-one-group x ngroups, which is
what makes the 10M-tet configuration reachable on a single chip.  (A
``vmap`` over groups would process them concurrently — same peak memory
as no groups at all; ``map`` is the memory-bounding choice.  Groups also
shorten the O(n log^2 n) TPU sorts inside each wave.)

Group interfaces are frozen exactly like rank interfaces (MG_PARBDY —
the same ``split_to_shards`` freeze contract, tag_pmmg.c:39-124) and
displaced between outer iterations with the same advancing-front
machinery, so previously-frozen group seams get remeshed later — the
two-level loop of the reference.

The MULTI-device composition of the same idea (G logical shards per
device, ``dist.distributed_adapt_multi(n_devices=...)``) shares this
module's lax.map HBM discipline and additionally keeps the
between-iteration refresh on device: grouped analysis
(analysis_dev.dist_analysis_grouped) + the grouped/packed halo exchange
(comms.halo_exchange_grouped[_packed]), all governed under the same
compile-ledger budgets as the blocks below.

``-metis-ratio`` note: the reference multiplies the group count by
``metis_ratio`` for the REDISTRIBUTION split, whose many small groups are
the METIS graph nodes (grpsplit_pmmg.c:1595-1614).  This framework
migrates interface bands directly (parallel/migrate.py) instead of
re-partitioning a group graph, so the flag has no load-bearing role; it
is parsed and validated for CLI parity only.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.mesh import Mesh
from ..core import constants as C
from ..obs import trace as otrace


def how_many_groups(ne: int, target: int) -> int:
    """Group count with the reference's clamps (grpsplit_pmmg.c:47)."""
    if target <= 0:
        return 1
    return max(1, min((ne + target - 1) // target, C.REMESHER_NGRPS_MAX))


def _polish_subproc() -> bool:
    """Whether the grouped polish phase runs in its own process
    (PARMMG_POLISH_SUBPROC; default: only on the tunneled TPU, where
    the in-session polish dispatch reliably kills the worker — see
    parallel/_polish_worker.py)."""
    import os
    v = os.environ.get("PARMMG_POLISH_SUBPROC", "")
    if v:
        return v != "0"
    return jax.default_backend() == "tpu"


def group_chunk(ngroups: int) -> int:
    """Groups per dispatch (0 = all in one ``lax.map``).

    On the tunneled TPU a single dispatch spanning every group runs for
    minutes (43 groups x fused cycle block) and the tunnel kills the
    worker mid-execution ("TPU worker process crashed"; reproduced
    rounds 3-4 at the 1M-tet scale).  Chunking the map axis bounds each
    dispatch to ~chunk group-blocks (~10-20 s) — same compiled program
    per chunk, same results — at the cost of one counter pull per
    chunk.  Elsewhere (CPU tests) chunking buys nothing: default 0.
    Returns 0 (unchunked) when the chunk would cover every group
    anyway.  Override with PARMMG_GROUP_CHUNK; PARMMG_GROUP_CHUNK=auto
    adopts the newest trajectory-derived recommendation
    (sched.recommend_group_chunk, recorded at the end of every grouped
    pass) and falls back to the backend default before the first pass
    has produced one."""
    import os
    v = os.environ.get("PARMMG_GROUP_CHUNK", "")
    if v == "auto":
        from .sched import auto_chunk_recommendation
        rec = auto_chunk_recommendation()
        c = rec if rec is not None else (
            8 if jax.default_backend() == "tpu" else 0)
    else:
        c = max(0, int(v)) if v else (
            8 if jax.default_backend() == "tpu" else 0)
    return 0 if c >= ngroups else c


def block_schedule(c0: int, nblk: int, cycles: int, noswap: bool):
    """(flags, pres) for the cycle block starting at global cycle
    ``c0`` — THE block signature of the grouped cycle scheduler: swap
    every 3rd cycle plus the final-two polish cycles (swap-inclusive
    AND exact split veto via prescreen bypass — ops/split.py, ADVICE
    r3).  Factored out so the serving pool (serve/pool.py) runs
    byte-identical block sequences: same signature => same cached
    compiled program (_group_block key)."""
    flags = tuple((cc % 3 == 2 or cc >= cycles - 2) and not noswap
                  for cc in range(c0, c0 + nblk))
    pres = tuple(cc < cycles - 2 for cc in range(c0, c0 + nblk))
    return flags, pres


# lint: ok(R2) — cs is host numpy (the per-block counters the drain
# already pulled); the early-exit decision is pure host bookkeeping
def block_converged(cs: np.ndarray, flags: tuple, noswap: bool) -> bool:
    """The grouped loop's early-exit rule on a block's summed counts
    ``cs`` [nblk, >=3]: any swap-inclusive cycle posting zero
    split+collapse+swap ends the sizing loop.  Shared with the serving
    pool, where it is evaluated per tenant (a tenant IS one group, so
    the per-tenant rule equals the standalone ngroups=1 rule — the
    serving parity contract)."""
    return any((flags[i] or noswap) and
               int(cs[i][0]) + int(cs[i][1]) + int(cs[i][2]) == 0
               for i in range(len(flags)))


# module-level compiled-block caches (compile governor): the builders
# below close only over hashable knobs, and jax.jit caches by function
# IDENTITY — per-pass local builders recompiled the group programs
# every outer iteration even at identical shapes.  Bounded: a handful
# of (flags, pres, knobs) combos per session.
_GROUP_BLOCK_CACHE: dict = {}
_POLISH_BLOCK_CACHE: dict = {}


def _group_block(flags: tuple, pres: tuple, nomove: bool,
                 noinsert: bool, hausd):
    """Fused cycle block for the group axis (lax.map body): one
    dispatch + one counter pull per block per outer step (ops.adapt
    adapt_cycles_fused analogue).  Cached by knobs so repeat passes
    reuse the compiled program.

    The compiled program takes a per-slot ``active`` bool mask (the
    device-resident quiet mask, parallel/sched.py): inactive slots —
    quiet groups of an unchunked dispatch, repeat-padded tail rows of a
    compacted chunk plan — return their state unchanged with zero
    counts via ``lax.cond`` instead of running the wave math
    (ops/adapt.py ``active=``).  The mask is ALWAYS an argument (an
    all-true mask when masking is off), so toggling it mints zero new
    compile families — the grouped_sched_gate contract.

    ``cadence`` (last argument of the compiled program) is the
    smoothing-cadence enable (PARMMG_SMOOTH_CADENCE via
    sched.cadence_enabled): like the quiet mask it is ALWAYS a traced
    argument, so toggling it mints zero new compile families
    (the hotloop_knob_gate contract).  The per-slot idle carry is
    derived on-device from each cycle's counts inside the map body —
    a cycle following a full no-op cycle skips its smoothing wave as a
    proven identity (ops/adapt.py ``smooth_idle``).

    ``incr``/``topo`` (PARMMG_INCR_TOPO, ops/topo_incr): per-slot
    retained-sort + dirty-band state rides the group axis through the
    SAME compiled program — the knob scalar and the state are ALWAYS
    traced arguments, so toggling the incremental path mints zero new
    compile families (the hotloop_knob_gate contract).  Quiet/pad slots
    pass through the ``active`` lax.cond with their state untouched
    (an idle slot's retained tables stay valid)."""
    from ..ops.adapt import adapt_cycle_impl
    from ..utils.compilecache import governed
    key = (flags, pres, nomove, noinsert, hausd)
    if key in _GROUP_BLOCK_CACHE:
        return _GROUP_BLOCK_CACHE[key]

    def body(args):
        m, k, wave, act, cad, inc, tp = args
        counts_all = []
        sm_idle = jnp.zeros((), bool)
        for cc, dosw in enumerate(flags):
            # named_scope: XLA ops of each unrolled cycle carry the
            # phase name on a profiler's device timeline (obs/trace.py)
            with otrace.scope(f"grp_cycle{cc}"):
                m, k, counts, tp = adapt_cycle_impl(
                    m, k, wave + cc, do_swap=dosw,
                    do_smooth=not nomove, do_insert=not noinsert,
                    hausd=hausd, final_rebuild=(cc == len(flags) - 1),
                    prescreen=pres[cc], active=act,
                    smooth_idle=cad & sm_idle, topo=tp, incr=inc)
            sm_idle = ((counts[0] + counts[1] + counts[2]) == 0) & \
                (counts[3] == 0)
            counts_all.append(counts)
        return m, k, jnp.stack(counts_all), tp   # counts [n, 9]

    # variant budget: the cycle scheduler emits a handful of (flags,
    # pres) combos per session and the chunked dispatch pads every
    # chunk to ONE shape family — growth past this is recompile churn
    @governed("groups.adapt_block", budget=6)
    @jax.jit
    def run(stacked, met_s, wave, active, cadence, incr, topo):
        n_map = stacked.vert.shape[0]            # chunk or g_exec
        waves = jnp.full(n_map, wave, jnp.int32)
        cads = jnp.full(n_map, cadence, bool)
        incs = jnp.full(n_map, incr, bool)
        m, k, counts, tp = jax.lax.map(
            body, (stacked, met_s, waves, active, cads, incs, topo))
        return m, k, counts, tp                  # counts [G, n, 9]

    _GROUP_BLOCK_CACHE[key] = run
    return run


def _group_polish_block(noinsert: bool, noswap: bool, nomove: bool,
                        hausd):
    """Grouped sliver-polish block (sliver_polish per group under
    lax.map), cached by knobs for the same jit-identity reason.  Takes
    the same per-slot ``active`` mask as :func:`_group_block` — the
    wave-major polish retires groups at their own collapse+swap==0
    fixed point, and a retired/pad slot's row is cond-skipped."""
    from ..ops.adapt import sliver_polish_impl
    from ..utils.compilecache import governed
    key = (noinsert, noswap, nomove, hausd)
    if key in _POLISH_BLOCK_CACHE:
        return _POLISH_BLOCK_CACHE[key]

    @governed("groups.polish_block", budget=4)
    @jax.jit
    def polish_block(stacked, met_s, wave, active):
        def body(args):
            m, k, w, act = args
            m, cnt = sliver_polish_impl(
                m, k, w, do_collapse=not noinsert,
                do_swap=not noswap, do_smooth=not nomove,
                hausd=hausd, active=act)
            return m, k, cnt
        n_map = stacked.vert.shape[0]            # chunk or g_exec
        waves = jnp.full(n_map, wave, jnp.int32)
        m, k, cnt = jax.lax.map(body, (stacked, met_s, waves, active))
        return m, k, cnt

    _POLISH_BLOCK_CACHE[key] = polish_block
    return polish_block


def _pad_groups(tree, g_new: int):
    """Pad a stacked pytree's leading group axis to ``g_new`` with dead
    groups (all-zero arrays: masks False, counts 0 — every wave kernel
    is a no-op on a fully-dead mesh)."""
    def pad(a):
        g = a.shape[0]
        if g >= g_new:
            return a
        return jnp.concatenate(
            [a, jnp.zeros((g_new - g,) + a.shape[1:], a.dtype)])
    return jax.tree.map(pad, tree)


def _pipeline_chunks(fn, stacked, met_s, wave, plans, tim, done=None,
                     extra=(), topo=None):
    """Double-buffered chunked dispatch over gathered group-index slices.

    ``extra``: additional positional device scalars appended to each
    ``fn`` dispatch after the active mask (the adapt block's traced
    cadence enable + incremental-topology knob; empty for the polish
    block).

    ``topo``: optional host-numpy TopoState [g_exec, ...]
    (ops/topo_incr.topo_init_np) — the per-slot retained-table state of
    the incremental topology engine.  Its rows ride the same gather /
    dispatch / writeback path as the mesh state, and like it they only
    mutate when a drain COMMITS, so the band state is covered by the
    idempotent-writeback contract: a faulted dispatch's retry replays
    from the retained table bit-for-bit.

    ``plans``: [(idx_exec [chunk], nreal)] from the quiet-group
    scheduler (parallel/sched.py); the SAME compiled [chunk, ...]
    program runs on every gathered slice, so compaction adds zero new
    shape families.  The legacy loop was a serial
    upload -> compute -> sync -> download train per chunk; here chunk
    k+1's host gather + device upload + dispatch are issued BEFORE
    blocking on chunk k, so host staging and device->host pulls overlap
    device compute (two chunks in flight, bounding peak device memory
    at 2 chunk states — the HBM discipline of the chunked mode).  The
    per-chunk counter sync is deferred into the chunk's drain: counts
    ride the same batched pull as the mesh download, after the next
    chunk is already enqueued.

    Writeback generalizes the old contiguous ``_assign`` to index
    lists: only the first ``nreal`` rows of a padded tail plan are
    scattered back.  ``tim`` (utils.timers.Timers) records the
    upload / compute-wait / download / writeback split; the compute
    wait of a drained chunk overlaps the next chunk's execution, so
    the recorded segments are the PIPELINE's residual stalls, not raw
    kernel time.

    PARMMG_GROUP_PIPELINE=0 serializes (drain each chunk before
    enqueuing the next): double-buffering holds TWO chunk states on
    device instead of the legacy loop's one, and a PARMMG_GROUP_CHUNK
    tuned against the HBM ceiling (the 16 GB-chip OOM note below) may
    need the legacy memory bound back rather than a smaller chunk.

    Fault tolerance (resilience/): a chunk whose dispatch or drain
    fails (the tunnel's mid-session crash mode; injectable via
    ``PARMMG_FAULT=dispatch.chunk``) is re-run SERIALLY under the
    retry/backoff wrapper.  This is exact, not best-effort: the host
    state is only mutated by a drain's writeback (its last step, and
    idempotent), so a failed chunk's inputs are intact and a
    re-dispatch from them is bit-identical.  Retry-budget exhaustion
    raises ``RetryBudgetExhausted`` — the driver's LOWFAILURE signal.
    ``done`` (optional dict) records each plan's counts as its drain
    COMMITS (i.e. after writeback): a caller catching the exhaustion
    can tell exactly which plans already mutated the host state and
    which never ran — the serve pool's isolation fallback needs that
    to avoid re-applying a wave to already-advanced slots.

    Returns the per-plan host count arrays (trimmed to nreal), in plan
    order."""
    import os
    from ..resilience.faults import faultpoint
    from ..resilience.recover import retry_call
    from ..resilience.watchdog import deadline_knob, run_with_deadline
    from .sched import pad_mask
    depth = 2 if os.environ.get("PARMMG_GROUP_PIPELINE", "1") != "0" \
        else 1
    # deadline watchdog on each dispatch/drain unit (0 = off, the
    # default): a wedged device dispatch raises WatchdogTimeout into
    # the SAME except/redo/retry path as a crashed one.  The abandoned
    # monitor-thread attempt is harmless here: a drain's writeback is
    # idempotent and deterministic, so a late commit racing the retry
    # writes identical bytes (the redo contract below)
    ddl = deadline_knob("PARMMG_DEADLINE_DISPATCH_S")
    out = [None] * len(plans)

    def dispatch(pi, idx, nreal):
        with tim("upload"):
            sl = jax.tree.map(lambda a: jnp.asarray(a[idx]), stacked)
            kl = jnp.asarray(met_s[idx])
            # device quiet mask: the repeat-padded tail rows compute
            # nothing (lax.cond identity) — their results were always
            # discarded at writeback (sched.pad_mask)
            act = jnp.asarray(pad_mask(len(idx), nreal))
            tl = None if topo is None else \
                jax.tree.map(lambda a: jnp.asarray(a[idx]), topo)
        faultpoint("dispatch.chunk", key=str(pi))
        with otrace.annotate(f"grp_dispatch_chunk{pi}"):
            if topo is None:
                m, k, cnt = fn(sl, kl, wave, act, *extra)
                tp = None
            else:
                m, k, cnt, tp = fn(sl, kl, wave, act, *extra, tl)
        return (pi, idx, nreal, m, k, cnt, tp)

    # lint: ok(R2) — the pipeline's ONE designed sync point: chunked
    # mode keeps the pass state host-resident, so the drain downloads
    # O(chunk) tables + [chunk,nblk,9] counters while chunk k+1 is
    # already dispatched (PR-5 double buffering; segments timed)
    def drain(p):
        pi, idx, nreal, m, k, cnt, tp = p
        with tim("compute"):
            jax.block_until_ready(cnt)
        with tim("download"):
            mh = jax.tree.map(lambda s: np.asarray(s), m)
            kh = np.asarray(k)
            th = None if tp is None else \
                jax.tree.map(lambda s: np.asarray(s), tp)
            out[pi] = np.asarray(cnt)[:nreal]
        with tim("writeback"):
            rows = idx[:nreal]

            def w(d, s):
                d[rows] = s[:nreal]
                return d
            jax.tree.map(w, stacked, mh)
            met_s[rows] = kh[:nreal]
            if th is not None:
                jax.tree.map(w, topo, th)
        if done is not None:
            done[pi] = out[pi]

    # the watchdog-guarded forms (inline when PARMMG_DEADLINE_DISPATCH_S
    # is 0/unset — zero threads on the default path)
    def gdispatch(pi, idx, nreal):
        return run_with_deadline(lambda: dispatch(pi, idx, nreal),
                                 ddl, "dispatch.chunk")

    def gdrain(p):
        return run_with_deadline(lambda: drain(p), ddl,
                                 "dispatch.chunk")

    def redo(pi, idx, nreal, first):
        # serial dispatch+drain re-attempt of one failed chunk; the
        # inline fast-path attempt already counted (initial_failure).
        # One deadline bounds the serial pair: a retry that ALSO wedges
        # keeps feeding the retry budget until it exhausts (LOWFAILURE)
        retry_call(lambda: run_with_deadline(
            lambda: drain(dispatch(pi, idx, nreal)), ddl,
            "dispatch.chunk"),
            site="dispatch.chunk", initial_failure=first)

    def safe_drain(p):
        try:
            gdrain(p)
        except Exception as e:
            redo(p[0], p[1], p[2], e)

    pending = None
    for pi, (idx, nreal) in enumerate(plans):
        cur = first = None
        try:
            cur = gdispatch(pi, idx, nreal)
        except Exception as e:
            first = e
        if pending is not None:
            p0, pending = pending, None
            safe_drain(p0)
        if cur is None:
            redo(pi, idx, nreal, first)
        elif depth == 1:
            safe_drain(cur)
        else:
            pending = cur
    if pending is not None:
        safe_drain(pending)
    return out


def grouped_adapt_pass(mesh: Mesh, met, ngroups: int, cycles: int = 12,
                       part: np.ndarray | None = None,
                       verbose: int = 0, stats=None,
                       noinsert: bool = False, noswap: bool = False,
                       nomove: bool = False, hausd: float | None = None,
                       polish: bool = False, cap_mult: float = 3.0,
                       timers=None, ckpt_tag: str | None = None,
                       ckpt_it: int = 0):
    """One outer pass: split into groups, run adapt cycles with lax.map
    over the group axis, merge.  Returns (mesh, met, part_of_merged).

    The per-group program is the SAME adapt_cycle_impl as the whole-mesh
    path (frozen MG_PARBDY group seams make it correct); the map axis
    serializes groups so HBM holds one group's working set at a time.

    Quiet-group scheduler (parallel/sched.py, PARMMG_GROUP_SCHED=0 to
    disable): per-group counts mark groups quiet once a swap-inclusive
    block is a no-op for them, and subsequent chunked dispatches gather
    only the ACTIVE indices — same compiled [chunk, ...] program, fewer
    executions of it.  The quiet proof is ALSO pushed down into the
    compiled programs as a device-resident active mask
    (PARMMG_DEVICE_MASK=0 to disable): every group-block dispatch takes
    a per-slot bool mask and ``lax.cond``-skips the wave math for
    inactive slots — quiet groups of an unchunked dispatch (where
    compaction cannot change the dispatch shape) and the repeat-padded
    tail rows of chunk plans.  Skipping is bit-for-bit exact either way
    (frozen seams + deterministic waves make a zero-op state a fixed
    point; see the sched module docstring for the prescreen-level and
    regrow caveats).
    Chunked dispatches ride a double-buffered pipeline
    (:func:`_pipeline_chunks`); its upload/compute/download/writeback
    split lands in ``timers`` (driver reporting) and, with the
    skipped-group / saved-dispatch counters and the active-group
    trajectory, in ``stats.sched_extra``.
    """
    from ..ops.adapt import default_cycle_block
    from ..utils.timers import Timers
    from .partition import morton_partition, fix_contiguity
    from .distribute import split_to_shards, merge_shards, grow_shards
    from .sched import QuietGroupScheduler
    from ..core.mesh import mesh_to_host

    vert_h, tet_h, _, _, _ = mesh_to_host(mesh)
    if part is None:
        cent = vert_h[tet_h].mean(axis=1)
        part = fix_contiguity(tet_h, morton_partition(cent, ngroups))

    # chunked dispatch (group_chunk docstring): pad the group axis so
    # every chunk runs the SAME compiled [chunk,...] program.  In chunk
    # mode the stacked state lives in HOST RAM between dispatches and
    # only the in-flight chunk occupies HBM — the zaldy_pmmg.c memory
    # philosophy at chip scale: this is what bounds peak HBM by the
    # CHUNK, not the mesh (a device-resident 43-group state OOMed the
    # 16 GB chip mid-polish at the 1M-tet scale, 2026-08-02), and what
    # makes the 10M-tet configuration fit.  The split itself is staged
    # on the CPU backend for the same reason: split_to_shards runs a
    # per-shard adjacency program and stacks the result, which would
    # otherwise materialize the WHOLE stacked state in HBM.
    chunk = group_chunk(ngroups)
    if chunk:
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            stacked, met_s = split_to_shards(mesh, met, part, ngroups,
                                             cap_mult=cap_mult)
            g_exec = -(-ngroups // chunk) * chunk
            # np.array (copy): np.asarray of a jax array can hand back
            # a READ-ONLY buffer, and the host state is mutated in
            # place by the per-chunk writebacks
            stacked = jax.tree.map(
                lambda a: np.array(a), _pad_groups(stacked, g_exec))
            met_s = np.array(_pad_groups(met_s, g_exec))
    else:
        chunk = 0
        g_exec = ngroups
        stacked, met_s = split_to_shards(mesh, met, part, ngroups,
                                         cap_mult=cap_mult)

    def _assign(dst_tree, src_tree, g0):
        """Write a chunk's device results back into the host state
        (contiguous-slice legacy form; the scheduler path scatters by
        index list inside :func:`_pipeline_chunks`)."""
        def w(d, s):
            d[g0:g0 + chunk] = np.asarray(s)
            return d
        jax.tree.map(w, dst_tree, src_tree)

    sched = QuietGroupScheduler(ngroups, g_exec, chunk)
    # smoothing-cadence enable as a DEVICE SCALAR: always an argument
    # of the compiled block (like the quiet mask), so toggling
    # PARMMG_SMOOTH_CADENCE mints zero new compile families
    from .sched import cadence_enabled
    cad = jnp.asarray(cadence_enabled())
    # incremental topology engine (ops/topo_incr, PARMMG_INCR_TOPO):
    # per-slot retained-table + dirty-band state rides the group axis —
    # host-resident in chunk mode (rows committed by drain writebacks,
    # same idempotent contract as the mesh state), device-resident
    # otherwise.  The knob is a traced scalar like the cadence.
    from ..ops.topo_incr import incr_topo_enabled, topo_init, topo_init_np
    inc = jnp.asarray(incr_topo_enabled())
    capT_s = stacked.tet.shape[1]
    topo_s = topo_init_np(g_exec, capT_s) if chunk else \
        topo_init(capT_s, stack=g_exec)
    # pipeline segment timers on a LOCAL registry: folded into
    # stats.sched_extra and (prefixed) into the caller's Timers at the
    # end, so the driver report shows the transfer/compute split
    ltim = Timers()
    block = default_cycle_block(stacked.vert)
    c = 0
    regrows = 0
    dirty_traj: list[int] = []
    while c < cycles:
        nblk = min(block, cycles - c)
        flags, pres = block_schedule(c, nblk, cycles, noswap)
        step = _group_block(flags, pres, nomove, noinsert, hausd)
        swap_inc = any(flags) or noswap
        pres_all_on = all(pres)
        wave = jnp.asarray(c, jnp.int32)
        act, plans = sched.plan_block(pres_all_on)
        with otrace.context(block=c, chunk=chunk or 0):
            if chunk:
                parts = _pipeline_chunks(step, stacked, met_s, wave,
                                         plans, ltim, extra=(cad, inc),
                                         topo=topo_s)
                sched.note_plan_pads(plans)
                counts_act = np.concatenate(parts) if parts else \
                    np.zeros((0, nblk, 9), np.int32)
                if sched.enabled:
                    otrace.log(
                        2, f"  grp block {c}..{c + nblk - 1}: active "
                           f"{len(act)}/{g_exec} groups, {len(plans)} "
                           "dispatches", verbose=verbose)
            else:
                # unchunked: compaction cannot change the dispatch
                # shape — the device-resident quiet mask is what skips
                # converged groups here (lax.cond identity rows,
                # sched.block_mask; bit-for-bit by the fixed point)
                stacked, met_s, counts, topo_s = step(
                    stacked, met_s, wave,
                    jnp.asarray(sched.block_mask(pres_all_on)), cad,
                    inc, topo_s)
                counts_act = np.asarray(counts)  # [g_exec, nblk, 9]
        sched.record_block(act, counts_act, swap_inc, pres_all_on)
        # quiet groups contribute exact zeros (that is what marked them)
        cs = counts_act.sum(axis=0, dtype=np.int64)     # [nblk, 8]
        # ONE host conversion for the whole block's counters (counts_act
        # is already host numpy — the drain pulled it); the per-counter
        # int() casts were R2-baselined noise
        cs_l = cs.tolist()                              # python ints
        for i in range(nblk):
            tot = cs_l[i]
            # counts[8]: dirty tets pending at each cycle start, summed
            # over groups — the band-occupancy trajectory (bench extras)
            dirty_traj.append(tot[8])
            if stats is not None:
                stats.nsplit += tot[0]
                stats.ncollapse += tot[1]
                stats.nswap += tot[2]
                stats.nmoved += tot[3]
                stats.cycles += 1
            otrace.log(3, f"  grp cycle {c + i}: split {tot[0]} "
                          f"collapse {tot[1]} swap {tot[2]} move "
                          f"{tot[3]} over {ngroups} groups",
                       verbose=verbose)
        if any(row[4] != 0 for row in cs_l):
            if regrows >= 6:
                raise MemoryError("group capacity exhausted")
            capP = stacked.vert.shape[1]
            capT = stacked.tet.shape[1]
            if chunk:
                # host-resident grow (np.pad mirror of grow_shards —
                # jnp.pad would re-materialize the state on device)
                import dataclasses as _dc

                def _padP(x, fill=0):
                    pad = [(0, 0)] * x.ndim
                    pad[1] = (0, capP)
                    return np.pad(x, pad, constant_values=fill)

                def _padT(x, fill=0):
                    pad = [(0, 0)] * x.ndim
                    pad[1] = (0, capT)
                    return np.pad(x, pad, constant_values=fill)

                stacked = _dc.replace(
                    stacked,
                    vert=_padP(stacked.vert), vref=_padP(stacked.vref),
                    vtag=_padP(stacked.vtag),
                    vmask=_padP(stacked.vmask, False),
                    tet=_padT(stacked.tet), tref=_padT(stacked.tref),
                    tmask=_padT(stacked.tmask, False),
                    adja=_padT(stacked.adja, -1),
                    ftag=_padT(stacked.ftag), fref=_padT(stacked.fref),
                    etag=_padT(stacked.etag))
                met_s = _padP(met_s)
            else:
                stacked, met_s = grow_shards(stacked, met_s, 2 * capP,
                                             2 * capT)
            # regrow permutes tet slots (compact) and changes capT: the
            # retained sorts are stale at the new capacity — re-init
            # (ok=False => next derivation is a full rebuild, exact)
            capT_s = stacked.tet.shape[1]
            topo_s = topo_init_np(g_exec, capT_s) if chunk else \
                topo_init(capT_s, stack=g_exec)
            regrows += 1
            # the wave top-K budgets scale with capT: every quiet proof
            # is stale at the new capacity — reactivate the full set
            # (truncated winners must rerun)
            sched.on_regrow()
            continue        # re-run the block: truncated winners rerun
        c += nblk
        if block_converged(cs, flags, noswap):
            break
    pol_traj: list[int] = []
    if polish and not (noinsert and noswap and nomove):
        # grouped bad-element pass: sliver_polish per group under the
        # same lax.map regime (seams stay frozen; the outer-iteration
        # displacement exposes them to a later pass).  This is what
        # makes a >=1M-tet run report a REAL post-tail min quality
        # without a whole-mesh-width program (which does not compile
        # through the TPU tunnel at that width).
        polish_block = _group_polish_block(noinsert, noswap, nomove,
                                           hausd)

        if chunk and _polish_subproc():
            # fresh-process polish (see _polish_worker module docstring:
            # the tunnel worker reliably dies when this program lands
            # late in a long session; a fresh client runs it fine).
            # Worker failure (rc != 0 — the real tunnel-crash shape,
            # injectable via PARMMG_FAULT=polish.worker) is a ladder
            # path: retry with backoff in a fresh process first (the
            # invocation is idempotent from in.npz), then degrade one
            # rung — grouped polish skipped, the caller's merged polish
            # + repair tail still covers the quality tail.  The temp
            # .npz staging (multi-GB at the 1M-tet scale) is removed in
            # a finally: a crashed worker or an unwinding retry must
            # not leak it in /tmp.
            import shutil
            import subprocess
            import sys as _sys
            import tempfile
            from ..core.mesh import MESH_FIELDS
            from ..obs.metrics import REGISTRY
            from ..resilience.faults import subprocess_fault_env
            from ..resilience.recover import (RetryBudgetExhausted,
                                              WorkerExitError,
                                              ladder_step, retry_call)
            from ..resilience.watchdog import (WatchdogTimeout,
                                               deadline_knob,
                                               record_timeout)
            td = tempfile.mkdtemp(prefix="parmmg_polish_")
            try:
                inp, outp = f"{td}/in.npz", f"{td}/out.npz"
                np.savez(inp, met=met_s, chunk=chunk, ngroups=ngroups,
                         noinsert=noinsert, noswap=noswap, nomove=nomove,
                         hausd=(np.nan if hausd is None else hausd),
                         **{f: getattr(stacked, f) for f in MESH_FIELDS})
                import os as _os
                env0 = dict(_os.environ)
                pkg_parent = _os.path.dirname(_os.path.dirname(
                    _os.path.dirname(_os.path.abspath(__file__))))
                env0["PYTHONPATH"] = (env0.get("PYTHONPATH", "") +
                                      _os.pathsep + pkg_parent).lstrip(
                    _os.pathsep)

                # wall-clock bound on each worker invocation (0 = off):
                # a WEDGED worker used to hang the whole pass forever —
                # run() kills the subprocess on expiry and the
                # WatchdogTimeout rides the same retry -> merged_polish
                # ladder as a crashed worker.  Size the knob for a cold
                # worker (it pays its own compiles per invocation)
                wdl = deadline_knob("PARMMG_POLISH_TIMEOUT_S")

                def _invoke():
                    if _os.path.exists(outp):
                        _os.unlink(outp)        # stale partial output
                    env = dict(env0)
                    env.update(subprocess_fault_env("polish.worker"))
                    try:
                        r = subprocess.run(
                            [_sys.executable, "-m",
                             "parmmg_tpu.parallel._polish_worker", inp,
                             outp],
                            stderr=subprocess.PIPE, text=True, env=env,
                            timeout=wdl or None)
                    except subprocess.TimeoutExpired as te:
                        # run() already killed the worker; drop any
                        # partial output so no retry (or a later code
                        # path) can ever load a half-written npz
                        if _os.path.exists(outp):
                            _os.unlink(outp)
                        record_timeout("polish.worker", wdl)
                        raise WatchdogTimeout("polish.worker",
                                              wdl) from te
                    if r.returncode != 0:
                        raise WorkerExitError("polish.worker",
                                              r.returncode, r.stderr)
                    return r
                try:
                    r = retry_call(_invoke, site="polish.worker")
                    import dataclasses as _dc
                    z = np.load(outp)
                    stacked = _dc.replace(
                        stacked, **{f: z[f] for f in MESH_FIELDS})
                    met_s = z["met"]
                    if r.stderr:
                        # relay the worker's stderr protocol lines
                        # through the one gated print path
                        otrace.log(2, r.stderr.rstrip("\n"),
                                   verbose=verbose)
                except RetryBudgetExhausted as e:
                    REGISTRY.counter(
                        "resilience.polish_worker_failures").inc()
                    ladder_step("merged_polish", site="polish.worker",
                                detail=str(e.__cause__ or e))
                    otrace.log(1, "  ## Warning: grouped polish worker "
                                  f"failed ({e.__cause__ or e}); "
                                  "skipping grouped polish — the merged "
                                  "polish + repair tail still runs.",
                               err=True)
            finally:
                shutil.rmtree(td, ignore_errors=True)
        elif chunk and sched.enabled:
            # quiet-group polish: wave-major over COMPACTED active
            # chunks, retiring each group at its own collapse+swap==0
            # point — the per-group form of the legacy loop's per-chunk
            # break (identical to it at chunk granularity 1; the old
            # chunk-coupled break let a chunk-mate's work extend a quiet
            # group's wave count, an artifact the compaction drops).
            # All groups re-enter here: polish ops (sliver collapses,
            # swapgen, opt-q smoothing) are a different candidate class
            # than the cycle loop, so cycle-quiet proves nothing.
            # Trade-off vs the legacy chunk-resident loop: a group
            # active for w waves is shipped w times instead of once —
            # paid back by retirement shrinking later waves and by the
            # pipeline overlapping the transfers; the TPU in-session
            # case keeps the legacy loop via PARMMG_GROUP_SCHED=0 (the
            # default TPU polish rides the subprocess worker anyway).
            from .sched import chunk_plans
            from ..resilience.recover import (RetryBudgetExhausted,
                                              ladder_step)
            pol_act = np.arange(ngroups)
            try:
                for w in range(4):
                    if not len(pol_act):
                        break
                    plans = chunk_plans(pol_act, chunk)
                    sched.dispatches += len(plans)
                    parts = _pipeline_chunks(
                        polish_block, stacked, met_s,
                        jnp.asarray(2000 + w, jnp.int32), plans, ltim)
                    sched.note_plan_pads(plans)
                    cnts = np.concatenate(parts)      # [n_act, 4]
                    pol_traj.append(len(pol_act))
                    tot = cnts.sum(axis=0, dtype=np.int64).tolist()
                    otrace.log(2, f"  grp polish w{w}: collapse "
                                  f"{tot[0]} swap {tot[1]} "
                                  f"move {tot[2]} over "
                                  f"{len(pol_act)} active groups",
                               verbose=verbose)
                    pol_act = pol_act[(cnts[:, 0] + cnts[:, 1]) > 0]
            except RetryBudgetExhausted as e:
                # polish is a quality tail, not the sizing loop: a
                # persistent dispatch fault here degrades one rung
                # (remaining grouped polish skipped — the state is
                # conforming with or without it; committed chunks keep
                # their polish) instead of escalating to the driver's
                # LOWFAILURE, which would throw away the whole adapted
                # mesh (README ladder: merged_polish)
                ladder_step("merged_polish", site="dispatch.chunk",
                            detail=str(e.__cause__ or e))
                otrace.log(1, "  ## Warning: grouped polish dispatch "
                              f"kept failing ({e.__cause__ or e}); "
                              "skipping the remaining grouped polish "
                              "waves — the merged polish + repair tail "
                              "still runs.", err=True)
        elif chunk:
            # per-chunk wave loop (PARMMG_GROUP_SCHED=0 legacy): each
            # chunk polishes to ITS quiet point while resident, one
            # upload/download per chunk total
            for g0 in range(0, g_exec, chunk):
                sl = jax.tree.map(
                    lambda a: jnp.asarray(a[g0:g0 + chunk]), stacked)
                kl = jnp.asarray(met_s[g0:g0 + chunk])
                for w in range(4):
                    sl, kl, cnt = polish_block(
                        sl, kl, jnp.asarray(2000 + w, jnp.int32),
                        jnp.ones(chunk, bool))
                    # one host pull for the chunk's counters (the
                    # legacy loop's designed sync point), python ints
                    # from it without per-counter casts
                    tot = np.asarray(cnt).sum(axis=0).tolist()
                    otrace.log(2, f"  grp polish chunk {g0 // chunk} "
                                  f"w{w}: collapse {tot[0]} swap "
                                  f"{tot[1]} move {tot[2]}",
                               verbose=verbose)
                    if tot[0] == 0 and tot[1] == 0:
                        break
                _assign(stacked, sl, g0)
                met_s[g0:g0 + chunk] = np.asarray(kl)
        else:
            for w in range(4):
                stacked, met_s, cnt = polish_block(
                    stacked, met_s, jnp.asarray(2000 + w, jnp.int32),
                    jnp.ones(g_exec, bool))
                tot = np.asarray(cnt).sum(axis=0).tolist()
                otrace.log(2, f"  grp polish {w}: collapse "
                              f"{tot[0]} swap {tot[1]} move "
                              f"{tot[2]}", verbose=verbose)
                if tot[0] == 0 and tot[1] == 0:
                    break
    # fold the scheduler instrumentation: counters + the active-group
    # trajectory into AdaptStats.sched_extra (bench/SCALE artifacts),
    # the pipeline segment times into the caller's Timers (driver
    # report) under a "grp <segment>" prefix
    # chunk auto-tune (ROADMAP 1b, lightweight): fold this pass's
    # active-group trajectory into a chunk recommendation for the NEXT
    # pass — adopted only under PARMMG_GROUP_CHUNK=auto, logged always.
    # The cost model's overhead constant is CALIBRATED from this pass's
    # measured pipeline segment timings when a chunked pipeline ran
    # (sched.calibrate_dispatch_overhead; hand-set default otherwise)
    from .sched import (calibrate_dispatch_overhead,
                        note_chunk_recommendation, recommend_group_chunk)
    overhead = calibrate_dispatch_overhead(ltim.acc, ltim.count, chunk) \
        if chunk else None
    chunk_rec = recommend_group_chunk(
        sched.active_per_block, g_exec if chunk else ngroups,
        dispatch_overhead=(1.0 if overhead is None else overhead))
    note_chunk_recommendation(chunk_rec)
    otrace.log(2, f"  grp chunk auto-tune: recommend "
                  f"PARMMG_GROUP_CHUNK={chunk_rec or 'unchunked'} "
                  f"(current {chunk or 'unchunked'}, overhead "
                  f"{'default' if overhead is None else round(overhead, 3)}"
                  " group-units)", verbose=verbose)
    # metrics spine: the pass's scheduler counters + pipeline segment
    # seconds land in the process registry regardless of whether the
    # caller threaded a stats/timers object through
    from ..obs.metrics import REGISTRY
    REGISTRY.counter("groups.dispatches").inc(sched.dispatches)
    REGISTRY.counter("groups.dispatches_saved").inc(
        sched.saved_dispatches)
    REGISTRY.counter("groups.group_blocks_skipped").inc(
        sched.skipped_group_blocks)
    # group-slot executions the device-resident quiet mask cond-skipped
    # (unchunked quiet slots + padded tail rows of chunk plans)
    REGISTRY.counter("groups.cond_skipped").inc(sched.cond_skipped)
    REGISTRY.gauge("groups.chunk_recommendation").set(chunk_rec)
    if overhead is not None:
        REGISTRY.gauge("groups.chunk_overhead_units").set(overhead)
    for k, v in ltim.acc.items():
        # lint: ok(R6) — k ranges over the fixed _pipeline_chunks
        # segment set (upload/compute/download/writeback): bounded
        REGISTRY.counter(f"groups.pipeline.{k}_s").inc(v)
    if stats is not None:
        stats.group_dispatches += sched.dispatches
        stats.group_dispatches_saved += sched.saved_dispatches
        stats.groups_skipped += sched.skipped_group_blocks
        se = stats.sched_extra
        se["cond_skipped_rows"] = se.get("cond_skipped_rows", 0) + \
            sched.cond_skipped
        se.setdefault("chunk_recommendation", []).append(chunk_rec)
        if overhead is not None:
            se.setdefault("chunk_overhead_units", []).append(
                round(overhead, 4))
        se.setdefault("active_groups_per_block", []).extend(
            sched.active_per_block)
        if dirty_traj:
            # per-cycle dirty-band occupancy (counts[8] summed over
            # groups): shows when the incremental path engages and how
            # small the decay-regime bands get (bench extra.incr_topo)
            se.setdefault("incr_dirty_per_cycle", []).extend(dirty_traj)
        if pol_traj:
            se.setdefault("polish_active_per_wave", []).extend(pol_traj)
        for k, v in ltim.acc.items():
            se[f"grp_{k}_s"] = se.get(f"grp_{k}_s", 0.0) + v
    if timers is not None:
        for k, v in ltim.acc.items():
            timers.add(f"grp {k}", v, ltim.count[k])
    # pass-level durability (resilience/checkpoint.py): the pre-merge
    # stacked state doubles as the merge-free distributed-file snapshot
    # of this pass (the reference's -distributed-output checkpoint
    # role).  ckpt_due-gated: free unless PARMMG_CKPT_DIR is armed.
    if ckpt_tag is not None:
        from ..resilience.checkpoint import snapshot_stacked
        snapshot_stacked(ckpt_tag, ckpt_it, stacked, ngroups)
    if chunk:
        # merge on the CPU backend: merge_shards rebuilds adjacency at
        # MERGED-mesh width — a whole-mesh device program that OOMs the
        # chip at the >=1M-tet scale (same staging rule as the split)
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            return merge_shards(stacked, met_s, return_part=True)
    return merge_shards(stacked, met_s, return_part=True)


@otrace.profile_guard()
def grouped_adapt(mesh: Mesh, met, target_size: int, niter: int = 3,
                  cycles: int = 12, verbose: int = 0, stats=None,
                  noinsert: bool = False, noswap: bool = False,
                  nomove: bool = False, hausd: float | None = None,
                  ifc_layers: int = 2, timers=None,
                  resume: bool = False, ckpt_tag: str = "grouped"):
    """The two-level outer loop on one device: grouped passes with
    interface displacement between them (the rank-level loop of
    libparmmg1.c:636-948 collapsed onto one device, groups as the only
    level).  Engaged by the driver when ``-mesh-size`` yields >= 2
    groups.

    Durability (resilience/checkpoint.py, PARMMG_CKPT_DIR armed): the
    merged state + displaced partition are checkpointed after each
    completed outer pass; ``resume=True`` restarts from the newest
    complete pass checkpoint instead of from scratch.  Passes are
    deterministic from their input state, so a resumed run finishes
    bit-identical to an uninterrupted one (chaos-gated)."""
    from .partition import move_interfaces
    from ..core.mesh import mesh_to_host
    from ..resilience import checkpoint as ckpt

    part = None
    it0 = 0
    # run-identity fingerprint of the ORIGINAL input: stored in every
    # checkpoint and matched at resume, so a reused PARMMG_CKPT_DIR can
    # never silently resume a stale checkpoint from a different run
    fp = None
    if resume or ckpt.ckpt_config()[0]:
        fp = ckpt.run_fingerprint(mesh, met, target_size, niter, cycles,
                                  noinsert, noswap, nomove, hausd,
                                  ifc_layers)
    if resume:
        found = ckpt.latest_pass_checkpoint(ckpt_tag, fingerprint=fp)
        if found is not None:
            path, k = found
            mesh, met, part, _ = ckpt.load_pass_checkpoint(path)
            it0 = k + 1
            from ..obs.metrics import REGISTRY
            REGISTRY.counter("resilience.resumes").inc()
            otrace.event("ckpt.resumed", tag=ckpt_tag, it=it0, path=path)
            otrace.log(1, f"  resume: loaded {path}; restarting at "
                          f"outer pass {it0}", err=True)
            # crash-loop breaker: resuming into the SAME pass more
            # than PARMMG_RESUME_MAX times means that pass
            # deterministically kills the run — skip past it and hand
            # the caller the last conforming checkpointed state (the
            # bounded-time contract; the driver's merged polish /
            # repair tail still runs on it).  The mh_allgather-style
            # rung for this site is the merged_polish-grade skip:
            # record it on the ladder so the run's failure story shows
            # the escalation
            _, esc = ckpt.crash_loop(ckpt_tag, fp, it0)
            if esc:
                from ..resilience.recover import ladder_step
                ladder_step("lowfailure", site="ckpt.resume",
                            detail=f"crash loop at pass {it0}: "
                                   "returning last conforming "
                                   "checkpoint")
                return mesh, met
    for it in range(it0, max(1, niter)):
        # profiler capture window (PARMMG_PROFILE_DIR over the
        # PARMMG_PROFILE_PASS outer-pass range — obs/trace.py)
        otrace.profile_pass_begin(it)
        with otrace.context(**{"pass": it}):
            ne = int(np.asarray(mesh.tmask).sum())
            # a displaced partition fixes the group count (its labels
            # index the previous split); fresh iterations re-derive it
            ngroups = (int(part.max()) + 1) if part is not None \
                else how_many_groups(ne, target_size)
            if ngroups < 2:
                from ..ops.adapt import adapt_mesh
                mesh, met, st = adapt_mesh(
                    mesh, met, verbose=verbose, noinsert=noinsert,
                    noswap=noswap, nomove=nomove, hausd=hausd)
                if stats is not None:
                    stats += st
                part = None
                ckpt.save_pass_checkpoint(ckpt_tag, it, mesh, met, part,
                                          fingerprint=fp)
                otrace.profile_pass_end(it)
                continue
            mesh, met, part_m = grouped_adapt_pass(
                mesh, met, ngroups, cycles=cycles, part=part,
                verbose=verbose, stats=stats, noinsert=noinsert,
                noswap=noswap, nomove=nomove, hausd=hausd,
                timers=timers, ckpt_tag=ckpt_tag, ckpt_it=it)
            if it + 1 < max(1, niter):
                _, tet_h, _, _, _ = mesh_to_host(mesh)
                part = move_interfaces(tet_h, part_m, ngroups,
                                       nlayers=ifc_layers)
                # the checkpoint carries the DISPLACED labels: pass
                # it+1's exact input, which is what makes resume
                # bit-identical to the uninterrupted run
                ckpt.save_pass_checkpoint(ckpt_tag, it, mesh, met, part,
                                          fingerprint=fp)
            else:
                # the FINAL pass checkpoints too (part=None — there is
                # no next pass to feed): a kill during the caller's
                # post-adapt tail (merged polish / repair / IO, minutes
                # at the 1M-tet scale) must not restart the whole
                # adaptation; resume with it0 == niter skips the loop
                # and hands the tail this state
                ckpt.save_pass_checkpoint(ckpt_tag, it, mesh, met,
                                          None, fingerprint=fp)
        otrace.profile_pass_end(it)
    return mesh, met
