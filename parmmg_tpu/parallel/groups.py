"""Two-level decomposition: sub-device remesh groups.

The reference splits each rank's mesh into ``-mesh-size``-element groups
and remeshes them one at a time (``PMMG_splitPart_grps`` / ``howManyGroups``
grpsplit_pmmg.c:47,1551-1614, capped at ``PMMG_REMESHER_NGRPS_MAX``); the
group is the unit that bounds the remesher's working set.  TPU-native
analogue: groups are slots of a stacked pytree traversed with ``lax.map``
— XLA compiles ONE cycle program for the group shape and executes it per
group, so peak HBM scales with the GROUP capacity, not the mesh.  Mesh
size per chip is then bounded by HBM-for-one-group x ngroups, which is
what makes the 10M-tet configuration reachable on a single chip.  (A
``vmap`` over groups would process them concurrently — same peak memory
as no groups at all; ``map`` is the memory-bounding choice.  Groups also
shorten the O(n log^2 n) TPU sorts inside each wave.)

Group interfaces are frozen exactly like rank interfaces (MG_PARBDY —
the same ``split_to_shards`` freeze contract, tag_pmmg.c:39-124) and
displaced between outer iterations with the same advancing-front
machinery, so previously-frozen group seams get remeshed later — the
two-level loop of the reference.

``-metis-ratio`` note: the reference multiplies the group count by
``metis_ratio`` for the REDISTRIBUTION split, whose many small groups are
the METIS graph nodes (grpsplit_pmmg.c:1595-1614).  This framework
migrates interface bands directly (parallel/migrate.py) instead of
re-partitioning a group graph, so the flag has no load-bearing role; it
is parsed and validated for CLI parity only.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.mesh import Mesh
from ..core import constants as C


def how_many_groups(ne: int, target: int) -> int:
    """Group count with the reference's clamps (grpsplit_pmmg.c:47)."""
    if target <= 0:
        return 1
    return max(1, min((ne + target - 1) // target, C.REMESHER_NGRPS_MAX))


def grouped_adapt_pass(mesh: Mesh, met, ngroups: int, cycles: int = 12,
                       part: np.ndarray | None = None,
                       verbose: int = 0, stats=None,
                       noinsert: bool = False, noswap: bool = False,
                       nomove: bool = False, hausd: float | None = None,
                       polish: bool = False):
    """One outer pass: split into groups, run adapt cycles with lax.map
    over the group axis, merge.  Returns (mesh, met, part_of_merged).

    The per-group program is the SAME adapt_cycle_impl as the whole-mesh
    path (frozen MG_PARBDY group seams make it correct); the map axis
    serializes groups so HBM holds one group's working set at a time.
    """
    from ..ops.adapt import adapt_cycle_impl, default_cycle_block
    from .partition import morton_partition, fix_contiguity
    from .distribute import split_to_shards, merge_shards, grow_shards
    from ..core.mesh import mesh_to_host

    vert_h, tet_h, _, _, _ = mesh_to_host(mesh)
    if part is None:
        cent = vert_h[tet_h].mean(axis=1)
        part = fix_contiguity(tet_h, morton_partition(cent, ngroups))
    stacked, met_s = split_to_shards(mesh, met, part, ngroups,
                                     cap_mult=3.0)

    def one_block(flags: tuple):
        # fused cycle block inside the lax.map body: one dispatch + one
        # counter pull per block per outer step (ops.adapt
        # adapt_cycles_fused analogue for the group axis)
        def body(args):
            m, k, wave = args
            counts_all = []
            for cc, dosw in enumerate(flags):
                m, k, counts = adapt_cycle_impl(
                    m, k, wave + cc, do_swap=dosw,
                    do_smooth=not nomove, do_insert=not noinsert,
                    hausd=hausd, final_rebuild=(cc == len(flags) - 1))
                counts_all.append(counts)
            return m, k, jnp.stack(counts_all)       # [n, 6]

        @jax.jit
        def run(stacked, met_s, wave):
            waves = jnp.full(ngroups, wave, jnp.int32)
            m, k, counts = jax.lax.map(body, (stacked, met_s, waves))
            return m, k, counts                      # counts [G, n, 6]

        return run

    steps: dict = {}
    block = default_cycle_block(stacked.vert)
    c = 0
    regrows = 0
    while c < cycles:
        nblk = min(block, cycles - c)
        flags = tuple((cc % 3 == 2 or cc >= cycles - 2) and not noswap
                      for cc in range(c, c + nblk))
        if flags not in steps:
            steps[flags] = one_block(flags)
        stacked, met_s, counts = steps[flags](stacked, met_s,
                                              jnp.asarray(c, jnp.int32))
        cs = np.asarray(counts).sum(axis=0)       # [n, 6] over groups
        for i in range(nblk):
            tot = cs[i]
            if stats is not None:
                stats.nsplit += int(tot[0])
                stats.ncollapse += int(tot[1])
                stats.nswap += int(tot[2])
                stats.nmoved += int(tot[3])
                stats.cycles += 1
            if verbose >= 3:
                print(f"  grp cycle {c + i}: split {tot[0]} collapse "
                      f"{tot[1]} swap {tot[2]} move {tot[3]} over "
                      f"{ngroups} groups")
        if int(cs[:, 4].max()) != 0:
            if regrows >= 6:
                raise MemoryError("group capacity exhausted")
            capP = stacked.vert.shape[1]
            capT = stacked.tet.shape[1]
            stacked, met_s = grow_shards(stacked, met_s, 2 * capP,
                                         2 * capT)
            regrows += 1
            continue        # re-run the block: truncated winners rerun
        c += nblk
        if any((flags[i] or noswap) and
               int(cs[i][0]) + int(cs[i][1]) + int(cs[i][2]) == 0
               for i in range(nblk)):
            break
    if polish and not (noinsert and noswap and nomove):
        # grouped bad-element pass: sliver_polish per group under the
        # same lax.map regime (seams stay frozen; the outer-iteration
        # displacement exposes them to a later pass).  This is what
        # makes a >=1M-tet run report a REAL post-tail min quality
        # without a whole-mesh-width program (which does not compile
        # through the TPU tunnel at that width).
        from ..ops.adapt import sliver_polish_impl

        @jax.jit
        def polish_block(stacked, met_s, wave):
            def body(args):
                m, k, w = args
                m, cnt = sliver_polish_impl(
                    m, k, w, do_collapse=not noinsert,
                    do_swap=not noswap, do_smooth=not nomove,
                    hausd=hausd)
                return m, k, cnt
            waves = jnp.full(ngroups, wave, jnp.int32)
            m, k, cnt = jax.lax.map(body, (stacked, met_s, waves))
            return m, k, cnt

        for w in range(4):
            stacked, met_s, cnt = polish_block(
                stacked, met_s, jnp.asarray(2000 + w, jnp.int32))
            tot = np.asarray(cnt).sum(axis=0)
            if verbose >= 2:
                print(f"  grp polish {w}: collapse {int(tot[0])} "
                      f"swap {int(tot[1])} move {int(tot[2])}")
            if int(tot[0]) == 0 and int(tot[1]) == 0:
                break
    return merge_shards(stacked, met_s, return_part=True)


def grouped_adapt(mesh: Mesh, met, target_size: int, niter: int = 3,
                  cycles: int = 12, verbose: int = 0, stats=None,
                  noinsert: bool = False, noswap: bool = False,
                  nomove: bool = False, hausd: float | None = None,
                  ifc_layers: int = 2):
    """The two-level outer loop on one device: grouped passes with
    interface displacement between them (the rank-level loop of
    libparmmg1.c:636-948 collapsed onto one device, groups as the only
    level).  Engaged by the driver when ``-mesh-size`` yields >= 2
    groups."""
    from .partition import move_interfaces
    from ..core.mesh import mesh_to_host

    part = None
    for it in range(max(1, niter)):
        ne = int(np.asarray(mesh.tmask).sum())
        # a displaced partition fixes the group count (its labels index
        # the previous split); fresh iterations re-derive it from ne
        ngroups = (int(part.max()) + 1) if part is not None \
            else how_many_groups(ne, target_size)
        if ngroups < 2:
            from ..ops.adapt import adapt_mesh
            mesh, met, st = adapt_mesh(
                mesh, met, verbose=verbose, noinsert=noinsert,
                noswap=noswap, nomove=nomove, hausd=hausd)
            if stats is not None:
                stats += st
            part = None
            continue
        mesh, met, part_m = grouped_adapt_pass(
            mesh, met, ngroups, cycles=cycles, part=part,
            verbose=verbose, stats=stats, noinsert=noinsert,
            noswap=noswap, nomove=nomove, hausd=hausd)
        if it + 1 < max(1, niter):
            _, tet_h, _, _, _ = mesh_to_host(mesh)
            part = move_interfaces(tet_h, part_m, ngroups,
                                   nlayers=ifc_layers)
    return mesh, met
