"""Parallel surface analysis across shards — PMMG_analys equivalent.

The reference reproduces Mmg's sequential surface analysis *across rank
boundaries* (/root/reference/src/analys_pmmg.c, SURVEY §2.4): ridge
detection on parallel edges by exchanging face normals (``PMMG_setdhd``
:2001), corner/singularity classification of parallel points by reducing
per-rank incident-special-edge counts (``PMMG_singul`` :1679), and normal
accumulation at parallel points (``PMMG_hashNorver`` :199-1171,
``_communication_nor`` :799).

Model reproduced here, in three reductions keyed by *global* entity ids
(the ordering/ownership contract of the comm layer):

1. every true-boundary face lives in exactly one shard, so each surface
   edge has 1 or 2 local boundary-face records per shard; edges with both
   records local get the dihedral test locally; edges split across shards
   exchange one normal each way and both sides run the same test
   (deterministic: both compute the identical dot product);
2. the *global* set of special (ridge/ref/non-manifold) edges is the
   deduplicated union over shards; a vertex's singularity class follows
   from its global incident-special count (2 -> ridge point, 1 or >2 ->
   corner) — the reference's int-comm count reduction;
3. vertex normals: area-weighted boundary-face normals accumulated once
   per face (faces are uniquely owned) and summed across shards at
   interface points.

Host-side implementation over numpy shard arrays + InterfaceComms; the
same reductions map 1:1 onto halo_exchange/psum for an on-device variant.
"""
from __future__ import annotations

import numpy as np

from ..core.constants import (
    ANGEDG, IDIR, MG_BDY, MG_CRN, MG_GEO, MG_NOM, MG_PARBDY, MG_REF)
from .comms import InterfaceComms, global_node_numbering


def extend_numbering(comms: InterfaceComms, npoin_new: list[int]
                     ) -> list[np.ndarray]:
    """Global numbering for ADAPTED shards: comm-table vertices keep the
    split-time numbering (interfaces are frozen, so slots are stable);
    vertices created by adaptation get fresh, globally-unique ids (they
    are shard-private by the freeze contract).  The PMMG_update_analys
    prerequisite (analys_pmmg.c:1571): entity matching across shards
    stays keyed by the pre-adaptation numbering."""
    base = global_node_numbering(comms, [len(o) for o in comms.owner])
    top = max((int(g.max()) if len(g) else 0) for g in base) + 1
    out = []
    for s, g in enumerate(base):
        extra = npoin_new[s] - len(g)
        ext = np.concatenate([
            g, top + np.arange(max(0, extra), dtype=np.int64)])
        top += max(0, extra)
        out.append(ext)
    return out


def analyze_shards(verts: list[np.ndarray], tets: list[np.ndarray],
                   ftags: list[np.ndarray], frefs: list[np.ndarray],
                   comms: InterfaceComms, angedg: float = ANGEDG,
                   glo: list[np.ndarray] | None = None):
    """Cross-shard surface analysis.

    ``glo`` overrides the global numbering — required when shards have
    grown past the comm tables' vertex range (adaptation creates
    shard-private vertices; give them unique global ids, see
    ``extend_numbering``).

    Returns per-shard:
      vtag_add[s]    uint32 bits (MG_BDY/GEO/CRN/REF/NOM) for vertices,
      special_edges[s]  [k,3] rows (lva, lvb, tagbits) for edge tagging,
      vnormal[s]     [np,3] unit outward normals (0 off-surface).
    """
    S = len(verts)
    if glo is None:
        glo = global_node_numbering(comms, [len(v) for v in verts])

    # ---- collect boundary-face edge records per shard -------------------
    # rec: (gkey_lo, gkey_hi, local_a, local_b, nx, ny, nz, fref, shard)
    recs = []
    for s in range(S):
        is_bdy = ((ftags[s] & MG_BDY) != 0) & ((ftags[s] & MG_PARBDY) == 0)
        tet = tets[s]
        for f in range(4):
            sel = np.where(is_bdy[:, f])[0]
            if not len(sel):
                continue
            tri = tet[sel][:, IDIR[f]]
            p = verts[s][tri]
            nrm = np.cross(p[:, 1] - p[:, 0], p[:, 2] - p[:, 0])
            fr = frefs[s][sel, f]
            for a, b in ((0, 1), (1, 2), (0, 2)):
                la, lb = tri[:, a], tri[:, b]
                ga, gb = glo[s][la], glo[s][lb]
                lo = np.minimum(ga, gb)
                hi = np.maximum(ga, gb)
                recs.append((lo, hi, la, lb, nrm, fr,
                             np.full(len(sel), s)))
    if not recs:
        return ([np.zeros(len(v), np.uint32) for v in verts],
                [np.zeros((0, 3), np.int64) for _ in verts],
                [np.zeros((len(v), 3)) for v in verts])
    lo = np.concatenate([r[0] for r in recs])
    hi = np.concatenate([r[1] for r in recs])
    la = np.concatenate([r[2] for r in recs])
    lb = np.concatenate([r[3] for r in recs])
    nrm = np.concatenate([r[4] for r in recs])
    fr = np.concatenate([r[5] for r in recs])
    sh = np.concatenate([r[6] for r in recs])

    # ---- global edge grouping ------------------------------------------
    key = lo.astype(np.int64) << 32 | hi
    order = np.argsort(key, kind="stable")
    ks = key[order]
    seg_start = np.concatenate([[True], ks[1:] != ks[:-1]])
    seg_id = np.cumsum(seg_start) - 1
    nseg = int(seg_id[-1]) + 1 if len(seg_id) else 0
    cnt = np.bincount(seg_id, minlength=nseg)

    # dihedral + ref + manifold tests per global edge
    nu = nrm[order] / np.maximum(
        np.linalg.norm(nrm[order], axis=1, keepdims=True), 1e-30)
    first_of = np.zeros(nseg, np.int64)
    first_of[seg_id[seg_start]] = np.where(seg_start)[0]
    # pairwise dot for 2-record segments
    is2 = cnt == 2
    i1 = first_of[np.where(is2)[0]]
    dot = np.einsum("ij,ij->i", nu[i1], nu[i1 + 1])
    ridge_seg = np.zeros(nseg, bool)
    ridge_seg[np.where(is2)[0]] = dot < angedg
    ref_seg = np.zeros(nseg, bool)
    ref_seg[np.where(is2)[0]] = fr[order][i1] != fr[order][i1 + 1]
    nom_seg = cnt != 2
    special_seg = ridge_seg | ref_seg | nom_seg
    tagbits_seg = (np.where(ridge_seg, MG_GEO, 0)
                   | np.where(ref_seg, MG_REF, 0)
                   | np.where(nom_seg, MG_NOM, 0)).astype(np.uint32)

    # ---- vertex classification by global incident-special count ---------
    glo_lo = lo[order][seg_start]          # [nseg] endpoint global ids
    glo_hi = hi[order][seg_start]
    maxg = int(max(glo_lo.max(), glo_hi.max())) + 1 if nseg else 1
    nsing = np.zeros(maxg, np.int64)
    sp = np.where(special_seg)[0]
    np.add.at(nsing, glo_lo[sp], 1)
    np.add.at(nsing, glo_hi[sp], 1)
    has_ref = np.zeros(maxg, bool)
    np.maximum.at(has_ref, glo_lo[np.where(ref_seg)[0]], True)
    np.maximum.at(has_ref, glo_hi[np.where(ref_seg)[0]], True)
    has_nom = np.zeros(maxg, bool)
    np.maximum.at(has_nom, glo_lo[np.where(nom_seg)[0]], True)
    np.maximum.at(has_nom, glo_hi[np.where(nom_seg)[0]], True)
    on_bdy_g = np.zeros(maxg, bool)
    on_bdy_g[glo_lo] = True
    on_bdy_g[glo_hi] = True

    gtag = np.where(on_bdy_g, MG_BDY, 0).astype(np.uint32)
    gtag |= np.where(nsing == 2, MG_GEO, 0).astype(np.uint32)
    gtag |= np.where((nsing == 1) | (nsing > 2), MG_CRN, 0
                     ).astype(np.uint32)
    gtag |= np.where(has_ref, MG_REF, 0).astype(np.uint32)
    gtag |= np.where(has_nom, MG_NOM, 0).astype(np.uint32)

    # ---- normals: one face record per (face, corner); dedup per face ----
    # each boundary face contributed 3 edge records; corner contribution
    # per face = appears in exactly 2 of its 3 edge records -> add once
    # with weight 1/2
    gacc = np.zeros((maxg, 3))
    np.add.at(gacc, lo, 0.5 * nrm)
    np.add.at(gacc, hi, 0.5 * nrm)

    # ---- scatter back per shard ----------------------------------------
    vtag_add, special_edges, vnormal = [], [], []
    for s in range(S):
        g = glo[s]
        safe = np.clip(g, 0, maxg - 1)
        # rows with g < 0 are dead slots (the session numbering marks
        # reusable holes with -1): no classification, no normal
        ok = (g >= 0) & (g < maxg)
        vt = np.where(ok, gtag[safe], 0).astype(np.uint32)
        vtag_add.append(vt)
        vn = np.where(ok[:, None], gacc[safe], 0.0)
        nl = np.linalg.norm(vn, axis=1, keepdims=True)
        vnormal.append(np.where(nl > 1e-30, vn / np.maximum(nl, 1e-30), 0))
        # special edges present in this shard (by its own records)
        mine = sh[order] == s
        segm = special_seg[seg_id] & mine
        rows = np.stack([la[order][segm], lb[order][segm],
                         tagbits_seg[seg_id][segm].astype(np.int64)], 1)
        # dedup (an edge appears once per adjacent local bdy face)
        if len(rows):
            rows = np.unique(rows, axis=0)
        special_edges.append(rows.astype(np.int64))
    return vtag_add, special_edges, vnormal
