"""Subprocess worker: grouped sliver polish from a host-state file.

Why a subprocess: at the >=1M-tet scale the tunneled TPU worker
reliably dies when the grouped polish program is compiled/dispatched
LATE in a session that already ran the full grouped sizing phase
(reproduced twice on 2026-08-02: device-resident state OOMs, chunked
state kernel-faults — while the identical polish program compiles and
runs fine in a fresh client).  Running the polish phase in its own
process gives it a fresh tunnel client and bounds the blast radius:
a crash here costs the quality tail, not the run (the caller treats a
non-zero exit as "skip grouped polish" and falls back to the merged
CPU polish).

Polish schedule (PR 12, ROADMAP 1c — the quiet-group scheduler's
wave-major compacted loop ported to this TPU-tunnel path): instead of
the legacy per-chunk ladder (each chunk resident through up to 4 waves
with a CHUNK-coupled break, so one busy chunk-mate extended a quiet
group's wave count), the still-active group indices are compacted into
dense ``[chunk]`` plans each wave (sched.chunk_plans) and every group
retires at its OWN collapse+swap==0 fixed point; repeat-padded tail
rows are lax.cond-skipped on device (sched.pad_mask — the same
device-resident quiet-mask machinery as the in-session path).  Each
wave's dispatches reuse the one compiled [chunk, ...] program.
``PARMMG_GROUP_SCHED=0`` (inherited from the parent env) keeps the
legacy per-chunk ladder here too, mirroring the in-session escape
hatch.

Protocol: argv[1] = input .npz (stacked Mesh leaves + met + knobs +
ngroups), argv[2] = output .npz (updated tet-axis leaves + met).
Invoked by ``parallel.groups.grouped_adapt_pass`` via
``sys.executable -m``.
"""
from __future__ import annotations

import dataclasses
import os
import sys

# injected worker crash (resilience/faults.py "polish.worker" site): the
# parent decided the firing and forced it through PARMMG_FAULT_FORCE;
# exit non-zero BEFORE the heavy jax import so the injected failure is
# cheap while still exercising the parent's real rc!=0 recovery path.
# Guarded on __main__ so merely importing this module never exits.
if __name__ == "__main__" and \
        os.environ.get("PARMMG_FAULT_FORCE", "").startswith(
            "polish.worker"):
    _force = os.environ["PARMMG_FAULT_FORCE"]
    _, _, _act = _force.partition(":")
    if _act.startswith("hang="):
        # the WEDGED-worker drill (hang=S action): sleep pre-jax, then
        # proceed normally — the parent's PARMMG_POLISH_TIMEOUT_S is
        # what must kill us (resilience/watchdog.py)
        import time as _time
        # lint: ok(R3) — pre-jax protocol line, relayed by the parent
        # through obs.trace.log like the exit-3 arm below
        print(f"injected hang: {_force}", file=sys.stderr, flush=True)
        _time.sleep(float(_act[5:]))
    else:
        # lint: ok(R3) — pre-jax fast exit: this line must not import
        # the obs spine (the whole point is dying before any heavy
        # import); the parent relays worker stderr through
        # obs.trace.log
        print("injected fault: polish.worker (PARMMG_FAULT_FORCE)",
              file=sys.stderr, flush=True)
        raise SystemExit(3)

import numpy as np

from ..core.mesh import MESH_FIELDS


def main(inp: str, outp: str) -> None:
    # persistent compile cache (compile governor): this fresh-client
    # process would otherwise recompile the grouped polish program from
    # scratch every run.  Must be the config-push variant: the
    # MESH_FIELDS import above already imported jax, which reads
    # JAX_COMPILATION_CACHE_DIR only once at import time — an env-only
    # set here would be a silent no-op.  Declines on a CPU backend (the
    # XLA:CPU AOT cache is unreliable on this image — tests/conftest.py
    # rationale).
    from ..utils.compilecache import enable_persistent_cache
    enable_persistent_cache()
    import jax
    import jax.numpy as jnp
    from ..core.mesh import Mesh
    from ..ops.adapt import sliver_polish_impl

    from .sched import chunk_plans, pad_mask, sched_enabled

    z = np.load(inp)
    stacked = Mesh(**{f: z[f] for f in MESH_FIELDS})
    met_s = z["met"]
    chunk = int(z["chunk"])
    noinsert, noswap, nomove = (bool(z["noinsert"]), bool(z["noswap"]),
                                bool(z["nomove"]))
    hausd = float(z["hausd"]) if np.isfinite(z["hausd"]) else None
    g_exec = stacked.vert.shape[0]
    # real group count: pad groups (dead at birth) never enter the
    # active set at all.  Absent on old hand-over files -> treat every
    # slot as real (pads retire at wave 0 with zero counts anyway).
    ngroups = int(z["ngroups"]) if "ngroups" in z.files else g_exec
    met_s = np.array(met_s)
    stacked = dataclasses.replace(
        stacked, **{f: np.array(getattr(stacked, f))
                    for f in MESH_FIELDS})

    # lint: ok(R1) — one-shot subprocess: main() runs once per worker
    # process, so this jit object lives exactly as long as the process
    # (the persistent compile cache shares the executable across runs)
    @jax.jit
    def polish_block(stacked, met_s, wave, active):
        def body(args):
            m, k, w, a = args
            m, cnt = sliver_polish_impl(
                m, k, w, do_collapse=not noinsert, do_swap=not noswap,
                do_smooth=not nomove, hausd=hausd, active=a)
            return m, k, cnt
        waves = jnp.full(stacked.vert.shape[0], wave, jnp.int32)
        return jax.lax.map(body, (stacked, met_s, waves, active))

    if sched_enabled():
        # wave-major compacted polish (module docstring): each group
        # retires at its OWN collapse+swap==0 fixed point; per-wave
        # plans gather only the still-active groups, pad rows
        # cond-skipped
        pol_act = np.arange(ngroups)
        for w in range(4):
            if not len(pol_act):
                break
            parts = []
            for idx, nreal in chunk_plans(pol_act, chunk):
                sl = jax.tree.map(lambda a: jnp.asarray(a[idx]),
                                  stacked)
                kl = jnp.asarray(met_s[idx])
                act = jnp.asarray(pad_mask(len(idx), nreal))
                sl, kl, cnt = polish_block(
                    sl, kl, jnp.asarray(2000 + w, jnp.int32), act)
                rows = idx[:nreal]
                for f in MESH_FIELDS:
                    getattr(stacked, f)[rows] = np.asarray(
                        getattr(sl, f))[:nreal]
                met_s[rows] = np.asarray(kl)[:nreal]
                parts.append(np.asarray(cnt)[:nreal])
            cnts = np.concatenate(parts)              # [n_act, 4]
            tot = cnts.sum(axis=0)
            # lint: ok(R3) — worker->parent stderr protocol: the parent
            # captures this stream and relays it via obs.trace.log at
            # its own verbosity (groups.py polish-worker invocation)
            print(f"polish w{w}: collapse {int(tot[0])} "
                  f"swap {int(tot[1])} move {int(tot[2])} over "
                  f"{len(pol_act)} active groups",
                  file=sys.stderr, flush=True)
            pol_act = pol_act[(cnts[:, 0] + cnts[:, 1]) > 0]
    else:
        # PARMMG_GROUP_SCHED=0 escape hatch: the legacy per-chunk wave
        # ladder (each chunk resident through up to 4 waves with a
        # chunk-coupled break), bit-identical to the pre-wave-major
        # worker — the same compiled polish_block with an all-true mask
        for g0 in range(0, g_exec, chunk):
            sl = jax.tree.map(lambda a: jnp.asarray(a[g0:g0 + chunk]),
                              stacked)
            kl = jnp.asarray(met_s[g0:g0 + chunk])
            for w in range(4):
                sl, kl, cnt = polish_block(
                    sl, kl, jnp.asarray(2000 + w, jnp.int32),
                    jnp.ones(sl.vert.shape[0], bool))
                tot = np.asarray(cnt).sum(axis=0)
                # lint: ok(R3) — worker->parent stderr protocol (above)
                print(f"polish chunk {g0 // chunk} w{w}: "
                      f"collapse {int(tot[0])} swap {int(tot[1])} "
                      f"move {int(tot[2])}", file=sys.stderr,
                      flush=True)
                if int(tot[0]) == 0 and int(tot[1]) == 0:
                    break
            for f in MESH_FIELDS:
                getattr(stacked, f)[g0:g0 + chunk] = np.asarray(
                    getattr(sl, f))
            met_s[g0:g0 + chunk] = np.asarray(kl)

    np.savez(outp, met=met_s,
             **{f: getattr(stacked, f) for f in MESH_FIELDS})


if __name__ == "__main__":
    main(sys.argv[1], sys.argv[2])
