"""Mesh partitioners (host-side v1).

Reference: ParMmg partitions with METIS (``PMMG_part_meshElts2metis``,
/root/reference/src/metis_pmmg.c:1271) for the initial element split, with
edge weights boosting old parallel interfaces (metis_pmmg.c:746-843) so
they land inside partitions on later iterations.

v1 provides:
- Morton (Z-order) space-filling-curve partitioning of tet centroids —
  geometric, fast, cache/gather friendly (the SFC ordering also replaces
  SCOTCH renumbering, which is pointless on TPU);
- a greedy BFS graph-growing partitioner with optional per-face weights —
  the structural slot where METIS-parity (interface-weight 1e6 and the
  metric-aware alpha=28 weighting, metis_pmmg.c:280) plugs in;
- contiguity correction (majority-neighbor relabel of stranded islands,
  reference moveinterfaces_pmmg.c:176-626 flavor).
"""
from __future__ import annotations

import numpy as np


def _morton3(u: np.ndarray) -> np.ndarray:
    """Interleave 21-bit coords into a 63-bit Morton key. u: [n,3] in [0,1)."""
    q = np.clip((u * (1 << 21)).astype(np.uint64), 0, (1 << 21) - 1)

    def spread(x):
        x = x.astype(np.uint64)
        x = (x | (x << np.uint64(32))) & np.uint64(0x1F00000000FFFF)
        x = (x | (x << np.uint64(16))) & np.uint64(0x1F0000FF0000FF)
        x = (x | (x << np.uint64(8))) & np.uint64(0x100F00F00F00F00F)
        x = (x | (x << np.uint64(4))) & np.uint64(0x10C30C30C30C30C3)
        x = (x | (x << np.uint64(2))) & np.uint64(0x1249249249249249)
        return x

    return (spread(q[:, 0]) | (spread(q[:, 1]) << np.uint64(1))
            | (spread(q[:, 2]) << np.uint64(2)))


def morton_partition(centroids: np.ndarray, nparts: int,
                     weights: np.ndarray | None = None) -> np.ndarray:
    """Equal-weight contiguous-along-curve partition of points."""
    # host-by-contract inputs (signature: np.ndarray): astype is a
    # dtype view/copy of host memory, never a device pull
    c = centroids.astype(np.float64, copy=False)
    lo = c.min(axis=0)
    span = np.maximum(c.max(axis=0) - lo, 1e-30)
    key = _morton3((c - lo) / span * 0.999999)
    order = np.argsort(key, kind="stable")
    w = np.ones(len(c)) if weights is None \
        else weights.astype(np.float64, copy=False)
    cw = np.cumsum(w[order])
    total = cw[-1]
    part_sorted = np.minimum((cw - 1e-12) / total * nparts,
                             nparts - 1e-9).astype(np.int32)
    part = np.empty(len(c), np.int32)
    part[order] = part_sorted
    return part


def build_dual_graph(tet: np.ndarray):
    """Tet-tet adjacency as CSR (host), via sorted faces."""
    n = len(tet)
    faces = np.sort(tet[:, [[1, 2, 3], [0, 3, 2], [0, 1, 3], [0, 2, 1]]]
                    .reshape(n * 4, 3), axis=1)
    key = (faces[:, 0].astype(np.int64) << 42) | \
          (faces[:, 1].astype(np.int64) << 21) | faces[:, 2].astype(np.int64)
    order = np.argsort(key, kind="stable")
    ks = key[order]
    same = ks[1:] == ks[:-1]
    i = order[:-1][same] // 4
    j = order[1:][same] // 4
    src = np.concatenate([i, j])
    dst = np.concatenate([j, i])
    o = np.argsort(src, kind="stable")
    src, dst = src[o], dst[o]
    xadj = np.zeros(n + 1, np.int64)
    np.add.at(xadj, src + 1, 1)
    xadj = np.cumsum(xadj)
    return xadj, dst.astype(np.int32)


def greedy_partition(tet: np.ndarray, centroids: np.ndarray, nparts: int,
                     weights: np.ndarray | None = None) -> np.ndarray:
    """BFS graph growing from spread seeds; balanced by element weight.

    Uses the native C++ kernel (native/meshkit.cpp) when available; the
    numpy path below is the reference implementation and fallback.
    """
    n = len(tet)
    try:
        from .. import native
        if native.available():
            c = np.asarray(centroids, np.float64)
            lo = c.min(axis=0)
            span = np.maximum(c.max(axis=0) - lo, 1e-30)
            key = _morton3((c - lo) / span * 0.999999)
            order = np.argsort(key)
            seeds = order[np.linspace(0, n - 1, nparts).astype(int)]
            adja = native.build_adjacency(np.asarray(tet, np.int32))
            return native.greedy_partition(
                adja, nparts, seeds.astype(np.int64),
                None if weights is None
                else np.asarray(weights, np.float64))
    except Exception:
        pass
    xadj, adj = build_dual_graph(tet)
    w = np.ones(n) if weights is None else np.asarray(weights, float)
    target = w.sum() / nparts
    # seeds: spread along the Morton curve
    c = np.asarray(centroids, np.float64)
    lo = c.min(axis=0)
    span = np.maximum(c.max(axis=0) - lo, 1e-30)
    key = _morton3((c - lo) / span * 0.999999)
    order = np.argsort(key)
    seeds = order[np.linspace(0, n - 1, nparts).astype(int)]
    part = np.full(n, -1, np.int32)
    from collections import deque
    queues = [deque([s]) for s in seeds]
    loads = np.zeros(nparts)
    remaining = n
    while remaining:
        progressed = False
        for p in np.argsort(loads):
            qd = queues[p]
            while qd:
                t = qd.popleft()
                if part[t] == -1:
                    part[t] = p
                    loads[p] += w[t]
                    remaining -= 1
                    for v in adj[xadj[t]:xadj[t + 1]]:
                        if part[v] == -1:
                            qd.append(v)
                    progressed = True
                    break
            if loads[p] > target * 1.05:
                continue
        if not progressed:
            # disconnected leftovers: assign to least-loaded part
            rest = np.where(part == -1)[0]
            for t in rest:
                p = int(np.argmin(loads))
                part[t] = p
                loads[p] += w[t]
            remaining = 0
    return part


def metric_edge_weights(tet: np.ndarray, vert: np.ndarray,
                        met: np.ndarray,
                        ifc_pairs: tuple[np.ndarray, np.ndarray] | None
                        = None, alpha: float = 28.0) -> dict:
    """Metric-aware dual-graph edge weights (PMMG_computeWgt,
    /root/reference/src/metis_pmmg.c:280-300): a face between two tets
    whose edges are far from unit metric length gets weight
    ``min(exp(alpha * mean|len-1|), 1e6)`` so partition cuts avoid
    regions that still need remeshing; old-interface faces get the flat
    1e6 boost (metis_pmmg.c:746-843) so previous interfaces fall inside
    partitions on the next iteration.

    Returns {"pairs": (i, j), "w": weights} aligned with the matched
    face pairs of the dual graph.
    """
    n = len(tet)
    faces = np.sort(tet[:, [[1, 2, 3], [0, 3, 2], [0, 1, 3], [0, 2, 1]]]
                    .reshape(n * 4, 3), axis=1)
    key = (faces[:, 0].astype(np.int64) << 42) | \
          (faces[:, 1].astype(np.int64) << 21) | faces[:, 2].astype(np.int64)
    order = np.argsort(key, kind="stable")
    ks = key[order]
    same = ks[1:] == ks[:-1]
    fa, fb = order[:-1][same], order[1:][same]
    i, j = fa // 4, fb // 4
    tri = faces[fa]                                   # [m,3] shared face
    # mean deviation of the 3 face edge metric lengths from 1
    h = met if met.ndim == 1 else None
    ev = np.stack([tri[:, [0, 1]], tri[:, [1, 2]], tri[:, [0, 2]]], axis=1)
    p0 = vert[ev[..., 0]]
    p1 = vert[ev[..., 1]]
    d = np.linalg.norm(p1 - p0, axis=-1)
    if h is not None:
        hm = 0.5 * (h[ev[..., 0]] + h[ev[..., 1]])
        L = d / np.maximum(hm, 1e-30)
    else:  # aniso: use mean of the two endpoint tensor lengths (approx)
        L = d
    dev = np.abs(L - 1.0).mean(axis=1)
    w = np.minimum(np.exp(alpha * dev / 3.0), 1.0e6)
    if ifc_pairs is not None:
        mark = np.zeros(n, bool)
        mark[np.asarray(ifc_pairs[0])] = True
        boost = mark[i] & mark[j]
        w = np.where(boost, 1.0e6, w)
    return {"pairs": (i.astype(np.int64), j.astype(np.int64)), "w": w}


def refine_partition(part: np.ndarray, nparts: int,
                     pairs: tuple[np.ndarray, np.ndarray],
                     w: np.ndarray, elem_w: np.ndarray | None = None,
                     npasses: int = 3, tol: float = 1.05) -> np.ndarray:
    """Weighted boundary refinement of a partition (KL/FM-flavored).

    The production consumer of :func:`metric_edge_weights` — the role of
    METIS k-way refinement under PMMG_computeWgt edge weighting
    (/root/reference/src/metis_pmmg.c:280-300,746-843): cut-boundary tets
    move to the neighbor part they are most heavily connected to, so
    partition cuts avoid regions whose edges are far from unit metric
    length (still to be remeshed) and previous-interface bands.

    Vectorized sweeps: per pass, every cut tet computes its connection
    weight to each adjacent part and moves when the gain is positive and
    the destination stays under ``tol`` x target load.  A few passes
    suffice (the cut only shrinks); callers re-run fix_contiguity after.
    """
    i, j = pairs
    part = np.asarray(part, np.int32).copy()
    n = len(part)
    ew = np.ones(n) if elem_w is None else np.asarray(elem_w, float)
    target = ew.sum() / nparts
    src = np.concatenate([i, j])
    oth = np.concatenate([j, i])
    ww = np.concatenate([w, w])
    for _ in range(npasses):
        cut = part[i] != part[j]
        if not cut.any():
            break
        cand = np.unique(np.concatenate([i[cut], j[cut]]))
        cidx = np.full(n, -1, np.int64)
        cidx[cand] = np.arange(len(cand))
        sel = cidx[src] >= 0
        conn = np.zeros((len(cand), nparts))
        np.add.at(conn, (cidx[src[sel]], part[oth[sel]]), ww[sel])
        cur = conn[np.arange(len(cand)), part[cand]]
        best_p = np.argmax(conn, axis=1).astype(np.int32)
        gain = conn[np.arange(len(cand)), best_p] - cur
        loads = np.bincount(part, weights=ew, minlength=nparts)
        move = (gain > 0) & (best_p != part[cand])
        if not move.any():
            break
        # capacity-aware admission: within each destination, admit movers
        # in gain order while the CUMULATIVE weight keeps the destination
        # under tol*target — simultaneous moves cannot overshoot (the
        # load check alone only blocks inflow against stale loads)
        mi = cand[move]
        gp = best_p[move]
        gw = ew[mi]
        gg = gain[move]
        o = np.lexsort((-gg, gp))
        mi, gp, gw = mi[o], gp[o], gw[o]
        seg = np.concatenate([[True], gp[1:] != gp[:-1]])
        cs = np.cumsum(gw)
        base = np.maximum.accumulate(np.where(seg, cs - gw, 0))
        within = cs - base                     # inclusive per-dest cumsum
        okm = loads[gp] + within <= tol * target
        if not okm.any():
            break
        part[mi[okm]] = gp[okm]
    return part


def correct_empty_parts(part: np.ndarray, nparts: int,
                        tet: np.ndarray) -> np.ndarray:
    """Donate one boundary element to every empty part
    (PMMG_correct_meshElts2metis, metis_pmmg.c:542-637)."""
    part = part.copy()
    counts = np.bincount(part, minlength=nparts)
    empties = np.where(counts == 0)[0]
    if len(empties) == 0:
        return part
    xadj, adj = build_dual_graph(tet)
    donors = np.argsort(counts)[::-1]
    for e in empties:
        big = donors[0]
        # pick an element of the big part with a neighbor outside it
        cand = np.where(part == big)[0]
        for t in cand:
            nb = adj[xadj[t]:xadj[t + 1]]
            if (part[nb] != big).any() or len(nb) < 4:
                part[t] = e
                break
        else:
            part[cand[0]] = e
        counts = np.bincount(part, minlength=nparts)
        donors = np.argsort(counts)[::-1]
    return part


def move_interfaces(tet: np.ndarray, part: np.ndarray, nparts: int,
                    nlayers: int = 2,
                    ne_min: int | None = None) -> np.ndarray:
    """Advancing-front interface displacement
    (PMMG_part_moveInterfaces, moveinterfaces_pmmg.c:1306-1466): for
    ``nlayers`` waves, the *larger* part's color advances across the
    interface into the smaller part (priority = part tet count,
    PMMG_get_ifcDirection :77-98), by flooding the tet balls of front
    vertices; a part never shrinks below ``ne_min``
    (min(6, ne/2+1), :1343).  Returns the displaced partition — old
    interfaces end up strictly inside the winning part, so the next
    adaptation can remesh them (the core idea of the iterative
    remesh-repartition scheme).
    """
    n = len(tet)
    part = part.copy()
    if ne_min is None:
        ne_min = min(6, n // (2 * max(nparts, 1)) + 1)
    nvert = int(tet.max()) + 1
    for _ in range(nlayers):
        sizes = np.bincount(part, minlength=nparts).astype(np.int64)
        # vertex color: the max-priority (larger part wins; ties by id)
        # among incident tets — the owner-priority merge of the reference
        pri = sizes[part] * np.int64(nparts) + part     # unique ordering
        vpri = np.zeros(nvert, np.int64)
        np.maximum.at(vpri, tet.reshape(-1), np.repeat(pri, 4))
        vcol = (vpri % nparts).astype(np.int32)
        # front vertices: incident to ≥2 colors
        vmin = np.full(nvert, np.int64(1) << 60)
        np.minimum.at(vmin, tet.reshape(-1), np.repeat(pri, 4))
        front = vmin != vpri
        # advance: every tet touching a front vertex whose winning color
        # differs takes that color (ball flood), respecting ne_min
        tfront = front[tet].any(axis=1)
        # winning color per tet = max vertex color priority over corners
        wpri = vpri[tet].max(axis=1)
        wcol = (wpri % nparts).astype(np.int32)
        change = tfront & (wcol != part)
        # donor-side floor: do not let a part drop below ne_min
        donors = part[change]
        loss = np.bincount(donors, minlength=nparts)
        allowed = sizes - ne_min
        scale_ok = loss <= np.maximum(allowed, 0)
        blocked = ~scale_ok[donors]
        if blocked.any():
            # keep only as many moves per donor as allowed (first-come)
            idx = np.where(change)[0]
            keep = np.ones(len(idx), bool)
            budget = np.maximum(allowed, 0).copy()
            for q, t in enumerate(idx):
                d = part[t]
                if budget[d] > 0:
                    budget[d] -= 1
                else:
                    keep[q] = False
            change[:] = False
            change[idx[keep]] = True
        part[change] = wcol[change]
    return fix_contiguity(tet, part)


def partition_metrics(tet: np.ndarray, part: np.ndarray,
                      nparts: int) -> dict:
    """Edge-cut + imbalance diagnostics (for tests and the LB driver)."""
    xadj, adj = build_dual_graph(tet)
    src = np.repeat(np.arange(len(tet)), np.diff(xadj))
    cut = int((part[src] != part[adj]).sum()) // 2
    counts = np.bincount(part, minlength=nparts)
    imb = float(counts.max() / max(1.0, counts.mean()))
    return {"edge_cut": cut, "imbalance": imb,
            "counts": counts.tolist()}


def fix_contiguity(tet: np.ndarray, part: np.ndarray) -> np.ndarray:
    """Relabel all but the largest connected blob of each color into a
    neighboring color (reference PMMG_fix_contiguity semantics,
    moveinterfaces_pmmg.c:475)."""
    n = len(tet)
    xadj, adj = build_dual_graph(tet)
    part = part.copy()
    # connected components within colors
    comp = np.full(n, -1, np.int64)
    ncomp = 0
    from collections import deque
    for s in range(n):
        if comp[s] != -1:
            continue
        comp[s] = ncomp
        dq = deque([s])
        while dq:
            t = dq.popleft()
            for v in adj[xadj[t]:xadj[t + 1]]:
                if comp[v] == -1 and part[v] == part[t]:
                    comp[v] = ncomp
                    dq.append(v)
        ncomp += 1
    sizes = np.bincount(comp, minlength=ncomp)
    # biggest component per color keeps it
    keep = {}
    for cid in range(ncomp):
        col = part[np.argmax(comp == cid)]
        if col not in keep or sizes[cid] > sizes[keep[col]]:
            keep[col] = cid
    keepset = set(keep.values())
    for cid in range(ncomp):
        if cid in keepset:
            continue
        idx = np.where(comp == cid)[0]
        # majority neighboring color outside this comp
        votes = {}
        for t in idx:
            for v in adj[xadj[t]:xadj[t + 1]]:
                if comp[v] != cid:
                    votes[part[v]] = votes.get(part[v], 0) + 1
        if votes:
            newc = max(votes, key=votes.get)
            part[idx] = newc
    return part
