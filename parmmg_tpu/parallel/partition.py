"""Mesh partitioners (host-side v1).

Reference: ParMmg partitions with METIS (``PMMG_part_meshElts2metis``,
/root/reference/src/metis_pmmg.c:1271) for the initial element split, with
edge weights boosting old parallel interfaces (metis_pmmg.c:746-843) so
they land inside partitions on later iterations.

v1 provides:
- Morton (Z-order) space-filling-curve partitioning of tet centroids —
  geometric, fast, cache/gather friendly (the SFC ordering also replaces
  SCOTCH renumbering, which is pointless on TPU);
- a greedy BFS graph-growing partitioner with optional per-face weights —
  the structural slot where METIS-parity (interface-weight 1e6 and the
  metric-aware alpha=28 weighting, metis_pmmg.c:280) plugs in;
- contiguity correction (majority-neighbor relabel of stranded islands,
  reference moveinterfaces_pmmg.c:176-626 flavor).
"""
from __future__ import annotations

import numpy as np


def _morton3(u: np.ndarray) -> np.ndarray:
    """Interleave 21-bit coords into a 63-bit Morton key. u: [n,3] in [0,1)."""
    q = np.clip((u * (1 << 21)).astype(np.uint64), 0, (1 << 21) - 1)

    def spread(x):
        x = x.astype(np.uint64)
        x = (x | (x << np.uint64(32))) & np.uint64(0x1F00000000FFFF)
        x = (x | (x << np.uint64(16))) & np.uint64(0x1F0000FF0000FF)
        x = (x | (x << np.uint64(8))) & np.uint64(0x100F00F00F00F00F)
        x = (x | (x << np.uint64(4))) & np.uint64(0x10C30C30C30C30C3)
        x = (x | (x << np.uint64(2))) & np.uint64(0x1249249249249249)
        return x

    return (spread(q[:, 0]) | (spread(q[:, 1]) << np.uint64(1))
            | (spread(q[:, 2]) << np.uint64(2)))


def morton_partition(centroids: np.ndarray, nparts: int,
                     weights: np.ndarray | None = None) -> np.ndarray:
    """Equal-weight contiguous-along-curve partition of points."""
    c = np.asarray(centroids, np.float64)
    lo = c.min(axis=0)
    span = np.maximum(c.max(axis=0) - lo, 1e-30)
    key = _morton3((c - lo) / span * 0.999999)
    order = np.argsort(key, kind="stable")
    w = np.ones(len(c)) if weights is None else np.asarray(weights, float)
    cw = np.cumsum(w[order])
    total = cw[-1]
    part_sorted = np.minimum((cw - 1e-12) / total * nparts,
                             nparts - 1e-9).astype(np.int32)
    part = np.empty(len(c), np.int32)
    part[order] = part_sorted
    return part


def build_dual_graph(tet: np.ndarray):
    """Tet-tet adjacency as CSR (host), via sorted faces."""
    n = len(tet)
    faces = np.sort(tet[:, [[1, 2, 3], [0, 3, 2], [0, 1, 3], [0, 2, 1]]]
                    .reshape(n * 4, 3), axis=1)
    key = (faces[:, 0].astype(np.int64) << 42) | \
          (faces[:, 1].astype(np.int64) << 21) | faces[:, 2].astype(np.int64)
    order = np.argsort(key, kind="stable")
    ks = key[order]
    same = ks[1:] == ks[:-1]
    i = order[:-1][same] // 4
    j = order[1:][same] // 4
    src = np.concatenate([i, j])
    dst = np.concatenate([j, i])
    o = np.argsort(src, kind="stable")
    src, dst = src[o], dst[o]
    xadj = np.zeros(n + 1, np.int64)
    np.add.at(xadj, src + 1, 1)
    xadj = np.cumsum(xadj)
    return xadj, dst.astype(np.int32)


def greedy_partition(tet: np.ndarray, centroids: np.ndarray, nparts: int,
                     weights: np.ndarray | None = None) -> np.ndarray:
    """BFS graph growing from spread seeds; balanced by element weight."""
    n = len(tet)
    xadj, adj = build_dual_graph(tet)
    w = np.ones(n) if weights is None else np.asarray(weights, float)
    target = w.sum() / nparts
    # seeds: spread along the Morton curve
    c = np.asarray(centroids, np.float64)
    lo = c.min(axis=0)
    span = np.maximum(c.max(axis=0) - lo, 1e-30)
    key = _morton3((c - lo) / span * 0.999999)
    order = np.argsort(key)
    seeds = order[np.linspace(0, n - 1, nparts).astype(int)]
    part = np.full(n, -1, np.int32)
    from collections import deque
    queues = [deque([s]) for s in seeds]
    loads = np.zeros(nparts)
    remaining = n
    while remaining:
        progressed = False
        for p in np.argsort(loads):
            qd = queues[p]
            while qd:
                t = qd.popleft()
                if part[t] == -1:
                    part[t] = p
                    loads[p] += w[t]
                    remaining -= 1
                    for v in adj[xadj[t]:xadj[t + 1]]:
                        if part[v] == -1:
                            qd.append(v)
                    progressed = True
                    break
            if loads[p] > target * 1.05:
                continue
        if not progressed:
            # disconnected leftovers: assign to least-loaded part
            rest = np.where(part == -1)[0]
            for t in rest:
                p = int(np.argmin(loads))
                part[t] = p
                loads[p] += w[t]
            remaining = 0
    return part


def fix_contiguity(tet: np.ndarray, part: np.ndarray) -> np.ndarray:
    """Relabel all but the largest connected blob of each color into a
    neighboring color (reference PMMG_fix_contiguity semantics,
    moveinterfaces_pmmg.c:475)."""
    n = len(tet)
    xadj, adj = build_dual_graph(tet)
    part = part.copy()
    # connected components within colors
    comp = np.full(n, -1, np.int64)
    ncomp = 0
    from collections import deque
    for s in range(n):
        if comp[s] != -1:
            continue
        comp[s] = ncomp
        dq = deque([s])
        while dq:
            t = dq.popleft()
            for v in adj[xadj[t]:xadj[t + 1]]:
                if comp[v] == -1 and part[v] == part[t]:
                    comp[v] = ncomp
                    dq.append(v)
        ncomp += 1
    sizes = np.bincount(comp, minlength=ncomp)
    # biggest component per color keeps it
    keep = {}
    for cid in range(ncomp):
        col = part[np.argmax(comp == cid)]
        if col not in keep or sizes[cid] > sizes[keep[col]]:
            keep[col] = cid
    keepset = set(keep.values())
    for cid in range(ncomp):
        if cid in keepset:
            continue
        idx = np.where(comp == cid)[0]
        # majority neighboring color outside this comp
        votes = {}
        for t in idx:
            for v in adj[xadj[t]:xadj[t + 1]]:
                if comp[v] != cid:
                    votes[part[v]] = votes.get(part[v], 0) + 1
        if votes:
            newc = max(votes, key=votes.get)
            part[idx] = newc
    return part
