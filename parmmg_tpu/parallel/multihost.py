"""Multi-host SPMD backend (jax.distributed over ICI/DCN).

The reference scales across nodes with MPI ranks (mpi_pmmg.h; rank
discovery + shared-memory budget split in zaldy_pmmg.c:53-96).  The
JAX-native equivalent is ``jax.distributed.initialize``: each host
process owns its local TPU devices, ``jax.devices()`` becomes the GLOBAL
device list, and the same ``shard_map`` programs of parallel/dist.py run
unchanged — XLA lowers the 'shard' axis collectives onto ICI within a
pod slice and DCN across slices (gloo on the CPU dev backend, knob
PARMMG_MH_COLLECTIVES).

What runs multi-host (the pod runtime, parallel/pod.py):
- the SPMD adapt blocks, quality reductions, the on-device interface
  echo and the whole band-migration pipeline — device arrays are
  global ('shard'-sharded via :func:`shard_stacked_global`);
- the band-path host stages: every process executes the identical host
  driver (the reference's "all ranks agree via Allreduce" idiom) on
  compacted band tables replicated through ``pod.gather_band`` — ONE
  cached shard_map collective per table family, never a per-leaf
  ``process_allgather``;
- the persistent compile cache is SHARED across workers
  (PARMMG_MH_CACHE_DIR): a warmed cache means worker N+1 deserializes
  executables instead of re-paying the multi-minute SPMD compiles —
  the scripts/multihost_run.py phase structure.

What stays single-host: the full-view fallback stages (split, merge,
full-mesh migration oracle) assert single-process via
:func:`require_single_process` rather than silently computing on a
partial device view.

:func:`pull_host` remains as the METERED escape hatch: every
process_allgather it performs counts ``mh.allgather_bytes``, and one
reached inside a :func:`hot_path` section additionally counts
``mh.hot_allgather_bytes`` (the ``--multihost`` gate asserts that
counter is ZERO) and raises under PARMMG_MH_STRICT — a stray allgather
on the hot path fails the gate, it does not just slow the run.  The
static mirror of the same tripwire is lint rule R7
(parmmg_tpu/lint/rules_hostsync.py).
"""
from __future__ import annotations

import contextlib
import os

import numpy as np


def mh_uniform(value, why: str):
    """Identity marker asserting ``value`` is SPMD-safe: either agreed
    across ranks (same value everywhere) or deliberately rank-scoped
    with the agreement protocol described in ``why``.

    The flagship use is the rank-0-writes idiom::

        write=mh_uniform((not multi) or jax.process_index() == 0,
                         "rank 0 durably writes; every rank computed "
                         "the identical predicate shape")

    Lint rule R8 (parmmg_tpu/lint/rules_spmd.py) taints everything
    derived from ``jax.process_index()`` and flags collectives or side
    effects that depend on the taint; ``mh_uniform``'s RESULT is
    untainted, so wrapping a value here is the in-code, reasoned
    alternative to a ``# lint: ok(R8)`` comment.  ``why`` is mandatory
    for the same reason suppression reasons are: the assertion is only
    as good as its argument.
    """
    if not why or not why.strip():
        raise ValueError("mh_uniform() requires a non-empty 'why' "
                         "describing the cross-rank agreement")
    return value


def init_multihost(coordinator: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None) -> bool:
    """Initialize jax.distributed from args or the standard env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID).

    Returns True if a multi-process runtime was initialized; False for
    the single-process degenerate case (no-op — the NP=1 column of the
    reference CI matrix).  Safe to call twice.

    Pod wiring performed here, BEFORE the backend client exists:
    cross-process CPU collectives (jax refuses multiprocess CPU
    computations without an implementation; PARMMG_MH_COLLECTIVES,
    default gloo) and the shared persistent compile cache
    (PARMMG_MH_CACHE_DIR — the explicit opt-in path of
    ``set_cache_env``, so the pinned-CPU dev pod behaves like the chip
    pod: one worker compiles, the others deserialize).
    """
    import jax

    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("JAX_PROCESS_ID", "0"))
    cache = os.environ.get("PARMMG_MH_CACHE_DIR", "")
    if cache:
        # cache even the sub-second programs: the pod pays hundreds of
        # small eager-op compiles whose sum dwarfs any deserialize cost
        os.environ.setdefault(
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
        os.environ.setdefault(
            "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
        from ..utils.compilecache import set_cache_env
        from ..utils.jaxcompat import multiprocess_cache_key_shim
        set_cache_env(cache)
        # without this shim worker N+1 misses every entry worker 0
        # wrote (per-process autotune-cache mode + serialized topology
        # poison the key — jaxcompat.multiprocess_cache_key_shim)
        multiprocess_cache_key_shim()
    if not coordinator or num_processes <= 1:
        # single-process degenerate pod: still wire the shared cache
        # (the 1-process parity reference of multihost_run warms its
        # own program family once per scenario)
        if cache:
            from ..utils.compilecache import enable_persistent_cache
            enable_persistent_cache(cache)
        return False
    impl = os.environ.get("PARMMG_MH_COLLECTIVES", "gloo")
    if impl and impl != "none":
        try:
            jax.config.update("jax_cpu_collectives_implementation", impl)
        except Exception:
            pass            # other jax versions: backend handles it
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id)
    except RuntimeError as e:
        if "already initialized" in str(e).lower():
            return True
        raise
    if cache:
        from ..utils.compilecache import enable_persistent_cache
        enable_persistent_cache(cache)
    return True


def is_multiprocess() -> bool:
    import jax
    return jax.process_count() > 1


# ---------------------------------------------------------------------------
# hot-path metering (the pull_host escape hatch's tripwire)
# ---------------------------------------------------------------------------
_HOT_DEPTH = [0]


@contextlib.contextmanager
def hot_path():
    """Mark a section as the multi-host HOT PATH: any process_allgather
    ``pull_host`` performs inside it is counted on
    ``mh.hot_allgather_bytes`` (gate-asserted zero) and raises under
    PARMMG_MH_STRICT.  The per-iteration body of
    ``distributed_adapt_multi`` runs inside one.  Entering a hot
    section also beats this rank's heartbeat file (throttled by
    PARMMG_HEARTBEAT_S) so the pod supervisor's lease
    (scripts/multihost_run.py --lease) sees liveness exactly where
    wedging matters."""
    from ..resilience.watchdog import beat
    beat()
    _HOT_DEPTH[0] += 1
    try:
        yield
    finally:
        _HOT_DEPTH[0] -= 1


@contextlib.contextmanager
def cold_io():
    """Exempt a nested IO section (checkpoint write, artifact dump)
    from hot-path metering: replicating state for durable output is the
    designed cost of that path, not a stray hot-loop allgather."""
    d, _HOT_DEPTH[0] = _HOT_DEPTH[0], 0
    try:
        yield
    finally:
        _HOT_DEPTH[0] = d


def in_hot_path() -> bool:
    return _HOT_DEPTH[0] > 0


def _note_allgather(nbytes: int, what: str = "") -> None:
    """Meter one escape-hatch allgather (factored for host-only tests):
    total bytes always; hot-path bytes + trace event + the
    PARMMG_MH_STRICT tripwire when inside :func:`hot_path`."""
    from ..obs import trace as otrace
    from ..obs.metrics import REGISTRY
    REGISTRY.counter("mh.allgather_bytes").inc(float(nbytes))
    if in_hot_path():
        REGISTRY.counter("mh.hot_allgather_bytes").inc(float(nbytes))
        otrace.event("mh.hot_allgather", nbytes=int(nbytes),
                     what=str(what))
        if os.environ.get("PARMMG_MH_STRICT", "") == "1":
            raise RuntimeError(
                f"hot-path process_allgather of {nbytes} bytes"
                + (f" ({what})" if what else "")
                + " — the pod band path must route through "
                "pod.gather_band [PARMMG_MH_STRICT]")


# cached resharding identities keyed by the target sharding (compile
# governor): the non-addressable branch below used to build a FRESH
# ``jax.jit(lambda a: a)`` per call — one recompile per leaf per upload
# on multi-process runs (the io.distributed writers and every band-table
# pull route through here).  One cached object per (devices, spec) pair
# + ledger registration, the check_interface_echo caching pattern.
_RESHARD_CACHE: dict = {}


def _reshard_identity(sh):
    # lint: ok(R2) — device-id METADATA (sharding.mesh.devices is a
    # host numpy object array), no device sync
    key = (tuple(d.id for d in np.asarray(sh.mesh.devices).flat),
           str(sh.spec))
    fn = _RESHARD_CACHE.get(key)
    if fn is None:
        import jax
        from ..utils.compilecache import governed
        fn = governed("multihost.reshard", budget=4)(
            jax.jit(lambda a: a, out_shardings=sh))
        _RESHARD_CACHE[key] = fn
    return fn


def shard_stacked_global(stacked_host, dmesh):
    """Place a [D, ...]-stacked HOST pytree onto a (possibly multi-host)
    device mesh: each process uploads only the shard slices that live on
    its addressable devices, then the global array is assembled with
    ``jax.make_array_from_single_device_arrays`` — the multi-host
    replacement for a plain ``jax.device_put`` (which requires all
    devices addressable).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(dmesh, P("shard"))
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), sh),
                            stacked_host)

    devs = list(dmesh.devices.reshape(-1))

    def put(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            # already a multi-process global array (e.g. the output of
            # grow_shards' pad on a sharded input): np.asarray would
            # raise on non-addressable shards — reshard with the cached
            # jitted identity instead (XLA inserts the collectives)
            return _reshard_identity(sh)(x)
        # lint: ok(R2) — input is the HOST-resident stacked pytree
        # (numpy or addressable upload staging), never a device pull
        x = np.asarray(x)
        if x.shape[0] % len(devs):
            raise ValueError(
                f"leading axis {x.shape[0]} not divisible by "
                f"{len(devs)} devices (groups x shards requires "
                "G whole rows per device)")
        g = x.shape[0] // len(devs)   # logical shards per device (G)
        pieces = []
        for i, d in enumerate(devs):
            if d.process_index == jax.process_index():
                # lint: ok(R8) — rank-scoped BY DESIGN: each process
                # uploads exactly its addressable shard slices; every
                # rank runs this identical loop over the global device
                # list, and make_array_from_single_device_arrays below
                # is the agreement that assembles the pieces
                pieces.append(jax.device_put(x[i * g:(i + 1) * g], d))
        return jax.make_array_from_single_device_arrays(
            x.shape, sh, pieces)

    return jax.tree.map(put, stacked_host)


def require_single_process(what: str) -> None:
    """Guard for host-orchestration stages not yet distributed across
    processes (split/merge/migration packaging) — fail loudly instead of
    silently computing on a partial device view."""
    import jax
    if jax.process_count() > 1:
        raise NotImplementedError(
            f"{what} is single-controller today; run it on one host or "
            "use the per-process distributed I/O entry "
            "(io.distributed) — multi-process host orchestration is the "
            "next step documented in parallel/multihost.py")


def pull_host(x, what: str = "") -> np.ndarray:
    """Device -> host pull that is correct on a multi-process runtime —
    and METERED: the band path's hot-loop stages must ride
    ``pod.gather_band`` instead, this is the escape hatch.

    Single-process (or an already fully-addressable / fully-replicated
    array): plain ``np.asarray``.  Multi-process with a 'shard'-sharded
    global array: every process holds only its addressable slices, so
    the pull is a ``process_allgather`` — each process receives the
    full value and the host stages compute identically everywhere (the
    reference's every-rank-agrees idiom, distributegrps_pmmg.c:1631).
    Every such allgather bumps ``mh.allgather_bytes``; inside a
    :func:`hot_path` section it additionally bumps
    ``mh.hot_allgather_bytes`` (asserted ZERO by ``run_tests.sh
    --multihost``) and raises under PARMMG_MH_STRICT."""
    import jax
    if isinstance(x, np.ndarray):
        return x
    if jax.process_count() == 1 or not isinstance(x, jax.Array) \
            or x.is_fully_addressable or x.is_fully_replicated:
        return np.asarray(x)
    _note_allgather(int(np.prod(x.shape)) * x.dtype.itemsize, what)
    from jax.experimental import multihost_utils
    # lint: ok(R7) — pull_host IS the metered escape hatch (module
    # docstring): the allgather is counted above and trips the
    # PARMMG_MH_STRICT / gate assertions when reached hot
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))
