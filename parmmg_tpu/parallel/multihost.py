"""Multi-host SPMD backend (jax.distributed over ICI/DCN).

The reference scales across nodes with MPI ranks (mpi_pmmg.h; rank
discovery + shared-memory budget split in zaldy_pmmg.c:53-96).  The
JAX-native equivalent is ``jax.distributed.initialize``: each host
process owns its local TPU devices, ``jax.devices()`` becomes the GLOBAL
device list, and the same ``shard_map`` programs of parallel/dist.py run
unchanged — XLA lowers the 'shard' axis collectives onto ICI within a
pod slice and DCN across slices.

What runs multi-host today:
- the SPMD adapt blocks (`dist_adapt_block`), quality reductions
  (`dist_quality`) and the on-device interface echo — their inputs are
  built with :func:`shard_stacked_global`, which feeds each process only
  its addressable shards (``jax.make_array_from_single_device_arrays``);
- every process executes the identical host driver (single-program
  multiple-data at the Python level too — the reference's "all ranks
  agree via Allreduce" idiom maps to every process computing the same
  host decisions from the same replicated scalars).

What stays single-host: the host-side orchestration that materializes
per-shard numpy views (split, merge, migration packaging, analysis
refresh) currently runs on process 0's data layout and asserts
single-process when invoked multi-host — distributing those host stages
across processes is the designed next step (each process already only
needs ITS shards' views; the package exchange maps to a DCN
all-to-all).

This module is exercised in CI only in its single-process degenerate
form (the image has one host); the multi-process paths follow the
documented jax.distributed contract.
"""
from __future__ import annotations

import os

import numpy as np


def init_multihost(coordinator: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None) -> bool:
    """Initialize jax.distributed from args or the standard env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID).

    Returns True if a multi-process runtime was initialized; False for
    the single-process degenerate case (no-op — the NP=1 column of the
    reference CI matrix).  Safe to call twice.
    """
    import jax

    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("JAX_PROCESS_ID", "0"))
    if not coordinator or num_processes <= 1:
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id)
    except RuntimeError as e:
        if "already initialized" in str(e).lower():
            return True
        raise
    return True


def is_multiprocess() -> bool:
    import jax
    return jax.process_count() > 1


# cached resharding identities keyed by the target sharding (compile
# governor): the non-addressable branch below used to build a FRESH
# ``jax.jit(lambda a: a)`` per call — one recompile per leaf per upload
# on multi-process runs (the io.distributed writers and every band-table
# pull route through here).  One cached object per (devices, spec) pair
# + ledger registration, the check_interface_echo caching pattern.
_RESHARD_CACHE: dict = {}


def _reshard_identity(sh):
    key = (tuple(d.id for d in np.asarray(sh.mesh.devices).flat),
           str(sh.spec))
    fn = _RESHARD_CACHE.get(key)
    if fn is None:
        import jax
        from ..utils.compilecache import governed
        fn = governed("multihost.reshard", budget=4)(
            jax.jit(lambda a: a, out_shardings=sh))
        _RESHARD_CACHE[key] = fn
    return fn


def shard_stacked_global(stacked_host, dmesh):
    """Place a [D, ...]-stacked HOST pytree onto a (possibly multi-host)
    device mesh: each process uploads only the shard slices that live on
    its addressable devices, then the global array is assembled with
    ``jax.make_array_from_single_device_arrays`` — the multi-host
    replacement for a plain ``jax.device_put`` (which requires all
    devices addressable).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(dmesh, P("shard"))
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), sh),
                            stacked_host)

    devs = list(dmesh.devices.reshape(-1))

    def put(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            # already a multi-process global array (e.g. the output of
            # grow_shards' pad on a sharded input): np.asarray would
            # raise on non-addressable shards — reshard with the cached
            # jitted identity instead (XLA inserts the collectives)
            return _reshard_identity(sh)(x)
        x = np.asarray(x)
        if x.shape[0] % len(devs):
            raise ValueError(
                f"leading axis {x.shape[0]} not divisible by "
                f"{len(devs)} devices (groups x shards requires "
                "G whole rows per device)")
        g = x.shape[0] // len(devs)   # logical shards per device (G)
        pieces = []
        for i, d in enumerate(devs):
            if d.process_index == jax.process_index():
                pieces.append(jax.device_put(x[i * g:(i + 1) * g], d))
        return jax.make_array_from_single_device_arrays(
            x.shape, sh, pieces)

    return jax.tree.map(put, stacked_host)


def require_single_process(what: str) -> None:
    """Guard for host-orchestration stages not yet distributed across
    processes (split/merge/migration packaging) — fail loudly instead of
    silently computing on a partial device view."""
    import jax
    if jax.process_count() > 1:
        raise NotImplementedError(
            f"{what} is single-controller today; run it on one host or "
            "use the per-process distributed I/O entry "
            "(io.distributed) — multi-process host orchestration is the "
            "next step documented in parallel/multihost.py")


def pull_host(x) -> np.ndarray:
    """Device -> host pull that is correct on a multi-process runtime.

    Single-process (or an already fully-addressable / replicated array):
    plain ``np.asarray``.  Multi-process with a 'shard'-sharded global
    array: every process holds only its addressable slices, so the pull
    is a ``process_allgather`` — each process receives the full value
    and the host stages compute identically everywhere (the reference's
    every-rank-agrees idiom: its host decisions ride MPI_Allreduce/
    Allgather the same way, e.g. the distributegrps_pmmg.c:1631
    metadata exchange).  Band-path tables are band/interface-sized, so
    replicating them is DCN-cheap; the full-view fallback paths must NOT
    be pulled this way (guarded by require_single_process at their
    entry)."""
    import jax
    if isinstance(x, np.ndarray):
        return x
    if jax.process_count() == 1 or not isinstance(x, jax.Array) \
            or x.is_fully_addressable:
        return np.asarray(x)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))
