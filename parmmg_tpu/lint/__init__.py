"""Static invariant linter (rules R1-R10).

Pure-stdlib ``ast`` checks for the project's load-bearing invariants —
compile hygiene (R1/R5), the zero-host-pull hot path (R2/R7), obs
routing (R3), the PARMMG_* knob registry (R4), static telemetry names
(R6) — plus the flow-sensitive provers built on ``lint.flow``'s
interprocedural core: SPMD collective alignment (R8), lock discipline
(R9) and shape-ladder hygiene (R10) — so a violation class the runtime
gates (``--ledger``/``--obs``/``--chaos``/``--serve``/``--multihost``)
would need minutes of XLA:CPU compile (or a live 2-process pod) to
catch fails in seconds at lint time, before review.
``scripts/lint_check.py`` is the CLI (``--sarif``/``--changed-only``
for CI and the inner loop); ``run_tests.sh --lint`` the gate;
``lint_baseline.json`` the grandfathered burn-down list.  Importing
this package never imports jax (enforced by lint_check's own
self-check and tests/test_lint.py).
"""
from . import (rules_compile, rules_hostsync, rules_knobs,  # noqa: F401
               rules_locks, rules_obs, rules_shapes, rules_spmd)
from .engine import (RULES, RULE_TITLES, GateResult, LintReport,  # noqa: F401
                     SourceFile, Violation, baseline_payload,
                     collect_files, format_report, gate,
                     load_baseline, run_lint)
