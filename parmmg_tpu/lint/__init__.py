"""Static invariant linter (rules R1-R6).

Pure-stdlib ``ast`` checks for the project's load-bearing invariants —
compile hygiene (R1/R5), the zero-host-pull hot path (R2), obs routing
(R3), the PARMMG_* knob registry (R4) and static telemetry names (R6)
— so a violation class the runtime gates (``--ledger``/``--obs``/
``--chaos``) would need minutes of XLA:CPU compile to catch fails in
seconds at lint time, before review.  ``scripts/lint_check.py`` is the
CLI; ``run_tests.sh --lint`` the gate; ``lint_baseline.json`` the
grandfathered burn-down list.  Importing this package never imports
jax (enforced by lint_check's own self-check and tests/test_lint.py).
"""
from . import rules_compile, rules_hostsync, rules_knobs, rules_obs  # noqa: F401,E501  (register rules)
from .engine import (RULES, RULE_TITLES, GateResult, LintReport,  # noqa: F401
                     SourceFile, Violation, baseline_payload,
                     collect_files, format_report, gate,
                     load_baseline, run_lint)
