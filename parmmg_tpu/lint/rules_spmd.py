"""R8 SPMD collective alignment: no collective control-dependent on a
rank-divergent value.

The most expensive hang shape on a pod is statically decidable: a
collective (``gather_band``, ``process_allgather``, ``psum`` /
``all_gather`` inside ``shard_map``, ``permute_shards``) that one rank
reaches and another does not wedges every rank until the heartbeat
lease kills the pack (resilience/watchdog.py — R8 is the static half
of that ladder).  The rule taints ``jax.process_index()`` results,
propagates through assignments (flow.taint_names), and flags:

- **divergent-collective** — a collective call (or a call into any
  function whose summary says it transitively performs one) that is
  control-dependent on rank-tainted state: unless every rank computes
  the same truth value, the ranks disagree on how many collectives
  they run;
- **collective-after-divergent-exit** — a rank-tainted guard around a
  ``return``/``raise``/``break``/``continue`` with a collective later
  in the same function: the exiting rank skips it, the rest block;
- **rank-tainted-arg** — a rank-divergent value escaping as an
  argument into an ordinary call (the checkpoint ``write=`` idiom:
  divergence by data instead of control flow);
- **rank-gated-call** — any effectful call under a rank-tainted guard
  (the device-pick loop shape: per-rank side effects that must be an
  explicitly blessed rank-scoped action, not an accident).

Blessed idioms the rule recognizes (no suppression needed):

- ``multihost.mh_uniform(value, why)`` — the runtime-identity marker
  asserting a rank-derived value is agreed (or deliberately
  rank-scoped with the agreement described in ``why``); its result is
  untainted.
- the agreement collectives themselves: PASSING a rank-local value to
  ``process_allgather`` (etc.) is exactly how ranks agree, and the
  *result* of a collective is uniform by construction, so it launders
  taint.

Anything else carries a reasoned ``# lint: ok(R8)`` — a def-line
suppression exempts the whole function (engine-resolved anchors).
"""
from __future__ import annotations

import ast

from . import flow
from .engine import Violation, rule

_SCOPE = ("parmmg_tpu/",)
_EXCLUDE = ("parmmg_tpu/lint/",)

#: taint sources: calls whose leaf name is the rank query
_SOURCE_LEAFS = frozenset({"process_index"})

#: launderers: their RESULT is uniform across ranks
_BLESSED = frozenset({"mh_uniform"}) | flow.COLLECTIVE_PRIMITIVES

#: effect-free builtins a tainted guard may call without divergence
_PURE = frozenset({"bool", "int", "float", "str", "repr", "format",
                   "abs", "len", "round", "min", "max", "isinstance",
                   "getattr", "hasattr", "type", "tuple", "list"})


def _is_source(node) -> bool:
    return isinstance(node, ast.Call) \
        and flow.leaf_name(node.func) in _SOURCE_LEAFS


def _stmt_exprs(stmt):
    """Expression roots evaluated AT this statement's nesting level
    (compound bodies are walked separately by walk_guarded)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in stmt.items]
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []
    return [stmt]


def _calls_in(root, skip):
    for n in ast.walk(root):
        if id(n) in skip:
            continue
        if isinstance(n, ast.Call):
            yield n


@rule("R8")
def check_r8(ctx) -> list:
    graph = flow.CallGraph(ctx, _SCOPE, _EXCLUDE)
    may_collect = graph.fixpoint(
        lambda fi: fi.call_leafs & flow.COLLECTIVE_PRIMITIVES)
    out = []
    for fi in graph.infos:
        tainted = flow.taint_names(fi.node, fi.nested_skip,
                                   _is_source, _BLESSED)
        if not tainted and not any(
                _is_source(n) for n in ast.walk(fi.node)
                if id(n) not in fi.nested_skip):
            continue

        def dirty(expr):
            return flow.expr_tainted(expr, tainted, _is_source,
                                     _BLESSED)

        flagged: set = set()
        div_exit_line: int | None = None
        collective_sites = []   # (line, leaf, node) in source order
        for stmt, guards in flow.walk_guarded(fi.node.body,
                                              fi.nested_skip):
            tainted_guards = [g for g in guards if dirty(g)]
            if isinstance(stmt, (ast.Return, ast.Raise, ast.Break,
                                 ast.Continue)) and tainted_guards:
                if div_exit_line is None \
                        or stmt.lineno < div_exit_line:
                    div_exit_line = stmt.lineno
            for root in _stmt_exprs(stmt):
                for call in _calls_in(root, fi.nested_skip):
                    leaf = flow.leaf_name(call.func)
                    if not leaf:
                        continue
                    eguards = flow.expr_guards(root, call)
                    all_tainted = tainted_guards \
                        + [g for g in eguards if dirty(g)]
                    collective = (
                        leaf in flow.COLLECTIVE_PRIMITIVES
                        or leaf in may_collect)
                    if collective:
                        collective_sites.append((call.lineno, leaf,
                                                 call))
                        if all_tainted:
                            flagged.add(id(call))
                            out.append(Violation(
                                "R8", fi.sf.rel, call.lineno,
                                fi.qualname,
                                f"divergent-collective:{leaf}",
                                f"collective {leaf}() is control-"
                                "dependent on rank-divergent state "
                                "(jax.process_index taint) — ranks "
                                "disagreeing on this branch wedge the "
                                "pod; agree first (process_allgather) "
                                "or bless via mh_uniform()"))
                            continue
                        if leaf in flow.COLLECTIVE_PRIMITIVES:
                            # the agreement idiom: a rank-LOCAL value
                            # passed to the primitive is the payload
                            # being agreed.  Transitively-collective
                            # callees get no such pass — fall through
                            # to the tainted-arg check (the checkpoint
                            # write= shape).
                            continue
                    if leaf in _PURE or leaf in _BLESSED:
                        continue
                    if all_tainted:
                        flagged.add(id(call))
                        out.append(Violation(
                            "R8", fi.sf.rel, call.lineno, fi.qualname,
                            f"rank-gated-call:{leaf}",
                            f"call {leaf}() under a rank-divergent "
                            "guard — a per-rank side effect must ride "
                            "an agreed decision or an mh_uniform()-"
                            "blessed rank-scoped action"))
                        continue
                    if any(dirty(a) for a in call.args) or any(
                            dirty(kw.value) for kw in call.keywords):
                        out.append(Violation(
                            "R8", fi.sf.rel, call.lineno, fi.qualname,
                            f"rank-tainted-arg:{leaf}",
                            f"rank-divergent value passed into "
                            f"{leaf}() — divergence by data: wrap the "
                            "value in mh_uniform(value, why) citing "
                            "the agreement, or agree it via "
                            "process_allgather first"))
        if div_exit_line is not None:
            for line, leaf, call in collective_sites:
                if line > div_exit_line and id(call) not in flagged:
                    out.append(Violation(
                        "R8", fi.sf.rel, line, fi.qualname,
                        f"collective-after-divergent-exit:{leaf}",
                        f"collective {leaf}() reachable after a rank-"
                        f"divergent early exit (line {div_exit_line})"
                        " — the exiting rank skips it and the rest "
                        "block forever"))
    return out
