"""R9 lock discipline: order cycles, dispatch under a held lock,
unguarded cross-thread fields.

The tree's ``threading.Lock``/``RLock`` instances (daemon, trace,
metrics, faults, compilecache — plus any future class- or module-level
lock, detected automatically) are modeled as abstract resources.  From
with/acquire summaries the rule builds a lock-order graph and fails
on:

- **lock-order** — a cycle in the acquired-while-holding graph
  (self-edges allowed only on RLocks: re-entry is their contract;
  a plain Lock re-acquired on the same thread deadlocks);
- **lock-held-dispatch** — a call made while holding a lock whose
  transitive summary reaches a collective or a subprocess spawn (the
  serving-loop wedge shape: the daemon RLock held across
  ``service_once`` -> grouped pass -> polish ``subprocess.run``).
  Where the runtime watchdog ladder (PARMMG_DEADLINE_SERVE_S,
  PARMMG_POLISH_TIMEOUT_S) makes the hold survivable by design, the
  site carries a reasoned suppression naming that watchdog — the
  static rule keeps every such hold enumerated and argued;
- **unguarded-field** — a field of a two-thread class (PoolDaemon:
  HTTP handler thread vs serving loop) written outside the class lock
  in one thread domain and touched in the other.  GIL-atomic probe
  flags (``paused``, ``_wedged``) are the documented suppression
  pattern, with the atomicity argument in the reason.
"""
from __future__ import annotations

import ast

from . import flow
from .engine import Violation, dotted, rule

_SCOPE = ("parmmg_tpu/",)
_EXCLUDE = ("parmmg_tpu/lint/",)

#: friendly resource names for the five contract locks; any other
#: detected lock is named Class.attr (or the module-level var name)
_FRIENDLY = {"PoolDaemon": "daemon", "Tracer": "trace",
             "MetricsRegistry": "metrics", "FaultRegistry": "faults",
             "CompileLedger": "compilecache"}

#: two-thread classes: {class: (domain-A root methods, domain-B root
#: methods)} — A is the request/handler side, B the long-lived loop
_DOMAINS = {"PoolDaemon": (("handle_rpc", "_dispatch"), ("_loop",))}


def _lock_decls(ctx):
    """Detected lock resources:
    ``{(cls, attr): (resource, kind)}`` for ``self.attr = threading
    .Lock()`` in a class, ``{(rel, var): (resource, kind)}`` for
    module-level locks."""
    attrs: dict[tuple, tuple] = {}
    mods: dict[tuple, tuple] = {}

    def scan(body, cls, rel):
        for node in body:
            if isinstance(node, ast.ClassDef):
                scan(node.body, node.name, rel)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                scan(node.body, cls, rel)
            elif isinstance(node, (ast.If, ast.Try, ast.With,
                                   ast.For, ast.While)):
                scan(list(ast.iter_child_nodes(node)), cls, rel)
            elif isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                d = dotted(node.value.func)
                if d not in ("threading.Lock", "threading.RLock"):
                    continue
                kind = "RLock" if d.endswith("RLock") else "Lock"
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self" and cls:
                        res = _FRIENDLY.get(cls, f"{cls}.{t.attr}")
                        attrs[(cls, t.attr)] = (res, kind)
                    elif isinstance(t, ast.Name) and cls is None:
                        mods[(rel, t.id)] = (t.id, kind)

    for sf in ctx.iter(_SCOPE, _EXCLUDE):
        if sf.tree is not None:
            scan(sf.tree.body, None, sf.rel)
    return attrs, mods


def _resource_of(expr, fi, attrs, mods):
    """Lock resource acquired by a with-item / ``.acquire()`` target
    expression, or None."""
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self" and fi.cls:
        got = attrs.get((fi.cls, expr.attr))
        return got[0] if got else None
    if isinstance(expr, ast.Name):
        got = mods.get((fi.sf.rel, expr.id))
        return got[0] if got else None
    return None


def _held_regions(fi, attrs, mods):
    """(resource, with-node) for every lock-holding with-block in the
    function's direct body.  Bare ``.acquire()`` holds are not region-
    modeled; they still contribute order edges when they happen inside
    another lock's with-block."""
    for n in ast.walk(fi.node):
        if id(n) in fi.nested_skip:
            continue
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                res = _resource_of(item.context_expr, fi, attrs, mods)
                if res is not None:
                    yield res, n


def _is_subprocess_call(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = dotted(node.func)
    leaf = flow.leaf_name(node.func)
    return leaf in flow.SUBPROCESS_LEAFS \
        or any(d.startswith(p) for p in flow.SUBPROCESS_PREFIXES)


@rule("R9")
def check_r9(ctx) -> list:
    graph = flow.CallGraph(ctx, _SCOPE, _EXCLUDE)
    attrs, mods = _lock_decls(ctx)
    kinds = {res: kind for res, kind in attrs.values()}
    kinds.update({res: kind for res, kind in mods.values()})

    def direct_acquires(fi):
        return {res for res, _n in _held_regions(fi, attrs, mods)}

    may_acquire = graph.fixpoint_sets(direct_acquires)
    may_collect = graph.fixpoint(
        lambda fi: fi.call_leafs & flow.COLLECTIVE_PRIMITIVES)
    may_sub = graph.fixpoint(
        lambda fi: any(_is_subprocess_call(n)
                       for n in ast.walk(fi.node)
                       if id(n) not in fi.nested_skip))

    out = []
    edges: dict[tuple, tuple] = {}   # (A, B) -> (sf, line, qualname)
    for fi in graph.infos:
        for res, wnode in _held_regions(fi, attrs, mods):
            inner_skip = set(fi.nested_skip)
            for n in ast.walk(wnode):
                if id(n) in inner_skip or n is wnode:
                    continue
                if isinstance(n, (ast.With, ast.AsyncWith)):
                    for item in n.items:
                        r2 = _resource_of(item.context_expr, fi,
                                          attrs, mods)
                        if r2 is not None:
                            edges.setdefault(
                                (res, r2),
                                (fi.sf, n.lineno, fi.qualname))
                elif isinstance(n, ast.Call):
                    leaf = flow.leaf_name(n.func)
                    if leaf == "acquire":
                        r2 = _resource_of(
                            getattr(n.func, "value", None), fi,
                            attrs, mods)
                        if r2 is not None:
                            edges.setdefault(
                                (res, r2),
                                (fi.sf, n.lineno, fi.qualname))
                        continue
                    if not leaf:
                        continue
                    for r2 in sorted(may_acquire.get(leaf, ())):
                        edges.setdefault(
                            (res, r2), (fi.sf, n.lineno, fi.qualname))
                    wedge = []
                    if leaf in may_collect \
                            or leaf in flow.COLLECTIVE_PRIMITIVES:
                        wedge.append("a collective")
                    if leaf in may_sub or _is_subprocess_call(n):
                        wedge.append("a subprocess spawn")
                    if wedge:
                        out.append(Violation(
                            "R9", fi.sf.rel, n.lineno, fi.qualname,
                            f"lock-held-dispatch:{res}:{leaf}",
                            f"{leaf}() may transitively reach "
                            f"{' and '.join(wedge)} while the "
                            f"{res} lock is held — a wedge there "
                            "holds the lock forever; release first, "
                            "or suppress naming the watchdog that "
                            "bounds the hold"))

    # ---- order cycles over the acquired-while-holding graph --------------
    adj: dict[str, set] = {}
    for (a, b), _site in edges.items():
        if a == b:
            if kinds.get(a) != "RLock":
                sf, line, qn = edges[(a, b)]
                out.append(Violation(
                    "R9", sf.rel, line, qn, f"lock-order:{a}->{b}",
                    f"non-reentrant Lock {a!r} re-acquired while "
                    "already held — self-deadlock (use RLock or "
                    "restructure)"))
            continue
        adj.setdefault(a, set()).add(b)

    state: dict[str, int] = {}

    def cyclic(v, stack):
        state[v] = 1
        for w in sorted(adj.get(v, ())):
            if state.get(w, 0) == 1:
                return stack[stack.index(w):] + [w] \
                    if w in stack else [v, w]
            if state.get(w, 0) == 0 and (c := cyclic(w, stack + [w])):
                return c
        state[v] = 2
        return None

    for v in sorted(adj):
        if state.get(v, 0) == 0:
            cyc = cyclic(v, [v])
            if cyc:
                for a, b in zip(cyc, cyc[1:]):
                    sf, line, qn = edges[(a, b)]
                    out.append(Violation(
                        "R9", sf.rel, line, qn,
                        f"lock-order:{a}->{b}",
                        f"lock-order cycle {' -> '.join(cyc)}: "
                        f"{b!r} acquired while holding {a!r} here, "
                        "and the reverse order exists elsewhere — "
                        "two threads interleaving these deadlock"))
                break

    # ---- cross-thread field discipline -----------------------------------
    for cls, (dom_a, dom_b) in _DOMAINS.items():
        members = [fi for fi in graph.infos if fi.cls == cls]
        names = {fi.name for fi in members}

        def domain(roots):
            seen = set(r for r in roots if r in names)
            work = list(seen)
            while work:
                m = work.pop()
                for fi in members:
                    if fi.name != m:
                        continue
                    # calls includes bare Name loads: the loop passes
                    # its step() closure into run_with_deadline
                    for cal in fi.calls & names:
                        if cal not in seen:
                            seen.add(cal)
                            work.append(cal)
            return seen

        da, db = domain(dom_a), domain(dom_b)
        lock_attrs = {attr for (c, attr) in attrs if c == cls}

        def field_uses(fi):
            """(attr, node, is_write, guarded) self-field accesses."""
            guarded_ids: set = set()
            for n in ast.walk(fi.node):
                if isinstance(n, (ast.With, ast.AsyncWith)) \
                        and any(isinstance(i.context_expr,
                                           ast.Attribute)
                                and isinstance(
                                    i.context_expr.value, ast.Name)
                                and i.context_expr.value.id == "self"
                                and i.context_expr.attr in lock_attrs
                                for i in n.items):
                    guarded_ids.update(id(x) for x in ast.walk(n))
            for n in ast.walk(fi.node):
                if id(n) in fi.nested_skip:
                    continue     # nested defs are their own members
                if isinstance(n, ast.Attribute) \
                        and isinstance(n.value, ast.Name) \
                        and n.value.id == "self":
                    yield (n.attr, n,
                           isinstance(n.ctx, (ast.Store, ast.Del)),
                           id(n) in guarded_ids)

        touched_a: dict[str, bool] = {}
        touched_b: dict[str, bool] = {}
        writes = []   # (fi, attr, node, in_a)
        for fi in members:
            in_a, in_b = fi.name in da, fi.name in db
            if not (in_a or in_b):
                continue
            for attr, node, is_write, guarded in field_uses(fi):
                if attr in lock_attrs:
                    continue
                if in_a:
                    touched_a[attr] = True
                if in_b:
                    touched_b[attr] = True
                if is_write and not guarded:
                    writes.append((fi, attr, node, in_a))
        for fi, attr, node, in_a in writes:
            other = touched_b if in_a else touched_a
            if other.get(attr):
                out.append(Violation(
                    "R9", fi.sf.rel, node.lineno, fi.qualname,
                    f"unguarded-field:{attr}",
                    f"self.{attr} written outside the {cls} lock in "
                    f"the {'handler' if in_a else 'loop'} thread and "
                    "touched from the other thread — guard the write "
                    "or suppress with the atomicity argument"))
    return out
