"""Flow-sensitive interprocedural analysis core (R8/R9/R10 substrate).

The R1-R7 rules match names; the rules this module powers prove
*dataflow* facts: "no collective is control-dependent on a
rank-divergent value" (R8), "no collective/subprocess call runs while
the daemon RLock is held, and the lock-order graph is acyclic" (R9),
"every int that reaches a device-array shape passed through the
``bucket()`` ladder" (R10).  Still pure stdlib ``ast``, still jax-free,
still whole-tree-in-seconds; the moving parts are:

- :class:`CallGraph` — a real function index over a file subset:
  every ``def`` (nested included) with its qualname, enclosing class
  chain, direct-body node set, and simple-name call edges.  Name-based
  edge resolution is deliberately kept from R2 (over-approximate: a
  missed edge is a silent pod wedge, an extra edge costs one reasoned
  suppression).
- per-function **summaries** via :meth:`CallGraph.fixpoint` — "may
  transitively call a collective / acquire lock X / spawn a
  subprocess" propagated over the call edges to a fixed point.
- a content-keyed **summary cache** (:func:`file_summary`) so the
  per-file local facts are computed once per file *content*: editing a
  file invalidates exactly its own entry (tested by
  tests/test_lint_flow.py), repeat runs in one process are cheap.
- **taint** (:func:`taint_names` / :func:`expr_tainted`) — forward
  propagation of a source predicate through a function's assignments
  to a fixed point, with a blessing set (``mh_uniform`` and the
  agreement collectives launder rank-taint: their *result* is uniform
  by construction).
- **control dependence** (:func:`walk_guarded`) — every direct-body
  statement with the stack of enclosing If/While/IfExp/BoolOp tests
  that decide whether it executes.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib

from .engine import SourceFile, dotted

#: collective primitives every rank must reach the same number of
#: times (the SPMD alignment contract R8 proves, and the "never while
#: holding a lock" resources R9 tracks).  Simple (leaf) callee names:
#: the jax collectives usable inside shard_map plus this repo's own
#: collective entry points (pod band exchange, multihost agreement).
COLLECTIVE_PRIMITIVES = frozenset({
    "process_allgather", "gather_band", "permute_shards",
    "all_gather", "psum", "pmax", "pmin", "ppermute", "all_to_all",
    "psum_scatter", "broadcast_one_to_all", "sync_global_devices",
    "pull_host",
})

#: subprocess spawn primitives (R9's "never while holding the daemon
#: lock" second class; ``subprocess.run`` is matched by dotted prefix
#: so a bare ``run()`` method elsewhere never aliases it).
SUBPROCESS_LEAFS = frozenset({"Popen", "check_call", "check_output"})
SUBPROCESS_PREFIXES = ("subprocess.", "os.system", "os.popen",
                       "os.spawn")

#: leaf names too generic to carry summary facts across the name-based
#: edges: ``d.get(...)`` would alias any scoped ``def get`` and weld
#: the whole tree into one summary blob.  Excluded from the *property
#: fixpoints* only — R2/R7 reachability keeps every edge (there a
#: false edge costs a suppression, a dropped one hides a pull).
GENERIC_LEAFS = frozenset({
    "get", "set", "setdefault", "add", "append", "extend", "insert",
    "update", "pop", "popleft", "remove", "discard", "clear", "copy",
    "keys", "values", "items", "join", "split", "strip", "format",
    "encode", "decode", "open", "read", "write", "close", "flush",
    "seek", "run", "start", "stop", "wait", "acquire", "release",
    "send", "recv", "put", "sort", "sorted", "index", "count", "inc",
    "result", "mkdir", "exists", "touch", "main", "next", "replace",
})

#: module roots whose attribute calls never resolve back into this
#: repo: ``np.load(...)`` must not alias a scoped ``def load``.
#: jax/jnp are deliberately NOT here — the collective primitives are
#: matched through exactly those dotted calls.
HOST_MODULE_ROOTS = frozenset({
    "np", "numpy", "os", "sys", "json", "base64", "pickle", "io",
    "pathlib", "time", "math", "re", "struct", "zlib", "gzip",
    "hashlib", "logging", "itertools", "functools", "collections",
    "socket", "shutil", "tempfile", "threading", "queue", "ast",
    "textwrap", "traceback", "warnings", "ctypes", "dataclasses",
})


def leaf_name(func) -> str:
    """Simple (rightmost) name of a call target; "" when dynamic."""
    d = dotted(func)
    return d.rsplit(".", 1)[-1] if d else ""


@dataclasses.dataclass
class FuncInfo:
    """One ``def`` in the analyzed subset."""
    sf: SourceFile
    qualname: str          # Class.method / outer.<locals-style> chain
    name: str              # simple name
    node: object           # ast.FunctionDef | ast.AsyncFunctionDef
    cls: str | None        # innermost enclosing class name
    def_lines: tuple       # def line + decorator lines (suppression
    #                        anchors, engine-resolved for every rule)
    nested_skip: frozenset  # id()s of nodes inside nested defs
    calls: frozenset       # simple callee names + bare Name loads
    call_leafs: frozenset  # simple callee names of actual Call nodes


def _index_file(sf: SourceFile) -> list:
    """Every function in one module as plain FuncInfo records — the
    cached per-file "local summary" the interprocedural passes stitch
    together (cache key: file content, see :func:`file_summary`)."""
    infos: list[FuncInfo] = []
    if sf.tree is None:
        return infos

    def visit(node, names, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = ".".join(names + [child.name])
                skip = set()
                for nf in ast.walk(child):
                    if isinstance(nf, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)) \
                            and nf is not child:
                        skip.update(id(x) for x in ast.walk(nf))
                calls, call_leafs = set(), set()
                for n in ast.walk(child):
                    if isinstance(n, ast.Call):
                        ln = leaf_name(n.func)
                        if ln:
                            # ``calls`` keeps every edge (R2/R7
                            # reachability, baseline-stable);
                            # ``call_leafs`` — the summary edges —
                            # drops host-module attribute calls so
                            # ``np.load`` never aliases a scoped
                            # ``def load``
                            calls.add(ln)
                            d = dotted(n.func)
                            if "." in d and d.split(".", 1)[0] \
                                    in HOST_MODULE_ROOTS:
                                continue
                            call_leafs.add(ln)
                    elif isinstance(n, ast.Name) \
                            and isinstance(n.ctx, ast.Load):
                        calls.add(n.id)
                infos.append(FuncInfo(
                    sf, qn, child.name, child, cls,
                    (child.lineno,) + tuple(
                        d.lineno for d in child.decorator_list),
                    frozenset(skip), frozenset(calls),
                    frozenset(call_leafs)))
                visit(child, names + [child.name], cls)
            elif isinstance(child, ast.ClassDef):
                visit(child, names + [child.name], child.name)
            else:
                visit(child, names, cls)

    visit(sf.tree, [], None)
    return infos


# ---------------------------------------------------------------------------
# content-keyed summary cache
# ---------------------------------------------------------------------------
_SUMMARY_CACHE: dict[tuple, object] = {}
_CACHE_CAP = 4096


def file_summary(sf: SourceFile, tag: str, compute):
    """``compute(sf)`` memoized on (tag, path, content-hash): a file
    edit changes the hash and recomputes exactly that file's entry;
    unrelated files keep their cached summaries."""
    key = (tag, sf.rel,
           hashlib.sha1(sf.text.encode("utf-8")).hexdigest())
    if key not in _SUMMARY_CACHE:
        if len(_SUMMARY_CACHE) >= _CACHE_CAP:
            _SUMMARY_CACHE.clear()
        _SUMMARY_CACHE[key] = compute(sf)
    return _SUMMARY_CACHE[key]


def summary_cache_clear() -> None:
    _SUMMARY_CACHE.clear()


class CallGraph:
    """Function index + name-edge call graph over a file subset."""

    def __init__(self, ctx, prefixes: tuple, exclude: tuple = ()):
        self.infos: list[FuncInfo] = []
        for sf in ctx.iter(prefixes, exclude):
            self.infos.extend(file_summary(sf, "callgraph", _index_file))
        self.by_name: dict[str, list[FuncInfo]] = {}
        for fi in self.infos:
            self.by_name.setdefault(fi.name, []).append(fi)

    def reachable(self, roots) -> list:
        """FuncInfos reachable from the named roots via simple-name
        edges (R2's worklist, shared)."""
        seen: dict[int, FuncInfo] = {}
        work = []
        for r in roots:
            for fi in self.by_name.get(r, ()):
                if id(fi.node) not in seen:
                    seen[id(fi.node)] = fi
                    work.append(fi)
        while work:
            fi = work.pop()
            for name in fi.calls:
                for cal in self.by_name.get(name, ()):
                    if id(cal.node) not in seen:
                        seen[id(cal.node)] = cal
                        work.append(cal)
        return list(seen.values())

    def fixpoint(self, seed) -> set:
        """Transitive may-property as a set of function *names*:
        ``seed(info)`` truthy marks a function; any function calling a
        marked name is marked, to a fixed point.  Name-level on
        purpose — same over-approximation as the edges themselves —
        but GENERIC_LEAFS neither carry the mark nor propagate it
        (``d.get(...)`` must not inherit some scoped ``get``'s
        summary)."""
        marked = {fi.name for fi in self.infos
                  if fi.name not in GENERIC_LEAFS and seed(fi)}
        changed = True
        while changed:
            changed = False
            for fi in self.infos:
                if fi.name in marked or fi.name in GENERIC_LEAFS:
                    continue
                if (fi.call_leafs - GENERIC_LEAFS) & marked:
                    marked.add(fi.name)
                    changed = True
        return marked

    def fixpoint_sets(self, seed) -> dict:
        """Like :meth:`fixpoint` but each function name maps to a SET
        it accumulates (e.g. lock resources it may acquire):
        ``seed(info)`` returns the direct set; callers' sets absorb
        their callees' to a fixed point (GENERIC_LEAFS edges dropped,
        as in :meth:`fixpoint`)."""
        acc: dict[str, set] = {}
        for fi in self.infos:
            if fi.name in GENERIC_LEAFS:
                continue
            acc.setdefault(fi.name, set()).update(seed(fi) or ())
        changed = True
        while changed:
            changed = False
            for fi in self.infos:
                mine = acc.get(fi.name)
                if mine is None:
                    continue
                before = len(mine)
                for cal in fi.call_leafs - GENERIC_LEAFS:
                    got = acc.get(cal)
                    if got:
                        mine |= got
                if len(mine) != before:
                    changed = True
        return acc


# ---------------------------------------------------------------------------
# control dependence
# ---------------------------------------------------------------------------
def walk_guarded(body, skip, guards=()):
    """Yield ``(stmt, guards)`` for every direct-body statement, where
    ``guards`` is the tuple of enclosing If/While test expressions that
    decide whether the statement runs.  Loop/try/with bodies pass
    through; nested defs (``skip``) are their own graph nodes."""
    for stmt in body:
        if id(stmt) in skip:
            continue
        yield stmt, guards
        if isinstance(stmt, ast.If):
            yield from walk_guarded(stmt.body, skip,
                                    guards + (stmt.test,))
            yield from walk_guarded(stmt.orelse, skip,
                                    guards + (stmt.test,))
        elif isinstance(stmt, ast.While):
            yield from walk_guarded(stmt.body, skip,
                                    guards + (stmt.test,))
            yield from walk_guarded(stmt.orelse, skip, guards)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            yield from walk_guarded(stmt.body, skip, guards)
            yield from walk_guarded(stmt.orelse, skip, guards)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            yield from walk_guarded(stmt.body, skip, guards)
        elif isinstance(stmt, ast.Try):
            for b in (stmt.body, stmt.orelse, stmt.finalbody):
                yield from walk_guarded(b, skip, guards)
            for h in stmt.handlers:
                yield from walk_guarded(h.body, skip, guards)


def expr_guards(root, target) -> tuple:
    """Expression-level tests deciding whether ``target`` (a node
    inside ``root``) evaluates: IfExp tests and the earlier operands of
    enclosing BoolOps (short-circuit guards)."""
    found = []

    def visit(node, guards):
        if node is target:
            found.append(guards)
            return
        if isinstance(node, ast.IfExp):
            visit(node.test, guards)
            visit(node.body, guards + (node.test,))
            visit(node.orelse, guards + (node.test,))
            return
        if isinstance(node, ast.BoolOp):
            for i, v in enumerate(node.values):
                visit(v, guards + tuple(node.values[:i]))
            return
        for child in ast.iter_child_nodes(node):
            visit(child, guards)

    visit(root, ())
    return found[0] if found else ()


# ---------------------------------------------------------------------------
# taint
# ---------------------------------------------------------------------------
def expr_tainted(expr, tainted: set, is_source, blessed=()) -> bool:
    """Does ``expr`` carry taint?  True when any sub-node satisfies
    ``is_source`` or reads a Name in ``tainted`` — except inside a
    call to a ``blessed`` laundering function (``mh_uniform``, the
    agreement collectives), whose *result* is uniform."""
    def visit(node) -> bool:
        if isinstance(node, ast.Call) and leaf_name(node.func) in blessed:
            return False
        if is_source(node):
            return True
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and node.id in tainted:
            return True
        return any(visit(c) for c in ast.iter_child_nodes(node))
    return visit(expr)


def taint_names(fn_node, skip, is_source, blessed=()) -> set:
    """Local variable names that (transitively, through direct-body
    assignments) carry a source value — forward fixpoint, flow-
    insensitive within the function (an over-approximation: a name once
    tainted stays tainted)."""
    tainted: set = set()

    def targets_of(stmt):
        if isinstance(stmt, ast.Assign):
            return stmt.targets
        if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            return [stmt.target]
        return []

    def name_leaves(t):
        if isinstance(t, ast.Name):
            yield t.id
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from name_leaves(e)

    changed = True
    while changed:
        changed = False
        for n in ast.walk(fn_node):
            if id(n) in skip:
                continue
            value = None
            tgts = []
            if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = n.value
                tgts = targets_of(n)
            elif isinstance(n, ast.NamedExpr):
                value = n.value
                tgts = [n.target]
            elif isinstance(n, (ast.For, ast.AsyncFor)):
                value = n.iter
                tgts = [n.target]
            if value is None:
                continue
            if expr_tainted(value, tainted, is_source, blessed):
                for t in tgts:
                    for nm in name_leaves(t):
                        if nm not in tainted:
                            tainted.add(nm)
                            changed = True
    return tainted
