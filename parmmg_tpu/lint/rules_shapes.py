"""R10 shape-ladder escapes: every measured int that becomes a device
array's shape must pass through the compile governor's ladder.

``--ledger`` observes compile-family boundedness empirically, after
paying the compiles; R10 proves the producing side statically.  A
device-array constructor's shape IS a compile family per distinct
value, so the sinks are the host-visible ``jnp.zeros/ones/full/empty``
size arguments and ``jnp.pad`` widths.  The rule resolves each size
expression backwards through the function's assignments (in source
order — flow-sensitive reaching definitions) and fails when a
**measurement** feeds the shape raw:

- ``len(...)``, and data-dependent reductions called as methods or
  via a module (``x.max()``, ``np.sum(...)``, ``counts.item()``...)

unless the value passes through a **ladder producer** first:
``bucket()``, ``pad_comm_tables()``, or any function whose returns are
themselves ladder-derived (summarized to a fixed point, so
``narrow_budget()``-style wrappers are recognized without a registry).

Trusted by construction (the check happens where the measurement is):

- parameters and attribute reads — a caller passing a raw measured
  size is flagged at ITS measurement site;
- ``.shape``/``.size`` of an existing array — an array built at a
  bucketed capacity carries its ladder;
- constants and arithmetic over trusted values (``capT * 6`` stays in
  the family of ``capT``).

Legitimately un-laddered shapes (one-shot ingest of a host mesh, the
cold boundary where the input defines the family) carry a reasoned
``# lint: ok(R10)``.
"""
from __future__ import annotations

import ast

from . import flow
from .engine import Violation, dotted, rule

_SCOPE = ("parmmg_tpu/",)
_EXCLUDE = ("parmmg_tpu/lint/",)

#: base ladder producers; extended each run by the returns-ladder
#: summary fixpoint
_LADDER_BASE = frozenset({"bucket", "pad_comm_tables"})

#: constructor leaf -> positional index of the shape argument
_SIZED = {"zeros": 0, "ones": 0, "full": 0, "empty": 0}
_PAD = {"pad": 1}

#: reductions that measure data when called as an attribute
#: (``x.max()``, ``np.sum(...)``); builtins stay transparent
_MEASURE_ATTRS = frozenset({"max", "min", "sum", "prod", "item",
                            "count_nonzero", "argmax", "argmin",
                            "nonzero", "searchsorted", "tolist"})

#: transparent numeric wrappers — recurse into their arguments
_TRANSPARENT = frozenset({"int", "float", "bool", "abs", "round",
                          "max", "min", "sum", "divmod"})


def _device_ns(call) -> bool:
    d = dotted(call.func)
    return d.startswith("jnp.") or d.startswith("jax.numpy.")


def _ladder_names(graph) -> set:
    """Function names whose returns are ladder-derived: a return value
    containing a call to a known ladder producer, to a fixed point."""
    names = set(_LADDER_BASE)
    changed = True
    while changed:
        changed = False
        for fi in graph.infos:
            if fi.name in names:
                continue
            for n in ast.walk(fi.node):
                if id(n) in fi.nested_skip \
                        or not isinstance(n, ast.Return) \
                        or n.value is None:
                    continue
                if any(isinstance(c, ast.Call)
                       and flow.leaf_name(c.func) in names
                       for c in ast.walk(n.value)):
                    names.add(fi.name)
                    changed = True
                    break
    return names


def _first_raw(expr, env, ladder, seen=()):
    """Tag of the first raw measurement in a (resolved) shape
    expression, or None when every leaf is ladder/trusted."""
    if isinstance(expr, ast.Call):
        leaf = flow.leaf_name(expr.func)
        if leaf in ladder:
            return None          # laundered: the ladder bounds it
        if leaf == "len":
            return "len()"
        if isinstance(expr.func, ast.Attribute) \
                and leaf in _MEASURE_ATTRS:
            return f".{leaf}()"
        if isinstance(expr.func, ast.Name) and leaf in _TRANSPARENT:
            for a in expr.args:
                got = _first_raw(a, env, ladder, seen)
                if got:
                    return got
            return None
        # unknown callee: its own returns are checked at ITS sinks
        return None
    if isinstance(expr, ast.Name):
        if expr.id in seen:
            return None
        bound = env.get(expr.id)
        if bound is None:
            return None          # parameter / outer scope: trusted
        return _first_raw(bound, env, ladder, seen + (expr.id,))
    if isinstance(expr, ast.Attribute):
        return None              # .shape/.size/self.cap: inherits
    if isinstance(expr, (ast.Constant,)):
        return None
    for child in ast.iter_child_nodes(expr):
        got = _first_raw(child, env, ladder, seen)
        if got:
            return got
    return None


def _scan_function(fi, ladder, out):
    """Walk the direct body in source order, tracking simple Name
    bindings (reaching definitions), checking each constructor sink
    against the bindings live at that point."""
    env: dict[str, object] = {}

    def check_expr(root):
        for n in ast.walk(root):
            if id(n) in fi.nested_skip or not isinstance(n, ast.Call):
                continue
            leaf = flow.leaf_name(n.func)
            size = None
            if _device_ns(n) and leaf in _SIZED:
                if n.args:
                    size = n.args[0]
                else:
                    size = next((kw.value for kw in n.keywords
                                 if kw.arg == "shape"), None)
            elif _device_ns(n) and leaf in _PAD:
                if len(n.args) > _PAD[leaf]:
                    size = n.args[_PAD[leaf]]
                else:
                    size = next((kw.value for kw in n.keywords
                                 if kw.arg == "pad_width"), None)
            if size is None:
                continue
            raw = _first_raw(size, env, ladder)
            if raw:
                out.append(Violation(
                    "R10", fi.sf.rel, n.lineno, fi.qualname,
                    f"raw-shape:{leaf}:{raw}",
                    f"jnp.{leaf}() shape fed by raw measurement "
                    f"{raw} — every distinct value is a new compile "
                    "family; route it through bucket()/"
                    "pad_comm_tables() (or suppress at a one-shot "
                    "ingest boundary with the reason)"))

    def walk(body):
        for stmt in body:
            if id(stmt) in fi.nested_skip or isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
                continue
            # sinks first: an assignment's RHS sees the env BEFORE it
            for root in _stmt_roots(stmt):
                check_expr(root)
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.expr):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        env[t.id] = stmt.value
            elif isinstance(stmt, ast.AnnAssign) \
                    and stmt.value is not None \
                    and isinstance(stmt.target, ast.Name):
                env[stmt.target.id] = stmt.value
            if isinstance(stmt, ast.If):
                walk(stmt.body)
                walk(stmt.orelse)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                walk(stmt.body)
                walk(stmt.orelse)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                walk(stmt.body)
            elif isinstance(stmt, ast.Try):
                walk(stmt.body)
                for h in stmt.handlers:
                    walk(h.body)
                walk(stmt.orelse)
                walk(stmt.finalbody)

    def _stmt_roots(stmt):
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return [stmt.iter]
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return [i.context_expr for i in stmt.items]
        if isinstance(stmt, ast.Try):
            return []
        return [stmt]

    walk(fi.node.body)


@rule("R10")
def check_r10(ctx) -> list:
    graph = flow.CallGraph(ctx, _SCOPE, _EXCLUDE)
    ladder = _ladder_names(graph)
    out: list = []
    for fi in graph.infos:
        _scan_function(fi, ladder, out)
    return out
