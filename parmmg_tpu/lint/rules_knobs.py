"""R4 knob-registry: every ``PARMMG_*`` env knob declared exactly once.

``parmmg_tpu/api/knobs.py`` is the registry (type + default + one-line
doc per knob).  R4 cross-checks it against the live tree in BOTH
directions, with NO baseline (the registry ships clean):

- every env READ of a ``PARMMG_*`` name (``os.environ.get`` /
  ``os.environ[...]`` / ``os.getenv`` / ``setdefault`` / ``pop`` /
  helper functions whose name contains ``env``, e.g. the serve pool's
  ``_env_int``) must name a registered knob;
- a read through a non-literal name expression is flagged outright
  (an f-string env key is an unauditable surface);
- every registered knob must have at least one AST usage anywhere in
  the tree (env access, kwarg, or string literal outside docstrings) —
  otherwise it is dead and fails;
- every registered knob must appear in README.md, and every
  ``PARMMG_*`` token README mentions must be registered — the README
  knob tables stay a *verified* rendering of the registry
  (``python -m parmmg_tpu.api.knobs`` prints the canonical table).
"""
from __future__ import annotations

import ast
import re

from .engine import (KNOBS_REL, Violation, dotted, rule, str_const,
                     walk_scoped)

_KNOB_RE = re.compile(r"^PARMMG_[A-Z0-9_]+$")
_KNOB_TOKEN_RE = re.compile(r"PARMMG_[A-Z0-9_]+")

_SCOPE = ("parmmg_tpu/", "scripts/", "tests/", "bench.py")

_ENV_GET_ATTRS = ("get", "setdefault", "pop", "__getitem__")


def _env_read_name_node(call):
    """If ``call`` is an env access, return its name-argument node."""
    f = call.func
    if isinstance(f, ast.Attribute):
        base = dotted(f.value)
        if f.attr in _ENV_GET_ATTRS and base.endswith("environ"):
            return call.args[0] if call.args else None
        if f.attr == "getenv" and base in ("os", ""):
            return call.args[0] if call.args else None
        if "env" in f.attr.lower():
            return call.args[0] if call.args else None
    if isinstance(f, ast.Name) and "env" in f.id.lower() and call.args:
        return call.args[0]
    return None


def _docstring_nodes(tree) -> set:
    """ids of docstring Constant nodes (excluded from usage evidence)."""
    out = set()
    for n in ast.walk(tree):
        if isinstance(n, (ast.Module, ast.FunctionDef,
                          ast.AsyncFunctionDef, ast.ClassDef)):
            body = getattr(n, "body", [])
            if body and isinstance(body[0], ast.Expr) and \
                    isinstance(body[0].value, ast.Constant) and \
                    isinstance(body[0].value.value, str):
                out.add(id(body[0].value))
    return out


@rule("R4")
def check_r4(ctx) -> list:
    registry = ctx.knob_registry()
    out: list[Violation] = []
    used: set = set()

    for sf in ctx.iter(_SCOPE, exclude=(KNOBS_REL,)):
        if sf.tree is None:
            continue
        docstrings = _docstring_nodes(sf.tree)
        for node, qn, _funcs in walk_scoped(sf.tree):
            # env accesses: literal name must be registered
            if isinstance(node, ast.Call):
                nm = _env_read_name_node(node)
                if nm is not None:
                    s = str_const(nm)
                    if s is None:
                        # dynamic name: only flag when it visibly
                        # builds a PARMMG key
                        if any(_KNOB_TOKEN_RE.search(c.value)
                               for c in ast.walk(nm)
                               if isinstance(c, ast.Constant)
                               and isinstance(c.value, str)):
                            out.append(Violation(
                                "R4", sf.rel, node.lineno, qn,
                                "dynamic-env-read",
                                "PARMMG_* env access through a "
                                "non-literal name — unauditable"))
                        continue
                    if _KNOB_RE.match(s):
                        used.add(s)
                        if s not in registry:
                            out.append(Violation(
                                "R4", sf.rel, node.lineno, qn, s,
                                f"env read of unregistered knob {s} — "
                                "declare it in parmmg_tpu/api/knobs.py"))
            # subscript access os.environ["PARMMG_X"] (read or write)
            if isinstance(node, ast.Subscript) and \
                    dotted(node.value).endswith("environ"):
                s = str_const(node.slice)
                if s and _KNOB_RE.match(s):
                    used.add(s)
                    if s not in registry:
                        out.append(Violation(
                            "R4", sf.rel, node.lineno, qn, s,
                            f"env access of unregistered knob {s} — "
                            "declare it in parmmg_tpu/api/knobs.py"))
            # usage evidence: kwargs + non-docstring literals
            if isinstance(node, ast.keyword) and node.arg and \
                    _KNOB_RE.match(node.arg):
                used.add(node.arg)
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    id(node) not in docstrings and \
                    _KNOB_RE.match(node.value):
                used.add(node.value)

    # dead registered knobs
    for name, info in sorted(registry.items()):
        if name not in used:
            out.append(Violation(
                "R4", KNOBS_REL, info.get("line", 0), "KNOBS", name,
                f"registered knob {name} has no usage anywhere in the "
                "tree — dead; delete it or wire it"))

    # README two-way check
    readme = ctx.readme_text or ""
    readme_knobs = set(_KNOB_TOKEN_RE.findall(readme))
    for name, info in sorted(registry.items()):
        if name not in readme_knobs:
            out.append(Violation(
                "R4", KNOBS_REL, info.get("line", 0), "KNOBS", name,
                f"registered knob {name} missing from README.md — "
                "regenerate the knob table "
                "(python -m parmmg_tpu.api.knobs)"))
    for name in sorted(readme_knobs - set(registry)):
        out.append(Violation(
            "R4", "README.md", 0, "<doc>", name,
            f"README mentions unregistered knob {name} — register it "
            "or fix the doc"))
    return out
