"""R1 jit-hygiene + R5 jaxcompat: the compile-governor contracts,
statically.

R1 — every ``jax.jit`` / ``jax.pmap`` / ``shard_map`` construction must
be module-level-cached so repeat calls reuse ONE traced program (jit
caches by function identity: a fresh jit object per call recompiles
forever — the exact churn the runtime ``--ledger`` gate prices in
minutes of XLA:CPU compile).  Accepted caching idioms, matched on the
AST (these are the idioms PRs 3-5 actually converged on):

- module scope: decorator on a module-level def, or a module-level
  assignment (``analyze_mesh = jax.jit(...)``);
- a builder whose result is bound at module level
  (``swapgen_wave_j = _make_swapgen_jit()``);
- an ``functools.lru_cache``-ed builder;
- a builder that stores into a module-level CAPS cache
  (``_GROUP_BLOCK_CACHE[key] = run``, ``_QPROBE.append(probe)``, or a
  ``global`` rebind — the _EXTRACT_PROBE idiom);
- an instance cache (``self.x = ...`` — the DistSteps pattern);
- a ``governed(...)``-wrapped construction in the same statement (the
  ledger then bounds the variant count at runtime even if the caller
  caches); a bare ``shard_map`` wrapper also passes when its builder
  governs a product anywhere in the function — the compile object is
  the jit built around it (the dist_adapt_block idiom), while a
  per-call ``jax.jit``/``pmap`` must be governed in its own statement.

Anything else is a per-call construction and gets flagged.

R5 — the jax 0.4.37 shims live ONLY in ``utils/jaxcompat.py``
(ROADMAP housekeeping): direct use of the shimmed spellings
(``jax.experimental.shard_map``, ``jax.shard_map``,
``jax.lax.axis_size``, ``jax.lax.platform_dependent``) anywhere else
bypasses the one sanctioned bridge and breaks on the pinned image or
on the next jax bump.
"""
from __future__ import annotations

import ast
import re

from .engine import Violation, dotted, rule, walk_scoped

_CAPS_RE = re.compile(r"^_?[A-Z][A-Z0-9_]*$")
_CACHED_DECOS = ("lru_cache", "cache")

# dotted spellings that construct a compiled-program object
_JIT_DOTTED = {"jax.jit", "jax.pmap"}
# local names bound by `from ... import X` that do the same
_JIT_FROM = {"shard_map": ("jax.experimental.shard_map", "jaxcompat"),
             "jit": ("jax",), "pmap": ("jax",)}

_R5_DOTTED = {
    "jax.experimental.shard_map.shard_map": "shard_map",
    "jax.shard_map": "shard_map",
    "jax.lax.axis_size": "axis_size",
    "jax.lax.platform_dependent": "platform_dependent",
}
_R5_MODULES = ("jax.experimental.shard_map",)
_SHIM_REL = "parmmg_tpu/utils/jaxcompat.py"


def _jit_aliases(tree) -> set:
    """Local names that are jit-like constructors in this module
    (``from jax import jit``, ``from ..utils.jaxcompat import
    shard_map``, ``from jax.experimental.shard_map import shard_map``)."""
    out = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ImportFrom) or not node.module:
            continue
        for a in node.names:
            local = a.asname or a.name
            srcs = _JIT_FROM.get(a.name)
            if srcs and any(s in node.module for s in srcs):
                out.add(local)
    return out


def _decorated_cached(fn_node) -> bool:
    for d in fn_node.decorator_list:
        base = d.func if isinstance(d, ast.Call) else d
        name = dotted(base)
        if name.split(".")[-1] in _CACHED_DECOS:
            return True
    return False


def _module_cache_store(fn_node) -> bool:
    """Does the function body persist something into a module-level
    cache (CAPS subscript store / .append, a ``global`` rebind) or an
    instance attribute?"""
    globals_declared = set()
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Global):
            globals_declared.update(n.names)
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and _CAPS_RE.match(t.value.id)):
                    return True
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    return True
                if isinstance(t, ast.Name) and t.id in globals_declared:
                    return True
        if (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "append"
                and isinstance(n.func.value, ast.Name)
                and _CAPS_RE.match(n.func.value.id)):
            return True
    return False


def _module_level_builders(tree) -> set:
    """Function names whose call result is bound at module scope
    (``x = _make_...()``)."""
    out = set()
    for stmt in tree.body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            val = stmt.value
            if val is None:
                continue
            for n in ast.walk(val):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Name):
                    out.add(n.func.id)
    return out


def _governed_in(node) -> bool:
    """Any ``governed(...)`` application inside ``node`` (statement or
    decorator list) — the ledger-registration escape hatch."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            base = n.func.func if isinstance(n.func, ast.Call) \
                else n.func
            if dotted(base).split(".")[-1] == "governed":
                return True
    return False


@rule("R1")
def check_r1(ctx) -> list:
    out = []
    for sf in ctx.iter(("parmmg_tpu/",), exclude=(_SHIM_REL,)):
        if sf.tree is None:
            continue
        aliases = _jit_aliases(sf.tree)
        builders = _module_level_builders(sf.tree)

        # index: function node -> list of its body statements is free via
        # ast; we need, per offending node, its enclosing stmt + fn chain
        for node, qn, funcs in walk_scoped(sf.tree):
            name = None
            if isinstance(node, (ast.Attribute, ast.Name)):
                d = dotted(node)
                if d in _JIT_DOTTED:
                    name = d
                elif isinstance(node, ast.Name) and node.id in aliases \
                        and isinstance(node.ctx, ast.Load):
                    name = node.id
            if name is None:
                continue
            if not funcs:
                continue                      # module scope: cached
            fn = funcs[-1]
            # the mention may be a decorator of a nested def: walk_scoped
            # reports decorator nodes under the *enclosing* function, so
            # funcs[-1] is already the scope whose caching matters
            chain_cached = any(_decorated_cached(f) for f in funcs)
            stores = any(_module_cache_store(f) for f in funcs)
            built_once = any(f.name in builders for f in funcs)
            if chain_cached or stores or built_once:
                continue
            # governed() in the SAME statement registers this very
            # construction with the compile ledger, whose variant
            # budget bounds churn at runtime
            stmt = _enclosing_stmt(fn, node)
            if stmt is not None and _governed_in(stmt):
                continue
            # a bare shard_map wrapper is cheap by itself — the compile
            # object is the jit built around it; accept it when the
            # builder governs a product anywhere (the dist_adapt_block
            # idiom: fn = shard_map(...); return governed(...)(jit(fn)))
            # while a per-call jit/pmap still needs ITS OWN statement
            # governed or a cache
            if name.split(".")[-1] == "shard_map" and _governed_in(fn):
                continue
            out.append(Violation(
                "R1", sf.rel, node.lineno, qn, name,
                f"per-call {name} construction in {qn}(): cache at "
                "module level (CAPS cache dict / lru_cache / module "
                "assignment) or register via governed()"))
    return out


def _enclosing_stmt(fn_node, target):
    """Smallest statement within ``fn_node`` containing ``target``
    (walk order guarantees later matches are nested deeper)."""
    best = None
    for n in ast.walk(fn_node):
        if not isinstance(n, ast.stmt):
            continue
        for sub in ast.walk(n):
            if sub is target:
                best = n
                break
    return best


@rule("R5")
def check_r5(ctx) -> list:
    out = []
    for sf in ctx.iter(("parmmg_tpu/", "scripts/", "bench.py"),
                       exclude=(_SHIM_REL,)):
        if sf.tree is None:
            continue
        for node, qn, _funcs in walk_scoped(sf.tree):
            if isinstance(node, ast.ImportFrom) and node.module and \
                    any(node.module.startswith(m) for m in _R5_MODULES):
                out.append(Violation(
                    "R5", sf.rel, node.lineno, qn, node.module,
                    f"direct import of {node.module} — use the "
                    "utils/jaxcompat.py shim"))
                continue
            if isinstance(node, ast.Import):
                for a in node.names:
                    if any(a.name.startswith(m) for m in _R5_MODULES):
                        out.append(Violation(
                            "R5", sf.rel, node.lineno, qn, a.name,
                            f"direct import of {a.name} — use the "
                            "utils/jaxcompat.py shim"))
                continue
            if isinstance(node, ast.Attribute):
                d = dotted(node)
                sym = _R5_DOTTED.get(d)
                if sym:
                    out.append(Violation(
                        "R5", sf.rel, node.lineno, qn, sym,
                        f"direct use of {d} — shimmed symbol; import "
                        f"{sym} from utils/jaxcompat.py"))
    return out
