"""Invariant-linter engine: file model, suppressions, baseline gate.

Pure stdlib (``ast`` + ``json``) and deliberately jax-free: the rules
check the *source* of the compile/host-sync/obs/knob contracts that the
runtime gates (``run_tests.sh --ledger/--obs/--chaos``) can only verify
by paying minutes of XLA:CPU compile.  The engine is the shared layer:

- :class:`SourceFile` — parsed module + the per-line suppression map
  (``# lint: ok(R3) — reason``; the reason is mandatory, a reasonless
  suppression is itself a violation, rule ``SUPP``);
- :class:`LintContext` — the file set plus the cross-file registries
  some rules need (the ``api/knobs.py`` knob dict, the
  ``resilience.faults.SITES`` / ``recover.LADDER`` name sets, the
  README text), all recovered by AST/text so nothing heavy imports;
- :func:`run_lint` — run a rule subset over a root (or an explicit
  file dict, the unit-test entry) and split raw findings into
  suppressed / unsuppressed;
- :func:`gate` + :func:`load_baseline` / :func:`baseline_payload` —
  the zero-new-violations gate: ``lint_baseline.json`` grandfathers
  the violations that predate the linter as ``{key: count}`` and the
  gate fails only on keys (or counts) beyond it, printing a per-rule
  burn-down so the grandfathered debt is visible shrinking.

Violation identity (:attr:`Violation.key`) is ``rule:path:scope:detail``
— no line numbers, so unrelated edits that shift lines never invalidate
the baseline, while a NEW offender in a touched function still fails.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from collections import Counter

#: every rule the engine knows; rule modules register their checker in
#: RULES via :func:`rule` at import time (lint/__init__ imports them).
RULES: dict[str, "object"] = {}

RULE_TITLES = {
    "R1": "jit-hygiene (cached + governed jit/pmap/shard_map sites)",
    "R2": "host-sync (no stray device->host pulls on the hot paths)",
    "R3": "obs-routing (no bare print outside obs/; use obs.trace.log)",
    "R4": "knob-registry (PARMMG_* reads match api/knobs.py + README)",
    "R5": "jaxcompat (version-shimmed jax symbols only via the shim)",
    "R6": "name-schemes (static dotted metric/trace/fault names)",
    "R7": "mh-allgather (no pull_host/process_allgather on the pod "
          "hot path; route band tables through pod.gather_band)",
    "R8": "spmd-alignment (no collective control-dependent on "
          "rank-divergent state; mh_uniform/allgather-agreed only)",
    "R9": "lock-discipline (acyclic lock order; no collective/"
          "subprocess dispatch under a held lock; guarded "
          "cross-thread fields)",
    "R10": "shape-ladder (device-array shapes from measured ints "
           "must pass bucket()/pad_comm_tables)",
    "SUPP": "suppression hygiene (reason required)",
}


def rule(rid: str):
    """Decorator registering ``check(ctx) -> list[Violation]`` under a
    rule id."""
    def deco(fn):
        RULES[rid] = fn
        return fn
    return deco


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str            # repo-relative, posix separators
    line: int
    scope: str           # enclosing qualname, or "<module>"
    detail: str          # stable offender tag (callee / knob / name)
    message: str
    #: extra lines a suppression may sit on (e.g. the enclosing def
    #: line for R2's whole-function fallback exemption); not part of
    #: the identity key
    anchor_lines: tuple = ()

    @property
    def key(self) -> str:
        """Line-free identity used by suppression-independent baseline
        matching."""
        return f"{self.rule}:{self.path}:{self.scope}:{self.detail}"


@dataclasses.dataclass(frozen=True)
class Suppression:
    line: int            # line the suppression APPLIES to
    rules: tuple
    reason: str
    comment_line: int    # line the comment physically sits on


_SUPP_RE = re.compile(
    r"#\s*lint:\s*ok\(\s*([A-Za-z0-9_,\s]+?)\s*\)\s*(.*)$")
# separators allowed between ok(...) and the reason: em/en dash, hyphen,
# colon — whatever is left after stripping them must be non-empty
_SEP_RE = re.compile(r"^[\s—–:\-]+")


class SourceFile:
    """One parsed module: text, ast, parent links, suppression map."""

    def __init__(self, rel: str, text: str):
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.parse_error: str | None = None
        try:
            self.tree = ast.parse(text)
        except SyntaxError as e:           # pragma: no cover - tree is clean
            self.tree = None
            self.parse_error = f"{rel}:{e.lineno}: {e.msg}"
        self.suppressions: dict[int, list[Suppression]] = {}
        self.bad_suppressions: list[Violation] = []
        self._def_index: list | None = None
        self._scan_suppressions()

    def _scan_suppressions(self) -> None:
        for i, ln in enumerate(self.lines, start=1):
            m = _SUPP_RE.search(ln)
            if not m:
                continue
            rules = tuple(r.strip() for r in m.group(1).split(",")
                          if r.strip())
            reason = _SEP_RE.sub("", m.group(2)).strip()
            # standalone comment -> applies to the next non-comment
            # line (the reason may wrap onto continuation comment
            # lines); trailing comment -> applies to its own line
            standalone = ln.strip().startswith("#")
            target = i
            if standalone:
                target = i + 1
                while (target <= len(self.lines)
                       and self.lines[target - 1].strip()
                       .startswith("#")):
                    target += 1
            if not reason:
                self.bad_suppressions.append(Violation(
                    "SUPP", self.rel, i, "<comment>",
                    ",".join(rules) or "?",
                    "suppression without a reason — write "
                    "'# lint: ok(<rule>) — why this is allowed'"))
                continue
            unknown = [r for r in rules if r not in RULE_TITLES]
            if unknown or not rules:
                self.bad_suppressions.append(Violation(
                    "SUPP", self.rel, i, "<comment>",
                    ",".join(rules) or "?",
                    f"suppression names unknown rule(s) {unknown}"))
                continue
            s = Suppression(target, rules, reason, i)
            self.suppressions.setdefault(target, []).append(s)

    def suppressed(self, rid: str, line: int,
                   extra_lines: tuple = ()) -> Suppression | None:
        """Suppression covering ``line`` (or any of ``extra_lines`` —
        rules pass e.g. the enclosing ``def`` line for function-scoped
        exemptions) for rule ``rid``."""
        for ln in (line, *extra_lines):
            for s in self.suppressions.get(ln, ()):
                if rid in s.rules:
                    return s
        return None

    def def_anchors(self, line: int) -> tuple:
        """Def + decorator lines of the innermost function enclosing
        ``line`` — the engine-level anchors that make a def-line
        ``# lint: ok(...)`` exempt the whole function identically for
        EVERY rule (not just the ones that pass anchor_lines)."""
        if self.tree is None:
            return ()
        if self._def_index is None:
            idx = []
            for node in ast.walk(self.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    anchors = (node.lineno,) + tuple(
                        d.lineno for d in node.decorator_list)
                    start = min(anchors)
                    end = getattr(node, "end_lineno", None) \
                        or node.lineno
                    idx.append((start, end, anchors))
            self._def_index = idx
        best = None
        for start, end, anchors in self._def_index:
            if start <= line <= end and (
                    best is None or end - start < best[0]):
                best = (end - start, anchors)
        return best[1] if best else ()


# ---------------------------------------------------------------------------
# shared AST helpers (used by the rule modules)
# ---------------------------------------------------------------------------
def dotted(node) -> str:
    """Dotted source name of a Name/Attribute chain (``jax.jit``,
    ``os.environ.get``); "" for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def walk_scoped(tree):
    """Yield ``(node, qualname, func_stack)`` for every node, where
    ``func_stack`` is the chain of enclosing FunctionDef nodes and
    ``qualname`` joins class/function names (module scope =
    "<module>").  Decorator expressions are attributed to the scope
    CONTAINING the decorated def (a ``@jax.jit`` on a module-level def
    is a module-scope construction, not one "inside" that function)."""
    def visit(node, names, funcs):
        qn = ".".join(names) if names else "<module>"
        deco = set()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            deco = {id(d) for d in node.decorator_list}
        for child in ast.iter_child_nodes(node):
            if id(child) in deco:
                continue           # already attributed to the outer scope
            is_fn = isinstance(child, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
            is_cls = isinstance(child, ast.ClassDef)
            if is_fn or is_cls:
                for d in child.decorator_list:
                    for n in ast.walk(d):
                        yield n, qn, tuple(funcs)
            yield child, qn, tuple(funcs)
            if is_fn or is_cls:
                yield from visit(child, names + [child.name],
                                 funcs + [child] if is_fn else funcs)
            else:
                yield from visit(child, names, funcs)
    yield from visit(tree, [], [])


def str_const(node) -> str | None:
    return node.value if (isinstance(node, ast.Constant)
                          and isinstance(node.value, str)) else None


# ---------------------------------------------------------------------------
# context
# ---------------------------------------------------------------------------
KNOBS_REL = "parmmg_tpu/api/knobs.py"
FAULTS_REL = "parmmg_tpu/resilience/faults.py"
RECOVER_REL = "parmmg_tpu/resilience/recover.py"


class LintContext:
    def __init__(self, files: dict[str, SourceFile],
                 readme_text: str = ""):
        self.files = files
        self.readme_text = readme_text

    def iter(self, prefixes: tuple, exclude: tuple = ()):
        """SourceFiles under any of ``prefixes`` (a rel file name is
        its own prefix), minus ``exclude`` prefixes."""
        for rel in sorted(self.files):
            if not rel.endswith(".py"):
                continue
            if not any(rel == p or rel.startswith(p) for p in prefixes):
                continue
            if any(rel == p or rel.startswith(p) for p in exclude):
                continue
            yield self.files[rel]

    # -- registries recovered by AST (never imported) -----------------------
    def knob_registry(self) -> dict[str, dict]:
        """{knob: {type, default, doc}} parsed from api/knobs.py's
        KNOBS dict literal."""
        sf = self.files.get(KNOBS_REL)
        out: dict[str, dict] = {}
        if sf is None or sf.tree is None:
            return out
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            else:
                continue
            if not (any(isinstance(t, ast.Name) and t.id == "KNOBS"
                        for t in targets)
                    and isinstance(node.value, ast.Dict)):
                continue
            for k, v in zip(node.value.keys, node.value.values):
                name = str_const(k)
                if name is None:
                    continue
                args = [str_const(a) for a in getattr(v, "args", [])]
                out[name] = {
                    "line": k.lineno,
                    "type": args[0] if len(args) > 0 else "",
                    "default": args[1] if len(args) > 1 else "",
                    "doc": args[2] if len(args) > 2 else "",
                }
        return out

    def _const_names(self, rel: str, var: str) -> set:
        """String keys/items of a module-level dict/tuple constant
        (faults.SITES, recover.LADDER)."""
        sf = self.files.get(rel)
        if sf is None or sf.tree is None:
            return set()
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == var
                            for t in node.targets)):
                continue
            v = node.value
            if isinstance(v, ast.Dict):
                return {s for s in (str_const(k) for k in v.keys) if s}
            if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
                return {s for s in (str_const(e) for e in v.elts) if s}
        return set()

    def fault_sites(self) -> set:
        return self._const_names(FAULTS_REL, "SITES")

    def ladder_steps(self) -> set:
        return self._const_names(RECOVER_REL, "LADDER")


# ---------------------------------------------------------------------------
# running
# ---------------------------------------------------------------------------
SCAN_ROOTS = ("parmmg_tpu", "scripts", "tests")
SCAN_SINGLES = ("bench.py",)


def collect_files(root: str) -> dict[str, SourceFile]:
    files: dict[str, SourceFile] = {}
    for top in SCAN_ROOTS:
        base = os.path.join(root, top)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and
                           not d.startswith(".")]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                p = os.path.join(dirpath, fn)
                rel = os.path.relpath(p, root).replace(os.sep, "/")
                with open(p, encoding="utf-8") as f:
                    files[rel] = SourceFile(rel, f.read())
    for single in SCAN_SINGLES:
        p = os.path.join(root, single)
        if os.path.exists(p):
            with open(p, encoding="utf-8") as f:
                files[single] = SourceFile(single, f.read())
    return files


@dataclasses.dataclass
class LintReport:
    violations: list       # unsuppressed, gate-relevant
    suppressed: list       # (Violation, Suppression) pairs
    bad: list              # SUPP violations + parse errors

    def by_rule(self) -> dict[str, list]:
        out: dict[str, list] = {}
        for v in self.violations:
            out.setdefault(v.rule, []).append(v)
        return out


def run_lint(root: str | None = None, rules=None,
             files: dict[str, SourceFile] | None = None,
             readme_text: str | None = None) -> LintReport:
    """Run ``rules`` (default: all registered) over ``root`` (or an
    explicit ``files`` dict — the test entry point)."""
    if files is None:
        assert root is not None
        files = collect_files(root)
    if readme_text is None:
        readme_text = ""
        if root is not None:
            rp = os.path.join(root, "README.md")
            if os.path.exists(rp):
                with open(rp, encoding="utf-8") as f:
                    readme_text = f.read()
    ctx = LintContext(files, readme_text)
    wanted = tuple(rules) if rules else tuple(sorted(RULES))
    unknown = [r for r in wanted if r not in RULES]
    if unknown:
        raise ValueError(f"unknown lint rule id(s) {unknown}; "
                         f"known: {sorted(RULES)}")
    raw: list[Violation] = []
    for rid in wanted:
        raw.extend(RULES[rid](ctx))
    bad: list[Violation] = []
    for sf in files.values():
        bad.extend(sf.bad_suppressions)
        if sf.parse_error:
            bad.append(Violation("SUPP", sf.rel, 0, "<module>",
                                 "parse-error", sf.parse_error))
    kept, supp = [], []
    for v in raw:
        sf = files.get(v.path)
        # rule-provided anchors plus the engine-resolved enclosing-def
        # lines: a def-line suppression exempts the whole function for
        # any rule, decorated or not
        s = sf.suppressed(
            v.rule, v.line,
            tuple(v.anchor_lines) + sf.def_anchors(v.line)) if sf \
            else None
        (supp if s else kept).append((v, s) if s else v)
    kept.sort(key=lambda v: (v.path, v.line, v.rule))
    return LintReport(kept, supp, bad)


# ---------------------------------------------------------------------------
# baseline gate
# ---------------------------------------------------------------------------
def load_baseline(path: str) -> dict:
    """{key: count} from lint_baseline.json (empty when absent)."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    g = doc.get("grandfathered", doc)
    return {str(k): int(v) for k, v in g.items()}


def baseline_payload(report: LintReport) -> dict:
    counts = Counter(v.key for v in report.violations)
    return {"version": 1,
            "note": "grandfathered pre-linter violations; burn down, "
                    "never add — scripts/lint_check.py --baseline-update "
                    "rewrites after an intentional rotation",
            "grandfathered": {k: counts[k] for k in sorted(counts)}}


@dataclasses.dataclass
class GateResult:
    new: list              # violations beyond the baseline
    bad: list              # SUPP findings (never baselineable)
    burndown: dict         # rule -> {baseline, current, retired}

    @property
    def ok(self) -> bool:
        return not self.new and not self.bad


def gate(report: LintReport, baseline: dict,
         no_baseline_rules: tuple = ("R4",)) -> GateResult:
    """Zero-new-violations gate.  Rules in ``no_baseline_rules`` ignore
    the baseline entirely (the knob registry ships clean from day one)."""
    counts = Counter(v.key for v in report.violations)
    allowed = dict(baseline)
    for k in list(allowed):
        rid = k.split(":", 1)[0]
        if rid in no_baseline_rules:
            del allowed[k]
    new: list[Violation] = []
    seen: Counter = Counter()
    for v in report.violations:
        seen[v.key] += 1
        if seen[v.key] > allowed.get(v.key, 0):
            new.append(v)
    burn: dict[str, dict] = {}
    for k, n in allowed.items():
        rid = k.split(":", 1)[0]
        b = burn.setdefault(rid, {"baseline": 0, "current": 0,
                                  "retired": 0})
        b["baseline"] += n
        cur = min(counts.get(k, 0), n)
        b["current"] += cur
        b["retired"] += n - cur
    return GateResult(new, list(report.bad), burn)


def format_report(report: LintReport, result: GateResult) -> str:
    lines = []
    for v in result.bad:
        lines.append(f"SUPP {v.path}:{v.line}: {v.message}")
    for v in result.new:
        lines.append(f"{v.rule} {v.path}:{v.line} [{v.scope}] "
                     f"{v.message}")
    lines.append("")
    lines.append(f"{'rule':5s} {'new':>4s} {'baselined':>9s} "
                 f"{'retired':>8s} {'suppressed':>10s}  title")
    nsupp = Counter(v.rule for v, _ in report.suppressed)
    nnew = Counter(v.rule for v in result.new)
    for rid in sorted(RULE_TITLES):
        if rid == "SUPP":
            continue
        b = result.burndown.get(rid, {})
        lines.append(f"{rid:5s} {nnew.get(rid, 0):4d} "
                     f"{b.get('current', 0):9d} "
                     f"{b.get('retired', 0):8d} "
                     f"{nsupp.get(rid, 0):10d}  {RULE_TITLES[rid]}")
    return "\n".join(lines)
