"""R3 obs-routing + R6 name-schemes: the PR-8 telemetry contracts,
statically.

R3 — no bare ``print(`` in ``parmmg_tpu/`` outside ``obs/``:
``obs.trace.log(level, msg, verbose=...)`` is the ONE imprim-gated
print path, and it emits a trace record whether or not the line shows,
so suppressed runs still reach the trace ring.  ``scripts/`` are
exempt (artifact emitters own their stdout), and the few legitimate
stdout contracts inside the package (the CLI's machine-readable dumps,
the polish worker's stderr relay protocol) carry reasoned
suppressions.

R6 — metric / trace-event / faultpoint names must be STATIC
dotted-lowercase literals: series names are the cross-artifact join
key (``ledger_check.py --diff`` matches them by equality) and every
dynamic name is a potential unbounded-cardinality series.  Checked
call surfaces: ``REGISTRY.counter/gauge/histogram``, ``*.event`` /
``event`` (obs.trace), ``faultpoint`` / ``fault_trigger`` (site must
exist in ``resilience.faults.SITES``), ``ladder_step`` (step must
exist in ``recover.LADDER``).  A conditional expression over literals
is fine; an f-string or concatenation needs a suppression arguing the
cardinality bound (e.g. the serve occupancy gauge keyed by the finite
capacity ladder).
"""
from __future__ import annotations

import ast
import re

from .engine import Violation, dotted, rule, str_const, walk_scoped

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)*$")

_R3_SCOPE = ("parmmg_tpu/",)
_R3_EXCLUDE = ("parmmg_tpu/obs/",)

_R6_SCOPE = ("parmmg_tpu/",)
# the spine itself (generic emitters take the name as a parameter) and
# the registries' home modules are exempt by construction
_R6_EXCLUDE = ("parmmg_tpu/obs/", "parmmg_tpu/resilience/faults.py",
               "parmmg_tpu/resilience/recover.py")

_METRIC_METHODS = ("counter", "gauge", "histogram")


@rule("R3")
def check_r3(ctx) -> list:
    out = []
    for sf in ctx.iter(_R3_SCOPE, exclude=_R3_EXCLUDE):
        if sf.tree is None:
            continue
        for node, qn, _funcs in walk_scoped(sf.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                out.append(Violation(
                    "R3", sf.rel, node.lineno, qn, "print",
                    "bare print() outside obs/ — route through "
                    "obs.trace.log so the trace ring sees it"))
    return out


def _literal_names(node):
    """All string literals a name argument can evaluate to, or None if
    any branch is dynamic.  Handles plain constants and (nested)
    conditional expressions over constants."""
    s = str_const(node)
    if s is not None:
        return [s]
    if isinstance(node, ast.IfExp):
        a = _literal_names(node.body)
        b = _literal_names(node.orelse)
        if a is not None and b is not None:
            return a + b
    return None


@rule("R6")
def check_r6(ctx) -> list:
    sites = ctx.fault_sites()
    ladder = ctx.ladder_steps()
    out = []
    for sf in ctx.iter(_R6_SCOPE, exclude=_R6_EXCLUDE):
        if sf.tree is None:
            continue
        for node, qn, _funcs in walk_scoped(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _call_kind(node)
            if kind is None or not node.args:
                continue
            names = _literal_names(node.args[0])
            if names is None:
                out.append(Violation(
                    "R6", sf.rel, node.lineno, qn, f"{kind}:dynamic",
                    f"dynamic {kind} name — series names must be "
                    "static literals (suppress with the cardinality "
                    "bound if the dynamic part is finite)"))
                continue
            for s in names:
                if not _NAME_RE.match(s):
                    out.append(Violation(
                        "R6", sf.rel, node.lineno, qn, f"{kind}:{s}",
                        f"{kind} name {s!r} is not dotted-lowercase "
                        "([a-z0-9_] segments joined by '.')"))
                elif kind == "faultpoint" and sites and s not in sites:
                    out.append(Violation(
                        "R6", sf.rel, node.lineno, qn, f"{kind}:{s}",
                        f"faultpoint site {s!r} not in "
                        "resilience.faults.SITES"))
                elif kind == "ladder_step" and ladder and \
                        s not in ladder:
                    out.append(Violation(
                        "R6", sf.rel, node.lineno, qn, f"{kind}:{s}",
                        f"ladder step {s!r} not in recover.LADDER"))
    return out


def _call_kind(node) -> str | None:
    """Classify a call as a named-series emitter, or None."""
    f = node.func
    if isinstance(f, ast.Attribute):
        base = dotted(f.value)
        if f.attr in _METRIC_METHODS and base.endswith("REGISTRY"):
            return f"metric.{f.attr}"
        if f.attr == "event" and base in ("otrace", "trace", "obs.trace"):
            return "event"
    if isinstance(f, ast.Name):
        if f.id in ("faultpoint", "fault_trigger"):
            return "faultpoint"
        if f.id == "ladder_step":
            return "ladder_step"
        if f.id == "event":
            return "event"
    return None
