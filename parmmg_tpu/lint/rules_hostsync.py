"""R2 host-sync: the PR-4 "zero O(mesh) host pulls on the grouped
path" contract, statically.

A lightweight call-graph reachability pass over ``parmmg_tpu/parallel/``:
starting from the hot-path roots (the grouped pass + its chunk
pipeline, the device analysis refresh, and the per-pass distributed
cycle loop), follow simple-name call edges between functions defined in
the package and flag every host-synchronising primitive in a reachable
function:

- ``jax.device_get`` / ``device_get``
- ``.item()`` / ``.block_until_ready()`` method calls
- ``np.asarray`` / ``np.array`` / ``np.stack`` on device values
- ``float(x)`` / ``int(x)`` where ``x`` is a subscript or a call
  result (the traced-scalar pull idiom ``int(counts[g])``)

The graph is name-based and over-approximate on purpose: a false
positive costs one reasoned suppression or a baseline entry; a false
negative is a silent O(mesh) pull multiplying under the chip campaigns.
Functions that ARE the documented host fallback (the KS-overflow
ladder) carry a def-line suppression — the engine honours a
suppression on the violating line, the line above, or the enclosing
``def`` line, so one annotation exempts a whole fallback function with
its reason attached.

The function index, call edges and reachability worklist live in
``lint.flow`` (the interprocedural core R8-R10 build their summaries
on); R2/R7 are its original reachability clients.
"""
from __future__ import annotations

import ast

from . import flow
from .engine import Violation, dotted, rule

#: reachability roots — the grouped/dist hot paths (PR-4/PR-5 contract)
ROOTS = (
    "grouped_adapt_pass",
    "_pipeline_chunks",
    "refresh_shard_analysis_device",
    "dist_analysis_grouped",
    "run_adapt_cycles",
)

_SYNC_CALLS = {"jax.device_get": "jax.device_get",
               "device_get": "jax.device_get",
               "np.asarray": "np.asarray",
               "np.array": "np.array",
               "np.stack": "np.stack",
               "numpy.asarray": "np.asarray"}
_SYNC_METHODS = {"item": ".item()",
                 "block_until_ready": ".block_until_ready()"}
_CAST_FNS = ("float", "int")

_SCOPE = ("parmmg_tpu/parallel/",)

# float()/int() args that can never be a traced-value sync: env reads
# and other obviously-host producers
_HOST_FUNCS = ("environ.get", "os.getenv", "getenv", "len", "round",
               "time.perf_counter", "time.time")


def _host_only_arg(arg) -> bool:
    if isinstance(arg, ast.Call):
        from .engine import dotted as _d
        d = _d(arg.func)
        return any(d == h or d.endswith("." + h) for h in _HOST_FUNCS)
    return False


#: R7 reachability roots — R2's hot-path roots plus the band-migration
#: pipeline and the multi-iteration distributed driver (the pod hot
#: path, parallel/pod.py): these are the functions whose steady state
#: must never replicate state through the pull_host escape hatch
R7_ROOTS = ROOTS + (
    "distributed_adapt_multi",
    "band_migrate_iteration",
    "band_weld",
    "repair_flood_labels",
    "graph_repartition_labels_band",
)

#: the escape-hatch primitives R7 flags (callee simple/dotted names)
_R7_CALLS = ("pull_host", "_pull", "process_allgather")


@rule("R7")
def check_r7(ctx) -> list:
    """The runtime tripwire's static mirror: ``pull_host`` increments
    ``mh.hot_allgather_bytes`` when reached inside a hot_path section
    (gate-asserted zero); this rule flags the CALL SITES so a stray
    allgather on the pod hot path fails in seconds at lint time, before
    any 2-process run.  Legitimate escape hatches (budget-overflow
    fallbacks, checkpoint IO under cold_io, the final-output gather)
    carry reasoned suppressions."""
    graph = flow.CallGraph(ctx, _SCOPE)
    out = []
    for fi in graph.reachable(R7_ROOTS):
        for n in ast.walk(fi.node):
            if id(n) in fi.nested_skip or not isinstance(n, ast.Call):
                continue
            d = dotted(n.func)
            leaf = d.rsplit(".", 1)[-1] if d else ""
            if leaf not in _R7_CALLS:
                continue
            out.append(Violation(
                "R7", fi.sf.rel, n.lineno, fi.qualname, leaf,
                f"escape-hatch allgather {leaf}() reachable from the "
                f"pod hot path (roots: {', '.join(R7_ROOTS)}) — band "
                "tables ride pod.gather_band",
                anchor_lines=fi.def_lines))
    return out


@rule("R2")
def check_r2(ctx) -> list:
    graph = flow.CallGraph(ctx, _SCOPE)
    out = []
    for fi in graph.reachable(ROOTS):
        # direct body only (nested defs are separate graph nodes); the
        # def/decorator lines anchor whole-function fallback
        # suppressions
        for n in ast.walk(fi.node):
            if id(n) in fi.nested_skip or not isinstance(n, ast.Call):
                continue
            tag = None
            d = dotted(n.func)
            if d in _SYNC_CALLS:
                tag = _SYNC_CALLS[d]
            elif isinstance(n.func, ast.Attribute) \
                    and n.func.attr in _SYNC_METHODS:
                tag = _SYNC_METHODS[n.func.attr]
            elif isinstance(n.func, ast.Name) \
                    and n.func.id in _CAST_FNS and len(n.args) == 1 \
                    and isinstance(n.args[0], (ast.Subscript, ast.Call)) \
                    and not _host_only_arg(n.args[0]):
                tag = f"{n.func.id}()"
            if tag is None:
                continue
            # def_lines ride along as anchor_lines so the ENGINE
            # resolves a def-line suppression (whole-function fallback
            # exemption) and the pair still lands in report.suppressed
            out.append(Violation(
                "R2", fi.sf.rel, n.lineno, fi.qualname, tag,
                f"host-sync {tag} reachable from the grouped/dist hot "
                f"path (roots: {', '.join(ROOTS)})",
                anchor_lines=fi.def_lines))
    return out
