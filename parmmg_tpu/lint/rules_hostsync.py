"""R2 host-sync: the PR-4 "zero O(mesh) host pulls on the grouped
path" contract, statically.

A lightweight call-graph reachability pass over ``parmmg_tpu/parallel/``:
starting from the hot-path roots (the grouped pass + its chunk
pipeline, the device analysis refresh, and the per-pass distributed
cycle loop), follow simple-name call edges between functions defined in
the package and flag every host-synchronising primitive in a reachable
function:

- ``jax.device_get`` / ``device_get``
- ``.item()`` / ``.block_until_ready()`` method calls
- ``np.asarray`` / ``np.array`` / ``np.stack`` on device values
- ``float(x)`` / ``int(x)`` where ``x`` is a subscript or a call
  result (the traced-scalar pull idiom ``int(counts[g])``)

The graph is name-based and over-approximate on purpose: a false
positive costs one reasoned suppression or a baseline entry; a false
negative is a silent O(mesh) pull multiplying under the chip campaigns.
Functions that ARE the documented host fallback (the KS-overflow
ladder) carry a def-line suppression — R2 honours a suppression on the
violating line, the line above, or the enclosing ``def`` line, so one
annotation exempts a whole fallback function with its reason attached.
"""
from __future__ import annotations

import ast

from .engine import Violation, dotted, rule, walk_scoped

#: reachability roots — the grouped/dist hot paths (PR-4/PR-5 contract)
ROOTS = (
    "grouped_adapt_pass",
    "_pipeline_chunks",
    "refresh_shard_analysis_device",
    "dist_analysis_grouped",
    "run_adapt_cycles",
)

_SYNC_CALLS = {"jax.device_get": "jax.device_get",
               "device_get": "jax.device_get",
               "np.asarray": "np.asarray",
               "np.array": "np.array",
               "np.stack": "np.stack",
               "numpy.asarray": "np.asarray"}
_SYNC_METHODS = {"item": ".item()",
                 "block_until_ready": ".block_until_ready()"}
_CAST_FNS = ("float", "int")

_SCOPE = ("parmmg_tpu/parallel/",)

# float()/int() args that can never be a traced-value sync: env reads
# and other obviously-host producers
_HOST_FUNCS = ("environ.get", "os.getenv", "getenv", "len", "round",
               "time.perf_counter", "time.time")


def _host_only_arg(arg) -> bool:
    if isinstance(arg, ast.Call):
        from .engine import dotted as _d
        d = _d(arg.func)
        return any(d == h or d.endswith("." + h) for h in _HOST_FUNCS)
    return False


def _functions(ctx):
    """{simple name: [(SourceFile, qualname, node)]} for every def in
    scope (nested defs included — the dispatch/drain closures are where
    the pulls live)."""
    idx: dict[str, list] = {}
    for sf in ctx.iter(_SCOPE):
        if sf.tree is None:
            continue
        for node, qn, _funcs in walk_scoped(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                idx.setdefault(node.name, []).append((sf, qn, node))
    return idx


def _called_names(fn_node) -> set:
    """Simple callee names referenced inside a function: direct Name
    calls, terminal attribute calls (``sched.chunk_plans``), and bare
    Name references (callbacks passed around, e.g.
    ``_pipeline_chunks(fn, ...)`` receiving ``dispatch``)."""
    out = set()
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Call):
            if isinstance(n.func, ast.Name):
                out.add(n.func.id)
            elif isinstance(n.func, ast.Attribute):
                out.add(n.func.attr)
        elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            out.add(n.id)
    return out


def _reachable(idx, roots=ROOTS) -> dict:
    """{id(fn_node): (SourceFile, qualname, node)} reachable from
    ``roots`` via simple-name edges (shared by R2 and R7)."""
    seen: dict[int, tuple] = {}
    work = []
    for r in roots:
        for ent in idx.get(r, ()):
            if id(ent[2]) not in seen:
                seen[id(ent[2])] = ent
                work.append(ent)
    while work:
        _sf, _qn, node = work.pop()
        for name in _called_names(node):
            for ent in idx.get(name, ()):
                if id(ent[2]) not in seen:
                    seen[id(ent[2])] = ent
                    work.append(ent)
    return seen


def _direct_body(qn: str, fn_node):
    """(full qualname, nested-node id set to skip, suppression anchor
    lines) for scanning a function's DIRECT body: nested defs are their
    own graph nodes, and a def-line (or first-decorator-line)
    suppression exempts the whole function — the shared R2/R7
    per-function scaffolding."""
    qn_full = f"{qn}.{fn_node.name}" if qn != "<module>" \
        else fn_node.name
    skip = set()
    for nf in ast.walk(fn_node):
        if isinstance(nf, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and nf is not fn_node:
            for x in ast.walk(nf):
                skip.add(id(x))
    def_lines = (fn_node.lineno,) + (
        (fn_node.decorator_list[0].lineno,)
        if fn_node.decorator_list else ())
    return qn_full, skip, def_lines


#: R7 reachability roots — R2's hot-path roots plus the band-migration
#: pipeline and the multi-iteration distributed driver (the pod hot
#: path, parallel/pod.py): these are the functions whose steady state
#: must never replicate state through the pull_host escape hatch
R7_ROOTS = ROOTS + (
    "distributed_adapt_multi",
    "band_migrate_iteration",
    "band_weld",
    "repair_flood_labels",
    "graph_repartition_labels_band",
)

#: the escape-hatch primitives R7 flags (callee simple/dotted names)
_R7_CALLS = ("pull_host", "_pull", "process_allgather")


@rule("R7")
def check_r7(ctx) -> list:
    """The runtime tripwire's static mirror: ``pull_host`` increments
    ``mh.hot_allgather_bytes`` when reached inside a hot_path section
    (gate-asserted zero); this rule flags the CALL SITES so a stray
    allgather on the pod hot path fails in seconds at lint time, before
    any 2-process run.  Legitimate escape hatches (budget-overflow
    fallbacks, checkpoint IO under cold_io, the final-output gather)
    carry reasoned suppressions."""
    idx = _functions(ctx)
    out = []
    for sf, qn, fn_node in _reachable(idx, R7_ROOTS).values():
        qn_full, skip, def_lines = _direct_body(qn, fn_node)
        for n in ast.walk(fn_node):
            if id(n) in skip or not isinstance(n, ast.Call):
                continue
            d = dotted(n.func)
            leaf = d.rsplit(".", 1)[-1] if d else ""
            if leaf not in _R7_CALLS:
                continue
            out.append(Violation(
                "R7", sf.rel, n.lineno, qn_full, leaf,
                f"escape-hatch allgather {leaf}() reachable from the "
                f"pod hot path (roots: {', '.join(R7_ROOTS)}) — band "
                "tables ride pod.gather_band",
                anchor_lines=def_lines))
    return out


@rule("R2")
def check_r2(ctx) -> list:
    idx = _functions(ctx)
    reach = _reachable(idx)
    out = []
    for sf, qn, fn_node in reach.values():
        # direct body only (nested defs are separate graph nodes); the
        # def/decorator lines anchor whole-function fallback
        # suppressions — shared scaffolding, _direct_body
        qn_full, skip, def_lines = _direct_body(qn, fn_node)
        for n in ast.walk(fn_node):
            if id(n) in skip or not isinstance(n, ast.Call):
                continue
            tag = None
            d = dotted(n.func)
            if d in _SYNC_CALLS:
                tag = _SYNC_CALLS[d]
            elif isinstance(n.func, ast.Attribute) \
                    and n.func.attr in _SYNC_METHODS:
                tag = _SYNC_METHODS[n.func.attr]
            elif isinstance(n.func, ast.Name) \
                    and n.func.id in _CAST_FNS and len(n.args) == 1 \
                    and isinstance(n.args[0], (ast.Subscript, ast.Call)) \
                    and not _host_only_arg(n.args[0]):
                tag = f"{n.func.id}()"
            if tag is None:
                continue
            # def_lines ride along as anchor_lines so the ENGINE
            # resolves a def-line suppression (whole-function fallback
            # exemption) and the pair still lands in report.suppressed
            out.append(Violation(
                "R2", sf.rel, n.lineno, qn_full, tag,
                f"host-sync {tag} reachable from the grouped/dist hot "
                f"path (roots: {', '.join(ROOTS)})",
                anchor_lines=def_lines))
    return out
